"""Benchmark: TPC-H-Q1-shaped aggregation pipeline on the device engine.

Mirrors BASELINE.md config ladder steps 1-2: 1M-row filter+project+grouped
aggregation (sum/avg/count per key) — the hot pattern of the reference's NDS
benchmarks. Baseline = the same query through pandas on this host's CPU
(the role CPU Spark plays for the reference's speedup claims).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Env: SRTPU_BENCH_CPU=1 forces the JAX CPU backend; SRTPU_BENCH_ROWS
overrides the row count.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    if os.environ.get("SRTPU_BENCH_CPU") == "1":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import pyarrow as pa

    from spark_rapids_tpu.api import TpuSession, functions as F

    n = int(os.environ.get("SRTPU_BENCH_ROWS", 1_000_000))
    rng = np.random.RandomState(42)
    data = {
        "k": rng.randint(0, 1000, size=n).astype(np.int64),
        "status": rng.randint(0, 4, size=n).astype(np.int32),
        "qty": rng.randint(1, 51, size=n).astype(np.int64),
        "price": (rng.random_sample(n) * 1000).astype(np.float64),
        "disc": (rng.random_sample(n) * 0.1).astype(np.float64),
    }
    table = pa.table({k: pa.array(v) for k, v in data.items()})
    log(f"bench: {n} rows on {jax.devices()[0].platform}")

    def run_engine():
        s = TpuSession()
        df = s.create_dataframe(table)
        out = (df.filter(F.col("status") < 3)
               .with_column("gross", F.col("price") * F.col("qty"))
               .with_column("net", F.col("price") * F.col("qty")
                            * (1.0 - F.col("disc")))
               .group_by("k")
               .agg(F.sum(F.col("qty")).with_name("sum_qty"),
                    F.sum(F.col("gross")).with_name("sum_gross"),
                    F.sum(F.col("net")).with_name("sum_net"),
                    F.avg(F.col("price")).with_name("avg_price"),
                    F.count_star().with_name("cnt")))
        return out.collect_arrow()

    # warm-up (compilation) then timed runs
    t0 = time.perf_counter()
    res = run_engine()
    warm = time.perf_counter() - t0
    log(f"bench: warm-up (incl. compile) {warm:.2f}s, groups={res.num_rows}")
    iters = 3
    t0 = time.perf_counter()
    for _ in range(iters):
        res = run_engine()
    engine_s = (time.perf_counter() - t0) / iters
    engine_rate = n / engine_s
    log(f"bench: engine {engine_s:.3f}s/iter -> {engine_rate:,.0f} rows/s")

    # pandas CPU baseline (the reference's CPU-Spark role)
    import pandas as pd
    pdf = table.to_pandas()
    t0 = time.perf_counter()
    for _ in range(iters):
        f = pdf[pdf["status"] < 3].copy()
        f["gross"] = f["price"] * f["qty"]
        f["net"] = f["gross"] * (1.0 - f["disc"])
        base = f.groupby("k").agg(
            sum_qty=("qty", "sum"), sum_gross=("gross", "sum"),
            sum_net=("net", "sum"), avg_price=("price", "mean"),
            cnt=("qty", "size"))
    base_s = (time.perf_counter() - t0) / iters
    base_rate = n / base_s
    log(f"bench: pandas {base_s:.3f}s/iter -> {base_rate:,.0f} rows/s")

    # correctness spot-check against the baseline
    got = res.to_pandas().set_index("k").sort_index()
    np.testing.assert_allclose(got["sum_net"].to_numpy(),
                               base.sort_index()["sum_net"].to_numpy(),
                               rtol=1e-9)

    print(json.dumps({
        "metric": "q1_like_agg_rows_per_sec",
        "value": round(engine_rate, 1),
        "unit": "rows/s",
        "vs_baseline": round(engine_rate / base_rate, 3),
    }))


if __name__ == "__main__":
    main()
