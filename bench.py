"""Benchmark ladder: TPC-H q1/q6 (1M + 10M rows), TPC-DS q3/q9/q28,
bounded window.

Covers BASELINE.md configs #2/#3 plus the window workload so regressions in
ANY ladder query are visible to the driver every round (VERDICT r1 #3), not
just the winning one. Baseline = the same queries through pandas on this
host's CPU (the role CPU Spark plays for the reference's speedups).

The 10M-row rungs (VERDICT r2 #2) measure the regime where throughput, not
the tunnel's fixed dispatch+fetch floor (~0.1 s/query — docs/performance.md),
decides: at 1M rows every engine result is floor-bound, which is the least
representative regime for a throughput engine.

Prints one JSON line per workload (metric/value/unit/vs_baseline) and a
final summary line whose vs_baseline is the geometric mean of the
per-workload speedups — the driver's single-line parse lands on the
summary; the per-workload lines ride along in the recorded tail and in the
summary's "details".

Env: SRTPU_BENCH_CPU=1 forces the JAX CPU backend; SRTPU_BENCH_ROWS
overrides the base row count; SRTPU_BENCH_BIG_ROWS the big-rung row count
(0 disables the big rungs); SRTPU_BENCH_ITERS the per-workload iterations.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _time_min(fn, iters):
    best = float("inf")
    out = None
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn()
        best = min(best, time.perf_counter() - t0)
    return best, out


def gen_string_table(n: int, seed: int = 13, card: int = 1000):
    import pyarrow as pa
    rng = np.random.RandomState(seed)
    pool = np.asarray([f"  Item-{i:05d}-{'x' * (i % 7)}  "
                       for i in range(card)], dtype=object)
    return pa.table({
        "s": pa.array(pool[rng.randint(0, card, n)]),
        "v": pa.array(rng.uniform(0, 10, n)),
    })


def gen_window_table(n: int, seed: int = 11):
    import pyarrow as pa
    rng = np.random.RandomState(seed)
    return pa.table({
        "p": pa.array(rng.randint(0, 512, n)),
        "o": pa.array(rng.randint(0, 1 << 30, n)),
        "v": pa.array(rng.uniform(-100.0, 100.0, n)),
    })


def main():
    if os.environ.get("SRTPU_BENCH_CPU") == "1":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax

    from spark_rapids_tpu.api import TpuSession, functions as F

    from benchmarks import tpch, tpcds

    n = int(os.environ.get("SRTPU_BENCH_ROWS", 1_000_000))
    nbig = int(os.environ.get("SRTPU_BENCH_BIG_ROWS", 10_000_000))
    iters = int(os.environ.get("SRTPU_BENCH_ITERS", 3))
    nw = min(n, 500_000)
    lineitem = tpch.gen_lineitem(n)
    lineitem_big = tpch.gen_lineitem(nbig) if nbig else None
    store_sales = tpcds.gen_store_sales(n)
    date_dim = tpcds.gen_date_dim()
    item = tpcds.gen_item()
    wtab = gen_window_table(nw)
    stab = gen_string_table(n)
    log(f"bench: ladder on {jax.devices()[0].platform}, {n} rows, "
        f"{iters} iters")

    # ---------------- engine side ----------------
    def eng_q1():
        s = TpuSession()
        return tpch.q1(s.create_dataframe(lineitem), F).collect_arrow()

    def eng_q6():
        s = TpuSession()
        return tpch.q6(s.create_dataframe(lineitem), F).collect_arrow()

    def eng_q1_big():
        s = TpuSession()
        return tpch.q1(s.create_dataframe(lineitem_big), F).collect_arrow()

    def eng_q6_big():
        s = TpuSession()
        return tpch.q6(s.create_dataframe(lineitem_big), F).collect_arrow()

    def eng_q3():
        s = TpuSession()
        return tpcds.q3(s.create_dataframe(store_sales),
                        s.create_dataframe(date_dim),
                        s.create_dataframe(item), F).collect_arrow()

    def eng_q9():
        s = TpuSession()
        return tpcds.q9(s.create_dataframe(store_sales), F).collect_arrow()

    def eng_q28():
        s = TpuSession()
        return tpcds.q28(s.create_dataframe(store_sales), F).collect_arrow()

    def eng_window():
        from spark_rapids_tpu.exprs import ColumnRef
        from spark_rapids_tpu.exprs.aggregates import Sum
        s = TpuSession()
        return (s.create_dataframe(wtab)
                .with_window_column("wsum", Sum(ColumnRef("v")),
                                    partition_by=["p"],
                                    order_by=[F.col("o").asc()],
                                    frame=("rows", -2, 0))
                .collect_arrow())

    def eng_strings():
        # dict-transform path (r3): upper/trim/substring evaluate once
        # per distinct dictionary entry; rows stay device-resident codes
        s = TpuSession()
        return (s.create_dataframe(stab)
                .select(F.upper(F.trim(F.col("s"))).alias("u"),
                        F.substring(F.col("s"), 3, 4).alias("pre"),
                        F.col("v"))
                .group_by("u", "pre")
                .agg(F.sum(F.col("v")).with_name("sv"),
                     F.count_star().with_name("n"))
                .collect_arrow())

    # ---------------- pandas baselines ----------------
    def _base_q1(table):
        pdf = table.to_pandas(date_as_object=False)
        cutoff = (np.datetime64("1998-12-01")
                  - np.timedelta64(90, "D")).astype("datetime64[ns]")
        f = pdf[pdf["l_shipdate"] <= cutoff].copy()
        f["disc_price"] = f["l_extendedprice"] * (1.0 - f["l_discount"])
        f["charge"] = f["disc_price"] * (1.0 + f["l_tax"])
        return f.groupby(["l_returnflag", "l_linestatus"]).agg(
            sum_qty=("l_quantity", "sum"),
            sum_base_price=("l_extendedprice", "sum"),
            sum_disc_price=("disc_price", "sum"),
            sum_charge=("charge", "sum"),
            avg_qty=("l_quantity", "mean"),
            avg_price=("l_extendedprice", "mean"),
            avg_disc=("l_discount", "mean"),
            count_order=("l_quantity", "size")).sort_index()

    def _base_q6(table):
        pdf = table.to_pandas(date_as_object=False)
        m = ((pdf["l_shipdate"] >= np.datetime64("1994-01-01"))
             & (pdf["l_shipdate"] < np.datetime64("1995-01-01"))
             & (pdf["l_discount"] >= 0.05) & (pdf["l_discount"] <= 0.07)
             & (pdf["l_quantity"] < 24.0))
        f = pdf[m]
        return float((f["l_extendedprice"] * f["l_discount"]).sum())

    def base_q1():
        return _base_q1(lineitem)

    def base_q6():
        return _base_q6(lineitem)

    def base_q1_big():
        return _base_q1(lineitem_big)

    def base_q6_big():
        return _base_q6(lineitem_big)

    def base_q3():
        ss = store_sales.to_pandas()
        dd = date_dim.to_pandas(date_as_object=False)
        it = item.to_pandas()
        dd = dd[dd["d_moy"] == 11]
        it = it[it["i_manufact_id"] == 128]
        j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
        j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
        g = (j.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False)
             ["ss_ext_sales_price"].sum()
             .rename(columns={"ss_ext_sales_price": "sum_agg"}))
        return g.sort_values(["d_year", "sum_agg", "i_brand_id"],
                             ascending=[True, False, True])

    def base_q9():
        ss = store_sales.to_pandas()
        out = {}
        for i, (lo, hi) in enumerate(
                [(1, 20), (21, 40), (41, 60), (61, 80), (81, 100)], 1):
            m = (ss["ss_quantity"] >= lo) & (ss["ss_quantity"] <= hi)
            out[f"cnt{i}"] = int(m.sum())
            out[f"avg_price{i}"] = float(ss.loc[m, "ss_ext_sales_price"].mean())
            out[f"avg_paid{i}"] = float(ss.loc[m, "ss_net_paid"].mean())
        return out

    def base_q28():
        ss = store_sales.to_pandas()
        buckets = [(0, 5, 11, 460, 14930), (6, 10, 91, 1430, 32370),
                   (11, 15, 66, 1480, 3750), (16, 20, 142, 3270, 21910),
                   (21, 25, 135, 2450, 17300), (26, 30, 28, 2340, 33660)]
        rows = []
        for lo, hi, lp, cp, wc in buckets:
            m = ((ss["ss_quantity"] >= lo) & (ss["ss_quantity"] <= hi)
                 & ((ss["ss_list_price"] >= float(lp))
                    | (ss["ss_coupon_amt"] >= float(cp))
                    | (ss["ss_wholesale_cost"] >= float(wc))))
            b = ss.loc[m, "ss_list_price"]
            rows.append((float(b.mean()), int(b.count()), int(b.nunique())))
        return rows

    def base_strings():
        pdf = stab.to_pandas()
        pdf["u"] = pdf["s"].str.strip().str.upper()
        pdf["pre"] = pdf["s"].str.slice(2, 6)
        return (pdf.groupby(["u", "pre"], as_index=False)
                .agg(sv=("v", "sum"), n=("v", "size")))

    def base_window():
        pdf = wtab.to_pandas()
        pdf = pdf.sort_values(["p", "o"], kind="stable")
        pdf["wsum"] = (pdf.groupby("p")["v"]
                       .rolling(3, min_periods=1).sum()
                       .reset_index(level=0, drop=True))
        return pdf

    workloads = [
        ("tpch_q1", eng_q1, base_q1),
        ("tpch_q6", eng_q6, base_q6),
        ("tpcds_q3", eng_q3, base_q3),
        ("tpcds_q9", eng_q9, base_q9),
        ("tpcds_q28", eng_q28, base_q28),
        ("window_bounded", eng_window, base_window),
        ("string_transforms", eng_strings, base_strings),
    ]
    if lineitem_big is not None:
        workloads += [
            ("tpch_q1_10m", eng_q1_big, base_q1_big),
            ("tpch_q6_10m", eng_q6_big, base_q6_big),
        ]

    details = {}
    checks = {}
    for name, eng, base in workloads:
        t0 = time.perf_counter()
        eng_res = eng()                       # warm-up incl. compile
        warm = time.perf_counter() - t0
        eng_s, eng_res = _time_min(eng, iters)
        base_s, base_res = _time_min(base, iters)
        speedup = base_s / eng_s
        rows = (nw if name == "window_bounded"
                else nbig if name.endswith("_10m") else n)
        details[name] = {
            "engine_s": round(eng_s, 4), "baseline_s": round(base_s, 4),
            "speedup": round(speedup, 3),
            "rows_per_sec": round(rows / eng_s, 1),
        }
        checks[name] = (eng_res, base_res)
        log(f"bench: {name:15s} engine {eng_s:7.3f}s  pandas {base_s:7.3f}s "
            f"-> {speedup:5.2f}x  (warm-up {warm:.1f}s)")

    # ---------------- correctness spot-checks ----------------
    res, base = checks["tpch_q1"]
    got = res.to_pandas().set_index(["l_returnflag", "l_linestatus"]) \
             .sort_index()
    np.testing.assert_allclose(got["sum_disc_price"].to_numpy(),
                               base["sum_disc_price"].to_numpy(), rtol=1e-9)
    np.testing.assert_array_equal(got["count_order"].to_numpy(),
                                  base["count_order"].to_numpy())
    res, base = checks["tpch_q6"]
    np.testing.assert_allclose(res.column("revenue")[0].as_py(), base,
                               rtol=1e-9)
    res, base = checks["tpcds_q3"]
    np.testing.assert_allclose(
        np.sort(res.column("sum_agg").to_numpy()),
        np.sort(base["sum_agg"].to_numpy()), rtol=1e-9)
    assert res.num_rows == len(base)
    res, base = checks["tpcds_q9"]
    grow = res.to_pylist()[0]
    for k, v in base.items():
        np.testing.assert_allclose(grow[k], v, rtol=1e-9, err_msg=k)
    res, base = checks["tpcds_q28"]
    eng_rows = [(r["b_avg"], r["b_cnt"], r["b_cntd"]) for r in res.to_pylist()]
    for (ea, ec, ed), (ba, bc, bd) in zip(eng_rows, base):
        np.testing.assert_allclose(ea, ba, rtol=1e-9)
        assert (ec, ed) == (bc, bd)
    res, base = checks["window_bounded"]
    eng_sum = float(np.nansum(res.column("wsum").to_numpy(
        zero_copy_only=False)))
    np.testing.assert_allclose(eng_sum, float(base["wsum"].sum()), rtol=1e-6)
    res, base = checks["string_transforms"]
    got = res.to_pandas().sort_values(["u", "pre"]).reset_index(drop=True)
    base = base.sort_values(["u", "pre"]).reset_index(drop=True)
    assert len(got) == len(base), (len(got), len(base))
    np.testing.assert_array_equal(got["u"], base["u"])
    np.testing.assert_array_equal(got["n"], base["n"])
    np.testing.assert_allclose(got["sv"], base["sv"], rtol=1e-9)
    if "tpch_q1_10m" in checks:
        res, base = checks["tpch_q1_10m"]
        got = res.to_pandas().set_index(["l_returnflag", "l_linestatus"]) \
                 .sort_index()
        np.testing.assert_allclose(got["sum_disc_price"].to_numpy(),
                                   base["sum_disc_price"].to_numpy(),
                                   rtol=1e-9)
        np.testing.assert_array_equal(got["count_order"].to_numpy(),
                                      base["count_order"].to_numpy())
        res, base = checks["tpch_q6_10m"]
        np.testing.assert_allclose(res.column("revenue")[0].as_py(), base,
                                   rtol=1e-9)
    log("bench: all correctness checks passed")

    for name, d in details.items():
        print(json.dumps({"metric": name + "_speedup", "value": d["speedup"],
                          "unit": "x_vs_pandas",
                          "vs_baseline": d["speedup"]}))
    geo = float(np.exp(np.mean([np.log(d["speedup"])
                                for d in details.values()])))
    print(json.dumps({
        "metric": "ladder_geomean_speedup",
        "value": round(geo, 3),
        "unit": "x_vs_pandas",
        "vs_baseline": round(geo, 3),
        "details": details,
    }))


if __name__ == "__main__":
    main()
