"""Benchmark ladder: TPC-H q1/q6, TPC-DS q3/q9/q28, bounded window, string
transforms — at 1M rows AND 10M rows (q1/q6/q9/q28) — plus a distributed
rung (8-virtual-device CPU mesh) run in a subprocess.

Design (VERDICT r3 #1: "finish the bench — at scale, with placement
honesty"):
  * every workload is timed AND correctness-checked before the next one
    starts, so a timeout can never discard finished results;
  * each workload records which engine actually ran ("device"/"host" from
    session.last_placement) — host-numpy wins are labeled as such;
  * a LADDER budget (SRTPU_BENCH_BUDGET, default 1500 s) gracefully
    skips remaining rungs; the budget clock starts AFTER backend init —
    a held/unavailable chip costs up to SRTPU_BENCH_BACKEND_WAIT extra
    wall (r5: hours-long outages made the wait eat the whole budget and
    produce an empty artifact). Total wall is therefore bounded by
    backend wait + table generation + budget; every finished rung's
    metric line is flushed IMMEDIATELY, so even an external timeout
    mid-ladder preserves all completed results;
  * the summary carries an overall geomean, a DEVICE-ONLY geomean, and a
    regression check against the previous round's BENCH_r*.json.

Baseline = the same queries through pandas on this host's CPU (the role CPU
Spark plays for the reference's speedups, docs/index.md:8-24).

Env: SRTPU_BENCH_CPU=1 forces the JAX CPU backend; SRTPU_BENCH_ROWS
overrides the base row count; SRTPU_BENCH_BIG_ROWS the big-rung count
(0 disables); SRTPU_BENCH_ITERS per-workload iterations;
SRTPU_BENCH_BUDGET the wall budget in seconds; SRTPU_BENCH_DIST=0
disables the distributed rung.
"""
from __future__ import annotations

import glob
import json
import os
import re
import subprocess
import sys
import time

import numpy as np

START = time.perf_counter()


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _time_min(fn, iters):
    best = float("inf")
    out = None
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn()
        best = min(best, time.perf_counter() - t0)
    return best, out


def gen_string_table(n: int, seed: int = 13, card: int = 1000):
    import pyarrow as pa
    rng = np.random.RandomState(seed)
    pool = np.asarray([f"  Item-{i:05d}-{'x' * (i % 7)}  "
                       for i in range(card)], dtype=object)
    return pa.table({
        "s": pa.array(pool[rng.randint(0, card, n)]),
        "v": pa.array(rng.uniform(0, 10, n)),
    })


def gen_window_table(n: int, seed: int = 11):
    import pyarrow as pa
    rng = np.random.RandomState(seed)
    return pa.table({
        "p": pa.array(rng.randint(0, 512, n)),
        "o": pa.array(rng.randint(0, 1 << 30, n)),
        "v": pa.array(rng.uniform(-100.0, 100.0, n)),
    })


# ---------------------------------------------------------------------------
# correctness checks (one per workload shape, run IMMEDIATELY after timing)
# ---------------------------------------------------------------------------

def check_q1(res, base):
    got = res.to_pandas().set_index(["l_returnflag", "l_linestatus"]) \
             .sort_index()
    np.testing.assert_allclose(got["sum_disc_price"].to_numpy(),
                               base["sum_disc_price"].to_numpy(), rtol=1e-9)
    np.testing.assert_array_equal(got["count_order"].to_numpy(),
                                  base["count_order"].to_numpy())


def check_q6(res, base):
    np.testing.assert_allclose(res.column("revenue")[0].as_py(), base,
                               rtol=1e-9)


def check_q3(res, base):
    np.testing.assert_allclose(
        np.sort(res.column("sum_agg").to_numpy()),
        np.sort(base["sum_agg"].to_numpy()), rtol=1e-9)
    assert res.num_rows == len(base)


def check_q9(res, base):
    grow = res.to_pylist()[0]
    for k, v in base.items():
        np.testing.assert_allclose(grow[k], v, rtol=1e-9, err_msg=k)


def check_q28(res, base):
    eng_rows = [(r["b_avg"], r["b_cnt"], r["b_cntd"])
                for r in res.to_pylist()]
    for (ea, ec, ed), (ba, bc, bd) in zip(eng_rows, base):
        np.testing.assert_allclose(ea, ba, rtol=1e-9)
        assert (ec, ed) == (bc, bd)


def check_window(res, base):
    eng_sum = float(np.nansum(res.column("wsum").to_numpy(
        zero_copy_only=False)))
    np.testing.assert_allclose(eng_sum, float(base["wsum"].sum()), rtol=1e-6)


def check_strings(res, base):
    got = res.to_pandas().sort_values(["u", "pre"]).reset_index(drop=True)
    base = base.sort_values(["u", "pre"]).reset_index(drop=True)
    assert len(got) == len(base), (len(got), len(base))
    np.testing.assert_array_equal(got["u"], base["u"])
    np.testing.assert_array_equal(got["n"], base["n"])
    np.testing.assert_allclose(got["sv"], base["sv"], rtol=1e-9)


# ---------------------------------------------------------------------------

def _bench_round_no(p):
    m = re.search(r"r(\d+)", os.path.basename(p))
    return int(m.group(1)) if m else -1


def _bench_artifacts():
    """Every BENCH_r*.json beside this script, oldest round first — ONE
    discovery for the regression gate and the regress-delta emitter."""
    return sorted(glob.glob(os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "BENCH_r*.json")),
        key=_bench_round_no)


def previous_bench():
    """Newest BENCH_r*.json with a parsed summary (regression gate)."""
    best = None
    for p in _bench_artifacts():
        try:
            j = json.load(open(p))
        except Exception:
            continue
        tail = j.get("tail", "")
        m = re.findall(r'\{"metric": "(\w+)_speedup", "value": ([\d.]+)',
                       tail)
        if j.get("parsed") and isinstance(j["parsed"], dict) \
                and j["parsed"].get("details"):
            best = (p, {k: d.get("speedup")
                        for k, d in j["parsed"]["details"].items()})
        elif m:
            best = (p, {k: float(v) for k, v in m})
    return best


def run_distributed_rung(iters: int):
    """q3 + a string-key agg on an 8-virtual-device CPU mesh, subprocess
    (XLA device count is fixed at backend init, so it cannot run in this
    process next to the TPU backend). Differential vs pandas; wall is
    reported for visibility, not compared to the TPU numbers."""
    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    env["PYTHONPATH"] = here + os.pathsep + env.get("PYTHONPATH", "")
    p = subprocess.run(
        [sys.executable, os.path.join(here, "benchmarks",
                                      "distributed_rung.py"),
         str(iters)],
        capture_output=True, text=True, timeout=600, env=env)
    if p.returncode != 0:
        log("bench: distributed rung FAILED:\n" + p.stderr[-2000:])
        return None
    for line in reversed(p.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except Exception:
            continue
    return None


def main():
    if os.environ.get("SRTPU_BENCH_CPU") == "1":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    # a SIGKILLed predecessor can leave the tunneled chip held for many
    # minutes ("grant unclaimed" on the relay side); one failed init must
    # not zero out the whole bench artifact — retry within a bounded
    # window before giving up
    wait = float(os.environ.get("SRTPU_BENCH_BACKEND_WAIT", 900))
    deadline = time.perf_counter() + wait
    # backend init can FAIL FAST (UNAVAILABLE raise) or HANG inside the
    # plugin's acquire loop in C, past any in-process alarm (both modes
    # observed r5). Probe it in a SUBPROCESS: a hang is bounded by
    # SIGTERM (never SIGKILL — a killed holder wedges the relay grant
    # for hours, docs/performance.md), and only a SUCCESSFUL probe lets
    # this process touch the axon backend at all.
    import subprocess

    def _probe(slice_s: float):
        """(backend_ok, child_abandoned): the child is SIGTERM'd on
        timeout (never SIGKILL — a killed holder wedges the relay
        grant); if it survives even SIGTERM it is left running and the
        caller must stop probing."""
        p = subprocess.Popen(
            [sys.executable, "-c",
             "import jax\njax.devices()\nprint('BACKEND_OK')"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
        try:
            out, _ = p.communicate(timeout=slice_s)
            return (p.returncode == 0 and "BACKEND_OK" in (out or ""),
                    False)
        except subprocess.TimeoutExpired:
            p.terminate()                    # SIGTERM, never SIGKILL
            try:
                p.wait(timeout=60)
            except subprocess.TimeoutExpired:
                log("bench: backend probe ignored SIGTERM; abandoning")
                if p.stdout is not None:
                    p.stdout.close()
                return False, True
            if p.stdout is not None:
                p.stdout.close()
            return False, False

    ok = False
    abandoned = False
    if os.environ.get("SRTPU_BENCH_CPU") == "1":
        ok = True                  # CPU-forced: never touch the chip
    while not ok and not abandoned and time.perf_counter() < deadline:
        got, abandoned = _probe(
            min(120.0, max(deadline - time.perf_counter(), 5.0)))
        if got:
            ok = True
            break
        if abandoned:
            # a child stuck in the C acquire loop is still contending
            # for the chip: spawning more probes just multiplies
            # holders — go straight to the CPU fallback
            break
        log("bench: backend unavailable; retrying...")
        time.sleep(min(20.0, max(deadline - time.perf_counter(), 0)))
    if ok:
        try:
            jax.devices()
        except RuntimeError as e:   # lost the chip between probe and
            ok = False              # init (TOCTOU): fall back
            log(f"bench: backend lost after probe ({e})")
    if not ok:
        # an artifact on the WRONG backend beats an empty one: fall
        # back to CPU, clearly labeled via the platform field (the
        # held-chip wedge produced rc=1/rc=124 artifacts in r3/r4)
        log(f"bench: backend still unavailable after {wait:.0f}s; "
            "falling back to the CPU backend")
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_default_device", "cpu")
        jax.devices()

    from spark_rapids_tpu.api import TpuSession, functions as F

    from benchmarks import tpch, tpcds

    n = int(os.environ.get("SRTPU_BENCH_ROWS", 1_000_000))
    nbig = int(os.environ.get("SRTPU_BENCH_BIG_ROWS", 10_000_000))
    iters = int(os.environ.get("SRTPU_BENCH_ITERS", 3))
    budget = float(os.environ.get("SRTPU_BENCH_BUDGET", 1500))
    nw = min(n, 500_000)
    lineitem = tpch.gen_lineitem(n)
    store_sales = tpcds.gen_store_sales(n)
    date_dim = tpcds.gen_date_dim()
    item = tpcds.gen_item()
    wtab = gen_window_table(nw)
    stab = gen_string_table(n)
    stab_hc = gen_string_table(n, card=100_000)   # byte-rectangle regime
    # big tables generate LAZILY right before their rung: eager generation
    # would burn minutes of budget (and >1 GB resident) even when the
    # budget ends up skipping every big rung
    _big = {}

    def lineitem_big():
        if "l" not in _big:
            _big["l"] = tpch.gen_lineitem(nbig)
        return _big["l"]

    def store_sales_big():
        if "s" not in _big:
            _big["s"] = tpcds.gen_store_sales(nbig)
        return _big["s"]

    nhuge = int(os.environ.get("SRTPU_BENCH_HUGE_ROWS", 100_000_000))

    def store_sales_huge():
        # SF100-class rung (BASELINE.md config #3 ladder): generated
        # COLUMN-PRUNED (q9 touches 3 of the 12 columns; the full table
        # would be ~10 GB host RAM for nothing) and only if the budget
        # survives to the last rung
        if "h" not in _big:
            _big.pop("s", None)       # reclaim the 10M table first
            import pyarrow as pa
            rng = np.random.RandomState(7)
            _big["h"] = pa.table({
                "ss_quantity": pa.array(
                    rng.randint(1, 101, nhuge)),
                "ss_ext_sales_price": pa.array(
                    np.round(rng.uniform(1.0, 20000.0, nhuge), 2)),
                "ss_net_paid": pa.array(
                    np.round(rng.uniform(1.0, 20000.0, nhuge), 2)),
            })
        return _big["h"]
    log(f"bench: ladder on {jax.devices()[0].platform}, {n} rows "
        f"(+{nbig} big rungs), {iters} iters, budget {budget:.0f}s")
    # the budget buys LADDER time: a long backend wait (r5: hours of
    # chip unavailability) must not exhaust it before the first rung
    ladder_t0 = time.perf_counter()

    last_session = [None]

    def eng(q_builder):
        def run():
            s = TpuSession()
            last_session[0] = s
            return q_builder(s).collect_arrow()
        return run

    # ---------------- engine queries (tables via thunk: big rungs
    # generate lazily) ----------------
    def q1_of(tab):
        return eng(lambda s: tpch.q1(s.create_dataframe(tab()), F))

    def q6_of(tab):
        return eng(lambda s: tpch.q6(s.create_dataframe(tab()), F))

    def q9_of(tab):
        return eng(lambda s: tpcds.q9(s.create_dataframe(tab()), F))

    def q28_of(tab):
        return eng(lambda s: tpcds.q28(s.create_dataframe(tab()), F))

    eng_q3 = eng(lambda s: tpcds.q3(s.create_dataframe(store_sales),
                                    s.create_dataframe(date_dim),
                                    s.create_dataframe(item), F))

    def _window_q(s):
        from spark_rapids_tpu.exprs import ColumnRef
        from spark_rapids_tpu.exprs.aggregates import Sum
        return (s.create_dataframe(wtab)
                .with_window_column("wsum", Sum(ColumnRef("v")),
                                    partition_by=["p"],
                                    order_by=[F.col("o").asc()],
                                    frame=("rows", -2, 0)))
    eng_window = eng(_window_q)

    def _strings_q_of(table):
        def q(s):
            return (s.create_dataframe(table)
                    .select(F.upper(F.trim(F.col("s"))).alias("u"),
                            F.substring(F.col("s"), 3, 4).alias("pre"),
                            F.col("v"))
                    .group_by("u", "pre")
                    .agg(F.sum(F.col("v")).with_name("sv"),
                         F.count_star().with_name("n")))
        return q
    eng_strings = eng(_strings_q_of(stab))
    eng_strings_hc = eng(_strings_q_of(stab_hc))

    # ---------------- pandas baselines ----------------
    def base_q1_of(tab):
        def run():
            pdf = tab().to_pandas(date_as_object=False)
            cutoff = (np.datetime64("1998-12-01")
                      - np.timedelta64(90, "D")).astype("datetime64[ns]")
            f = pdf[pdf["l_shipdate"] <= cutoff].copy()
            f["disc_price"] = f["l_extendedprice"] * (1.0 - f["l_discount"])
            f["charge"] = f["disc_price"] * (1.0 + f["l_tax"])
            return f.groupby(["l_returnflag", "l_linestatus"]).agg(
                sum_qty=("l_quantity", "sum"),
                sum_base_price=("l_extendedprice", "sum"),
                sum_disc_price=("disc_price", "sum"),
                sum_charge=("charge", "sum"),
                avg_qty=("l_quantity", "mean"),
                avg_price=("l_extendedprice", "mean"),
                avg_disc=("l_discount", "mean"),
                count_order=("l_quantity", "size")).sort_index()
        return run

    def base_q6_of(tab):
        def run():
            pdf = tab().to_pandas(date_as_object=False)
            m = ((pdf["l_shipdate"] >= np.datetime64("1994-01-01"))
                 & (pdf["l_shipdate"] < np.datetime64("1995-01-01"))
                 & (pdf["l_discount"] >= 0.05) & (pdf["l_discount"] <= 0.07)
                 & (pdf["l_quantity"] < 24.0))
            f = pdf[m]
            return float((f["l_extendedprice"] * f["l_discount"]).sum())
        return run

    def base_q3():
        ss = store_sales.to_pandas()
        dd = date_dim.to_pandas(date_as_object=False)
        it = item.to_pandas()
        dd = dd[dd["d_moy"] == 11]
        it = it[it["i_manufact_id"] == 128]
        j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
        j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
        g = (j.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False)
             ["ss_ext_sales_price"].sum()
             .rename(columns={"ss_ext_sales_price": "sum_agg"}))
        return g.sort_values(["d_year", "sum_agg", "i_brand_id"],
                             ascending=[True, False, True])

    def base_q9_of(tab):
        def run():
            ss = tab().to_pandas()
            out = {}
            for i, (lo, hi) in enumerate(
                    [(1, 20), (21, 40), (41, 60), (61, 80), (81, 100)], 1):
                m = (ss["ss_quantity"] >= lo) & (ss["ss_quantity"] <= hi)
                out[f"cnt{i}"] = int(m.sum())
                out[f"avg_price{i}"] = float(
                    ss.loc[m, "ss_ext_sales_price"].mean())
                out[f"avg_paid{i}"] = float(ss.loc[m, "ss_net_paid"].mean())
            return out
        return run

    def base_q28_of(tab):
        def run():
            ss = tab().to_pandas()
            buckets = [(0, 5, 11, 460, 14930), (6, 10, 91, 1430, 32370),
                       (11, 15, 66, 1480, 3750), (16, 20, 142, 3270, 21910),
                       (21, 25, 135, 2450, 17300), (26, 30, 28, 2340, 33660)]
            rows = []
            for lo, hi, lp, cp, wc in buckets:
                m = ((ss["ss_quantity"] >= lo) & (ss["ss_quantity"] <= hi)
                     & ((ss["ss_list_price"] >= float(lp))
                        | (ss["ss_coupon_amt"] >= float(cp))
                        | (ss["ss_wholesale_cost"] >= float(wc))))
                b = ss.loc[m, "ss_list_price"]
                rows.append((float(b.mean()), int(b.count()),
                             int(b.nunique())))
            return rows
        return run

    def base_strings_of(table):
        def run():
            pdf = table.to_pandas()
            pdf["u"] = pdf["s"].str.strip(" ").str.upper()
            pdf["pre"] = pdf["s"].str.slice(2, 6)
            return (pdf.groupby(["u", "pre"], as_index=False)
                    .agg(sv=("v", "sum"), n=("v", "size")))
        return run
    base_strings = base_strings_of(stab)
    base_strings_hc = base_strings_of(stab_hc)

    def base_window():
        pdf = wtab.to_pandas()
        pdf = pdf.sort_values(["p", "o"], kind="stable")
        pdf["wsum"] = (pdf.groupby("p")["v"]
                       .rolling(3, min_periods=1).sum()
                       .reset_index(level=0, drop=True))
        return pdf

    li = lambda: lineitem          # noqa: E731
    ss_ = lambda: store_sales      # noqa: E731
    workloads = [
        ("tpch_q1", n, q1_of(li), base_q1_of(li), check_q1),
        ("tpch_q6", n, q6_of(li), base_q6_of(li), check_q6),
        ("tpcds_q3", n, eng_q3, base_q3, check_q3),
        ("tpcds_q9", n, q9_of(ss_), base_q9_of(ss_), check_q9),
        ("tpcds_q28", n, q28_of(ss_), base_q28_of(ss_), check_q28),
        ("window_bounded", nw, eng_window, base_window, check_window),
        ("string_transforms", n, eng_strings, base_strings, check_strings),
        ("string_transforms_100k", n, eng_strings_hc, base_strings_hc,
         check_strings),
    ]
    if nbig:
        workloads += [
            ("tpch_q1_10m", nbig, q1_of(lineitem_big),
             base_q1_of(lineitem_big), check_q1),
            ("tpch_q6_10m", nbig, q6_of(lineitem_big),
             base_q6_of(lineitem_big), check_q6),
            ("tpcds_q9_10m", nbig, q9_of(store_sales_big),
             base_q9_of(store_sales_big), check_q9),
            ("tpcds_q28_10m", nbig, q28_of(store_sales_big),
             base_q28_of(store_sales_big), check_q28),
        ]
    if nhuge:
        # SF100-class global-agg rung: the wide-batch path runs the
        # whole 100M-row query as a handful of fused dispatches
        workloads += [
            ("tpcds_q9_100m", nhuge, q9_of(store_sales_huge),
             base_q9_of(store_sales_huge), check_q9),
        ]

    # per-rung trace + metrics artifacts (ISSUE 4 / ISSUE 5): one extra
    # INSTRUMENTED engine run per finished rung — trace AND metric
    # registry enabled together so the rung ships both a Chrome-trace
    # JSON (where the time went) and a final metrics snapshot (HBM /
    # spill / semaphore / shuffle / OOM totals, renderable with
    # python -m spark_rapids_tpu.tools.history --metrics-file). The
    # instrumented run is never the timed run.
    trace_dir = os.environ.get("SRTPU_BENCH_TRACE_DIR",
                               os.path.join(os.getcwd(), "bench_traces"))
    metrics_dir = os.environ.get("SRTPU_BENCH_METRICS_DIR",
                                 os.path.join(os.getcwd(),
                                              "bench_metrics"))
    trace_on = os.environ.get("SRTPU_BENCH_TRACE", "1") != "0"

    def capture_artifacts(name, eng_fn):
        """(trace_path, metrics_path) for one instrumented run; either
        may be None — best effort, a wedged capture never fails the
        rung."""
        if not trace_on:
            return None, None
        tpath = os.path.join(trace_dir, f"trace_{name}.json")
        mpath = os.path.join(metrics_dir, f"metrics_{name}.json")
        saved = {k: os.environ.get(k)
                 for k in ("SPARK_RAPIDS_TPU_TRACE_ENABLED",
                           "SPARK_RAPIDS_TPU_TRACE_OUTPUT",
                           "SPARK_RAPIDS_TPU_METRICS_ENABLED")}
        got_metrics = None
        try:
            os.makedirs(trace_dir, exist_ok=True)
            os.makedirs(metrics_dir, exist_ok=True)
            os.environ["SPARK_RAPIDS_TPU_TRACE_ENABLED"] = "true"
            os.environ["SPARK_RAPIDS_TPU_TRACE_OUTPUT"] = tpath
            os.environ["SPARK_RAPIDS_TPU_METRICS_ENABLED"] = "true"
            eng_fn()
            try:
                from spark_rapids_tpu.metrics import (registry_snapshot,
                                                      active_registry)
                reg = active_registry()
                if reg is not None:
                    with open(mpath, "w") as f:
                        json.dump({"rung": name,
                                   "snapshot": registry_snapshot(reg)},
                                  f, sort_keys=True, default=float)
                    got_metrics = mpath
            except Exception as e:           # noqa: BLE001 - best effort
                log(f"bench: {name} metrics snapshot failed: {e}")
            return tpath, got_metrics
        except Exception as e:               # noqa: BLE001 - best effort
            log(f"bench: {name} trace capture failed: {e}")
            return None, got_metrics
        finally:
            for k, v in saved.items():       # restore, don't clobber
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            from spark_rapids_tpu.trace import install_tracer
            install_tracer(None)   # drop the buffer between rungs
            from spark_rapids_tpu.metrics import shutdown_metrics
            shutdown_metrics()     # stop the sampler between rungs

    details = {}
    skipped = []
    failed = []
    wrong = []
    for name, rows, eng_fn, base_fn, check_fn in workloads:
        elapsed = time.perf_counter() - ladder_t0
        if elapsed > budget:
            skipped.append(name)
            log(f"bench: {name:18s} SKIPPED (budget {budget:.0f}s "
                f"exhausted at {elapsed:.0f}s)")
            continue
        if name == "tpcds_q9_10m":
            _big.pop("l", None)      # last lineitem rung done: ~1 GB back
        try:
            from spark_rapids_tpu.plan import exec_cache
            cache0 = exec_cache.stats()
            t0 = time.perf_counter()
            eng_res = eng_fn()                # COLD run incl. compile
            warm = time.perf_counter() - t0
            cache_cold = exec_cache.stats()
            eng_s, eng_res = _time_min(eng_fn, iters)
            cache_warm = exec_cache.stats()
            placement = getattr(last_session[0], "last_placement",
                                None) or "?"
            # coded not-on-device summary (ISSUE 7; schema in
            # docs/tuning.md): the artifact itself says WHY a rung
            # stayed on host — {} for all-device rungs
            pl_report = getattr(last_session[0], "last_placement_report",
                                None) or {}
            base_s, base_res = _time_min(base_fn, iters)
        except Exception as e:                # noqa: BLE001
            # INFRA failure (OOM, backend error): must not discard the
            # finished rungs; listed in the summary, rc stays 0 as long
            # as some rung completed
            failed.append(name)
            log(f"bench: {name:18s} FAILED: {type(e).__name__}: {e}")
            continue
        try:
            check_fn(eng_res, base_res)       # per-workload, immediately
        except AssertionError as e:
            # WRONG ANSWER: a correctness regression always fails the
            # run (rc=1), unlike infra flakes above
            wrong.append(name)
            log(f"bench: {name:18s} WRONG RESULT: {e}")
            continue
        speedup = base_s / eng_s
        # cold-vs-warm compile split (ISSUE 6; schema note in
        # docs/tuning.md): warm_s keeps its historical meaning — the
        # FIRST run of the query in this process (the cold warm-up,
        # including every trace + XLA compile the persistent tier did
        # not serve); engine_s is the warm best-of-iters. The
        # executable-cache counter deltas attribute WHERE the cold cost
        # went and prove the warm iterations recompile nothing.
        details[name] = {
            "engine_s": round(eng_s, 4), "baseline_s": round(base_s, 4),
            "speedup": round(speedup, 3), "placement": placement,
            "rows_per_sec": round(rows / eng_s, 1),
            "warm_s": round(warm, 1), "checked": True,
            "placement_reasons": pl_report.get("codes") or {},
            "compile": {
                "cold": {k: round(cache_cold[k] - cache0[k], 3)
                         for k in cache_cold},
                "warm": {k: round(cache_warm[k] - cache_cold[k], 3)
                         for k in cache_warm},
            },
        }
        # adaptive-execution decisions the LAST engine run made
        # (ISSUE 19; kind -> count, {} when none fired — schema note in
        # docs/tuning.md): the ladder artifact shows WHETHER runtime
        # re-planning touched a rung, not just how fast it went
        aqe_counts = {}
        for d in getattr(last_session[0], "last_aqe_decisions",
                         None) or []:
            aqe_counts[d["kind"]] = aqe_counts.get(d["kind"], 0) + 1
        details[name]["aqe"] = aqe_counts
        # emit the metric line NOW — a later failure or timeout (even a
        # wedged best-effort trace run below) must never discard a
        # finished workload's result
        print(json.dumps({"metric": name + "_speedup", "value": speedup,
                          "unit": "x_vs_pandas", "vs_baseline": speedup,
                          "platform": jax.devices()[0].platform}),
              flush=True)
        cold_compile = details[name]["compile"]["cold"]["compile_s"]
        warm_compile = details[name]["compile"]["warm"]["compile_s"]
        log(f"bench: {name:18s} engine {eng_s:7.3f}s [{placement:6s}] "
            f"pandas {base_s:7.3f}s -> {speedup:5.2f}x "
            f"(cold {warm:.1f}s incl. {cold_compile:.1f}s compile; "
            f"warm recompiled {warm_compile:.1f}s, checked)")
        tr_path, m_path = capture_artifacts(name, eng_fn)
        details[name]["trace"] = tr_path
        details[name]["metrics"] = m_path

    # ---------------- distributed rung (subprocess) ----------------
    dist = None
    if os.environ.get("SRTPU_BENCH_DIST", "1") != "0" \
            and time.perf_counter() - ladder_t0 < budget:
        try:
            dist = run_distributed_rung(iters)
        except Exception as e:                       # noqa: BLE001
            log(f"bench: distributed rung error: {e}")
        if dist:
            log(f"bench: distributed(8dev) {dist}")

    # ---------------- regression gate ----------------
    prev = previous_bench()
    regressions = {}
    if prev:
        prev_path, prev_speeds = prev
        for k, d in details.items():
            p = prev_speeds.get(k)
            if p and d["speedup"] < 0.8 * p:
                regressions[k] = {"prev": p, "now": d["speedup"]}
        if regressions:
            log(f"bench: REGRESSIONS vs {os.path.basename(prev_path)}: "
                f"{regressions}")

    geo = (float(np.exp(np.mean([np.log(d["speedup"])
                                 for d in details.values()])))
           if details else 0.0)     # budget ate everything: valid JSON > NaN
    dev = [d["speedup"] for d in details.values()
           if d["placement"] == "device"]
    geo_dev = (float(np.exp(np.mean(np.log(dev)))) if dev else None)
    # one-line-diffable regression surface (schema note in
    # docs/tuning.md): top-level geomean + device/host rung tally, so
    # BENCH_rXX rounds compare on two keys instead of a details crawl
    placement_counts = {"device": 0, "host": 0}
    for d in details.values():
        placement_counts[d["placement"]] = \
            placement_counts.get(d["placement"], 0) + 1
    print(json.dumps({
        "metric": "ladder_geomean_speedup",
        "value": round(geo, 3),
        "unit": "x_vs_pandas",
        "vs_baseline": round(geo, 3),
        "geomean": round(geo, 3),
        "placement_counts": placement_counts,
        "platform": jax.devices()[0].platform,
        "device_only_geomean": (round(geo_dev, 3)
                                if geo_dev is not None else None),
        "device_workloads": len(dev),
        "skipped": skipped,
        "failed": failed,
        "wrong": wrong,
        "distributed": dist,
        "regressions": regressions,
        "wall_s": round(time.perf_counter() - START, 1),
        "details": details,
    }))
    # one-line machine-checkable delta vs the newest prior BENCH_r*.json
    # (ISSUE 15 satellite): the SAME differ the tools/regress CLI
    # exposes, so ladder rounds land with evidence, not eyeballed
    # geomeans — golden-tested in tests/test_ops.py
    try:
        from spark_rapids_tpu.tools.regress import (
            diff_bench, format_bench_delta, load_bench, normalize_bench)
        priors = _bench_artifacts()
        if priors and details:
            cur = normalize_bench({"geomean": round(geo, 3),
                                   "placement_counts": placement_counts,
                                   "details": details})
            delta = diff_bench(load_bench(priors[-1]), cur)
            log("bench: " + format_bench_delta(
                delta, os.path.basename(priors[-1])))
    except Exception as e:                           # noqa: BLE001
        log(f"bench: regress delta unavailable: {e}")

    if wrong or (failed and not details):
        # correctness regressions ALWAYS fail the run; infra failures
        # only when nothing completed (a partial ladder with real
        # numbers beats rc=1 discarding them)
        sys.exit(1)


if __name__ == "__main__":
    main()
