"""Benchmark: real TPC-H Q1 on the device engine (BASELINE.md ladder #2).

Generated lineitem (benchmarks/tpch.py, TPC-H column domains), the full Q1
pricing-summary query — date filter -> projections -> string-keyed grouped
aggregation (8 aggregates). Baseline = the same query through pandas on
this host's CPU (the role CPU Spark plays for the reference's speedups).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Env: SRTPU_BENCH_CPU=1 forces the JAX CPU backend; SRTPU_BENCH_ROWS
overrides the row count.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    if os.environ.get("SRTPU_BENCH_CPU") == "1":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import pyarrow as pa

    from spark_rapids_tpu.api import TpuSession, functions as F

    from benchmarks import tpch

    n = int(os.environ.get("SRTPU_BENCH_ROWS", 1_000_000))
    table = tpch.gen_lineitem(n)
    log(f"bench: TPC-H Q1, {n}-row lineitem on {jax.devices()[0].platform}")

    def run_engine():
        s = TpuSession()
        return tpch.q1(s.create_dataframe(table), F).collect_arrow()

    # warm-up (compilation) then timed runs; min-of-iters on both sides
    # (wall-clock on a shared host is noisy — min is the stable statistic)
    t0 = time.perf_counter()
    res = run_engine()
    warm = time.perf_counter() - t0
    log(f"bench: warm-up (incl. compile) {warm:.2f}s, groups={res.num_rows}")
    iters = 5
    engine_s = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        res = run_engine()
        engine_s = min(engine_s, time.perf_counter() - t0)
    engine_rate = n / engine_s
    log(f"bench: engine {engine_s:.3f}s/iter -> {engine_rate:,.0f} rows/s")

    # pandas CPU baseline (the reference's CPU-Spark role). Parity of
    # starting point: each iteration begins from the SAME in-memory Arrow
    # table the engine ingests (the engine side pays H2D per iteration;
    # pandas pays its own arrow->numpy materialization).
    cutoff = np.datetime64("1998-12-01") - np.timedelta64(90, "D")
    base_s = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        pdf = table.to_pandas(date_as_object=False)
        f = pdf[pdf["l_shipdate"] <= cutoff.astype("datetime64[ns]")].copy()
        f["disc_price"] = f["l_extendedprice"] * (1.0 - f["l_discount"])
        f["charge"] = f["disc_price"] * (1.0 + f["l_tax"])
        base = f.groupby(["l_returnflag", "l_linestatus"]).agg(
            sum_qty=("l_quantity", "sum"),
            sum_base_price=("l_extendedprice", "sum"),
            sum_disc_price=("disc_price", "sum"),
            sum_charge=("charge", "sum"),
            avg_qty=("l_quantity", "mean"),
            avg_price=("l_extendedprice", "mean"),
            avg_disc=("l_discount", "mean"),
            count_order=("l_quantity", "size")).sort_index()
        base_s = min(base_s, time.perf_counter() - t0)
    base_rate = n / base_s
    log(f"bench: pandas {base_s:.3f}s/iter -> {base_rate:,.0f} rows/s")

    # correctness spot-check against the baseline
    got = res.to_pandas().set_index(["l_returnflag", "l_linestatus"]) \
             .sort_index()
    np.testing.assert_allclose(got["sum_disc_price"].to_numpy(),
                               base["sum_disc_price"].to_numpy(),
                               rtol=1e-9)
    np.testing.assert_array_equal(got["count_order"].to_numpy(),
                                  base["count_order"].to_numpy())

    print(json.dumps({
        "metric": "tpch_q1_rows_per_sec",
        "value": round(engine_rate, 1),
        "unit": "rows/s",
        "vs_baseline": round(engine_rate / base_rate, 3),
    }))


if __name__ == "__main__":
    main()
