"""TPC-derived benchmark queries and data generators (ref: the NDS/TPC-DS
suites the reference benchmarks against live in NVIDIA/spark-rapids-benchmarks;
BASELINE.md config ladder steps 2-3 name TPC-H SF10 q1/q6 and TPC-DS SF100
q3/q9/q28 as this repo's targets)."""
