"""Distributed bench rung: TPC-DS q3 and a string-key aggregation planned
onto an 8-virtual-device CPU mesh (run as a subprocess of bench.py with
JAX_PLATFORMS=cpu and --xla_force_host_platform_device_count=8).

This measures the SPMD path every round (VERDICT r3 #5: "add a distributed
rung so the SPMD path is measured, not just dryrun-validated") — the same
planner lowering the driver's dryrun_multichip validates, but timed and
differentially checked against pandas. Wall times are CPU-mesh times, for
trend tracking only; they are not comparable to the TPU ladder.

Prints ONE JSON line: {"q3_s": ..., "agg_s": ..., "n_devices": 8, "ok": true}
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def main(iters: int = 3) -> None:
    import jax
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    from benchmarks import tpcds
    from spark_rapids_tpu.api import TpuSession, functions as F
    from spark_rapids_tpu.parallel import make_mesh

    devs = jax.devices("cpu")
    n_dev = min(8, len(devs))
    mesh = make_mesh(devices=devs[:n_dev])

    n = 1_000_000
    ss = tpcds.gen_store_sales(n)
    dd = tpcds.gen_date_dim()
    it = tpcds.gen_item()

    def session():
        return TpuSession({
            "spark.rapids.tpu.distributed.enabled": True,
            "spark.rapids.tpu.sql.optimizer.enabled": False,
        }, mesh=mesh)

    # --- q3: scan -> filter -> join -> join -> grouped agg, distributed
    def q3():
        s = session()
        q = tpcds.q3(s.create_dataframe(ss), s.create_dataframe(dd),
                     s.create_dataframe(it), F)
        return q, s

    q, s = q3()
    plan = q.explain()
    assert "DistributedPipeline" in plan, plan
    got = None
    best_q3 = float("inf")
    for _ in range(max(iters, 1)):
        t0 = time.perf_counter()
        q, s = q3()
        got = q.collect_arrow().to_pandas()
        best_q3 = min(best_q3, time.perf_counter() - t0)
    # differential check vs pandas
    pss, pdd, pit = ss.to_pandas(), dd.to_pandas(), it.to_pandas()
    pdd = pdd[pdd["d_moy"] == 11]
    pit = pit[pit["i_manufact_id"] == 128]
    j = pss.merge(pdd, left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j.merge(pit, left_on="ss_item_sk", right_on="i_item_sk")
    want = (j.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False)
            ["ss_ext_sales_price"].sum())
    assert len(got) == len(want), (len(got), len(want))
    np.testing.assert_allclose(
        np.sort(got["sum_agg"].to_numpy()),
        np.sort(want["ss_ext_sales_price"].to_numpy()), rtol=1e-9)

    # --- grouped agg over a string key, distributed
    import pyarrow as pa
    rng = np.random.RandomState(3)
    keys = np.asarray([f"k{i:03d}" for i in range(500)], dtype=object)
    at = pa.table({"k": pa.array(keys[rng.randint(0, 500, n)]),
                   "v": pa.array(rng.uniform(-10, 10, n))})

    def agg():
        s = session()
        df = s.create_dataframe(at)
        return (df.group_by("k")
                .agg(F.sum(F.col("v")).with_name("sv"),
                     F.count_star().with_name("n")), s)

    q, s = agg()
    plan = q.explain()
    assert "DistributedPipeline" in plan, plan
    best_agg = float("inf")
    for _ in range(max(iters, 1)):
        t0 = time.perf_counter()
        q, s = agg()
        got = q.collect_arrow().to_pandas()
        best_agg = min(best_agg, time.perf_counter() - t0)
    want = (at.to_pandas().groupby("k", as_index=False)
            .agg(sv=("v", "sum"), n=("v", "size")))
    got = got.sort_values("k").reset_index(drop=True)
    want = want.sort_values("k").reset_index(drop=True)
    assert len(got) == len(want)
    np.testing.assert_array_equal(got["k"], want["k"])
    np.testing.assert_allclose(got["sv"], want["sv"], rtol=1e-9)
    np.testing.assert_array_equal(got["n"], want["n"])

    # --- cross-PROCESS sort and window through the TCP shuffle cluster
    # (r4 added range-partitioned sorts and hash-partitioned windows to
    # shuffle/cluster.py with differential tests but no timed rung —
    # VERDICT r4 weak #8; smaller row count: every shuffled byte crosses
    # a real socket)
    from spark_rapids_tpu.exprs import ColumnRef
    from spark_rapids_tpu.exprs.aggregates import Sum as AggSum
    from spark_rapids_tpu.shuffle.cluster import LocalCluster
    nc = n // 4
    st = pa.table({"a": pa.array(rng.randint(-10**6, 10**6, nc)),
                   "b": pa.array(rng.uniform(0, 1, nc))})
    wt = pa.table({"p": pa.array(rng.randint(0, 64, nc)),
                   "o": pa.array(rng.permutation(nc)),
                   "v": pa.array(np.round(rng.uniform(-5, 5, nc), 2))})
    cl = LocalCluster(2)
    try:
        s = session()
        best_sort = best_win = float("inf")
        sorted_got = wgot = None
        for _ in range(max(iters, 1)):
            df = s.create_dataframe(st).order_by(F.col("a").asc())
            t0 = time.perf_counter()
            sorted_got = cl.execute(df).to_pandas()
            best_sort = min(best_sort, time.perf_counter() - t0)
        a = sorted_got["a"].to_numpy()
        assert len(a) == nc and (a[:-1] <= a[1:]).all()
        for _ in range(max(iters, 1)):
            dfw = s.create_dataframe(wt).with_window_column(
                "wsum", AggSum(ColumnRef("v")), partition_by=["p"],
                order_by=[F.col("o").asc()], frame=("rows", -2, 0))
            t0 = time.perf_counter()
            wgot = cl.execute(dfw).to_pandas()
            best_win = min(best_win, time.perf_counter() - t0)
        wgot = wgot.sort_values(["p", "o"])
        wp = wt.to_pandas().sort_values(["p", "o"])
        wexp = (wp.groupby("p")["v"].rolling(3, min_periods=1).sum()
                .reset_index(level=0, drop=True))
        np.testing.assert_allclose(wgot["wsum"].to_numpy(),
                                   wexp.to_numpy(), rtol=1e-9, atol=1e-9)
    finally:
        cl.shutdown()

    # --- skewed_join micro-rung (ISSUE 19): a Zipf key column puts most
    # of one join side into a single hash partition, so the AQE
    # read-side re-plan must salt-split it (and coalesce the tiny
    # remainder) — timed with AQE on, byte-identical vs AQE off
    from spark_rapids_tpu.config import TpuConf
    nk = n // 8
    # zipf(2.5) puts ~75% of rows on key 0: with 3 reduce partitions the
    # hot partition clears threshold x mean (2.0 x 1/3). Integer values
    # + a total order keep the differential exact: int sums are
    # associative, so split/coalesced partial aggs cannot drift
    zk = np.minimum(rng.zipf(2.5, nk), 64).astype(np.int64) - 1
    left = pa.table({"k": pa.array(zk),
                     "v": pa.array(rng.randint(0, 1000, nk)
                                   .astype(np.int64))})
    # small multiplicity (~16 matches/key): the rung times the skew
    # re-plan, not a multiplicative join blow-up
    right = pa.table({"k2": pa.array(rng.randint(0, 64, 1024)
                                     .astype(np.int64)),
                      "w": pa.array(rng.randint(0, 100, 1024)
                                    .astype(np.int64))})

    def skew_query(s):
        df = s.create_dataframe(left)
        return (df.join(s.create_dataframe(right),
                        on=[(F.col("k"), F.col("k2"))], how="inner")
                .group_by("k")
                .agg(F.sum(F.col("v")).with_name("sv"),
                     F.count_star().with_name("n"))
                .order_by(F.col("k").asc()))

    def skew_conf(on: bool):
        # skew.minBytes drops so the CPU-rung byte counts clear the
        # don't-bother floor; the decision thresholds themselves stay
        # at their defaults
        return (TpuConf()
                .set("spark.rapids.tpu.aqe.enabled", on)
                .set("spark.rapids.tpu.aqe.skew.minBytes", 64 * 1024))

    best_skew = float("inf")
    aqe_counts: dict = {}
    cl = LocalCluster(3, shuffle_join_min_rows=1024, conf=skew_conf(True))
    try:
        s = session()
        sgot = None
        for _ in range(max(iters, 1)):
            t0 = time.perf_counter()
            sgot = cl.execute(skew_query(s)).to_pandas()
            best_skew = min(best_skew, time.perf_counter() - t0)
        for d in (s.last_aqe_decisions or []):
            aqe_counts[d["kind"]] = aqe_counts.get(d["kind"], 0) + 1
        assert aqe_counts.get("skew_split", 0) >= 1, aqe_counts
        assert aqe_counts.get("coalesce_partitions", 0) >= 1, aqe_counts
    finally:
        cl.shutdown()
    cl = LocalCluster(3, shuffle_join_min_rows=1024, conf=skew_conf(False))
    try:
        s = session()
        soff = cl.execute(skew_query(s)).to_pandas()
        assert not (s.last_aqe_decisions or []), s.last_aqe_decisions
    finally:
        cl.shutdown()
    # byte-identity, not allclose: re-planning may only change the
    # execution shape, never the answer
    import pandas.testing as pdt
    pdt.assert_frame_equal(sgot, soff)

    print(json.dumps({"q3_s": round(best_q3, 3),
                      "agg_s": round(best_agg, 3),
                      "xproc_sort_s": round(best_sort, 3),
                      "xproc_window_s": round(best_win, 3),
                      "xproc_rows": nc,
                      "skewed_join_s": round(best_skew, 3),
                      "skewed_join_rows": nk,
                      "aqe": aqe_counts,
                      "n_devices": n_dev, "rows": n, "ok": True}))


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 3)
