"""TPC queries as SQL text — the interface reference users actually write.
Run with `session.sql()` after registering lineitem / store_sales /
date_dim / item temp views (generators in tpch.py / tpcds.py)."""

TPCH_Q1 = """
SELECT l_returnflag, l_linestatus,
       sum(l_quantity)                                      AS sum_qty,
       sum(l_extendedprice)                                 AS sum_base_price,
       sum(l_extendedprice * (1 - l_discount))              AS sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
       avg(l_quantity)                                      AS avg_qty,
       avg(l_extendedprice)                                 AS avg_price,
       avg(l_discount)                                      AS avg_disc,
       count(*)                                             AS count_order
FROM lineitem
WHERE l_shipdate <= date '1998-12-01' - interval '90' day
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""

TPCH_Q6 = """
SELECT sum(l_extendedprice * l_discount) AS revenue
FROM lineitem
WHERE l_shipdate >= date '1994-01-01'
  AND l_shipdate < date '1995-01-01'
  AND l_discount BETWEEN 0.05 AND 0.07
  AND l_quantity < 24
"""

TPCDS_Q3 = """
SELECT d_year, i_brand_id, i_brand, sum(ss_ext_sales_price) AS sum_agg
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manufact_id = 128
  AND d_moy = 11
GROUP BY d_year, i_brand_id, i_brand
ORDER BY d_year, sum_agg DESC, i_brand_id
"""

TPCDS_Q9_BUCKET = """
SELECT count(CASE WHEN ss_quantity BETWEEN {lo} AND {hi}
                  THEN 1 ELSE NULL END)                        AS cnt,
       avg(CASE WHEN ss_quantity BETWEEN {lo} AND {hi}
                THEN ss_ext_sales_price ELSE NULL END)          AS avg_price,
       avg(CASE WHEN ss_quantity BETWEEN {lo} AND {hi}
                THEN ss_net_paid ELSE NULL END)                 AS avg_paid
FROM store_sales
"""


def register_tpch(session, n_rows: int = 100_000):
    from . import tpch
    session.create_dataframe(tpch.gen_lineitem(n_rows)) \
        .create_or_replace_temp_view("lineitem")


def register_tpcds(session, n_rows: int = 100_000):
    from . import tpcds
    session.create_dataframe(tpcds.gen_store_sales(n_rows)) \
        .create_or_replace_temp_view("store_sales")
    session.create_dataframe(tpcds.gen_date_dim()) \
        .create_or_replace_temp_view("date_dim")
    session.create_dataframe(tpcds.gen_item()) \
        .create_or_replace_temp_view("item")
