"""TPC-DS subset: store_sales/date_dim/item generators + q3/q9(/q28).

q3  — star join (date_dim x store_sales x item) into a string-keyed grouped
      aggregation with a descending order by aggregate.
q9  — conditional aggregation: bucketed sums/avgs/counts over quantity
      ranges via CASE WHEN, the engine-level execution of the reference's
      scalar-subquery formulation.
q28 — bucketed avg/count + count(distinct) over list-price ranges (needs
      distinct aggregate support).
"""
from __future__ import annotations

import numpy as np
import pyarrow as pa

BRANDS = [f"brand#{i}" for i in range(1, 61)]


def gen_store_sales(n_rows: int, seed: int = 7, n_items: int = 2000,
                    n_dates: int = 1826) -> pa.Table:
    rng = np.random.RandomState(seed)
    return pa.table({
        "ss_sold_date_sk": pa.array(rng.randint(0, n_dates, n_rows)),
        "ss_item_sk": pa.array(rng.randint(0, n_items, n_rows)),
        "ss_customer_sk": pa.array(rng.randint(0, n_rows // 8 + 2, n_rows)),
        "ss_quantity": pa.array(rng.randint(1, 101, n_rows)),
        "ss_ext_sales_price": pa.array(
            np.round(rng.uniform(1.0, 20000.0, n_rows), 2)),
        "ss_ext_discount_amt": pa.array(
            np.round(rng.uniform(0.0, 1000.0, n_rows), 2)),
        "ss_net_paid": pa.array(np.round(rng.uniform(1.0, 20000.0, n_rows),
                                         2)),
        "ss_net_profit": pa.array(
            np.round(rng.uniform(-5000.0, 5000.0, n_rows), 2)),
        "ss_list_price": pa.array(np.round(rng.uniform(1.0, 200.0, n_rows),
                                           2)),
        "ss_coupon_amt": pa.array(np.round(rng.uniform(0.0, 500.0, n_rows),
                                           2)),
        "ss_wholesale_cost": pa.array(
            np.round(rng.uniform(1.0, 100.0, n_rows), 2)),
    })


def gen_date_dim(n_dates: int = 1826, seed: int = 8) -> pa.Table:
    # 5 years of days starting 1998-01-01
    days = np.arange(n_dates)
    dates = np.datetime64("1998-01-01") + days
    years = dates.astype("datetime64[Y]").astype(int) + 1970
    moys = dates.astype("datetime64[M]").astype(int) % 12 + 1
    return pa.table({
        "d_date_sk": pa.array(days),
        "d_date": pa.array(dates.astype("datetime64[D]")),
        "d_year": pa.array(years.astype(np.int32)),
        "d_moy": pa.array(moys.astype(np.int32)),
    })


def gen_item(n_items: int = 2000, seed: int = 9) -> pa.Table:
    rng = np.random.RandomState(seed)
    return pa.table({
        "i_item_sk": pa.array(np.arange(n_items)),
        "i_brand_id": pa.array(rng.randint(1, 61, n_items).astype(np.int32)),
        "i_brand": pa.array([BRANDS[b - 1] for b in
                             rng.randint(1, 61, n_items)]),
        "i_manufact_id": pa.array(rng.randint(1, 251, n_items)
                                  .astype(np.int32)),
    })


def q3(store_sales, date_dim, item, F, manufact_id: int = 128):
    """Brand revenue by year for one manufacturer, November only."""
    return (store_sales
            .join(date_dim.filter(F.col("d_moy") == F.lit(11)),
                  on=[("ss_sold_date_sk", "d_date_sk")])
            .join(item.filter(F.col("i_manufact_id") == F.lit(manufact_id)),
                  on=[("ss_item_sk", "i_item_sk")])
            .group_by("d_year", "i_brand_id", "i_brand")
            .agg(F.sum(F.col("ss_ext_sales_price")).with_name("sum_agg"))
            .order_by("d_year", F.desc("sum_agg"), "i_brand_id"))


def q9(store_sales, F):
    """Bucketed quantity-range statistics via conditional aggregation."""
    aggs = []
    buckets = [(1, 20), (21, 40), (41, 60), (61, 80), (81, 100)]
    for i, (lo, hi) in enumerate(buckets, 1):
        in_b = ((F.col("ss_quantity") >= F.lit(lo))
                & (F.col("ss_quantity") <= F.lit(hi)))
        one_if = F.when(in_b, F.lit(1)).otherwise(F.lit(None))
        price_if = F.when(in_b, F.col("ss_ext_sales_price")) \
                    .otherwise(F.lit(None))
        paid_if = F.when(in_b, F.col("ss_net_paid")).otherwise(F.lit(None))
        aggs += [F.count(one_if).with_name(f"cnt{i}"),
                 F.avg(price_if).with_name(f"avg_price{i}"),
                 F.avg(paid_if).with_name(f"avg_paid{i}")]
    return store_sales.agg(*aggs)


def q28(store_sales, F):
    """Bucketed list-price stats incl. distinct counts (6 buckets)."""
    buckets = [(0, 5, 11, 460, 14930), (6, 10, 91, 1430, 32370),
               (11, 15, 66, 1480, 3750), (16, 20, 142, 3270, 21910),
               (21, 25, 135, 2450, 17300), (26, 30, 28, 2340, 33660)]
    outs = []
    for lo, hi, lp, cp, wc in buckets:
        b = store_sales.filter(
            (F.col("ss_quantity") >= F.lit(lo))
            & (F.col("ss_quantity") <= F.lit(hi))
            & ((F.col("ss_list_price") >= F.lit(float(lp)))
               | (F.col("ss_coupon_amt") >= F.lit(float(cp)))
               | (F.col("ss_wholesale_cost") >= F.lit(float(wc)))))
        outs.append(b.agg(
            F.avg(F.col("ss_list_price")).with_name("b_avg"),
            F.count(F.col("ss_list_price")).with_name("b_cnt"),
            F.count_distinct(F.col("ss_list_price")).with_name("b_cntd")))
    res = outs[0]
    for o in outs[1:]:
        res = res.union(o)
    return res
