"""TPC-H lineitem generator + q1/q6 through the DataFrame API.

The generator follows the TPC-H column domains (dbgen's lineitem spec) at a
row-count scale rather than SF so it runs anywhere: SF1 lineitem ~= 6M rows.
Queries are written exactly as their SQL shapes, so they exercise the
engine's hot path: date filter -> project -> (string-keyed) grouped
aggregation.
"""
from __future__ import annotations

import numpy as np
import pyarrow as pa


def gen_lineitem(n_rows: int, seed: int = 42) -> pa.Table:
    rng = np.random.RandomState(seed)
    base = np.datetime64("1992-01-01")
    shipdate = base + rng.randint(0, 2526, n_rows)  # through 1998-11-28
    receiptdate = shipdate + rng.randint(1, 31, n_rows)
    qty = rng.randint(1, 51, n_rows).astype(np.float64)
    price = np.round(rng.uniform(900.0, 105000.0, n_rows), 2)
    return pa.table({
        "l_orderkey": pa.array(rng.randint(1, n_rows // 4 + 2, n_rows)),
        "l_quantity": pa.array(qty),
        "l_extendedprice": pa.array(price),
        "l_discount": pa.array(np.round(rng.randint(0, 11, n_rows) / 100.0,
                                        2)),
        "l_tax": pa.array(np.round(rng.randint(0, 9, n_rows) / 100.0, 2)),
        "l_returnflag": pa.array(rng.choice(["A", "N", "R"], n_rows)),
        "l_linestatus": pa.array(rng.choice(["O", "F"], n_rows)),
        "l_shipdate": pa.array(shipdate.astype("datetime64[D]")),
        "l_receiptdate": pa.array(receiptdate.astype("datetime64[D]")),
        "l_shipmode": pa.array(rng.choice(
            ["AIR", "MAIL", "RAIL", "SHIP", "TRUCK", "REG AIR", "FOB"],
            n_rows)),
    })


def q1(df, F):
    """Pricing summary report (TPC-H Q1)."""
    cutoff = np.datetime64("1998-12-01") - np.timedelta64(90, "D")
    disc_price = F.col("l_extendedprice") * (F.lit(1.0) -
                                             F.col("l_discount"))
    charge = disc_price * (F.lit(1.0) + F.col("l_tax"))
    return (df.filter(F.col("l_shipdate") <= F.lit(cutoff))
            .with_column("disc_price", disc_price)
            .with_column("charge", charge)
            .group_by("l_returnflag", "l_linestatus")
            .agg(F.sum(F.col("l_quantity")).with_name("sum_qty"),
                 F.sum(F.col("l_extendedprice")).with_name("sum_base_price"),
                 F.sum(F.col("disc_price")).with_name("sum_disc_price"),
                 F.sum(F.col("charge")).with_name("sum_charge"),
                 F.avg(F.col("l_quantity")).with_name("avg_qty"),
                 F.avg(F.col("l_extendedprice")).with_name("avg_price"),
                 F.avg(F.col("l_discount")).with_name("avg_disc"),
                 F.count_star().with_name("count_order"))
            .order_by("l_returnflag", "l_linestatus"))


def q6(df, F):
    """Forecasting revenue change (TPC-H Q6): pure filter + reduction."""
    lo = np.datetime64("1994-01-01")
    hi = np.datetime64("1995-01-01")
    return (df.filter((F.col("l_shipdate") >= F.lit(lo))
                      & (F.col("l_shipdate") < F.lit(hi))
                      & (F.col("l_discount") >= F.lit(0.05))
                      & (F.col("l_discount") <= F.lit(0.07))
                      & (F.col("l_quantity") < F.lit(24.0)))
            .agg(F.sum(F.col("l_extendedprice") * F.col("l_discount"))
                 .with_name("revenue")))
