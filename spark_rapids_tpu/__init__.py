"""spark-rapids-tpu: a TPU-native columnar SQL execution framework.

A ground-up re-design of the capabilities of the RAPIDS Accelerator for Apache
Spark (reference: /root/reference, spark-rapids 24.12) for TPU hardware:
columnar batches are shape-bucketed jax.Arrays in HBM, operators compile to
XLA computations (jax.numpy / Pallas), distribution rides jax.sharding meshes
with ICI/DCN collectives, and a tiered HBM->host->disk memory runtime provides
spill + OOM-retry semantics.
"""

import jax as _jax

# Spark semantics require real int64/float64 columns (bigint/double).
# On TPU f64 is software-emulated by XLA; the planner prefers f32/bf16 where
# the user opts into approximate float, but parity mode needs x64 on.
_jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: large variadic sorts compile in ~40 s
# per signature on TPU; caching makes that a once-ever cost (the analog of
# the reference shipping precompiled fatbins per architecture). Override
# with SRTPU_COMPILE_CACHE=/path or disable with SRTPU_COMPILE_CACHE=0.
import os as _os

def _machine_fingerprint() -> str:
    """CPU-feature fingerprint partitioning the cache per machine type.

    XLA:CPU persists AOT executables specialized to the compiling host's
    ISA features; jax loads them on a DIFFERENT host with only a warning
    ("could lead to execution errors such as SIGILL") — measured here as
    a segfault ~92% into the test suite when the cache was written by an
    avx512-richer machine. TPU executables are target-serialized and
    machine-independent, but they ride the same cache dir, so the whole
    dir is keyed: same machine -> warm cache across rounds (critical:
    first-ever sort-kernel compiles take minutes); new machine -> cold
    but correct."""
    import hashlib
    import platform
    raw = platform.machine() + ";" + platform.processor()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    raw += ";" + " ".join(sorted(line.split()))
                    break
    except OSError:
        pass
    return "m-" + hashlib.sha1(raw.encode()).hexdigest()[:10]


_cache_dir = _os.environ.get("SRTPU_COMPILE_CACHE",
                             _os.path.expanduser("~/.cache/srtpu_xla"))
if _cache_dir and _cache_dir != "0":
    try:
        _cache_dir = _os.path.join(_cache_dir, _machine_fingerprint())
        _jax.config.update("jax_compilation_cache_dir", _cache_dir)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:  # cache is an optimization, never a hard dependency
        pass

from .version import __version__
from .types import Schema, StructField
from .columnar import ColumnarBatch, DeviceColumn, HostColumn
from .config import TpuConf

__all__ = ["__version__", "Schema", "StructField", "ColumnarBatch",
           "DeviceColumn", "HostColumn", "TpuConf"]
