"""spark-rapids-tpu: a TPU-native columnar SQL execution framework.

A ground-up re-design of the capabilities of the RAPIDS Accelerator for Apache
Spark (reference: /root/reference, spark-rapids 24.12) for TPU hardware:
columnar batches are shape-bucketed jax.Arrays in HBM, operators compile to
XLA computations (jax.numpy / Pallas), distribution rides jax.sharding meshes
with ICI/DCN collectives, and a tiered HBM->host->disk memory runtime provides
spill + OOM-retry semantics.
"""

import jax as _jax

# Spark semantics require real int64/float64 columns (bigint/double).
# On TPU f64 is software-emulated by XLA; the planner prefers f32/bf16 where
# the user opts into approximate float, but parity mode needs x64 on.
_jax.config.update("jax_enable_x64", True)

from .version import __version__
from .types import Schema, StructField
from .columnar import ColumnarBatch, DeviceColumn, HostColumn
from .config import TpuConf

__all__ = ["__version__", "Schema", "StructField", "ColumnarBatch",
           "DeviceColumn", "HostColumn", "TpuConf"]
