from .dataframe import DataFrame, GroupedData, TpuSession
from . import functions

__all__ = ["DataFrame", "GroupedData", "TpuSession", "functions"]
