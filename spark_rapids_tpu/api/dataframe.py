"""DataFrame + session API (PySpark-shaped front-end over the TPU planner).

The reference plugs into Spark's session (SQLExecPlugin.scala:26); standalone
we provide the session. `TpuSession.conf` toggles behave like RapidsConf —
notably setting spark.rapids.tpu.sql.enabled=False runs the identical plan
through the host (CPU-oracle) path, which is how the differential test
harness mirrors the reference's with_cpu_session/with_gpu_session pattern
(integration_tests spark_session.py:145-151).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

from ..config import TpuConf
from ..exec.base import ExecContext
from ..exprs.aggregates import AggregateExpression
from ..exprs.base import Alias, ColumnRef, Expression
from ..plan import logical as L
from ..plan.overrides import explain_potential_tpu_plan, plan_query
from ..types import Schema, from_arrow
from .functions import Col, _to_expr, col as _col


def _as_schema(schema) -> Schema:
    """Schema | {name: DataType} | pyarrow.Schema -> Schema."""
    if isinstance(schema, Schema):
        return schema
    if isinstance(schema, dict):
        return Schema.of(**schema)
    import pyarrow as pa
    if isinstance(schema, pa.Schema):
        from ..types import StructField
        return Schema([StructField(f.name, from_arrow(f.type), f.nullable)
                       for f in schema])
    raise TypeError(f"cannot interpret schema {schema!r}")

__all__ = ["TpuSession", "DataFrame", "GroupedData"]


def _rename_refs(e: Expression, mapping: dict) -> Expression:
    """Deep-copied expression with ColumnRef names remapped (set-op
    right-side rename)."""
    import copy as _copy
    e = _copy.deepcopy(e)

    def walk(x):
        if isinstance(x, ColumnRef) and x.name in mapping:
            x.name = mapping[x.name]
        for c in getattr(x, "children", ()):
            walk(c)
    walk(e)
    return e


def _as_expr(c, alias_ok=True) -> Expression:
    if isinstance(c, str):
        return ColumnRef(c)
    return _to_expr(c)


class TpuSession:
    def __init__(self, conf: Optional[TpuConf] = None, mesh=None):
        if isinstance(conf, dict):
            conf = TpuConf(conf)
        self.conf = conf or TpuConf()
        self._ctx: Optional[ExecContext] = None
        #: temp-view registry consumed by session.sql()
        self._views: dict = {}
        from ..aux.profiler import Profiler
        self.profiler = Profiler(self.conf)
        #: per-query runtime summary (ref GpuTaskMetrics accumulators)
        self.last_query_metrics = None
        #: rotating query-history log (ref spark.eventLog.*), None when
        #: spark.rapids.tpu.eventLog.enabled is off
        from ..metrics.events import EventLogWriter
        self.event_log = EventLogWriter.from_conf(self.conf)
        import itertools as _it
        self._query_seq = _it.count(1)
        #: tenant id this session's queries run as — the admission
        #: controller's priority/fairness unit and the memory manager's
        #: quota unit (sched/admission.py; empty conf = anonymous None)
        from ..sched.admission import TENANT_ID
        self.tenant = str(self.conf.get(TENANT_ID)) or None
        #: fault_stats of the last LocalCluster.execute on this session
        #: (the event log's queryEnd picks it up)
        self.last_fault_stats = None
        #: AqeDecision summaries of the last query (aqe/__init__.py):
        #: a list of {"kind", "detail", "parts", "shuffle"?} dicts for
        #: every adaptive re-planning decision the run recorded —
        #: explain("analyze") renders them, bench.py counts them per
        #: rung, queryEnd/clusterQuery records carry the kind->count
        self.last_aqe_decisions = None
        #: engine that ran the last materialized query: "device"/"host"
        self.last_placement = None
        #: coded PlacementReport summary of the last planned query
        #: ({"verdict", "codes", "ops", "estRows"} — plan/tags.py);
        #: bench.py records it per rung as details[rung]["placement_reasons"]
        self.last_placement_report = None
        #: device mesh for distributed execution: explicit, or built from
        #: spark.rapids.tpu.distributed.* conf (the planner lowers
        #: supported fragments onto it — parallel/planner.py)
        self.mesh = mesh
        #: True when the mesh was built from conf defaults rather than
        #: supplied explicitly: the planner only uses an auto mesh above
        #: the distributed.minRows threshold (distribution_gate)
        self.mesh_is_auto = False
        from ..bootstrap import STARTUP_CHECK
        if self.conf.get(STARTUP_CHECK):
            # BEFORE the auto-mesh device query: in the broken-backend
            # environments this diagnoses, jax.devices() below would
            # raise first and eat the diagnostic
            import logging
            from ..bootstrap import check_environment, engine_banner
            lg = logging.getLogger("spark_rapids_tpu.bootstrap")
            lg.info("%s", engine_banner())
            for r in check_environment(self.conf):
                lvl = (lg.info if r["level"] == "ok"
                       else lg.error if r["level"] == "fatal"
                       else lg.warning)
                lvl("startup check %s [%s]: %s", r["check"], r["level"],
                    r["detail"])
        if self.mesh is None:
            from ..parallel.planner import (DISTRIBUTED_ENABLED,
                                            DISTRIBUTED_NUM_DEVICES)
            if self.conf.get(DISTRIBUTED_ENABLED):
                import jax
                n = int(self.conf.get(DISTRIBUTED_NUM_DEVICES)) or None
                avail = len(jax.devices())
                # a 1-device mesh adds shard_map overhead for nothing —
                # distributed-by-default only engages with real devices
                if (n or avail) > 1 and avail > 1:
                    from ..parallel.mesh import make_mesh
                    self.mesh = make_mesh(n)
                    self.mesh_is_auto = True

    # ------------------------------------------------------------ lifecycle
    def close(self) -> None:
        """Release session resources. With
        spark.rapids.tpu.memory.leakDetection on, assert that no device
        buffer registration outlived its query — the MemoryCleaner
        shutdown leak check analog (ref Plugin.scala:573-588). Like the
        reference's shutdown hook, the audit is PROCESS-wide (buffer
        registries are per-memory-budget, not per-session): run it from
        single-session debug harnesses, not while other sessions have
        queries in flight."""
        if self._ctx is not None:
            self._ctx.close()
            self._ctx = None
        from ..config import LEAK_DETECTION
        if self.conf.get(LEAK_DETECTION):
            from ..mem.manager import MemoryManager
            leaks = MemoryManager.audit_all_leaks()
            if leaks:
                raise AssertionError(
                    f"{len(leaks)} leaked device buffer registration(s) "
                    f"at session close: {leaks[:5]} "
                    f"(set SRTPU_LEAK_DEBUG=1 for creation sites)")

    def __enter__(self) -> "TpuSession":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            # never mask the in-flight exception with a leak assertion
            # (leaks ARE likely mid-exception — batches were abandoned)
            if self._ctx is not None:
                self._ctx.close()
                self._ctx = None
            return
        self.close()

    # ------------------------------------------------------------- config
    def set_conf(self, key: str, value) -> "TpuSession":
        self.conf = self.conf.set(key, value)
        self._ctx = None
        from ..aux.profiler import Profiler
        self.profiler = Profiler(self.conf)
        from ..metrics.events import EventLogWriter
        self.event_log = EventLogWriter.from_conf(self.conf)
        from ..sched.admission import TENANT_ID
        self.tenant = str(self.conf.get(TENANT_ID)) or None
        return self

    def exec_context(self) -> ExecContext:
        if self._ctx is None:
            self._ctx = ExecContext(self.conf)
        return self._ctx

    # ------------------------------------------------------------- sources
    def create_dataframe(self, data, num_partitions: int = 1) -> "DataFrame":
        import pandas as pd
        import pyarrow as pa
        if isinstance(data, pd.DataFrame):
            table = pa.Table.from_pandas(data, preserve_index=False)
        elif isinstance(data, pa.Table):
            table = data
        elif isinstance(data, dict):
            table = pa.table(data)
        else:  # list of dicts / rows
            table = pa.Table.from_pylist(list(data))
        schema = Schema.of(**{f.name: from_arrow(f.type)
                              for f in table.schema})
        if num_partitions <= 1:
            parts = [table]
        else:
            n = table.num_rows
            step = -(-n // num_partitions)
            parts = [table.slice(i * step, step)
                     for i in range(num_partitions)]
        return DataFrame(self, L.LogicalScan(parts, schema))

    def range(self, start: int, end: Optional[int] = None, step: int = 1,
              num_partitions: int = 1) -> "DataFrame":
        if end is None:
            start, end = 0, start
        return DataFrame(self, L.RangeRel(start, end, step, num_partitions))

    def read_parquet(self, *paths: str,
                     columns: Optional[List[str]] = None) -> "DataFrame":
        from ..io.file_scan import apply_path_rules
        from ..io.parquet import parquet_schema, expand_paths
        files = expand_paths(apply_path_rules(self.conf, paths))
        schema = parquet_schema(files[0])
        return DataFrame(self, L.ParquetScan(files, schema, columns))

    def read_orc(self, *paths: str,
                 columns: Optional[List[str]] = None) -> "DataFrame":
        from ..io.file_scan import apply_path_rules
        from ..io.orc import expand_orc_paths, orc_schema
        files = expand_orc_paths(apply_path_rules(self.conf, paths))
        return DataFrame(self, L.OrcScan(files, orc_schema(files[0]),
                                         columns))

    def read_avro(self, *paths: str,
                  columns: Optional[List[str]] = None) -> "DataFrame":
        from ..io.avro import avro_schema, expand_avro_paths
        from ..io.file_scan import apply_path_rules
        files = expand_avro_paths(apply_path_rules(self.conf, paths))
        return DataFrame(self, L.AvroScan(files, avro_schema(files[0]),
                                          columns))

    def read_iceberg(self, path: str, columns: Optional[List[str]] = None,
                     snapshot_id: Optional[int] = None) -> "DataFrame":
        from ..iceberg import IcebergTable
        from ..io.file_scan import apply_path_rules
        path = apply_path_rules(self.conf, [path])[0]
        return IcebergTable(path).to_df(self, columns, snapshot_id)

    def read_delta(self, path: str, columns: Optional[List[str]] = None,
                   version: Optional[int] = None) -> "DataFrame":
        from ..delta import DeltaTable
        from ..io.file_scan import apply_path_rules
        path = apply_path_rules(self.conf, [path])[0]
        return DeltaTable(self, path).to_df(columns, version)

    def delta_table(self, path: str):
        from ..delta import DeltaTable
        return DeltaTable(self, path)

    def sql(self, text: str) -> "DataFrame":
        """Run a SQL query over registered temp views (ANSI analytics
        subset — see spark_rapids_tpu.sql)."""
        from ..sql import lower_statement
        return lower_statement(self, text, self._views)

    def create_temp_view(self, name: str, df: "DataFrame") -> None:
        self._views[name.lower()] = df

    def drop_temp_view(self, name: str) -> None:
        self._views.pop(name.lower(), None)

    def register_delta_table(self, name: str, path: str) -> None:
        """Expose a Delta table to SQL, both as a readable view (always
        reading the CURRENT version) and as the target of UPDATE / DELETE
        / MERGE INTO statements. One registry: replacing the name with a
        temp view later redirects BOTH reads and DML resolution."""
        self._views[name.lower()] = self.delta_table(path)

    @property
    def catalog(self):
        """Named-table catalog over the conf'd warehouse directory (ref
        GpuDeltaCatalogBase / IcebergProviderImpl — see sql/catalog.py)."""
        from ..sql.catalog import Catalog
        return Catalog(self)

    def table(self, name: str) -> "DataFrame":
        """Resolve a table by name: temp views first, then the catalog
        ([db.]table). The SQL FROM clause resolves identically."""
        v = self._views.get(name.lower())
        if v is not None:
            from ..delta.table import DeltaTable
            return v.to_df() if isinstance(v, DeltaTable) else v
        return self.catalog.table(name)

    def read_csv(self, *paths: str, schema=None, header=True) -> "DataFrame":
        from ..io.file_scan import apply_path_rules
        from ..io.text import csv_to_tables
        tables, sch = csv_to_tables(apply_path_rules(self.conf, paths),
                                    schema, header)
        return DataFrame(self, L.LogicalScan(tables, sch))

    def read_hive_text(self, *paths: str, schema,
                       field_delim: str = "\x01",
                       null_value: str = "\\N") -> "DataFrame":
        """Hive text tables (LazySimpleSerDe ^A-delimited, \\N nulls —
        ref GpuHiveTextFileFormat / hive text scans)."""
        from ..io.file_scan import apply_path_rules
        from ..io.text import hive_text_to_tables
        tables, sch = hive_text_to_tables(
            apply_path_rules(self.conf, paths), schema,
            field_delim=field_delim, null_value=null_value)
        return DataFrame(self, L.LogicalScan(tables, sch))

    def read_json(self, *paths: str, schema=None) -> "DataFrame":
        from ..io.file_scan import apply_path_rules
        from ..io.text import json_to_tables
        tables, sch = json_to_tables(apply_path_rules(self.conf, paths),
                                     schema)
        return DataFrame(self, L.LogicalScan(tables, sch))


class DataFrame:
    def __init__(self, session: TpuSession, plan: L.LogicalPlan):
        self.session = session
        self.plan = plan

    # ------------------------------------------------------------ plan ops
    def select(self, *cols) -> "DataFrame":
        exprs = [_as_expr(c) for c in cols]
        gen = self._extract_generator(exprs)
        if gen is not None:
            return gen
        return DataFrame(self.session, L.Project(exprs, self.plan))

    def _extract_generator(self, exprs) -> Optional["DataFrame"]:
        """Spark's ExtractGenerator analyzer rule: a select list containing
        explode/posexplode/stack plans a Generate node, with the other
        expressions evaluated on top of its pass-through columns."""
        from ..exprs.base import Alias
        from ..exprs.generators import Generator
        gen_idx = [i for i, e in enumerate(exprs)
                   if isinstance(e, Generator)
                   or (isinstance(e, Alias) and isinstance(e.children[0],
                                                           Generator))]
        if not gen_idx:
            return None
        if len(gen_idx) > 1:
            raise ValueError("only one generator allowed per select clause")
        i = gen_idx[0]
        e = exprs[i]
        alias = e.name if isinstance(e, Alias) else None
        generator = e.children[0] if isinstance(e, Alias) else e
        others = [x for j, x in enumerate(exprs) if j != i]
        child_schema = self.plan.schema()
        needed, seen = [], set()
        for o in others:
            for r in o.references():
                if r not in seen:
                    seen.add(r)
                    needed.append(r)
        gen_fields = generator.generator_output(child_schema)
        out_names = None
        if alias is not None:
            if len(gen_fields) != 1:
                raise ValueError(
                    "single alias on a multi-column generator; use the "
                    "default names instead")
            out_names = [alias]
        plan = L.Generate(generator, needed, self.plan, out_names)
        gen_names = [f.name for f in (plan.schema().fields[len(needed):])]
        top = (others[:i] + [ColumnRef(n) for n in gen_names] + others[i:])
        return DataFrame(self.session, L.Project(top, plan))

    def with_column(self, name: str, c) -> "DataFrame":
        schema = self.plan.schema()
        exprs: List[Expression] = []
        replaced = False
        for f in schema.fields:
            if f.name == name:
                exprs.append(Alias(_as_expr(c), name))
                replaced = True
            else:
                exprs.append(ColumnRef(f.name))
        if not replaced:
            exprs.append(Alias(_as_expr(c), name))
        return DataFrame(self.session, L.Project(exprs, self.plan))

    withColumn = with_column

    def filter(self, cond) -> "DataFrame":
        return DataFrame(self.session, L.Filter(_as_expr(cond), self.plan))

    where = filter

    def group_by(self, *cols) -> "GroupedData":
        return GroupedData(self, [_as_expr(c) for c in cols])

    groupBy = group_by

    def agg(self, *aggs) -> "DataFrame":
        return GroupedData(self, []).agg(*aggs)

    def order_by(self, *orders) -> "DataFrame":
        from ..plan.logical import SortOrder
        os = []
        for o in orders:
            if isinstance(o, SortOrder):
                os.append(o)
            elif isinstance(o, str):
                os.append(SortOrder(ColumnRef(o), True))
            elif isinstance(o, Col):
                os.append(SortOrder(o.expr, True))
            else:
                os.append(o)
        return DataFrame(self.session, L.Sort(os, self.plan))

    orderBy = sort = order_by

    def sort_within_partitions(self, *orders) -> "DataFrame":
        df = self.order_by(*orders)
        df.plan.global_sort = False
        return df

    def with_window_column(self, name: str, fn, partition_by=(),
                           order_by=(), frame=None) -> "DataFrame":
        """Add a window-function column (ref GpuWindowExec). `fn` is a
        WindowFunction or AggregateExpression; frame is None (Spark default)
        or ('rows', lo, hi) with None = unbounded."""
        from ..plan.logical import SortOrder, Window, WindowSpec
        pks = [_as_expr(c) for c in partition_by]
        obs = []
        for o in order_by:
            if isinstance(o, SortOrder):
                obs.append(o)
            elif isinstance(o, str):
                obs.append(SortOrder(ColumnRef(o), True))
            else:
                obs.append(SortOrder(_to_expr(o), True))
        spec = WindowSpec(pks, obs, frame)
        return DataFrame(self.session,
                         Window([(fn, spec, name)], self.plan))

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(self.session, L.GlobalLimit(n, self.plan))

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(self.session, L.Union([self.plan, other.plan]))

    unionAll = union

    # ------------------------------------------------------- set operations
    def _nullsafe_key_pairs(self, other):
        """Per-column join-key expression pairs implementing SQL set-op
        equality over standard equi-joins: each column contributes an
        is-null flag plus a default-filled value, so NULLs match NULLs
        and never a real default. NaN == NaN and -0.0 == 0.0 come from
        the join key encoding itself (exec/encoding.py float
        canonicalization). Columns pair POSITIONALLY (SQL set-op
        semantics — names may differ between the sides); the output
        keeps the left side's names. Ref: Spark plans set ops as joins
        with EqualNullSafe keys (ReplaceOperators)."""
        from ..exprs import Coalesce, IsNull, Literal
        sch = self.plan.schema()
        osch = other.plan.schema()
        if len(sch.fields) != len(osch.fields):
            raise ValueError(
                "set operations require the same number of columns "
                f"({len(sch.fields)} vs {len(osch.fields)})")
        defaults = {"string": "", "boolean": False, "float": 0.0,
                    "double": 0.0}
        pairs = []
        for lf_, rf_ in zip(sch.fields, osch.fields):
            d = defaults.get(lf_.dtype.name, 0)
            l, r = ColumnRef(lf_.name), ColumnRef(rf_.name)
            pairs.append((IsNull(l), IsNull(r)))
            pairs.append((Coalesce(l, Literal(d, lf_.dtype)),
                          Coalesce(r, Literal(d, rf_.dtype))))
        return pairs

    def intersect(self, other: "DataFrame") -> "DataFrame":
        """Distinct rows present in BOTH frames (SQL INTERSECT; ref
        Spark ReplaceIntersectWithSemiJoin -> GpuShuffledHashJoin)."""
        return self.distinct().join(other,
                                    on=self._nullsafe_key_pairs(other),
                                    how="leftsemi")

    def subtract(self, other: "DataFrame") -> "DataFrame":
        """Distinct rows of this frame absent from ``other`` (SQL
        EXCEPT; ref ReplaceExceptWithAntiJoin)."""
        return self.distinct().join(other,
                                    on=self._nullsafe_key_pairs(other),
                                    how="leftanti")

    def _counted_setop(self, other, all_kind: str) -> "DataFrame":
        from . import functions as F
        from ..exprs import Coalesce, Literal
        from ..exprs.aggregates import CountStar
        from ..exprs.conditional import Least
        from ..exprs.arithmetic import Subtract
        names = [f.name for f in self.plan.schema().fields]
        rnames = [f.name for f in other.plan.schema().fields]
        lc = GroupedData(self, [ColumnRef(n) for n in names]).agg(
            CountStar().with_name("__so_l"))
        rc = GroupedData(other, [ColumnRef(n) for n in rnames]).agg(
            CountStar().with_name("__so_r"))
        # rename the right side wholesale: positional pairing, and the
        # joined frame must not carry duplicate names
        rmap = {rn: f"__so_r_{i}" for i, rn in enumerate(rnames)}
        rc = rc.select(*([F.col(rn).alias(rmap[rn]) for rn in rnames]
                         + [F.col("__so_r")]))
        lk = self._nullsafe_key_pairs(other)
        pairs = [(le, _rename_refs(re, rmap)) for le, re in lk]
        if all_kind == "intersect":
            j = lc.join(rc, on=pairs, how="inner")
            m = Least(ColumnRef("__so_l"), ColumnRef("__so_r"))
        else:                           # exceptAll
            j = lc.join(rc, on=pairs, how="left")
            m = Subtract(ColumnRef("__so_l"),
                         Coalesce(ColumnRef("__so_r"), Literal(0)))
        j = j.with_column("__so_m", Col(m)) \
             .filter(F.col("__so_m") > F.lit(0))
        # multiset semantics: replicate each row m times via an exploded
        # 1..m sequence (the ReplicateRows analog)
        j = j.select(*(names
                       + [F.explode(F.sequence(F.lit(1),
                                               F.col("__so_m")))
                          .alias("__so_i")]))
        return j.select(*names)

    def intersect_all(self, other: "DataFrame") -> "DataFrame":
        """Multiset INTERSECT ALL (ref ReplaceIntersectAll +
        GpuReplicateRowsExec)."""
        return self._counted_setop(other, "intersect")

    intersectAll = intersect_all

    def except_all(self, other: "DataFrame") -> "DataFrame":
        """Multiset EXCEPT ALL (ref ReplaceExceptAll +
        GpuReplicateRowsExec)."""
        return self._counted_setop(other, "except")

    exceptAll = except_all

    def join(self, other: "DataFrame", on=None, how: str = "inner",
             condition=None) -> "DataFrame":
        lk, rk = [], []
        on_list = [on] if isinstance(on, str) else (on or [])
        all_named = bool(on_list) and all(isinstance(k, str)
                                          for k in on_list)
        bc = "right" if getattr(other, "_broadcast_hint", False) else (
            "left" if getattr(self, "_broadcast_hint", False) else None)
        cond = _as_expr(condition) if condition is not None else None
        if not all_named:
            for k in on_list:
                if isinstance(k, str):
                    lk.append(ColumnRef(k))
                    rk.append(ColumnRef(k))
                else:  # (left_col, right_col) pair
                    lk.append(_as_expr(k[0]))
                    rk.append(_as_expr(k[1]))
            return DataFrame(self.session,
                             L.Join(self.plan, other.plan, how, lk, rk,
                                    cond, broadcast=bc))
        # USING-style join (shared key NAMES): PySpark emits ONE key
        # column, not both sides' duplicates — otherwise a later
        # col("k") can silently resolve to the right side's null-filled
        # copy, and the device/host twins disagree on duplicate-name
        # layouts (r5 ground-truth finding). Rename the right side's
        # columns before the join so both execs see distinct names,
        # then project: keys FIRST (PySpark order), one column per key
        # (left's values; right's for RIGHT joins; coalesced for FULL).
        # Colliding NON-key names keep both sides' data, the right one
        # under a "<name>_r" suffix (this engine's schemas are
        # name-addressed, so true duplicate names cannot be kept).
        named_keys = list(on_list)
        keyset = set(named_keys)
        lnames = [f.name for f in self.plan.schema().fields]
        rcols = [f.name for f in other.plan.schema().fields]
        taken = set(lnames) | set(rcols)
        rmap = {}
        for i, k in enumerate(named_keys):
            rmap[k] = f"__ju_{i}"
        for c in rcols:
            if c in keyset or c not in lnames:
                continue
            alt = f"{c}_r"
            while alt in taken:
                alt += "_"
            taken.add(alt)
            rmap[c] = alt
        right2 = other.select(*[_col(c).alias(rmap.get(c, c))
                                for c in rcols])
        lk = [ColumnRef(k) for k in named_keys]
        rk = [ColumnRef(rmap[k]) for k in named_keys]
        joined = DataFrame(self.session,
                           L.Join(self.plan, right2.plan, how, lk, rk,
                                  cond, broadcast=bc))
        jt = joined.plan.join_type
        if jt in ("leftsemi", "leftanti", "existence"):
            return joined          # left-only output: nothing to drop
        from ..exprs import Coalesce
        exprs = []
        for k in named_keys:       # keys first, PySpark column order
            if jt == "right":
                exprs.append(Alias(ColumnRef(rmap[k]), k))
            elif jt == "full":
                exprs.append(Alias(Coalesce(ColumnRef(k),
                                            ColumnRef(rmap[k])), k))
            else:
                exprs.append(ColumnRef(k))
        for c in lnames:
            if c not in keyset:
                exprs.append(ColumnRef(c))
        for c in rcols:
            if c in keyset:
                continue
            out_name = rmap.get(c, c)
            exprs.append(ColumnRef(out_name))
        return DataFrame(self.session, L.Project(exprs, joined.plan))

    def hint(self, name: str) -> "DataFrame":
        """Spark-style plan hint; only "broadcast" is meaningful (ref
        Spark's broadcast() function / GpuBroadcastHashJoinExec selection)."""
        df = DataFrame(self.session, self.plan)
        if name.lower() == "broadcast":
            df._broadcast_hint = True
        return df

    def map_in_pandas(self, fn, schema) -> "DataFrame":
        """Per-batch pandas transform (ref GpuMapInPandasExec)."""
        return DataFrame(self.session,
                         L.MapInPandas(fn, _as_schema(schema), self.plan))

    def create_or_replace_temp_view(self, name: str) -> None:
        self.session.create_temp_view(name, self)

    createOrReplaceTempView = create_or_replace_temp_view

    def cache(self) -> "DataFrame":
        """Materialize once into in-memory parquet-encoded batches
        (ref ParquetCachedBatchSerializer)."""
        from ..exec.cached import CACHE_CODEC, CachedRelation, \
            encode_batches
        codec = str(self.session.conf.get(CACHE_CODEC))
        blobs = self._execute_wrapped(
            lambda p, ctx: encode_batches(p.execute(ctx), codec))
        return DataFrame(self.session,
                         CachedRelation(blobs, self.schema))

    def sample(self, fraction: float, seed: int = 42) -> "DataFrame":
        return DataFrame(self.session, L.Sample(fraction, seed, self.plan))

    def repartition(self, n: Optional[int] = None, *cols) -> "DataFrame":
        """Shuffle into n partitions (hash by cols when given). With no
        explicit n, the count comes from
        spark.rapids.tpu.sql.shuffle.partitions and adaptive execution
        may coalesce small output partitions (Spark AQE semantics: an
        explicit n is a hard contract, an implicit one is advisory)."""
        import numpy as _np
        if n is not None and not isinstance(n, (int, _np.integer)):
            cols = (n,) + cols      # repartition(col, ...) form
            n = None
        keys = [_as_expr(c) for c in cols]
        mode = "hash" if keys else "roundrobin"
        plan = L.Repartition(int(n) if n is not None else None, keys,
                             self.plan, mode, adaptive_ok=n is None)
        return DataFrame(self.session, plan)

    def drop(self, *names: str) -> "DataFrame":
        keep = [f.name for f in self.plan.schema().fields
                if f.name not in names]
        return self.select(*keep)

    def distinct(self) -> "DataFrame":
        names = [f.name for f in self.plan.schema().fields]
        return GroupedData(self, [ColumnRef(n) for n in names]).agg()

    # ------------------------------------------------------------- actions
    @property
    def schema(self) -> Schema:
        return self.plan.schema()

    @property
    def columns(self) -> List[str]:
        return self.plan.schema().names()

    def _physical(self, conf=None):
        return plan_query(self.plan, conf or self.session.conf,
                          mesh=getattr(self.session, "mesh", None),
                          mesh_auto=getattr(self.session, "mesh_is_auto",
                                            False))

    def _aqe_feedback_conf(self, aqe_log):
        """Sentinel-history feedback (ISSUE 19, aqe/feedback.py): a
        digest whose baseline shows repeated rung>=3 escalation or
        warm-slowdown flags is admitted with an overlay conf — smaller
        target batches or host placement — BEFORE planning. Returns the
        overlay conf, or None on the (common) clean-history path."""
        if aqe_log is None:
            return None
        from .. import aqe as aqe_mod
        conf = self.session.conf
        if not bool(conf.get(aqe_mod.AQE_FEEDBACK_ENABLED)):
            return None
        from ..ops import sentinel as sentinel_mod
        from ..ops import slo as slo_mod
        sent = sentinel_mod.SENTINEL
        if sent is None and slo_mod.TRACKER is None:
            return None
        from ..aqe.feedback import plan_feedback
        from ..metrics.events import plan_digest
        digest = plan_digest(self.plan)
        fb = plan_feedback(
            digest,
            sent.baselines().get(digest) if sent is not None else None,
            conf)
        if fb is None:
            return None
        over = conf
        for k, v in sorted(fb.settings.items()):
            over = over.set(k, v)
        try:  # tpulint: never-raise
            aqe_log.record(aqe_mod.make_decision(
                aqe_mod.FEEDBACK_REPLAN, detail=fb.reason,
                parts=len(fb.settings)))
        except Exception:  # noqa: BLE001 - observability only
            pass
        return over

    def _execute_wrapped(self, consume):
        """Run the physical plan through the full execution pipeline
        (explainOnly guard, LORE wrap, profiler, task metrics, fault
        dumps) — every materializing sink goes through here. Speculative
        join sizing is reset per query, validated after the consume, and
        transparently retried with exact sizing on overflow; plans with
        side effects (file writes) run with speculation OFF so a retry
        can never duplicate output files."""
        # stale-telemetry guard: a query that RAISES must not leave the
        # prior run's summary behind for callers to misattribute — and a
        # non-distributed query must not inherit the last cluster run's
        # fault stats. Cleared before anything (planning included) can
        # fail.
        self.session.last_query_metrics = None
        self.session.last_fault_stats = None
        self.session.last_placement_report = None
        self.session.last_aqe_decisions = None
        # closed-loop AQE (ISSUE 19): install the decision log up front
        # and mark it, so the finally below can slice out exactly THIS
        # query's decisions (thread-ident attribution); the feedback
        # hook may hand back an overlay conf the whole run then uses
        from .. import aqe as aqe_mod
        import threading as _threading
        aqe_log = aqe_mod.ensure_aqe_from_conf(self.session.conf)
        aqe_mark = aqe_log.mark() if aqe_log is not None else 0
        run_conf = self._aqe_feedback_conf(aqe_log)
        physical = self._physical(run_conf)
        report = getattr(physical, "placement_report", None)
        # one summary, three consumers (session attribute, queryStart
        # record, metric increments) — computed once
        placement_summary = (report.summary() if report is not None
                             else None)
        self.session.last_placement_report = placement_summary
        if self.session.conf.is_explain_only:
            raise RuntimeError("session is in explainOnly mode")
        # re-install this query's per-expression disables for the runtime
        # device/host checks: planning by another session in between must
        # not leak its conf into this execution (thread-local set)
        from ..plan.op_confs import install_from_conf
        install_from_conf(self.session.conf)
        from ..aux.fault import DeviceDumpHandler
        from ..aux.lore import lore_wrap
        from ..aux.metrics import TaskMetrics
        from ..columnar.batch import SpeculativeOverflow
        from ..trace import core as trace_core
        physical = lore_wrap(physical, run_conf or self.session.conf)
        ctx = self.session.exec_context()
        if run_conf is not None:
            # batch targets are consumed at EXEC time through ctx.conf
            # (exec/basic.py), so a feedback overlay needs a context
            # carrying it — sharing the session context's memory manager
            # and semaphore so budgets/permits stay per-process
            ctx = ExecContext(run_conf, semaphore=ctx.semaphore,
                              memory=ctx.memory)
        from ..metrics import registry as metrics_registry
        mreg0 = metrics_registry.REGISTRY   # installed by the ctx above
        if mreg0 is not None and placement_summary is not None:
            # per-query fallback accounting (the qualification feed):
            # one increment per (reason code, operator) tag occurrence
            for op, codes in sorted(placement_summary["ops"].items()):
                for code, n in sorted(codes.items()):
                    mreg0.counter("srtpu_placement_fallback_total",
                                  code=code, op=op).inc(n)
        tracer = trace_core.ensure_tracer_from_conf(ctx.conf)
        t0q = tracer.now() if tracer is not None else 0
        side_effects = isinstance(self.plan, L.WriteFile)
        ctx.speculations.clear()
        ctx.speculate = (ctx.conf.join_speculative_sizing
                         and not side_effects)
        prof = self.session.profiler
        tm = TaskMetrics(ctx)
        prof.maybe_start()
        elog = self.session.event_log
        qid = digest = None

        def _resolve_digest():
            # the planner already hashed the pre-rewrite tree when the
            # optimizer ran (overrides.plan_query attaches it) — re-hash
            # only when it didn't. ONE resolution chain for both
            # consumers (queryStart record, record_plan_compiled):
            # lookup and record must agree on the digest.
            d = getattr(physical, "plan_digest", None)
            if d is None:
                from ..metrics.events import plan_digest
                d = plan_digest(self.plan)
            return d

        # live ops plane (ISSUE 15): one module-global load + branch per
        # consumer when nothing is configured — the trace/metrics
        # disabled-path contract
        from ..ops import flight as flight_mod
        from ..ops import sentinel as sentinel_mod
        from ..ops import server as ops_server_mod
        from ..ops import slo as slo_mod
        frec = flight_mod.RECORDER
        sentinel = sentinel_mod.SENTINEL
        slo = slo_mod.TRACKER
        _srv = ops_server_mod.SERVER
        tracker = _srv.tracker if _srv is not None else None
        if (elog is not None or tracker is not None or frec is not None
                or sentinel is not None or slo is not None):
            qid = next(self.session._query_seq)
            digest = _resolve_digest()
        if elog is not None:
            elog.write({"event": "queryStart", "queryId": qid,
                        "planDigest": digest,
                        "root": type(self.plan).__name__,
                        # coded placement summary: what tools/qualify
                        # mines across the history (docs/placement.md)
                        "placement": placement_summary,
                        "conf": {k: str(v) for k, v
                                 in sorted(self.session.conf.raw.items())}})
        track_tok = None
        if tracker is not None:
            track_tok = tracker.begin(
                qid, digest, (placement_summary or {}).get("verdict"),
                root=type(self.plan).__name__,
                tenant=self.session.tenant)
        if frec is not None:
            # anomaly dumps fired from THIS thread (semaphore wedge, OOM
            # ladder) carry the in-flight query's digest + coded report
            frec.set_query({"queryId": qid, "planDigest": digest,
                            "placement": placement_summary})
        trace_path = None
        import time as _time
        # executable-cache counters around the run: zero in-process
        # misses AND zero backend-compile seconds = a COMPILE-FREE run,
        # the only kind the cost model learns walls from (plan/cost.py
        # record_engine_wall / record_op_wall exec-cache-hit keying)
        from ..plan import exec_cache
        cache_before = exec_cache.stats()
        # warm-digest recompile detector (ops/flight.py): this digest's
        # executables were vouched warm — any backend-compile seconds
        # the run pays anyway is an anomaly worth a bundle
        was_warm = (frec is not None and digest is not None
                    and exec_cache.plan_digest_cached(digest))
        # bundle census before the run: any bundle beyond this count was
        # written DURING this query, so an SLO exemplar can link to it
        bundles_before = (len(frec.stats()["bundles"])
                          if frec is not None else 0)
        # ---------------- query-lifecycle controller (ISSUE 14) --------
        # cooperative deadline: every operator checks it per produced
        # batch and the semaphore polls it, so a timed-out query unwinds
        # through the normal exception path (permits released, batches
        # closed — the zero-leak audit holds)
        from ..config import QUERY_TIMEOUT
        from ..mem.manager import (OutOfDeviceMemory, RetryOOM,
                                   SplitAndRetryOOM)
        from ..mem.semaphore import QueryTimeout
        qt = float(self.session.conf.get(QUERY_TIMEOUT))
        ctx.set_query_deadline(_time.monotonic() + qt if qt > 0 else None)
        ctx.take_oom_degradations()          # per-query reset
        ctx.take_ladder_rung()               # per-query reset
        degs: List[dict] = []

        def _attempt(p):
            """One full run of the plan through the execution pipeline,
            with the speculative-sizing overflow retry inside (plans
            with side effects run with speculation off, so this inner
            retry can never duplicate output files)."""
            try:
                out = DeviceDumpHandler(self.session.conf).wrap(
                    lambda: consume(p, ctx), p)
                ctx.check_speculations()
                return out
            except SpeculativeOverflow:
                ctx.speculate = False
                ctx.speculations.clear()
                ctx.metrics.clear()
                return DeviceDumpHandler(self.session.conf).wrap(
                    lambda: consume(p, ctx), p)

        def _note_timeout():
            from ..metrics import registry as _mr
            if _mr.REGISTRY is not None:
                _mr.REGISTRY.counter("srtpu_query_timeout_total").inc()
            if frec is not None:
                frec.trigger(
                    "query_timeout",
                    detail=f"query {qid if qid is not None else '?'} "
                           f"(digest {digest or '?'}) cancelled by "
                           "spark.rapids.tpu.query.timeout")

        # ------------- multi-tenant admission front door (ISSUE 18) ----
        # one module-global load + branch when admission is off; with a
        # controller installed the query queues HERE — before any device
        # work — so an overloaded or pressure-degraded process refuses
        # work with a structured AdmissionRejected (retry-after hint)
        # instead of piling onto the semaphore
        from ..sched import admission as adm_mod
        adm = adm_mod.CONTROLLER
        adm_ticket = None
        queued_ms = None
        admission_status = None
        tenant = self.session.tenant
        if tenant is not None:
            # per-tenant HBM quota attribution for every buffer this
            # query retains (mem/manager.py census; cleared in finally)
            from ..sched.admission import TENANT_HBM_SHARE
            share = float(self.session.conf.get(TENANT_HBM_SHARE))
            ctx.memory.set_thread_tenant(
                tenant, int(share * ctx.memory.budget)
                if share > 0 else 0)
        t0 = _time.perf_counter()
        ok = False
        fail_reason = None
        try:
            if adm is not None:
                if tracker is not None and track_tok is not None:
                    tracker.admission(track_tok, "queued")
                from ..sched.admission import TENANT_PRIORITY
                try:
                    adm_ticket = adm.admit(
                        tenant=tenant,
                        priority=int(
                            self.session.conf.get(TENANT_PRIORITY)),
                        deadline=ctx.deadline)
                except adm_mod.AdmissionRejected:
                    admission_status = "shed"
                    if tracker is not None and track_tok is not None:
                        tracker.admission(track_tok, "shed")
                    raise
                admission_status = "admitted"
                queued_ms = adm_ticket.queued_ms
                if tracker is not None and track_tok is not None:
                    tracker.admission(track_tok, "admitted", queued_ms)
            try:
                out = _attempt(physical)
                ok = True
                return out
            except (RetryOOM, SplitAndRetryOOM, OutOfDeviceMemory) as e:
                # an OOM escaped every operator-level retry frame (a
                # reserve outside any with_retry scope, or a ladder with
                # host fallback disabled). Side-effecting plans must not
                # re-run — a retry could duplicate output files.
                if side_effects:
                    raise
                try:
                    out = self._oom_query_ladder(e, physical, ctx,
                                                 _attempt, consume)
                except QueryTimeout:
                    # raised from inside this handler, so the sibling
                    # except below never sees it — count it here
                    _note_timeout()
                    raise
                ok = True
                return out
            except QueryTimeout:
                _note_timeout()
                raise
        except BaseException as e:
            # satellite fix (ISSUE 15): the event log only distinguished
            # ok/exception — a cancelled or failed query now records WHY
            # (tools/history renders the reason column)
            fail_reason = f"{type(e).__name__}: {e}"
            raise
        finally:
            if adm_ticket is not None:
                adm.release(adm_ticket)   # idempotent; never raises
            if tenant is not None:
                ctx.memory.set_thread_tenant(None)
            ctx.set_query_deadline(None)
            degs = ctx.take_oom_degradations()
            ladder_rung = ctx.take_ladder_rung()
            prof.maybe_stop()
            self.session.last_query_metrics = tm.finish()
            if tracer is not None:
                # the whole-query span wraps the existing TaskMetrics
                # capture: one umbrella every operator span nests under;
                # it carries the placement verdict so the trace alone
                # answers "did this query even touch the device"
                qargs = {"ok": ok}
                if report is not None:
                    qargs["placement"] = report.verdict
                tracer.complete("query", t0q, cat="query", args=qargs)
                out_path = str(ctx.conf.get(trace_core.TRACE_OUTPUT))
                if out_path:
                    from ..trace.export import write_chrome_trace
                    try:
                        write_chrome_trace(out_path, tracer)
                        trace_path = out_path
                    except Exception as e:  # noqa: BLE001
                        # tracing must never fail a query — but a
                        # silently missing artifact after paying the
                        # recording overhead must at least be loud
                        import logging
                        logging.getLogger(__name__).warning(
                            "could not write trace to %s: %s",
                            out_path, e)
            if degs and report is not None:
                # runtime pressure degradations join the query's coded
                # placement report: explain-analyze renderers, the
                # session summary and the event log all see the operator
                # that fell back (the only tag recorded AFTER planning)
                from ..plan.tags import OOM_PRESSURE_HOST, make_tag
                for d in degs:
                    report.plan_tags.append(make_tag(
                        OOM_PRESSURE_HOST, d["detail"], node=d["op"]))
                placement_summary = report.summary()
                self.session.last_placement_report = placement_summary
            from ..metrics import registry as metrics_registry
            mreg = metrics_registry.REGISTRY
            wall_s = _time.perf_counter() - t0
            # PROCESS-global counter delta (the compile_free_since
            # contract): a concurrent query's compile lands in this
            # delta too. Both consumers err conservative with it — the
            # sentinel treats the run as cold (skips, never
            # false-flags) and warm_recompile is rate-limited — but a
            # page's compileSeconds can over-attribute under mixed
            # concurrent traffic, exactly like the learned-cost feeds.
            compile_s_paid = round(
                exec_cache.stats()["compile_s"]
                - cache_before["compile_s"], 4)
            # one reason for every consumer (event log, /queries): a
            # failed query carries its exception, a rung-4 degraded one
            # carries which operators fell back
            if not ok:
                reason = fail_reason
            elif degs:
                reason = ("degraded: " + "; ".join(
                    f"{d['op']}: {d['detail']}" for d in degs))[:500]
            else:
                reason = None
            if mreg is not None:
                mreg.counter("srtpu_queries_total",
                             status="ok" if ok else "failed").inc()
                # per-tenant tail accounting (ISSUE 20): the wall lands
                # in the tenant's histogram lane AND in two mergeable
                # quantile sketches — per tenant for SLO burn math, per
                # plan digest (bounded: overflow -> "other") so /slo can
                # rank digests by tail contribution
                mtenant = tenant or "default"
                mreg.histogram("srtpu_query_seconds",
                               tenant=mtenant).observe(wall_s)
                mreg.summary("srtpu_query_latency_seconds",
                             tenant=mtenant).observe(wall_s)
                if digest is not None:
                    mreg.summary(
                        "srtpu_digest_latency_seconds",
                        digest=mreg.bounded_label(
                            "srtpu_digest_latency_seconds", "digest",
                            digest)).observe(wall_s)
            # one drain for every consumer (session attribute, queryEnd
            # record, /queries): this thread drove every decision site
            # of this query, so the thread filter is the attribution
            aqe_decs = (aqe_log.since(aqe_mark,
                                      thread=_threading.get_ident())
                        if aqe_log is not None else [])
            aqe_summary = (aqe_mod.summarize(aqe_decs)
                           if aqe_decs else None)
            self.session.last_aqe_decisions = \
                [d.summary() for d in aqe_decs] if aqe_decs else None
            if elog is not None:
                from ..aux.metrics import metrics_to_json
                end_rec = {"event": "queryEnd", "queryId": qid,
                           "planDigest": digest, "ok": ok,
                           "durationMs": round(wall_s * 1000.0, 3),
                           # satellite (ISSUE 15): cancellation and
                           # degradation are first-class outcomes, not
                           # just "ok": false — the sentinel and
                           # tools/history read these four directly
                           "degraded": bool(degs),
                           "ladderRung": ladder_rung,
                           # multi-tenant serving fields (ISSUE 18):
                           # which tenant ran it and the admission
                           # wait it paid at the front door
                           "tenant": tenant,
                           "queuedMs": queued_ms,
                           "compileSeconds": compile_s_paid,
                           "placementVerdict": (placement_summary
                                                or {}).get("verdict"),
                           "metrics": metrics_to_json(
                               self.session.last_query_metrics),
                           "faultStats": self.session.last_fault_stats,
                           "trace": trace_path}
                if reason:
                    end_rec["reason"] = reason
                if admission_status:
                    end_rec["admission"] = admission_status
                if aqe_summary:
                    # compact kind -> count map (ISSUE 19); the full
                    # per-decision details ride the session attribute
                    # and the trace, not every event record
                    end_rec["aqe"] = aqe_summary
                if degs:
                    # queryStart already shipped the plan-time summary;
                    # degradations are runtime facts, so the END record
                    # carries them (and the refreshed placement summary
                    # tools/qualify prefers when present)
                    end_rec["oomDegradations"] = degs
                    end_rec["placement"] = placement_summary
                elog.write(end_rec)
            if frec is not None:
                if was_warm and compile_s_paid > 0:
                    # warm-digest recompile: the compiled-plan set
                    # vouched for this digest, yet the run paid real XLA
                    # compile — a retrace cliff or an evicted tier
                    frec.trigger(
                        "warm_recompile",
                        detail=f"digest {digest} is in the compiled-"
                               f"plan set but paid {compile_s_paid}s "
                               "of backend compile")
                frec.set_query(None)
            if sentinel is not None and digest is not None:
                # fold AFTER the event record: the sentinel sees exactly
                # what a tools/regress replay of this log would see
                sentinel.fold({"digest": digest,
                               "wallMs": round(wall_s * 1000.0, 3),
                               "verdict": (placement_summary
                                           or {}).get("verdict"),
                               "rung": ladder_rung, "ok": ok,
                               "compileS": compile_s_paid})
            if slo is not None:
                # SLO fold AFTER the trace write and any flight dump:
                # an over-target exemplar links the artifacts this very
                # query produced (the trace above; the newest bundle if
                # one landed during the run)
                flight_path = None
                if frec is not None:
                    _bundles = frec.stats()["bundles"]
                    if len(_bundles) > bundles_before:
                        flight_path = _bundles[-1]
                slo.observe(tenant=tenant, wall_ms=wall_s * 1000.0,
                            ok=ok, query_id=qid, digest=digest,
                            trace_path=trace_path,
                            flight_path=flight_path)
            if tracker is not None and track_tok is not None:
                tracker.end(track_tok, ok=ok,
                            wall_ms=wall_s * 1000.0, rung=ladder_rung,
                            reason=reason, degraded=bool(degs),
                            aqe=aqe_summary)
            if ok and not side_effects and not degs:
                # (a degraded run's wall mixes failed attempts and the
                # emergency host path — never feed it to the cost model)
                # measured whole-query wall per (shape, engine placement):
                # the cost optimizer prefers these over its model, so a
                # mispriced engine choice self-corrects on the next
                # planning of the same shape (plan/cost._ENGINE_WALLS)
                from ..plan.cost import plan_signature, record_engine_wall

                def _on_device(n):
                    # scans and engine-neutral pass-throughs (union,
                    # limit, branch-align) are shared by both engines;
                    # any OTHER device exec means the query actually
                    # touched the accelerator
                    if n.is_tpu and not n.engine_neutral \
                            and "Scan" not in type(n).__name__:
                        return True
                    return any(_on_device(c) for c in n.children)

                placement = ("device" if _on_device(physical) else "host")
                #: benchmark/diagnostic surface: which engine actually ran
                #: the last materialized query on this session
                self.session.last_placement = placement
                compile_free = exec_cache.compile_free_since(cache_before)
                # wall_s, not a fresh perf_counter diff: the elog write
                # and metrics export above are observability overhead,
                # not engine time — and a >=1-observation-trusted wall
                # inflated by them could flip a close arbitration
                record_engine_wall(plan_signature(self.plan), placement,
                                   wall_s, compile_free=compile_free)
                # per-operator self-times -> the learned cost table
                # (device AND host row costs; metrics/analyze.py)
                from ..metrics.analyze import record_learned_op_costs
                record_learned_op_costs(physical, ctx, compile_free)
                if placement == "device":
                    # this plan's kernels now live in the executable
                    # cache tiers: the planner's cache-aware floor
                    # charges warm repeats dispatch-only (plan/cost.py).
                    # Only the optimizer reads the digest set, and the
                    # planner hashes the tree exactly when the optimizer
                    # runs — with it off (and no event log) don't pay a
                    # full-tree hash to record a digest nothing reads.
                    if digest is None:
                        digest = getattr(physical, "plan_digest", None)
                    if digest is not None:
                        exec_cache.record_plan_compiled(digest)

    def _oom_query_ladder(self, err, physical, ctx, attempt, consume):
        """Query-level OOM escalation — the controller's backstop for an
        OOM that escaped every operator retry frame (a reserve outside
        any with_retry scope). Rung A: spill EVERY live session's
        spillables and re-run the plan once on the device. Rung B
        (``spark.rapids.tpu.oom.hostFallback.enabled``): re-plan the
        query onto the host engine and run it under an unbudgeted
        pressure grant, recorded as a whole-query OOM_PRESSURE_HOST
        degradation — pressure degrades *placement*, never results."""
        from ..mem.manager import (MemoryManager, OutOfDeviceMemory,
                                   RetryOOM, SplitAndRetryOOM)
        ctx.note_ladder_rung(
            3, f"query-level pressure spill after {type(err).__name__} "
               "escaped every operator retry frame")
        MemoryManager.spill_all_sessions()
        ctx.memory.spill_everything()    # explicit managers too
        ctx.metrics.clear()
        ctx.speculations.clear()
        try:
            return attempt(physical)
        except (RetryOOM, SplitAndRetryOOM, OutOfDeviceMemory) as e2:
            from ..config import OOM_HOST_FALLBACK_ENABLED
            if not bool(self.session.conf.get(OOM_HOST_FALLBACK_ENABLED)):
                raise
            ctx.record_oom_degradation(
                "Query", "whole-query host degradation after "
                f"{type(e2).__name__}: {e2}")
            host_conf = self.session.conf.set(
                "spark.rapids.tpu.sql.enabled", False)
            host_physical = plan_query(self.plan, host_conf)
            ctx.metrics.clear()
            ctx.speculations.clear()
            ctx.speculate = False
            with ctx.memory.pressure_host_grant():
                return consume(host_physical, ctx)

    def collect_arrow(self):
        return self._execute_wrapped(lambda p, ctx: p.collect(ctx))

    def to_pandas(self):
        return self.collect_arrow().to_pandas()

    def to_device_columns(self):
        """Zero-copy export of the result as device column batches for ML
        interop (ref ColumnarRdd.scala:42 convert(df): RDD[Table] used by
        XGBoost): a list of batches, each a dict name -> (data jax.Array,
        validity jax.Array), plus ``num_rows``. The arrays stay in HBM —
        no host round trip.

        The arrays keep their shape-bucket padded length: rows at index
        >= ``num_rows`` are padding whose data values are arbitrary (their
        validity lanes are False). Mask with ``validity`` or slice to
        ``num_rows`` before any reduction over the array."""
        def consume(physical, ctx):
            out = []
            for b in physical.execute(ctx):
                cols = {}
                for f, c in zip(b.schema.fields, b.columns):
                    if not hasattr(c, "data"):
                        raise ValueError(
                            f"column {f.name} is host-only "
                            f"({f.dtype.name}); device export requires "
                            "device-backed types")
                    cols[f.name] = (c.data, c.validity)
                out.append({"columns": cols, "num_rows": b.num_rows})
            return out
        return self._execute_wrapped(consume)

    toPandas = to_pandas

    def collect(self):
        return self.collect_arrow().to_pylist()

    def count(self) -> int:
        # count(*) as an aggregation: column pruning trims the scan to one
        # column and the aggregate's single-fetch path makes the whole
        # count one device round trip
        from .functions import count_star
        t = self.agg(count_star().with_name("n")).collect_arrow()
        return t.column("n")[0].as_py()

    def write_parquet(self, path: str, mode: str = "overwrite",
                      partition_by: Sequence[str] = ()):
        df = DataFrame(self.session,
                       L.WriteFile(path, "parquet", self.plan, mode,
                                   partition_by))
        return df.collect_arrow()

    def write_delta(self, path: str, mode: str = "overwrite",
                    partition_by: Sequence[str] = ()):
        from ..delta.table import write_delta
        write_delta(self.session, self, path, mode, partition_by)

    def write_orc(self, path: str, mode: str = "overwrite",
                  partition_by: Sequence[str] = ()):
        df = DataFrame(self.session,
                       L.WriteFile(path, "orc", self.plan, mode,
                                   partition_by))
        return df.collect_arrow()

    def write_csv(self, path: str, mode: str = "overwrite",
                  partition_by: Sequence[str] = ()):
        df = DataFrame(self.session,
                       L.WriteFile(path, "csv", self.plan, mode,
                                   partition_by))
        return df.collect_arrow()

    def write_hive_text(self, path: str, mode: str = "overwrite",
                        partition_by: Sequence[str] = (),
                        field_delim: Optional[str] = None,
                        null_value: Optional[str] = None):
        opts = {k: v for k, v in (("field_delim", field_delim),
                                  ("null_value", null_value))
                if v is not None}
        df = DataFrame(self.session,
                       L.WriteFile(path, "hive_text", self.plan, mode,
                                   partition_by, opts))
        return df.collect_arrow()

    def explain(self, mode: str = "physical") -> str:
        if mode == "logical":
            s = self.plan.tree_string()
        elif mode == "potential":
            s = explain_potential_tpu_plan(self.plan, self.session.conf)
        elif mode == "analyze":
            s = self._explain_analyze()
        elif mode == "placement":
            # the coded placement report (plan/tags.py): per-operator
            # device/host verdicts with reason codes — plans only,
            # never executes (docs/placement.md)
            physical = self._physical()
            rep = getattr(physical, "placement_report", None)
            s = (rep.render() if rep is not None
                 else "<no placement report>")
            decision = getattr(physical, "placement_decision", None)
            if decision:
                s = f"placement: {decision}\n" + s
        else:
            physical = self._physical()
            s = physical.tree_string()
            decision = getattr(physical, "placement_decision", None)
            if decision:
                # the cost optimizer's recorded WHY: a plan staying on
                # host explains itself from the EXPLAIN output alone
                s = f"placement: {decision}\n" + s
        print(s)
        return s

    def _explain_analyze(self) -> str:
        """EXPLAIN ANALYZE (the SQL-UI analog): EXECUTE the query
        through the full pipeline, then render the physical plan
        annotated with each operator's output rows, batches, cumulative
        and self time from ``ExecContext.metrics``
        (metrics/analyze.py)."""
        from ..metrics.analyze import render_analyzed_plan
        holder = {}

        def consume(physical, ctx):
            holder["physical"] = physical
            holder["ctx"] = ctx
            return physical.collect(ctx)

        self._execute_wrapped(consume)
        out = render_analyzed_plan(holder["physical"], holder["ctx"])
        rep = getattr(holder["physical"], "placement_report", None)
        if rep is not None and rep.counts():
            # the report's top-level verdict: ANALYZE output alone says
            # why (and how much of) the plan stayed on host
            out = (f"placement fallbacks [{rep.verdict}]: "
                   f"{rep.format_counts()}\n" + out)
        decision = getattr(holder["physical"], "placement_decision", None)
        if decision:
            out = f"placement: {decision}\n" + out
        if self.session.last_aqe_decisions:
            # the run's closed-taxonomy AQE decisions (ISSUE 19,
            # docs/aqe.md): ANALYZE output alone shows what the
            # adaptive layer changed about the plan it just executed
            lines = "".join(
                f"  {d['kind']}: {d['detail']}\n"
                for d in self.session.last_aqe_decisions)
            out += "adaptive execution decisions:\n" + lines
        return out


class GroupedData:
    def __init__(self, df: DataFrame, keys: List[Expression]):
        self.df = df
        self.keys = keys

    def agg(self, *aggs) -> DataFrame:
        parsed: List[AggregateExpression] = []
        for a in aggs:
            assert isinstance(a, AggregateExpression), \
                f"expected aggregate function, got {a!r}"
            parsed.append(a)
        return DataFrame(self.df.session,
                         L.Aggregate(self.keys, parsed, self.df.plan))

    def apply_in_pandas(self, fn, schema) -> DataFrame:
        """Per-group pandas transform (ref GpuFlatMapGroupsInPandasExec)."""
        names = []
        for k in self.keys:
            assert isinstance(k, ColumnRef), \
                "apply_in_pandas requires plain column keys"
            names.append(k.name)
        return DataFrame(self.df.session,
                         L.FlatMapGroupsInPandas(names, fn,
                                                 _as_schema(schema),
                                                 self.df.plan))

    # pyspark-style helpers
    def count(self) -> DataFrame:
        from ..exprs.aggregates import CountStar
        return self.agg(CountStar("count"))

    def sum(self, *names: str) -> DataFrame:
        from ..exprs.aggregates import Sum
        return self.agg(*[Sum(ColumnRef(n)) for n in names])

    def avg(self, *names: str) -> DataFrame:
        from ..exprs.aggregates import Average
        return self.agg(*[Average(ColumnRef(n)) for n in names])

    def min(self, *names: str) -> DataFrame:
        from ..exprs.aggregates import Min
        return self.agg(*[Min(ColumnRef(n)) for n in names])

    def max(self, *names: str) -> DataFrame:
        from ..exprs.aggregates import Max
        return self.agg(*[Max(ColumnRef(n)) for n in names])
