"""Column DSL + functions, PySpark-flavoured (the reference accelerates
Spark's DataFrame API; standalone we provide the same surface).

    from spark_rapids_tpu.api import functions as F
    df.select(F.col("a") + 1, F.when(F.col("b") > 0, 1).otherwise(0))
"""
from __future__ import annotations

from typing import Optional

from .. import exprs as E
from ..exprs.aggregates import (Average, Count, CountStar, First, Last, Max,
                                Min, StddevPop, StddevSamp, Sum, VariancePop,
                                VarianceSamp)
from ..types import (BOOL, DataType, FLOAT32, FLOAT64, INT8, INT16, INT32,
                     INT64, STRING, DATE, TIMESTAMP)

__all__ = ["Col", "col", "lit", "when", "coalesce", "isnan", "isnull",
           "sqrt", "exp", "log", "sin", "cos", "tan", "floor", "ceil",
           "round", "pow", "abs", "sum", "count", "count_star", "avg",
           "mean", "min", "max", "first", "last", "stddev", "stddev_pop",
           "var_samp", "var_pop", "cast", "asc", "desc"]

_builtin_abs, _builtin_sum, _builtin_min, _builtin_max, _builtin_round = \
    abs, sum, min, max, round


def _to_expr(v) -> E.Expression:
    if isinstance(v, Col):
        return v.expr
    if isinstance(v, E.Expression):
        return v
    return E.Literal(v)


class Col:
    """Wrapper giving Expression a PySpark-like operator surface."""

    def __init__(self, expr: E.Expression):
        self.expr = expr

    # arithmetic
    def __add__(self, o): return Col(E.Add(self.expr, _to_expr(o)))
    def __radd__(self, o): return Col(E.Add(_to_expr(o), self.expr))
    def __sub__(self, o): return Col(E.Subtract(self.expr, _to_expr(o)))
    def __rsub__(self, o): return Col(E.Subtract(_to_expr(o), self.expr))
    def __mul__(self, o): return Col(E.Multiply(self.expr, _to_expr(o)))
    def __rmul__(self, o): return Col(E.Multiply(_to_expr(o), self.expr))
    def __truediv__(self, o): return Col(E.Divide(self.expr, _to_expr(o)))
    def __rtruediv__(self, o): return Col(E.Divide(_to_expr(o), self.expr))
    def __mod__(self, o): return Col(E.Remainder(self.expr, _to_expr(o)))
    def __neg__(self): return Col(E.UnaryMinus(self.expr))
    def __pow__(self, o): return Col(E.Pow(self.expr, _to_expr(o)))

    # comparison
    def __eq__(self, o): return Col(E.EqualTo(self.expr, _to_expr(o)))
    def __ne__(self, o): return Col(E.NotEqual(self.expr, _to_expr(o)))
    def __lt__(self, o): return Col(E.LessThan(self.expr, _to_expr(o)))
    def __le__(self, o): return Col(E.LessThanOrEqual(self.expr, _to_expr(o)))
    def __gt__(self, o): return Col(E.GreaterThan(self.expr, _to_expr(o)))
    def __ge__(self, o): return Col(E.GreaterThanOrEqual(self.expr, _to_expr(o)))
    def eqNullSafe(self, o): return Col(E.EqualNullSafe(self.expr, _to_expr(o)))

    # logic
    def __and__(self, o): return Col(E.And(self.expr, _to_expr(o)))
    def __or__(self, o): return Col(E.Or(self.expr, _to_expr(o)))
    def __invert__(self): return Col(E.Not(self.expr))

    # misc
    def isNull(self): return Col(E.IsNull(self.expr))
    def isNotNull(self): return Col(E.IsNotNull(self.expr))

    # -- string predicates (PySpark Column parity) --
    def contains(self, s): return Col(E.Contains(self.expr, s))
    def startswith(self, s): return Col(E.StartsWith(self.expr, s))
    def endswith(self, s): return Col(E.EndsWith(self.expr, s))
    def like(self, pattern): return Col(E.Like(self.expr, pattern))
    def rlike(self, pattern): return Col(E.RLike(self.expr, pattern))
    def isin(self, *vals):
        vals = vals[0] if len(vals) == 1 and isinstance(vals[0], (list, tuple)) \
            else vals
        return Col(E.In(self.expr, vals))

    def alias(self, name: str): return Col(E.Alias(self.expr, name))
    name = alias

    def cast(self, dtype): return Col(E.Cast(self.expr, _dtype_of(dtype)))

    def asc(self, nulls_first: Optional[bool] = None):
        from ..plan.logical import SortOrder
        return SortOrder(self.expr, True, nulls_first)

    def desc(self, nulls_first: Optional[bool] = None):
        from ..plan.logical import SortOrder
        return SortOrder(self.expr, False, nulls_first)

    def __hash__(self):
        return id(self)

    def __repr__(self):
        return f"Col<{self.expr.name_hint}>"


_DTYPES = {"boolean": BOOL, "bool": BOOL, "tinyint": INT8, "byte": INT8,
           "smallint": INT16, "short": INT16, "int": INT32, "integer": INT32,
           "bigint": INT64, "long": INT64, "float": FLOAT32,
           "double": FLOAT64, "string": STRING, "date": DATE,
           "timestamp": TIMESTAMP}


def _dtype_of(d) -> DataType:
    if isinstance(d, DataType):
        return d
    return _DTYPES[str(d).lower()]


def col(name: str) -> Col:
    return Col(E.ColumnRef(name))


def lit(v) -> Col:
    return Col(E.Literal(v))


class _WhenBuilder:
    def __init__(self, branches):
        self.branches = branches

    def when(self, cond, value) -> "_WhenBuilder":
        return _WhenBuilder(self.branches + [(_to_expr(cond), _to_expr(value))])

    def otherwise(self, value) -> Col:
        return Col(E.CaseWhen(self.branches, _to_expr(value)))

    @property
    def col(self) -> Col:
        return Col(E.CaseWhen(self.branches, None))


def when(cond, value) -> _WhenBuilder:
    return _WhenBuilder([(_to_expr(cond), _to_expr(value))])


def coalesce(*cols) -> Col:
    return Col(E.Coalesce(*[_to_expr(c) for c in cols]))


def nullif(a, b) -> Col:
    """nullif(a, b): NULL when a == b else a (Spark semantics)."""
    return Col(E.NullIf(_to_expr(a), _to_expr(b)))


def isnan(c) -> Col: return Col(E.IsNaN(_to_expr(c)))
def isnull(c) -> Col: return Col(E.IsNull(_to_expr(c)))
def sqrt(c) -> Col: return Col(E.Sqrt(_to_expr(c)))
def exp(c) -> Col: return Col(E.Exp(_to_expr(c)))
def log(c) -> Col: return Col(E.Log(_to_expr(c)))
def sin(c) -> Col: return Col(E.Sin(_to_expr(c)))
def cos(c) -> Col: return Col(E.Cos(_to_expr(c)))
def tan(c) -> Col: return Col(E.Tan(_to_expr(c)))
def floor(c) -> Col: return Col(E.Floor(_to_expr(c)))
def ceil(c) -> Col: return Col(E.Ceil(_to_expr(c)))
def round(c, scale: int = 0) -> Col: return Col(E.Round(_to_expr(c), scale))
def pow(a, b) -> Col: return Col(E.Pow(_to_expr(a), _to_expr(b)))
def abs(c) -> Col: return Col(E.Abs(_to_expr(c)))
def cast(c, dtype) -> Col: return Col(E.Cast(_to_expr(c), _dtype_of(dtype)))


# --- datetime -------------------------------------------------------------
def year(c) -> Col: return Col(E.Year(_to_expr(c)))
def month(c) -> Col: return Col(E.Month(_to_expr(c)))
def dayofmonth(c) -> Col: return Col(E.DayOfMonth(_to_expr(c)))
def hour(c) -> Col: return Col(E.Hour(_to_expr(c)))
def minute(c) -> Col: return Col(E.Minute(_to_expr(c)))
def second(c) -> Col: return Col(E.Second(_to_expr(c)))
def dayofweek(c) -> Col: return Col(E.DayOfWeek(_to_expr(c)))
def weekday(c) -> Col: return Col(E.WeekDay(_to_expr(c)))
def dayofyear(c) -> Col: return Col(E.DayOfYear(_to_expr(c)))
def quarter(c) -> Col: return Col(E.Quarter(_to_expr(c)))
def date_add(c, days) -> Col: return Col(E.DateAdd(_to_expr(c), _to_expr(days)))
def date_sub(c, days) -> Col: return Col(E.DateSub(_to_expr(c), _to_expr(days)))
def datediff(end, start) -> Col:
    return Col(E.DateDiff(_to_expr(end), _to_expr(start)))


# --- strings ----------------------------------------------------------------
def length(c) -> Col: return Col(E.Length(_to_expr(c)))
def upper(c) -> Col: return Col(E.Upper(_to_expr(c)))
def lower(c) -> Col: return Col(E.Lower(_to_expr(c)))
def substring(c, pos, ln=None) -> Col:
    return Col(E.Substring(_to_expr(c), pos, ln))
def concat(*cols) -> Col:
    return Col(E.ConcatStrings(*[_to_expr(c) for c in cols]))
def contains(c, s) -> Col: return Col(E.Contains(_to_expr(c), s))
def startswith(c, s) -> Col: return Col(E.StartsWith(_to_expr(c), s))
def endswith(c, s) -> Col: return Col(E.EndsWith(_to_expr(c), s))
def like(c, pattern) -> Col: return Col(E.Like(_to_expr(c), pattern))
def rlike(c, pattern) -> Col: return Col(E.RLike(_to_expr(c), pattern))
def replace(c, search: str, replacement: str = "") -> Col:
    return Col(E.StringReplace(_to_expr(c), search, replacement))
def regexp_replace(c, pattern, repl) -> Col:
    return Col(E.RegExpReplace(_to_expr(c), pattern, repl))
def regexp_extract(c, pattern, group=1) -> Col:
    return Col(E.RegExpExtract(_to_expr(c), pattern, group))
def trim(c) -> Col: return Col(E.StringTrim(_to_expr(c)))
def ltrim(c) -> Col: return Col(E.StringTrimLeft(_to_expr(c)))
def rtrim(c) -> Col: return Col(E.StringTrimRight(_to_expr(c)))
def lpad(c, ln, pad=" ") -> Col: return Col(E.Lpad(_to_expr(c), ln, pad))
def rpad(c, ln, pad=" ") -> Col: return Col(E.Rpad(_to_expr(c), ln, pad))
def reverse(c) -> Col: return Col(E.Reverse(_to_expr(c)))
def repeat(c, n) -> Col: return Col(E.StringRepeat(_to_expr(c), n))
def initcap(c) -> Col: return Col(E.InitCap(_to_expr(c)))
def locate(substr, c) -> Col: return Col(E.StringLocate(substr, _to_expr(c)))
def split(c, pattern, limit=-1) -> Col:
    return Col(E.StringSplit(_to_expr(c), pattern, limit))
def parse_url(c, part, key=None) -> Col:
    return Col(E.ParseUrl(_to_expr(c), part, key))
def from_utc_timestamp(c, tz) -> Col:
    return Col(E.FromUtcTimestamp(_to_expr(c), tz))
def to_utc_timestamp(c, tz) -> Col:
    return Col(E.ToUtcTimestamp(_to_expr(c), tz))
def substring_index(c, delim, count) -> Col:
    return Col(E.SubstringIndex(_to_expr(c), delim, count))


# --- collections / complex types (ref collectionOperations.scala) -----------
def size(c) -> Col: return Col(E.Size(_to_expr(c)))
def array_contains(c, value) -> Col:
    return Col(E.ArrayContains(_to_expr(c), _to_expr(value)))
def array_position(c, value) -> Col:
    return Col(E.ArrayPosition(_to_expr(c), _to_expr(value)))
def element_at(c, extraction) -> Col:
    return Col(E.ElementAt(_to_expr(c), _to_expr(extraction)))
def get(c, index) -> Col:
    return Col(E.GetArrayItem(_to_expr(c), _to_expr(index)))
def get_field(c, name: str) -> Col:
    return Col(E.GetStructField(_to_expr(c), name))
def sort_array(c, asc: bool = True) -> Col:
    return Col(E.SortArray(_to_expr(c), E.Literal(asc)))
def array_min(c) -> Col: return Col(E.ArrayMin(_to_expr(c)))
def array_max(c) -> Col: return Col(E.ArrayMax(_to_expr(c)))
def array_join(c, delimiter, null_replacement=None) -> Col:
    rep = E.Literal(null_replacement) if null_replacement is not None else None
    return Col(E.ArrayJoin(_to_expr(c), E.Literal(delimiter), rep))
def slice(c, start, length) -> Col:
    return Col(E.Slice(_to_expr(c), _to_expr(start), _to_expr(length)))
def array_repeat(c, count) -> Col:
    return Col(E.ArrayRepeat(_to_expr(c), _to_expr(count)))
def arrays_zip(*cols) -> Col:
    names = [c.expr.name_hint if isinstance(c, Col) else str(i)
             for i, c in enumerate(cols)]
    return Col(E.ArraysZip(*[_to_expr(c) for c in cols], names=names))
def concat_arrays(*cols) -> Col:
    return Col(E.Concat(*[_to_expr(c) for c in cols]))
def flatten(c) -> Col: return Col(E.Flatten(_to_expr(c)))
def sequence(start, stop, step=None) -> Col:
    return Col(E.Sequence(_to_expr(start), _to_expr(stop),
                          _to_expr(step) if step is not None else None))
def array_distinct(c) -> Col: return Col(E.ArrayDistinct(_to_expr(c)))
def array_union(a, b) -> Col:
    return Col(E.ArrayUnion(_to_expr(a), _to_expr(b)))
def array_intersect(a, b) -> Col:
    return Col(E.ArrayIntersect(_to_expr(a), _to_expr(b)))
def array_except(a, b) -> Col:
    return Col(E.ArrayExcept(_to_expr(a), _to_expr(b)))
def array_remove(c, element) -> Col:
    return Col(E.ArrayRemove(_to_expr(c), _to_expr(element)))
def arrays_overlap(a, b) -> Col:
    return Col(E.ArraysOverlap(_to_expr(a), _to_expr(b)))
def array_reverse(c) -> Col: return Col(E.ArrayReverse(_to_expr(c)))
def map_keys(c) -> Col: return Col(E.MapKeys(_to_expr(c)))
def map_values(c) -> Col: return Col(E.MapValues(_to_expr(c)))
def map_entries(c) -> Col: return Col(E.MapEntries(_to_expr(c)))
def map_concat(*cols) -> Col:
    return Col(E.MapConcat(*[_to_expr(c) for c in cols]))
def map_from_arrays(keys, values) -> Col:
    return Col(E.MapFromArrays(_to_expr(keys), _to_expr(values)))
def str_to_map(c, pair_delim=",", kv_delim=":") -> Col:
    return Col(E.StringToMap(_to_expr(c), E.Literal(pair_delim),
                             E.Literal(kv_delim)))
def array(*cols) -> Col:
    return Col(E.CreateArray(*[_to_expr(c) for c in cols]))
def create_map(*cols) -> Col:
    return Col(E.CreateMap(*[_to_expr(c) for c in cols]))
def struct(*cols) -> Col:
    pairs = []
    for c in cols:
        pairs.append(E.Literal(c.expr.name_hint if isinstance(c, Col) else str(c)))
        pairs.append(_to_expr(c))
    return Col(E.CreateNamedStruct(*pairs))
def named_struct(*name_col_pairs) -> Col:
    return Col(E.CreateNamedStruct(*[_to_expr(p) for p in name_col_pairs]))


# --- higher-order functions (ref higherOrderFunctions.scala) ----------------
def _make_lambda(fn, hints, min_args=1):
    """Python callable over Col -> (arg vars, body expr). Arity is taken
    from the callable (like pyspark); min_args is per-function (e.g.
    zip_with and the map HOFs require exactly 2)."""
    import inspect
    n = len(inspect.signature(fn).parameters)
    if not min_args <= n <= len(hints):
        raise TypeError(
            f"lambda must take between {min_args} and {len(hints)} "
            f"arguments, got {n}")
    args = [E.NamedLambdaVariable(hints[i]) for i in range(n)]
    body = _to_expr(fn(*[Col(a) for a in args]))
    return args, body


def transform(c, fn) -> Col:
    args, body = _make_lambda(fn, ["x", "i"])
    return Col(E.ArrayTransform(_to_expr(c), args, body))
def filter(c, fn) -> Col:
    args, body = _make_lambda(fn, ["x", "i"])
    return Col(E.ArrayFilter(_to_expr(c), args, body))
def exists(c, fn) -> Col:
    args, body = _make_lambda(fn, ["x"])
    return Col(E.ArrayExists(_to_expr(c), args, body))
def forall(c, fn) -> Col:
    args, body = _make_lambda(fn, ["x"])
    return Col(E.ArrayForAll(_to_expr(c), args, body))
def aggregate(c, initial, merge, finish=None) -> Col:
    margs, mbody = _make_lambda(merge, ["acc", "x"], min_args=2)
    fargs = fbody = None
    if finish is not None:
        fargs, fbody = _make_lambda(finish, ["acc"])
    return Col(E.ArrayAggregate(_to_expr(c), _to_expr(initial), margs, mbody,
                                fargs, fbody))
def zip_with(a, b, fn) -> Col:
    args, body = _make_lambda(fn, ["x", "y"], min_args=2)
    return Col(E.ZipWith(_to_expr(a), _to_expr(b), args, body))
def transform_keys(c, fn) -> Col:
    args, body = _make_lambda(fn, ["k", "v"], min_args=2)
    return Col(E.TransformKeys(_to_expr(c), args, body))
def transform_values(c, fn) -> Col:
    args, body = _make_lambda(fn, ["k", "v"], min_args=2)
    return Col(E.TransformValues(_to_expr(c), args, body))
def map_filter(c, fn) -> Col:
    args, body = _make_lambda(fn, ["k", "v"], min_args=2)
    return Col(E.MapFilter(_to_expr(c), args, body))


# --- hashes / digests (ref HashFunctions.scala) -----------------------------
def hash(*cols) -> Col:
    return Col(E.Murmur3Hash([_to_expr(c) for c in cols]))
def xxhash64(*cols) -> Col:
    return Col(E.XxHash64([_to_expr(c) for c in cols]))
def hive_hash(*cols) -> Col:
    return Col(E.HiveHash([_to_expr(c) for c in cols]))
def md5(c) -> Col: return Col(E.Md5(_to_expr(c)))
def sha1(c) -> Col: return Col(E.Sha1(_to_expr(c)))
def sha2(c, num_bits: int = 256) -> Col:
    return Col(E.Sha2(_to_expr(c), num_bits))
def crc32(c) -> Col: return Col(E.Crc32(_to_expr(c)))


# --- JSON (ref GpuGetJsonObject / JsonToStructs / StructsToJson) ------------
def get_json_object(c, path: str) -> Col:
    return Col(E.GetJsonObject(_to_expr(c), E.Literal(path)))
def from_json(c, schema) -> Col:
    return Col(E.JsonToStructs(_to_expr(c), schema))
def to_json(c) -> Col: return Col(E.StructsToJson(_to_expr(c)))
def json_tuple(c, *fields) -> Col:
    return Col(E.JsonTuple(_to_expr(c), *fields))


# --- generators (ref GpuGenerateExec; planned via DataFrame.select) ---------
def explode(c) -> Col:
    from ..exprs.generators import Explode
    return Col(Explode(_to_expr(c)))
def explode_outer(c) -> Col:
    from ..exprs.generators import Explode
    return Col(Explode(_to_expr(c), outer=True))
def posexplode(c) -> Col:
    from ..exprs.generators import PosExplode
    return Col(PosExplode(_to_expr(c)))
def posexplode_outer(c) -> Col:
    from ..exprs.generators import PosExplode
    return Col(PosExplode(_to_expr(c), outer=True))
def stack(n: int, *cols) -> Col:
    from ..exprs.generators import Stack
    return Col(Stack(n, *[_to_expr(c) for c in cols]))


# --- task-context / non-deterministic ---------------------------------------
def monotonically_increasing_id() -> Col:
    from ..exprs.nondeterministic import MonotonicallyIncreasingID
    return Col(MonotonicallyIncreasingID())
def spark_partition_id() -> Col:
    from ..exprs.nondeterministic import SparkPartitionID
    return Col(SparkPartitionID())
def input_file_name() -> Col:
    from ..exprs.nondeterministic import InputFileName
    return Col(InputFileName())
def rand(seed: int = 0) -> Col:
    from ..exprs.nondeterministic import Rand
    return Col(Rand(seed))


# --- window -----------------------------------------------------------------
def row_number(): return E.RowNumber()
def rank(): return E.Rank()
def dense_rank(): return E.DenseRank()
def ntile(n): return E.NTile(n)
def nth_value(c, n): return E.NthValue(_to_expr(c), n)
def percent_rank(): return E.PercentRank()
def lag(c, offset=1, default=None):
    return E.Lag(_to_expr(c), offset, default)
def lead(c, offset=1, default=None):
    return E.Lead(_to_expr(c), offset, default)


def asc(name: str):
    return col(name).asc()


def desc(name: str):
    return col(name).desc()


# aggregates (return AggregateExpression, consumed by GroupedData/agg)
def sum(c): return Sum(_to_expr(c))
def count(c): return Count(_to_expr(c))
def count_star(): return CountStar()
def avg(c): return Average(_to_expr(c))
def count_distinct(c): return Count(_to_expr(c)).as_distinct()
def sum_distinct(c): return Sum(_to_expr(c)).as_distinct()
def avg_distinct(c): return Average(_to_expr(c)).as_distinct()
countDistinct = count_distinct
sumDistinct = sum_distinct
mean = avg
def min(c): return Min(_to_expr(c))
def max(c): return Max(_to_expr(c))
def first(c): return First(_to_expr(c))
def last(c): return Last(_to_expr(c))
def stddev(c): return StddevSamp(_to_expr(c))
def stddev_pop(c): return StddevPop(_to_expr(c))
def var_samp(c): return VarianceSamp(_to_expr(c))
def var_pop(c): return VariancePop(_to_expr(c))


def broadcast(df):
    """Mark a DataFrame as broadcastable for its next join (Spark's
    functions.broadcast; selects TpuBroadcastHashJoinExec in the planner)."""
    return df.hint("broadcast")


def udf(fn=None, return_type=None, compile: bool = True):
    """Python UDF: bytecode-compiled into the device plan when possible
    (ref udf-compiler), else row-based host fallback."""
    from ..udf import udf as _udf
    return _udf(fn, return_type, compile)


def columnar_udf(impl, *cols):
    """Hand-written columnar device UDF (ref RapidsUDF.java)."""
    from ..udf import ColumnarUDFExpr
    from .functions import _to_expr
    return ColumnarUDFExpr(impl, [_to_expr(c) for c in cols])


def df_udf(fn):
    """Dataframe-function UDF (ref DFUDFPlugin / sql-plugin-api
    functions.scala df_udf): the body is written in terms of Column
    expressions, so the call site inlines straight into the device plan —
    no bytecode compilation, no Python worker, full expression-level
    type checking and fusion."""
    def call(*cols):
        return fn(*[c if isinstance(c, Col) else lit(c) for c in cols])
    call.__name__ = getattr(fn, "__name__", "df_udf")
    return call


def pandas_udf(fn=None, return_type=None):
    """Vectorized pandas scalar UDF (ref GpuArrowEvalPythonExec role)."""
    if fn is None:
        return lambda f: pandas_udf(f, return_type)
    from ..udf.runtime import PandasUDF
    def call(*cols):
        return PandasUDF(fn, [_to_expr(c) for c in cols], return_type)
    call.__name__ = getattr(fn, "__name__", "pandas_udf")
    return call


# ---- round-3 breadth batch (ref GpuOverrides registry entries) -----------
def greatest(*cols) -> Col:
    return Col(E.Greatest(*[_to_expr(c) for c in cols]))
def least(*cols) -> Col:
    return Col(E.Least(*[_to_expr(c) for c in cols]))
def bitwise_not(c) -> Col: return Col(E.BitwiseNot(_to_expr(c)))
def shiftleft(c, n) -> Col:
    return Col(E.ShiftLeft(_to_expr(c), _to_expr(n)))
def shiftright(c, n) -> Col:
    return Col(E.ShiftRight(_to_expr(c), _to_expr(n)))
def shiftrightunsigned(c, n) -> Col:
    return Col(E.ShiftRightUnsigned(_to_expr(c), _to_expr(n)))
def hypot(a, b) -> Col: return Col(E.Hypot(_to_expr(a), _to_expr(b)))
def bround(c, scale: int = 0) -> Col:
    return Col(E.BRound(_to_expr(c), scale))
def asinh(c) -> Col: return Col(E.Asinh(_to_expr(c)))
def acosh(c) -> Col: return Col(E.Acosh(_to_expr(c)))
def atanh(c) -> Col: return Col(E.Atanh(_to_expr(c)))
def cot(c) -> Col: return Col(E.Cot(_to_expr(c)))
def last_day(c) -> Col: return Col(E.LastDay(_to_expr(c)))
def add_months(c, n) -> Col:
    return Col(E.AddMonths(_to_expr(c), _to_expr(n)))
def months_between(end, start, round_off: bool = True) -> Col:
    return Col(E.MonthsBetween(_to_expr(end), _to_expr(start), round_off))
def timestamp_seconds(c) -> Col:
    return Col(E.SecondsToTimestamp(_to_expr(c)))
def timestamp_millis(c) -> Col:
    return Col(E.MillisToTimestamp(_to_expr(c)))
def timestamp_micros(c) -> Col:
    return Col(E.MicrosToTimestamp(_to_expr(c)))
def to_unix_timestamp(c, fmt: str = "yyyy-MM-dd HH:mm:ss") -> Col:
    return Col(E.ToUnixTimestamp(_to_expr(c), fmt))
def unix_timestamp(c, fmt: str = "yyyy-MM-dd HH:mm:ss") -> Col:
    return Col(E.UnixTimestamp(_to_expr(c), fmt))
def from_unixtime(c, fmt: str = "yyyy-MM-dd HH:mm:ss") -> Col:
    return Col(E.FromUnixTime(_to_expr(c), fmt))
def date_format(c, fmt: str) -> Col:
    return Col(E.DateFormatClass(_to_expr(c), fmt))
def trunc(c, fmt: str) -> Col: return Col(E.TruncDate(_to_expr(c), fmt))
def ascii(c) -> Col: return Col(E.Ascii(_to_expr(c)))
def chr_(c) -> Col: return Col(E.Chr(_to_expr(c)))
def bit_length(c) -> Col: return Col(E.BitLength(_to_expr(c)))
def octet_length(c) -> Col: return Col(E.OctetLength(_to_expr(c)))
def instr(c, substr: str) -> Col:
    return Col(E.StringInstr(_to_expr(c), _to_expr(substr)))
def translate(c, src: str, dst: str) -> Col:
    return Col(E.StringTranslate(_to_expr(c), _to_expr(src),
                                 _to_expr(dst)))
def concat_ws(sep, *cols) -> Col:
    return Col(E.ConcatWs(_to_expr(sep), *[_to_expr(c) for c in cols]))
def format_number(c, d) -> Col:
    return Col(E.FormatNumber(_to_expr(c), _to_expr(d)))


def collect_list(c):
    from ..exprs.aggregates import CollectList
    return CollectList(_to_expr(c))
def collect_set(c):
    from ..exprs.aggregates import CollectSet
    return CollectSet(_to_expr(c))
def min_by(c, ordering):
    from ..exprs.aggregates import MinBy
    return MinBy(_to_expr(c), _to_expr(ordering))
def max_by(c, ordering):
    from ..exprs.aggregates import MaxBy
    return MaxBy(_to_expr(c), _to_expr(ordering))
def percentile(c, p: float):
    from ..exprs.aggregates import Percentile
    return Percentile(_to_expr(c), p)
