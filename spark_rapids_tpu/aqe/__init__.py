"""Closed-loop Adaptive Query Execution (ISSUE 19).

Reference analog: Spark AQE re-optimizes the remaining plan from the
MapOutputStatistics of every materialized shuffle (coalescing small
partitions, splitting skewed ones, demoting broadcasts whose build side
came in oversized) — the reference plugin rides those re-planned stages
through GpuCustomShuffleReaderExec. Our reproduction had every input to
that loop (the PR-4 profiler's per-partition histograms, the PR-8
learned costs, the PR-15 sentinel baselines, PR-3 lineage) but planned
once and executed blind. This package closes the loop:

* at each materialized shuffle boundary the cluster driver snapshots
  actual per-partition rows/bytes (:class:`~.planner.ShuffleStats`) and
  re-plans the not-yet-executed reduce side — runs of small partitions
  below ``spark.rapids.tpu.aqe.coalesce.targetBytes`` merge into one
  reduce unit, partitions above ``spark.rapids.tpu.aqe.skew.threshold``
  x mean are salted-rehashed into sub-partitions (shuffle/cluster.py);
* the single-process exchange's adaptive reader and the broadcast join
  record the same decisions when observed sizes flip a plan-time choice
  (shuffle/exchange.py, exec/joins.py, plan/overrides.py);
* :mod:`~.feedback` consumes sentinel history so a digest that
  repeatedly hit OOM rung >= 3 — or kept flagging warm-slowdown — is
  pre-emptively re-planned at admission (api/dataframe.py).

Every decision is an :class:`AqeDecision` with a kind from the CLOSED
``DECISION_KINDS`` registry (the plan/tags idiom: unknown kinds raise),
recorded into the process-global :class:`AqeLog` (install pattern of
trace/core.py) and fanned out to the metric inventory
(``srtpu_aqe_*``) and the tracer (one ``aqe.<kind>`` instant per
decision, which tools/profile counts). Surfaced in
``explain("analyze")``, ``GET /queries``, queryEnd / clusterQuery event
records and tools/history (docs/aqe.md).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..config import register

__all__ = [
    "AQE_ENABLED", "AQE_COALESCE_TARGET_BYTES", "AQE_SKEW_THRESHOLD",
    "AQE_SKEW_MIN_BYTES", "AQE_BROADCAST_DEMOTE_ENABLED",
    "AQE_FEEDBACK_ENABLED", "DECISION_KINDS", "COALESCE_PARTITIONS",
    "SKEW_SPLIT", "BROADCAST_DEMOTE", "BROADCAST_PROMOTE", "COST_REPLAN",
    "FEEDBACK_REPLAN", "AqeDecision", "make_decision", "AqeLog",
    "summarize", "LOG", "install_aqe", "ensure_aqe_from_conf"]

AQE_ENABLED = register(
    "spark.rapids.tpu.aqe.enabled", True,
    "Closed-loop adaptive query execution: re-plan at materialized "
    "shuffle boundaries from observed per-partition statistics "
    "(coalesce small partitions, split skewed ones with a salted "
    "rehash, demote oversized broadcasts) and record every decision as "
    "a closed-taxonomy AqeDecision (docs/aqe.md). Off = the pre-AQE "
    "one-shot plan with zero added overhead (ref Spark "
    "spark.sql.adaptive.enabled + GpuCustomShuffleReaderExec).",
    commonly_used=True)
AQE_COALESCE_TARGET_BYTES = register(
    "spark.rapids.tpu.aqe.coalesce.targetBytes", 64 * 1024 * 1024,
    "AQE merges consecutive shuffle partitions whose combined "
    "serialized size stays under this target into one reduce unit "
    "(ref spark.sql.adaptive.advisoryPartitionSizeInBytes).")
AQE_SKEW_THRESHOLD = register(
    "spark.rapids.tpu.aqe.skew.threshold", 2.0,
    "A shuffle partition is skewed when its serialized bytes exceed "
    "this factor times the mean partition size (the tools/profile "
    "SKEW_RATIO condition, now acted on at run time); skewed "
    "partitions are salted-rehashed into sub-partitions before the "
    "reduce (ref spark.sql.adaptive.skewJoin.skewedPartitionFactor).")
AQE_SKEW_MIN_BYTES = register(
    "spark.rapids.tpu.aqe.skew.minBytes", 1 << 20,
    "Partitions below this absolute size are never treated as skewed "
    "regardless of the ratio — splitting tiny partitions only adds "
    "task overhead (the profiler's SKEW_MIN_BYTES floor).")
AQE_BROADCAST_DEMOTE_ENABLED = register(
    "spark.rapids.tpu.aqe.broadcast.demote.enabled", True,
    "Record a broadcast_demote decision — and feed the measured size "
    "to the planner so the next plan of the same shape genuinely "
    "demotes — when a broadcast build side materializes LARGER than "
    "spark.rapids.tpu.sql.autoBroadcastJoinThreshold; the symmetric "
    "broadcast_promote fires when a measured side comes in under a "
    "threshold its estimate exceeded (ref AQE join-strategy "
    "switching, GpuOverrides.scala:4681).")
AQE_FEEDBACK_ENABLED = register(
    "spark.rapids.tpu.aqe.feedback.enabled", True,
    "Sentinel-history feedback: a plan digest whose baseline shows "
    "repeated OOM ladder escalation to rung >= 3 is pre-emptively "
    "re-planned at admission with quartered target batch sizes; one "
    "that keeps flagging warm-slowdown on the device is re-planned "
    "onto the host engine (aqe/feedback.py, docs/aqe.md). Requires "
    "both aqe.enabled and an installed regression sentinel.")

# --------------------------------------------------------------------------
# the closed decision taxonomy (docs/aqe.md mirrors this table)
# --------------------------------------------------------------------------

COALESCE_PARTITIONS = "coalesce_partitions"
SKEW_SPLIT = "skew_split"
BROADCAST_DEMOTE = "broadcast_demote"
BROADCAST_PROMOTE = "broadcast_promote"
COST_REPLAN = "cost_replan"
FEEDBACK_REPLAN = "feedback_replan"

#: kind -> one-line meaning; the single source docs/aqe.md, the
#: explain("analyze") renderer and tools/history share. CLOSED:
#: make_decision raises on anything not listed here (plan/tags.py
#: REASON_CODES pattern), so downstream consumers never see an
#: unclassifiable decision.
DECISION_KINDS: Dict[str, str] = {
    COALESCE_PARTITIONS:
        "a run of small shuffle partitions (each under "
        "aqe.coalesce.targetBytes combined) was merged into one "
        "reduce unit, or the single-process adaptive reader "
        "concatenated sub-target batches",
    SKEW_SPLIT:
        "a shuffle partition above aqe.skew.threshold x mean was "
        "salted-rehashed into sub-partitions before the reduce (for "
        "shuffled joins BOTH sides of the skewed partition are split "
        "with the same salt, keeping them co-partitioned)",
    BROADCAST_DEMOTE:
        "a planned broadcast's build side materialized larger than "
        "the auto-broadcast threshold; the measured size is recorded "
        "so the next plan of this shape uses a shuffled join",
    BROADCAST_PROMOTE:
        "a join side's MEASURED size came in under the auto-broadcast "
        "threshold its plan-time estimate exceeded, flipping the join "
        "to a broadcast build",
    COST_REPLAN:
        "observed row counts at a materialized boundary diverged from "
        "the scan-based estimate by >= 2x; the learned-cost optimizer "
        "re-plans the remaining stages (and future runs of this "
        "shape) with the observed cardinality",
    FEEDBACK_REPLAN:
        "sentinel history showed this digest repeatedly escalating "
        "the OOM ladder or flagging warm-slowdown; it was admitted "
        "with a pre-emptively re-planned configuration (smaller "
        "target batches or host placement)",
}


class AqeDecision:
    """One recorded adaptive decision: a registered ``kind``, free-text
    ``detail``, the shuffle id it acted on (when any) and how many
    partitions/sub-partitions it touched. Strings and ints only —
    decisions cross the event-log JSON boundary."""

    __slots__ = ("kind", "detail", "shuffle", "parts", "seq", "thread")

    def __init__(self, kind: str, detail: str = "",
                 shuffle: Optional[int] = None, parts: int = 0):
        if kind not in DECISION_KINDS:
            raise ValueError(
                f"unregistered AQE decision kind {kind!r} — add it to "
                "aqe.DECISION_KINDS (and docs/aqe.md) first")
        self.kind = kind
        self.detail = detail
        self.shuffle = shuffle
        self.parts = int(parts)
        self.seq = -1         # assigned by AqeLog.record
        self.thread = 0       # recording thread ident (attribution)

    def summary(self) -> dict:
        out = {"kind": self.kind, "detail": self.detail,
               "parts": self.parts}
        if self.shuffle is not None:
            out["shuffle"] = self.shuffle
        return out

    def __repr__(self):
        return (f"AqeDecision({self.kind}, parts={self.parts}, "
                f"shuffle={self.shuffle}, {self.detail!r})")

    def __getstate__(self):
        return {s: getattr(self, s) for s in self.__slots__}

    def __setstate__(self, st):
        for s in self.__slots__:
            setattr(self, s, st[s])


def make_decision(kind: str, detail: str = "",
                  shuffle: Optional[int] = None,
                  parts: int = 0) -> AqeDecision:
    """The one constructor decision sites use (raises on unknown kinds,
    keeping the taxonomy closed at every call site)."""
    return AqeDecision(kind, detail, shuffle=shuffle, parts=parts)


#: the specific per-kind counter next to the labeled replans_total
#: family (metrics/registry.py inventory; kinds without a row only
#: count in replans_total)
_KIND_COUNTER = {
    COALESCE_PARTITIONS: "srtpu_aqe_coalesced_partitions_total",
    SKEW_SPLIT: "srtpu_aqe_skew_splits_total",
    BROADCAST_DEMOTE: "srtpu_aqe_broadcast_demotions_total",
}


class AqeLog:
    """Process-global bounded decision log (install pattern of
    trace/core.py: module global, one attribute load + branch per
    decision site when AQE is off).

    Attribution contract: every decision site runs on the thread
    DRIVING its query (the cluster driver loop, the exchange's
    consuming generator, the broadcast build, the admission hook), so
    ``since(mark, thread=...)`` slices out exactly one query's
    decisions even with concurrent sessions in one process."""

    def __init__(self, max_events: int = 4096):
        self._lock = threading.Lock()
        self._seq = 0                        # tpulint: guarded-by _lock
        self._events: List[AqeDecision] = []  # tpulint: guarded-by _lock
        self._max = int(max_events)

    def mark(self) -> int:
        """Current sequence number — pair with :meth:`since` to scope
        one query's decisions."""
        with self._lock:
            return self._seq

    def record(self, d: AqeDecision) -> AqeDecision:
        """Append a decision and fan it out to the metric registry and
        the tracer (an ``aqe.<kind>`` instant tools/profile counts).
        The fan-out is observability: it must never fail the query
        that decided."""
        with self._lock:
            d.seq = self._seq
            self._seq += 1
            d.thread = threading.get_ident()
            self._events.append(d)
            if len(self._events) > self._max:
                del self._events[:len(self._events) - self._max]
        try:  # tpulint: never-raise
            from ..metrics import registry as metrics_registry
            mr = metrics_registry.REGISTRY
            if mr is not None:
                mr.counter("srtpu_aqe_replans_total", kind=d.kind).inc()
                spec = _KIND_COUNTER.get(d.kind)
                if spec is not None:
                    mr.counter(spec).inc(max(1, d.parts))
            from ..trace import core as trace_core
            tr = trace_core.TRACER
            if tr is not None:
                tr.instant(f"aqe.{d.kind}", cat="aqe", args=d.summary())
        except Exception:  # noqa: BLE001 - observability only
            pass
        return d

    def since(self, mark: int,
              thread: Optional[int] = None) -> List[AqeDecision]:
        """Decisions recorded at/after ``mark`` — optionally only those
        recorded by ``thread`` (per-query attribution under
        concurrency; see class docstring)."""
        with self._lock:
            evs = [d for d in self._events if d.seq >= mark]
        if thread is not None:
            evs = [d for d in evs if d.thread == thread]
        return evs

    def decisions(self) -> List[AqeDecision]:
        with self._lock:
            return list(self._events)


def summarize(decisions: List[AqeDecision]) -> Dict[str, int]:
    """decision kind -> count, the compact form queryEnd records,
    ``GET /queries`` and tools/history carry."""
    out: Dict[str, int] = {}
    for d in decisions:
        out[d.kind] = out.get(d.kind, 0) + 1
    return out


#: the installed log, or None = AQE off (every decision site is one
#: module-attribute load + branch on the disabled path)
LOG: Optional[AqeLog] = None


def install_aqe(log: Optional[AqeLog]) -> Optional[AqeLog]:
    """Install (or with None, tear down) the process AQE log."""
    global LOG
    LOG = log
    return log


def ensure_aqe_from_conf(conf) -> Optional[AqeLog]:
    """One conf lookup per ExecContext / cluster execute: installs the
    process log when ``spark.rapids.tpu.aqe.enabled`` is on and none is
    installed yet (the ensure_tracer_from_conf contract)."""
    if not bool(conf.get(AQE_ENABLED)):
        return None
    if LOG is None:
        install_aqe(AqeLog())
    return LOG
