"""Sentinel-history feedback: self-healing admission re-planning.

The regression sentinel (ops/sentinel.py) folds every queryEnd into a
per-digest baseline; since ISSUE 19 that baseline also counts how often
the digest escalated the OOM ladder to rung >= 3 (``highRungs``) and
how often it flagged warm-slowdown (``warmSlowdowns``). This module
turns those counters into an admission-time overlay: BEFORE the plan is
lowered, a digest with a bad history is re-planned

* with QUARTERED target batch sizes when it repeatedly hit rung >= 3 —
  the ladder's rung-2 split, applied pre-emptively so the query never
  pays the failed full-size attempts again; or
* onto the HOST engine when it repeatedly flagged warm-slowdown on the
  device — the same conf the query-level OOM ladder's final rung uses
  (``spark.rapids.tpu.sql.enabled=false``), chosen up front.

The overlay is a derived conf, not a mutation: the session conf — and
every other digest — is untouched, and the decision is recorded as a
``feedback_replan`` AqeDecision on the query's record (docs/aqe.md).
Thresholds are deliberately sticky: a digest that needed rung 3 twice
keeps its smaller batches even after the re-planned runs come back
healthy — the baseline remembers WHY they are healthy.
"""
from __future__ import annotations

from typing import Optional

#: how many rung>=3 folds / warm-slowdown flags a digest's baseline
#: must accumulate before feedback re-plans it (2 = "repeatedly":
#: one bad run can be noise, two is a pattern)
HIGH_RUNG_REPEATS = 2
WARM_SLOWDOWN_REPEATS = 2
#: how many over-SLO-target runs the live SLO tracker must attribute
#: to a digest before feedback shrinks its batches (ISSUE 20): the
#: burn alert sheds at the front door; this is the slower, per-digest
#: repair that removes the cause
SLO_BREACH_REPEATS = 2

#: the smaller-batch overlay divides both batch targets by this
#: (mirrors one SplitAndRetry halving applied twice, the ladder's
#: observed stable point for repeat offenders)
BATCH_SHRINK_FACTOR = 4
MIN_BATCH_BYTES = 1 << 20
MIN_BATCH_ROWS = 4096

__all__ = ["FeedbackPlan", "plan_feedback", "HIGH_RUNG_REPEATS",
           "WARM_SLOWDOWN_REPEATS", "SLO_BREACH_REPEATS",
           "BATCH_SHRINK_FACTOR"]


class FeedbackPlan:
    """One admission-time re-plan: conf ``settings`` to overlay and the
    human-readable ``reason`` the AqeDecision carries."""

    __slots__ = ("mode", "settings", "reason")

    def __init__(self, mode: str, settings: dict, reason: str):
        self.mode = mode            # smaller_batches | host
        self.settings = settings
        self.reason = reason


def _shrink_overlay(conf):
    """The quartered-batch settings, or None at the floor (shared by
    the rung-history and SLO-tail branches)."""
    from ..config import BATCH_SIZE_BYTES, BATCH_SIZE_ROWS
    cur_b = int(conf.get(BATCH_SIZE_BYTES))
    cur_r = int(conf.get(BATCH_SIZE_ROWS))
    new_b = max(MIN_BATCH_BYTES, cur_b // BATCH_SHRINK_FACTOR)
    new_r = max(MIN_BATCH_ROWS, cur_r // BATCH_SHRINK_FACTOR)
    if new_b >= cur_b and new_r >= cur_r:
        return None             # already at the floor: nothing to shrink
    return ({"spark.rapids.tpu.sql.batchSizeBytes": new_b,
             "spark.rapids.tpu.sql.batchSizeRows": new_r},
            cur_b, new_b, cur_r, new_r)


def plan_feedback(digest: Optional[str], baseline: Optional[dict],
                  conf) -> Optional[FeedbackPlan]:
    """Consult one digest's sentinel baseline and the live SLO
    tracker's per-digest breach counts; returns the overlay to apply
    at admission, or None when history is clean (the common path: two
    dict lookups and one None check)."""
    if not digest:
        return None
    high = int((baseline or {}).get("highRungs") or 0)
    warm = int((baseline or {}).get("warmSlowdowns") or 0)
    if high >= HIGH_RUNG_REPEATS:
        shrunk = _shrink_overlay(conf)
        if shrunk is None:
            return None
        settings, cur_b, new_b, cur_r, new_r = shrunk
        return FeedbackPlan(
            "smaller_batches", settings,
            f"digest {digest} hit OOM ladder rung>=3 {high}x — admitted "
            f"with batchSizeBytes {cur_b}->{new_b}, "
            f"batchSizeRows {cur_r}->{new_r}")
    if warm >= WARM_SLOWDOWN_REPEATS:
        return FeedbackPlan(
            "host",
            {"spark.rapids.tpu.sql.enabled": False},
            f"digest {digest} flagged warm-slowdown {warm}x on the "
            "device — admitted on the host engine")
    # SLO tail coupling (ISSUE 20): a digest the live tracker has
    # repeatedly attributed over-target walls to gets the same
    # pre-emptive batch shrink as a rung offender — smaller batches
    # shorten the longest device occupancy a single query can pin
    from ..ops import slo as slo_mod
    slo = slo_mod.TRACKER
    if slo is not None:
        breaches = slo.digest_breaches(digest)
        if breaches >= SLO_BREACH_REPEATS:
            shrunk = _shrink_overlay(conf)
            if shrunk is not None:
                settings, cur_b, new_b, cur_r, new_r = shrunk
                return FeedbackPlan(
                    "smaller_batches", settings,
                    f"digest {digest} exceeded its SLO target "
                    f"{breaches}x — admitted with batchSizeBytes "
                    f"{cur_b}->{new_b}, batchSizeRows {cur_r}->{new_r}")
    return None
