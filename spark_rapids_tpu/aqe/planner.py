"""Shuffle-boundary re-planning: observed partition statistics -> read
units (the Spark AQE OptimizeSkewedJoin / CoalesceShufflePartitions
analog, planned from real MapOutputStatistics instead of estimates).

The cluster driver folds every map task's per-partition (rows, bytes)
into a :class:`ShuffleStats` snapshot at materialization time
(shuffle/cluster.py ``_materialize``), then — before any reducer
launches — asks :func:`plan_reduce_units` how the reduce side should
read the shuffle. Pure functions over plain data: the same stats always
yield the same units, which is what keeps lineage re-execution (and the
chaos battery's byte-identity contract) safe with AQE on.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

__all__ = ["ShuffleStats", "ReadUnit", "plan_reduce_units", "split_width"]


class ShuffleStats:
    """Observed per-partition statistics of one materialized shuffle."""

    __slots__ = ("shuffle_id", "rows", "bytes", "n_parts")

    def __init__(self, shuffle_id: int,
                 part_stats: Dict[int, Tuple[int, int]], n_parts: int):
        self.shuffle_id = shuffle_id
        self.rows = {p: int(rb[0]) for p, rb in part_stats.items()}
        self.bytes = {p: int(rb[1]) for p, rb in part_stats.items()}
        self.n_parts = int(n_parts)

    @property
    def total_bytes(self) -> int:
        return sum(self.bytes.values())

    @property
    def total_rows(self) -> int:
        return sum(self.rows.values())

    @property
    def mean_bytes(self) -> float:
        return self.total_bytes / self.n_parts if self.n_parts else 0.0

    def part_bytes(self, p: int) -> int:
        return self.bytes.get(p, 0)

    def summary(self) -> dict:
        return {"shuffle": self.shuffle_id, "parts": self.n_parts,
                "rows": self.total_rows, "bytes": self.total_bytes,
                "max": max(self.bytes.values(), default=0)}


class ReadUnit:
    """One reduce-side task after re-planning: which partitions of
    which shuffle it reads, and which partition's owner runs it.
    ``order`` keeps driver-side concatenation in partition order (sort
    ranges stay globally ordered through coalescing; split sub-parts
    slot where their parent partition sat)."""

    __slots__ = ("sid", "parts", "owner_part", "order", "kind")

    def __init__(self, sid: int, parts: List[int], owner_part: int,
                 order: Tuple[int, int], kind: str = "plain"):
        self.sid = sid
        self.parts = list(parts)
        self.owner_part = int(owner_part)
        self.order = order
        self.kind = kind            # plain | coalesced | split

    def __repr__(self):
        return (f"ReadUnit(sid={self.sid}, parts={self.parts}, "
                f"owner={self.owner_part}, kind={self.kind})")


def is_skewed(size: int, mean: float, ratio: float, min_bytes: int) -> bool:
    """The profiler's skew condition (tools/profile SKEW_RATIO /
    SKEW_MIN_BYTES), now a planning predicate."""
    return size >= min_bytes and mean > 0 and size > ratio * mean


def split_width(size: int, mean: float, n_parts: int) -> int:
    """How many sub-partitions a skewed partition splits into: its
    multiple of the mean, clamped to [2, n_parts] (sub-partition j
    lands on the j-th owner, so the cluster width is the ceiling)."""
    k = int(round(size / mean)) if mean > 0 else 2
    return max(2, min(int(n_parts), k))


def plan_reduce_units(stats: ShuffleStats, *, target_bytes: int,
                      skew_threshold: float, skew_min_bytes: int,
                      allow_split: bool = True,
                      allow_coalesce: bool = True
                      ) -> Tuple[List[ReadUnit], Dict[int, int], int]:
    """Re-plan one shuffle's reduce side from its observed stats.

    Returns ``(units, splits, coalesced_groups)`` where ``units``
    covers every partition exactly once in partition order and
    ``splits`` maps each skewed partition to its sub-partition width.
    A skewed partition (``allow_split``) becomes a placeholder split
    unit per sub-partition (``sid`` = -1) — the caller materializes the
    salted re-shuffle and rewrites ``sid`` to the new shuffle id. Runs
    of consecutive non-skewed partitions whose combined bytes stay
    under ``target_bytes`` merge into one unit (``allow_coalesce``);
    empty partitions ride along with their neighbors.
    """
    n = stats.n_parts
    mean = stats.mean_bytes
    splits: Dict[int, int] = {}
    if allow_split:
        for p in range(n):
            if is_skewed(stats.part_bytes(p), mean,
                         skew_threshold, skew_min_bytes):
                splits[p] = split_width(stats.part_bytes(p), mean, n)
    split_set = set(splits)
    units: List[ReadUnit] = []
    coalesced = 0
    group: List[int] = []
    acc = 0

    def flush():
        nonlocal group, acc, coalesced
        if not group:
            return
        kind = "coalesced" if len(group) > 1 else "plain"
        if kind == "coalesced":
            coalesced += 1
        units.append(ReadUnit(stats.shuffle_id, group, group[0],
                              (group[0], 0), kind=kind))
        group, acc = [], 0

    for p in range(n):
        if p in split_set:
            flush()
            for j in range(splits[p]):
                units.append(ReadUnit(-1, [j], j, (p, j), kind="split"))
            continue
        b = stats.part_bytes(p)
        if not allow_coalesce:
            group, acc = [p], b
            flush()
            continue
        if group and acc + b > target_bytes:
            flush()
        group.append(p)
        acc += b
        if acc >= target_bytes:
            flush()
    flush()
    return units, splits, coalesced
