"""Auxiliary subsystems (ref SURVEY.md §5): LORE operator dump/replay,
profiler sessions, task-metrics aggregation, fatal-error dump handling,
allocation debug logging."""
from .lore import LoreDumpExec, lore_wrap, replay
from .profiler import Profiler
from .metrics import TaskMetrics, metrics_summary
from .fault import DeviceDumpHandler

__all__ = ["LoreDumpExec", "lore_wrap", "replay", "Profiler",
           "TaskMetrics", "metrics_summary", "DeviceDumpHandler"]
