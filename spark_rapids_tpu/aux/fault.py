"""Fatal device-error handling (ref Plugin.scala:661-686 — on fatal CUDA
errors the executor captures nvidia-smi output + a GPU core dump
(GpuCoreDumpHandler.scala:48-138) then self-terminates with exit 20 so
Spark replaces it).

TPU analog: on an XLA runtime error escaping a query, capture a diagnostic
dump (device list, memory-manager accounting, live-spillable census, the
failing plan) into ``spark.rapids.tpu.coreDump.path`` before re-raising.
Recovery itself stays with the caller (Spark's task-retry role)."""
from __future__ import annotations

import json
import logging
import os
import time
import traceback

from ..config import register

log = logging.getLogger(__name__)

__all__ = ["DeviceDumpHandler"]

CORE_DUMP_PATH = register(
    "spark.rapids.tpu.coreDump.path", "",
    "Directory for device-failure diagnostic dumps; empty disables "
    "(ref spark.rapids.gpu.coreDump.dir, GpuCoreDumpHandler.scala).")


def _is_device_error(e: BaseException) -> bool:
    name = type(e).__name__
    return "XlaRuntimeError" in name or "RuntimeError" in name and \
        "RESOURCE_EXHAUSTED" in str(e)


class DeviceDumpHandler:
    def __init__(self, conf):
        self.path = str(conf.get(CORE_DUMP_PATH))

    def capture(self, exc: BaseException, plan=None) -> str:
        """Write the diagnostic dump; returns its path ('' if disabled)."""
        if not self.path:
            return ""
        os.makedirs(self.path, exist_ok=True)
        out = os.path.join(self.path, f"tpu-dump-{int(time.time()*1000)}.json")
        info = {"error": repr(exc),
                "traceback": traceback.format_exc(),
                "plan": plan.tree_string() if plan is not None else None}
        try:
            import jax
            info["devices"] = [str(d) for d in jax.devices()]
        except Exception:
            pass
        try:
            from ..mem.manager import MemoryManager
            info["memory"] = MemoryManager.get().stats()
        except Exception:
            pass
        with open(out, "w") as f:
            json.dump(info, f, indent=2)
        log.error("device failure diagnostic dumped to %s", out)
        return out

    def wrap(self, fn, plan=None):
        try:
            return fn()
        except Exception as e:
            if _is_device_error(e):
                self.capture(e, plan)
            raise
