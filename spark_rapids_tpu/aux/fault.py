"""Fault handling and fault injection.

Two halves, mirroring the reference plugin's split:

* ``DeviceDumpHandler`` — fatal device-error diagnostics (ref
  Plugin.scala:661-686: on fatal CUDA errors the executor captures
  nvidia-smi output + a GPU core dump (GpuCoreDumpHandler.scala:48-138)
  then self-terminates with exit 20 so Spark replaces it). TPU analog:
  on an XLA runtime error escaping a query, capture a diagnostic dump
  (device list, memory-manager accounting, live-spillable census, the
  failing plan) into ``spark.rapids.tpu.coreDump.path`` before
  re-raising. Recovery itself stays with the caller (Spark's task-retry
  role — here shuffle/cluster.py's fault-tolerant dispatch).

* ``ChaosController`` — deterministic, seeded fault injection for the
  distributed runtime: the cross-process analog of the memory layer's
  ``MemoryManager.force_retry_oom`` (ref RmmSpark.forceRetryOOM test
  hooks). Config-driven (``spark.rapids.tpu.chaos.*``): injects worker
  kills, dropped/corrupted/delayed blocks, and RPC delays at NAMED sites
  in the shuffle transport and the cluster dispatch loop, so the chaos
  suite can assert byte-identical results with chaos on vs. off.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
import traceback
import zlib
from typing import Dict, List, Optional, Tuple

from ..config import register

log = logging.getLogger(__name__)

__all__ = ["DeviceDumpHandler", "ChaosController", "install_chaos",
           "active_chaos", "CHAOS_SITES"]

CORE_DUMP_PATH = register(
    "spark.rapids.tpu.coreDump.path", "",
    "Directory for device-failure diagnostic dumps; empty disables "
    "(ref spark.rapids.gpu.coreDump.dir, GpuCoreDumpHandler.scala).")

CHAOS_SPEC = register(
    "spark.rapids.tpu.chaos.spec", "",
    "Fault-injection spec for the runtime; empty disables. "
    "Semicolon-separated `site=when` entries where `when` is an integer "
    "N (fire exactly on the Nth hit of that site), `pX` (fire with "
    "probability X per hit, seeded), or `*` (every hit). Transport/"
    "cluster sites: put.corrupt, put.drop, put.delay, fetch.corrupt, "
    "fetch.delay, task.delay, worker.kill. Memory/semaphore sites "
    "(docs/fault_tolerance.md): mem.oom (MemoryManager.reserve raises "
    "an injected RetryOOM), mem.reserve.delay (reserve sleeps delayMs), "
    "sem.stall (a successful semaphore acquire stalls delayMs while "
    "HOLDING the permit). Admission sites (sched/admission.py, "
    "docs/serving.md): admit.delay (an admission attempt stalls "
    "delayMs before queueing), admit.reject (an admission attempt is "
    "refused with AdmissionRejected(reason=chaos)). The config-driven "
    "analog of the OOM injection hooks (ref RmmSpark.forceRetryOOM).")

CHAOS_SEED = register(
    "spark.rapids.tpu.chaos.seed", 0,
    "Seed for probabilistic chaos rules — a fixed seed makes an "
    "injection schedule reproducible across runs.")

CHAOS_DELAY_MS = register(
    "spark.rapids.tpu.chaos.delayMs", 100,
    "Sleep injected by the *.delay chaos sites, in milliseconds.")

CHAOS_KILL_TARGET = register(
    "spark.rapids.tpu.chaos.killTarget", "",
    "Worker id (e.g. worker-1) the worker.kill chaos site terminates; "
    "empty means the first worker a task is dispatched to when the site "
    "fires.")


def _is_device_error(e: BaseException) -> bool:
    name = type(e).__name__
    # XlaRuntimeError is always a device failure; a bare RuntimeError
    # qualifies only when the runtime's RESOURCE_EXHAUSTED marker is in
    # the message (explicit grouping — `A or B and C` read ambiguously)
    return "XlaRuntimeError" in name or (
        "RuntimeError" in name and "RESOURCE_EXHAUSTED" in str(e))


class DeviceDumpHandler:
    def __init__(self, conf):
        self.path = str(conf.get(CORE_DUMP_PATH))

    def capture(self, exc: BaseException, plan=None) -> str:
        """Write the diagnostic dump; returns its path ('' if disabled)."""
        if not self.path:
            return ""
        os.makedirs(self.path, exist_ok=True)
        out = os.path.join(self.path, f"tpu-dump-{int(time.time()*1000)}.json")
        # format the PASSED exception's traceback — format_exc() reads
        # sys.exc_info() and is empty outside an active except block
        tb = "".join(traceback.format_exception(
            type(exc), exc, exc.__traceback__))
        info = {"error": repr(exc),
                "traceback": tb,
                "plan": plan.tree_string() if plan is not None else None}
        try:
            import jax
            info["devices"] = [str(d) for d in jax.devices()]
        except Exception:
            pass
        try:
            from ..mem.manager import MemoryManager
            info["memory"] = MemoryManager.get().stats()
        except Exception:
            pass
        with open(out, "w") as f:
            json.dump(info, f, indent=2)
        log.error("device failure diagnostic dumped to %s", out)
        return out

    def wrap(self, fn, plan=None):
        try:
            return fn()
        except Exception as e:
            if _is_device_error(e):
                self.capture(e, plan)
            raise


# ---------------------------------------------------------------------------
# chaos injection
# ---------------------------------------------------------------------------

#: the closed set of injection sites (a site name outside this set is a
#: spec error — named sites are the contract between the controller and
#: the transport/cluster hooks, like the reference's typed message enum)
CHAOS_SITES = ("put.corrupt", "put.drop", "put.delay", "fetch.corrupt",
               "fetch.delay", "task.delay", "worker.kill",
               # memory / semaphore sites (mem/manager.py reserve(),
               # mem/semaphore.py acquire()) — ISSUE 14 pressure battery
               "mem.oom", "mem.reserve.delay", "sem.stall",
               # admission sites (sched/admission.py admit()) — ISSUE 18
               # mixed-tenant serving battery
               "admit.delay", "admit.reject")


class ChaosController:
    """Deterministic fault injector.

    Each named site calls ``fires(site)`` (or a convenience wrapper) once
    per potential injection point; the spec decides whether that hit
    injects. Counting is per-site and the probabilistic rules use a
    per-site RNG seeded from (seed, site), so a given (spec, seed) yields
    the SAME injection schedule on every run — the property the chaos
    suite's byte-identical assertion rests on."""

    def __init__(self, spec: str = "", seed: int = 0,
                 delay_ms: int = 100, kill_target: str = ""):
        self.seed = int(seed)
        self.delay_ms = int(delay_ms)
        self.kill_target = kill_target
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}  # tpulint: guarded-by _lock
        self._fired: List[Tuple[str, int]] = []  # tpulint: guarded-by _lock
        # site -> distinct caller contexts that fired (mem.* sites record
        # the operator-level reserve site so the chaos battery can assert
        # coverage breadth, e.g. "mem.oom hit >= 3 distinct reserve sites")
        self._contexts: Dict[str, set] = {}  # tpulint: guarded-by _lock
        self._rules: Dict[str, Tuple[str, float]] = {}
        self._rngs: Dict[str, "object"] = {}
        for entry in str(spec).split(";"):
            entry = entry.strip()
            if not entry:
                continue
            site, _, when = entry.partition("=")
            site, when = site.strip(), when.strip()
            if site not in CHAOS_SITES:
                raise ValueError(
                    f"unknown chaos site {site!r}; sites: {CHAOS_SITES}")
            if when == "*":
                self._rules[site] = ("always", 0.0)
            elif when.startswith("p"):
                self._rules[site] = ("prob", float(when[1:]))
            else:
                self._rules[site] = ("nth", float(int(when)))

    @classmethod
    def from_conf(cls, conf) -> Optional["ChaosController"]:
        spec = str(conf.get(CHAOS_SPEC))
        if not spec.strip():
            return None
        return cls(spec, seed=int(conf.get(CHAOS_SEED)),
                   delay_ms=int(conf.get(CHAOS_DELAY_MS)),
                   kill_target=str(conf.get(CHAOS_KILL_TARGET)))

    def _rng(self, site: str):
        import numpy as np
        if site not in self._rngs:
            self._rngs[site] = np.random.RandomState(
                (self.seed * 1_000_003 + zlib.crc32(site.encode()))
                % (2 ** 31))
        return self._rngs[site]

    def wants(self, site: str) -> bool:
        """Does the spec name this site at all? (Callers with expensive
        hooks — e.g. the driver's worker-kill — can skip the counter.)"""
        return site in self._rules

    def fires(self, site: str) -> bool:
        """One potential injection point was hit; inject?"""
        rule = self._rules.get(site)
        with self._lock:
            n = self._counts[site] = self._counts.get(site, 0) + 1
            if rule is None:
                return False
            mode, arg = rule
            hit = (mode == "always"
                   or (mode == "nth" and n == int(arg))
                   or (mode == "prob"
                       and self._rng(site).uniform() < arg))
            if hit:
                self._fired.append((site, n))
                log.warning("chaos: injecting %s (hit #%d)", site, n)
            return hit

    # convenience wrappers for the transport hooks -----------------------
    def corrupt(self, site: str, data: bytes) -> bytes:
        """Flip a byte of ``data`` when the site fires (CRC-detectable,
        never a silent truncation)."""
        if data and self.fires(site):
            buf = bytearray(data)
            buf[len(buf) // 2] ^= 0xFF
            return bytes(buf)
        return data

    def maybe_delay(self, site: str) -> None:
        if self.fires(site):
            time.sleep(self.delay_ms / 1000.0)

    def note_context(self, site: str, detail: str) -> None:
        """Record the caller context of a fired injection (mem.* sites
        pass the operator-level reserve site, e.g. 'sort.py:do_sort')."""
        with self._lock:
            self._contexts.setdefault(site, set()).add(detail)

    def contexts(self, site: str) -> List[str]:
        """Distinct caller contexts recorded for a site, sorted."""
        with self._lock:
            return sorted(self._contexts.get(site, ()))

    def fired(self) -> List[Tuple[str, int]]:
        with self._lock:
            return list(self._fired)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)


_ACTIVE: List[Optional[ChaosController]] = [None]


def install_chaos(ctl: Optional[ChaosController]) -> None:
    """Install (or with None, remove) the process-global controller —
    the driver arms workers through the `chaos` task RPC, which lands
    here in each worker process."""
    _ACTIVE[0] = ctl


def active_chaos() -> Optional[ChaosController]:
    return _ACTIVE[0]
