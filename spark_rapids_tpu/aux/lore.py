"""LORE: per-operator dump + offline replay (ref lore/GpuLore.scala:22-40,
dump.scala, replay.scala; tagging at GpuOverrides.scala:4840 tagForLore).

Every exec in a physical plan gets a stable LORE id (preorder index).
With ``spark.rapids.tpu.lore.dumpPath`` set and ``...lore.idsToDump``
listing ids, those operators' INPUT batches are written as parquet files
plus a plan.json describing the operator, so a single device operator can
be re-executed offline against its captured inputs — the reference's
debugging workflow for "this one exec misbehaves at scale".
"""
from __future__ import annotations

import json
import os
from typing import Iterator, List, Optional

from ..columnar import ColumnarBatch
from ..config import LORE_DUMP_PATH, LORE_IDS
from ..exec.base import ExecContext, TpuExec

__all__ = ["LoreDumpExec", "lore_wrap", "replay"]


class LoreDumpExec(TpuExec):
    """Transparent pass-through that tees the child's batches to disk."""

    def __init__(self, child: TpuExec, lore_id: int, wrapped: TpuExec,
                 path: str, child_slot: int):
        super().__init__([child])
        self.lore_id = lore_id
        self.wrapped = wrapped
        self.path = path
        self.child_slot = child_slot

    def output_schema(self):
        return self.children[0].output_schema()

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        import pyarrow.parquet as pq
        d = os.path.join(self.path, f"loreId-{self.lore_id}",
                         f"input-{self.child_slot}")
        os.makedirs(d, exist_ok=True)
        for i, b in enumerate(self.children[0].execute(ctx)):
            pq.write_table(b.to_arrow(), os.path.join(d, f"batch-{i}.parquet"))
            yield b

    def describe(self):
        return f"LoreDump[id={self.lore_id}, slot={self.child_slot}]"


def _plan_repr(e: TpuExec) -> dict:
    return {"exec": type(e).__name__, "describe": e.describe(),
            "module": type(e).__module__,
            "schema": [(f.name, f.dtype.name)
                       for f in e.output_schema().fields]}


def lore_wrap(root: TpuExec, conf) -> TpuExec:
    """Assign LORE ids (preorder) and interpose dump nodes around the
    requested operators' inputs."""
    path = str(conf.get(LORE_DUMP_PATH))
    ids = {int(x) for x in str(conf.get(LORE_IDS)).split(",")
           if x.strip().isdigit()}
    counter = [0]

    def walk(e: TpuExec) -> TpuExec:
        my_id = counter[0]
        counter[0] += 1
        e.lore_id = my_id
        new_children = [walk(c) for c in e.children]
        if path and my_id in ids:
            d = os.path.join(path, f"loreId-{my_id}")
            os.makedirs(d, exist_ok=True)
            with open(os.path.join(d, "plan.json"), "w") as f:
                json.dump(_plan_repr(e), f, indent=2)
            new_children = [
                LoreDumpExec(c, my_id, e, path, slot)
                for slot, c in enumerate(new_children)]
        e.children = new_children
        return e

    return walk(root)


def replay(dump_path: str, lore_id: int, exec_factory) -> "object":
    """Re-run one operator against its captured inputs
    (ref lore/replay.scala). ``exec_factory(children) -> TpuExec`` builds
    the operator over InMemoryScan children of the captured batches;
    returns the collected Arrow table."""
    import pyarrow.parquet as pq

    from ..exec.basic import InMemoryScanExec
    from ..types import Schema, StructField, from_arrow
    d = os.path.join(dump_path, f"loreId-{lore_id}")
    children: List[TpuExec] = []
    slot = 0
    while os.path.isdir(os.path.join(d, f"input-{slot}")):
        sd = os.path.join(d, f"input-{slot}")
        tables = [pq.read_table(os.path.join(sd, f))
                  for f in sorted(os.listdir(sd)) if f.endswith(".parquet")]
        schema = Schema([StructField(f.name, from_arrow(f.type), f.nullable)
                         for f in tables[0].schema])
        children.append(InMemoryScanExec(tables, schema))
        slot += 1
    if not children:
        raise FileNotFoundError(f"no LORE capture at {d}")
    op = exec_factory(children)
    return op.collect()
