"""Task metrics aggregation (ref GpuTaskMetrics.scala:110-195 — semaphore
wait, spill-to-host/disk time+bytes, max device footprint — merged into
Spark accumulators; here merged into a per-query summary dict exposed as
``TpuSession.last_query_metrics``)."""
from __future__ import annotations

from typing import Dict

__all__ = ["TaskMetrics", "metrics_summary"]


class TaskMetrics:
    """Point-in-time capture of runtime counters to diff across a query."""

    def __init__(self, ctx):
        self.ctx = ctx
        mm = ctx.memory
        self._before = {
            "semWaitSec": ctx.semaphore.total_wait_s,
            "spillToHostBytes": mm.spill_to_host_bytes,
            **{k: v for k, v in mm.stats().items()},
        }

    def finish(self) -> Dict[str, object]:
        ctx = self.ctx
        mm = ctx.memory
        after = mm.stats()
        out = {
            "semWaitSec": round(
                ctx.semaphore.total_wait_s - self._before["semWaitSec"], 6),
            "spillToHostBytes":
                mm.spill_to_host_bytes - self._before["spillToHostBytes"],
            "spillToDiskBytes":
                after["spill_to_disk_bytes"]
                - self._before["spill_to_disk_bytes"],
            "maxDeviceBytes": after["max_device_used"],
        }
        out["operators"] = metrics_summary(ctx)
        return out


def metrics_summary(ctx) -> Dict[str, Dict[str, object]]:
    """Per-exec metric values keyed by exec id (the SQL-UI GpuMetric view,
    GpuExec.scala:54-165; levels preserved)."""
    out: Dict[str, Dict[str, object]] = {}
    for exec_id, ms in ctx.metrics.items():
        # metric adds may accumulate lazy device scalars (row counts kept
        # unforced to avoid tunnel syncs); force to plain ints ONCE here
        out[exec_id] = {name: (m.value.item()
                               if hasattr(m.value, "item") else m.value)
                        for name, m in ms.items()}
    return out
