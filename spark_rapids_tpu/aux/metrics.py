"""Task metrics aggregation (ref GpuTaskMetrics.scala:110-195 — semaphore
wait, spill-to-host/disk time+bytes, max device footprint — merged into
Spark accumulators; here merged into a per-query summary dict exposed as
``TpuSession.last_query_metrics``)."""
from __future__ import annotations

from collections.abc import Mapping
from typing import Dict, Optional

__all__ = ["TaskMetrics", "metrics_summary", "metrics_to_json"]


class TaskMetrics:
    """Point-in-time capture of runtime counters to diff across a query."""

    def __init__(self, ctx):
        self.ctx = ctx
        mm = ctx.memory
        self._before = {
            "semWaitSec": ctx.semaphore.total_wait_s,
            "spillToHostBytes": mm.spill_to_host_bytes,
            **{k: v for k, v in mm.stats().items()},
        }

    def finish(self) -> Dict[str, object]:
        ctx = self.ctx
        mm = ctx.memory
        after = mm.stats()
        out = {
            "semWaitSec": round(
                ctx.semaphore.total_wait_s - self._before["semWaitSec"], 6),
            "spillToHostBytes":
                mm.spill_to_host_bytes - self._before["spillToHostBytes"],
            "spillToDiskBytes":
                after["spill_to_disk_bytes"]
                - self._before["spill_to_disk_bytes"],
            "maxDeviceBytes": after["max_device_used"],
        }
        out["operators"] = metrics_summary(ctx)
        return out


class LazyMetricsView(Mapping):
    """Per-exec metric mapping that defers forcing lazy device-scalar
    values (row counts kept unforced to avoid tunnel syncs) until someone
    READS the metrics — then forces them all in ONE packed fetch instead
    of one ~100 ms round trip per metric. A query that never inspects
    last_query_metrics pays nothing.

    The VALUES are snapshotted at construction (finish time): jax scalars
    are immutable, so later queries mutating the live Metric objects
    cannot contaminate this view, and forcing never writes back into
    engine state. Mapping (not dict) so every access path — [], get, in,
    iteration, dict(view) — funnels through the forcing accessors."""

    def __init__(self, values):
        #: exec_id -> {name: raw value (host number or jax scalar)}
        self._raw = values
        self._data = None

    def _force(self):
        if self._data is not None:
            return self._data
        lazy = [(eid, name, v) for eid, ms in self._raw.items()
                for name, v in ms.items() if hasattr(v, "item")]
        forced = {}
        if lazy:
            from ..columnar.packing import fetch_packed
            got = fetch_packed([v for _, _, v in lazy])
            for (eid, name, _), v in zip(lazy, got):
                forced[(eid, name)] = v.item() if hasattr(v, "item") else v
        self._data = {
            eid: {name: forced.get((eid, name), v)
                  for name, v in ms.items()}
            for eid, ms in self._raw.items()}
        return self._data

    def __getitem__(self, k):
        return self._force()[k]

    def __iter__(self):
        return iter(self._force())

    def __len__(self):
        return len(self._force())

    def __repr__(self):
        return repr(self._force())


def metrics_to_json(summary: Optional[dict]) -> Optional[dict]:
    """TaskMetrics.finish() output -> plain JSON-able dict (forces the
    lazy operator view — one packed fetch). Used by the event log's
    queryEnd record; NEVER raises: forcing device scalars after a failed
    query can itself fail, and the event-log path must not mask the
    query's real exception — it degrades to operators=None instead."""
    if summary is None:
        return None
    out = {}
    for k, v in summary.items():
        if k != "operators":
            out[k] = v.item() if hasattr(v, "item") else v
            continue
        try:
            ops = {}
            for eid, ms in dict(v).items():
                ops[eid] = {
                    n: (val.item() if hasattr(val, "item") else val)
                    for n, val in ms.items()}
            out[k] = ops
        except Exception:  # noqa: BLE001 - degrade, never mask
            out[k] = None
    return out


def metrics_summary(ctx):
    """Per-exec metric values keyed by exec id (the SQL-UI GpuMetric view,
    GpuExec.scala:54-165). The verbosity conf plays the role of the
    reference's DEBUG/MODERATE/ESSENTIAL metric levels: lower verbosity
    drops the noisier counters from the summary. Lazy: LazyMetricsView."""
    from ..config import METRICS_LEVEL
    level = str(ctx.conf.get(METRICS_LEVEL)).upper()
    lvl_rank = {"ESSENTIAL": 0, "MODERATE": 1, "DEBUG": 2}
    keep = lvl_rank.get(level, 2)
    # snapshot the raw VALUES of THIS query now — Metric objects live on
    # the session-cached context and later queries mutate them
    snap = {}
    for exec_id, ms in ctx.metrics.items():
        kept = {name: m.value for name, m in ms.items()
                if lvl_rank.get(m.level, 1) <= keep}
        if kept:
            snap[exec_id] = kept
    return LazyMetricsView(snap)
