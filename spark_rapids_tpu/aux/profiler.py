"""Profiler sessions (ref profiler.scala ProfilerOnExecutor/OnDriver
wrapping the CUPTI JNI Profiler; TPU analog = jax.profiler traces viewable
in xprof/TensorBoard).

The reference scopes captures by time/job/stage ranges coordinated over
driver RPC (ProfileMsg, Plugin.scala:441). Here the executing process is
the session, so captures are scoped by QUERY index ranges: with
``spark.rapids.tpu.profile.pathPrefix`` set, queries whose ordinal falls in
``spark.rapids.tpu.profile.queryRanges`` (e.g. "0-2,5") are traced.
"""
from __future__ import annotations

import logging
import os
from typing import Optional, Set

from ..config import PROFILE_PATH, register

log = logging.getLogger(__name__)

__all__ = ["Profiler"]

PROFILE_RANGES = register(
    "spark.rapids.tpu.profile.queryRanges", "0-999999",
    "Query ordinals to capture, e.g. \"0-2,5\" (ref the reference's "
    "time/job/stage range scoping, profiler.scala).")


def _parse_ranges(s: str) -> Set[int]:
    out: Set[int] = set()
    for part in s.split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            a, b = part.split("-")
            out.update(range(int(a), int(b) + 1))
        else:
            out.add(int(part))
    return out


class Profiler:
    """Per-session profiler; wraps query execution in a jax trace when the
    query ordinal is in range."""

    def __init__(self, conf):
        self.path = str(conf.get(PROFILE_PATH))
        self.ranges = _parse_ranges(str(conf.get(PROFILE_RANGES))) \
            if self.path else set()
        self.query_index = 0
        self._active = False

    def maybe_start(self) -> None:
        idx = self.query_index
        self.query_index += 1
        if not self.path or idx not in self.ranges or self._active:
            return
        import jax
        d = os.path.join(self.path, f"query-{idx}")
        os.makedirs(d, exist_ok=True)
        try:
            jax.profiler.start_trace(d)
            self._active = True
            log.info("profiler capture started -> %s", d)
        except Exception as e:  # profiler busy/unsupported backend
            log.warning("profiler start failed: %s", e)

    def maybe_stop(self) -> None:
        if not self._active:
            return
        import jax
        try:
            jax.profiler.stop_trace()
        finally:
            self._active = False
