"""Engine bootstrap: environment validation + lifecycle diagnostics.

Reference analog: the plugin's driver/executor startup path
(Plugin.scala — RapidsDriverPlugin.init:418, RapidsExecutorPlugin
init/arch checks:488-568, shutdown hooks:479/649, version banner and
mismatch errors:50-120). Standalone engine shape: no Spark plugin
registry to hook, so the checks run at session start (opt-in via
``check_environment`` / ``spark.rapids.tpu.startupCheck.enabled``) and
shutdown behavior lives on ``TpuSession.close`` (leak audit) plus the
process-exit cache flush jax owns.

Every check returns a record instead of printing, so callers (tests,
the driver, a user diagnosing a deploy) can assert on them; FATAL
findings raise ``EnvironmentProblem`` only when ``strict=True`` — the
reference similarly distinguishes hard version mismatches from
warnings.
"""
from __future__ import annotations

from typing import Dict, List

from .config import TpuConf, register

__all__ = ["check_environment", "EnvironmentProblem", "engine_banner",
           "STARTUP_CHECK"]

STARTUP_CHECK = register(
    "spark.rapids.tpu.startupCheck.enabled", False,
    "Run the environment validation (bootstrap.check_environment) when "
    "a session is created, logging findings: backend platform and "
    "device count, x64 mode, compile-cache writability, memory-pool "
    "conf sanity, suspicious conf combinations (ref Plugin.scala "
    "executor startup checks:488-568).")


class EnvironmentProblem(RuntimeError):
    """A FATAL environment finding under strict checking (the
    CudfVersionMismatchException analog, Plugin.scala:50)."""


def engine_banner() -> str:
    import jax

    from .version import __version__
    try:
        devs = jax.devices()
        plat = devs[0].platform
        nd = len(devs)
    except RuntimeError:
        plat, nd = "unavailable", 0
    return (f"spark-rapids-tpu {__version__} on jax {jax.__version__} "
            f"[{plat} x{nd}]")


def check_environment(conf: TpuConf = None, strict: bool = False) -> List[Dict]:
    """Validate the runtime the way the reference validates executors at
    startup. Returns [{check, level(ok|warn|fatal), detail}]; raises
    EnvironmentProblem on fatal findings when ``strict``."""
    import os

    import jax

    conf = conf or TpuConf()
    out: List[Dict] = []

    def rec(check: str, level: str, detail: str):
        out.append({"check": check, "level": level, "detail": detail})

    # --- backend / devices (GpuDeviceManager analog) -------------------
    try:
        devs = jax.devices()
        rec("backend", "ok",
            f"{devs[0].platform} x{len(devs)} ({type(devs[0]).__name__})")
        if devs[0].platform == "cpu":
            rec("accelerator", "warn",
                "no accelerator backend: the engine runs, but device "
                "placement will never win against the host baseline")
    except RuntimeError as e:
        rec("backend", "fatal", f"no jax backend initializes: {e}")

    # --- numerics mode --------------------------------------------------
    if jax.config.jax_enable_x64:
        rec("x64", "ok", "int64/float64 enabled (Spark parity mode)")
    else:
        rec("x64", "fatal",
            "jax_enable_x64 is OFF: bigint/double columns would "
            "silently truncate — import spark_rapids_tpu before "
            "flipping jax config")

    # --- compile cache (the fatbin-cache analog) -----------------------
    cache = jax.config.jax_compilation_cache_dir
    if not cache:
        rec("compile_cache", "warn",
            "persistent compile cache disabled: first-ever kernel "
            "compiles repeat every process (minutes for sort-bearing "
            "kernels on a tunneled backend)")
    else:
        try:
            os.makedirs(cache, exist_ok=True)
            probe = os.path.join(cache, ".srtpu_probe")
            with open(probe, "w") as f:
                f.write("ok")
            os.remove(probe)
            rec("compile_cache", "ok", cache)
        except OSError as e:
            rec("compile_cache", "warn",
                f"cache dir {cache} not writable ({e}): compiles "
                "will not persist")

    # --- memory pool sanity (GpuDeviceManager pool checks) -------------
    from .config import ALLOC_FRACTION, HBM_LIMIT_BYTES
    frac = float(conf.get(ALLOC_FRACTION))
    limit = int(conf.get(HBM_LIMIT_BYTES))
    if not 0.0 < frac <= 1.0:
        rec("memory_pool", "fatal",
            f"memory.hbm.allocFraction {frac} outside (0, 1]")
    else:
        rec("memory_pool", "ok",
            f"allocFraction {frac}" + (
                f", explicit limit {limit >> 20} MiB" if limit
                else ", limit derived from device"))

    # --- conf combination lint ----------------------------------------
    from .io.device_decode import DEVICE_DECODE_ENABLED
    from .config import PARQUET_READER_TYPE
    rt = str(conf.get(PARQUET_READER_TYPE)).upper()
    if bool(conf.get(DEVICE_DECODE_ENABLED)) \
            and rt not in ("PERFILE", "AUTO"):
        # AUTO resolves to PERFILE for single-file scans, so only the
        # explicitly-incompatible modes warrant the warning
        rec("conf", "warn",
            f"io.parquet.deviceDecode.enabled is on but reader.type="
            f"{rt} never takes the per-file path the decode requires")

    if strict and any(r["level"] == "fatal" for r in out):
        bad = [r for r in out if r["level"] == "fatal"]
        raise EnvironmentProblem("; ".join(
            f"{r['check']}: {r['detail']}" for r in bad))
    return out
