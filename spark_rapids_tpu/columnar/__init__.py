from .bucketing import DEFAULT_BUCKETS, bucket_for, padded_len
from .column import Column, DeviceColumn, DictColumn, HostColumn
from .batch import ColumnarBatch, concat_batches

__all__ = ["DEFAULT_BUCKETS", "bucket_for", "padded_len", "Column",
           "DeviceColumn", "DictColumn", "HostColumn", "ColumnarBatch", "concat_batches"]
