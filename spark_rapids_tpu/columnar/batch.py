"""ColumnarBatch: an ordered set of columns with one logical row count.

Reference analog: Spark's ColumnarBatch of GpuColumnVectors
(GpuColumnVector.java:40 from(Table)/from(batch)); here the device side is a
pytree of DeviceColumns so an entire batch can be an argument/result of a
jitted operator kernel. Mixed batches (device + host columns) are first-class:
the planner splits expression evaluation between the XLA kernel and vectorized
Arrow host kernels.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

from ..types import STRING, DataType, Schema, StructField, from_arrow
from .bucketing import DEFAULT_BUCKETS, bucket_for
from .column import DeviceColumn, DictColumn, HostColumn

ColumnLike = Union[DeviceColumn, HostColumn]


class SpeculativeOverflow(Exception):
    """A speculatively-sized output (join bucket guess) was too small; the
    sink catches this, disables speculation on the ExecContext, and
    re-executes the plan with exact (synchronous) sizing."""

    def __init__(self, needed: int, capacity: int):
        super().__init__(f"speculative capacity {capacity} < {needed} rows")
        self.needed = needed
        self.capacity = capacity

#: dictionary-encode string columns into device codes when the cardinality
#: is below this fraction of rows (and the absolute cap). Flip to 0 to
#: force host strings (tests use this to cover both paths). Above the cap
#: the BYTE-RECTANGLE layout takes over (strrect.py): per-distinct-value
#: dictionary work loses to per-row vectorized rectangles once the
#: dictionary stops being small relative to the rows.
DICT_ENCODE_MAX_FRACTION = 0.5
DICT_ENCODE_MAX_CARD = 1 << 16


def _decimal_unscaled_int64(arr, valid: np.ndarray) -> np.ndarray:
    """decimal128 arrow array -> unscaled int64 values (invalid rows 0).

    The decimal128 buffer stores the unscaled int128 little-endian; a
    value fits the device's int64 lane iff the high word is the sign
    extension of the low word. Out-of-range values raise — silently
    truncating money would be the worst failure mode (ref DecimalUtils'
    checked casts)."""
    buf = arr.buffers()[1]
    words = np.frombuffer(buf, dtype=np.int64)
    off = arr.offset
    lo = words[2 * off::2][:len(arr)]
    hi = words[2 * off + 1::2][:len(arr)]
    ok = (hi == np.where(lo < 0, -1, 0))
    if not ok[valid].all():
        raise ValueError(
            "decimal value exceeds the device's 64-bit unscaled range "
            "(|unscaled| >= 2^63); this magnitude needs host execution")
    return np.where(valid, lo, 0)


def _is_device_list(dt) -> bool:
    from .nested import device_list_ok
    return device_list_ok(dt)


def _try_dict_encode(col, n: int, p: int):
    """pa string array -> (codes, valid, sorted dictionary) or None."""
    import pyarrow as pa
    if n == 0 or DICT_ENCODE_MAX_FRACTION <= 0:
        return None
    de = col.dictionary_encode()
    card = len(de.dictionary)
    if card > min(n * DICT_ENCODE_MAX_FRACTION + 1, DICT_ENCODE_MAX_CARD):
        return None
    dvals = de.dictionary.to_numpy(zero_copy_only=False)
    order = np.argsort(dvals)          # codepoint == UTF-8 byte order
    rank = np.empty(card, np.int32)
    rank[order] = np.arange(card, dtype=np.int32)
    valid = ~np.asarray(de.indices.is_null())
    local = np.asarray(de.indices.fill_null(0).to_numpy(
        zero_copy_only=False), dtype=np.int64)
    codes = rank[local] if card else np.zeros(n, np.int32)
    d = np.zeros(p, np.int32)
    v = np.zeros(p, bool)
    d[:n] = codes
    v[:n] = valid
    return d, v, dvals[order]


class ColumnarBatch:
    __slots__ = ("columns", "_num_rows", "schema", "meta", "__weakref__")

    def __init__(self, columns: Sequence[ColumnLike], num_rows,
                 schema: Schema, meta: Optional[dict] = None):
        assert len(columns) == len(schema), (len(columns), len(schema))
        lazy = not isinstance(num_rows, (int, np.integer))
        for c in columns:
            if not lazy and isinstance(c, DeviceColumn) \
                    and c.padded_len < num_rows:
                raise ValueError("device column shorter than num_rows")
        self.columns = list(columns)
        # num_rows may be a device scalar (e.g. a filter's surviving-row
        # count): forcing it costs a full tunnel round trip (~40-100 ms on
        # this backend), so it stays on device until host code actually
        # needs the int — kernels consume num_rows_raw without syncing
        self._num_rows = num_rows if lazy else int(num_rows)
        self.schema = schema
        #: task-context metadata consumed by non-deterministic expressions
        #: (ref TaskContext.partitionId / InputFileBlockHolder):
        #: {"partition_id": int, "input_file": str}
        self.meta = meta or {}

    @property
    def num_rows(self) -> int:
        nr = self._num_rows
        if not isinstance(nr, int):
            nr = int(nr)            # device sync
            cap = next((c.padded_len for c in self.columns
                        if isinstance(c, DeviceColumn)), None)
            if cap is not None and nr > cap:
                # a speculatively-sized producer (join) guessed too small:
                # rows beyond the padded capacity were truncated
                raise SpeculativeOverflow(nr, cap)
            self._resolve_count(nr)
        return nr

    def _resolve_count(self, nr: int) -> None:
        """Install a now-known row count; feeds the cost model's measured
        row statistics when the producer tagged THIS batch (deferred —
        lazy device counts resolve at the sink fetch, never via an extra
        sync). The weakref identity check keeps derived batches that
        copied or share this meta dict from mis-attributing their counts
        to the producer's accumulator."""
        self._num_rows = nr
        tag = self.meta.get("rows_accum")
        if tag is not None:
            accum, ref = tag
            if ref() is self:
                accum.add(nr)
                self.meta.pop("rows_accum", None)
        tag = self.meta.get("count_cb")
        if tag is not None:
            # producer-installed callback (e.g. the aggregate exec's group
            # count statistic): fires when the count resolves, so stats
            # stay fresh without the producer paying its own device sync.
            # Same weakref identity guard as rows_accum: derived batches
            # sharing/copying this meta dict must not fire it.
            cb, ref = tag
            if ref() is self:
                self.meta.pop("count_cb", None)
                cb(nr)

    @property
    def num_rows_raw(self):
        """num_rows without forcing a device sync: a host int or a device
        scalar — both valid inputs to a traced kernel argument."""
        return self._num_rows

    # -- structure ---------------------------------------------------------
    def __len__(self):
        return self.num_rows

    def num_columns(self) -> int:
        return len(self.columns)

    def column(self, i: int) -> ColumnLike:
        return self.columns[i]

    def column_by_name(self, name: str) -> ColumnLike:
        return self.columns[self.schema.index_of(name)]

    @property
    def padded_len(self) -> int:
        for c in self.columns:
            if isinstance(c, DeviceColumn):
                return c.padded_len
        return self.num_rows

    @property
    def all_device(self) -> bool:
        return all(isinstance(c, DeviceColumn) for c in self.columns)

    def device_size_bytes(self) -> int:
        return sum(c.nbytes() for c in self.columns if isinstance(c, DeviceColumn))

    def host_size_bytes(self) -> int:
        return sum(c.nbytes() for c in self.columns if isinstance(c, HostColumn))

    def size_bytes(self) -> int:
        return sum(c.nbytes() for c in self.columns)

    def with_columns(self, columns: Sequence[ColumnLike], schema: Schema,
                     num_rows: Optional[int] = None) -> "ColumnarBatch":
        return ColumnarBatch(columns,
                             self._num_rows if num_rows is None else num_rows,
                             schema, meta=self.meta)

    # -- conversions -------------------------------------------------------
    @staticmethod
    def from_arrow(table, buckets: Sequence[int] = DEFAULT_BUCKETS,
                   pad: bool = True, encode_lists: bool = True,
                   rect_cap: Optional[int] = None) -> "ColumnarBatch":
        """Arrow table -> batch; device-backed types are H2D'd padded to the
        row bucket (ref HostColumnarToGpu / GpuRowToColumnarExec device copy)."""
        import jax
        import pyarrow as pa
        import pyarrow.compute as pc
        n = table.num_rows
        p = bucket_for(n, buckets) if pad else n
        cols: List[ColumnLike] = []
        fields: List[StructField] = []
        staged = []    # (col index, dtype) for one batched H2D at the end
        host_pairs = []
        list_staged = []   # (col index, dtype, rectangle arrays, mirror)
        rect_staged = []   # (col index, (rect, lens, valid, ascii), mirror)
        for name, col in zip(table.column_names, table.columns):
            if isinstance(col, pa.ChunkedArray):
                col = col.combine_chunks() if col.num_chunks != 1 else col.chunk(0)
            dt = from_arrow(col.type)
            fields.append(StructField(name, dt, True))
            if dt.device_backed:
                arr = col
                if pa.types.is_date32(arr.type):
                    arr = arr.cast(pa.int32())
                elif pa.types.is_timestamp(arr.type):
                    arr = arr.cast(pa.int64())
                mask = np.asarray(col.is_null())
                if pa.types.is_decimal(arr.type):
                    # unscaled int64 straight from the decimal128
                    # buffer; values beyond int64 fail LOUDLY (the
                    # device lane is 64-bit — types.DecimalType).
                    # Narrower decimal32/64 arrays widen first.
                    if arr.type.bit_width != 128:
                        arr = arr.cast(pa.decimal128(38, arr.type.scale))
                    vals = _decimal_unscaled_int64(arr, ~mask)
                else:
                    fill = False if pa.types.is_boolean(arr.type) else 0
                    vals = arr.fill_null(fill).to_numpy(
                        zero_copy_only=False)
                d, v = DeviceColumn.host_prepare(vals, dt, mask=~mask,
                                                 padded_len=p)
                # canonical arrow type NOW so mirror-served batches have
                # the same schema a device round trip would produce
                from ..types import to_arrow as _toa
                mirror = col if col.type == _toa(dt) else col.cast(_toa(dt))
                staged.append((len(cols), dt, None, mirror))
                host_pairs.extend([d, v])
                cols.append(None)
            elif pad and encode_lists and _is_device_list(dt):
                # list-of-primitive: dense rectangular device layout
                # (columnar/nested.py); width-capped columns stay host
                from .nested import encode_list_column
                encl = encode_list_column(col, dt, p)
                if encl is not None:
                    list_staged.append((len(cols), dt, encl, col))
                    cols.append(None)
                else:
                    cols.append(HostColumn(col, dt))
            else:
                # only the padded (device-bound) path dict-encodes; host
                # execs using pad=False want plain host strings
                enc = (_try_dict_encode(col, n, p)
                       if dt == STRING and pad else None)
                if enc is not None:
                    d, v, dictionary = enc
                    from ..types import to_arrow as _toa
                    mirror = (col if col.type == _toa(dt)
                              else col.cast(_toa(dt)))
                    staged.append((len(cols), dt, dictionary, mirror))
                    host_pairs.extend([d, v])
                    cols.append(None)
                    continue
                if dt == STRING and pad:
                    # high cardinality: the byte-rectangle device layout
                    # (VERDICT r3 #4) — transforms/grouping stay in HBM.
                    # Callers with a session conf pass rect_cap (the scan
                    # exec does); the registered default covers the rest.
                    from .strrect import RECT_MAX_BYTES, encode_string_rect
                    cap = rect_cap
                    if cap is None:
                        from ..config import TpuConf as _TC
                        cap = int(_TC().get(RECT_MAX_BYTES))
                    renc = encode_string_rect(col, n, p, cap)
                    if renc is not None:
                        rectd, lens, rv, asc = renc
                        from ..types import to_arrow as _toa
                        mirror = (col if col.type == _toa(dt)
                                  else col.cast(_toa(dt)))
                        rect_staged.append((len(cols),
                                            (rectd, lens, rv, asc),
                                            mirror))
                        cols.append(None)
                        continue
                cols.append(HostColumn(col, dt))
        if staged:
            # ONE device_put for the whole table: each separate transfer
            # pays a full round trip on a tunneled TPU backend. Above the
            # size threshold, columns are narrowed/bitpacked host-side and
            # decoded by one fused kernel after the transfer — H2D bytes
            # drop 4-16x on TPC-shaped data (columnar/transfer.py).
            from .transfer import (decode_with_len, encode_columns,
                                   traced_device_put, worthwhile)
            pairs = [(host_pairs[2 * k], host_pairs[2 * k + 1])
                     for k in range(len(staged))]
            flat, specs, enc_params, ratio, raw_bytes = \
                encode_columns(pairs)
            if worthwhile(ratio, raw_bytes):
                put = traced_device_put(flat, label="h2d.encoded")
                decoded = decode_with_len(put, specs, enc_params, p)
                for k, (i, dt, dictionary, mirror) in enumerate(staged):
                    d, v = decoded[k]
                    if dictionary is None:
                        cols[i] = DeviceColumn(d, v, dt,
                                               host_mirror=mirror)
                    else:
                        cols[i] = DictColumn(d, v, dt, dictionary,
                                             host_mirror=mirror)
            else:
                put = traced_device_put(host_pairs, label="h2d.raw")
                for k, (i, dt, dictionary, mirror) in enumerate(staged):
                    if dictionary is None:
                        cols[i] = DeviceColumn(put[2 * k],
                                               put[2 * k + 1], dt,
                                               host_mirror=mirror)
                    else:
                        cols[i] = DictColumn(put[2 * k], put[2 * k + 1],
                                             dt, dictionary,
                                             host_mirror=mirror)
        if list_staged:
            from .nested import ListColumn
            flat = []
            for _i, _dt, (vals, ev, lens, rv, _w), _m in list_staged:
                flat.extend((vals, ev, lens, rv))
            from .transfer import traced_device_put
            # one transfer for all rectangles
            put = traced_device_put(flat, label="h2d.list")
            for k, (i, dt, enc, mirror) in enumerate(list_staged):
                cols[i] = ListColumn(put[4 * k], put[4 * k + 3], dt,
                                     put[4 * k + 1], put[4 * k + 2],
                                     host_mirror=mirror)
        if rect_staged:
            from .strrect import ByteRectColumn
            flat = []
            for _i, (rectd, lens, rv, _a), _m in rect_staged:
                flat.extend((rectd, lens, rv))
            from .transfer import traced_device_put
            # one transfer for all rectangles
            put = traced_device_put(flat, label="h2d.strrect")
            for k, (i, enc, mirror) in enumerate(rect_staged):
                cols[i] = ByteRectColumn(put[3 * k], put[3 * k + 2],
                                         put[3 * k + 1],
                                         ascii_only=enc[3],
                                         host_mirror=mirror)
        return ColumnarBatch(cols, n, Schema(fields))

    @staticmethod
    def from_arrow_host(table) -> "ColumnarBatch":
        """Arrow table -> batch of HostColumns only (no device transfer):
        for terminal host stages (final sort feeding collect) whose output
        would otherwise bounce host->device->host through the tunnel."""
        import pyarrow as pa
        cols: List[ColumnLike] = []
        fields: List[StructField] = []
        for name, col in zip(table.column_names, table.columns):
            if isinstance(col, pa.ChunkedArray):
                col = col.combine_chunks() if col.num_chunks != 1 \
                    else col.chunk(0)
            dt = from_arrow(col.type)
            fields.append(StructField(name, dt, True))
            cols.append(HostColumn(col, dt))
        return ColumnarBatch(cols, table.num_rows, Schema(fields))

    @staticmethod
    def from_pandas(df, buckets: Sequence[int] = DEFAULT_BUCKETS) -> "ColumnarBatch":
        import pyarrow as pa
        # column-by-column: pa.Table.from_pandas rejects duplicate column
        # names, which are legal in intermediate frames (e.g. t.k joined
        # with r.k — Spark allows ambiguous names until they're referenced).
        # Copy numeric buffers: Array.from_pandas zero-copies null-free
        # numpy columns, and ingested arrays become host mirrors that must
        # be snapshots (the user may mutate the DataFrame afterwards)
        arrays = []
        for i in range(df.shape[1]):
            series = df.iloc[:, i]
            vals = series.to_numpy()
            if vals.dtype != object:
                vals = np.array(vals, copy=True)
                arrays.append(pa.Array.from_pandas(
                    __import__("pandas").Series(vals, index=series.index)))
            else:
                arrays.append(pa.Array.from_pandas(series))
        table = pa.Table.from_arrays(arrays,
                                     names=[str(c) for c in df.columns])
        return ColumnarBatch.from_arrow(table, buckets)

    def to_arrow(self):
        import pyarrow as pa
        from .packing import fetch_packed
        # ONE packed transfer for every device column (leaf-by-leaf waits
        # pay per-transfer latency on a tunneled TPU)
        from .nested import ListColumn
        from .strrect import ByteRectColumn
        dev = [(i, c) for i, c in enumerate(self.columns)
               if isinstance(c, DeviceColumn)
               and not isinstance(c, (ListColumn, ByteRectColumn))
               and getattr(c, "host_mirror", None) is None]
        mirror_pos = {i for i, c in enumerate(self.columns)
                      if isinstance(c, DeviceColumn)
                      and getattr(c, "host_mirror", None) is not None}
        fetched = {}
        if dev:
            lazy = not isinstance(self._num_rows, int)
            # fetch only a prefix covering num_rows (64k granularity keeps
            # the pack-kernel variant count small): at ~10 MB/s tunnel
            # bandwidth the padded tail is pure waste
            cut = None
            if not lazy:
                cut = min(self.padded_len,
                          ((self._num_rows + 65535) // 65536) * 65536)
                if cut == 0:
                    cut = 1
            flat = []
            for _, c in dev:
                d, v = c.data, c.validity
                if cut is not None and cut < c.padded_len:
                    d, v = d[:cut], v[:cut]
                flat.extend((d, v))
            if lazy:
                flat.append(self._num_rows)   # ride the same transfer
            got = fetch_packed(flat)
            if lazy:
                nr = int(got[-1])
                cap = dev[0][1].padded_len
                if nr > cap:
                    raise SpeculativeOverflow(nr, cap)
                self._resolve_count(nr)
            n = self.num_rows
            for k, (i, c) in enumerate(dev):
                fetched[i] = (got[2 * k][:n], got[2 * k + 1][:n])
        arrays = []
        for i, c in enumerate(self.columns):
            if i in fetched:
                arrays.append(c.arrow_from_host(*fetched[i]))
            elif i in mirror_pos:
                arrays.append(c.host_mirror.slice(0, self.num_rows))
            else:
                arrays.append(c.to_arrow(self.num_rows))
        return pa.Table.from_arrays(arrays, names=self.schema.names())

    def to_pandas(self):
        return self.to_arrow().to_pandas()

    def ensure_device(self) -> "ColumnarBatch":
        """Re-materialize device-backed columns that are host-resident
        (an upstream exec produced a host batch — e.g. the aggregate's
        single-fetch path or a host sort) back into HBM. No-op when
        every device-backed column is already on device."""
        needs = any(isinstance(c, HostColumn) and f.dtype.device_backed
                    for c, f in zip(self.columns, self.schema.fields))
        if not needs:
            return self
        # encode_lists=False: a host-resident list column stays host here —
        # the execs that call ensure_device either route it per batch
        # (project/filter) or demote it anyway (joins), so re-encoding the
        # rectangle just to fetch it back would waste an H2D+D2H
        out = ColumnarBatch.from_arrow(self.to_arrow(), encode_lists=False)
        out.meta = self.meta
        return out

    def with_lists_on_host(self) -> "ColumnarBatch":
        """Demote 2-D device layouts (list rectangles AND string byte
        rectangles) to HostColumns.

        Row-rearranging execs that own their kernels (joins, sorts, aggs,
        windows, partitioning) move 1D (data, validity) pairs; rectangle
        payloads crossing them materialize host-side first — project/
        filter pipelines keep rectangles on device via the lane
        decomposition (exprs/compiler._lane_pairs). Honest fallback,
        mirrored in supported_ops docs."""
        from .nested import ListColumn
        from .strrect import ByteRectColumn
        rect_types = (ListColumn, ByteRectColumn)
        if not any(isinstance(c, rect_types) for c in self.columns):
            return self
        n = self.num_rows

        def demote(c):
            if not isinstance(c, rect_types):
                return c
            if c.host_mirror is not None:   # fresh ingest: zero-cost slice
                return HostColumn(c.host_mirror.slice(0, n), c.dtype)
            return HostColumn(c.to_arrow(n), c.dtype)

        return ColumnarBatch([demote(c) for c in self.columns], n,
                             self.schema, meta=self.meta)

    # -- ops used by the runtime ------------------------------------------
    def slice(self, offset: int, length: int) -> "ColumnarBatch":
        """Host-side logical slice (used by split-and-retry); produces a new
        padded batch."""
        import pyarrow as pa
        t = self.to_arrow().slice(offset, length)
        out = ColumnarBatch.from_arrow(pa.table(t))
        out.meta = self.meta
        return out

    def __repr__(self):
        kinds = "".join("D" if isinstance(c, DeviceColumn) else "H"
                        for c in self.columns)
        return (f"ColumnarBatch(rows={self.num_rows}, padded={self.padded_len}, "
                f"cols=[{kinds}], {self.schema})")


def _device_concat_compact(counts, cols):
    """Traced device concat of prefix-packed batches: per batch a liveness
    mask from its (traced) count, one stable argsort moves live rows to the
    front, every column gathers through the same permutation. Counts ride
    as a traced vector so varying row counts never recompile."""
    import jax.numpy as jnp
    live = jnp.concatenate([
        jnp.arange(d.shape[0], dtype=jnp.int32) < counts[i]
        for i, (d, _) in enumerate(cols[0])])
    perm = jnp.argsort(jnp.logical_not(live), stable=True)
    out = []
    for per_batch in cols:
        d = jnp.concatenate([d for d, _ in per_batch])[perm]
        v = jnp.concatenate([v for _, v in per_batch])[perm]
        out.append((d, v))
    return out


_DEVICE_CONCAT_JIT = None


def _clear_device_concat() -> None:
    global _DEVICE_CONCAT_JIT
    _DEVICE_CONCAT_JIT = None


def concat_batches_device(batches: Sequence[ColumnarBatch],
                          buckets: Sequence[int] = DEFAULT_BUCKETS):
    """Device-resident concat: no D2H. Requires every column of every batch
    to be a plain DeviceColumn and every row count to be a host int (the
    aggregate merge path qualifies). Returns None when not applicable —
    callers fall back to the host-staged concat_batches."""
    import jax
    import jax.numpy as jnp
    from .strrect import ByteRectColumn
    counts = []
    for b in batches:
        if not isinstance(b.num_rows_raw, int):
            return None
        counts.append(b.num_rows_raw)
        for c in b.columns:
            if type(c) is not DeviceColumn \
                    and type(c) is not ByteRectColumn:
                return None
    schema = batches[0].schema
    for b in batches[1:]:
        if [f.dtype for f in b.schema.fields] != \
                [f.dtype for f in schema.fields]:
            return None
    # decompose into 1-D lanes: byte-rectangle strings ride as packed
    # word + length lanes (width-normalized across batches) so both
    # concat paths below stay 1-D-only
    lane_cols = []     # per LANE: [per-batch (d, v)]
    rebuilds = []      # (n_lanes, rebuild fn from lane (d, v) list)
    for ci, f in enumerate(schema.fields):
        per_batch = [b.columns[ci] for b in batches]
        if any(isinstance(c, ByteRectColumn) for c in per_batch):
            if not all(type(c) is ByteRectColumn for c in per_batch):
                return None      # mixed rect/dict (spill round trip):
                                 # host-staged concat handles it
            max_w = max(c.width for c in per_batch)
            normed = []
            for c in per_batch:
                if c.width < max_w:
                    c = ByteRectColumn(
                        jnp.pad(c.data, ((0, 0), (0, max_w - c.width))),
                        c.validity, c.lengths, ascii_only=c.ascii_only)
                normed.append(c)
            lane_lists = [c.kernel_lanes() for c in normed]
            n_lanes = len(lane_lists[0])
            for li in range(n_lanes):
                lane_cols.append([ll[li] for ll in lane_lists])
            template = normed[0]
            asc = all(c.ascii_only for c in per_batch)

            def rebuild(outs, template=template, asc=asc):
                col = template.from_lanes(outs)
                col.ascii_only = asc
                return col
            rebuilds.append((n_lanes, rebuild))
        else:
            lane_cols.append([(c.data, c.validity) for c in per_batch])

            def rebuild(outs, dt=f.dtype):
                return DeviceColumn(outs[0][0], outs[0][1], dt)
            rebuilds.append((1, rebuild))
    total = sum(counts)
    if all(c == b.padded_len for c, b in
           zip(counts[:-1], batches[:-1])):
        # every batch but the last is full: plain concatenation is already
        # prefix-packed — no compaction permutation needed (the common
        # scan-fed case: N full bucket batches + one partial tail)
        outs = [(jnp.concatenate([d for d, _ in per]),
                 jnp.concatenate([v for _, v in per]))
                for per in lane_cols]
    else:
        global _DEVICE_CONCAT_JIT
        # bind to a local: a concurrent exec_cache.clear() may null the
        # memo between the check and the call
        concat_fn = _DEVICE_CONCAT_JIT
        if concat_fn is None:
            # resolved through the executable cache (not an ad-hoc
            # jit): one process-wide callable, compiles visible to the
            # srtpu_compile_* metrics; the front memo registers a
            # clear hook so exec_cache.clear() releases it too
            from ..plan import exec_cache
            exec_cache.register_clear_hook(_clear_device_concat)
            concat_fn = _DEVICE_CONCAT_JIT = exec_cache.get_or_build_jit(
                "columnar.device_concat", _device_concat_compact)
        outs = concat_fn(
            jnp.asarray(np.asarray(counts, np.int32)), lane_cols)
    target = bucket_for(total, buckets)
    sized = []
    for d, v in outs:
        if target < d.shape[0]:
            d, v = d[:target], v[:target]
        elif target > d.shape[0]:
            # pad UP to the ladder bucket too: padded_len is a static jit
            # arg downstream, so an off-ladder length (sum of input
            # paddings) would compile a fresh kernel variant per distinct
            # sum — exactly what the bucket ladder exists to prevent
            pad = target - d.shape[0]
            d = jnp.pad(d, (0, pad))
            v = jnp.pad(v, (0, pad))
        sized.append((d, v))
    out_cols = []
    pos = 0
    for n_lanes, rebuild in rebuilds:
        out_cols.append(rebuild(sized[pos:pos + n_lanes]))
        pos += n_lanes
    return ColumnarBatch(out_cols, total, schema)


def concat_batches(batches: Sequence[ColumnarBatch],
                   buckets: Sequence[int] = DEFAULT_BUCKETS) -> ColumnarBatch:
    """Concatenate batches (ref GpuCoalesceBatches concatenation,
    GpuCoalesceBatches.scala:112-176). Device-resident batches concatenate
    on device (one dispatch, no D2H round trips); mixed device/host falls
    back to the host-staged Arrow path."""
    import pyarrow as pa
    assert batches, "empty concat"
    if len(batches) == 1:
        return batches[0]
    dev = concat_batches_device(batches, buckets)
    if dev is not None:
        return dev
    tables = [b.to_arrow() for b in batches]
    return ColumnarBatch.from_arrow(pa.concat_tables(tables), buckets)
