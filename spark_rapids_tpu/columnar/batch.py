"""ColumnarBatch: an ordered set of columns with one logical row count.

Reference analog: Spark's ColumnarBatch of GpuColumnVectors
(GpuColumnVector.java:40 from(Table)/from(batch)); here the device side is a
pytree of DeviceColumns so an entire batch can be an argument/result of a
jitted operator kernel. Mixed batches (device + host columns) are first-class:
the planner splits expression evaluation between the XLA kernel and vectorized
Arrow host kernels.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

from ..types import DataType, Schema, StructField, from_arrow
from .bucketing import DEFAULT_BUCKETS, bucket_for
from .column import DeviceColumn, HostColumn

ColumnLike = Union[DeviceColumn, HostColumn]


class ColumnarBatch:
    __slots__ = ("columns", "num_rows", "schema", "meta")

    def __init__(self, columns: Sequence[ColumnLike], num_rows: int,
                 schema: Schema, meta: Optional[dict] = None):
        assert len(columns) == len(schema), (len(columns), len(schema))
        for c in columns:
            if isinstance(c, DeviceColumn) and c.padded_len < num_rows:
                raise ValueError("device column shorter than num_rows")
        self.columns = list(columns)
        self.num_rows = int(num_rows)
        self.schema = schema
        #: task-context metadata consumed by non-deterministic expressions
        #: (ref TaskContext.partitionId / InputFileBlockHolder):
        #: {"partition_id": int, "input_file": str}
        self.meta = meta or {}

    # -- structure ---------------------------------------------------------
    def __len__(self):
        return self.num_rows

    def num_columns(self) -> int:
        return len(self.columns)

    def column(self, i: int) -> ColumnLike:
        return self.columns[i]

    def column_by_name(self, name: str) -> ColumnLike:
        return self.columns[self.schema.index_of(name)]

    @property
    def padded_len(self) -> int:
        for c in self.columns:
            if isinstance(c, DeviceColumn):
                return c.padded_len
        return self.num_rows

    @property
    def all_device(self) -> bool:
        return all(isinstance(c, DeviceColumn) for c in self.columns)

    def device_size_bytes(self) -> int:
        return sum(c.nbytes() for c in self.columns if isinstance(c, DeviceColumn))

    def host_size_bytes(self) -> int:
        return sum(c.nbytes() for c in self.columns if isinstance(c, HostColumn))

    def size_bytes(self) -> int:
        return sum(c.nbytes() for c in self.columns)

    def with_columns(self, columns: Sequence[ColumnLike], schema: Schema,
                     num_rows: Optional[int] = None) -> "ColumnarBatch":
        return ColumnarBatch(columns, self.num_rows if num_rows is None else num_rows,
                             schema, meta=self.meta)

    # -- conversions -------------------------------------------------------
    @staticmethod
    def from_arrow(table, buckets: Sequence[int] = DEFAULT_BUCKETS,
                   pad: bool = True) -> "ColumnarBatch":
        """Arrow table -> batch; device-backed types are H2D'd padded to the
        row bucket (ref HostColumnarToGpu / GpuRowToColumnarExec device copy)."""
        import pyarrow as pa
        import pyarrow.compute as pc
        n = table.num_rows
        p = bucket_for(n, buckets) if pad else n
        cols: List[ColumnLike] = []
        fields: List[StructField] = []
        for name, col in zip(table.column_names, table.columns):
            if isinstance(col, pa.ChunkedArray):
                col = col.combine_chunks() if col.num_chunks != 1 else col.chunk(0)
            dt = from_arrow(col.type)
            fields.append(StructField(name, dt, True))
            if dt.device_backed:
                arr = col
                if pa.types.is_date32(arr.type):
                    arr = arr.cast(pa.int32())
                elif pa.types.is_timestamp(arr.type):
                    arr = arr.cast(pa.int64())
                elif pa.types.is_decimal(arr.type):
                    # unscaled int64 view for precision<=18
                    arr = pc.multiply_checked(
                        arr.cast(pa.decimal128(38, arr.type.scale)),
                        10 ** arr.type.scale).cast(pa.int64())
                mask = np.asarray(col.is_null())
                fill = False if pa.types.is_boolean(arr.type) else 0
                vals = arr.fill_null(fill).to_numpy(zero_copy_only=False)
                cols.append(DeviceColumn.from_numpy(
                    vals, dt, mask=~mask, padded_len=p))
            else:
                cols.append(HostColumn(col, dt))
        return ColumnarBatch(cols, n, Schema(fields))

    @staticmethod
    def from_pandas(df, buckets: Sequence[int] = DEFAULT_BUCKETS) -> "ColumnarBatch":
        import pyarrow as pa
        return ColumnarBatch.from_arrow(pa.Table.from_pandas(df, preserve_index=False),
                                        buckets)

    def to_arrow(self):
        import pyarrow as pa
        arrays = [c.to_arrow(self.num_rows) for c in self.columns]
        names = self.schema.names()
        return pa.Table.from_arrays(arrays, names=names)

    def to_pandas(self):
        return self.to_arrow().to_pandas()

    # -- ops used by the runtime ------------------------------------------
    def slice(self, offset: int, length: int) -> "ColumnarBatch":
        """Host-side logical slice (used by split-and-retry); produces a new
        padded batch."""
        import pyarrow as pa
        t = self.to_arrow().slice(offset, length)
        out = ColumnarBatch.from_arrow(pa.table(t))
        out.meta = self.meta
        return out

    def __repr__(self):
        kinds = "".join("D" if isinstance(c, DeviceColumn) else "H"
                        for c in self.columns)
        return (f"ColumnarBatch(rows={self.num_rows}, padded={self.padded_len}, "
                f"cols=[{kinds}], {self.schema})")


def concat_batches(batches: Sequence[ColumnarBatch],
                   buckets: Sequence[int] = DEFAULT_BUCKETS) -> ColumnarBatch:
    """Concatenate batches (ref GpuCoalesceBatches concatenation,
    GpuCoalesceBatches.scala:112-176). Host-staged for simplicity and
    correctness across mixed device/host columns; the hot device-only path is
    overridden by exec/coalesce.py with an on-device concat kernel."""
    import pyarrow as pa
    assert batches, "empty concat"
    if len(batches) == 1:
        return batches[0]
    tables = [b.to_arrow() for b in batches]
    return ColumnarBatch.from_arrow(pa.concat_tables(tables), buckets)
