"""Row-count shape bucketing.

TPU-specific core design (no reference analog — cudf kernels are shape-dynamic,
XLA compiles per static shape, SURVEY.md section 7 "Hard parts" #1): every
columnar batch is padded up to the nearest bucket in a geometric ladder so a
compiled operator kernel is reused across all batches that land in the same
bucket. Padding rows carry validity=False so masked kernels ignore them; the
true row count travels as a dynamic scalar.
"""
from __future__ import annotations

from typing import List, Sequence

DEFAULT_BUCKETS: List[int] = [1024, 8192, 65536, 262144, 1048576, 4194304]


def bucket_for(num_rows: int, buckets: Sequence[int] = DEFAULT_BUCKETS) -> int:
    """Smallest bucket >= num_rows; beyond the ladder, round up to the next
    multiple of the largest bucket (keeps compilation count bounded)."""
    if num_rows < 0:
        raise ValueError("negative row count")
    for b in buckets:
        if num_rows <= b:
            return b
    top = buckets[-1]
    return ((num_rows + top - 1) // top) * top


def padded_len(num_rows: int, buckets: Sequence[int] = DEFAULT_BUCKETS) -> int:
    return bucket_for(num_rows, buckets)
