"""Columnar vectors: HBM-resident (jax.Array) and host (Arrow) columns.

TPU-native re-design of the reference's columnar data layer
(GpuColumnVector.java:40 device vector over cudf; RapidsHostColumnVector for
host side). On TPU a column is:

  * ``DeviceColumn`` — a dense ``jax.Array`` ``data`` padded to a shape bucket
    plus a ``validity`` bool mask (False for nulls AND for padding rows).
    Registered as a pytree so whole batches flow through ``jax.jit``.
  * ``HostColumn``  — a pyarrow Array for types XLA cannot hold densely
    (strings, binary, nested). The planner's TypeSig tagging routes
    expressions over these to vectorized host kernels (honest CPU fallback,
    the analog of the reference's per-type fallback tagging).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..types import (DataType, DecimalType, STRING, TIMESTAMP, DATE,
                     from_arrow, to_arrow)

__all__ = ["DeviceColumn", "HostColumn", "Column"]


class DeviceColumn:
    """A typed device vector: ``data`` + ``validity`` jax arrays of equal
    (padded) length. Slots where validity is False hold the dtype's default
    value so arithmetic never sees garbage (NaN-free padding)."""

    __slots__ = ("data", "validity", "dtype", "host_mirror")

    def __init__(self, data, validity, dtype: DataType, host_mirror=None):
        self.data = data
        self.validity = validity
        self.dtype = dtype
        #: the SOURCE arrow array this column was ingested from, when the
        #: device content is a verbatim padded copy of it. Materialization
        #: serves a prefix slice of the mirror instead of a D2H fetch
        #: (tunnel transfers run at ~10-30 MB/s). Any transform that
        #: rearranges rows goes through with_arrays(), which drops it.
        self.host_mirror = host_mirror

    # -- constructors ------------------------------------------------------
    @staticmethod
    def host_prepare(values: np.ndarray, dtype: DataType,
                     mask: Optional[np.ndarray] = None,
                     padded_len: Optional[int] = None):
        """Build the padded host (data, validity) numpy pair for a column —
        split from the device transfer so callers can batch many columns
        into ONE device_put (each blocking transfer pays a full round trip
        on a tunneled TPU)."""
        n = len(values)
        p = padded_len if padded_len is not None else n
        if p < n:
            raise ValueError("padded_len < len(values)")
        np_dt = dtype.np_dtype
        assert np_dt is not None, f"{dtype} is not device-backed"
        out = np.zeros(p, dtype=np_dt)
        vals = np.asarray(values).astype(np_dt, copy=False)
        valid = np.zeros(p, dtype=np.bool_)
        if mask is None:
            out[:n] = vals
            valid[:n] = True
        else:
            m = np.asarray(mask, dtype=np.bool_)
            out[:n] = np.where(m, vals, np_dt.type(0))
            valid[:n] = m
        return out, valid

    @staticmethod
    def from_numpy(values: np.ndarray, dtype: DataType,
                   mask: Optional[np.ndarray] = None,
                   padded_len: Optional[int] = None) -> "DeviceColumn":
        out, valid = DeviceColumn.host_prepare(values, dtype, mask,
                                               padded_len)
        return DeviceColumn(jnp.asarray(out), jnp.asarray(valid), dtype)

    @staticmethod
    def all_valid(data, dtype: DataType) -> "DeviceColumn":
        return DeviceColumn(data, jnp.ones(data.shape, dtype=jnp.bool_), dtype)

    def with_arrays(self, data, validity) -> "DeviceColumn":
        """Rebuild this column around row-rearranged arrays (gather /
        compact / concat) — subclasses carry their extra state across."""
        return DeviceColumn(data, validity, self.dtype)

    # -- properties --------------------------------------------------------
    @property
    def padded_len(self) -> int:
        return int(self.data.shape[0])

    @property
    def device_backed(self) -> bool:
        return True

    def nbytes(self) -> int:
        return int(self.data.size * self.data.dtype.itemsize + self.validity.size)

    # -- host materialization ---------------------------------------------
    def to_numpy(self, num_rows: int):
        """Return (values, validity) host arrays truncated to num_rows."""
        d = np.asarray(jax.device_get(self.data))[:num_rows]
        v = np.asarray(jax.device_get(self.validity))[:num_rows]
        return d, v

    def arrow_from_host(self, d: np.ndarray, v: np.ndarray):
        """Assemble the arrow array from already-fetched host (data,
        validity) — the fetch itself is batched at the ColumnarBatch level
        (one device_get round trip for the whole batch)."""
        return arrow_from_numpy(d, v, self.dtype)

    def to_arrow(self, num_rows: int):
        if self.host_mirror is not None:
            # serve the exact source bits: besides skipping the D2H
            # fetch, this is a CORRECTNESS requirement for f64 — the
            # backend's emulated float64 carries ~48 mantissa bits, so a
            # device round trip of an untouched column would hand host
            # expressions values 1 ulp off (q6's `discount >= 0.05`
            # silently dropped every boundary row on the host engine)
            return self.host_mirror.slice(0, num_rows)
        d, v = self.to_numpy(num_rows)
        return self.arrow_from_host(d, v)

    def __repr__(self):
        return f"DeviceColumn({self.dtype.name}, padded={self.padded_len})"


def arrow_from_numpy(d: np.ndarray, v: np.ndarray, dtype: DataType):
    """Host (data, validity) numpy pair -> arrow array of the declared
    logical type (shared by every D2H materialization path)."""
    import pyarrow as pa
    at = to_arrow(dtype)
    if dtype == TIMESTAMP:
        return pa.Array.from_pandas(d, mask=~v).cast(pa.int64()).cast(at)
    if dtype == DATE:
        return pa.Array.from_pandas(d, mask=~v).cast(pa.int32()).cast(at)
    if isinstance(dtype, DecimalType):
        import decimal as _dec
        scale = dtype.scale
        py = [None if not ok else _dec.Decimal(int(x)).scaleb(-scale)
              for x, ok in zip(d.tolist(), v.tolist())]
        return pa.array(py, type=at)
    return pa.Array.from_pandas(d, mask=~v, type=at)


def _flatten_device_column(c: DeviceColumn):
    return (c.data, c.validity), c.dtype


def _unflatten_device_column(dtype, children):
    data, validity = children
    return DeviceColumn(data, validity, dtype)


jax.tree_util.register_pytree_node(
    DeviceColumn, _flatten_device_column, _unflatten_device_column)


class DictColumn(DeviceColumn):
    """A STRING column living in HBM as dictionary codes.

    TPU-first design for SURVEY.md hard-part #2 (strings in HBM without
    cudf): ``data`` holds int32 codes into a SORTED host-side dictionary,
    so equality AND relative order of codes match the string semantics
    (UTF-8 byte order == codepoint order). Row-rearranging device kernels
    (filter compaction, join gathers, partition scatter) move the codes
    like any fixed-width column — strings never round-trip through the
    host on the hot path; only final materialization decodes.

    The reference holds strings in device memory via cudf's offset+char
    layout; codes+dictionary is the XLA-friendly equivalent (static
    widths, MXU/VPU-amenable, no ragged buffers)."""

    __slots__ = ("dictionary",)

    def __init__(self, data, validity, dtype: DataType,
                 dictionary: np.ndarray, host_mirror=None):
        super().__init__(data, validity, dtype, host_mirror=host_mirror)
        self.dictionary = dictionary     # np object/str array, sorted

    def with_arrays(self, data, validity) -> "DictColumn":
        return DictColumn(data, validity, self.dtype, self.dictionary)

    def to_numpy(self, num_rows: int):
        codes, v = super().to_numpy(num_rows)
        vals = self.dictionary[np.clip(codes, 0, len(self.dictionary) - 1)] \
            if len(self.dictionary) else np.full(len(codes), "", object)
        return vals, v

    def arrow_from_host(self, d: np.ndarray, v: np.ndarray):
        """``d`` holds CODES here (what lives on device), not strings."""
        import pyarrow as pa
        if not len(self.dictionary):
            return pa.nulls(len(d), type=pa.string())
        idx = pa.array(np.clip(d, 0, len(self.dictionary) - 1)
                       .astype(np.int64), mask=~v)
        return pa.array(self.dictionary, type=pa.string()).take(idx)

    def to_arrow(self, num_rows: int):
        if self.host_mirror is not None:
            return self.host_mirror.slice(0, num_rows)
        codes = np.asarray(jax.device_get(self.data))[:num_rows]
        v = np.asarray(jax.device_get(self.validity))[:num_rows]
        return self.arrow_from_host(codes, v)

    def __repr__(self):
        return (f"DictColumn(card={len(self.dictionary)}, "
                f"padded={self.padded_len})")


def _flatten_dict_column(c: DictColumn):
    return (c.data, c.validity), (c.dtype, c.dictionary)


def _unflatten_dict_column(aux, children):
    dtype, dictionary = aux
    data, validity = children
    return DictColumn(data, validity, dtype, dictionary)


jax.tree_util.register_pytree_node(
    DictColumn, _flatten_dict_column, _unflatten_dict_column)


class HostColumn:
    """Arrow-backed host column for types without a dense device layout.

    Reference analog: RapidsHostColumnVector + the per-type CPU fallback the
    TypeSig machinery makes cheap to express (SURVEY.md section 7 hard part #2).
    """

    __slots__ = ("array", "dtype")

    def __init__(self, array, dtype: Optional[DataType] = None):
        import pyarrow as pa
        if isinstance(array, pa.ChunkedArray):
            array = array.combine_chunks()
        self.array = array
        self.dtype = dtype if dtype is not None else from_arrow(array.type)

    @staticmethod
    def from_pylist(values, dtype: DataType = STRING) -> "HostColumn":
        import pyarrow as pa
        return HostColumn(pa.array(values, type=to_arrow(dtype)), dtype)

    @property
    def device_backed(self) -> bool:
        return False

    @property
    def padded_len(self) -> int:
        return len(self.array)

    def nbytes(self) -> int:
        return self.array.nbytes

    def to_arrow(self, num_rows: int):
        return self.array.slice(0, num_rows)

    def to_numpy(self, num_rows: int):
        a = self.array.slice(0, num_rows)
        v = ~np.asarray(a.is_null())
        return a.to_numpy(zero_copy_only=False), v

    def __repr__(self):
        return f"HostColumn({self.dtype.name}, len={len(self.array)})"


Column = (DeviceColumn, HostColumn)
