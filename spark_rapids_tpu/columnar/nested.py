"""Device-resident list columns: dense ragged-to-rectangular layout.

TPU-first design for SURVEY.md hard-part #2 (nested types in HBM without
cudf — ref collectionOperations.scala, 1,802 LoC of cudf list kernels).
cudf stores lists as offsets + child buffers; XLA wants static shapes, so a
list column here is a RECTANGLE:

  * ``data``        [P, W] element values, W = bucketed max list length
  * ``elem_valid``  [P, W] element validity (False for NULL elements AND
                    for slots at/after each row's length)
  * ``lengths``     [P]    int32 per-row lengths (0 for NULL rows)
  * ``validity``    [P]    row validity (inherited DeviceColumn slot)

Collection expressions become plain vectorized ops over axis 1 (masked
reductions, axis-1 sorts, gathers) that XLA fuses like any elementwise
work — no ragged buffers, no scalar loops. Rows whose lists exceed the
width cap stay host columns (honest per-column fallback, the same
cost-based split the string dictionary uses for high cardinality).

Row-rearranging kernels (filter compaction, joins' gathers) operate on 1D
(data, validity) pairs; ``kernel_lanes``/``from_lanes`` decompose a list
column into W+1 such pairs and reassemble it, so the existing variadic-sort
compaction machinery moves list rows without learning about axis 1.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..types import ArrayType, DataType, from_arrow
from .column import DeviceColumn

__all__ = ["ListColumn", "encode_list_column", "WIDTH_BUCKETS",
           "device_list_ok"]

#: list-width buckets: each distinct W compiles its own kernel variants,
#: so widths snap to a short ladder (the row-count bucket idea on axis 1)
WIDTH_BUCKETS = (4, 8, 16, 32, 64, 128, 256)


def width_bucket(w: int) -> Optional[int]:
    for b in WIDTH_BUCKETS:
        if w <= b:
            return b
    return None


def device_list_ok(dt: DataType) -> bool:
    """True when ``dt`` is a list type whose elements can live densely on
    device (primitive element — nested-of-nested stays host)."""
    return (isinstance(dt, ArrayType) and dt.element.np_dtype is not None)


class ListColumn(DeviceColumn):
    """Device list column in the rectangular layout (module docstring)."""

    __slots__ = ("elem_valid", "lengths")

    def __init__(self, data, validity, dtype: ArrayType, elem_valid,
                 lengths, host_mirror=None):
        super().__init__(data, validity, dtype, host_mirror=host_mirror)
        self.elem_valid = elem_valid
        self.lengths = lengths

    # -- shape -------------------------------------------------------------
    @property
    def width(self) -> int:
        return int(self.data.shape[1])

    @property
    def padded_len(self) -> int:
        return int(self.data.shape[0])

    def nbytes(self) -> int:
        return int(self.data.size * self.data.dtype.itemsize
                   + self.elem_valid.size + self.validity.size
                   + self.lengths.size * 4)

    # -- rearranging-kernel interop ---------------------------------------
    def kernel_lanes(self) -> List[tuple]:
        """Decompose into 1D (data, validity) pairs for the variadic
        compaction/gather kernels: W value lanes + one (lengths, row
        validity) pair, in that order."""
        return ([(self.data[:, j], self.elem_valid[:, j])
                 for j in range(self.width)]
                + [(self.lengths, self.validity)])

    def from_lanes(self, pairs: List[tuple]) -> "ListColumn":
        w = self.width
        data = jnp.stack([d for d, _ in pairs[:w]], axis=1)
        ev = jnp.stack([v for _, v in pairs[:w]], axis=1)
        lengths, validity = pairs[w]
        return ListColumn(data, validity, self.dtype, ev, lengths)

    def with_arrays(self, data, validity):
        raise TypeError(
            "ListColumn rows rearrange via kernel_lanes()/from_lanes(); "
            "a 1D with_arrays() would silently corrupt the rectangle")

    # -- host materialization ---------------------------------------------
    def to_arrow(self, num_rows: int):
        import pyarrow as pa
        from .packing import fetch_packed
        from ..types import to_arrow as _toa
        n = int(num_rows)
        vals, ev, lens, rv = fetch_packed([
            self.data.reshape(-1), self.elem_valid.reshape(-1),
            self.lengths, self.validity])
        w = self.width
        vals = vals.reshape(-1, w)[:n]
        ev = ev.reshape(-1, w)[:n]
        lens = np.clip(lens[:n], 0, w).astype(np.int32)
        rv = rv[:n]
        pos = np.arange(w)[None, :] < lens[:, None]
        flat_vals = vals[pos]
        flat_valid = ev[pos]
        offsets = np.zeros(n + 1, dtype=np.int32)
        np.cumsum(lens, out=offsets[1:])
        # a null at offsets position i marks LIST ROW i as null
        off_arr = pa.array(offsets.astype(np.int64), mask=np.concatenate(
            [~rv, [False]]).astype(bool)).cast(pa.int32())
        elem_pa = pa.array(flat_vals, type=_toa(self.dtype.element),
                           from_pandas=True, mask=~flat_valid)
        return pa.ListArray.from_arrays(off_arr, elem_pa)

    def to_numpy(self, num_rows: int):
        a = self.to_arrow(num_rows)
        return (np.asarray(a.to_pylist(), dtype=object),
                ~np.asarray(a.is_null()))

    def __repr__(self):
        return (f"ListColumn({self.dtype.element.name}[{self.width}], "
                f"padded={self.padded_len})")


def _flatten_list_column(c: ListColumn):
    return (c.data, c.validity, c.elem_valid, c.lengths), c.dtype


def _unflatten_list_column(dtype, children):
    data, validity, elem_valid, lengths = children
    return ListColumn(data, validity, dtype, elem_valid, lengths)


jax.tree_util.register_pytree_node(
    ListColumn, _flatten_list_column, _unflatten_list_column)


def encode_list_column(col, dtype: ArrayType, padded_len: int,
                       width_cap: int = WIDTH_BUCKETS[-1]):
    """Arrow ListArray -> host-prepared rectangle arrays, or None when the
    column cannot (or should not) live densely on device: non-primitive
    element, or max list length beyond the cap (W*P element slots are
    materialized — a few long lists would explode HBM).

    Returns (values[P,W], elem_valid[P,W], lengths[P], row_valid[P], W).
    """
    import pyarrow as pa
    if not device_list_ok(dtype):
        return None
    if isinstance(col, pa.ChunkedArray):
        col = col.combine_chunks()
    n = len(col)
    if n == 0:
        w = WIDTH_BUCKETS[0]
        np_dt = dtype.element.np_dtype
        return (np.zeros((padded_len, w), np_dt),
                np.zeros((padded_len, w), np.bool_),
                np.zeros(padded_len, np.int32),
                np.zeros(padded_len, np.bool_), w)
    offsets = np.asarray(col.offsets)
    row_valid = ~np.asarray(col.is_null())
    lens = (offsets[1:] - offsets[:-1]).astype(np.int64)
    lens = np.where(row_valid, lens, 0)
    maxw = int(lens.max()) if n else 0
    w = width_bucket(max(maxw, 1))
    if w is None or w * padded_len > (1 << 26):
        return None                     # width cap or >64M element slots
    np_dt = dtype.element.np_dtype
    flat = col.values                   # raw child array; offsets are
    elem_valid_flat = ~np.asarray(flat.is_null())   # absolute into it
    if np_dt == np.bool_:
        fv = flat.fill_null(False)
    else:
        fv = flat.fill_null(0)
    at = fv.type
    if pa.types.is_date32(at):
        fv = fv.cast(pa.int32())
    elif pa.types.is_timestamp(at):
        fv = fv.cast(pa.int64())
    flat_np = fv.to_numpy(zero_copy_only=False).astype(np_dt, copy=False)
    base = offsets[:-1].astype(np.int64)
    pos = base[:, None] + np.arange(w)[None, :]
    in_list = np.arange(w)[None, :] < lens[:, None]
    pos = np.clip(pos, 0, max(len(flat_np) - 1, 0))
    values = np.zeros((padded_len, w), dtype=np_dt)
    ev = np.zeros((padded_len, w), dtype=np.bool_)
    if len(flat_np):
        values[:n] = np.where(in_list, flat_np[pos], np_dt.type(0))
        ev[:n] = in_list & elem_valid_flat[pos]
    lengths = np.zeros(padded_len, dtype=np.int32)
    lengths[:n] = lens.astype(np.int32)
    rv = np.zeros(padded_len, dtype=np.bool_)
    rv[:n] = row_valid
    return values, ev, lengths, rv, w
