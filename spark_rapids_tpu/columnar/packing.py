"""Batched device->host fetch in (at most) two transfers.

On a tunneled TPU every array fetched pays per-transfer latency, and
`jax.device_get` of a list waits leaf by leaf (measured: 21 small leaves
cost ~35-200 ms in straggler waits after the first). This packs results
into TWO device buffers — a uint32 stream (32-bit types bitcast, bools
bit-packed 32:1, int64 split into lo/hi words by arithmetic shifts) and
one concatenated float64 buffer (this backend's X64-removal pass cannot
bitcast 64-bit element types at all, so f64 bits are unreachable in-graph;
a plain f64 fetch is still a single transfer).

The reference ships query results through JCudfSerialization host buffers
(GpuColumnarBatchSerializer.scala) — one contiguous buffer per table — for
the same reason.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["fetch_packed", "pack_traced", "unpack_streams"]


def _u32_words(dt: np.dtype, shape) -> int:
    count = int(np.prod(shape)) if shape else 1
    if dt == np.bool_:
        return (count + 31) // 32
    if dt.itemsize == 8:
        return count * 2
    return count


def pack_traced(flat):
    """Traceable packing — call INSIDE an operator kernel so results leave
    the device as two buffers with no extra dispatch.
    -> (u32 stream, f64 stream); f64 arrays contribute only to the
    second, everything else only to the first."""
    words = []
    f64s = []
    for a in flat:
        if a.ndim == 0:
            a = a[None]
        if a.dtype == jnp.float64:
            f64s.append(a)
            continue
        if a.dtype == jnp.bool_:
            n = a.shape[0]
            k = (n + 31) // 32
            bits = jnp.zeros((k * 32,), jnp.uint32).at[:n].set(
                a.astype(jnp.uint32))
            w = bits.reshape(k, 32) << jnp.arange(32, dtype=jnp.uint32)
            words.append(jnp.sum(w, axis=1, dtype=jnp.uint32))
        elif a.dtype.itemsize == 8:      # i64/u64: arithmetic split
            ai = a.astype(jnp.int64)
            lo = (ai & 0xFFFFFFFF).astype(jnp.uint32)
            hi = ((ai >> 32) & 0xFFFFFFFF).astype(jnp.uint32)
            words.append(jnp.stack([lo, hi], axis=1).reshape(-1))
        elif a.dtype.itemsize == 4:
            words.append(jax.lax.bitcast_convert_type(a, jnp.uint32))
        elif jnp.issubdtype(a.dtype, jnp.floating):
            # f16/bf16: value-cast would drop fraction bits — carry the
            # raw 16-bit pattern instead
            words.append(jax.lax.bitcast_convert_type(a, jnp.uint16)
                         .astype(jnp.uint32))
        else:                            # 1/2-byte ints: widen (rare)
            words.append(a.astype(jnp.uint32))
    u32 = (jnp.concatenate(words) if words
           else jnp.zeros((0,), jnp.uint32))
    f64 = (jnp.concatenate(f64s) if f64s
           else jnp.zeros((0,), jnp.float64))
    return u32, f64


#: lazily resolved through the executable cache (exec_cache is the
#: blessed jit owner); the module-global memo keeps the per-fetch hit
#: path one attribute read — the compiler-front-memo idiom
_PACK = None


def _clear_pack() -> None:
    global _PACK
    _PACK = None


def _pack(flat):
    global _PACK
    # bind to a local: a concurrent exec_cache.clear() may null the
    # memo between the check and the call
    fn = _PACK
    if fn is None:
        from ..plan import exec_cache
        # front-memo contract: exec_cache.clear() must release THIS
        # strong reference too, or the dropped tier keeps serving
        exec_cache.register_clear_hook(_clear_pack)
        fn = _PACK = exec_cache.get_or_build_jit("columnar.pack_traced",
                                                 pack_traced)
    return fn(flat)


def unpack_streams(u32, f64, specs):
    """Host-side inverse of pack_traced; specs = [(np dtype, shape)]."""
    u32 = np.asarray(u32)
    f64 = np.asarray(f64)
    out = []
    woff = foff = 0
    for dt, shape in specs:
        count = int(np.prod(shape)) if shape else 1
        if dt == np.float64:
            arr = f64[foff:foff + count]
            foff += count
        else:
            w = _u32_words(dt, shape)
            raw = u32[woff:woff + w]
            woff += w
            if dt == np.bool_:
                bits = (raw[:, None] >> np.arange(32, dtype=np.uint32)) & 1
                arr = bits.reshape(-1)[:count].astype(bool)
            elif dt.itemsize == 8:
                pair = raw.reshape(-1, 2).astype(np.uint64)
                arr = ((pair[:, 1] << np.uint64(32)) | pair[:, 0]).view(dt)
            elif dt.itemsize == 4:
                arr = raw.view(dt)
            elif np.issubdtype(dt, np.floating) or dt.kind == 'V':
                arr = raw.astype(np.uint16).view(dt)
            else:
                arr = raw.astype(dt)
        out.append(arr.reshape(shape) if shape else arr[0])
    return out


def fetch_packed(arrays):
    """Fetch a list of device arrays in at most two transfers; returns
    numpy arrays with the original dtypes/shapes."""
    from ..trace import core as trace_core
    flat = list(arrays)
    specs = [(np.dtype(a.dtype), tuple(a.shape)) for a in flat]
    tr = trace_core.TRACER           # single branch when tracing is off
    if tr is None:
        u32, f64 = jax.device_get(_pack(tuple(flat)))
        return unpack_streams(u32, f64, specs)
    from .transfer import trace_fetch
    t0 = tr.now()
    packed = _pack(tuple(flat))      # pack-kernel dispatch (async)
    t1 = tr.now()
    u32, f64 = jax.device_get(packed)
    trace_fetch(t0, t1, int(u32.nbytes + f64.nbytes))
    return unpack_streams(u32, f64, specs)
