"""TPU-native segmented reductions (the engine's groupby/join/window core).

Measured on TPU v5e: XLA lowers `jax.ops.segment_*` to scatter, and 1M-row
scatters serialize on the scalar core at ~15-77 ns/element — 72-155 ms per
segment-sum (emulated-64-bit tuple combiners are worst). Row-sized gathers
(`jnp.take` with 1M indices) cost ~15-45 ms for the same reason. Dense
one-hot masked reductions instead run on the vector units at HBM bandwidth:
~15 us per segment over 1M rows (0.3 ms for 12 groups, 15 ms for 1024).

Strategy implemented here:
  * ``num_segments <= DENSE_MAX``: one-hot broadcast + reduce. The
    ``gid[None, :] == iota[:, None]`` mask fuses into the reduction loop, so
    the [G, n] intermediate never materializes.
  * larger: scatter fallback (cheap when the row count is small, e.g. the
    merge pass over already-grouped partials; the 1M-row big-G case is
    handled by the sorted-segment scan pipeline in groupby_core).

Group-sized (output-sized) gathers and scatters stay: G <= 4096 elements on
the scalar core is ~60 us, which is noise.

The reference gets segmented reductions from cudf's hash-based groupby
(CUDA hash tables + atomics); there is no XLA analog of device atomics, and
emulating one via scatter is exactly the wrong shape for this hardware.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["DENSE_MAX", "bucket_segments", "seg_sum", "seg_min", "seg_max",
           "seg_count", "onehot_gather"]

#: largest static segment count handled by the dense one-hot strategy
DENSE_MAX = 4096

#: static bucket sizes: kernels recompile only when the group-count estimate
#: crosses a bucket boundary (5 variants max), never per dictionary growth
_BUCKETS = (16, 64, 256, 1024, 4096)


def bucket_segments(n: int) -> int:
    """Smallest static bucket >= n (for jit static num_segments args)."""
    for b in _BUCKETS:
        if n <= b:
            return b
    return n


def _dense_mask(gid, num_segments: int):
    """[G, n] one-hot mask; stays fused into the consuming reduction."""
    iota = jax.lax.broadcasted_iota(jnp.int32, (num_segments, gid.shape[0]),
                                    0)
    return gid.astype(jnp.int32)[None, :] == iota


def seg_sum(data, gid, num_segments: int):
    """Sum of data per segment; rows with gid outside [0, G) are dropped."""
    if num_segments <= DENSE_MAX:
        m = _dense_mask(gid, num_segments)
        return jnp.sum(jnp.where(m, data[None, :], jnp.zeros_like(data[:1])),
                       axis=1)
    return jax.ops.segment_sum(data, gid, num_segments=num_segments)


def seg_count(pred, gid, num_segments: int, dtype=jnp.int64):
    """Count of True rows per segment (pred bool)."""
    return seg_sum(pred.astype(dtype), gid, num_segments)


def seg_min(data, gid, num_segments: int):
    if num_segments <= DENSE_MAX:
        m = _dense_mask(gid, num_segments)
        big = _neutral_max(data.dtype)
        return jnp.min(jnp.where(m, data[None, :], big), axis=1)
    return jax.ops.segment_min(data, gid, num_segments=num_segments)


def seg_max(data, gid, num_segments: int):
    if num_segments <= DENSE_MAX:
        m = _dense_mask(gid, num_segments)
        small = _neutral_min(data.dtype)
        return jnp.max(jnp.where(m, data[None, :], small), axis=1)
    return jax.ops.segment_max(data, gid, num_segments=num_segments)


def _neutral_max(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf, dtype=dtype)
    if dtype == jnp.bool_:
        return jnp.array(True)
    return jnp.array(jnp.iinfo(dtype).max, dtype=dtype)


def _neutral_min(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(-jnp.inf, dtype=dtype)
    if dtype == jnp.bool_:
        return jnp.array(False)
    return jnp.array(jnp.iinfo(dtype).min, dtype=dtype)


def onehot_gather(table, codes, num_entries: int):
    """table[codes] for a SMALL table (dictionary remap): dense one-hot
    select instead of a row-sized gather (44 ms -> 0.3 ms at 1M rows).
    Codes outside [0, num_entries) map to 0 of the table dtype."""
    if num_entries == 0:
        return jnp.zeros(codes.shape, dtype=table.dtype)
    # crossover vs the serialized row-gather (~30 ms/1M rows) is ~2k entries
    if num_entries > 2048:
        return jnp.take(table, codes, mode="clip")
    iota = jax.lax.broadcasted_iota(jnp.int32,
                                    (num_entries, codes.shape[0]), 0)
    m = codes.astype(jnp.int32)[None, :] == iota
    t = table[:num_entries].astype(table.dtype)[:, None]
    return jnp.sum(jnp.where(m, t, jnp.zeros_like(t[:1])), axis=0)
