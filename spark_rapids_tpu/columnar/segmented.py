"""TPU-native segmented reductions (the engine's groupby/join/window core).

Measured on TPU v5e: XLA lowers `jax.ops.segment_*` to scatter, and 1M-row
scatters serialize on the scalar core at ~15-77 ns/element — 72-155 ms per
segment-sum (emulated-64-bit tuple combiners are worst). Row-sized gathers
(`jnp.take` with 1M indices) cost ~15-45 ms for the same reason. Dense
one-hot masked reductions instead run on the vector units at HBM bandwidth:
~15 us per segment over 1M rows (0.3 ms for 12 groups, 15 ms for 1024).

Strategy implemented here:
  * ``num_segments <= DENSE_MAX``: one-hot broadcast + reduce. The
    ``gid[None, :] == iota[:, None]`` mask fuses into the reduction loop, so
    the [G, n] intermediate never materializes.
  * larger: scatter fallback (cheap when the row count is small, e.g. the
    merge pass over already-grouped partials; the 1M-row big-G case is
    handled by the sorted-segment scan pipeline in groupby_core).

Group-sized (output-sized) gathers and scatters stay: G <= 4096 elements on
the scalar core is ~60 us, which is noise.

The reference gets segmented reductions from cudf's hash-based groupby
(CUDA hash tables + atomics); there is no XLA analog of device atomics, and
emulating one via scatter is exactly the wrong shape for this hardware.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["DENSE_MAX", "SortedSegments", "GlobalSegments",
           "bucket_segments", "seg_sum", "seg_min", "seg_max",
           "seg_count", "onehot_gather"]

#: largest static segment count handled by the dense one-hot strategy
DENSE_MAX = 4096

#: static bucket sizes: kernels recompile only when the group-count estimate
#: crosses a bucket boundary (5 variants max), never per dictionary growth
_BUCKETS = (16, 64, 256, 1024, 4096)


def bucket_segments(n: int) -> int:
    """Smallest static bucket >= n (for jit static num_segments args)."""
    for b in _BUCKETS:
        if n <= b:
            return b
    return n




#: Hillis-Steele scans are UNROLLED with static shift distances. The
#: rolled form (lax.fori_loop whose body dynamic-slices by a traced
#: 1<<i) compiles pathologically on this backend WHEN COMPOSED WITH the
#: sort pipeline around it: sort+scan+sort measured 65-95 s to compile at
#: 262k rows (vs 24 s for the two sorts alone), multiplying per key and
#: per aggregate until the q28 merge kernel took >20 minutes. The same
#: pipeline with static-shift unrolled scans compiles in 15-17 s total.
#: (jnp.cumsum and lax.associative_scan are still worse: 191 s / 63 s at
#: 1M rows — see docs/performance.md.)


def prefix_sum(x, dtype=None):
    """Inclusive prefix sum via log2(n) static-shift/add passes."""
    v = x if dtype is None else x.astype(dtype)
    n = v.shape[0]
    zero = jnp.zeros((), v.dtype)
    d = 1
    while d < n:
        v = v + shift_static(v, d, zero)
        d <<= 1
    return v


def last_valid_scan(values, present):
    """Per row: the ``values`` entry at the most recent row (itself
    included) where ``present`` is True; rows before any present row keep
    their own value with present=False propagated. The vector-native way
    to broadcast a per-segment value (e.g. at segment starts) to every row
    without the group-table gather (~15-45 ms per 1M rows on TPU)."""
    v, p = values, present
    n = v.shape[0]
    zero = jnp.zeros((), v.dtype)
    d = 1
    while d < n:
        pv = shift_static(v, d, zero)
        pp = shift_static(p, d, False)
        v = jnp.where(p, v, pv)
        p = jnp.logical_or(p, pp)
        d <<= 1
    return v, p


def reverse_last_valid_scan(values, present):
    """last_valid_scan scanning right-to-left (broadcast from segment
    ENDS backward)."""
    v, p = last_valid_scan(jnp.flip(values), jnp.flip(present))
    return jnp.flip(v), jnp.flip(p)


def shift_static(arr, d: int, fill):
    """arr shifted by a STATIC distance (positive = right), fill-padded —
    a concatenate, not a gather."""
    if d == 0:
        return arr
    n = arr.shape[0]
    k = min(abs(d), n)
    pad = jnp.full((k,), fill, dtype=arr.dtype)
    if d > 0:
        return jnp.concatenate([pad, arr[:n - k]])
    return jnp.concatenate([arr[k:], pad])


def _dense_mask(gid, num_segments: int):
    """[G, n] one-hot mask; stays fused into the consuming reduction."""
    iota = jax.lax.broadcasted_iota(jnp.int32, (num_segments, gid.shape[0]),
                                    0)
    return gid.astype(jnp.int32)[None, :] == iota


class SortedSegments:
    """Segment context for rows already sorted by group key (groupby_core's
    sort pipeline). Segment reductions become Hillis-Steele segmented scans
    — log2(n) shift/combine passes, all vector ops — and each segment's
    aggregate lands at the segment's LAST row. Callers pass an instance in
    place of the ``gid`` array; every seg_* op dispatches on it and returns
    PER-ROW arrays (value at each row = scan up to that row). groupby_core
    extracts the per-segment results at the end positions with one shared
    compaction sort.

    ``live`` marks real rows (False = padding/filtered); dead rows
    contribute the combine-neutral to every scan and carry no boundary
    flags, so a trailing dead region just extends the last segment without
    changing its total.
    """

    def __init__(self, flags, live, orig_index=None):
        self.flags = flags            # bool[n]: True at segment starts
        self.live = live              # bool[n]
        #: original (pre-sort) row index per row — the rank FIRST/LAST
        #: select by; required when those aggregates run over this context
        self.orig_index = orig_index

    def _scan(self, v, combine, neutral):
        n = v.shape[0]
        neutral = jnp.asarray(neutral, dtype=v.dtype)
        f = self.flags
        d = 1
        while d < n:
            pv = shift_static(v, d, neutral)
            pf = shift_static(f, d, True)
            v = jnp.where(f, v, combine(pv, v))
            f = jnp.logical_or(f, pf)
            d <<= 1
        return v

    def sum(self, data, valid):
        ok = jnp.logical_and(valid, self.live)
        z = jnp.zeros((), dtype=data.dtype)
        masked = jnp.where(ok, data, z)
        return self._scan(masked, lambda a, b: a + b, 0)

    def min(self, data, valid):
        ok = jnp.logical_and(valid, self.live)
        big = _neutral_max(data.dtype)
        return self._scan(jnp.where(ok, data, big), jnp.minimum, big)

    def max(self, data, valid):
        ok = jnp.logical_and(valid, self.live)
        small = _neutral_min(data.dtype)
        return self._scan(jnp.where(ok, data, small), jnp.maximum, small)

    def count(self, pred, dtype=jnp.int64):
        ok = jnp.logical_and(pred, self.live)
        return self._scan(ok.astype(dtype), lambda a, b: a + b, 0)

    def select_by_rank(self, values, rank, valid, mode: str):
        """argmin/argmax scan: per row, the (values..., rank) of the valid
        row with the smallest (mode='min') / largest ('max') rank seen so
        far in the segment. Returns (selected_values list, sel_rank, ok).
        Used for FIRST/LAST (rank = original row index)."""
        ok = jnp.logical_and(valid, self.live)
        if mode == "min":
            neutral_r = _neutral_max(rank.dtype)
            better = lambda a, b: a <= b
        else:
            neutral_r = _neutral_min(rank.dtype)
            better = lambda a, b: a >= b
        r = jnp.where(ok, rank, neutral_r)
        n = r.shape[0]
        neutral_r = jnp.asarray(neutral_r, dtype=r.dtype)
        o, f, vs = ok, self.flags, tuple(values)
        d = 1
        while d < n:
            pr = shift_static(r, d, neutral_r)
            po = shift_static(o, d, False)
            pf = shift_static(f, d, True)
            pvs = tuple(shift_static(v, d, jnp.zeros((), v.dtype))
                        for v in vs)
            # take the predecessor when it is valid and (we're invalid or
            # its rank is better) — standard argmin/argmax monoid
            take_prev = jnp.logical_and(
                jnp.logical_not(f),
                jnp.logical_and(po, jnp.logical_or(jnp.logical_not(o),
                                                   better(pr, r))))
            r, o, f, vs = (
                jnp.where(take_prev, pr, r),
                jnp.where(f, o, jnp.logical_or(o, po)),
                jnp.logical_or(f, pf),
                tuple(jnp.where(take_prev, pv, v)
                      for pv, v in zip(pvs, vs)))
            d <<= 1
        return list(vs), r, o


class GlobalSegments(SortedSegments):
    """Single-segment (key-less aggregation) context: every reduction is
    ONE masked vector reduce instead of a log2(n) Hillis-Steele scan.
    The q9 shape — N conditional aggregates over the whole batch — drops
    from ~2N scans x log2(P) full-array shift/combine passes to N single
    reduces that XLA fuses into a handful of HBM sweeps, all still ONE
    kernel dispatch per batch.

    Results come back as shape-(1,) totals; callers (global_groupby)
    read element [-1] exactly as they do the scan path's last row, so
    every AggregateExpression.update/merge works over either context
    unchanged. Reduction ORDER differs from the scan path for floats
    (both differ from a sequential sum; neither is more exact)."""

    def __init__(self, live, orig_index=None):
        flags = jnp.zeros(live.shape, jnp.bool_).at[0].set(True)
        super().__init__(flags, live, orig_index=orig_index)

    def sum(self, data, valid):
        ok = jnp.logical_and(valid, self.live)
        z = jnp.zeros((), dtype=data.dtype)
        return jnp.sum(jnp.where(ok, data, z), dtype=data.dtype)[None]

    def min(self, data, valid):
        ok = jnp.logical_and(valid, self.live)
        big = _neutral_max(data.dtype)
        return jnp.min(jnp.where(ok, data, big))[None]

    def max(self, data, valid):
        ok = jnp.logical_and(valid, self.live)
        small = _neutral_min(data.dtype)
        return jnp.max(jnp.where(ok, data, small))[None]

    def count(self, pred, dtype=jnp.int64):
        ok = jnp.logical_and(pred, self.live)
        return jnp.sum(ok.astype(dtype), dtype=dtype)[None]

    def select_by_rank(self, values, rank, valid, mode: str):
        """Global argmin/argmax over rank — one reduce + one row gather
        (group-sized, i.e. a single element) instead of the scan."""
        ok = jnp.logical_and(valid, self.live)
        if mode == "min":
            neutral_r = _neutral_max(rank.dtype)
            r = jnp.where(ok, rank, neutral_r)
            i = jnp.argmin(r)
        else:
            neutral_r = _neutral_min(rank.dtype)
            r = jnp.where(ok, rank, neutral_r)
            i = jnp.argmax(r)
        any_ok = jnp.any(ok)[None]
        sel = [v[i][None] for v in values]
        return sel, r[i][None], any_ok


def seg_sum(data, gid, num_segments: int):
    """Sum of data per segment; rows with gid outside [0, G) are dropped.
    Callers pre-mask invalid rows to the neutral. With a SortedSegments
    context, returns the per-row segmented scan."""
    if isinstance(gid, SortedSegments):
        return gid.sum(data, jnp.ones(data.shape, jnp.bool_))
    if num_segments <= DENSE_MAX:
        m = _dense_mask(gid, num_segments)
        return jnp.sum(jnp.where(m, data[None, :], jnp.zeros_like(data[:1])),
                       axis=1)
    return jax.ops.segment_sum(data, gid, num_segments=num_segments)


def seg_count(pred, gid, num_segments: int, dtype=jnp.int64):
    """Count of True rows per segment (pred bool)."""
    if isinstance(gid, SortedSegments):
        return gid.count(pred, dtype)
    return seg_sum(pred.astype(dtype), gid, num_segments)


def seg_min(data, gid, num_segments: int):
    if isinstance(gid, SortedSegments):
        return gid.min(data, jnp.ones(data.shape, jnp.bool_))
    if num_segments <= DENSE_MAX:
        m = _dense_mask(gid, num_segments)
        big = _neutral_max(data.dtype)
        return jnp.min(jnp.where(m, data[None, :], big), axis=1)
    return jax.ops.segment_min(data, gid, num_segments=num_segments)


def seg_max(data, gid, num_segments: int):
    if isinstance(gid, SortedSegments):
        return gid.max(data, jnp.ones(data.shape, jnp.bool_))
    if num_segments <= DENSE_MAX:
        m = _dense_mask(gid, num_segments)
        small = _neutral_min(data.dtype)
        return jnp.max(jnp.where(m, data[None, :], small), axis=1)
    return jax.ops.segment_max(data, gid, num_segments=num_segments)


def _neutral_max(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf, dtype=dtype)
    if dtype == jnp.bool_:
        return jnp.array(True)
    return jnp.array(jnp.iinfo(dtype).max, dtype=dtype)


def _neutral_min(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(-jnp.inf, dtype=dtype)
    if dtype == jnp.bool_:
        return jnp.array(False)
    return jnp.array(jnp.iinfo(dtype).min, dtype=dtype)


def compact_rows(arrays, keep, padded_len: int):
    """Move keep-rows to the front preserving order: ONE stable variadic
    sort on (!keep) carrying every column as payload. Replaces the
    cumsum+scatter idiom — per-column 1M-row scatters serialize on the
    scalar core, while the sort network is bandwidth-bound (~5 ms).

    arrays: [(data, validity), ...]; returns (compacted pairs, count)."""
    count = jnp.sum(keep).astype(jnp.int32)
    live = jnp.arange(padded_len, dtype=jnp.int32) < count
    key = jnp.where(keep, jnp.uint8(0), jnp.uint8(1))
    flat = []
    for d, v in arrays:
        flat.extend((d, v))
    packed = jax.lax.sort(tuple([key] + flat), num_keys=1, is_stable=True)
    it = iter(packed[1:])
    outs = [(next(it), jnp.logical_and(next(it), live)) for _ in arrays]
    return outs, count


def onehot_gather(table, codes, num_entries: int):
    """table[codes] for a SMALL table (dictionary remap): dense one-hot
    select instead of a row-sized gather (44 ms -> 0.3 ms at 1M rows).
    Codes outside [0, num_entries) map to 0 of the table dtype."""
    if num_entries == 0:
        return jnp.zeros(codes.shape, dtype=table.dtype)
    # crossover vs the serialized row-gather (~30 ms/1M rows) is ~2k entries
    if num_entries > 2048:
        return jnp.take(table, codes, mode="clip")
    iota = jax.lax.broadcasted_iota(jnp.int32,
                                    (num_entries, codes.shape[0]), 0)
    m = codes.astype(jnp.int32)[None, :] == iota
    t = table[:num_entries].astype(table.dtype)[:, None]
    return jnp.sum(jnp.where(m, t, jnp.zeros_like(t[:1])), axis=0)
