"""Columnar batch (de)serialization for shuffle and spill.

Reference analog: GpuColumnarBatchSerializer.scala:127 over the
JCudfSerialization host-buffer format + TableCompressionCodec. Here the wire
format is Arrow IPC stream bytes (zero-copy-friendly, language-neutral) with
optional LZ4/ZSTD frame compression — the natural host format when the
device side is Arrow-layout HBM buffers.
"""
from __future__ import annotations

from typing import Optional

from .batch import ColumnarBatch

__all__ = ["serialize_batch", "deserialize_batch", "serialize_table",
           "deserialize_table"]


def serialize_table(table, codec: Optional[str] = "lz4") -> bytes:
    import pyarrow as pa
    sink = pa.BufferOutputStream()
    options = pa.ipc.IpcWriteOptions(
        compression=codec if codec in ("lz4", "zstd") else None)
    with pa.ipc.new_stream(sink, table.schema, options=options) as w:
        w.write_table(table)
    return sink.getvalue().to_pybytes()


def deserialize_table(data: bytes):
    import pyarrow as pa
    return pa.ipc.open_stream(pa.BufferReader(data)).read_all()


def serialize_batch(batch: ColumnarBatch, codec: Optional[str] = "lz4") -> bytes:
    """D2H + encode (ref SerializedTableColumn travelling through shuffle)."""
    return serialize_table(batch.to_arrow(), codec)


def deserialize_batch(data: bytes) -> ColumnarBatch:
    return ColumnarBatch.from_arrow(deserialize_table(data))
