"""Device strings as dense byte rectangles — the HIGH-cardinality string
representation (VERDICT r3 #4; ref stringFunctions.scala:1-2377, where
cudf holds strings device-side in an offset+chars layout).

Low-cardinality strings stay dictionary codes (DictColumn — transforms
evaluate once per distinct value). Past the dictionary crossover the r3
design collapsed, so rectangle columns carry EVERY row's bytes in HBM:

  bytes_[P, W] uint8   zero-padded past each row's length
  lengths[P]   int32   byte length per row (ASCII-gated: byte == char)
  validity[P]  bool

The XLA-friendly choices:
  * W is a small static bucket (8/16/32/64/... up to rect.maxBytes) —
    transforms are axis-1 vectorized ops over [P, W], no ragged buffers;
  * grouping/sorting packs each 8 bytes into one order-preserving int64
    word (big-endian, sign bit flipped), so a W-byte key is W/8 sort
    operands and the existing sort-based groupby machinery applies;
  * non-ASCII batches fall back to the host path honestly (case mapping
    and char semantics beyond ASCII need real Unicode tables — the
    reference leans on cudf's; a bad fast path would be silently wrong).
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..config import register
from ..types import STRING
from .column import DeviceColumn

__all__ = ["ByteRectColumn", "encode_string_rect", "RECT_MAX_BYTES",
           "rect_width_bucket", "pack_words", "unpack_words",
           "decode_rect_numpy"]

_LANE_JIT = {}

RECT_MAX_BYTES = register(
    "spark.rapids.tpu.sql.string.rect.maxBytes", 64,
    "Width cap for the device byte-rectangle string layout: columns "
    "whose longest value exceeds this stay host-resident (HBM cost is "
    "rows*width; cudf's ragged layout has no such cap but also no XLA "
    "static shapes). Power of two.")

def rect_width_bucket(max_len: int, cap: int) -> Optional[int]:
    """Smallest power-of-two width >= max_len (floor 8), or None past the
    cap. The ladder is unbounded below the CALLER's cap — merge-path
    re-encodes pass a huge cap because grouping consistency beats HBM
    economy there."""
    w = 8
    while w < max_len:
        w <<= 1
    return w if w <= cap else None


_WIDTH_BUCKETS = (8, 16, 32, 64, 128, 256)   # first-ingest ladder (docs)


def encode_string_rect(col, n: int, padded: int, cap: int):
    """pa.StringArray -> (rect uint8[P, W], lengths int32[P],
    valid bool[P], ascii_only) or None when too wide. Vectorized host
    encode: one flat byte copy, no per-row Python."""
    import pyarrow as pa
    if n == 0:
        w = _WIDTH_BUCKETS[0]
        return (np.zeros((padded, w), np.uint8),
                np.zeros(padded, np.int32), np.zeros(padded, bool), True)
    arr = col
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    valid_n = ~np.asarray(arr.is_null())
    arr = arr.fill_null("")
    # offsets/data straight from the arrow buffers (large_string widened)
    if pa.types.is_large_string(arr.type):
        arr = arr.cast(pa.string())
    bufs = arr.buffers()
    offsets = np.frombuffer(bufs[1], np.int32,
                            count=len(arr) + 1 + arr.offset)[arr.offset:]
    data = np.frombuffer(bufs[2], np.uint8) if bufs[2] is not None \
        else np.zeros(0, np.uint8)
    lens = (offsets[1:] - offsets[:-1]).astype(np.int32)
    max_len = int(lens.max()) if len(lens) else 0
    w = rect_width_bucket(max_len, cap)
    if w is None:
        return None
    rect = np.zeros((padded, w), np.uint8)
    # flat scatter: target positions row*W + col for every source byte
    total = int(offsets[-1] - offsets[0])
    if total:
        rows = np.repeat(np.arange(n, dtype=np.int64), lens)
        within = (np.arange(total, dtype=np.int64)
                  - np.repeat((offsets[:-1] - offsets[0]).astype(np.int64),
                              lens))
        rect.reshape(-1)[rows * w + within] = \
            data[offsets[0]:offsets[0] + total]
    lengths = np.zeros(padded, np.int32)
    lengths[:n] = lens
    valid = np.zeros(padded, bool)
    valid[:n] = valid_n
    ascii_only = bool((rect < 0x80).all())
    return rect, lengths, valid, ascii_only


def decode_rect_numpy(rect: np.ndarray, lengths: np.ndarray,
                      valid: np.ndarray, num_rows: int):
    """Host rect -> pa.StringArray (one pass through arrow's builder)."""
    import pyarrow as pa
    r = rect[:num_rows]
    ln = lengths[:num_rows].astype(np.int64)
    v = valid[:num_rows]
    ln = np.where(v, ln, 0)
    w = r.shape[1] if r.ndim == 2 else 0
    mask = np.arange(w, dtype=np.int64)[None, :] < ln[:, None]
    flat = r[mask]                       # concatenated live bytes
    offsets = np.zeros(num_rows + 1, np.int32)
    np.cumsum(ln, out=offsets[1:])
    nulls = int((~v).sum())
    return pa.StringArray.from_buffers(
        num_rows, pa.py_buffer(offsets.tobytes()),
        pa.py_buffer(flat.tobytes()),
        (pa.py_buffer(np.packbits(v, bitorder="little").tobytes())
         if nulls else None),
        nulls)


def pack_words(bytes_, lengths):
    """uint8[P, W] -> order-preserving int64 words [P, W/8]: big-endian
    byte packing so integer comparison equals bytewise (UTF-8/codepoint)
    comparison; the sign bit is flipped so the SIGNED sort order matches
    the unsigned byte order. Bytes past each row's length are zero in the
    rectangle, which compares below every real byte — so shorter strings
    sort before their extensions, exactly the string order."""
    import jax.numpy as jnp
    p, w = bytes_.shape
    nw = max(w // 8, 1)
    words = []
    for k in range(nw):
        word = jnp.zeros(bytes_.shape[:1], jnp.int64)
        for j in range(8):
            word = (word << 8) | bytes_[:, k * 8 + j].astype(jnp.int64)
        # flip the sign bit: unsigned byte order in the signed domain
        words.append(word ^ jnp.int64(np.int64(-0x8000000000000000)))
    return words


def unpack_words(words, width: int):
    """Inverse of pack_words -> uint8[P, W]."""
    import jax.numpy as jnp
    cols = []
    for k, word in enumerate(words):
        u = word ^ jnp.int64(np.int64(-0x8000000000000000))
        for j in range(8):
            shift = 8 * (7 - j)
            cols.append(((u >> shift) & 0xFF).astype(jnp.uint8))
    return jnp.stack(cols[:width], axis=1)


class ByteRectColumn(DeviceColumn):
    """STRING column living in HBM as a byte rectangle (module doc)."""

    __slots__ = ("lengths", "ascii_only")

    def __init__(self, data, validity, lengths, ascii_only: bool = True,
                 host_mirror=None):
        super().__init__(data, validity, STRING, host_mirror=host_mirror)
        self.lengths = lengths
        self.ascii_only = ascii_only

    @property
    def width(self) -> int:
        return int(self.data.shape[1])

    @property
    def padded_len(self) -> int:
        return int(self.data.shape[0])

    def nbytes(self) -> int:
        return int(self.data.size + self.validity.size + 4 * self.lengths.size)

    def with_arrays(self, data, validity) -> "DeviceColumn":
        # row-rearranging kernels move (bytes, lengths) together via
        # kernel_lanes()/from_lanes(); a caller handing back only 1-D
        # data is moving some DERIVED column, not this rectangle
        raise TypeError("ByteRectColumn rows move via kernel_lanes")

    # -- rearranging-kernel interop (the ListColumn lane protocol:
    # exprs/compiler._lane_pairs): the rectangle rides variadic 1-D row
    # kernels as W/8 order-preserving int64 word lanes + the length lane
    def kernel_lanes(self):
        import jax
        key = ("lanes", self.width)
        fn = _LANE_JIT.get(key)
        if fn is None:
            def mk(bytes_, lengths):
                return tuple(pack_words(bytes_, lengths))
            fn = _LANE_JIT[key] = jax.jit(mk)
        words = fn(self.data, self.lengths)
        return ([(w, self.validity) for w in words]
                + [(self.lengths, self.validity)])

    def from_lanes(self, outs):
        import jax
        words = tuple(d for d, _ in outs[:-1])
        lengths, validity = outs[-1]
        key = ("unlanes", self.width, len(words))
        fn = _LANE_JIT.get(key)
        if fn is None:
            w = self.width

            def mk(ws, ln):
                return unpack_words(list(ws), w), ln.astype("int32")
            fn = _LANE_JIT[key] = jax.jit(mk)
        bytes_, ln = fn(words, lengths)
        return ByteRectColumn(bytes_, validity, ln,
                              ascii_only=self.ascii_only)

    def strval(self):
        from ..exprs.base import DVal, StrVal
        return DVal(StrVal(self.data, self.lengths), self.validity, STRING)

    def to_numpy(self, num_rows: int):
        import jax
        rect = np.asarray(jax.device_get(self.data))[:num_rows]
        ln = np.asarray(jax.device_get(self.lengths))[:num_rows]
        v = np.asarray(jax.device_get(self.validity))[:num_rows]
        w = rect.shape[1]
        mask = np.arange(w)[None, :] < np.where(v, ln, 0)[:, None]
        vals = np.empty(num_rows, object)
        # bulk decode: join on the flat live bytes with per-row splits
        flat = rect[mask].tobytes()
        offs = np.zeros(num_rows + 1, np.int64)
        np.cumsum(np.where(v, ln, 0), out=offs[1:])
        for i in range(num_rows):
            vals[i] = flat[offs[i]:offs[i + 1]].decode("utf-8",
                                                       "replace")
        return vals, v

    def to_arrow(self, num_rows: int):
        if self.host_mirror is not None:
            return self.host_mirror.slice(0, num_rows)
        import jax
        rect = np.asarray(jax.device_get(self.data))
        ln = np.asarray(jax.device_get(self.lengths))
        v = np.asarray(jax.device_get(self.validity))
        return decode_rect_numpy(rect, ln, v, num_rows)

    def arrow_from_host(self, d, v):
        # d arrives as the fetched rectangle rows when the batched sink
        # fetch resolved this column (packing flattens 2-D arrays)
        if isinstance(d, np.ndarray) and d.ndim == 2:
            ln = np.asarray(self.lengths)[:len(d)]
            return decode_rect_numpy(d, ln, np.asarray(v), len(d))
        return super().arrow_from_host(d, v)

    def __repr__(self):
        return (f"ByteRectColumn(w={self.width}, "
                f"padded={self.padded_len}, ascii={self.ascii_only})")
