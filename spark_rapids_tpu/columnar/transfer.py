"""Host->device transfer compression for batch ingest.

On the tunneled TPU backend H2D moves ~450 MB/s (docs/performance.md), so
ingest bytes are a first-order cost of every query. TPC-shaped data is
massively narrowable: dates span ~2.5k days (int32 -> uint16+offset),
quantities/discounts are small ints or 2-decimal fixed-point doubles
(float64 -> int8/int16/int32 + scale), dictionary codes have tiny
cardinality (int32 -> uint8), and validity is usually all-true (dropped)
or bitpackable 8:1.

Encodings are chosen per column ONLY when a host-side check proves the
device decode reproduces identical bits (the decode formula is evaluated
on the host with the same IEEE ops). The decode runs as ONE fused XLA
kernel right after the single device_put, costing one extra dispatch —
worth it only above a size threshold, so small batches keep the raw path.

Reference analog: the GPU parquet reader ships compressed pages to the
device and decodes there (GpuParquetScan.scala Table.readParquet); this is
the same move for in-memory ingest, with XLA as the decoder.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import numpy as np

from ..trace import core as trace_core

__all__ = ["encode_columns", "decode_with_len", "worthwhile", "RAW",
           "traced_device_put"]

RAW = ("raw",)

#: encoded batch must be at most this fraction of raw bytes to pay for
#: the extra decode dispatch
_WORTH_RATIO = 0.6
#: and the raw batch at least this big (small batches: dispatch dominates)
MIN_RAW_BYTES = 4 << 20

#: f64 columns NEVER narrow: the TPU backend's emulated f64 is not
#: bit-exact for division (5/100.0 < 0.05) NOR for int->f64 conversion
#: (measured wrong bits even in int32 range), so any value-recomputing
#: decode would shift comparison results at band edges. The host-side
#: exactness proof only covers ops the device computes identically —
#: for floats that is the raw bit-copy alone. Integer ops (add, astype
#: between int widths) are exact on device (verified), so ints, dates,
#: bools, dict codes, and validity still narrow.
_F64_INV_SCALES = ()


def _narrow_int(rng: int):
    if rng < (1 << 8):
        return np.uint8
    if rng < (1 << 16):
        return np.uint16
    if rng < (1 << 31):
        return np.int32
    return None


def encode_column(data: np.ndarray, valid: np.ndarray):
    """(padded data, padded validity) -> (host arrays, spec, params).

    spec is a STATIC tuple (kernel cache key); params are per-batch traced
    scalars (offset, scale) so varying data never recompiles. Returns
    (arrays=[data_enc] or [], spec, params, vspec, varrays) with validity
    handled separately."""
    # -- validity ----------------------------------------------------------
    if valid.all():
        vspec, varrays = ("valid_all",), []
    elif not valid.any():
        vspec, varrays = ("valid_none",), []
    else:
        vspec, varrays = ("valid_bits",), [np.packbits(valid)]

    n_valid = int(valid.sum())
    if n_valid == 0:
        return [], ("zero", data.dtype.str), (), vspec, varrays

    dt = data.dtype
    if dt == np.bool_:
        return ([np.packbits(data & valid)], ("bool_bits",), (),
                vspec, varrays)

    if np.issubdtype(dt, np.integer):
        vmin = int(data[valid].min())
        vmax = int(data[valid].max())
        enc_dt = _narrow_int(vmax - vmin)
        if enc_dt is None or np.dtype(enc_dt).itemsize >= dt.itemsize:
            return [data], RAW, (), vspec, varrays
        enc = np.zeros(data.shape, enc_dt)
        enc[valid] = (data[valid].astype(np.int64)
                      - vmin).astype(enc_dt)
        return ([enc], ("int_off", dt.str, enc_dt().dtype.str),
                (np.int64(vmin),), vspec, varrays)

    if dt == np.float64:
        v = data[valid]
        if not np.isfinite(v).all():
            return [data], RAW, (), vspec, varrays
        for inv in _F64_INV_SCALES:
            s = v * inv
            r = np.round(s)
            if not (np.abs(r) < (1 << 62)).all():
                continue
            ri = r.astype(np.int64)
            vmin = int(ri.min())
            rng = int(ri.max()) - vmin
            enc_dt = _narrow_int(rng)
            if enc_dt is None:
                continue
            # exactness proof: the DEVICE decode formula evaluated on the
            # host must reproduce the input bit-for-bit
            back = (ri - vmin + vmin).astype(np.float64) / inv
            if not np.array_equal(back, v):
                continue
            enc = np.zeros(data.shape, enc_dt)
            enc[valid] = (ri - vmin).astype(enc_dt)
            return ([enc], ("f64_scaled", enc_dt().dtype.str),
                    (np.int64(vmin), np.float64(inv)), vspec, varrays)
        return [data], RAW, (), vspec, varrays

    return [data], RAW, (), vspec, varrays


def encode_columns(pairs: List[Tuple[np.ndarray, np.ndarray]]):
    """[(padded data, padded validity)] -> (flat host arrays, specs,
    flat params, saved_ratio). specs is the static kernel key."""
    flat: List[np.ndarray] = []
    params: List = []
    specs: List = []
    raw_bytes = enc_bytes = 0
    for d, v in pairs:
        arrays, spec, ps, vspec, varrays = encode_column(d, v)
        raw_bytes += d.nbytes + v.nbytes
        enc_bytes += sum(a.nbytes for a in arrays + varrays)
        specs.append((spec, vspec, len(arrays), len(varrays), len(ps)))
        flat.extend(arrays)
        flat.extend(varrays)
        params.extend(ps)
    ratio = enc_bytes / max(raw_bytes, 1)
    return flat, tuple(specs), params, ratio, raw_bytes


def worthwhile(ratio: float, raw_bytes: int) -> bool:
    return raw_bytes >= MIN_RAW_BYTES and ratio <= _WORTH_RATIO


@functools.lru_cache(maxsize=256)
def _decode_kernel(specs, padded_len: int):
    import jax
    import jax.numpy as jnp

    def unpack_bits(bits, p):
        # bits: uint8[ceil(p/8)] -> bool[p] (elementwise, no gather)
        b = bits[:, None] >> (7 - jnp.arange(8, dtype=jnp.uint8))
        return (b & 1).astype(jnp.bool_).reshape(-1)[:p]

    @jax.jit
    def decode(arrays, params):
        ai = pi = 0
        out = []
        for spec, vspec, n_a, n_v, n_p in specs:
            a = arrays[ai:ai + n_a]
            va = arrays[ai + n_a:ai + n_a + n_v]
            ps = params[pi:pi + n_p]
            ai += n_a + n_v
            pi += n_p
            if vspec == ("valid_all",):
                valid = jnp.ones(padded_len, jnp.bool_)
            elif vspec == ("valid_none",):
                valid = jnp.zeros(padded_len, jnp.bool_)
            else:
                valid = unpack_bits(va[0], padded_len)
            kind = spec[0]
            if kind == "raw":
                data = a[0]
            elif kind == "zero":
                data = jnp.zeros(padded_len, dtype=np.dtype(spec[1]))
            elif kind == "bool_bits":
                data = unpack_bits(a[0], padded_len)
            elif kind == "int_off":
                tgt = np.dtype(spec[1])
                off = ps[0]
                data = (a[0].astype(jnp.int64) + off).astype(tgt)
                data = jnp.where(valid, data, jnp.zeros((), tgt))
            elif kind == "f64_scaled":
                off, inv = ps
                data = ((a[0].astype(jnp.int64) + off)
                        .astype(jnp.float64) / inv)
                data = jnp.where(valid, data, 0.0)
            else:  # pragma: no cover
                raise ValueError(spec)
            out.append((data, valid))
        return out

    return decode


def decode_with_len(dev_arrays, specs, params, padded_len: int):
    """One fused decode dispatch over the already-transferred arrays."""
    import jax.numpy as jnp
    return _decode_kernel(specs, padded_len)(
        tuple(dev_arrays), tuple(jnp.asarray(p) for p in params))


# ---------------------------------------------------------------------------
# traced transfers (trace/core.py): H2D/D2H time + bytes attribution
# ---------------------------------------------------------------------------

def traced_device_put(host_arrays, label: str = "h2d"):
    """``jax.device_put`` with H2D attribution when tracing is on: the
    DISPATCH span (host-side enqueue, what the query thread pays even
    asynchronously) is recorded separately from the DEVICE span (the
    block_until_ready wait covering the actual tunnel transfer), so the
    profile can split host time from device/transfer time. When tracing
    is off this is exactly one branch around a plain device_put."""
    import jax
    tr = trace_core.TRACER
    if tr is None:
        return jax.device_put(host_arrays)
    nbytes = sum(getattr(a, "nbytes", 0) for a in host_arrays)
    t0 = tr.now()
    out = jax.device_put(host_arrays)
    t1 = tr.now()
    tr.complete(f"{label}.dispatch", t0, t1, cat="transfer",
                args={"bytes": nbytes, "arrays": len(host_arrays)})
    # the wait is only forced while TRACING: attribution requires the
    # transfer boundary, and an async put would bill it to whichever
    # kernel happens to touch the arrays first
    jax.block_until_ready(out)
    tr.complete(f"{label}.device", t1, cat="transfer",
                args={"bytes": nbytes})
    tr.counter("h2d.bytes", {"bytes": nbytes}, cat="transfer")
    return out


def trace_fetch(t0_ns: int, t1_ns: int, nbytes: int,
                label: str = "d2h") -> None:
    """Record a device->host fetch that already happened: dispatch span
    ``t0..t1`` (building/enqueueing the pack kernel) and transfer span
    ``t1..now`` (the blocking device_get). Callers guard on the tracer
    themselves so the disabled path stays a single branch."""
    tr = trace_core.TRACER
    if tr is None:
        return
    tr.complete(f"{label}.dispatch", t0_ns, t1_ns, cat="transfer",
                args={"bytes": nbytes})
    tr.complete(f"{label}.transfer", t1_ns, cat="transfer",
                args={"bytes": nbytes})
    tr.counter("d2h.bytes", {"bytes": nbytes}, cat="transfer")
