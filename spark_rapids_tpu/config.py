"""Typed configuration system.

TPU-native analog of the reference's RapidsConf (sql-plugin/.../RapidsConf.scala:
122-261 ConfEntry/ConfBuilder DSL, registry at 320-328, `help()` doc generation).
Keys live under ``spark.rapids.tpu.*``. The registry is introspectable so
``generate_docs()`` can emit docs/configs.md just like the reference.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, List, Optional

__all__ = ["ConfEntry", "TpuConf", "register", "all_entries", "generate_docs"]

_LOCK = threading.Lock()
_REGISTRY: Dict[str, "ConfEntry"] = {}  # tpulint: guarded-by _LOCK


class ConfEntry:
    def __init__(self, key: str, default: Any, doc: str, conv: Callable[[str], Any],
                 internal: bool = False, startup_only: bool = False,
                 commonly_used: bool = False):
        self.key = key
        self.default = default
        self.doc = doc
        self.conv = conv
        self.internal = internal
        self.startup_only = startup_only
        self.commonly_used = commonly_used

    def get(self, conf: "TpuConf") -> Any:
        raw = conf.raw.get(self.key)
        if raw is None:
            env_key = self.key.upper().replace(".", "_")
            raw = os.environ.get(env_key)
        if raw is None:
            return self.default
        if isinstance(raw, str):
            return self.conv(raw)
        return raw


def _bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


def _register(key: str, default, doc, conv, **kw) -> ConfEntry:
    with _LOCK:
        if key in _REGISTRY:
            raise ValueError(f"duplicate conf key {key}")
        e = ConfEntry(key, default, doc, conv, **kw)
        _REGISTRY[key] = e
        return e


def register(key: str, default, doc: str, **kw) -> ConfEntry:
    conv: Callable[[str], Any]
    if isinstance(default, bool):
        conv = _bool
    elif isinstance(default, int):
        conv = int
    elif isinstance(default, float):
        conv = float
    else:
        conv = str
    return _register(key, default, doc, conv, **kw)


def all_entries() -> List[ConfEntry]:
    # snapshot under the lock: the docs generator or qualify tool may
    # enumerate while ensure_op_confs() is still registering per-op keys
    with _LOCK:
        entries = list(_REGISTRY.values())
    return sorted(entries, key=lambda e: e.key)


# ---------------------------------------------------------------------------
# Registered configs (counterparts of the reference's key knobs; reference
# file:line cited per entry)
# ---------------------------------------------------------------------------

SQL_ENABLED = register(
    "spark.rapids.tpu.sql.enabled", True,
    "Enable plan replacement onto the TPU (ref RapidsConf spark.rapids.sql.enabled).",
    commonly_used=True)

EXPLAIN = register(
    "spark.rapids.tpu.sql.explain", "NONE",
    "NONE / NOT_ON_TPU / ALL: log why (parts of) a plan did or did not run on the "
    "TPU (ref RapidsConf spark.rapids.sql.explain).", commonly_used=True)

MODE = register(
    "spark.rapids.tpu.sql.mode", "executeOnTPU",
    "executeOnTPU or explainOnly (ref GpuOverrides.scala:4701 explain-only mode).")

CONCURRENT_TPU_TASKS = register(
    "spark.rapids.tpu.sql.concurrentTpuTasks", 2,
    "Number of tasks that may hold the device semaphore concurrently "
    "(ref RapidsConf.scala:545 concurrentGpuTasks / GpuSemaphore.scala:137).",
    commonly_used=True)

BATCH_SIZE_BYTES = register(
    "spark.rapids.tpu.sql.batchSizeBytes", 512 * 1024 * 1024,
    "Target columnar batch size; coalesce goal ceiling "
    "(ref RapidsConf.scala:554 batchSizeBytes).", commonly_used=True)

BATCH_SIZE_ROWS = register(
    "spark.rapids.tpu.sql.batchSizeRows", 1 << 20,
    "Target max rows per columnar batch (shape-bucket ceiling; TPU-specific: "
    "bounds XLA recompilation via the bucket ladder).")

AGG_WIDE_BATCH_ROWS = register(
    "spark.rapids.tpu.sql.agg.wideBatchRows", 0,
    "Batch-width ceiling for in-memory scans feeding a GLOBAL (no group "
    "key) aggregation: such pipelines have no per-batch group-bucket "
    "risk, and their steady-state cost is per-dispatch latency, so the "
    "scan feeds the widest batches possible — one batch means the whole "
    "query runs as ONE fused kernel dispatch + one fetch (ref "
    "GpuAggregateExec.scala:718 first-pass concatenation). 0 = auto: "
    "widen up to the whole partition ONLY while the estimated batch "
    "bytes fit half the HBM budget (the OOM retry-split machinery "
    "remains the backstop); set a row count to pin the ceiling instead.")

AUTO_BROADCAST_THRESHOLD = register(
    "spark.rapids.tpu.sql.autoBroadcastJoinThreshold", 10 * 1024 * 1024,
    "Equi-joins broadcast a side whose plan-time size estimate is at or "
    "below this many bytes (build once, probe per shard — ref Spark's "
    "autoBroadcastJoinThreshold + the reference's AQE join-strategy "
    "switching, GpuOverrides.scala:4681). <=0 disables auto selection.",
    commonly_used=True)

JOIN_BLOOM_FILTER = register(
    "spark.rapids.tpu.sql.join.bloomFilter.enabled", False,
    "Build a device bloom filter from the build side's join keys and "
    "pre-filter the stream side before inner/semi hash joins (ref Spark's "
    "InjectRuntimeFilter + spark-rapids-jni BloomFilter).")

JOIN_SUBPARTITION_SIZE = register(
    "spark.rapids.tpu.sql.join.subPartitionSizeBytes", 256 * 1024 * 1024,
    "When the combined input of an equi-join exceeds this many bytes the join "
    "hash-partitions both sides and runs N independent sub-joins "
    "(ref GpuSubPartitionHashJoin.scala / GpuShuffledSizedHashJoinExec.scala:1255). "
    "<= 0 disables sub-partitioning.")

JOIN_SPECULATIVE_SIZING = register(
    "spark.rapids.tpu.sql.join.speculativeSizing", True,
    "Size join outputs from the input shape bucket instead of syncing the "
    "exact pair count to the host (each sync is a full tunnel round trip). "
    "Sinks validate the real totals once per query and transparently "
    "re-execute with exact sizing if a guess was too small.")

ALLOC_FRACTION = register(
    "spark.rapids.tpu.memory.hbm.allocFraction", 0.85,
    "Fraction of HBM the pool manager budgets for columnar buffers "
    "(ref RapidsConf spark.rapids.memory.gpu.allocFraction).", startup_only=True)

HBM_LIMIT_BYTES = register(
    "spark.rapids.tpu.memory.hbm.limitBytes", 0,
    "Explicit HBM budget in bytes; 0 = derive from device "
    "(ref GpuDeviceManager.computeRmmPoolSize).", startup_only=True)

HOST_SPILL_LIMIT = register(
    "spark.rapids.tpu.memory.host.spillStorageSize", 4 * 1024 * 1024 * 1024,
    "Bytes of host memory for spilled buffers before going to disk "
    "(ref RapidsHostMemoryStore.scala:41).")

OOM_RETRY_ENABLED = register(
    "spark.rapids.tpu.memory.oomRetry.enabled", True,
    "Enable the per-thread OOM retry/split state machine "
    "(ref RmmRapidsRetryIterator.scala:33).")

OOM_MAX_SPLIT_DEPTH = register(
    "spark.rapids.tpu.oom.maxSplitDepth", 8,
    "How many times a single input batch may be halved by the "
    "SplitAndRetryOOM rung of the retry state machine before the "
    "escalation ladder moves on (cross-session pressure spill, then the "
    "OOM_PRESSURE_HOST degradation rung — mem/retry.py, "
    "docs/fault_tolerance.md). Depth 8 means pieces as small as "
    "1/256th of the original batch.")

OOM_HOST_FALLBACK_ENABLED = register(
    "spark.rapids.tpu.oom.hostFallback.enabled", True,
    "Allow the final rung of the OOM escalation ladder: after retries, "
    "splits and a cross-session pressure spill all fail, run the one "
    "starving operator on the host backend under an unbudgeted memory "
    "grant instead of failing the query (recorded as an "
    "OOM_PRESSURE_HOST placement tag and counted by "
    "srtpu_oom_host_fallback_total). Off = the ladder ends in "
    "OutOfDeviceMemory, the pre-r14 behavior.")

ADAPTIVE_ENABLED = register(
    "spark.rapids.tpu.sql.adaptive.enabled", True,
    "Adaptive execution: post-shuffle partition coalescing by observed "
    "partition sizes (ref Spark AQE + GpuCustomShuffleReaderExec).",
    commonly_used=True)

ADAPTIVE_TARGET_BYTES = register(
    "spark.rapids.tpu.sql.adaptive.targetPostShuffleBytes",
    64 * 1024 * 1024,
    "Adaptive coalescing merges consecutive shuffle partitions until this "
    "many bytes (ref spark.sql.adaptive.advisoryPartitionSizeInBytes).")

DEFAULT_SHUFFLE_PARTITIONS = register(
    "spark.rapids.tpu.sql.shuffle.partitions", 8,
    "Partition count for repartition() without an explicit count "
    "(ref spark.sql.shuffle.partitions).")

SHUFFLE_MODE = register(
    "spark.rapids.tpu.shuffle.mode", "MULTITHREADED",
    "MULTITHREADED (host-staged) / ICI (device-resident collective exchange) / "
    "CACHE_ONLY (single-process testing) "
    "(ref RapidsShuffleInternalManagerBase.scala:1264-1276).", commonly_used=True)

SHUFFLE_CODEC = register(
    "spark.rapids.tpu.shuffle.compression.codec", "lz4",
    "Compression for serialized shuffle blocks: lz4 / zstd / none "
    "(ref spark.rapids.shuffle.compression.codec + TableCompressionCodec).")

SHUFFLE_THREADS = register(
    "spark.rapids.tpu.shuffle.multiThreaded.numThreads", 8,
    "Writer/reader threads for the multithreaded shuffle "
    "(ref RapidsShuffleThreadedWriterBase).")

MULTITHREADED_READ_THREADS = register(
    "spark.rapids.tpu.sql.multiThreadedRead.numThreads", 8,
    "Host read thread-pool size for cloud/coalescing file readers "
    "(ref Plugin.scala:269-281).")

IO_PATH_REPLACEMENT = register(
    "spark.rapids.tpu.io.pathReplacementRules", "",
    "Semicolon-separated 'prefix->replacement' rules applied to scan paths "
    "before opening (ref AlluxioUtils.scala s3://->alluxio:// rewriting); "
    "e.g. 's3://bucket->/mnt/alluxio/bucket'.")

PARQUET_READER_TYPE = register(
    "spark.rapids.tpu.sql.format.parquet.reader.type", "AUTO",
    "PERFILE / COALESCING / MULTITHREADED / AUTO "
    "(ref GpuParquetScan.scala reader factory:1070).")

CBO_ENABLED = register(
    "spark.rapids.tpu.sql.optimizer.enabled", True,
    "Cost-based reversion of device subtrees (and whole small-input "
    "queries, which lose to the per-query dispatch+fetch floor on a "
    "tunneled TPU) to the host engine (ref CostBasedOptimizer.scala; "
    "floor model: plan/cost.py DEVICE_QUERY_FLOOR). ON by default since "
    "r3: the engine picks the faster engine per query; tests pin it off "
    "to keep device-path coverage.", commonly_used=True)

CPU_EXEC_COST_PER_ROW = register(
    "spark.rapids.tpu.sql.optimizer.cpu.exec.defaultRowCost", 2.0e-4,
    "CBO default CPU cost s/row (ref RapidsConf.scala:2133).", internal=True)

TPU_EXEC_COST_PER_ROW = register(
    "spark.rapids.tpu.sql.optimizer.tpu.exec.defaultRowCost", 1.0e-4,
    "CBO default TPU cost s/row (ref RapidsConf.scala:2149).", internal=True)

MEMORY_DEBUG = register(
    "spark.rapids.tpu.memory.debug", False,
    "Log every device allocation/free with the running footprint "
    "(ref spark.rapids.memory.gpu.debug=STDOUT, RapidsConf.scala:376).")

LEAK_DETECTION = register(
    "spark.rapids.tpu.memory.leakDetection", False,
    "Debug-mode allocation auditing: every SpillableBatch records its "
    "creation site, and TpuSession.close() raises if any device buffer "
    "registration is still live (ref cudf MemoryCleaner leak tracking at "
    "shutdown, Plugin.scala:573-588). The test suite runs with this "
    "effectively on via its per-test zero-leak fixture.")

METRICS_LEVEL = register(
    "spark.rapids.tpu.sql.metrics.level", "MODERATE",
    "DEBUG / MODERATE / ESSENTIAL metric verbosity (ref GpuExec.scala:54-165).")

STABLE_SORT = register(
    "spark.rapids.tpu.sql.stableSort.enabled", False,
    "Force stable device sorts (ref RapidsConf stableSort).")

IMPROVED_FLOAT_OPS = register(
    "spark.rapids.tpu.sql.improvedFloatOps.enabled", False,
    "Allow float aggregation orderings that can differ from CPU bit-for-bit.")

HAS_NANS = register(
    "spark.rapids.tpu.sql.hasNans", True,
    "Assume float columns may contain NaN (ref RapidsConf spark.rapids.sql.hasNans).")

UDF_COMPILER_ENABLED = register(
    "spark.rapids.tpu.sql.udfCompiler.enabled", False,
    "Translate Python UDF bytecode into columnar expressions at plan time "
    "(ref udf-compiler/, Plugin.scala:122-128).")

SPILL_DIR = register(
    "spark.rapids.tpu.memory.spillDir", "/tmp/srtpu_spill",
    "Directory for disk-tier spill files (ref RapidsDiskStore.scala:38).")

OOM_INJECTION = register(
    "spark.rapids.tpu.memory.oomInjection.mode", "NONE",
    "Test-only fault injection mode (ref RmmSpark.forceRetryOOM test hooks).",
    internal=True)

LORE_DUMP_PATH = register(
    "spark.rapids.tpu.sql.lore.dumpPath", "",
    "When set, operators tagged by lore ids dump input batches for offline "
    "replay (ref lore/GpuLore.scala).")

LORE_IDS = register(
    "spark.rapids.tpu.sql.lore.idsToDump", "",
    "Comma-separated lore ids to dump (ref GpuLore.tagForLore).")

PROFILE_PATH = register(
    "spark.rapids.tpu.profile.pathPrefix", "",
    "When set, capture XLA/TPU profiler traces to this path "
    "(ref profiler.scala ProfilerOnExecutor).")

DELTA_OPTIMIZE_WRITE_TARGET_ROWS = register(
    "spark.rapids.tpu.delta.optimizeWrite.targetRows", 1 << 20,
    "Target rows per output file when delta.autoOptimize.optimizeWrite is set "
    "on a table (ref GpuOptimizeWriteExchangeExec.scala); also the "
    "auto-compaction target size.")

DELTA_AUTO_COMPACT_MIN_FILES = register(
    "spark.rapids.tpu.delta.autoCompact.minNumFiles", 8,
    "Minimum number of sub-target-size files before post-commit "
    "auto-compaction folds them (ref delta autoCompact.minNumFiles).")

SHAPE_BUCKETS = register(
    "spark.rapids.tpu.sql.shapeBuckets", "1024,8192,65536,262144,1048576,4194304",
    "Row-count bucket ladder; batches pad up to the nearest bucket so each "
    "operator compiles once per bucket (TPU-specific, no reference analog — "
    "cudf is shape-dynamic, XLA is not).")

AGG_OPTIMISTIC_GROUPS = register(
    "spark.rapids.tpu.sql.agg.optimisticGroups", 4096,
    "Single-batch aggregations speculatively fetch final results sized "
    "for at most this many groups in ONE device round trip; more groups "
    "fall back to the classic multi-pass pipeline (TPU-specific: the "
    "fetch is the unit of cost on a tunneled backend).")

WINDOW_HOST_SINK_ROWS = register(
    "spark.rapids.tpu.window.hostSinkRowThreshold", 65536,
    "A terminal window exec whose input has at least this many rows runs "
    "its kernel on the host XLA backend instead of the device: the result "
    "is row-sized and heading to a host collect, so the D2H fetch — not "
    "compute — dominates on a tunneled TPU (measured 0.25-0.9 s per "
    "MB-scale fetch; docs/performance.md). Identical kernel, identical "
    "semantics; 0 disables (ref CostBasedOptimizer transition-cost "
    "reverts, RapidsConf.scala:2126).")

CPU_FALLBACK_ENABLED = register(
    "spark.rapids.tpu.sql.cpuFallback.enabled", True,
    "Allow per-operator CPU fallback (off = fail when a plan node is unsupported).")

TASK_TIMEOUT = register(
    "spark.rapids.tpu.task.semaphore.timeoutSeconds", 600,
    "Max seconds a task waits on the device semaphore before erroring.")

SEMAPHORE_WEDGE_TIMEOUT_MS = register(
    "spark.rapids.tpu.semaphore.wedgeTimeoutMs", 10000,
    "Wedge-watchdog horizon for the device semaphore: a task blocked in "
    "acquire() for this long wakes up, dumps a holder/waiter/held-bytes "
    "diagnostic, and force-releases permits whose holder THREAD is dead "
    "(a killed worker can no longer wedge every later query; counted by "
    "srtpu_semaphore_wedge_total). <= 0 disables the watchdog — waits "
    "block until task.semaphore.timeoutSeconds as before.")

QUERY_TIMEOUT = register(
    "spark.rapids.tpu.query.timeout", 0.0,
    "Whole-query deadline in seconds, enforced by cooperative "
    "cancellation: every operator checks the deadline at each produced "
    "batch (and semaphore waits poll it), so a timed-out query unwinds "
    "through the normal exception path — the device semaphore is "
    "released and every spillable batch is closed (the zero-leak audit "
    "holds). Raises QueryTimeout; counted by srtpu_query_timeout_total. "
    "0 disables (ref spark.sql.broadcastTimeout / spark.network.timeout "
    "query-level analogs).")


class TpuConf:
    """Immutable snapshot of raw key->string (or typed) settings.

    Reference: RapidsConf wraps SQLConf the same way; the driver serializes the
    conf map to executors (Plugin.scala:472) — here sessions pass TpuConf down
    the plan explicitly.
    """

    def __init__(self, raw: Optional[Dict[str, Any]] = None):
        self.raw = dict(raw or {})

    def with_settings(self, **kv) -> "TpuConf":
        new = dict(self.raw)
        for k, v in kv.items():
            new[k] = v
        return TpuConf(new)

    def set(self, key: str, value) -> "TpuConf":
        new = dict(self.raw)
        new[key] = value
        return TpuConf(new)

    def get(self, entry: ConfEntry):
        return entry.get(self)

    # convenience accessors mirroring RapidsConf's vals
    @property
    def sql_enabled(self) -> bool: return self.get(SQL_ENABLED)
    @property
    def explain(self) -> str: return str(self.get(EXPLAIN)).upper()
    @property
    def mode(self) -> str: return self.get(MODE)
    @property
    def concurrent_tpu_tasks(self) -> int: return self.get(CONCURRENT_TPU_TASKS)
    @property
    def batch_size_bytes(self) -> int: return self.get(BATCH_SIZE_BYTES)
    @property
    def batch_size_rows(self) -> int: return self.get(BATCH_SIZE_ROWS)
    @property
    def join_speculative_sizing(self) -> bool:
        return bool(self.get(JOIN_SPECULATIVE_SIZING))
    @property
    def join_subpartition_size_bytes(self) -> int:
        return self.get(JOIN_SUBPARTITION_SIZE)
    @property
    def shuffle_mode(self) -> str: return str(self.get(SHUFFLE_MODE)).upper()
    @property
    def is_explain_only(self) -> bool: return self.get(MODE) == "explainOnly"
    @property
    def shape_buckets(self):
        return [int(x) for x in str(self.get(SHAPE_BUCKETS)).split(",") if x]
    @property
    def cpu_fallback_enabled(self) -> bool: return self.get(CPU_FALLBACK_ENABLED)


DEFAULT = TpuConf()


def generate_docs() -> str:
    """Emit markdown config docs (ref RapidsConf.help() -> docs/configs.md)."""
    out = ["# spark-rapids-tpu configuration", "",
           "Name | Description | Default", "--- | --- | ---"]
    for e in all_entries():
        if e.internal:
            continue
        out.append(f"{e.key} | {e.doc} | {e.default}")
    return "\n".join(out) + "\n"
