"""Deterministic scalable data generation DSL (ref datagen/ module,
bigDataGen.scala + ScaleTestDataGen.scala: seed-stable correlated/skewed
multi-table generation for scale tests).

Design mirrors the reference's core ideas:
  * determinism by (seed, table, column, row): any row range of any column
    can be generated independently and reproducibly — generation scales out
    without coordination;
  * distributions: Flat (uniform), Normal, Exponential, Zipf (skew) over a
    configurable key cardinality;
  * correlated keys: a KeyGroup gives several tables columns drawn from the
    same key universe (the reference's correlated multi-table joins);
  * null ratios per column.
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["ColumnGen", "KeyGroup", "TableGen", "flat", "normal",
           "exponential", "zipf"]


def _rng_for(seed: int, table: str, column: str, start_row: int):
    h = hashlib.sha256(
        f"{seed}|{table}|{column}|{start_row}".encode()).digest()
    return np.random.Generator(np.random.PCG64(
        int.from_bytes(h[:8], "little")))


class _Dist:
    def __init__(self, kind: str, **kw):
        self.kind = kind
        self.kw = kw

    def sample(self, rng, n: int, cardinality: int) -> np.ndarray:
        if self.kind == "flat":
            return rng.integers(0, cardinality, size=n)
        if self.kind == "normal":
            v = rng.normal(cardinality / 2.0,
                           cardinality * self.kw.get("sigma", 0.15), size=n)
            return np.clip(v, 0, cardinality - 1).astype(np.int64)
        if self.kind == "exponential":
            v = rng.exponential(cardinality * self.kw.get("scale", 0.1),
                                size=n)
            return np.clip(v, 0, cardinality - 1).astype(np.int64)
        if self.kind == "zipf":
            a = self.kw.get("a", 1.5)
            v = rng.zipf(a, size=n) - 1
            return np.clip(v, 0, cardinality - 1).astype(np.int64)
        raise ValueError(self.kind)


def flat() -> _Dist:
    return _Dist("flat")


def normal(sigma: float = 0.15) -> _Dist:
    return _Dist("normal", sigma=sigma)


def exponential(scale: float = 0.1) -> _Dist:
    return _Dist("exponential", scale=scale)


def zipf(a: float = 1.5) -> _Dist:
    return _Dist("zipf", a=a)


class KeyGroup:
    """Shared key universe: columns in the group (possibly across tables)
    draw from the same `cardinality` keys via `mapping(key_ordinal)`, so
    joins across the tables hit (ref bigDataGen correlated key groups)."""

    def __init__(self, name: str, cardinality: int,
                 mapping: str = "identity", seed_salt: int = 0):
        self.name = name
        self.cardinality = cardinality
        self.mapping = mapping
        self.seed_salt = seed_salt

    def materialize(self, ordinals: np.ndarray) -> np.ndarray:
        if self.mapping == "identity":
            return ordinals.astype(np.int64)
        if self.mapping == "hashed":
            # spread ordinals over int64 deterministically
            x = ordinals.astype(np.uint64)
            x = (x ^ (x >> np.uint64(30))) * np.uint64(0xbf58476d1ce4e5b9)
            x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94d049bb133111eb)
            return (x ^ (x >> np.uint64(31))).view(np.int64)
        raise ValueError(self.mapping)


class ColumnGen:
    def __init__(self, dtype: str = "long",
                 dist: Optional[_Dist] = None,
                 cardinality: int = 1 << 31,
                 key_group: Optional[KeyGroup] = None,
                 null_ratio: float = 0.0,
                 lo: float = 0.0, hi: float = 1.0,
                 string_len: int = 12):
        self.dtype = dtype
        self.dist = dist or flat()
        self.cardinality = cardinality
        self.key_group = key_group
        self.null_ratio = null_ratio
        self.lo, self.hi = lo, hi
        self.string_len = string_len

    def generate(self, rng, n: int):
        import pyarrow as pa
        if self.key_group is not None:
            ords = self.dist.sample(rng, n, self.key_group.cardinality)
            vals = self.key_group.materialize(ords)
            arr = pa.array(vals, pa.int64())
        elif self.dtype in ("long", "int"):
            vals = self.dist.sample(rng, n, self.cardinality)
            arr = pa.array(vals.astype(
                np.int64 if self.dtype == "long" else np.int32))
        elif self.dtype == "double":
            vals = rng.random(n) * (self.hi - self.lo) + self.lo
            arr = pa.array(vals, pa.float64())
        elif self.dtype == "boolean":
            arr = pa.array(rng.random(n) < 0.5)
        elif self.dtype == "string":
            keys = self.dist.sample(rng, n, self.cardinality)
            arr = pa.array([f"k{int(k):0{self.string_len}d}" for k in keys])
        elif self.dtype == "date":
            days = self.dist.sample(rng, n, 20000)
            arr = pa.array(days.astype("datetime64[D]"))
        elif self.dtype == "timestamp":
            us = self.dist.sample(rng, n, 10**15)
            arr = pa.array(us.astype("datetime64[us]"))
        else:
            raise ValueError(self.dtype)
        if self.null_ratio > 0:
            mask = rng.random(n) < self.null_ratio
            import pyarrow.compute as pc
            arr = pc.if_else(pa.array(~mask), arr, pa.nulls(n, arr.type))
        return arr


class TableGen:
    #: fixed generation granule: every (table, column, granule) substream is
    #: independently seeded, so ANY requested row range reproduces the same
    #: values regardless of how the caller chunks the work (the reference's
    #: location-determined value contract, bigDataGen LocationToSeedMapping)
    GRANULE = 4096

    def __init__(self, name: str, rows: int,
                 columns: Dict[str, ColumnGen], seed: int = 0):
        self.name = name
        self.rows = rows
        self.columns = columns
        self.seed = seed

    def slice(self, start: int, n: int):
        """Arrow table for rows [start, start+n) — independently callable
        per range (the scale-out contract)."""
        import pyarrow as pa
        n = max(0, min(n, self.rows - start))
        g = self.GRANULE
        cols = {}
        for cname, gen in self.columns.items():
            parts = []
            pos = start
            end = start + n
            while pos < end:
                g_start = (pos // g) * g
                take_off = pos - g_start
                take_n = min(end - pos, g - take_off)
                rng = _rng_for(self.seed, self.name, cname, g_start)
                full = gen.generate(rng, min(g, self.rows - g_start))
                parts.append(full.slice(take_off, take_n))
                pos += take_n
            cols[cname] = (pa.concat_arrays([p.combine_chunks()
                                             if hasattr(p, "combine_chunks")
                                             else p for p in parts])
                          if parts else gen.generate(
                              _rng_for(self.seed, self.name, cname, 0), 0))
        return pa.table(cols)

    def to_table(self, chunk_rows: int = 1 << 20):
        import pyarrow as pa
        parts = [self.slice(off, chunk_rows)
                 for off in range(0, self.rows, chunk_rows)] or \
            [self.slice(0, 0)]
        return pa.concat_tables(parts)

    def write_parquet(self, path: str, files: int = 1) -> List[str]:
        import os

        import pyarrow.parquet as pq
        os.makedirs(path, exist_ok=True)
        per = -(-self.rows // files)
        out = []
        for i in range(files):
            t = self.slice(i * per, per)
            p = os.path.join(path, f"{self.name}-{i:05d}.parquet")
            pq.write_table(t, p)
            out.append(p)
        return out
