"""Delta Lake support (ref delta-lake/ module, ~35k LoC across
delta-20x..24x: GpuDeltaLog.scala, GpuOptimisticTransactionBase.scala,
GpuDeltaParquetFileFormat*.scala, GpuStatisticsCollection.scala,
GpuDeleteCommand.scala, GpuUpdateCommand.scala, GpuMergeIntoCommand.scala,
zorder/ZOrderRules.scala).

TPU-native re-design: the transaction log is pure host-side bookkeeping
(ported as idiomatic Python over the open Delta protocol), while the data
path — scans with file skipping + deletion-vector row filtering, rewrite
kernels for DELETE/UPDATE/MERGE, Z-order interleave — runs through the same
device exec/expression machinery as every other query.
"""
from .log import DeltaLog, Snapshot, AddFile, RemoveFile, Metadata
from .table import DeltaTable
from .zorder import InterleaveBits

__all__ = ["DeltaLog", "Snapshot", "AddFile", "RemoveFile", "Metadata",
           "DeltaTable", "InterleaveBits"]
