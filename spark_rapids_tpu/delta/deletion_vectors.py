"""Deletion vectors (ref GpuDeltaParquetFileFormatUtils.scala — DV scatter
onto the row mask; delta protocol "deletionVectors" table feature).

A DV marks deleted row positions of one data file as a RoaringBitmapArray
(64-bit positions bucketed by high-32 key into standard 32-bit roaring
bitmaps). Storage forms handled, per the protocol:
  * ``storageType=i`` — inline: z85-encoded bytes in the add action;
  * ``storageType=u`` / ``p`` — a DV file (uuid-derived or absolute path)
    whose payload is [size:int32-BE][magic:int32-LE=1681511377][data].

The 32-bit roaring container set implemented: array, bitmap, run — enough
to read DVs produced by delta-spark and by our own writer. Deleted
positions come back as a sorted numpy int64 array and are applied as a
device-side keep-mask on the scanned batch (the TPU analog of the
reference's scatter kernel).
"""
from __future__ import annotations

import os
import struct
import uuid
from typing import Dict, List, Optional

import numpy as np

__all__ = ["RoaringBitmapArray", "read_deletion_vector",
           "write_deletion_vector", "z85_encode", "z85_decode"]

_MAGIC = 1681511377

# ---------------------------------------------------------------------------
# z85 (ZeroMQ base85) — delta encodes inline DVs and DV file uuids with it
# ---------------------------------------------------------------------------
_Z85 = ("0123456789abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ"
        ".-:+=^!/*?&<>()[]{}@%$#")
_Z85_REV = {c: i for i, c in enumerate(_Z85)}


def z85_encode(data: bytes) -> str:
    assert len(data) % 4 == 0, "z85 needs 4-byte alignment"
    out = []
    for i in range(0, len(data), 4):
        v = struct.unpack(">I", data[i:i + 4])[0]
        chunk = []
        for _ in range(5):
            chunk.append(_Z85[v % 85])
            v //= 85
        out.extend(reversed(chunk))
    return "".join(out)


def z85_decode(s: str) -> bytes:
    assert len(s) % 5 == 0, "z85 needs 5-char alignment"
    out = bytearray()
    for i in range(0, len(s), 5):
        v = 0
        for c in s[i:i + 5]:
            v = v * 85 + _Z85_REV[c]
        out += struct.pack(">I", v)
    return bytes(out)


# ---------------------------------------------------------------------------
# 32-bit roaring bitmap (standard serialization) within a 64-bit array
# ---------------------------------------------------------------------------

_SERIAL_COOKIE_NO_RUN = 12346
_SERIAL_COOKIE = 12347


def _parse_rb32(buf: bytes, pos: int):
    """Parse one standard 32-bit roaring bitmap; return (uint32 array, pos)."""
    cookie = struct.unpack_from("<I", buf, pos)[0]
    has_run = (cookie & 0xFFFF) == _SERIAL_COOKIE
    if has_run:
        n_containers = (cookie >> 16) + 1
        pos += 4
        run_bytes = (n_containers + 7) // 8
        run_flags = buf[pos:pos + run_bytes]
        pos += run_bytes
    else:
        if cookie != _SERIAL_COOKIE_NO_RUN:
            raise ValueError(f"bad roaring cookie {cookie}")
        pos += 4
        n_containers = struct.unpack_from("<I", buf, pos)[0]
        pos += 4
        run_flags = b"\x00" * ((n_containers + 7) // 8)
    keys = np.zeros(n_containers, dtype=np.uint32)
    cards = np.zeros(n_containers, dtype=np.int64)
    for i in range(n_containers):
        k, c = struct.unpack_from("<HH", buf, pos)
        keys[i] = k
        cards[i] = c + 1
        pos += 4
    # offset header present when no-run or >=4 containers
    if not has_run or n_containers >= 4:
        pos += 4 * n_containers
    vals: List[np.ndarray] = []
    for i in range(n_containers):
        is_run = bool(run_flags[i // 8] & (1 << (i % 8)))
        if is_run:
            n_runs = struct.unpack_from("<H", buf, pos)[0]
            pos += 2
            runs = np.frombuffer(buf, dtype="<u2",
                                 count=2 * n_runs, offset=pos).reshape(-1, 2)
            pos += 4 * n_runs
            parts = [np.arange(int(s), int(s) + int(l) + 1, dtype=np.uint32)
                     for s, l in runs]
            lo = np.concatenate(parts) if parts else np.zeros(0, np.uint32)
        elif cards[i] <= 4096:
            lo = np.frombuffer(buf, dtype="<u2", count=int(cards[i]),
                               offset=pos).astype(np.uint32)
            pos += 2 * int(cards[i])
        else:
            words = np.frombuffer(buf, dtype="<u8", count=1024, offset=pos)
            pos += 8192
            bits = np.unpackbits(
                words.view(np.uint8), bitorder="little")
            lo = np.nonzero(bits)[0].astype(np.uint32)
        vals.append((np.uint32(keys[i]) << np.uint32(16)) | lo)
    arr = np.concatenate(vals) if vals else np.zeros(0, np.uint32)
    return arr, pos


def _serialize_rb32(values: np.ndarray) -> bytes:
    """Serialize uint32 values as a no-run 32-bit roaring bitmap (array and
    bitmap containers only — valid standard format)."""
    values = np.unique(values.astype(np.uint32))
    hi = (values >> np.uint32(16)).astype(np.uint16)
    lo = (values & np.uint32(0xFFFF)).astype(np.uint16)
    keys, starts = np.unique(hi, return_index=True)
    bounds = list(starts) + [len(values)]
    out = bytearray()
    out += struct.pack("<I", _SERIAL_COOKIE_NO_RUN)
    out += struct.pack("<I", len(keys))
    payloads = []
    for i, k in enumerate(keys):
        chunk = lo[bounds[i]:bounds[i + 1]]
        out += struct.pack("<HH", int(k), len(chunk) - 1)
        if len(chunk) <= 4096:
            payloads.append(chunk.astype("<u2").tobytes())
        else:
            bits = np.zeros(65536, dtype=np.uint8)
            bits[chunk] = 1
            payloads.append(np.packbits(bits, bitorder="little").tobytes())
    # offset header
    off = len(out) + 4 * len(keys)
    for p in payloads:
        out += struct.pack("<I", off)
        off += len(p)
    for p in payloads:
        out += p
    return bytes(out)


class RoaringBitmapArray:
    """64-bit positions as {high32 -> 32-bit roaring} (delta's
    RoaringBitmapArray portable serialization)."""

    @staticmethod
    def deserialize(buf: bytes) -> np.ndarray:
        magic = struct.unpack_from("<I", buf, 0)[0]
        if magic != _MAGIC:
            raise ValueError(f"bad DV magic {magic}")
        n = struct.unpack_from("<q", buf, 4)[0]
        pos = 12
        parts = []
        for i in range(n):
            vals32, pos = _parse_rb32(buf, pos)
            parts.append(vals32.astype(np.int64) | (np.int64(i) << 32))
        out = (np.concatenate(parts) if parts
               else np.zeros(0, dtype=np.int64))
        out.sort()
        return out

    @staticmethod
    def serialize(positions: np.ndarray) -> bytes:
        positions = np.unique(np.asarray(positions, dtype=np.int64))
        n_keys = int(positions[-1] >> 32) + 1 if len(positions) else 0
        out = bytearray(struct.pack("<Iq", _MAGIC, n_keys))
        for k in range(n_keys):
            sel = positions[(positions >> 32) == k]
            out += _serialize_rb32((sel & 0xFFFFFFFF).astype(np.uint32))
        return bytes(out)


# ---------------------------------------------------------------------------
# DV descriptor <-> storage
# ---------------------------------------------------------------------------

def read_deletion_vector(table_path: str, dv: dict) -> np.ndarray:
    """Deleted positions from an add action's deletionVector descriptor."""
    st = dv.get("storageType", "u")
    if st == "i":
        data = z85_decode(dv["pathOrInlineDv"])
        return RoaringBitmapArray.deserialize(data)
    if st == "u":
        enc = dv["pathOrInlineDv"]
        prefix, uid = enc[:-20], enc[-20:]
        u = uuid.UUID(bytes=z85_decode(uid))
        name = f"deletion_vector_{u}.bin"
        path = os.path.join(table_path, prefix, name) if prefix else \
            os.path.join(table_path, name)
    elif st == "p":
        path = dv["pathOrInlineDv"]
    else:
        raise ValueError(f"unknown DV storage type {st}")
    with open(path, "rb") as f:
        raw = f.read()
    off = dv.get("offset", 0) or 0
    size = struct.unpack_from(">i", raw, off)[0]
    return RoaringBitmapArray.deserialize(raw[off + 4:off + 4 + size])


def write_deletion_vector(table_path: str, positions: np.ndarray) -> dict:
    """Write a DV file; returns the deletionVector descriptor for the add
    action (uuid storage, protocol layout [size BE][payload][crc? omitted —
    readers use size])."""
    u = uuid.uuid4()
    payload = RoaringBitmapArray.serialize(positions)
    name = f"deletion_vector_{u}.bin"
    with open(os.path.join(table_path, name), "wb") as f:
        f.write(struct.pack(">i", len(payload)))
        f.write(payload)
    return {"storageType": "u",
            "pathOrInlineDv": z85_encode(u.bytes),
            "offset": 0, "sizeInBytes": len(payload),
            "cardinality": int(len(np.unique(positions)))}
