"""Delta transaction log (ref GpuDeltaLog.scala / delta-io protocol).

Log layout: ``<table>/_delta_log/%020d.json`` commits holding newline-
delimited action objects ({metaData, add, remove, protocol, commitInfo}),
parquet checkpoints every CHECKPOINT_INTERVAL commits plus a
``_last_checkpoint`` pointer. A Snapshot replays checkpoint + later commits
into the live file set (add - remove) and table metadata.
"""
from __future__ import annotations

import json
import os
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..types import (BINARY, BOOL, DATE, DataType, DecimalType, FLOAT32,
                     FLOAT64, INT16, INT32, INT64, INT8, STRING, TIMESTAMP,
                     Schema, StructField)

__all__ = ["DeltaLog", "Snapshot", "AddFile", "RemoveFile", "Metadata",
           "schema_from_delta_json", "schema_to_delta_json",
           "ConcurrentCommitException", "ConcurrentModificationException"]

CHECKPOINT_INTERVAL = 10


class ConcurrentCommitException(RuntimeError):
    """A concurrent writer won the race for this log version."""


class ConcurrentModificationException(RuntimeError):
    """The transaction's snapshot is stale and its actions cannot be
    safely replayed on top of the winning commits (ref delta-io
    ConcurrentModificationException family)."""

_PRIM = {
    "string": STRING, "long": INT64, "integer": INT32, "short": INT16,
    "byte": INT8, "float": FLOAT32, "double": FLOAT64, "boolean": BOOL,
    "binary": BINARY, "date": DATE, "timestamp": TIMESTAMP,
}
_PRIM_REV = {v.name: k for k, v in _PRIM.items()}


def schema_from_delta_json(j: dict) -> Schema:
    """Spark schema JSON ({"type":"struct","fields":[...]}) -> Schema."""
    fields = []
    for f in j["fields"]:
        t = f["type"]
        if isinstance(t, str):
            if t.startswith("decimal"):
                p, s = t[t.index("(") + 1:-1].split(",")
                dt: DataType = DecimalType(int(p), int(s))
            else:
                dt = _PRIM[t]
        else:
            raise ValueError(f"unsupported delta type {t}")
        fields.append(StructField(f["name"], dt, f.get("nullable", True)))
    return Schema(fields)


def schema_to_delta_json(schema: Schema) -> dict:
    fields = []
    for f in schema.fields:
        if isinstance(f.dtype, DecimalType):
            t = f"decimal({f.dtype.precision},{f.dtype.scale})"
        else:
            t = _PRIM_REV[f.dtype.name]
        fields.append({"name": f.name, "type": t,
                       "nullable": bool(f.nullable), "metadata": {}})
    return {"type": "struct", "fields": fields}


@dataclass
class AddFile:
    path: str
    size: int = 0
    partition_values: Dict[str, str] = field(default_factory=dict)
    modification_time: int = 0
    data_change: bool = True
    stats: Optional[str] = None          # JSON: numRecords/minValues/...
    deletion_vector: Optional[dict] = None

    def to_action(self) -> dict:
        a = {"path": self.path, "partitionValues": self.partition_values,
             "size": self.size, "modificationTime": self.modification_time,
             "dataChange": self.data_change}
        if self.stats:
            a["stats"] = self.stats
        if self.deletion_vector:
            a["deletionVector"] = self.deletion_vector
        return {"add": a}

    @staticmethod
    def from_action(a: dict) -> "AddFile":
        return AddFile(a["path"], a.get("size", 0),
                       a.get("partitionValues") or {},
                       a.get("modificationTime", 0),
                       a.get("dataChange", True), a.get("stats"),
                       a.get("deletionVector"))


@dataclass
class RemoveFile:
    path: str
    deletion_timestamp: int = 0
    data_change: bool = True

    def to_action(self) -> dict:
        return {"remove": {"path": self.path,
                           "deletionTimestamp": self.deletion_timestamp,
                           "dataChange": self.data_change}}


@dataclass
class Metadata:
    schema: Schema
    partition_columns: List[str] = field(default_factory=list)
    table_id: str = field(default_factory=lambda: str(uuid.uuid4()))
    name: Optional[str] = None
    configuration: Dict[str, str] = field(default_factory=dict)

    def to_action(self) -> dict:
        return {"metaData": {
            "id": self.table_id, "name": self.name,
            "format": {"provider": "parquet", "options": {}},
            "schemaString": json.dumps(schema_to_delta_json(self.schema)),
            "partitionColumns": self.partition_columns,
            "configuration": self.configuration,
            "createdTime": int(time.time() * 1000)}}

    @staticmethod
    def from_action(m: dict) -> "Metadata":
        return Metadata(
            schema=schema_from_delta_json(json.loads(m["schemaString"])),
            partition_columns=m.get("partitionColumns") or [],
            table_id=m.get("id", ""), name=m.get("name"),
            configuration=m.get("configuration") or {})


class Snapshot:
    """Materialized table state at a version (ref Snapshot in delta-io,
    consumed by GpuDeltaLog.update)."""

    def __init__(self, version: int, metadata: Optional[Metadata],
                 files: Dict[str, AddFile]):
        self.version = version
        self.metadata = metadata
        self.files = files             # path -> AddFile (live set)

    @property
    def schema(self) -> Schema:
        assert self.metadata is not None, "table has no metadata"
        return self.metadata.schema

    def file_paths(self, root: str) -> List[str]:
        return [os.path.join(root, f.path) for f in self.files.values()]


class DeltaLog:
    def __init__(self, table_path: str):
        self.table_path = table_path
        self.log_path = os.path.join(table_path, "_delta_log")

    # ----------------------------------------------------------- reading
    def version(self) -> int:
        """Latest committed version, -1 if the table does not exist."""
        if not os.path.isdir(self.log_path):
            return -1
        vs = [int(f[:20]) for f in os.listdir(self.log_path)
              if f.endswith(".json") and f[:20].isdigit()]
        return max(vs) if vs else -1

    def _checkpoint_start(self) -> tuple:
        """(version_after_checkpoint, metadata, files) from the newest
        checkpoint, or (0, None, {})."""
        lc = os.path.join(self.log_path, "_last_checkpoint")
        if not os.path.exists(lc):
            return 0, None, {}
        with open(lc) as f:
            ver = json.load(f)["version"]
        cp = os.path.join(self.log_path, f"{ver:020d}.checkpoint.parquet")
        import pyarrow.parquet as pq
        t = pq.read_table(cp)
        meta = None
        files: Dict[str, AddFile] = {}
        for row in t.to_pylist():
            action = json.loads(row["action"])
            if "metaData" in action:
                meta = Metadata.from_action(action["metaData"])
            elif "add" in action:
                af = AddFile.from_action(action["add"])
                files[af.path] = af
        return ver + 1, meta, files

    def snapshot(self, version: Optional[int] = None) -> Snapshot:
        latest = self.version()
        if latest < 0:
            raise FileNotFoundError(f"not a delta table: {self.table_path}")
        target = latest if version is None else version
        start, meta, files = 0, None, {}
        if version is None:
            start, meta, files = self._checkpoint_start()
            if start > target + 1:
                start, meta, files = 0, None, {}
        for v in range(start, target + 1):
            p = os.path.join(self.log_path, f"{v:020d}.json")
            with open(p) as f:
                for line in f:
                    if not line.strip():
                        continue
                    action = json.loads(line)
                    if "metaData" in action:
                        meta = Metadata.from_action(action["metaData"])
                    elif "add" in action:
                        af = AddFile.from_action(action["add"])
                        files[af.path] = af
                    elif "remove" in action:
                        files.pop(action["remove"]["path"], None)
        return Snapshot(target, meta, files)

    # ----------------------------------------------------------- writing
    def commit_with_retry(self, version: int, actions: List[dict],
                          op: str = "WRITE", max_retries: int = 10,
                          blind_append: Optional[bool] = None) -> int:
        """Optimistic-concurrency commit with conflict checking (ref
        delta-io OptimisticTransaction.checkForConflicts as driven by
        GpuOptimisticTransaction): on losing the version race, read the
        winning commits and decide —

          * our commit is a BLIND APPEND (adds only) and every winner
            only added data -> retry at the next version;
          * a winner changed metadata, removed files, or our commit
            removes/rewrites files (DML/OPTIMIZE) -> raise
            ConcurrentModificationException (the snapshot our actions
            were computed from is stale).

        ``blind_append``: callers that READ the table before writing
        (e.g. an insert-only MERGE, whose adds-only action shape LOOKS
        blind) must pass False — retrying would replay a decision made
        against a stale snapshot. None infers from the action shape,
        which is only valid for true append paths.

        Returns the version actually committed."""
        ours_blind = blind_append
        if ours_blind is None:
            ours_blind = not any("remove" in a or "metaData" in a
                                 for a in actions)
        for attempt in range(max_retries + 1):
            try:
                self.commit(version, actions, op)
                return version
            except ConcurrentCommitException:
                if not ours_blind:
                    raise ConcurrentModificationException(
                        f"{op} at version {version} conflicts with a "
                        "concurrent writer (stale snapshot)")
                winner = os.path.join(self.log_path,
                                      f"{version:020d}.json")
                with open(winner) as f:
                    their = [json.loads(line) for line in f
                             if line.strip()]
                # only PURE APPENDS commute: anything beyond add/
                # commitInfo (removes, metadata, protocol upgrades, ...)
                # invalidates our snapshot (delta-io treats
                # ProtocolChanged as a hard conflict too)
                if not all(set(a) <= {"add", "commitInfo"}
                           for a in their):
                    raise ConcurrentModificationException(
                        f"append at version {version} conflicts with a "
                        "concurrent non-append commit")
                version += 1          # both pure appends: commute
        raise ConcurrentModificationException(
            f"gave up after {max_retries} concurrent-commit retries")

    def commit(self, version: int, actions: List[dict],
               op: str = "WRITE") -> None:
        """Atomic create-if-absent commit (optimistic concurrency: a
        concurrent writer winning the rename makes this raise, ref
        GpuOptimisticTransactionBase commit protocol)."""
        os.makedirs(self.log_path, exist_ok=True)
        path = os.path.join(self.log_path, f"{version:020d}.json")
        tmp = path + f".{uuid.uuid4().hex}.tmp"
        info = {"commitInfo": {"timestamp": int(time.time() * 1000),
                               "operation": op,
                               "engineInfo": "spark-rapids-tpu"}}
        with open(tmp, "w") as f:
            for a in [info] + actions:
                f.write(json.dumps(a) + "\n")
        try:
            # O_EXCL-like: link fails if the version already exists
            os.link(tmp, path)
        except FileExistsError:
            raise ConcurrentCommitException(
                f"concurrent delta commit conflict at version {version}")
        finally:
            os.unlink(tmp)
        if version > 0 and version % CHECKPOINT_INTERVAL == 0:
            self._write_checkpoint(version)

    def _write_checkpoint(self, version: int) -> None:
        """Parquet checkpoint of the full state (ref delta checkpoints;
        the reference's GpuOptimisticTransaction defers to delta-io's)."""
        import pyarrow as pa
        import pyarrow.parquet as pq
        snap = self.snapshot(version)
        # one JSON action per row: sidesteps parquet's empty-struct limits;
        # the real delta checkpoint schema is struct-typed — interop with
        # foreign readers would need that layout (tracked as future work)
        rows = []
        if snap.metadata:
            rows.append({"action": json.dumps(snap.metadata.to_action())})
        for af in snap.files.values():
            rows.append({"action": json.dumps(af.to_action())})
        t = pa.Table.from_pylist(rows)
        cp = os.path.join(self.log_path,
                          f"{version:020d}.checkpoint.parquet")
        pq.write_table(t, cp)
        with open(os.path.join(self.log_path, "_last_checkpoint"), "w") as f:
            json.dump({"version": version, "size": len(rows)}, f)

    def history(self) -> List[dict]:
        """commitInfo per version, newest first (DeltaTable.history)."""
        out = []
        for v in range(self.version(), -1, -1):
            p = os.path.join(self.log_path, f"{v:020d}.json")
            if not os.path.exists(p):
                continue
            with open(p) as f:
                for line in f:
                    a = json.loads(line)
                    if "commitInfo" in a:
                        out.append({"version": v, **a["commitInfo"]})
                        break
        return out
