"""Delta scan (ref GpuDeltaParquetFileFormat*.scala): snapshot file listing
-> stats-based file skipping -> parquet decode -> deletion-vector row
filtering on device."""
from __future__ import annotations

import os
from typing import Iterator, List, Optional

import jax.numpy as jnp
import numpy as np

from ..columnar import ColumnarBatch, DeviceColumn
from ..config import TpuConf
from ..exec.base import ESSENTIAL, ExecContext
from ..exprs.compiler import _compact_kernel
from ..io.parquet import ParquetScanExec
from ..types import Schema
from .deletion_vectors import read_deletion_vector
from .log import DeltaLog, Snapshot
from .stats import file_matches

__all__ = ["DeltaScanExec", "attach_partition_columns"]


def attach_partition_columns(t, partition_values, schema):
    """Append the log-recorded partition values as typed constant columns
    (delta stores them as strings; null as None /
    __HIVE_DEFAULT_PARTITION__)."""
    import pyarrow as pa
    from ..types import to_arrow
    for col, val in partition_values.items():
        at = to_arrow(schema[col].dtype)
        if val is None or val == "__HIVE_DEFAULT_PARTITION__":
            arr = pa.nulls(t.num_rows, at)
        else:
            scalar = pa.scalar(val).cast(at)
            arr = pa.repeat(scalar, t.num_rows)
        t = t.append_column(col, arr)
    return t


def _partition_matches(partition_values, schema, predicate) -> bool:
    """Partition pruning: evaluate the predicate over a 1-row table of the
    file's partition values; strictly-False means skip. Predicates that
    reference non-partition columns fail to evaluate -> keep the file."""
    if not partition_values or predicate is None:
        return True
    import pyarrow as pa
    from ..columnar import ColumnarBatch
    try:
        t = attach_partition_columns(
            pa.table({"__r": pa.array([0])}), partition_values, schema
        ).drop_columns(["__r"])
        b = ColumnarBatch.from_arrow_host(t)
        m = predicate.eval_host(b)
        v = m[0].as_py() if len(m) else True
        return v is not False
    except Exception:
        return True


class DeltaScanExec(ParquetScanExec):
    """Parquet scan over a snapshot's live files with DV row filtering.
    The DV keep-mask application is the device analog of the reference's
    metadata-column scatter (GpuDeltaParquetFileFormatUtils.scala,
    ref metrics GpuExec.scala:88-89 deletionVector* timers)."""

    def __init__(self, table_path: str, snapshot: Snapshot,
                 columns: Optional[List[str]], conf: TpuConf,
                 predicate=None):
        self.table_path = table_path
        self.snapshot = snapshot
        schema = snapshot.schema if columns is None else \
            Schema([snapshot.schema[c] for c in columns])
        super().__init__([], schema, columns, conf, predicate)
        self._prune()

    def _prune(self):
        adds = list(self.snapshot.files.values())
        kept = [a for a in adds
                if file_matches(a.stats, self.predicate)
                and _partition_matches(a.partition_values,
                                       self.snapshot.schema,
                                       self.predicate)]
        self._skipped_files = len(adds) - len(kept)
        self._dv_by_path = {
            os.path.join(self.table_path, a.path): a.deletion_vector
            for a in kept if a.deletion_vector}
        # hive-partitioned files carry their partition VALUES in the log,
        # not in the parquet footer; the scan re-attaches them as constant
        # columns (ref GpuDeltaParquetFileFormat partition handling)
        self._pv_by_path = {
            os.path.join(self.table_path, a.path): a.partition_values
            for a in kept if a.partition_values}
        # log-recorded numRecords per file (None when stats absent): lets
        # the sharded scan bin-pack without opening parquet footers
        from .table import _file_rows
        self._rows_by_path = {
            os.path.join(self.table_path, a.path): _file_rows(a)
            for a in kept}
        self.paths = [os.path.join(self.table_path, a.path) for a in kept]
        self._empty = not self.paths
        # re-resolve AUTO now that the real path list is known (the base
        # resolved it against the pre-prune empty list)
        raw = str(self.conf.get(self.READER_TYPE_KEY)).upper()
        if raw == "AUTO":
            self.mode = "MULTITHREADED" if len(self.paths) > 1 else "PERFILE"
        else:
            self.mode = raw
        if self._dv_by_path and self.mode == "COALESCING":
            # coalesced batches lose their input_file identity, which the
            # DV lookup is keyed by; demote to the other multi-file mode
            self.mode = "MULTITHREADED"

    def set_predicate(self, pred) -> None:
        super().set_predicate(pred)
        self._prune()

    def collect_row_group_shards(self, n_shards: int):
        """Distributed sharded read with Delta semantics preserved: the
        reference applies the deletion-vector scatter inside the scan
        itself (GpuDeltaParquetFileFormatUtils.scala) so no execution
        path can skip it — this override is that guarantee for the
        row-group-sharded path. DV positions are file-absolute and
        partition values are per-file, so when either is present the
        shard unit is a whole FILE: each shard reads its files via
        ``_read_table`` (which attaches partition columns and reads
        DV-carrying files unpruned), then drops DV-deleted rows
        host-side before the shard table is encoded to devices."""
        if self._empty:
            return None
        if not self._dv_by_path and not self._pv_by_path:
            # plain parquet semantics: row-group sharding is safe
            return super().collect_row_group_shards(n_shards)
        import pyarrow as pa
        import pyarrow.parquet as pq
        from ..config import MULTITHREADED_READ_THREADS
        from ..io.parquet import _greedy_pack
        try:
            units = []                          # (rows, path)
            for path, rows in self._rows_by_path.items():
                if rows is None:   # no numRecords stat: footer fallback
                    rows = pq.ParquetFile(
                        self._cached_path(path)).metadata.num_rows
                units.append((rows, path))
        except Exception:
            return None
        bins = _greedy_pack(units, n_shards)
        want = self.columns or self.snapshot.schema.names()

        def read_bin(paths):
            if not paths:
                return None
            parts = []
            for path in paths:
                t = self._read_table(path).select(want)
                dv = self._dv_by_path.get(path)
                if dv is not None:
                    deleted = read_deletion_vector(self.table_path, dv)
                    deleted = deleted[deleted < t.num_rows]
                    if len(deleted):
                        keep = np.ones(t.num_rows, dtype=bool)
                        keep[deleted.astype(np.int64)] = False
                        t = t.filter(pa.array(keep))
                parts.append(t)
            return pa.concat_tables(parts) if len(parts) > 1 else parts[0]

        import concurrent.futures as cf
        nthreads = int(self.conf.get(MULTITHREADED_READ_THREADS))
        with cf.ThreadPoolExecutor(max_workers=max(nthreads, 1)) as pool:
            out = list(pool.map(read_bin, bins))
        empty = next(t for t in out if t is not None).schema.empty_table()
        return [t if t is not None else empty for t in out]

    def _read_table(self, path: str):
        pv = self._pv_by_path.get(path)
        if pv:
            import pyarrow.parquet as pq
            want = self.columns or self.snapshot.schema.names()
            file_cols = [c for c in want if c not in pv]
            t = pq.ParquetFile(self._cached_path(path)).read(
                columns=file_cols or None)
            t = attach_partition_columns(t, pv, self.snapshot.schema)
            return t.select(want)
        if path in self._dv_by_path:
            # DV positions are file-absolute: row-group pruning would shift
            # every subsequent row's offset and mis-apply the vector, so
            # read the whole file when one is attached
            import pyarrow.parquet as pq
            t = pq.ParquetFile(path).read(columns=self.columns)
            if self.columns:
                t = t.select(self.columns)
            return t
        return super()._read_table(path)

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        if self._empty:
            from ..exec.joins import _empty_batch
            yield _empty_batch(self._schema)
            return
        ctx.metric(self._exec_id, "filesSkipped").add(self._skipped_files)
        dv_rows = ctx.metric(self._exec_id, "deletionVectorRowsFiltered",
                             ESSENTIAL)
        for batch in super().do_execute(ctx):
            dv = self._dv_by_path.get((batch.meta or {}).get("input_file"))
            if dv is None:
                yield batch
                continue
            deleted = read_deletion_vector(self.table_path, dv)
            # batches may be slices of the file; offset arithmetic keyed by
            # emit order would need plumbing — the scan emits whole files
            # per batch unless batch_size_rows splits them; map positions
            # into this batch's [row_offset, row_offset+n) window
            off = (batch.meta or {}).get("row_offset", 0)
            sel = deleted[(deleted >= off) & (deleted < off + batch.num_rows)]
            if not len(sel):
                yield batch
                continue
            keep = np.ones(batch.padded_len, dtype=bool)
            keep[(sel - off).astype(np.int64)] = False
            keep[batch.num_rows:] = False
            arrays = [(c.data, c.validity) for c in batch.columns]
            with ctx.semaphore.held():
                outs, count = _compact_kernel(arrays, jnp.asarray(keep),
                                              batch.padded_len)
            cols = [DeviceColumn(d, v, c.dtype)
                    for (d, v), c in zip(outs, batch.columns)]
            dv_rows.add(batch.num_rows - int(count))
            yield ColumnarBatch(cols, int(count), batch.schema,
                                meta=batch.meta)

    def describe(self):
        return (f"DeltaScan[v{self.snapshot.version}, "
                f"{len(self.paths)} files (+{self._skipped_files} skipped)"
                + (f", pushdown={self.predicate.name_hint}"
                   if self.predicate else "") + "]")
