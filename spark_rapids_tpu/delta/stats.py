"""Per-file statistics collection on write (ref
GpuStatisticsCollection.scala — numRecords/minValues/maxValues/nullCount
computed on the device batch before it is written, used later for data
skipping in GpuDeltaParquetFileFormat scans)."""
from __future__ import annotations

import json
import math
from typing import Optional

__all__ = ["collect_stats", "file_matches"]


def _json_safe(v):
    import datetime

    import numpy as np
    if v is None:
        return None
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        f = float(v)
        return None if math.isnan(f) else f
    if isinstance(v, float) and math.isnan(v):
        return None
    if isinstance(v, (np.bool_,)):
        return bool(v)
    if isinstance(v, (datetime.date, datetime.datetime)):
        return v.isoformat()
    if isinstance(v, bytes):
        return None  # binary min/max not collected (matches delta)
    return v


def collect_stats(table) -> str:
    """Stats JSON for one written file from its Arrow table."""
    import pyarrow.compute as pc
    mins, maxs, nulls = {}, {}, {}
    for name in table.column_names:
        col = table.column(name)
        nulls[name] = col.null_count
        try:
            if col.length() - col.null_count > 0:
                mm = pc.min_max(col)
                mins[name] = _json_safe(mm["min"].as_py())
                maxs[name] = _json_safe(mm["max"].as_py())
        except Exception:
            pass  # non-orderable type: skip min/max, keep nullCount
    return json.dumps({"numRecords": table.num_rows, "minValues": mins,
                       "maxValues": maxs, "nullCount": nulls})


def file_matches(stats_json: Optional[str], pred) -> bool:
    """Conservative data skipping: False only when the predicate provably
    excludes every row of the file (ref delta data skipping consumed by the
    GPU scan). Reuses the parquet row-group interval logic."""
    if not stats_json or pred is None:
        return True
    try:
        st = json.loads(stats_json)
    except Exception:
        return True
    mins = st.get("minValues") or {}
    maxs = st.get("maxValues") or {}
    stats = {k: (mins[k], maxs[k]) for k in mins if k in maxs
             and mins[k] is not None and maxs[k] is not None}
    from ..io.parquet import _maybe_matches
    return _maybe_matches(pred, stats)
