"""DeltaTable: writes, DELETE / UPDATE / MERGE INTO, OPTIMIZE (+Z-order),
VACUUM, history (ref delta-24x/: GpuCreateDeltaTableCommand.scala,
GpuDeleteCommand.scala, GpuUpdateCommand.scala, GpuMergeIntoCommand.scala,
GpuOptimisticTransaction.scala; delta-lake/common GpuDeltaLog.scala).

Command shape follows the reference: identify touched files via the scan
(with stats skipping), rewrite or deletion-vector them, and commit
remove+add actions optimistically. Expression evaluation inside commands
uses the engine's host interpreters (commands are metadata-bound, not the
throughput path — same stance as the reference, whose MERGE planning runs
on the driver)."""
from __future__ import annotations

import json
import os
import time
import uuid
from typing import Dict, List, Optional

import numpy as np

from ..columnar import ColumnarBatch
from ..config import (DELTA_AUTO_COMPACT_MIN_FILES as AUTO_COMPACT_MIN_FILES,
                      DELTA_OPTIMIZE_WRITE_TARGET_ROWS
                      as OPTIMIZE_WRITE_TARGET_ROWS)
from ..exprs.base import Expression
from ..types import Schema
from .deletion_vectors import read_deletion_vector, write_deletion_vector
from .log import AddFile, DeltaLog, Metadata, RemoveFile
from .stats import collect_stats, file_matches

__all__ = ["DeltaTable", "write_delta"]


def _now_ms() -> int:
    return int(time.time() * 1000)


def _eval_predicate(pred: Expression, table) -> np.ndarray:
    """bool mask (nulls -> False) of pred over an Arrow table."""
    import pyarrow.compute as pc
    b = ColumnarBatch.from_arrow_host(table)
    mask = pc.fill_null(pred.eval_host(b), False)
    return np.asarray(mask.to_numpy(zero_copy_only=False), dtype=bool)


def _write_data_file(table_path: str, table,
                     partition_values: Optional[Dict[str, str]] = None
                     ) -> AddFile:
    import pyarrow.parquet as pq
    name = f"part-{uuid.uuid4().hex}.parquet"
    if partition_values:
        sub = "/".join(
            f"{k}={'__HIVE_DEFAULT_PARTITION__' if v is None else v}"
            for k, v in partition_values.items())
        os.makedirs(os.path.join(table_path, sub), exist_ok=True)
        name = f"{sub}/{name}"
    full = os.path.join(table_path, name)
    pq.write_table(table, full)
    return AddFile(name, size=os.path.getsize(full),
                   modification_time=_now_ms(), data_change=True,
                   stats=collect_stats(table),
                   partition_values=dict(partition_values or {}))


def write_delta(session, plan_df, path: str, mode: str = "overwrite",
                partition_by=()) -> None:
    """df.write_delta backend (ref GpuOptimisticTransaction write path +
    GpuStatisticsCollection); ``partition_by`` lays files out hive-style
    (col=value/ dirs) with the values recorded in each AddFile's
    partitionValues per the delta protocol."""
    from .constraints import check_invariants, fill_identity, identity_specs
    log = DeltaLog(path)
    version = log.version()
    data = plan_df.collect_arrow()
    os.makedirs(path, exist_ok=True)
    actions: List[dict] = []
    meta = None
    snap0 = log.snapshot() if version >= 0 else None
    old_meta = snap0.metadata if snap0 is not None else None
    existing_parts = list(old_meta.partition_columns) if old_meta else []
    if mode == "append" and old_meta is not None:
        part_cols = existing_parts
        if partition_by and list(partition_by) != existing_parts:
            raise ValueError(
                f"append partitioning {list(partition_by)} != "
                f"table partitioning {existing_parts}")
    else:
        # includes append-creates-table (version < 0): use the requested
        # layout, like Delta's saveAsTable with partitionBy
        part_cols = list(partition_by) if partition_by else existing_parts
    for c in part_cols:
        if c not in plan_df.schema.names():
            raise ValueError(f"partition column {c!r} not in dataframe")
    if version < 0 or mode == "overwrite":
        old_cfg = dict(old_meta.configuration) if old_meta else {}
        # reconcile config against the new schema: identity specs for
        # dropped columns would otherwise re-append phantom columns
        from .constraints import IDENTITY_PREFIX
        new_names = set(plan_df.schema.names())
        old_cfg = {k: v for k, v in old_cfg.items()
                   if not (k.startswith(IDENTITY_PREFIX)
                           and k[len(IDENTITY_PREFIX):] not in new_names)}
        meta = Metadata(schema=plan_df.schema, configuration=old_cfg,
                        partition_columns=part_cols,
                        **({"table_id": old_meta.table_id,
                            "name": old_meta.name} if old_meta else {}))
        schema, cfg = plan_df.schema, old_cfg
        if snap0 is not None and mode == "overwrite":
            actions += [RemoveFile(p, _now_ms()).to_action()
                        for p in snap0.files]
    elif mode == "append":
        # schema enforcement (delta writes validate against the committed
        # metadata — a mismatched append would corrupt every later scan)
        existing, cfg = snap0.schema, snap0.metadata.configuration
        new = plan_df.schema
        idents = set(identity_specs(cfg))
        got = [(f.name, f.dtype.name) for f in new.fields]
        want = [(f.name, f.dtype.name) for f in existing.fields
                if f.name not in idents or f.name in new.names()]
        if got != want:
            raise ValueError(
                f"delta append schema mismatch: table has {want}, "
                f"dataframe has {got}")
        schema = existing
    else:
        raise ValueError(f"unsupported delta write mode {mode}")
    data, new_cfg = fill_identity(data, schema, cfg)
    if new_cfg is not None:
        keep = meta if meta is not None else old_meta
        meta = Metadata(schema=schema, configuration=new_cfg,
                        table_id=keep.table_id, name=keep.name,
                        partition_columns=keep.partition_columns)
    if meta is not None:
        actions.insert(0, meta.to_action())
    check_invariants(session, schema, cfg, data)
    # optimize write (ref GpuOptimizeWriteExchangeExec): bin the output
    # into target-sized files instead of one arbitrary file per batch
    target = _optimize_write_target(session, cfg)
    for part_values, sub in _split_partitions(data, part_cols):
        if target and sub.num_rows > target:
            off = 0
            while off < sub.num_rows:
                actions.append(_write_data_file(
                    path, sub.slice(off, target),
                    part_values).to_action())
                off += target
        else:
            actions.append(
                _write_data_file(path, sub, part_values).to_action())
    # appends retry past concurrent pure-append winners; overwrites and
    # anything carrying metadata/removes abort on conflict
    log.commit_with_retry(version + 1, actions, op="WRITE")
    _maybe_auto_compact(session, path, cfg)


def _rewrite_file(table_path: str, table, src: AddFile,
                  part_cols) -> AddFile:
    """Rewrite of (part of) an existing file: keep the SOURCE file's
    partitionValues and drop the partition columns from the physical data
    (a compliant Delta reader derives them from partitionValues)."""
    if src.partition_values:
        keep = [c for c in table.column_names
                if c not in src.partition_values]
        table = table.select(keep)
    return _write_data_file(table_path, table, src.partition_values)


def _split_partitions(data, part_cols):
    """-> [(partition_values dict[str,str|None], table sans part cols)].
    Single empty-dict partition when the table is unpartitioned."""
    import pyarrow as pa
    import pyarrow.compute as pc
    if not part_cols:
        return [({}, data)]
    combos = (data.select(part_cols).group_by(part_cols).aggregate([])
              .to_pylist())
    out = []
    for row in combos:
        mask = None
        for k, v in row.items():
            cond = pc.is_null(data.column(k)) if v is None else \
                pc.equal(data.column(k), pa.scalar(v))
            mask = cond if mask is None else pc.and_(mask, cond)
        sub = data.filter(mask).drop_columns(part_cols)
        out.append(({k: (None if v is None else str(v))
                     for k, v in row.items()}, sub))
    return out


def _optimize_write_target(session, cfg: Dict[str, str]) -> int:
    if cfg.get("delta.autoOptimize.optimizeWrite", "").lower() != "true":
        return 0
    return int(OPTIMIZE_WRITE_TARGET_ROWS.get(session.conf))


def _maybe_auto_compact(session, path: str, cfg: Dict[str, str]) -> None:
    """Post-commit auto-compaction (ref delta autoCompact / the reference's
    auto-compaction support in GpuOptimisticTransaction): when enough small
    files accumulate, fold them into target-sized ones."""
    if cfg.get("delta.autoOptimize.autoCompact", "").lower() != "true":
        return
    import pyarrow as pa
    min_files = int(AUTO_COMPACT_MIN_FILES.get(session.conf))
    target = int(OPTIMIZE_WRITE_TARGET_ROWS.get(session.conf))
    dt = DeltaTable(session, path)
    snap = dt.log.snapshot()
    small = [a for a in snap.files.values()
             if _file_rows(a) is not None and _file_rows(a) < target]
    if len(small) < min_files:
        return
    # fold ONLY the small files into target-sized ones (dataChange=false:
    # compaction moves rows, it does not change them)
    merged = pa.concat_tables([dt._load_file(a, snap.schema)
                               for a in small])
    actions = [RemoveFile(a.path, _now_ms(), data_change=False).to_action()
               for a in small]
    for pv, sub in _split_partitions(merged,
                                     snap.metadata.partition_columns):
        off = 0
        while off < sub.num_rows:
            add = _write_data_file(path, sub.slice(off, target), pv)
            add.data_change = False
            actions.append(add.to_action())
            off += target
    dt.log.commit_with_retry(snap.version + 1, actions,
                             op="auto-OPTIMIZE")


def _file_rows(add: AddFile):
    if not add.stats:
        return None
    try:
        return int(json.loads(add.stats).get("numRecords"))
    except (ValueError, TypeError):
        return None


class DeltaTable:
    def __init__(self, session, path: str):
        self.session = session
        self.path = path
        self.log = DeltaLog(path)

    # ------------------------------------------------------------ reads
    def to_df(self, columns=None, version: Optional[int] = None):
        from ..api.dataframe import DataFrame
        from ..plan import logical as L
        snap = self.log.snapshot(version)
        return DataFrame(self.session,
                         _DeltaScanPlan(self.path, snap, columns))

    def history(self) -> List[dict]:
        return self.log.history()

    # ------------------------------------------------------- file rewrite
    def _load_file(self, add: AddFile, schema=None):
        """Arrow table of a live file with its DV already applied; hive
        partition values re-attach as typed constant columns. Pass the
        caller's snapshot schema — re-reading it here would replay the
        whole log once per file."""
        import pyarrow.parquet as pq
        # ParquetFile.read(), NOT read_table(): the dataset API would
        # infer hive partition columns from the col=value/ path segments
        # and duplicate the ones re-attached from partitionValues below
        t = pq.ParquetFile(os.path.join(self.path, add.path)).read()
        if add.partition_values:
            from .scan import attach_partition_columns
            schema = schema if schema is not None \
                else self.log.snapshot().schema
            t = attach_partition_columns(t, add.partition_values, schema)
            t = t.select(schema.names())
        if add.deletion_vector:
            deleted = read_deletion_vector(self.path, add.deletion_vector)
            keep = np.ones(t.num_rows, dtype=bool)
            keep[deleted[deleted < t.num_rows]] = False
            import pyarrow as pa
            t = t.filter(pa.array(keep))
        return t

    # ------------------------------------------------------------ DELETE
    def delete(self, condition: Optional[Expression] = None,
               use_deletion_vectors: bool = False) -> Dict[str, int]:
        """ref GpuDeleteCommand.scala: stats-skip untouched files, drop
        fully-deleted files, rewrite (or DV) partially-deleted ones."""
        snap = self.log.snapshot()
        schema = snap.schema
        actions: List[dict] = []
        deleted_rows = 0
        for add in snap.files.values():
            if condition is not None and not file_matches(add.stats,
                                                          condition):
                continue
            t = self._load_file(add, schema)
            mask = (_eval_predicate(condition, t) if condition is not None
                    else np.ones(t.num_rows, dtype=bool))
            n_del = int(mask.sum())
            if n_del == 0:
                continue
            deleted_rows += n_del
            actions.append(RemoveFile(add.path, _now_ms()).to_action())
            if n_del == t.num_rows:
                continue  # whole file gone
            if use_deletion_vectors and add.deletion_vector is None:
                # keep data file, attach a DV over deleted positions
                dv = write_deletion_vector(self.path,
                                           np.nonzero(mask)[0])
                new = AddFile(add.path, add.size, add.partition_values,
                              _now_ms(), True, add.stats, dv)
                actions.append(new.to_action())
            else:
                import pyarrow as pa
                kept = t.filter(pa.array(~mask))
                actions.append(_rewrite_file(self.path, kept, add,
                                             None).to_action())
        if actions:
            self.log.commit_with_retry(snap.version + 1, actions,
                                       op="DELETE")
        return {"num_deleted_rows": deleted_rows}

    # ------------------------------------------------------------ UPDATE
    def update(self, condition: Optional[Expression],
               assignments: Dict[str, Expression]) -> Dict[str, int]:
        """ref GpuUpdateCommand.scala."""
        import pyarrow as pa
        snap = self.log.snapshot()
        schema = snap.schema
        actions: List[dict] = []
        updated = 0
        for add in snap.files.values():
            if condition is not None and not file_matches(add.stats,
                                                          condition):
                continue
            t = self._load_file(add, schema)
            mask = (_eval_predicate(condition, t) if condition is not None
                    else np.ones(t.num_rows, dtype=bool))
            n_upd = int(mask.sum())
            if n_upd == 0:
                continue
            updated += n_upd
            b = ColumnarBatch.from_arrow_host(t)
            cols = {}
            for f in schema.fields:
                if f.name in assignments:
                    new_vals = assignments[f.name].eval_host(b)
                    old = t.column(f.name).combine_chunks()
                    m = pa.array(mask)
                    import pyarrow.compute as pc
                    cols[f.name] = pc.if_else(m, new_vals, old)
                else:
                    cols[f.name] = t.column(f.name)
            out = pa.table(cols)
            from .constraints import check_invariants
            check_invariants(self.session, schema,
                             snap.metadata.configuration, out)
            actions.append(RemoveFile(add.path, _now_ms()).to_action())
            actions.append(_rewrite_file(self.path, out, add,
                                         None).to_action())
        if actions:
            self.log.commit_with_retry(snap.version + 1, actions,
                                       op="UPDATE")
        return {"num_updated_rows": updated}

    # ------------------------------------------------------------- MERGE
    def merge(self, source, condition: Expression) -> "MergeBuilder":
        return MergeBuilder(self, source, condition)

    # ----------------------------------------------------------- OPTIMIZE
    # -- table evolution (constraints / identity / properties) -----------
    def _commit_metadata(self, schema, cfg, op: str) -> None:
        snap = self.log.snapshot()
        old = snap.metadata
        meta = Metadata(schema=schema,
                        partition_columns=old.partition_columns,
                        table_id=old.table_id, name=old.name,
                        configuration=cfg)
        self.log.commit_with_retry(snap.version + 1, [meta.to_action()],
                                   op=op)

    def add_check_constraint(self, name: str, expr: str) -> None:
        """ALTER TABLE ADD CONSTRAINT name CHECK (expr): existing rows are
        validated first (Spark/Delta semantics), then the constraint is
        committed and every future write enforces it
        (ref GpuCheckDeltaInvariant)."""
        from .constraints import CONSTRAINT_PREFIX, check_invariants
        snap = self.log.snapshot()
        cfg = dict(snap.metadata.configuration)
        cfg[CONSTRAINT_PREFIX + name] = expr
        check_invariants(self.session, snap.schema, cfg, self.to_df()
                         .collect_arrow())
        self._commit_metadata(snap.schema, cfg, "ADD CONSTRAINT")

    def drop_check_constraint(self, name: str) -> None:
        from .constraints import CONSTRAINT_PREFIX
        snap = self.log.snapshot()
        cfg = dict(snap.metadata.configuration)
        cfg.pop(CONSTRAINT_PREFIX + name, None)
        self._commit_metadata(snap.schema, cfg, "DROP CONSTRAINT")

    def set_nullable(self, column: str, nullable: bool) -> None:
        """ALTER COLUMN SET/DROP NOT NULL; tightening validates existing
        rows first."""
        from ..types import StructField
        from .constraints import InvariantViolation
        snap = self.log.snapshot()
        fields = []
        for f in snap.schema.fields:
            if f.name == column:
                if not nullable:
                    at = self.to_df().collect_arrow()
                    nulls = at.column(column).null_count
                    if nulls:
                        raise InvariantViolation(
                            f"cannot SET NOT NULL on {column!r}: "
                            f"{nulls} existing null value(s)")
                f = StructField(f.name, f.dtype, nullable)
            fields.append(f)
        self._commit_metadata(Schema(fields),
                              snap.metadata.configuration,
                              "CHANGE COLUMN")

    def add_identity_column(self, column: str, start: int = 1,
                            step: int = 1) -> None:
        """Declare an existing INT64 column GENERATED BY DEFAULT AS
        IDENTITY (ref GpuIdentityColumn): appends that omit the column (or
        leave it null) get values from the tracked high-water mark."""
        import json as _json
        from .constraints import IDENTITY_PREFIX
        if step == 0:
            raise ValueError("identity step must be non-zero")
        snap = self.log.snapshot()
        if column not in snap.schema.names():
            raise ValueError(f"no such column {column!r}")
        if snap.schema[column].dtype.name != "bigint":
            raise ValueError(
                f"identity column {column!r} must be BIGINT, is "
                f"{snap.schema[column].dtype.name} (Spark identity "
                "columns are always bigint)")
        cfg = dict(snap.metadata.configuration)
        cfg[IDENTITY_PREFIX + column] = _json.dumps(
            {"start": start, "step": step, "highWaterMark": None})
        self._commit_metadata(snap.schema, cfg, "CHANGE COLUMN")

    def set_properties(self, props: Dict[str, str]) -> None:
        """ALTER TABLE SET TBLPROPERTIES (e.g. delta.autoOptimize.*)."""
        snap = self.log.snapshot()
        cfg = dict(snap.metadata.configuration)
        cfg.update({k: str(v) for k, v in props.items()})
        self._commit_metadata(snap.schema, cfg, "SET TBLPROPERTIES")

    def optimize(self, target_file_rows: int = 1 << 20,
                 zorder_by: Optional[List[str]] = None) -> Dict[str, int]:
        """Compaction / Z-order rewrite (ref delta OPTIMIZE + ZOrderRules:
        sort by InterleaveBits of the cluster columns, rewrite files;
        dataChange=false actions)."""
        import pyarrow as pa
        snap = self.log.snapshot()
        if not snap.files:
            return {"files_removed": 0, "files_added": 0}
        tables = [self._load_file(a, snap.schema)
                  for a in snap.files.values()]
        big = pa.concat_tables(tables)
        if zorder_by:
            from ..api.dataframe import DataFrame
            from ..api import functions as F
            from .zorder import InterleaveBits
            from ..exprs import ColumnRef
            df = self.session.create_dataframe(big)
            z = InterleaveBits(*[ColumnRef(c) for c in zorder_by])
            df = df.with_column("__z", F.Col(z)).order_by(
                F.col("__z").asc()).drop("__z")
            big = df.collect_arrow()
        actions = [RemoveFile(a.path, _now_ms(), data_change=False)
                   .to_action() for a in snap.files.values()]
        added = 0
        pcols = snap.metadata.partition_columns
        for pv, sub in _split_partitions(big, pcols):
            for off in range(0, max(sub.num_rows, 1), target_file_rows):
                chunk = sub.slice(off, target_file_rows)
                af = _write_data_file(self.path, chunk, pv)
                af.data_change = False
                actions.append(af.to_action())
                added += 1
        self.log.commit_with_retry(
            snap.version + 1, actions,
            op="OPTIMIZE" if not zorder_by else "ZORDER")
        return {"files_removed": len(snap.files), "files_added": added}

    # ------------------------------------------------------------- VACUUM
    def vacuum(self, retention_hours: float = 168.0) -> List[str]:
        """Delete data files no longer referenced by the latest snapshot and
        older than the retention window."""
        snap = self.log.snapshot()
        live = set(snap.files)
        cutoff = time.time() - retention_hours * 3600
        removed = []
        for f in os.listdir(self.path):
            full = os.path.join(self.path, f)
            if (os.path.isfile(full) and f.endswith(".parquet")
                    and f not in live and os.path.getmtime(full) < cutoff):
                os.unlink(full)
                removed.append(f)
        return removed


class MergeBuilder:
    """MERGE INTO builder (ref GpuMergeIntoCommand.scala clause handling;
    low-shuffle variant GpuLowShuffleMergeCommand.scala is represented by
    the same single-pass implementation here — touched files only)."""

    def __init__(self, table: DeltaTable, source, condition: Expression):
        self.table = table
        self.source = source
        self.condition = condition
        self._matched_update: Optional[Dict[str, Expression]] = None
        self._matched_delete = False
        self._insert_values: Optional[Dict[str, Expression]] = None

    def when_matched_update(self, assignments: Dict[str, Expression]):
        self._matched_update = assignments
        return self

    def when_matched_delete(self):
        self._matched_delete = True
        return self

    def when_not_matched_insert(self,
                                values: Optional[Dict[str, Expression]] = None):
        self._insert_values = values if values is not None else {}
        return self

    def _equi_keys(self, schema, src):
        """[(target_col, source_col)] when the merge condition is a
        conjunction of column equalities, else None."""
        tnames = set(f.name for f in schema.fields)
        snames = set(src.column_names)

        def walk(e):
            from ..exprs import And, ColumnRef, EqualTo
            if isinstance(e, And):
                out = []
                for c in e.children:
                    k = walk(c)
                    if k is None:
                        return None
                    out.extend(k)
                return out
            if isinstance(e, EqualTo):
                l, r = e.children
                if isinstance(l, ColumnRef) and isinstance(r, ColumnRef):
                    if l.name in tnames and r.name in snames:
                        return [(l.name, r.name)]
                    if r.name in tnames and l.name in snames:
                        return [(r.name, l.name)]
            return None
        return walk(self.condition)

    def _prune_predicate(self, schema, src, keys):
        """Per-file skip predicate from the SOURCE keys' min/max: a
        target file whose key-column stats cannot overlap the source key
        range can neither match nor be rewritten — it is skipped without
        being READ (the low-shuffle property, ref
        GpuLowShuffleMergeCommand: only touched files rewrite)."""
        if not keys:
            return None
        import pyarrow.compute as pc
        from ..exprs import (And, ColumnRef, GreaterThanOrEqual,
                             LessThanOrEqual, Literal)
        pred = None
        for tk, sk in keys:
            col = src.column(sk)
            if col.length() == col.null_count:
                continue
            mm = pc.min_max(col)
            lo, hi = mm["min"].as_py(), mm["max"].as_py()
            if lo is None or hi is None:
                continue
            dt = schema[tk].dtype
            term = And(GreaterThanOrEqual(ColumnRef(tk), Literal(lo, dt)),
                       LessThanOrEqual(ColumnRef(tk), Literal(hi, dt)))
            pred = term if pred is None else And(pred, term)
        return pred

    def _candidate_pairs(self, tt, src, schema, keys):
        """(ti, si) candidate index pairs for the merge condition. Uses a
        hash join on any extractable equi-keys (the low-shuffle analog —
        ref GpuLowShuffleMergeCommand motivation) and only falls back to
        the cross product for pure theta conditions."""
        import pyarrow as pa
        n_t, n_s = tt.num_rows, src.num_rows
        if keys:
            kt = pa.table({f"__k{i}": tt.column(tk)
                           for i, (tk, _) in enumerate(keys)} |
                          {"__t": pa.array(np.arange(n_t))})
            ks = pa.table({f"__k{i}": src.column(sk)
                           for i, (_, sk) in enumerate(keys)} |
                          {"__s": pa.array(np.arange(n_s))})
            j = kt.join(ks, keys=[f"__k{i}" for i in range(len(keys))],
                        join_type="inner", coalesce_keys=True)
            return (j.column("__t").to_numpy().astype(np.int64),
                    j.column("__s").to_numpy().astype(np.int64))
        ti = np.repeat(np.arange(n_t), n_s)
        si = np.tile(np.arange(n_s), n_t)
        return ti, si

    def execute(self) -> Dict[str, int]:
        import pyarrow as pa
        import pyarrow.compute as pc
        t = self.table
        snap = t.log.snapshot()
        schema = snap.schema
        src = self.source.collect_arrow() if hasattr(self.source,
                                                     "collect_arrow") \
            else self.source
        stats = {"num_updated": 0, "num_deleted": 0, "num_inserted": 0,
                 "num_files_pruned": 0}
        actions: List[dict] = []
        src_matched = np.zeros(src.num_rows, dtype=bool)
        has_matched_clause = bool(self._matched_update) or \
            self._matched_delete
        keys = self._equi_keys(schema, src)
        prune_pred = self._prune_predicate(schema, src, keys)
        from .stats import file_matches
        for add in snap.files.values():
            if prune_pred is not None and not file_matches(add.stats,
                                                           prune_pred):
                # key ranges provably disjoint: untouched file, not read
                stats["num_files_pruned"] += 1
                continue
            tt = t._load_file(add, schema)
            n_t, n_s = tt.num_rows, src.num_rows
            if n_t == 0 or n_s == 0:
                continue
            ti, si = self._candidate_pairs(tt, src, schema, keys)
            if len(ti):
                pair = pa.Table.from_arrays(
                    list(tt.take(pa.array(ti)).columns) +
                    list(src.take(pa.array(si)).columns),
                    names=[f.name for f in schema.fields] + src.column_names)
                pb = ColumnarBatch.from_arrow_host(pair)
                m = np.asarray(pc.fill_null(self.condition.eval_host(pb),
                                            False)
                               .to_numpy(zero_copy_only=False), dtype=bool)
            else:
                m = np.zeros(0, dtype=bool)
            if not m.any():
                continue
            tm, sm = ti[m], si[m]
            src_matched[np.unique(sm)] = True
            if not has_matched_clause:
                # insert-only merge: matched target files stay untouched
                # and duplicate source matches are legal (delta semantics)
                continue
            # delta semantics: a target row matched by >1 source rows is an
            # error when a matched clause exists (ref MergeIntoCommand
            # multipleMatch check)
            if len(np.unique(tm)) != len(tm):
                raise ValueError(
                    "MERGE: target row matched by multiple source rows")
            row_matched = np.zeros(n_t, dtype=bool)
            row_matched[tm] = True
            actions.append(RemoveFile(add.path, _now_ms()).to_action())
            if self._matched_delete:
                stats["num_deleted"] += int(row_matched.sum())
                kept = tt.filter(pa.array(~row_matched))
                if kept.num_rows:
                    actions.append(
                        _rewrite_file(t.path, kept, add, None).to_action())
                continue
            # matched update: evaluate set-exprs over the matched pair rows
            out_cols = {}
            matched_pairs = pa.Table.from_arrays(
                list(tt.take(pa.array(tm)).columns) +
                list(src.take(pa.array(sm)).columns),
                names=[f.name for f in schema.fields] + src.column_names)
            mb = ColumnarBatch.from_arrow_host(matched_pairs)
            for f in schema.fields:
                col = tt.column(f.name).combine_chunks()
                if self._matched_update and f.name in self._matched_update:
                    new_vals = self._matched_update[f.name].eval_host(mb)
                    vals = col.to_pylist()
                    nv = new_vals.to_pylist()
                    for j, trow in enumerate(tm):
                        vals[int(trow)] = nv[j]
                    from ..types import to_arrow
                    col = pa.array(vals, type=to_arrow(f.dtype))
                out_cols[f.name] = col
            if self._matched_update:
                stats["num_updated"] += len(tm)
            new_content = pa.table(out_cols)
            from .constraints import check_invariants
            check_invariants(t.session, schema,
                             snap.metadata.configuration, new_content)
            actions.append(_rewrite_file(t.path, new_content, add, None)
                           .to_action())
        # not-matched inserts
        if self._insert_values is not None:
            unmatched = src.filter(pa.array(~src_matched))
            if unmatched.num_rows:
                ub = ColumnarBatch.from_arrow_host(unmatched)
                from ..types import to_arrow
                cols = {}
                for f in schema.fields:
                    if self._insert_values and f.name in self._insert_values:
                        cols[f.name] = self._insert_values[f.name].eval_host(ub)
                    elif f.name in unmatched.column_names:
                        cols[f.name] = unmatched.column(f.name).cast(
                            to_arrow(f.dtype))
                    else:
                        cols[f.name] = pa.nulls(unmatched.num_rows,
                                                to_arrow(f.dtype))
                ins = pa.table(cols)
                from .constraints import check_invariants, fill_identity
                ins, new_cfg = fill_identity(
                    ins, schema, snap.metadata.configuration)
                if new_cfg is not None:
                    old = snap.metadata
                    actions.append(Metadata(
                        schema=schema, configuration=new_cfg,
                        table_id=old.table_id, name=old.name,
                        partition_columns=old.partition_columns)
                        .to_action())
                check_invariants(t.session, schema,
                                 snap.metadata.configuration, ins)
                pcols = snap.metadata.partition_columns
                for pv, sub in _split_partitions(ins, pcols):
                    actions.append(
                        _write_data_file(t.path, sub, pv).to_action())
                stats["num_inserted"] = ins.num_rows
        if actions:
            # MERGE reads the table: even an insert-only merge (adds-only
            # action shape) must NOT retry as a blind append — the
            # not-matched determination is snapshot-dependent
            t.log.commit_with_retry(snap.version + 1, actions, op="MERGE",
                                    blind_append=False)
        return stats


class _DeltaScanPlan:
    """Logical plan node for a delta snapshot scan."""

    def __init__(self, table_path: str, snapshot, columns):
        self.table_path = table_path
        self.snapshot = snapshot
        self.columns = columns
        self.children = []

    def schema(self) -> Schema:
        if self.columns is None:
            return self.snapshot.schema
        return Schema([self.snapshot.schema[c] for c in self.columns])

    def describe(self):
        return f"DeltaScan[v{self.snapshot.version}]"

    def tree_string(self, indent: int = 0) -> str:
        return "  " * indent + self.describe() + "\n"


# planner registration (ref DeltaProvider rule injection)
from ..plan.meta import PlanMeta          # noqa: E402
from ..plan.overrides import rule         # noqa: E402


@rule(_DeltaScanPlan)
class _DeltaScanMeta(PlanMeta):
    def convert_to_tpu(self, children):
        from .scan import DeltaScanExec
        p = self.plan
        return DeltaScanExec(p.table_path, p.snapshot, p.columns, self.conf)

    convert_to_cpu = convert_to_tpu
