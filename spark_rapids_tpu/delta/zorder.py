"""Z-order clustering (ref zorder/ZOrderRules.scala + GpuInterleaveBits /
`ZOrder` JNI kernel; delta_zorder_test.py is the reference's test).

TPU-first: bit interleaving is pure integer shuffling — a fused vectorized
device kernel over int64 lanes. Each input column is first rank-normalized
to an unsigned value (sign-bit flip for ints — same total-order trick the
sort encoder uses), then up to 64/k bits per column are interleaved
round-robin, MSB first, into one int64 z-value whose sort order clusters
the space-filling curve.
"""
from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from ..exprs.base import DVal, EvalContext, Expression
from ..types import INT64, Schema, TypeSig, TypeEnum, _sig

__all__ = ["InterleaveBits"]


def _bits_per(k: int) -> int:
    # keep the z-value inside int64's positive range (bit 63 clear) so
    # plain signed ordering of the result is the curve order
    return 63 // k


class InterleaveBits(Expression):
    """interleave_bits(c1..ck) -> int64 z-value (ref GpuInterleaveBits).

    Each column is biased into [0, 2**bits_per) (order-preserving clamp of
    the signed value around 0 — Spark's kernel likewise treats inputs as
    fixed-width ints) and the low bits are interleaved LSB-first:
    z bit (i*k + j) = column j bit i."""

    device_type_sig = _sig(TypeEnum.BYTE, TypeEnum.SHORT, TypeEnum.INT,
                           TypeEnum.LONG, TypeEnum.DATE,
                           TypeEnum.TIMESTAMP, TypeEnum.BOOLEAN)

    def __init__(self, *children: Expression):
        self.children = list(children)

    def data_type(self, schema: Schema):
        return INT64

    def eval_device(self, ctx: EvalContext) -> DVal:
        cols = [c.eval_device(ctx) for c in self.children]
        k = len(cols)
        bp = _bits_per(k)
        bias = np.int64(1) << (bp - 1)
        z = jnp.zeros(cols[0].data.shape, dtype=jnp.uint64)
        for j, c in enumerate(cols):
            v = c.data.astype(jnp.int64)
            u = (jnp.clip(v, -bias, bias - 1) + bias).astype(jnp.uint64)
            for i in range(bp):
                bit = (u >> jnp.uint64(i)) & jnp.uint64(1)
                z = z | (bit << jnp.uint64(i * k + j))
        validity = cols[0].validity
        for c in cols[1:]:
            validity = jnp.logical_and(validity, c.validity)
        return DVal(z.astype(jnp.int64), validity, INT64)

    def eval_host(self, batch):
        import pyarrow as pa
        k = len(self.children)
        bp = _bits_per(k)
        bias = np.int64(1) << (bp - 1)
        arrays = []
        masks = []
        for c in self.children:
            a = c.eval_host(batch)
            vals = a.to_numpy(zero_copy_only=False)
            m = np.asarray(a.is_null())
            v = np.where(m, 0, np.nan_to_num(vals)).astype(np.int64)
            arrays.append((np.clip(v, -bias, bias - 1) + bias)
                          .astype(np.uint64))
            masks.append(m)
        z = np.zeros_like(arrays[0])
        for j, u in enumerate(arrays):
            for i in range(bp):
                bit = (u >> np.uint64(i)) & np.uint64(1)
                z |= bit << np.uint64(i * k + j)
        null = np.logical_or.reduce(masks)
        return pa.array(np.where(null, 0, z.view(np.int64)),
                        mask=null, type=pa.int64())

    def key(self):
        return "zorder(" + ",".join(c.key() for c in self.children) + ")"

    @property
    def name_hint(self):
        return "interleave_bits(" + ",".join(
            c.name_hint for c in self.children) + ")"
