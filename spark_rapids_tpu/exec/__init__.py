from .base import ExecContext, TpuExec, Metric
from .basic import (CoalesceBatchesExec, CpuFilterExec, CpuProjectExec,
                    InMemoryScanExec, LimitExec, TpuExpandExec, TpuFilterExec,
                    TpuProjectExec, TpuRangeExec, TpuSampleExec, UnionExec)
from .aggregate import CpuAggregateExec, TpuHashAggregateExec
from .sort import CpuSortExec, TpuSortExec

__all__ = ["ExecContext", "TpuExec", "Metric", "CoalesceBatchesExec",
           "CpuFilterExec", "CpuProjectExec", "InMemoryScanExec", "LimitExec",
           "TpuExpandExec", "TpuFilterExec", "TpuProjectExec", "TpuRangeExec",
           "TpuSampleExec", "UnionExec", "CpuAggregateExec",
           "TpuHashAggregateExec", "CpuSortExec", "TpuSortExec"]
