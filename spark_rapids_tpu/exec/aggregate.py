"""Hash-aggregate exec, TPU style.

Reference: GpuHashAggregateExec (GpuAggregateExec.scala:1776) — a 3-phase
pipeline: per-batch first-pass aggregation, merge passes over partial results
(GpuMergeAggregateIterator:718), finalize projection.

TPU-first divergence: the per-batch groupby is SORT-BASED (encode keys ->
one lax.sort -> segment boundaries -> jax.ops.segment_* reductions), all
static shapes, one fused XLA kernel per phase per shape bucket. cudf's hash
groupby has no XLA analog; sort+segments is the canonical accelerator-SQL
formulation for SPMD hardware. Merge uses the same kernel with each
aggregate's merge semantics — identical maths to the reference's merge pass.

Memory behaviour mirrors the reference: partial batches are Spillable, merge
runs under the retry framework, so injected/real RetryOOM spills and re-runs
(HashAggregateRetrySuite semantics).
"""
from __future__ import annotations

import functools
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import ColumnarBatch, DeviceColumn, HostColumn, concat_batches
from ..columnar.bucketing import bucket_for
from ..exprs.aggregates import AggregateExpression
from ..exprs.base import BoundReference, DVal, EvalContext, Expression
from ..mem import SpillableBatch, with_retry_no_split
from ..types import Schema, StructField
from .base import ESSENTIAL, ExecContext, TpuExec
from .groupby_core import segmented_groupby

__all__ = ["TpuHashAggregateExec", "CpuAggregateExec"]

_AGG_KERNEL_CACHE: Dict[Tuple, object] = {}


def _build_groupby_kernel(key_exprs: Sequence[Expression],
                          aggs: Sequence[AggregateExpression],
                          schema: Schema, mode: str,
                          partial_counts: Optional[List[int]] = None):
    """mode='update': key_exprs/agg inputs evaluated against input rows.
    mode='merge': schema is the partial schema [keys..., partials...] and
    aggs merge partial columns (referenced by ordinal; partial_counts gives
    how many partial columns each agg owns)."""
    dtypes = [f.dtype for f in schema.fields]
    num_keys = len(key_exprs)

    if mode == "update":
        value_exprs: List[List[Expression]] = [a.input_exprs() for a in aggs]
    else:
        # partial columns start after the keys, in agg order
        value_exprs = []
        ord_ = num_keys
        for a, n in zip(aggs, partial_counts):
            value_exprs.append([BoundReference(o, dtypes[o])
                                for o in range(ord_, ord_ + n)])
            ord_ += n

    @functools.partial(jax.jit, static_argnums=(2,))
    def kernel(cols, num_rows, padded_len):
        dvals = [None if c is None else DVal(c[0], c[1], dt)
                 for c, dt in zip(cols, dtypes)]
        ctx = EvalContext(schema, dvals, num_rows, padded_len)
        keys = [e.eval_device(ctx) for e in key_exprs]
        vals = [[e.eval_device(ctx) for e in exprs] for exprs in value_exprs]
        return segmented_groupby(keys, vals, aggs, mode, num_rows, padded_len)

    return kernel


def _get_kernel(key_exprs, aggs, schema, mode, partial_counts=None):
    key = (tuple(e.key() for e in key_exprs),
           tuple(a.key() for a in aggs),
           tuple((f.name, f.dtype.name) for f in schema.fields), mode)
    k = _AGG_KERNEL_CACHE.get(key)
    if k is None:
        k = _build_groupby_kernel(key_exprs, aggs, schema, mode,
                                  partial_counts)
        _AGG_KERNEL_CACHE[key] = k
    return k


class TpuHashAggregateExec(TpuExec):
    def __init__(self, groupings: Sequence[Expression],
                 aggs: Sequence[AggregateExpression], child: TpuExec):
        super().__init__([child])
        self.groupings = list(groupings)
        self.aggs = list(aggs)
        cs = child.output_schema()
        fields = [StructField(e.name_hint, e.data_type(cs), True)
                  for e in self.groupings]
        fields += [StructField(a.name_hint, a.data_type(cs), True)
                   for a in self.aggs]
        self._schema = Schema(fields)
        # partial (intermediate) schema: keys then each agg's partials
        pfields = [StructField(f"_k{i}", e.data_type(cs), True)
                   for i, e in enumerate(self.groupings)]
        self._partial_counts = []
        for ai, a in enumerate(self.aggs):
            pts = a.partial_types(cs)
            self._partial_counts.append(len(pts))
            for pi, pt in enumerate(pts):
                pfields.append(StructField(f"_a{ai}_{pi}", pt, True))
        self._partial_schema = Schema(pfields)

    def output_schema(self) -> Schema:
        return self._schema

    # ------------------------------------------------------------------
    def _run_kernel(self, kernel, batch: ColumnarBatch,
                    out_schema: Schema) -> ColumnarBatch:
        cols = []
        for c in batch.columns:
            if isinstance(c, DeviceColumn):
                cols.append((c.data, c.validity))
            else:
                cols.append(None)
        key_outs, partial_outs, num_groups = kernel(
            cols, jnp.int32(batch.num_rows), batch.padded_len)
        n = int(num_groups)
        # re-bucket: group count is usually orders of magnitude below the
        # input bucket; slicing keeps the merge pass (another sort) tiny
        target = bucket_for(n)
        out_cols = []
        for (d, v), f in zip(list(key_outs) + list(partial_outs),
                             out_schema.fields):
            if target < d.shape[0]:
                d, v = d[:target], v[:target]
            out_cols.append(DeviceColumn(d, v, f.dtype))
        return ColumnarBatch(out_cols, n, out_schema)

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        cs = self.children[0].output_schema()
        update_k = _get_kernel(self.groupings, self.aggs, cs, "update")
        rows_m = ctx.metric(self._exec_id, "numOutputRows", ESSENTIAL)

        partials: List[SpillableBatch] = []
        for batch in self.children[0].execute(ctx):
            def first_pass(b=batch):
                with ctx.semaphore.held():
                    pb = self._run_kernel(update_k, b, self._partial_schema)
                    return SpillableBatch(pb, ctx.memory)
            # idempotent over the input batch -> retry-safe
            partials.append(with_retry_no_split(first_pass, ctx.memory))

        total = sum(sb.device_bytes() for sb in partials)
        if (self.groupings and partials
                and total > ctx.conf.batch_size_bytes
                and self._repartitionable()):
            yield from self._repartitioned_merge(ctx, partials, total, rows_m)
            return

        merged = self._merge(ctx, partials)
        final = self._finalize(ctx, merged)
        rows_m.add(final.num_rows)
        yield final

    # -- re-partition fallback (ref GpuAggregateExec.scala:718-780: when the
    # merge target cannot fit, hash re-partition the partial batches by key
    # and merge each partition independently — group keys are disjoint
    # across partitions, so per-partition merge+finalize is exact) ---------
    #: distinct seed from shuffle partitioning (42) so a key-partitioned
    #: shuffle stage does not collapse all rows into one sub-partition
    REPARTITION_SEED = 1879048201

    def _repartitionable(self) -> bool:
        from ..exprs.hash_fns import device_hashable
        return not any(
            device_hashable.reason_not_supported(f.dtype)
            for f in self._partial_schema.fields[:len(self.groupings)])

    def _merge_kernel(self):
        merge_keys = [BoundReference(i, f.dtype) for i, f in
                      enumerate(self._partial_schema.fields[:len(self.groupings)])]
        merge_k = _get_kernel(merge_keys, self.aggs, self._partial_schema,
                              "merge", self._partial_counts)
        return merge_keys, merge_k

    def _repartitioned_merge(self, ctx: ExecContext, partials, total, rows_m
                             ) -> Iterator[ColumnarBatch]:
        from ..shuffle.partitioning import partition_batch, scatter_spillables
        merge_keys, merge_k = self._merge_kernel()
        n_parts = min(1 << max(1, (int(total) // ctx.conf.batch_size_bytes
                                   ).bit_length()), 64)
        ctx.metric(self._exec_id, "aggRepartitions").set(n_parts)
        slices = scatter_spillables(
            ctx, partials,
            lambda b: partition_batch(b, merge_keys, n_parts,
                                      seed=self.REPARTITION_SEED),
            n_parts)
        for p in range(n_parts):
            parts = slices[p]
            if not parts:
                continue

            def merge_part(parts=parts):
                with ctx.semaphore.held():
                    big = concat_batches([s.get() for s in parts])
                    return self._run_kernel(merge_k, big,
                                            self._partial_schema)
            merged = with_retry_no_split(merge_part, ctx.memory)
            for s in parts:
                s.close()
            final = self._finalize(ctx, merged)
            rows_m.add(final.num_rows)
            yield final

    # ------------------------------------------------------------------
    def _merge(self, ctx: ExecContext,
               partials: List[SpillableBatch]) -> ColumnarBatch:
        _, merge_k = self._merge_kernel()
        if not partials:
            # empty input: still one row for global agg, zero rows for grouped
            empty = ColumnarBatch.from_arrow(
                _empty_arrow(self._partial_schema))
            with ctx.semaphore.held():
                return self._run_kernel(merge_k, empty, self._partial_schema)

        def do_merge() -> ColumnarBatch:
            with ctx.semaphore.held():
                batches = [sb.get() for sb in partials]
                big = concat_batches(batches)
                return self._run_kernel(merge_k, big, self._partial_schema)

        out = with_retry_no_split(do_merge, ctx.memory)
        for sb in partials:
            sb.close()
        return out

    # ------------------------------------------------------------------
    def _finalize(self, ctx: ExecContext, merged: ColumnarBatch) -> ColumnarBatch:
        nkeys = len(self.groupings)
        out_cols: List[DeviceColumn] = list(merged.columns[:nkeys])
        ord_ = nkeys
        for ai, a in enumerate(self.aggs):
            n = self._partial_counts[ai]
            parts = [DVal(merged.columns[o].data, merged.columns[o].validity,
                          merged.columns[o].dtype)
                     for o in range(ord_, ord_ + n)]
            ord_ += n
            final = a.finalize(parts)
            out_cols.append(DeviceColumn(final.data, final.validity,
                                         self._schema.fields[nkeys + ai].dtype))
        return ColumnarBatch(out_cols, merged.num_rows, self._schema)

    def describe(self):
        g = ", ".join(e.name_hint for e in self.groupings)
        a = ", ".join(x.name_hint for x in self.aggs)
        return f"HashAggregate[keys=[{g}], aggs=[{a}]]"


def _empty_arrow(schema: Schema):
    import pyarrow as pa
    from ..types import to_arrow
    return pa.table({f.name: pa.array([], type=to_arrow(f.dtype))
                     for f in schema.fields})


class CpuAggregateExec(TpuExec):
    """Host fallback via pandas groupby (the CPU oracle for differential
    tests, playing the role CPU Spark plays for the reference)."""
    is_tpu = False

    def __init__(self, groupings, aggs, child: TpuExec):
        super().__init__([child])
        self.groupings = list(groupings)
        self.aggs = list(aggs)
        cs = child.output_schema()
        fields = [StructField(e.name_hint, e.data_type(cs), True)
                  for e in self.groupings]
        fields += [StructField(a.name_hint, a.data_type(cs), True)
                   for a in self.aggs]
        self._schema = Schema(fields)

    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        import pandas as pd
        import pyarrow as pa
        from ..exprs.aggregates import (Average, Count, CountStar, First,
                                        Last, Max, Min, StddevPop,
                                        StddevSamp, Sum, VariancePop,
                                        VarianceSamp)
        tables = [b.to_arrow() for b in self.children[0].execute(ctx)]
        if tables:
            df = pa.concat_tables(tables).to_pandas()
        else:
            df = _empty_arrow(self.children[0].output_schema()).to_pandas()

        # evaluate key + input expressions into temp columns
        work = pd.DataFrame(index=df.index)
        src = ColumnarBatch.from_pandas(df) if len(df) else None
        key_names = []
        for i, g in enumerate(self.groupings):
            col = f"_k{i}"
            work[col] = _host_series(g, df, src)
            key_names.append(col)
        in_names = []
        for i, a in enumerate(self.aggs):
            col = f"_a{i}"
            if isinstance(a, CountStar):
                work[col] = 1
            else:
                work[col] = _host_series(a.child, df, src)
            in_names.append(col)

        def agg_series(a, s: "pd.Series"):
            if isinstance(a, CountStar):
                return len(s)
            if isinstance(a, Count):
                return s.count()
            if isinstance(a, Sum):
                return s.sum(min_count=1)
            if isinstance(a, Min):
                return s.min()
            if isinstance(a, Max):
                return s.max()
            if isinstance(a, Average):
                return s.mean()
            if isinstance(a, First):
                nn = s.dropna()
                return nn.iloc[0] if len(nn) else None
            if isinstance(a, Last):
                nn = s.dropna()
                return nn.iloc[-1] if len(nn) else None
            if isinstance(a, StddevSamp):
                return s.std(ddof=1)
            if isinstance(a, StddevPop):
                return s.std(ddof=0)
            if isinstance(a, VarianceSamp):
                return s.var(ddof=1)
            if isinstance(a, VariancePop):
                return s.var(ddof=0)
            raise NotImplementedError(type(a).__name__)

        if self.groupings:
            grouped = work.groupby(key_names, dropna=False, sort=False)
            rows = []
            for key, sub in grouped:
                if not isinstance(key, tuple):
                    key = (key,)
                rows.append(list(key) + [agg_series(a, sub[c])
                                         for a, c in zip(self.aggs, in_names)])
            out = pd.DataFrame(rows, columns=self._schema.names())
        else:
            vals = [agg_series(a, work[c])
                    for a, c in zip(self.aggs, in_names)]
            out = pd.DataFrame([vals], columns=self._schema.names())
        # coerce to declared output types
        from ..types import to_arrow as _toa
        arrays = []
        for f in self._schema.fields:
            vals = [None if pd.isna(x) else x for x in out[f.name].tolist()]
            arrays.append(pa.array(vals, type=_toa(f.dtype)))
        table = pa.Table.from_arrays(arrays, names=self._schema.names())
        yield ColumnarBatch.from_arrow(table)

    def describe(self):
        g = ", ".join(e.name_hint for e in self.groupings)
        a = ", ".join(x.name_hint for x in self.aggs)
        return f"CpuAggregate[keys=[{g}], aggs=[{a}]]"


def _host_series(expr: Expression, df, src_batch):
    """Evaluate an expression to a pandas Series on the host."""
    import pandas as pd
    if src_batch is None:
        return pd.Series([], dtype="float64")
    return expr.eval_host(src_batch).to_pandas()
