"""Hash-aggregate exec, TPU style.

Reference: GpuHashAggregateExec (GpuAggregateExec.scala:1776) — a 3-phase
pipeline: per-batch first-pass aggregation, merge passes over partial results
(GpuMergeAggregateIterator:718), finalize projection.

TPU-first divergence: the per-batch groupby avoids scatter/gather entirely
(they serialize on the TPU scalar core). Dictionary-coded keys with a small
cardinality product take the direct-addressing kernel (dense one-hot
broadcast+reduce over a bucketed static group count); everything else takes
the sort pipeline in groupby_core (one variadic lax.sort carrying payloads,
segmented scans, one compaction sort), all static shapes, one fused XLA
kernel per phase per shape bucket. Merge uses the same kernels with each
aggregate's merge semantics — identical maths to the reference's merge pass.

Memory behaviour mirrors the reference: partial batches are Spillable, merge
runs under the retry framework, so injected/real RetryOOM spills and re-runs
(HashAggregateRetrySuite semantics).
"""
from __future__ import annotations

import functools
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import ColumnarBatch, DeviceColumn, HostColumn, concat_batches
from ..columnar.bucketing import bucket_for
from ..exprs.aggregates import AggregateExpression
from ..exprs.base import (BoundReference, DVal, EvalContext, Expression,
                          collect_param_literals, literal_scalars,
                          literal_slot_map, parameterized_keys)
from ..mem import SpillableBatch, with_retry_no_split
from ..types import STRING, Schema, StructField
from .base import ESSENTIAL, ExecContext, TpuExec
from .groupby_core import segmented_groupby

__all__ = ["TpuHashAggregateExec", "CpuAggregateExec"]

_AGG_KERNEL_CACHE: Dict[Tuple, object] = {}
#: last observed group count per kernel shape: the optimistic single-
#: fetch attempt is skipped while the statistic exceeds the bound and
#: refreshed on every execution, so it adapts back when the data changes
#: (the aggregate analog of the joins' _TOTAL_STATS sizing)
_FAST_GROUPS: Dict[Tuple, int] = {}


def _build_groupby_kernel(key_exprs: Sequence[Expression],
                          aggs: Sequence[AggregateExpression],
                          schema: Schema, mode: str,
                          partial_counts: Optional[List[int]] = None,
                          in_schema: Optional[Schema] = None,
                          stages: Optional[list] = None,
                          n_codes: int = 0):
    """mode='update': key_exprs/agg inputs evaluated against ``schema``
    (the eval schema + appended __gk code columns). When ``stages`` is
    given, the kernel first applies the FUSED pre-stages — ("filter",
    cond) / ("project", exprs, out_schema) — starting from ``in_schema``
    (the actual child exec's schema): the scan→filter→project→groupby
    pipeline becomes ONE XLA computation with a row mask instead of a
    separate compaction kernel per stage, eliminating per-stage host
    syncs (each costs a full round trip on a tunneled TPU).
    mode='merge': schema is the partial schema [keys..., partials...] and
    aggs merge partial columns (referenced by ordinal; partial_counts gives
    how many partial columns each agg owns)."""
    dtypes = [f.dtype for f in schema.fields]
    num_keys = len(key_exprs)
    base_schema = in_schema if in_schema is not None else None
    base_dtypes = ([f.dtype for f in base_schema.fields]
                   if base_schema is not None else None)

    if mode == "update":
        value_exprs: List[List[Expression]] = [a.input_exprs() for a in aggs]
    else:
        # partial columns start after the keys, in agg order
        value_exprs = []
        ord_ = num_keys
        for a, n in zip(aggs, partial_counts):
            value_exprs.append([BoundReference(o, dtypes[o])
                                for o in range(ord_, ord_ + n)])
            ord_ += n

    from ..types import INT32
    slots = literal_slot_map(_param_exprs(
        key_exprs, aggs, mode, stages,
        value_exprs=value_exprs if mode == "update" else None))

    def prep(cols, num_rows, padded_len, scalars):
        """Shared traced prologue: pre-stages + key/value evaluation."""
        keep = None
        from ..exprs.base import StrVal
        if base_schema is not None:
            n_base = len(base_dtypes)
            base = [None if c is None
                    else (DVal(StrVal(c[0], c[2]), c[1], dt)
                          if len(c) == 3 else DVal(c[0], c[1], dt))
                    for c, dt in zip(cols[:n_base], base_dtypes)]
            codes = [DVal(c[0], c[1], INT32) for c in cols[n_base:]]
            sctx, keep = _apply_pre_stages(stages, base_schema, base,
                                           num_rows, padded_len,
                                           scalars, slots)
            dvals = list(sctx.columns) + codes
            # schema = eval schema + __gk fields; pad dvals to match
            dvals = dvals[:len(dtypes)] + [None] * (len(dtypes) - len(dvals))
            ctx = EvalContext(schema, dvals, num_rows, padded_len,
                              scalars, slots)
        else:
            dvals = [None if c is None
                     else (DVal(StrVal(c[0], c[2]), c[1], dt)
                           if len(c) == 3 else DVal(c[0], c[1], dt))
                     for c, dt in zip(cols, dtypes)]
            ctx = EvalContext(schema, dvals, num_rows, padded_len,
                              scalars, slots)
        keys = [e.eval_device(ctx) for e in key_exprs]
        vals = [[e.eval_device(ctx) for e in exprs] for exprs in value_exprs]
        return keys, vals, keep

    @functools.partial(jax.jit, static_argnums=(2,))
    def kernel(cols, num_rows, padded_len, scalars=()):
        keys, vals, keep = prep(cols, num_rows, padded_len, scalars)
        return segmented_groupby(keys, vals, aggs, mode, num_rows,
                                 padded_len, row_mask=keep)

    kernel.n_param_slots = len(slots)
    kernel._prep = prep
    kernel._value_exprs = value_exprs
    kernel.n_dispatches = 1      # one fused module per batch
    return kernel


def _build_groupby_kernel_split(key_exprs, aggs, schema, mode,
                                partial_counts=None, in_schema=None,
                                stages=None, n_codes=0):
    """The same groupby as _build_groupby_kernel but run as THREE
    separately-jitted dispatches (prologue+sort / scans / compaction
    sort). Identical maths — the stages are groupby_core's own pieces —
    but each XLA module is small: on this backend a lax.sort's compile
    time multiplies with surrounding module complexity (the fused two-key
    merge kernel never finished compiling in >20 min; split stages total
    ~1 min). Used on the classic multi-batch/merge path where the extra
    ~2 dispatch round trips are amortized per QUERY, not per batch-row;
    the fused form remains for the single-batch fast path and shard_map
    fragments (dispatch count dominates there)."""
    from .groupby_core import stage_scan
    fused = _build_groupby_kernel(key_exprs, aggs, schema, mode,
                                  partial_counts, in_schema, stages,
                                  n_codes)
    if not key_exprs:
        return fused         # global path has no sort — fused is cheap
    prep = fused._prep
    value_exprs = fused._value_exprs
    key_dtypes = [e.data_type(schema) for e in key_exprs]
    val_dtypes = [[e.data_type(schema) for e in exprs]
                  for exprs in value_exprs]

    from .encoding import grouping_operands

    # Sort operand budget: every operand in the variadic sort costs
    # compile time, so the split path carries the MINIMUM. Keys whose
    # grouping encoding is the standard (null_rank, key) pair are NOT
    # duplicated as payload — k_scan reconstructs (data, validity) from
    # the sorted operands themselves (validity = rank==0; data =
    # operand cast back, canonicalized for floats — the
    # NormalizeFloatingNumbers semantics grouping already applies). The
    # original-row-index payload rides only when an order-dependent
    # aggregate (First/Last) needs it.
    from ..exprs.aggregates import First, Last
    from ..exprs.base import StrVal

    def _reconstructible(dt):
        if dt == STRING:
            return True          # rect: words + length operands suffice
        if dt.np_dtype is None:
            return False         # decimal etc.: carried as payload lanes
        import numpy as _np
        shapes = jax.eval_shape(
            lambda d, v: tuple(grouping_operands(DVal(d, v, dt))),
            jax.ShapeDtypeStruct((1,), dt.np_dtype),
            jax.ShapeDtypeStruct((1,), _np.bool_))
        return len(shapes) == 2

    recon = [_reconstructible(dt) for dt in key_dtypes]
    needs_rank = any(isinstance(a, (First, Last)) for a in aggs)

    @functools.partial(jax.jit, static_argnums=(2,))
    def k_prep(cols, num_rows, padded_len, scalars=()):
        """Prologue + key encoding ONLY — no sort. A lax.sort's compile
        time multiplies with everything else in its module (a fused
        filter/CASE prologue pushed the q28 update sort past 15 minutes),
        so the sort gets a module to itself with raw operands. Key ops
        come back as a NESTED per-key tuple (arities vary: scalar keys
        two operands, byte-rectangle strings 2 + W/8)."""
        keys, vals, keep = prep(cols, num_rows, padded_len, scalars)
        if keep is None:
            keep = jnp.arange(padded_len, dtype=jnp.int32) < num_rows
        pad_flag = jnp.where(keep, jnp.uint8(0), jnp.uint8(1))
        key_ops = tuple(tuple(grouping_operands(k)) for k in keys)
        payload = []
        if needs_rank:
            payload.append(jnp.arange(padded_len, dtype=jnp.int32))
        for k, r in zip(keys, recon):
            if not r:
                payload.extend((k.data, k.validity))
        for vs in vals:
            for v in vs:
                payload.extend((v.data, v.validity))
        live = jnp.sum(keep).astype(jnp.int32)
        return (pad_flag, key_ops, tuple(payload)), live

    _sort_jits = {}

    def k_sort(flat, nk):
        """The bare variadic sort — nothing else in the module."""
        fn = _sort_jits.get(nk)
        if fn is None:
            def mk(flat, nk=nk):
                return jax.lax.sort(tuple(flat), num_keys=nk,
                                    is_stable=True)
            fn = _sort_jits[nk] = jax.jit(mk)
        return fn(flat)

    @functools.partial(jax.jit, static_argnums=(1, 2))
    def k_scan(flat, arities, padded_len, live):
        it = iter(flat)
        s_ops = [next(it) for _ in range(1 + sum(arities))]
        perm = next(it) if needs_rank else None
        s_keys = []
        pos = 1
        for ar, dt, r in zip(arities, key_dtypes, recon):
            ops = s_ops[pos:pos + ar]
            pos += ar
            if not r:
                s_keys.append(DVal(next(it), next(it), dt))
            elif dt == STRING:
                from ..columnar.strrect import unpack_words
                rank, words, ln = ops[0], ops[1:-1], ops[-1]
                s_keys.append(DVal(
                    StrVal(unpack_words(list(words), 8 * len(words)),
                           ln.astype(jnp.int32)),
                    rank == 0, dt))
            else:
                rank, keyop = ops
                s_keys.append(DVal(keyop.astype(dt.np_dtype), rank == 0,
                                   dt))
        sorted_vals = [[DVal(next(it), next(it), dt) for dt in dts]
                       for dts in val_dtypes]
        ckey, carry, num_groups = stage_scan(
            aggs, mode, s_ops, perm, s_keys, sorted_vals, live,
            padded_len)
        return ckey, carry, num_groups

    @functools.partial(jax.jit, static_argnums=(2,))
    def k_pack(ckey, carry, padded_len, num_groups):
        """The compaction sort + nested rebuild (stage_pack), its own
        module."""
        from .groupby_core import stage_pack
        return stage_pack(ckey, carry, num_groups, key_dtypes,
                          padded_len)

    def kernel(cols, num_rows, padded_len, scalars=()):
        (pad_flag, key_ops, payload), live = k_prep(
            cols, num_rows, padded_len, scalars)
        arities = tuple(len(g) for g in key_ops)
        flat = [pad_flag]
        for g in key_ops:
            flat.extend(g)
        flat.extend(payload)
        sorted_all = k_sort(tuple(flat), 1 + sum(arities))
        ckey, carry, ng = k_scan(tuple(sorted_all), arities, padded_len,
                                 live)
        key_outs, partial_outs, _ = k_pack(ckey, carry, padded_len, ng)
        return list(key_outs), list(partial_outs), ng

    kernel.n_param_slots = fused.n_param_slots
    kernel.n_dispatches = 4      # prep + sort + scan + pack modules
    return kernel


def _apply_pre_stages(stages, in_schema, base_dvals, num_rows, padded_len,
                      scalars=None, slots=None):
    """Trace the fused ("filter", cond) / ("project", exprs, schema)
    pre-stages over the base context; returns (final EvalContext over the
    last stage's schema, keep mask). Shared by the sort-based and
    direct-addressing update kernels so the fusion semantics cannot
    diverge between them."""
    ctx = EvalContext(in_schema, base_dvals, num_rows, padded_len,
                      scalars, slots)
    keep = ctx.row_mask()
    for st in stages:
        if st[0] == "filter":
            pv = st[1].eval_device(ctx)
            keep = jnp.logical_and(keep,
                                   jnp.logical_and(pv.data, pv.validity))
        else:
            _, exprs, out_schema = st
            dv = [e.eval_device(ctx)
                  if e.fully_device_supported(ctx.schema) is None
                  else None for e in exprs]
            ctx = EvalContext(out_schema, dv, num_rows, padded_len,
                              ctx.scalars, ctx.literal_slots)
    return ctx, keep


def _param_exprs(key_exprs, aggs, mode, stages, value_exprs=None):
    """The expression list (deterministic order) whose parameterizable
    literals ride into the kernel as traced scalars — the ONE definition
    of slot order shared by kernel build and call sites. Builders pass
    their already-materialized ``value_exprs`` (the objects the kernel
    traces over); callers omit it and get structurally-aligned fresh
    lists from input_exprs()."""
    exprs = []
    for st in (stages or ()):
        if st[0] == "filter":
            exprs.append(st[1])
        else:
            exprs.extend(st[1])
    exprs.extend(key_exprs)
    if mode == "update":
        if value_exprs is not None:
            for ve in value_exprs:
                exprs.extend(ve)
        else:
            for a in aggs:
                exprs.extend(a.input_exprs())
    return exprs


def _stage_key(stages):
    if not stages:
        return ()
    out = []
    for st in stages:
        if st[0] == "filter":
            out.append(("F", st[1].key()))
        else:
            out.append(("P", tuple(e.key() for e in st[1]),
                        tuple((f.name, f.dtype.name)
                              for f in st[2].fields)))
    return tuple(out)


def _agg_kernel_key(key_exprs, aggs, schema, mode, in_schema=None,
                    stages=None, n_codes=0):
    with parameterized_keys():
        return (tuple(e.key() for e in key_exprs),
                tuple(a.key() for a in aggs),
                tuple((f.name, f.dtype.name) for f in schema.fields), mode,
                tuple((f.name, f.dtype.name) for f in in_schema.fields)
                if in_schema is not None else None,
                _stage_key(stages), n_codes)


def _check_scalar_slots(kernel, scalars):
    """Kernel slot maps and call-site scalars come from SEPARATE
    traversals of the parameterizable-literal set (value_exprs at build
    vs fresh input_exprs() at call); the alignment is an invariant, not a
    given — fail loudly instead of silently misbinding constants."""
    n = getattr(kernel, "n_param_slots", None)
    if n is not None and n != len(scalars):
        raise RuntimeError(
            f"aggregate kernel literal-slot mismatch: kernel built with "
            f"{n} parameter slots, call site collected {len(scalars)}")


def _get_kernel(key_exprs, aggs, schema, mode, partial_counts=None,
                in_schema=None, stages=None, n_codes=0,
                split: bool = False):
    """``split=True`` returns the three-dispatch variant (cheap XLA
    compiles, ~2 extra round trips) — the right form for direct calls
    from the classic multi-batch/merge path. The default fused form is
    required wherever the kernel is traced INSIDE another jit (the fast
    single-batch kernel, shard_map fragments)."""
    key = _agg_kernel_key(key_exprs, aggs, schema, mode, in_schema,
                          stages, n_codes)
    if split:
        key = ("split",) + key
    k = _AGG_KERNEL_CACHE.get(key)
    if k is None:
        build = (_build_groupby_kernel_split if split
                 else _build_groupby_kernel)
        k = build(key_exprs, aggs, schema, mode, partial_counts,
                  in_schema, stages, n_codes)
        _AGG_KERNEL_CACHE[key] = k
    return k


class TpuHashAggregateExec(TpuExec):
    """Device hash aggregate. String group keys are DICTIONARY-ENCODED at
    the exec boundary (TPU-first design: strings live on the host; the
    grouping machinery wants fixed-width device lanes — so each string key
    expression is evaluated on host, mapped through an exec-local
    string→int32 dictionary that stays consistent across batches, and the
    codes group on device; finalize decodes codes back to strings). The
    reference groups strings natively in cudf; this is the TPU analog."""

    def __init__(self, groupings: Sequence[Expression],
                 aggs: Sequence[AggregateExpression], child: TpuExec,
                 pre_stages: Optional[list] = None,
                 eval_schema: Optional[Schema] = None,
                 many_groups_hint: bool = False,
                 int_key_cards: Optional[Sequence] = None):
        super().__init__([child])
        self.groupings = list(groupings)
        self.aggs = list(aggs)
        #: planner-known high cardinality: never try the optimistic
        #: single-fetch path (its fused kernel compile would be wasted)
        self.many_groups_hint = many_groups_hint
        #: fused pre-stages: ("filter", cond) / ("project", exprs, schema)
        #: applied INSIDE the update kernel, bottom-up from the child's
        #: actual output (the folded scan→filter→project→agg pipeline)
        self.pre_stages = pre_stages or []
        cs = eval_schema if eval_schema is not None else child.output_schema()
        self._eval_schema = cs
        from ..types import INT32, STRING, IntegerType
        #: grouping ordinals that go through the string dictionary
        self._dict_keys = [i for i, g in enumerate(self.groupings)
                           if g.data_type(cs) == STRING]
        #: ordinal -> PROVEN cardinality for planner-constructed small
        #: int keys (values in [0, card), e.g. the union-rewrite branch
        #: id): these group by DIRECT one-hot addressing with no sort
        #: (the cudf hash-groupby trade). The key travels as an int32
        #: CODE in partials on BOTH the direct and split paths, so
        #: per-batch path choices merge consistently.
        cards_in = list(int_key_cards or [])
        self._int_cards = {
            i: int(c) for i, c in enumerate(cards_in)
            if c and isinstance(self.groupings[i].data_type(cs),
                                IntegerType)}
        # the kernel sees an augmented input schema: child columns plus one
        # appended int32 code column per string key; string groupings are
        # rewritten to BoundReferences onto those columns
        self._kernel_schema = cs
        self._kernel_groupings = list(self.groupings)
        if self._int_cards:
            from ..exprs.cast import Cast
            for i in self._int_cards:
                self._kernel_groupings[i] = Cast(self.groupings[i],
                                                 INT32)
        if self._dict_keys:
            extra = [StructField(f"__gk{i}", INT32, True)
                     for i in self._dict_keys]
            self._kernel_schema = Schema(list(cs.fields) + extra)
            for j, i in enumerate(self._dict_keys):
                self._kernel_groupings[i] = BoundReference(
                    len(cs.fields) + j, INT32)
        fields = [StructField(e.name_hint, e.data_type(cs), True)
                  for e in self.groupings]
        fields += [StructField(a.name_hint, a.data_type(cs), True)
                   for a in self.aggs]
        self._schema = Schema(fields)
        if self.pre_stages:
            # the trace contract for fused regions (exec/base._traced_iter
            # reads trace_args): one span per batch showing what the
            # update kernel swallowed — the partial-agg analog of
            # WholeStageExec's fused=[...] annotation
            self.trace_args = {"fused": [
                ("filter" if s[0] == "filter" else "project")
                for s in self.pre_stages] + ["partial-agg"]}
        # partial (intermediate) schema: keys then each agg's partials
        # (string keys travel as their int32 codes)
        pfields = [StructField(f"_k{i}",
                               e.data_type(self._kernel_schema), True)
                   for i, e in enumerate(self._kernel_groupings)]
        self._partial_counts = []
        afields = []
        for ai, a in enumerate(self.aggs):
            pts = a.partial_types(cs)
            self._partial_counts.append(len(pts))
            for pi, pt in enumerate(pts):
                afields.append(StructField(f"_a{ai}_{pi}", pt, True))
        self._partial_schema_dict = Schema(pfields + afields)
        self._partial_schema = self._partial_schema_dict
        # rect-key variant: string keys keep their STRING type (byte
        # rectangles ride the kernels directly, no int32 code columns)
        self._partial_schema_rect = Schema(
            [StructField(f"_k{i}", e.data_type(cs), True)
             for i, e in enumerate(self.groupings)] + afields)
        self._rect_mode = False

    def output_schema(self) -> Schema:
        return self._schema

    # ------------------------------------------------------------------
    def _run_kernel_raw(self, kernel, batch: ColumnarBatch,
                        extra_cols=(), scalars=()):
        """Dispatch the agg kernel; NO device sync — returns the raw
        (outs, num_groups device scalar) pair so multi-batch first passes
        can overlap every batch's kernel and resolve all counts in ONE
        stacked fetch (per-batch ``int(num_groups)`` cost a full tunnel
        round trip each, serializing the pipeline — 10 batches at 10M rows
        spent ~2 s in fetch latency alone)."""
        from ..columnar.strrect import ByteRectColumn
        cols = []
        for c in batch.columns:
            if isinstance(c, ByteRectColumn):
                cols.append((c.data, c.validity, c.lengths))
            elif isinstance(c, DeviceColumn):
                cols.append((c.data, c.validity))
            else:
                cols.append(None)
        for c in extra_cols:
            cols.append((c.data, c.validity))
        _check_scalar_slots(kernel, scalars)
        key_outs, partial_outs, num_groups = kernel(
            cols, jnp.int32(batch.num_rows_raw), batch.padded_len, scalars)
        return list(key_outs) + list(partial_outs), num_groups

    def _slice_to_count(self, outs, n, out_schema: Schema) -> ColumnarBatch:
        """Re-bucket raw kernel outputs once the group count is known:
        group counts are usually orders of magnitude below the input
        bucket; slicing keeps the merge pass (another sort) tiny."""
        from ..columnar.strrect import ByteRectColumn
        from ..exprs.base import StrVal
        target = bucket_for(int(n))
        out_cols = []
        for (d, v), f in zip(outs, out_schema.fields):
            if isinstance(d, StrVal):
                b, ln = d.bytes_, d.lengths
                if target < b.shape[0]:
                    b, ln, v = b[:target], ln[:target], v[:target]
                out_cols.append(ByteRectColumn(
                    b, v, ln,
                    ascii_only=getattr(self, "_rect_ascii", True)))
                continue
            if target < d.shape[0]:
                d, v = d[:target], v[:target]
            out_cols.append(DeviceColumn(d, v, f.dtype))
        return ColumnarBatch(out_cols, int(n), out_schema)

    def _run_kernel(self, kernel, batch: ColumnarBatch,
                    out_schema: Schema, extra_cols=(),
                    scalars=(), lazy: bool = False) -> ColumnarBatch:
        outs, num_groups = self._run_kernel_raw(kernel, batch, extra_cols,
                                                scalars)
        if lazy:
            # keep the count on device (resolved by the sink fetch); the
            # outputs stay at the input bucket — callers use this when the
            # input is already group-sized (merge passes), where slicing
            # would buy nothing but the sync would cost a round trip
            from ..columnar.strrect import ByteRectColumn
            from ..exprs.base import StrVal
            out_cols = [
                (ByteRectColumn(d.bytes_, v, d.lengths,
                                ascii_only=getattr(self, "_rect_ascii",
                                                   True))
                 if isinstance(d, StrVal) else DeviceColumn(d, v, f.dtype))
                for (d, v), f in zip(outs, out_schema.fields)]
            return ColumnarBatch(out_cols, num_groups, out_schema)
        return self._slice_to_count(outs, int(num_groups), out_schema)

    # -- string-key dictionary encoding --------------------------------
    def _encode_key(self, j: int, i: int, batch: ColumnarBatch):
        """ONE implementation of dictionary-encoding a string group key
        through the exec-local dictionary (consistent global codes across
        batches AND across the fused/classic paths — they must agree when
        the optimistic path bails out mid-query).

        Returns (data, validity, gmap, already_global):
          * DictColumn fast path: device codes in the SOURCE dictionary's
            space + the source->global remap table (applied later, on
            device, fused into the kernel when possible);
          * general path (computed keys, host strings): host-encoded codes
            already in GLOBAL space, gmap=None.
        """
        import pyarrow as pa
        from ..columnar import DictColumn
        from ..exprs.base import Alias, ColumnRef
        p = batch.padded_len
        d = self._dicts[j]
        g = self.groupings[i]
        if isinstance(g, Alias):
            g = g.children[0]
        src = None
        if isinstance(g, ColumnRef) and g.name in batch.schema.names():
            src = batch.column_by_name(g.name)
        if isinstance(src, DictColumn):
            gmap = np.asarray(
                [d.setdefault(s_, len(d)) for s_ in src.dictionary],
                dtype=np.int32)
            return src.data, src.validity, gmap, False
        arr = g.eval_host(batch)
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        de = arr.dictionary_encode()
        gmap = np.asarray([d.setdefault(s_, len(d))
                           for s_ in de.dictionary.to_pylist()],
                          dtype=np.int32)
        valid = ~np.asarray(de.indices.is_null())
        idx = np.asarray(de.indices.fill_null(0).to_numpy(
            zero_copy_only=False), dtype=np.int64)
        codes = gmap[idx] if len(gmap) else np.zeros(len(idx), np.int32)
        n = batch.num_rows      # host encode needs the exact count anyway
        data = np.zeros(p, dtype=np.int32)
        vmask = np.zeros(p, dtype=bool)
        data[:n] = codes[:n]
        vmask[:n] = valid[:n]
        return jnp.asarray(data), jnp.asarray(vmask), None, True

    def _augment(self, batch: ColumnarBatch) -> list:
        """One int32 GLOBAL-code device column per string group key (the
        classic/sort path: the remap is applied here with one dispatch)."""
        if not self._dict_keys:
            return []
        from ..columnar.segmented import onehot_gather
        from ..types import INT32
        cols = []
        for j, i in enumerate(self._dict_keys):
            data, validity, gmap, already_global = \
                self._encode_key(j, i, batch)
            if not already_global:
                if len(gmap):
                    data = onehot_gather(jnp.asarray(gmap), data, len(gmap))
                else:
                    data = jnp.zeros(batch.padded_len, jnp.int32)
            cols.append(DeviceColumn(data, validity, INT32))
        return cols

    def _augment_pairs(self, batch: ColumnarBatch):
        """Dict-key operands for the FUSED dense kernel: per key a raw
        (codes, validity) device pair plus its dictionary->global-code
        remap (numpy; identity when codes are already global) — the remap
        is applied INSIDE the kernel, so no extra dispatch per key."""
        if not self._dict_keys:
            return [], []
        pairs, remaps = [], []
        for j, i in enumerate(self._dict_keys):
            data, validity, gmap, already_global = \
                self._encode_key(j, i, batch)
            pairs.append((data, validity))
            if already_global:
                card = max(len(self._dicts[j]), 1)
                remaps.append(np.arange(card, dtype=np.int32))
            else:
                remaps.append(gmap if len(gmap)
                              else np.zeros(1, np.int32))
        return pairs, remaps

    def _inverse_dict(self, j: int) -> list:
        """code -> string list for dictionary key ordinal j."""
        inv = [None] * len(self._dicts[j])
        for s, c in self._dicts[j].items():
            inv[c] = s
        return inv

    def _decode_keys(self, out_cols: List, num_rows: int) -> List:
        """Replace int32 code key columns with device DictColumns whose
        dictionaries are sorted — only a tiny remap table touches the
        wire; the strings materialize lazily at the final sink (one
        batched fetch there instead of one per key here). Int-carded
        keys' codes ARE their values — just widen to the declared
        type."""
        for i in self._int_cards:
            col = out_cols[i]
            dt = self._schema.fields[i].dtype
            out_cols[i] = DeviceColumn(
                col.data.astype(dt.np_dtype), col.validity, dt)
        if not self._dict_keys or self._rect_mode:
            # rect keys pass through as ByteRectColumns: the sink decodes
            # the (group-sized) rectangles directly
            return out_cols
        from ..columnar import DictColumn
        from ..types import STRING
        for j, i in enumerate(self._dict_keys):
            inv = self._inverse_dict(j)
            col = out_cols[i]
            if not inv:
                out_cols[i] = DictColumn(col.data, col.validity, STRING,
                                         np.asarray([], dtype=object))
                continue
            inv = np.asarray(inv, dtype=object)
            order = np.argsort(inv)
            rank = np.empty(len(inv), np.int32)
            rank[order] = np.arange(len(inv), dtype=np.int32)
            codes2 = jnp.take(jnp.asarray(rank), col.data, mode="clip")
            out_cols[i] = DictColumn(codes2, col.validity, STRING,
                                     inv[order])
        return out_cols

    #: optimistic single-fetch group bound: the fused update+finalize
    #: kernel slices outputs to this many rows so num_groups AND the
    #: results come back in ONE device_get; more groups -> slow path
    OPTIMISTIC_GROUPS = 4096     # overridden per query from conf

    def _get_fast_kernel(self, update_k, kernel_key):
        cached = _AGG_KERNEL_CACHE.get(
            ("fast", self.OPTIMISTIC_GROUPS) + kernel_key)
        if cached is not None:
            return cached
        aggs, pcounts = self.aggs, self._partial_counts
        nkeys = len(self._kernel_groupings)
        ptypes = [f.dtype for f in self._partial_schema.fields]
        OPT = self.OPTIMISTIC_GROUPS

        @functools.partial(jax.jit, static_argnums=(2,))
        def fast(cols, num_rows, padded_len, scalars=()):
            key_outs, partial_outs, num_groups = update_k(
                cols, num_rows, padded_len, scalars)
            outs = list(key_outs)
            ord_ = 0
            for ai, a in enumerate(aggs):
                parts = [DVal(partial_outs[o][0], partial_outs[o][1],
                              ptypes[nkeys + o])
                         for o in range(ord_, ord_ + pcounts[ai])]
                ord_ += pcounts[ai]
                fin = a.finalize(parts)
                outs.append((fin.data, fin.validity))
            from ..columnar.packing import pack_traced
            flat = [num_groups] + [x for d, v in outs
                                   for x in (d[:OPT], v[:OPT])]
            spec_cell[padded_len] = [(np.dtype(x.dtype), tuple(x.shape))
                                     for x in flat]
            return pack_traced(flat)

        spec_cell = {}
        fast.out_specs = spec_cell
        fast.n_param_slots = getattr(update_k, "n_param_slots", None)
        _AGG_KERNEL_CACHE[("fast", self.OPTIMISTIC_GROUPS)
                          + kernel_key] = fast
        return fast

    def _get_fast_direct_kernel(self, g_bucket: int):
        """Direct-addressing groupby for ALL-dictionary-coded keys with a
        small cardinality product: gid = Σ code_i·stride_i — NO 1M-row
        sort (the sort is the dominant FLOPs of the sort-based path; the
        reference's cudf hash groupby makes the same trade). The static
        segment count is the smallest bucket >= the cardinality product,
        so the dense one-hot reduction (columnar/segmented.py) only pays
        for the groups that can exist; cardinalities themselves still ride
        in traced, so dictionary growth recompiles only on a bucket
        crossing (<=5 variants), never per new dictionary entry."""
        key = ("fastdirect", self.OPTIMISTIC_GROUPS,
               g_bucket) + self._kernel_key
        cached = _AGG_KERNEL_CACHE.get(key)
        if cached is not None:
            return cached
        aggs, pcounts = self.aggs, self._partial_counts
        nkeys = len(self._kernel_groupings)
        ptypes = [f.dtype for f in self._partial_schema.fields]
        OPT = self.OPTIMISTIC_GROUPS
        G = g_bucket
        core = self._build_direct_core(G)

        @functools.partial(jax.jit, static_argnums=(2,))
        def fast_direct(cols, num_rows, padded_len, cards, scalars,
                        code_pairs, remaps):
            key_outs, partial_outs, num_groups = core(
                cols, num_rows, padded_len, cards, scalars,
                code_pairs, remaps)
            outs = list(key_outs)
            live = jnp.arange(G, dtype=jnp.int32) < num_groups
            ord_ = 0
            for ai, a in enumerate(aggs):
                parts = []
                for o in range(ord_, ord_ + pcounts[ai]):
                    cd, cv = partial_outs[o]
                    parts.append(DVal(cd, jnp.logical_and(cv, live),
                                      ptypes[nkeys + o]))
                ord_ += pcounts[ai]
                fin = a.finalize(parts)
                outs.append((fin.data, fin.validity))
            from ..columnar.packing import pack_traced
            flat = [num_groups] + [x for d, v in outs
                                   for x in (d[:OPT], v[:OPT])]
            spec_cell[padded_len] = [(np.dtype(x.dtype), tuple(x.shape))
                                     for x in flat]
            return pack_traced(flat)

        spec_cell = {}
        fast_direct.out_specs = spec_cell
        fast_direct.n_param_slots = core.n_param_slots
        _AGG_KERNEL_CACHE[key] = fast_direct
        return fast_direct

    def _get_direct_update_kernel(self, g_bucket: int):
        """Direct-addressing UPDATE kernel for the multi-batch first pass:
        the dense one-hot pipeline of _get_fast_direct_kernel but emitting
        the sort-path update contract (compacted key-code rows + update
        partials + num_groups) so the merge/finalize phases are shared
        with the sort path. All-dictionary keys with a small cardinality
        product only. The point is COMPILE time as much as run time: the
        1M-row variadic-sort update kernel takes minutes to compile on a
        tunneled backend (bench_r3.log: q28 warm-up 2,381 s), while this
        kernel is elementwise + one-hot reductions that compile in
        seconds."""
        key = ("directupd", g_bucket) + self._kernel_key
        cached = _AGG_KERNEL_CACHE.get(key)
        if cached is not None:
            return cached
        core = self._build_direct_core(g_bucket)
        direct_update = jax.jit(core, static_argnums=(2,))
        direct_update.n_param_slots = core.n_param_slots
        _AGG_KERNEL_CACHE[key] = direct_update
        return direct_update

    def _build_direct_core(self, g_bucket: int):
        """The direct-addressing groupby pipeline SHARED by the fused
        single-batch kernel and the multi-batch update kernel (one
        implementation — null-key handling, stride packing, and pre-stage
        fusion cannot diverge between the two paths). Returns a traceable
        fn (cols, num_rows, padded_len, cards, scalars, code_pairs,
        remaps) -> (key_outs, partial_outs, num_groups) with compacted
        G-sized outputs; partial validities are ANDed with occupancy but
        NOT with the live prefix (callers needing fetch-stable tails mask
        with ``slot < num_groups`` themselves)."""
        aggs = self.aggs
        nkeys = len(self._kernel_groupings)
        value_exprs = [a.input_exprs() for a in aggs]
        schema = self._kernel_schema
        dtypes = [f.dtype for f in schema.fields]
        in_schema = (self.children[0].output_schema()
                     if self.pre_stages else None)
        base_dtypes = ([f.dtype for f in in_schema.fields]
                       if in_schema is not None else None)
        stages = self.pre_stages
        G = g_bucket
        from ..types import INT32
        from ..columnar.segmented import prefix_sum, seg_sum
        slots = literal_slot_map(_param_exprs(
            self._kernel_groupings, aggs, "update", stages,
            value_exprs=value_exprs))

        # only DICTIONARY keys occupy appended kernel-schema slots;
        # int-carded keys' codes feed gid directly and must NOT displace
        # real columns in the eval context (r5: the old tail-replace
        # clobbered the column after the last real one — e.g. the
        # distinct flag — whenever a non-appended key was present)
        dict_ords = tuple(self._dict_keys)

        def core(cols, num_rows, padded_len, cards, scalars,
                 code_pairs, remaps):
            from ..columnar.segmented import onehot_gather
            # dictionary remap FUSED into the kernel (each standalone
            # remap dispatch pays full tunnel latency)
            code_cols = [(onehot_gather(rm, cd, G), cv)
                         for (cd, cv), rm in zip(code_pairs, remaps)]
            dict_codes = [DVal(code_cols[i][0], code_cols[i][1], INT32)
                          for i in dict_ords]
            if base_dtypes is not None:
                n_base = len(base_dtypes)
                base = [None if c is None else DVal(c[0], c[1], dt)
                        for c, dt in zip(cols[:n_base], base_dtypes)]
                sctx, keep = _apply_pre_stages(stages, in_schema, base,
                                               num_rows, padded_len,
                                               scalars, slots)
                dvals = list(sctx.columns) + dict_codes
                ectx = EvalContext(schema, dvals, num_rows, padded_len,
                                   scalars, slots)
            else:
                n_base = len(dtypes) - len(dict_ords)
                dvals = [None if c is None else DVal(c[0], c[1], dt)
                         for c, dt in zip(cols[:n_base],
                                          dtypes[:n_base])]
                dvals += [None] * (n_base - len(dvals))
                dvals += dict_codes
                ectx = EvalContext(schema, dvals, num_rows, padded_len,
                                   scalars, slots)
                keep = ectx.row_mask()
            # gid from packed codes; null occupies the extra slot per key
            strides = []
            stride = jnp.int32(1)
            for i in reversed(range(nkeys)):
                strides.insert(0, stride)
                stride = stride * (cards[i] + 1)
            gid = jnp.zeros(padded_len, dtype=jnp.int32)
            for i in range(nkeys):
                cd, cv = code_cols[i]
                ceff = jnp.where(cv, cd, cards[i])
                gid = gid + ceff * strides[i]
            gid = jnp.where(keep, gid, G)        # dead rows drop out
            vals = [[e.eval_device(ectx) for e in exprs]
                    for exprs in value_exprs]
            partial_dense = []
            for a, vs in zip(aggs, vals):
                partial_dense.extend(a.update(vs, gid, G, keep))
            occ = seg_sum(keep.astype(jnp.int32), gid, num_segments=G) > 0
            num_groups = jnp.sum(occ).astype(jnp.int32)
            pos = jnp.where(occ, prefix_sum(occ, jnp.int32) - 1, G)
            slot = jnp.arange(G, dtype=jnp.int32)
            key_outs = []
            for i in range(nkeys):
                code_i = (slot // strides[i]) % (cards[i] + 1)
                valid_i = jnp.logical_and(code_i < cards[i], occ)
                kd = jnp.zeros(G, jnp.int32).at[pos].set(code_i,
                                                         mode="drop")
                kv = jnp.zeros(G, jnp.bool_).at[pos].set(valid_i,
                                                         mode="drop")
                key_outs.append((kd, kv))
            partial_outs = []
            for d, v in partial_dense:
                cd = jnp.zeros(G, d.dtype).at[pos].set(d, mode="drop")
                cv = jnp.zeros(G, jnp.bool_).at[pos].set(
                    jnp.logical_and(v, occ), mode="drop")
                partial_outs.append((cd, cv))
            return key_outs, partial_outs, num_groups

        core.n_param_slots = len(slots)
        return core

    def _rect_key_mode(self, batch) -> bool:
        """True when every string group key is a direct reference to a
        byte-rectangle ASCII column of this batch — keys then group on
        device via packed-word operands (exprs/string_rect design)."""
        if not self._dict_keys or batch is None:
            return False
        from ..columnar.strrect import ByteRectColumn
        from ..exprs.base import Alias, ColumnRef
        for i in self._dict_keys:
            g = self.groupings[i]
            if isinstance(g, Alias):
                g = g.children[0]
            if not isinstance(g, ColumnRef):
                return False
            try:
                col = batch.column_by_name(g.name)
            except (KeyError, ValueError):
                return False
            if not (isinstance(col, ByteRectColumn) and col.ascii_only):
                return False
        return True

    def _ensure_rect_cols(self, batch: ColumnarBatch, ordinals) -> ColumnarBatch:
        """Rect-mode invariant: the given STRING columns must be byte
        rectangles. A spill round trip or host-staged concat can re-ingest
        them as dictionary codes (whose code spaces differ per batch —
        grouping on them across batches would be wrong); re-encode those
        back to rectangles (grouping on bytes is exact for ANY UTF-8)."""
        from ..columnar.strrect import ByteRectColumn, encode_string_rect
        import jax
        cols = list(batch.columns)
        changed = False
        for i in ordinals:
            c = cols[i]
            if isinstance(c, ByteRectColumn):
                if not c.ascii_only:
                    self._rect_ascii = False
                continue
            arr = c.to_arrow(batch.num_rows)
            enc = encode_string_rect(arr, len(arr), batch.padded_len,
                                     1 << 30)     # correctness: no cap
            if enc is None:       # cannot happen below the 1<<30 cap,
                raise ValueError(  # but never unpack None silently
                    "string too wide for the rectangle re-encode")
            rect, lens, v, asc = enc
            if not asc:
                # grouping stays byte-exact for any UTF-8; only the
                # downstream case-transform eligibility flag must flip
                self._rect_ascii = False
            cols[i] = ByteRectColumn(jax.device_put(rect),
                                     jax.device_put(v),
                                     jax.device_put(lens),
                                     ascii_only=asc)
            changed = True
        if not changed:
            return batch
        return ColumnarBatch(cols, batch.num_rows_raw, batch.schema,
                             meta=batch.meta)

    def _rect_key_ordinals_for(self, batch: ColumnarBatch):
        """Ordinals of the key-leaf columns in an UPDATE input batch."""
        from ..exprs.base import Alias, ColumnRef
        out = []
        for i in self._dict_keys:
            g = self.groupings[i]
            if isinstance(g, Alias):
                g = g.children[0]
            out.append(batch.schema.index_of(g.name))
        return out

    def _direct_keys_ok(self) -> bool:
        """Every grouping is either a dictionary string key or a
        proven-cardinality int key — the direct core's requirement."""
        if not self.groupings or self._rect_mode:
            return False
        covered = set(self._dict_keys) | set(self._int_cards)
        return len(covered) == len(self.groupings)

    def _mixed_pairs(self, batch: ColumnarBatch):
        """(pairs, remaps, cards) for ALL groupings in grouping order:
        string keys dictionary-encode (global codes), int-carded keys
        pass their device values straight through as codes with an
        identity remap."""
        from ..exprs.base import Alias, ColumnRef
        s_pairs, s_remaps = self._augment_pairs(batch)
        by_dict = {i: j for j, i in enumerate(self._dict_keys)}
        pairs, remaps, cards = [], [], []
        for i in range(len(self.groupings)):
            if i in by_dict:
                j = by_dict[i]
                pairs.append(s_pairs[j])
                remaps.append(s_remaps[j])
                cards.append(max(len(self._dicts[j]), 1))
                continue
            card = self._int_cards[i]
            g = self.groupings[i]
            if isinstance(g, Alias):
                g = g.children[0]
            if not isinstance(g, ColumnRef):
                return None
            try:
                col = batch.column_by_name(g.name)
            except (KeyError, ValueError):
                return None
            if not isinstance(col, DeviceColumn):
                return None
            pairs.append((col.data, col.validity))
            remaps.append(np.arange(card, dtype=np.int32))
            cards.append(card)
        return pairs, remaps, np.asarray(cards, np.int32)

    def _direct_operands(self, batch: ColumnarBatch):
        """(cards_dev, pairs, padded_remaps, Gb) when direct addressing
        applies to this batch, else None — the shared operand builder of
        the fused single-batch and multi-batch update call sites."""
        if not self._direct_keys_ok():
            return None
        # current dictionary sizes are a lower bound on post-encode sizes:
        # once the product exceeds the bound it can only grow, so bail out
        # BEFORE paying the host-side dictionary encode a second time
        lower = 1
        for d in self._dicts:
            lower *= max(len(d), 1) + 1
        for c in self._int_cards.values():
            lower *= c + 1
        if lower > self.OPTIMISTIC_GROUPS:
            return None
        mixed = self._mixed_pairs(batch)
        if mixed is None:
            return None
        pairs, remaps, cards = mixed
        prod = int(np.prod(cards.astype(np.int64) + 1))
        if prod > self.OPTIMISTIC_GROUPS:
            return None
        from ..columnar.segmented import bucket_segments
        Gb = bucket_segments(prod)
        if jax.default_backend() == "cpu" \
                and Gb * batch.padded_len > (1 << 28):
            # XLA:CPU MATERIALIZES the dense one-hot (G x P) the TPU
            # backend fuses into its reduction — a 4096-segment bucket
            # over a 1M-row batch would allocate >100 GB on the CPU
            # fallback path (r5 rehearsal OOM). The split sort path
            # handles these shapes there.
            return None
        padded_remaps = tuple(
            jnp.asarray(np.pad(r, (0, max(Gb - len(r), 0)))[:Gb])
            for r in remaps)
        return jnp.asarray(cards), tuple(pairs), padded_remaps, Gb

    def _direct_update_args(self, batch: ColumnarBatch):
        """When the multi-batch first pass can use the direct-addressing
        update kernel for this batch, return (kernel, args); else None."""
        ops = self._direct_operands(batch)
        if ops is None:
            return None
        cards, pairs, padded_remaps, Gb = ops
        kern = self._get_direct_update_kernel(Gb)
        return kern, (cards, pairs, padded_remaps)

    def _fast_single_batch(self, ctx, batch: ColumnarBatch,
                           update_k) -> Optional[ColumnarBatch]:
        """Single-input-batch aggregation: ONE kernel dispatch (fused
        pre-stages + dictionary remap + update + finalize + result
        packing) and ONE fetch produce the final HOST batch — every extra
        dispatch or fetch pays full tunnel latency. Returns None when the
        group count exceeds the optimistic bound (caller takes the
        classic path)."""
        import jax
        from ..columnar.column import arrow_from_numpy
        from ..columnar.packing import unpack_streams
        from ..types import STRING
        base_cols = []
        for c in batch.columns:
            base_cols.append((c.data, c.validity)
                             if isinstance(c, DeviceColumn) else None)
        nkeys = len(self.groupings)
        packed = None
        if nkeys > 0:
            ops = self._direct_operands(batch)
            if ops is not None:
                cards, pairs, padded_remaps, Gb = ops
                fast = self._get_fast_direct_kernel(Gb)
                _check_scalar_slots(fast, self._upd_scalars)
                packed = fast(base_cols, jnp.int32(batch.num_rows_raw),
                              batch.padded_len, cards,
                              self._upd_scalars, pairs, padded_remaps)
                specs = fast.out_specs[batch.padded_len]
        if packed is None:
            if nkeys > 0:
                # SORT-based keyed aggregation must not compile the fused
                # update+finalize kernel: a lax.sort's compile time
                # multiplies with everything else in its module, and this
                # exact kernel stalled compiles for HOURS on the tunneled
                # backend (r3's 2,381 s q28 warm-up; an outer-agg variant
                # wedged a bench run for 90+ minutes in r4). The classic
                # path runs the SPLIT kernels instead — a couple more
                # dispatches on a single batch, compile in minutes.
                return None
            codes = self._augment(batch)
            cols = base_cols + [(c.data, c.validity) for c in codes]
            if self._fast_k is None:
                self._fast_k = self._get_fast_kernel(update_k,
                                                     self._kernel_key)
            _check_scalar_slots(self._fast_k, self._upd_scalars)
            packed = self._fast_k(
                cols, jnp.int32(batch.num_rows_raw), batch.padded_len,
                self._upd_scalars)
            specs = self._fast_k.out_specs[batch.padded_len]
        u32, f64 = jax.device_get(packed)       # the ONE round trip
        got = unpack_streams(u32, f64, specs)
        n = int(got[0])
        if n > self.OPTIMISTIC_GROUPS:
            _FAST_GROUPS[self._kernel_key] = n
            return None
        out_cols = []
        dict_pos = {i: j for j, i in enumerate(self._dict_keys)}
        for o, f in enumerate(self._schema.fields):
            d = np.asarray(got[1 + 2 * o])[:n]
            v = np.asarray(got[2 + 2 * o])[:n]
            if o in dict_pos:
                inv = self._inverse_dict(dict_pos[o])
                vals = [inv[int(x)] if ok else None
                        for x, ok in zip(d, v)]
                out_cols.append(HostColumn.from_pylist(vals, STRING))
            else:
                out_cols.append(HostColumn(arrow_from_numpy(d, v, f.dtype),
                                           f.dtype))
        return ColumnarBatch(out_cols, n, self._schema)

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        from ..config import AGG_OPTIMISTIC_GROUPS
        self.OPTIMISTIC_GROUPS = int(ctx.conf.get(AGG_OPTIMISTIC_GROUPS))
        self._dicts = [dict() for _ in self._dict_keys]
        self._fast_k = None
        in_schema = (self.children[0].output_schema()
                     if self.pre_stages else None)
        self._kernel_key = _agg_kernel_key(
            self._kernel_groupings, self.aggs, self._kernel_schema,
            "update", in_schema, self.pre_stages or None,
            len(self._dict_keys))
        # the fused (single-module) update kernel is only ever invoked for
        # GLOBAL aggregations (_fast_single_batch's nkeys==0 branch);
        # keyed aggregations always run the split kernels — the fused
        # sort-based form compiles pathologically on this backend
        update_k = None
        if not self.groupings:
            update_k = _get_kernel(self._kernel_groupings, self.aggs,
                                   self._kernel_schema, "update",
                                   in_schema=in_schema,
                                   stages=self.pre_stages or None,
                                   n_codes=len(self._dict_keys))
        # the multi-batch first pass calls the kernel directly (not traced
        # inside another jit) — the split three-dispatch form compiles in
        # ~1 min where the fused sort pipeline took >20 on this backend
        update_k_split = _get_kernel(self._kernel_groupings, self.aggs,
                                     self._kernel_schema, "update",
                                     in_schema=in_schema,
                                     stages=self.pre_stages or None,
                                     n_codes=len(self._dict_keys),
                                     split=True)
        self._upd_scalars = literal_scalars(collect_param_literals(
            _param_exprs(self._kernel_groupings, self.aggs, "update",
                         self.pre_stages or None)))
        rows_m = ctx.metric(self._exec_id, "numOutputRows", ESSENTIAL)
        #: compiled-module launches of the UPDATE phase, per query: the
        #: fused-partial-agg acceptance metric — a q9-shaped
        #: scan→filter→partial-agg region must cost exactly ONE dispatch
        #: per input batch (fused/direct kernels), vs 4 on the split
        #: sort pipeline and one per operator when fusion is off
        disp_m = ctx.metric(self._exec_id, "updateDispatches")

        it = self.children[0].execute(ctx)
        first = next(it, None)
        second = next(it, None) if first is not None else None
        # byte-rectangle key mode (VERDICT r3 #4): when every string
        # group key is a rectangle-backed ASCII column, the keys group
        # ON DEVICE through packed-word sort operands — no exec-local
        # dictionary, no host encode, no per-distinct-value work
        self._rect_mode = self._rect_key_mode(first)
        self._rect_ascii = True
        self._partial_schema = self._partial_schema_dict
        if self._rect_mode:
            self._kernel_key = ("rect",) + _agg_kernel_key(
                self.groupings, self.aggs, self._eval_schema, "update",
                in_schema, self.pre_stages or None, 0)
            update_k_split = _get_kernel(self.groupings, self.aggs,
                                         self._eval_schema, "update",
                                         in_schema=in_schema,
                                         stages=self.pre_stages or None,
                                         split=True)
            self._upd_scalars = literal_scalars(collect_param_literals(
                _param_exprs(self.groupings, self.aggs, "update",
                             self.pre_stages or None)))
            self._partial_schema = self._partial_schema_rect
        if first is not None and second is None \
                and not self.many_groups_hint \
                and not self._rect_mode \
                and (not self.groupings or self._direct_keys_ok()) \
                and _FAST_GROUPS.get(self._kernel_key, 0) \
                <= self.OPTIMISTIC_GROUPS:
            first = first.ensure_device()

            def run_fast():
                with ctx.semaphore.held():
                    return self._fast_single_batch(ctx, first, update_k)
            out = with_retry_no_split(run_fast, ctx=ctx, op=self._exec_id)
            if out is not None:
                disp_m.add(1)    # fused update+finalize: one module
                _FAST_GROUPS[self._kernel_key] = out.num_rows
                rows_m.add(out.num_rows)
                yield out
                return

        import itertools
        pending = [b for b in (first, second) if b is not None]
        # phase 1: dispatch EVERY batch's update kernel without syncing —
        # the kernels overlap in the device queue and the tunnel pipeline
        # (a per-batch int(num_groups) cost one round trip EACH, ~2 s of
        # pure latency for a 10-batch input on the tunneled backend).
        # Outputs are sliced immediately to a SPECULATIVE group bucket
        # (stat from previous runs of this kernel) so at most one
        # input-bucket-sized output is live at a time; the stacked count
        # fetch in phase 2 validates every guess and re-runs the (rare,
        # idempotent) overflowed batch at its true bucket.
        spec = bucket_for(max(_FAST_GROUPS.get(self._kernel_key, 0),
                              1 if not self.groupings else 1024))
        #: bound on input batches pinned by pending dispatch closures: the
        #: count fetch resolves per WINDOW, so a long scan never holds
        #: every input batch in HBM at once (one fetch per 8 batches
        #: instead of per batch — latency amortized 8x, memory bounded)
        WINDOW = 8
        partials: List[SpillableBatch] = []
        row_base = 0     # global row offset of the next batch
        # (sliced outs, num_groups dev scalar, dispatch, base, n_disp)
        window = []

        #: (value ordinal, position ordinal) per First/Last aggregate:
        #: their within-batch row positions must become GLOBAL before the
        #: merge, or ties between different batches' firsts break
        #: cross-batch arrival order (caught by
        #: test_agg_multibatch_first_last_order_dependent)
        from ..exprs.aggregates import First, Last
        pos_partials = []
        ord_ = len(self.groupings)
        for ai, a in enumerate(self.aggs):
            if isinstance(a, (First, Last)):
                pos_partials.append((ord_, ord_ + 1))
            ord_ += self._partial_counts[ai]

        def flush_window():
            if not window:
                return
            if not self.groupings:
                counts = [1] * len(window)
            elif len(window) == 1:
                counts = [int(window[0][1])]
            else:
                def resolve_counts():
                    import numpy as _np
                    return [int(x) for x in
                            _np.asarray(jnp.stack([w[1] for w in window]))]
                counts = with_retry_no_split(resolve_counts, ctx=ctx,
                                             op=self._exec_id)
            for (outs, _, dispatch, base, n_disp), n in zip(window,
                                                            counts):
                if n > spec:
                    # speculation overflow: re-run this batch's kernel
                    # (pure function of retained inputs) and slice at the
                    # true count — a second real launch, so the dispatch
                    # metric counts it again
                    disp_m.add(n_disp)

                    def redo(d=dispatch):
                        with ctx.semaphore.held():
                            return d()[0]
                    outs = with_retry_no_split(redo, ctx=ctx,
                                               op=self._exec_id)
                pb = self._slice_to_count(outs, n, self._partial_schema)
                for val_o, pos_o in pos_partials:
                    vcol, pcol = pb.columns[val_o], pb.columns[pos_o]
                    pd_ = jnp.where(vcol.validity,
                                    pcol.data + jnp.int64(base),
                                    pcol.data)
                    pb.columns[pos_o] = DeviceColumn(pd_, pcol.validity,
                                                     pcol.dtype)
                partials.append(SpillableBatch(pb, ctx.memory))
            window.clear()

        try:
            for batch in itertools.chain(pending, it):
                batch = batch.ensure_device()
                if self._rect_mode:
                    batch = self._ensure_rect_cols(
                        batch, self._rect_key_ordinals_for(batch))
                direct = self._direct_update_args(batch)
                if direct is not None:
                    kern, (cards, pairs, remaps) = direct
                    _check_scalar_slots(kern, self._upd_scalars)
                    n_disp = 1
                    disp_m.add(n_disp)

                    def dispatch(b=batch, k=kern, c=cards, p=pairs, r=remaps):
                        base_cols = [(cc.data, cc.validity)
                                     if isinstance(cc, DeviceColumn) else None
                                     for cc in b.columns]
                        ko, po, ng = k(base_cols, jnp.int32(b.num_rows_raw),
                                       b.padded_len, c, self._upd_scalars,
                                       p, r)
                        return list(ko) + list(po), ng
                else:
                    codes = [] if self._rect_mode else self._augment(batch)
                    n_disp = getattr(update_k_split, "n_dispatches", 1)
                    disp_m.add(n_disp)

                    def dispatch(b=batch, extra=codes):
                        return self._run_kernel_raw(
                            update_k_split, b, extra_cols=extra,
                            scalars=self._upd_scalars)

                def _spec_slice(d_, v):
                    from ..exprs.base import StrVal
                    if isinstance(d_, StrVal):
                        if spec < d_.bytes_.shape[0]:
                            return (StrVal(d_.bytes_[:spec],
                                           d_.lengths[:spec]), v[:spec])
                        return (d_, v)
                    if spec < d_.shape[0]:
                        return (d_[:spec], v[:spec])
                    return (d_, v)

                def first_pass(d=dispatch):
                    with ctx.semaphore.held():
                        outs, ng = d()
                        return [_spec_slice(d_, v) for d_, v in outs], ng
                # idempotent over the input batch -> retry-safe
                outs, ng = with_retry_no_split(first_pass, ctx=ctx,
                                               op=self._exec_id)
                window.append((outs, ng, dispatch, row_base, n_disp))
                row_base += batch.padded_len
                if len(window) >= WINDOW:
                    flush_window()
            flush_window()
        except BaseException:
            # fatal error (or cooperative QueryTimeout) mid-update:
            # accumulated partials would outlive the query and pin
            # pool budget — the zero-leak audit's contract
            for sb in partials:
                sb.close()
            raise

        total = sum(sb.device_bytes() for sb in partials)
        if (self.groupings and partials
                and total > ctx.conf.batch_size_bytes
                and self._repartitionable()):
            yield from self._repartitioned_merge(ctx, partials, total, rows_m)
            return

        if len(partials) == 1:
            # one update output already has unique groups — merge is the
            # identity, skip its kernel (and host sync) entirely
            merged = partials[0].get()
            partials[0].close()
        else:
            merged = self._merge(ctx, partials)
        final = self._finalize(ctx, merged)
        nr = final.num_rows_raw
        if isinstance(nr, int):
            _FAST_GROUPS[self._kernel_key] = nr   # refresh stat
            rows_m.add(nr)
        else:
            # lazy count: refresh the stat when the sink fetch resolves it
            # (never an extra sync — _resolve_count runs the callback)
            kk, fg = self._kernel_key, _FAST_GROUPS

            def _on_groups(n, _kk=kk, _fg=fg, _m=rows_m):
                _fg[_kk] = n
                _m.add(n)
            import weakref
            final.meta = dict(final.meta)
            final.meta["count_cb"] = (_on_groups, weakref.ref(final))
        yield final

    # -- re-partition fallback (ref GpuAggregateExec.scala:718-780: when the
    # merge target cannot fit, hash re-partition the partial batches by key
    # and merge each partition independently — group keys are disjoint
    # across partitions, so per-partition merge+finalize is exact) ---------
    #: distinct seed from shuffle partitioning (42) so a key-partitioned
    #: shuffle stage does not collapse all rows into one sub-partition
    REPARTITION_SEED = 1879048201

    def _repartitionable(self) -> bool:
        from ..exprs.hash_fns import device_hashable
        return not any(
            device_hashable.reason_not_supported(f.dtype)
            for f in self._partial_schema.fields[:len(self.groupings)])

    def _merge_kernel(self):
        merge_keys = [BoundReference(i, f.dtype) for i, f in
                      enumerate(self._partial_schema.fields[:len(self.groupings)])]
        merge_k = _get_kernel(merge_keys, self.aggs, self._partial_schema,
                              "merge", self._partial_counts, split=True)
        return merge_keys, merge_k

    def _repartitioned_merge(self, ctx: ExecContext, partials, total, rows_m
                             ) -> Iterator[ColumnarBatch]:
        from ..shuffle.partitioning import partition_batch, scatter_spillables
        merge_keys, merge_k = self._merge_kernel()
        n_parts = min(1 << max(1, (int(total) // ctx.conf.batch_size_bytes
                                   ).bit_length()), 64)
        ctx.metric(self._exec_id, "aggRepartitions").set(n_parts)
        slices = scatter_spillables(
            ctx, partials,
            lambda b: partition_batch(b, merge_keys, n_parts,
                                      seed=self.REPARTITION_SEED),
            n_parts)
        try:
            for p in range(n_parts):
                parts = slices[p]
                if not parts:
                    continue

                def merge_part(parts=parts):
                    with ctx.semaphore.held():
                        big = concat_batches([s.get() for s in parts])
                        return self._run_kernel(merge_k, big,
                                                self._partial_schema)
                try:
                    merged = with_retry_no_split(merge_part, ctx=ctx,
                                                 op=self._exec_id)
                finally:
                    for s in parts:
                        s.close()
                final = self._finalize(ctx, merged)
                rows_m.add(final.num_rows)
                yield final
        except BaseException:
            # fatal merge or abandoned consumer: LATER partitions' slices
            # still pin pool budget (close() is idempotent)
            for slot in slices:
                for s in slot:
                    s.close()
            raise

    # ------------------------------------------------------------------
    def _merge(self, ctx: ExecContext,
               partials: List[SpillableBatch]) -> ColumnarBatch:
        """Merge partial batches. Small totals concat once and run ONE
        lazy merge kernel. Totals whose concat would exceed batchSizeRows
        merge as a bounded-fan-in TREE instead: chunks of partials whose
        padded sum fits the cap merge in parallel (counts resolved in one
        stacked fetch per level), so no merge kernel is ever compiled
        above the bucket the cap implies. Before this, 10 high-cardinality
        partials at the 262144 bucket concatenated to a 4.19M-row shape
        whose variadic-sort merge kernel took >12 minutes to compile on
        the tunneled backend (TPC-DS q28 at 10M rows)."""
        _, merge_k = self._merge_kernel()
        if not partials:
            # empty input: still one row for global agg, zero rows for grouped
            empty = ColumnarBatch.from_arrow(
                _empty_arrow(self._partial_schema))
            with ctx.semaphore.held():
                return self._run_kernel(merge_k, empty, self._partial_schema)

        # the tree operates on SPILLABLES end to end: every level's inputs
        # materialize via sb.get() INSIDE the retried closure, so a
        # RetryOOM spill actually frees HBM and the retry re-materializes
        # from host (holding raw jax arrays across the retry would pin
        # the memory the spill claims to have released).
        # the cap never sits below the largest single partial (a chunk of
        # one merges nothing and would loop forever)
        cap = max(ctx.conf.batch_size_rows,
                  max(sb.padded_len for sb in partials))
        level: List[SpillableBatch] = list(partials)

        merged_level: List = []
        try:
            while len(level) > 1 and \
                    sum(sb.padded_len for sb in level) > cap:
                # greedy chunking by padded length
                chunks, cur, acc = [], [], 0
                for sb in level:
                    if cur and acc + sb.padded_len > cap:
                        chunks.append(cur)
                        cur, acc = [], 0
                    cur.append(sb)
                    acc += sb.padded_len
                chunks.append(cur)
                raws = []
                for chunk in chunks:
                    if len(chunk) == 1:
                        raws.append(chunk[0])    # spillable passthrough
                        continue

                    def level_merge(c=chunk):
                        with ctx.semaphore.held():
                            big = concat_batches([s.get() for s in c])
                            if self._rect_mode:
                                big = self._ensure_rect_cols(
                                    big, range(len(self.groupings)))
                            return self._run_kernel_raw(merge_k, big)
                    raws.append(with_retry_no_split(level_merge, ctx=ctx,
                                                    op=self._exec_id))
                ngs = [r[1] for r in raws if isinstance(r, tuple)]
                if len(ngs) > 1:
                    def resolve():
                        import numpy as _np
                        return [int(x) for x in _np.asarray(jnp.stack(ngs))]
                    counts = iter(with_retry_no_split(resolve, ctx=ctx,
                                                      op=self._exec_id))
                else:
                    counts = iter([int(ngs[0])] if ngs else [])
                merged_level = []
                for r in raws:
                    if not isinstance(r, tuple):
                        merged_level.append(r)
                        continue
                    pb = self._slice_to_count(r[0], next(counts),
                                              self._partial_schema)
                    merged_level.append(SpillableBatch(pb, ctx.memory))
                # consumed chunk inputs can release now (their content lives
                # on in the level outputs)
                for sb in level:
                    if sb not in merged_level:
                        sb.close()
                if len(merged_level) >= len(level):
                    # no progress (every chunk was a singleton — all partials
                    # at cap size): fall through to one oversized merge rather
                    # than loop forever
                    level = merged_level
                    break
                level = merged_level
        except BaseException:
            # fatal error (or QueryTimeout) mid-tree: the current
            # level's inputs AND any outputs already merged at this
            # level must release (close() is idempotent — items that
            # moved between the lists close once)
            for sb in level:
                sb.close()
            for sb in merged_level:
                if isinstance(sb, SpillableBatch):
                    sb.close()
            raise

        def do_merge() -> ColumnarBatch:
            with ctx.semaphore.held():
                big = concat_batches([s.get() for s in level])
                if self._rect_mode:
                    big = self._ensure_rect_cols(
                        big, range(len(self.groupings)))
                # lazy: the merge input is already group-sized, so the
                # output stays at its (small) bucket and the group count
                # rides to the sink fetch instead of syncing here
                return self._run_kernel(merge_k, big, self._partial_schema,
                                        lazy=True)

        try:
            if len(level) == 1:
                return level[0].get()
            return with_retry_no_split(do_merge, ctx=ctx, op=self._exec_id)
        finally:
            for sb in level:
                sb.close()

    # ------------------------------------------------------------------
    def _finalize(self, ctx: ExecContext, merged: ColumnarBatch) -> ColumnarBatch:
        nkeys = len(self.groupings)
        out_cols: List[DeviceColumn] = self._decode_keys(
            list(merged.columns[:nkeys]), merged.num_rows_raw)
        ord_ = nkeys
        for ai, a in enumerate(self.aggs):
            n = self._partial_counts[ai]
            parts = [DVal(merged.columns[o].data, merged.columns[o].validity,
                          merged.columns[o].dtype)
                     for o in range(ord_, ord_ + n)]
            ord_ += n
            final = a.finalize(parts)
            out_cols.append(DeviceColumn(final.data, final.validity,
                                         self._schema.fields[nkeys + ai].dtype))
        return ColumnarBatch(out_cols, merged.num_rows_raw, self._schema)

    def describe(self):
        g = ", ".join(e.name_hint for e in self.groupings)
        a = ", ".join(x.name_hint for x in self.aggs)
        fused = ""
        if self.pre_stages:
            parts = [("filter" if s[0] == "filter" else "project")
                     for s in self.pre_stages]
            fused = f" fused=[{'+'.join(parts)}]"
        return f"HashAggregate[keys=[{g}], aggs=[{a}]]{fused}"


def _empty_arrow(schema: Schema):
    import pyarrow as pa
    from ..types import to_arrow
    return pa.table({f.name: pa.array([], type=to_arrow(f.dtype))
                     for f in schema.fields})


class CpuAggregateExec(TpuExec):
    """Host fallback via pandas groupby (the CPU oracle for differential
    tests, playing the role CPU Spark plays for the reference)."""
    is_tpu = False

    def __init__(self, groupings, aggs, child: TpuExec):
        super().__init__([child])
        self.groupings = list(groupings)
        self.aggs = list(aggs)
        cs = child.output_schema()
        fields = [StructField(e.name_hint, e.data_type(cs), True)
                  for e in self.groupings]
        fields += [StructField(a.name_hint, a.data_type(cs), True)
                   for a in self.aggs]
        self._schema = Schema(fields)

    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        import pandas as pd
        import pyarrow as pa
        from ..exprs.aggregates import (Average, CollectList, CollectSet,
                                        Count, CountStar, First, Last, Max,
                                        MaxBy, Min, MinBy, Percentile,
                                        StddevPop, StddevSamp, Sum,
                                        VariancePop, VarianceSamp)
        tables = [b.to_arrow() for b in self.children[0].execute(ctx)]
        at = (pa.concat_tables(tables) if tables
              else _empty_arrow(self.children[0].output_schema()))
        df = at.to_pandas()

        # evaluate key + input expressions into temp columns; the source
        # batch comes straight from ARROW (from_pandas would turn every
        # NaN into a SQL NULL — Spark distinguishes them: NaN is a value)
        work = pd.DataFrame(index=df.index)
        src = ColumnarBatch.from_arrow_host(at) if len(df) else None
        key_names = []
        for i, g in enumerate(self.groupings):
            col = f"_k{i}"
            work[col] = _host_series(g, df, src)
            key_names.append(col)
        in_names = []
        for i, a in enumerate(self.aggs):
            col = f"_a{i}"
            if isinstance(a, (MinBy, MaxBy)) and src is not None:
                # second input: the ordering column rides alongside
                work[col + "__ord"] = a.ordering.eval_host(src).to_pandas()
            if isinstance(a, CountStar):
                work[col] = 1
                work[col + "__ok"] = True
            else:
                arr = (a.child.eval_host(src) if src is not None else None)
                if arr is None:
                    work[col] = pd.Series([], dtype="float64")
                    work[col + "__ok"] = pd.Series([], dtype="bool")
                else:
                    # keep SQL NULL distinct from NaN: pandas conflates
                    # them, but Spark's sum/avg/max PROPAGATE NaN while
                    # ignoring NULL (NaN is a value, NaN > everything)
                    work[col] = arr.to_pandas()
                    work[col + "__ok"] = ~np.asarray(arr.is_null())
            in_names.append(col)

        def agg_series(a, s: "pd.Series", ok: "pd.Series", sub=None,
                       col=None):
            okm = ok.to_numpy().astype(bool)
            vals = s.to_numpy()[okm]
            if a.distinct and not isinstance(a, CountStar):
                vals = pd.unique(pd.Series(vals))   # NaN == NaN, keep one
            if isinstance(a, CountStar):
                return len(s)
            if isinstance(a, Count):
                return len(vals)
            if isinstance(a, CollectSet):
                return list(pd.unique(pd.Series(vals)))
            if isinstance(a, CollectList):
                return list(vals)
            if isinstance(a, (MinBy, MaxBy)):
                # Spark: pick the VALUE (possibly NULL) at the extreme
                # ordering; only NULL-ordering rows are skipped
                o = sub[col + "__ord"].to_numpy()
                o_ok = ~pd.isna(o)
                if not o_ok.any():
                    return None
                idx = np.nanargmin(o[o_ok]) if a._pick_min \
                    else np.nanargmax(o[o_ok])
                if not okm[o_ok][idx]:
                    return None                     # value is SQL NULL
                return s.to_numpy()[o_ok][idx]
            if len(vals) == 0:
                return None
            if isinstance(a, Percentile):
                # incl. ApproximatePercentile: computed EXACTLY here
                fv = vals.astype(np.float64)
                fv = fv[~np.isnan(fv)]
                if len(fv) == 0:
                    return None
                return float(np.percentile(np.sort(fv),
                                           a.percentage * 100.0,
                                           method="linear"))
            if isinstance(a, Sum):
                return np.sum(vals)                 # NaN propagates
            if isinstance(a, Min):
                with np.errstate(invalid="ignore"):
                    m = np.nanmin(vals) if _is_float(vals) else np.min(vals)
                return m                            # all-NaN -> NaN
            if isinstance(a, Max):
                return np.max(vals)                 # NaN is greatest
            if isinstance(a, Average):
                return np.sum(vals) / len(vals)
            if isinstance(a, First):
                return vals[0]
            if isinstance(a, Last):
                return vals[-1]
            n = len(vals)
            if isinstance(a, (StddevSamp, VarianceSamp)) and n < 2:
                return None
            mean = np.sum(vals) / n
            var = np.sum((vals - mean) ** 2) / \
                (n - 1 if isinstance(a, (StddevSamp, VarianceSamp)) else n)
            if isinstance(a, (StddevSamp, StddevPop)):
                return np.sqrt(var)
            if isinstance(a, (VarianceSamp, VariancePop)):
                return var
            raise NotImplementedError(type(a).__name__)

        if self.groupings:
            grouped = work.groupby(key_names, dropna=False, sort=False)
            rows = []
            for key, sub in grouped:
                if not isinstance(key, tuple):
                    key = (key,)
                rows.append(list(key) +
                            [agg_series(a, sub[c], sub[c + "__ok"],
                                        sub, c)
                             for a, c in zip(self.aggs, in_names)])
            out = pd.DataFrame(rows, columns=self._schema.names())
        else:
            vals = [agg_series(a, work[c], work[c + "__ok"], work, c)
                    for a, c in zip(self.aggs, in_names)]
            out = pd.DataFrame([vals], columns=self._schema.names())
        # coerce to declared output types
        from ..types import to_arrow as _toa

        def _cell(x, is_float: bool):
            if x is None:
                return None
            if isinstance(x, (list, np.ndarray)):
                return list(x)         # collect_list/set array cells
            if is_float and isinstance(x, float) and np.isnan(x):
                return x               # NaN is a VALUE, not SQL NULL
            return None if pd.isna(x) else x

        arrays = []
        for f in self._schema.fields:
            isf = f.dtype.name in ("float", "double")
            vals = [_cell(x, isf) for x in out[f.name].tolist()]
            arrays.append(pa.array(vals, type=_toa(f.dtype)))
        table = pa.Table.from_arrays(arrays, names=self._schema.names())
        # host-only output (see CpuFilterExec): no device bounce on the
        # CPU-reverted path; downstream re-materializes if needed
        yield ColumnarBatch.from_arrow_host(table)

    def describe(self):
        g = ", ".join(e.name_hint for e in self.groupings)
        a = ", ".join(x.name_hint for x in self.aggs)
        return f"CpuAggregate[keys=[{g}], aggs=[{a}]]"


def _is_float(vals) -> bool:
    return getattr(vals, "dtype", None) is not None and \
        vals.dtype.kind == "f"


def _host_series(expr: Expression, df, src_batch):
    """Evaluate an expression to a pandas Series on the host."""
    import pandas as pd
    if src_batch is None:
        return pd.Series([], dtype="float64")
    return expr.eval_host(src_batch).to_pandas()
