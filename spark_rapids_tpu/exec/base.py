"""Physical operator base (ref GpuExec.scala:274).

A TpuExec produces an iterator of ColumnarBatch. Metrics mirror the
reference's GpuMetric registry with verbosity levels (GpuExec.scala:54-165);
the device semaphore gates concurrent device work (GpuSemaphore.scala:51).
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, Iterator, List, Optional

from ..columnar import ColumnarBatch
from ..config import TpuConf
from ..mem.semaphore import QueryTimeout
from ..trace import core as trace_core
from ..types import Schema

__all__ = ["ExecContext", "TpuExec", "Metric", "ESSENTIAL", "MODERATE",
           "DEBUG", "QueryTimeout"]

ESSENTIAL, MODERATE, DEBUG = "ESSENTIAL", "MODERATE", "DEBUG"


class Metric:
    __slots__ = ("name", "level", "value")

    def __init__(self, name: str, level: str = MODERATE):
        self.name = name
        self.level = level
        self.value = 0

    def add(self, v):
        self.value += v

    def set(self, v):
        self.value = v


class ExecContext:
    """Per-query execution context: conf + shared runtime services.

    Reference analog: the executor-process singletons (GpuSemaphore,
    RapidsBufferCatalog, GpuTaskMetrics) — scoped per query here since we are
    a library, not a long-lived executor."""

    def __init__(self, conf: Optional[TpuConf] = None, semaphore=None,
                 memory=None):
        from ..mem.semaphore import DeviceSemaphore
        from ..mem.manager import MemoryManager
        self.conf = conf or TpuConf()
        # one conf lookup per query context, never per event: installs
        # the process tracer iff spark.rapids.tpu.trace.enabled, and the
        # metric registry (+ sampler) iff spark.rapids.tpu.metrics.enabled
        trace_core.ensure_tracer_from_conf(self.conf)
        from ..metrics import registry as metrics_registry
        metrics_registry.ensure_metrics_from_conf(self.conf)
        # persistent executable tier: point jax's compilation cache at
        # the conf'd dir + trim to budget (one lookup per query context,
        # never per kernel — plan/exec_cache.py)
        from ..plan import exec_cache
        exec_cache.configure_from_conf(self.conf)
        # live ops plane: HTTP endpoint, flight recorder, regression
        # sentinel — same install pattern; with nothing configured this
        # is three conf lookups and no threads (ops/__init__.py)
        from ..ops import ensure_ops_plane_from_conf
        ensure_ops_plane_from_conf(self.conf)
        # multi-tenant admission controller (ISSUE 18): installed iff
        # spark.rapids.tpu.admission.enabled — same one-conf-lookup
        # install-once pattern; disabled it stays None and each query
        # pays one module-global load + branch (sched/admission.py)
        from ..sched.admission import ensure_admission_from_conf
        ensure_admission_from_conf(self.conf)
        # adaptive query execution (ISSUE 19): the closed-taxonomy
        # decision log, installed iff spark.rapids.tpu.aqe.enabled —
        # off, every decision site is one module load + branch
        from ..aqe import ensure_aqe_from_conf
        ensure_aqe_from_conf(self.conf)
        from ..config import SEMAPHORE_WEDGE_TIMEOUT_MS, TASK_TIMEOUT
        self.memory = memory or MemoryManager.get(self.conf)
        self.semaphore = semaphore or DeviceSemaphore(
            self.conf.concurrent_tpu_tasks,
            timeout_s=float(self.conf.get(TASK_TIMEOUT)),
            wedge_timeout_ms=int(self.conf.get(SEMAPHORE_WEDGE_TIMEOUT_MS)),
            memory=self.memory)
        self.metrics: Dict[str, Dict[str, Metric]] = {}
        self._cleanups = []
        #: query-lifecycle cooperative deadline (time.monotonic instant,
        #: None = no timeout); checked per produced batch and polled by
        #: semaphore waits (api/dataframe.py sets it per query)
        self.deadline: Optional[float] = None
        self._oom_lock = threading.Lock()
        #: runtime OOM_PRESSURE_HOST degradations recorded by the retry
        #: ladder (mem/retry.py): [{"op", "detail"}, ...]; drained per
        #: query by api/dataframe._execute_wrapped
        self.oom_degradations: List[dict] = []  # tpulint: guarded-by _oom_lock
        #: highest OOM-escalation rung any ladder reached this query
        #: (1 retry / 2 split / 3 pressure spill / 4 host degradation);
        #: drained per query next to oom_degradations — the queryEnd
        #: record, /queries and the regression sentinel all read it
        self.max_ladder_rung = 0  # tpulint: guarded-by _oom_lock
        #: speculative output sizing (joins skip the count->host sync and
        #: guess the bucket); the FINAL sink calls check_speculations() once
        self.speculate = self.conf.join_speculative_sizing
        #: [(device total, capacity, join stat key), ...]
        self.speculations = []

    # --------------------------------------------- query-lifecycle control
    def set_query_deadline(self, deadline: Optional[float]) -> None:
        """Install (or with None clear) this query's cooperative
        cancellation deadline; the semaphore polls the same instant
        (per-thread — a shared semaphore must not leak one query's
        deadline into another's wait) so a blocked acquire cancels
        promptly too."""
        self.deadline = deadline
        self.semaphore.set_thread_deadline(deadline)

    def check_cancelled(self) -> None:
        """Cooperative cancellation point: raises QueryTimeout past the
        deadline. Called at every produced batch (TpuExec.execute) and
        from the retry ladder — the exception unwinds through the normal
        cleanup paths, releasing the semaphore and closing spillables."""
        dl = self.deadline
        if dl is not None and time.monotonic() > dl:
            raise QueryTimeout(
                "query exceeded spark.rapids.tpu.query.timeout "
                f"(deadline passed by {time.monotonic() - dl:.3f}s)")

    def record_oom_degradation(self, op: str, detail: str) -> None:
        """The retry ladder's host-degradation rung fired for ``op``:
        remembered for the query's PlacementReport / event-log record
        and counted into the metric families immediately."""
        with self._oom_lock:
            self.oom_degradations.append({"op": op, "detail": detail})
        from ..metrics import registry as metrics_registry
        mr = metrics_registry.REGISTRY
        if mr is not None:
            mr.counter("srtpu_oom_host_fallback_total", op=op).inc()
            mr.counter("srtpu_placement_fallback_total",
                       code="OOM_PRESSURE_HOST", op=op).inc()
        self.note_ladder_rung(4, f"{op}: {detail}")

    def note_ladder_rung(self, rung: int, detail: str = "") -> None:
        """Record the OOM-escalation rung a ladder just reached (the
        per-query max survives to the queryEnd record). Crossing into
        rung >= 3 for the first time this query fires the flight
        recorder's ``oom_ladder`` trigger — the PR-14 anomaly sites
        dumped diagnostics only into exception strings before."""
        with self._oom_lock:
            prev = self.max_ladder_rung
            self.max_ladder_rung = max(prev, int(rung))
        if rung >= 3 and rung > prev and prev < 3:
            from ..ops import flight as flight_mod
            fr = flight_mod.RECORDER
            if fr is not None:
                fr.trigger("oom_ladder",
                           detail=detail
                           or f"OOM escalation reached rung {rung}")

    def take_ladder_rung(self) -> int:
        """Drain the per-query max escalation rung (per-query reset)."""
        with self._oom_lock:
            rung, self.max_ladder_rung = self.max_ladder_rung, 0
        return rung

    def take_oom_degradations(self) -> List[dict]:
        """Drain the recorded degradations (per-query reset)."""
        with self._oom_lock:
            out, self.oom_degradations = self.oom_degradations, []
        return out

    def check_speculations(self) -> None:
        """Validate every speculatively-sized output (ONE batched fetch of
        the tiny totals); raises SpeculativeOverflow if any guess was too
        small. Only the query's final sink may call this — a mid-plan
        validation would consume another join's pending record."""
        if not self.speculations:
            return
        from ..columnar.batch import SpeculativeOverflow
        from ..columnar.packing import fetch_packed
        from .joins import _TOTAL_STATS
        pending, self.speculations = self.speculations, []
        totals = fetch_packed([t for t, _, _, _ in pending])
        for n, (_, cap, stat_key, plan_sig) in zip(totals, pending):
            n = int(n)
            if stat_key is not None:
                _TOTAL_STATS[stat_key] = n     # keep the statistic fresh
            if plan_sig is not None:
                # measured join-output rows -> the cost model (the crudest
                # estimate it has); rides the same batched totals fetch
                from ..plan.cost import record_runtime_rows
                record_runtime_rows(plan_sig, n)
            if n > cap:
                raise SpeculativeOverflow(n, cap)

    def metric(self, exec_id: str, name: str, level: str = MODERATE) -> Metric:
        m = self.metrics.setdefault(exec_id, {})
        if name not in m:
            m[name] = Metric(name, level)
        return m[name]

    def add_cleanup(self, fn) -> None:
        """Register a resource release to run at context close (per-query
        caches like broadcast relations)."""
        self._cleanups.append(fn)

    def close(self) -> None:
        fns, self._cleanups = self._cleanups, []
        for fn in fns:
            try:
                fn()
            except Exception:  # pragma: no cover - best-effort teardown
                pass
        if getattr(self, "_broadcast_cache", None):
            self._broadcast_cache.clear()

    def __del__(self):  # pragma: no cover - GC backstop
        try:
            self.close()
        except Exception:
            pass


#: process-wide exec-id source (itertools.count is atomic under the GIL)
_EXEC_ID_COUNTER = itertools.count()


class TpuExec:
    """Base physical operator."""

    #: True if this exec runs its compute on the device
    is_tpu: bool = True
    #: True for pass-through operators shared by BOTH engines (union,
    #: branch-align, limit): they must not make a host-reverted query
    #: look device-placed to the measured-wall arbitration
    engine_neutral: bool = False

    def __init__(self, children: List["TpuExec"]):
        self.children = children
        # monotonic, never-reused id: keying metrics on id(self) lets a
        # freed plan tree's address be reused by a later exec, silently
        # MERGING two operators' metric entries in a shared ExecContext
        self._exec_id = f"{type(self).__name__}@{next(_EXEC_ID_COUNTER)}"

    # -- interface ---------------------------------------------------------
    def output_schema(self) -> Schema:
        raise NotImplementedError

    def execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        m = ctx.metric(self._exec_id, "opTime")
        t0 = time.perf_counter()
        it = self.do_execute(ctx)
        m.add(time.perf_counter() - t0)
        # per-batch metering: cumulative operator time (includes pulls
        # from children — EXPLAIN ANALYZE derives SELF time by
        # subtracting the children's cumulative) + produced batches
        it = self._metered_iter(
            it, m, ctx.metric(self._exec_id, "numOutputBatches"))
        if ctx.deadline is not None:
            # cooperative cancellation: one deadline check per produced
            # batch at every operator (zero cost with no timeout set)
            it = self._cancel_iter(it, ctx)
        sig = getattr(self, "plan_sig", None)
        if sig is not None:
            it = self._record_rows(it, sig)
        tr = trace_core.TRACER       # single branch when tracing is off
        if tr is not None:
            it = self._traced_iter(it, tr)
        return it

    @staticmethod
    def _metered_iter(it, m_time: Metric, m_batches: Metric):
        """Time every next() into the operator's cumulative opTime and
        count produced batches (two perf_counter reads per BATCH — noise
        next to batch-scale work, and the price of an always-on SQL-UI
        view; ref GpuMetric.ns around every GPU op)."""
        it = iter(it)
        while True:
            t0 = time.perf_counter()
            try:
                b = next(it)
            except StopIteration:
                m_time.add(time.perf_counter() - t0)
                return
            m_time.add(time.perf_counter() - t0)
            m_batches.add(1)
            yield b

    @staticmethod
    def _cancel_iter(it, ctx):
        """Raise QueryTimeout at the first batch boundary past the
        query deadline (spark.rapids.tpu.query.timeout). The exception
        unwinds through the generator stack: semaphore permits release
        via their with-scopes, spillables close via the operators'
        cleanup handlers — cancellation leaks nothing."""
        for b in it:
            ctx.check_cancelled()
            yield b

    def _traced_iter(self, it, tr):
        """One span per produced batch, named after the operator. Child
        operators' spans nest inside (the contextvar parent chain), so
        the profile analyzer can compute SELF time — where a query's
        wall actually goes, not just cumulative subtree time."""
        name = type(self).__name__
        # fused regions annotate their span with the operators they
        # swallowed (exec/wholestage.py trace_args = {"fused": [...]})
        args = {"exec": self._exec_id,
                **getattr(self, "trace_args", {})}
        it = iter(it)
        while True:
            with tr.span(name, cat="exec", args=args):
                try:
                    b = next(it)
                except StopIteration:
                    return
            yield b

    @staticmethod
    def _record_rows(it, sig):
        """Measured-rows feedback for the cost model (plan/cost.py
        _RUNTIME_ROWS): execs tagged with a plan signature record their
        output row counts — immediately for host ints, deferred to the
        sink fetch for lazy device counts (never an extra sync). One
        accumulator covers all of this exec's batches (true totals);
        the weakref tag pins each deferred count to its exact batch."""
        import weakref
        from ..plan.cost import RowsAccum
        accum = RowsAccum(sig)
        for b in it:
            if isinstance(b.num_rows_raw, int):
                accum.add(b.num_rows_raw)
            else:
                b.meta = dict(b.meta)
                b.meta["rows_accum"] = (accum, weakref.ref(b))
            yield b

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        raise NotImplementedError

    # -- explain -----------------------------------------------------------
    def describe(self) -> str:
        return type(self).__name__

    def tree_string(self, indent: int = 0) -> str:
        marker = "*" if self.is_tpu else "!"
        s = "  " * indent + marker + " " + self.describe() + "\n"
        for c in self.children:
            s += c.tree_string(indent + 1)
        return s

    def collect(self, ctx: Optional[ExecContext] = None,
                validate: bool = True):
        """Materialize to a single Arrow table (drives the whole pipeline).
        ``validate=False`` marks a MID-PLAN materialization (e.g. a join
        building its broadcast side): it must neither consume the context's
        pending speculation records nor retry a subtree on its own — an
        overflow propagates to the final sink, which re-runs the full plan.
        """
        import pyarrow as pa
        from ..columnar.batch import SpeculativeOverflow
        ctx = ctx or ExecContext()
        if not validate:
            return self._collect_tables(ctx)
        try:
            tables = [b.to_arrow() for b in self.execute(ctx)]
            ctx.check_speculations()
        except SpeculativeOverflow:
            # a join's guessed output bucket was too small: re-run the
            # whole plan with exact (synchronous) output sizing
            ctx.speculate = False
            ctx.speculations.clear()
            ctx.metrics.clear()        # don't double-count the failed run
            tables = [b.to_arrow() for b in self.execute(ctx)]
        if not tables:
            return self._empty_table()
        return pa.concat_tables(tables)

    def _collect_tables(self, ctx):
        import pyarrow as pa
        tables = [b.to_arrow() for b in self.execute(ctx)]
        if not tables:
            return self._empty_table()
        return pa.concat_tables(tables)

    def _empty_table(self):
        import pyarrow as pa
        from ..types import to_arrow
        fields = [(f.name, to_arrow(f.dtype)) for f in self.output_schema()]
        return pa.table({n: pa.array([], type=t) for n, t in fields})
