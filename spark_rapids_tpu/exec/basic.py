"""Basic physical operators: scan, project, filter, range, limit, union,
sample, expand, coalesce (ref basicPhysicalOperators.scala: GpuProjectExec:365,
GpuFilterExec:806, GpuRangeExec:1137; GpuCoalesceBatches.scala:112).
"""
from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..columnar import (ColumnarBatch, DeviceColumn, HostColumn,
                        concat_batches)
from ..columnar.bucketing import bucket_for
from ..exprs.base import Expression
from ..exprs.compiler import compile_projection, filter_batch_device
from ..types import INT64, Schema, StructField
from .base import DEBUG, ESSENTIAL, ExecContext, TpuExec

__all__ = ["InMemoryScanExec", "TpuProjectExec", "CpuProjectExec",
           "TpuFilterExec", "CpuFilterExec", "TpuRangeExec", "LimitExec",
           "UnionExec", "CoalesceBatchesExec", "TpuSampleExec",
           "TpuExpandExec"]


def _reset_task_state(exprs):
    """Restart task-context counters (monotonically_increasing_id, rand)
    at the start of each plan execution — Spark resets per-task state on
    every task launch."""
    stack = list(exprs)
    while stack:
        e = stack.pop()
        r = getattr(e, "reset_task_state", None)
        if r is not None:
            r()
        stack.extend(e.children)


#: device-batch cache for repeated scans of the same Arrow table (the
#: HostColumnarToGpu analog of keeping broadcast/shuffle data
#: device-resident): weak-keyed on the table so memory frees with it,
#: LRU-bounded so it cannot starve the spillable memory pool (the entries
#: live OUTSIDE the retry framework's reach — eviction here is the only
#: pressure valve)
import weakref

from ..config import register as _register_conf

SCAN_CACHE_MAX_BYTES = _register_conf(
    "spark.rapids.tpu.sql.scanCache.maxBytes", 2 * 1024 * 1024 * 1024,
    "Device-memory budget for cached in-memory-table scan batches; "
    "least-recently-used entries evict first. 0 disables the cache.")

_SCAN_CACHE: "weakref.WeakValueDictionary" = weakref.WeakValueDictionary()
_SCAN_CACHE_BATCHES: Dict[tuple, list] = {}
_SCAN_CACHE_LRU: Dict[tuple, int] = {}
_SCAN_CACHE_TICK = [0]


def _scan_cache_get(t, key):
    if _SCAN_CACHE.get(id(t)) is t:
        k = (id(t),) + key
        got = _SCAN_CACHE_BATCHES.get(k)
        if got is not None:
            _SCAN_CACHE_TICK[0] += 1
            _SCAN_CACHE_LRU[k] = _SCAN_CACHE_TICK[0]
        return got
    return None


def _scan_cache_bytes() -> int:
    # snapshot: weakref finalizers may evict entries mid-iteration (GC can
    # run _scan_cache_evict during any allocation inside the sum)
    return sum(b.device_size_bytes()
               for bs in list(_SCAN_CACHE_BATCHES.values()) for b in bs)


def _scan_cache_put(t, key, batches, limit: int):
    if limit <= 0:
        return
    new_bytes = sum(b.device_size_bytes() for b in batches)
    if new_bytes > limit:
        return
    # LRU-evict until the new entry fits
    while _SCAN_CACHE_BATCHES and _scan_cache_bytes() + new_bytes > limit:
        coldest = min(_SCAN_CACHE_LRU, key=_SCAN_CACHE_LRU.get)
        del _SCAN_CACHE_BATCHES[coldest]
        del _SCAN_CACHE_LRU[coldest]
    tid = id(t)
    if _SCAN_CACHE.get(tid) is not t:
        # new table under a reused id: drop stale entries for that id
        _scan_cache_evict(tid)
        try:
            _SCAN_CACHE[tid] = t
        except TypeError:
            return      # not weak-referenceable: skip caching
        weakref.finalize(t, _scan_cache_evict, tid)
    k = (tid,) + key
    _SCAN_CACHE_BATCHES[k] = batches
    _SCAN_CACHE_TICK[0] += 1
    _SCAN_CACHE_LRU[k] = _SCAN_CACHE_TICK[0]


def _scan_cache_evict(tid):
    for k in [k for k in _SCAN_CACHE_BATCHES if k[0] == tid]:
        del _SCAN_CACHE_BATCHES[k]
        _SCAN_CACHE_LRU.pop(k, None)


class InMemoryScanExec(TpuExec):
    """Scan over pre-partitioned Arrow tables (ref GpuInMemoryTableScanExec).
    Device batches are cached per (table, split) so re-running a query over
    the same in-memory data skips the H2D transfer entirely."""

    def __init__(self, tables, schema: Schema, batch_rows: int = 1 << 20,
                 columns=None):
        super().__init__([])
        self.tables = list(tables)
        self._schema = schema if columns is None else Schema(
            [schema[c] for c in columns])
        self.columns = list(columns) if columns is not None else None
        self.batch_rows = batch_rows

    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        rows_m = ctx.metric(self._exec_id, "numOutputRows", ESSENTIAL)
        names = tuple(self._schema.names())
        limit = int(ctx.conf.get(SCAN_CACHE_MAX_BYTES))
        for pid, t in enumerate(self.tables):
            key = (self.batch_rows, names)
            cached = _scan_cache_get(t, key)
            if cached is not None:
                for b in cached:
                    rows_m.add(b.num_rows)
                    yield b
                continue
            built = []
            src = t if self.columns is None else t.select(self.columns)
            off = 0
            while off < src.num_rows or (src.num_rows == 0 and off == 0):
                chunk = src.slice(off, self.batch_rows)
                if chunk.num_rows == 0 and off > 0:
                    break
                with ctx.semaphore.held():
                    from ..columnar.strrect import RECT_MAX_BYTES
                    b = ColumnarBatch.from_arrow(
                        chunk, rect_cap=int(ctx.conf.get(RECT_MAX_BYTES)))
                b.meta = {"partition_id": pid}
                rows_m.add(b.num_rows)
                built.append(b)
                yield b
                off += self.batch_rows
                if src.num_rows == 0:
                    break
            _scan_cache_put(t, key, built, limit)

    def describe(self):
        return f"InMemoryScan[{len(self.tables)} partitions]"


class TpuProjectExec(TpuExec):
    """Projection. Device-supported expressions compile into ONE fused XLA
    kernel; host-only expressions (strings etc.) evaluate via Arrow and are
    H2D'd when their output type is device-backed — per-expression fallback,
    finer-grained than the reference's whole-exec fallback."""

    def __init__(self, exprs: Sequence[Expression], child: TpuExec):
        super().__init__([child])
        self.exprs = list(exprs)
        in_schema = child.output_schema()
        self._schema = Schema([
            StructField(e.name_hint, e.data_type(in_schema), True)
            for e in self.exprs])
        self.device_idx = []
        self.host_idx = []
        self.passthrough = {}    # out ordinal -> source column name
        #: out ordinal -> (transform chain root, leaf column name):
        #: value-wise string transforms over ONE string column evaluate
        #: once per distinct dictionary entry and re-encode (VERDICT r2
        #: #4 — row data stays on device; ref stringFunctions.scala)
        self.dict_chain = {}
        #: out ordinal -> (chain root, leaf name): device byte-rectangle
        #: string chains (high cardinality — exprs/string_rect.py)
        self.rect_chain = {}
        from ..exprs.base import Alias, ColumnRef
        for i, e in enumerate(self.exprs):
            inner = e.children[0] if isinstance(e, Alias) else e
            if isinstance(inner, ColumnRef):
                # identity projection: reuse the column object — zero
                # compute AND it preserves runtime column state
                # (DictColumn dictionaries) the planner can't see
                self.passthrough[i] = inner.name
            elif e.fully_device_supported(in_schema) is None:
                self.device_idx.append(i)
            else:
                self.host_idx.append(i)
                leaf = self._dict_chain_leaf(inner, in_schema)
                if leaf is not None:
                    self.dict_chain[i] = (inner, leaf)
                from ..exprs.string_rect import rect_chain_leaf
                rleaf = rect_chain_leaf(inner, in_schema)
                if rleaf is not None:
                    # high-cardinality path: when the source column is a
                    # byte rectangle (ASCII), the chain compiles to ONE
                    # device kernel over [rows, width] (VERDICT r3 #4;
                    # ref stringFunctions.scala device kernels)
                    self.rect_chain[i] = (inner, rleaf)
        #: device exprs referencing ArrayType columns: the batch may carry
        #: them as HostColumns (width cap, columnar/nested.py) — those
        #: exprs drop to host PER BATCH (the dict-filter bail-out pattern)
        from ..types import ArrayType
        self._list_refs = {
            i: [r for r in set(self.exprs[i].references())
                if r in in_schema.names()
                and isinstance(in_schema[r].dtype, ArrayType)]
            for i in self.device_idx}
        self._list_refs = {i: v for i, v in self._list_refs.items() if v}
        self._projector = None
        self._sub_projectors = {}
        self._dict_xform_cache = {}

    @staticmethod
    def _dict_chain_leaf(e, schema):
        """Leaf column name when ``e`` is a chain of dict_transform
        string ops over one STRING ColumnRef, else None."""
        from ..exprs.base import ColumnRef
        from ..types import STRING
        cur = e
        hops = 0
        while getattr(cur, "dict_transform", False) \
                and len(cur.children) == 1:
            cur = cur.children[0]
            hops += 1
        if hops and isinstance(cur, ColumnRef) \
                and cur.name in schema.names() \
                and schema[cur.name].dtype == STRING:
            return cur.name
        return None

    def _dict_transform(self, expr, leaf: str, col):
        """DictColumn -> DictColumn with the TRANSFORMED dictionary;
        None when a transformed entry is NULL (caller takes the per-row
        path). Transforms can merge or reorder entries (upper('a') ==
        upper('A')), so the raw result is deduped + re-SORTED and the
        device codes remapped through one small one-hot gather —
        DictColumn's sorted-unique invariant (code order == string
        order) holds for every downstream consumer (sort, window
        partitioning, range predicates)."""
        import pyarrow as pa
        from ..columnar import ColumnarBatch, DictColumn
        from ..columnar.segmented import onehot_gather
        ck = expr.key()
        cached = self._dict_xform_cache.get(ck)
        if cached is not None and cached[0] is col.dictionary:
            uniq, rank = cached[1]
        else:
            fake = ColumnarBatch.from_arrow_host(
                pa.table({leaf: pa.array(col.dictionary,
                                         type=pa.string())}))
            out = expr.eval_host(fake)
            if pa.compute.any(pa.compute.is_null(out)).as_py():
                return None
            vals = np.asarray(out.to_numpy(zero_copy_only=False),
                              dtype=object)
            uniq, inv = np.unique(vals, return_inverse=True)
            rank = inv.astype(np.int32)
            self._dict_xform_cache[ck] = (col.dictionary, (uniq, rank))
        G = bucket_for(max(len(rank), 1), (64, 1024, 16384, 262144))
        table = np.zeros(G, np.int32)
        table[:len(rank)] = rank
        codes = onehot_gather(jnp.asarray(table), col.data, G)
        return DictColumn(codes, col.validity, col.dtype,
                          np.asarray(uniq, dtype=object))

    def _rect_eval(self, expr, col, ordinal: int, width_cap: int,
                   use_pallas: bool = False):
        """One jitted kernel for a whole rect string chain (upper/trim/
        substring/... fused), resolved through the PROCESS-wide
        executable cache keyed on (expr, width, padded, cap): a
        per-exec kernel dict re-traced the chain on every query — the
        string_transforms_100k 17.3 s warm cliff (ISSUE 6)."""
        from ..columnar.strrect import ByteRectColumn
        from ..exprs.base import StrVal
        from ..exprs.compiler import compile_rect_chain
        fn = compile_rect_chain(expr, col.width, col.padded_len,
                                width_cap, use_pallas)
        data, valid = fn(col.data, col.lengths, col.validity)
        if isinstance(data, StrVal):
            return ByteRectColumn(data.bytes_, valid, data.lengths,
                                  ascii_only=True)
        from ..columnar import DeviceColumn
        return DeviceColumn(data, valid,
                            self._schema.fields[ordinal].dtype)

    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        child_schema = self.children[0].output_schema()
        dev_exprs = [self.exprs[i] for i in self.device_idx]
        rows_m = ctx.metric(self._exec_id, "numOutputRows", ESSENTIAL)
        _reset_task_state(self.exprs)
        for batch in self.children[0].execute(ctx):
            batch = batch.ensure_device()
            out: List[Optional[object]] = [None] * len(self.exprs)
            for i, name in self.passthrough.items():
                out[i] = batch.column_by_name(name)
            host_now = []
            dev_now = self.device_idx
            if self._list_refs:
                from ..columnar.nested import ListColumn
                host_now = [
                    i for i, names in self._list_refs.items()
                    if any(not isinstance(batch.column_by_name(nm),
                                          ListColumn) for nm in names)]
                if host_now:
                    dev_now = [i for i in self.device_idx
                               if i not in host_now]
            if dev_now:
                if dev_now is self.device_idx:
                    if self._projector is None:
                        self._projector = compile_projection(dev_exprs,
                                                             child_schema)
                    proj = self._projector
                else:
                    key = tuple(dev_now)
                    proj = self._sub_projectors.get(key)
                    if proj is None:
                        proj = compile_projection(
                            [self.exprs[i] for i in dev_now],
                            child_schema)
                        self._sub_projectors[key] = proj
                with ctx.semaphore.held():
                    dcols = proj.run(batch)
                for i, c in zip(dev_now, dcols):
                    out[i] = c
            for i in host_now:
                arr = self.exprs[i].eval_host(batch)
                dt = self._schema.fields[i].dtype
                if dt.device_backed:
                    import pyarrow as pa
                    hb = ColumnarBatch.from_arrow(pa.table({"c": arr}))
                    out[i] = hb.columns[0]
                else:
                    out[i] = HostColumn(arr, dt)
            for i in self.host_idx:
                chain = self.dict_chain.get(i)
                if chain is not None:
                    from ..columnar import DictColumn
                    expr, leaf = chain
                    src = batch.column_by_name(leaf)
                    if isinstance(src, DictColumn) \
                            and len(src.dictionary):
                        xf = self._dict_transform(expr, leaf, src)
                        if xf is not None:
                            out[i] = xf
                            continue
                rchain = self.rect_chain.get(i)
                if rchain is not None:
                    from ..columnar.strrect import ByteRectColumn
                    from ..exprs.string_rect import RectUnsupported
                    expr, leaf = rchain
                    src = batch.column_by_name(leaf)
                    if isinstance(src, ByteRectColumn) and src.ascii_only:
                        from ..columnar.strrect import RECT_MAX_BYTES
                        from ..exprs.pallas_rect import PALLAS_ENABLED
                        cap = int(ctx.conf.get(RECT_MAX_BYTES))
                        pls = bool(ctx.conf.get(PALLAS_ENABLED))
                        try:
                            with ctx.semaphore.held():
                                out[i] = self._rect_eval(expr, src, i,
                                                         cap, pls)
                            continue
                        except RectUnsupported:
                            # the chain outgrows the width cap: host for
                            # this and (dropping the chain) later batches
                            # — no per-batch re-trace just to re-raise
                            self.rect_chain.pop(i, None)
                arr = self.exprs[i].eval_host(batch)
                dt = self._schema.fields[i].dtype
                if dt.device_backed:
                    import pyarrow as pa
                    hb = ColumnarBatch.from_arrow(
                        pa.table({"c": arr}))
                    out[i] = hb.columns[0]
                else:
                    out[i] = HostColumn(arr, dt)
            rows_m.add(batch.num_rows_raw)
            yield ColumnarBatch(out, batch.num_rows_raw, self._schema,
                                meta=batch.meta)

    def describe(self):
        tags = []
        plain_host = [i for i in self.host_idx
                      if i not in self.dict_chain
                      and i not in self.rect_chain]
        if plain_host:
            tags.append("host_fallback="
                        f"{[self.exprs[i].name_hint for i in plain_host]}")
        if self.dict_chain:
            tags.append("dict_transform="
                        f"{[self.exprs[i].name_hint for i in self.dict_chain]}")
        rect_only = [i for i in self.rect_chain if i not in self.dict_chain]
        if rect_only:
            tags.append("rect_device="
                        f"{[self.exprs[i].name_hint for i in rect_only]}")
        return ("Project[" + ", ".join(e.name_hint for e in self.exprs) + "]"
                + (" " + " ".join(tags) if tags else ""))


class CpuProjectExec(TpuExec):
    """Whole-node host fallback (ref: plan stays on CPU after tagging)."""
    is_tpu = False

    def __init__(self, exprs: Sequence[Expression], child: TpuExec):
        super().__init__([child])
        self.exprs = list(exprs)
        in_schema = child.output_schema()
        self._schema = Schema([
            StructField(e.name_hint, e.data_type(in_schema), True)
            for e in self.exprs])

    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        _reset_task_state(self.exprs)
        for batch in self.children[0].execute(ctx):
            cols = []
            for e, f in zip(self.exprs, self._schema.fields):
                arr = e.eval_host(batch)
                cols.append(HostColumn(arr, f.dtype))
            yield ColumnarBatch(cols, batch.num_rows_raw, self._schema,
                                meta=batch.meta)

    def describe(self):
        return "CpuProject[" + ", ".join(e.name_hint for e in self.exprs) + "]"


class TpuFilterExec(TpuExec):
    """Device filter with O(n) compaction (ref GpuFilterExec:806)."""

    def __init__(self, condition: Expression, child: TpuExec):
        super().__init__([child])
        self.condition = condition
        self._dict_eval = None
        self._dict_checked = False

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def _dict_evaluator(self, schema):
        if not self._dict_checked:
            self._dict_checked = True
            if self.condition.fully_device_supported(schema) is not None:
                from ..exprs.compiler import build_dict_filter
                self._dict_eval = build_dict_filter(self.condition,
                                                    schema)
        return self._dict_eval

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        from ..exprs.compiler import (DictFilterFallback,
                                      filter_batch_by_mask)
        rows_m = ctx.metric(self._exec_id, "numOutputRows", ESSENTIAL)
        schema = self.children[0].output_schema()
        for batch in self.children[0].execute(ctx):
            batch = batch.ensure_device()
            dict_eval = self._dict_evaluator(schema)
            with ctx.semaphore.held():
                if dict_eval is not None:
                    out = self._filter_dict(ctx, dict_eval, batch)
                elif batch.all_device:
                    out = filter_batch_device(self.condition, batch)
                else:
                    out = self._filter_mixed(batch)
            rows_m.add(out.num_rows_raw)
            yield out    # measured-rows feedback: base execute() records

    def _filter_dict(self, ctx, dict_eval, batch):
        """String predicates evaluated once over the dictionary,
        broadcast through codes on device; per-batch host fallback when a
        string column is not dict-coded (high-cardinality bail-out)."""
        import pyarrow.compute as pc
        from ..exprs.compiler import (DictFilterFallback,
                                      filter_batch_by_mask)
        try:
            keep = dict_eval.keep_mask(batch)
            return filter_batch_by_mask(batch, keep)
        except DictFilterFallback:
            mask = pc.fill_null(self.condition.eval_host(batch), False)
            return ColumnarBatch.from_arrow(
                batch.to_arrow().filter(mask))

    def _filter_mixed(self, batch: ColumnarBatch) -> ColumnarBatch:
        from ..exprs.compiler import filter_mixed_batch
        return filter_mixed_batch(self.condition, batch)

    def describe(self):
        return f"Filter[{self.condition.name_hint}]"


class CpuFilterExec(TpuExec):
    is_tpu = False

    def __init__(self, condition: Expression, child: TpuExec):
        super().__init__([child])
        self.condition = condition

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        import pyarrow.compute as pc
        for batch in self.children[0].execute(ctx):
            mask = self.condition.eval_host(batch)
            t = batch.to_arrow().filter(pc.fill_null(mask, False))
            # host-only output: a CPU-reverted chain must not bounce
            # every batch back through HBM (downstream device execs
            # re-materialize via ensure_device when they need to);
            # measured-rows feedback records in base execute()
            yield ColumnarBatch.from_arrow_host(t)

    def describe(self):
        return f"CpuFilter[{self.condition.name_hint}]"


class TpuRangeExec(TpuExec):
    """range(start, end, step) generated directly in HBM via iota
    (ref GpuRangeExec basicPhysicalOperators.scala:1137)."""

    def __init__(self, start: int, end: int, step: int, name: str = "id",
                 batch_rows: int = 1 << 20):
        super().__init__([])
        self.start, self.end, self.step = start, end, step
        self.name = name
        self.batch_rows = batch_rows
        self._schema = Schema([StructField(name, INT64, False)])

    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        total = max(0, -(-(self.end - self.start) // self.step)
                    if self.step > 0 else -((self.start - self.end) // -self.step))
        emitted = 0
        while emitted < total or (total == 0 and emitted == 0):
            n = min(self.batch_rows, total - emitted)
            p = bucket_for(max(n, 1))
            with ctx.semaphore.held():
                base = self.start + emitted * self.step
                data = base + jnp.arange(p, dtype=jnp.int64) * self.step
                valid = jnp.arange(p) < n
                col = DeviceColumn(data, valid, INT64)
            yield ColumnarBatch([col], n, self._schema)
            emitted += n
            if total == 0:
                break

    def describe(self):
        return f"Range[{self.start},{self.end},{self.step}]"


class LimitExec(TpuExec):
    engine_neutral = True
    def __init__(self, n: int, child: TpuExec):
        super().__init__([child])
        self.n = n

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        rows_m = ctx.metric(self._exec_id, "numOutputRows", ESSENTIAL)
        remaining = self.n
        for batch in self.children[0].execute(ctx):
            if remaining <= 0:
                break
            if batch.num_rows <= remaining:
                remaining -= batch.num_rows
                rows_m.add(batch.num_rows)
                yield batch
            else:
                rows_m.add(remaining)
                yield batch.slice(0, remaining)
                remaining = 0

    def describe(self):
        return f"Limit[{self.n}]"


class UnionExec(TpuExec):
    engine_neutral = True
    def __init__(self, children: List[TpuExec]):
        super().__init__(children)

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        for c in self.children:
            yield from c.execute(ctx)

    def describe(self):
        return f"Union[{len(self.children)}]"


class BranchAlignExec(TpuExec):
    engine_neutral = True
    """Host assembly of the union-of-aggregates single pass (see
    plan/rewrites.py _rewrite_union_agg): child rows are keyed by a
    branch-id first column; emit exactly n rows in branch order with
    empty-aggregate defaults for missing branches. At most n (tiny) rows
    — host by construction, zero device dispatches."""

    def __init__(self, n: int, fill_zero: List[bool], child: TpuExec):
        super().__init__([child])
        self.n = n
        self.fill_zero = list(fill_zero)
        cs = child.output_schema()
        self._schema = Schema(list(cs.fields)[1:])

    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        import pyarrow as pa
        from ..types import to_arrow
        t = self.children[0].collect(ctx, validate=False)
        bid = t.column(0).to_pylist()
        row_of = {int(b): i for i, b in enumerate(bid) if b is not None}
        arrays = []
        for ci, f in enumerate(self._schema.fields):
            col = t.column(ci + 1)
            vals = col.to_pylist()
            default = 0 if self.fill_zero[ci] else None
            out = [vals[row_of[i]] if i in row_of else default
                   for i in range(self.n)]
            arrays.append(pa.array(out, type=to_arrow(f.dtype)))
        yield ColumnarBatch.from_arrow_host(
            pa.Table.from_arrays(arrays, names=self._schema.names()))

    def describe(self):
        return f"BranchAlign[n={self.n}]"


class CoalesceBatchesExec(TpuExec):
    """Concatenate small batches up to a target size (ref
    GpuCoalesceBatches.scala CoalesceGoal/TargetSize; RequireSingleBatch via
    target_rows=None meaning 'all')."""

    def __init__(self, child: TpuExec, target_rows: Optional[int] = None,
                 target_bytes: Optional[int] = None):
        super().__init__([child])
        self.target_rows = target_rows
        self.target_bytes = target_bytes

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        conf_bytes = self.target_bytes or ctx.conf.batch_size_bytes
        conf_rows = self.target_rows or ctx.conf.batch_size_rows
        pending: List[ColumnarBatch] = []
        rows = 0
        nbytes = 0
        concat_m = ctx.metric(self._exec_id, "concatTime", DEBUG)
        for batch in self.children[0].execute(ctx):
            pending.append(batch)
            rows += batch.num_rows
            nbytes += batch.size_bytes()
            if (self.target_rows is None and self.target_bytes is None):
                continue  # single-batch goal: concat everything at the end
            if rows >= conf_rows or nbytes >= conf_bytes:
                yield concat_batches(pending)
                pending, rows, nbytes = [], 0, 0
        if pending:
            yield concat_batches(pending)

    def describe(self):
        goal = "RequireSingleBatch" if (self.target_rows is None and
                                        self.target_bytes is None) \
            else f"TargetSize(rows={self.target_rows}, bytes={self.target_bytes})"
        return f"CoalesceBatches[{goal}]"


class TpuSampleExec(TpuExec):
    """Bernoulli sample (ref GpuSampleExec)."""

    def __init__(self, fraction: float, seed: int, child: TpuExec):
        super().__init__([child])
        self.fraction = fraction
        self.seed = seed

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        rng = np.random.RandomState(self.seed)
        for batch in self.children[0].execute(ctx):
            mask = rng.random_sample(batch.num_rows) < self.fraction
            import pyarrow as pa
            t = batch.to_arrow().filter(pa.array(mask))
            yield ColumnarBatch.from_arrow(t)


class TpuExpandExec(TpuExec):
    """Each input row emits one output row per projection set
    (ref GpuExpandExec.scala)."""

    def __init__(self, projections, names, child: TpuExec):
        super().__init__([child])
        self.projections = projections
        self.names = names
        cs = child.output_schema()
        self._schema = Schema([StructField(n, e.data_type(cs), True)
                               for n, e in zip(names, projections[0])])

    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        child_schema = self.children[0].output_schema()
        projectors = [compile_projection(p, child_schema)
                      for p in self.projections]
        for batch in self.children[0].execute(ctx):
            for proj in projectors:
                with ctx.semaphore.held():
                    cols = proj.run(batch)
                yield ColumnarBatch(cols, batch.num_rows, self._schema,
                                meta=batch.meta)
