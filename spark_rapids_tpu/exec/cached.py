"""df.cache() storage (ref ParquetCachedBatchSerializer.scala, 1,407 LoC —
`spark.sql.cache` columnar serializer storing batches PARQUET-ENCODED in
memory: far smaller than raw buffers, decode on demand).

Same design here, with the reference's main serializer capabilities:
  * codec-aware encoding (``spark.rapids.tpu.sql.cache.codec``:
    zstd / lz4 / snappy / gzip / none) — per-column compressed pages;
  * column pruning at decode time (the cache holds every column, a
    pruned read decodes only what the query needs);
  * predicate skipping over cached batches using the parquet row-group
    statistics already embedded in each blob (the cached analog of
    GpuParquetScan.filterBlocks);
  * byte accounting surfaced in explain().
"""
from __future__ import annotations

from typing import Iterator, List, Optional

from ..config import register
from ..columnar import ColumnarBatch
from ..plan.meta import PlanMeta
from ..plan.overrides import rule
from ..types import Schema
from .base import ESSENTIAL, ExecContext, TpuExec

__all__ = ["CachedRelation", "ParquetCachedScanExec", "encode_batches"]

CACHE_CODEC = register(
    "spark.rapids.tpu.sql.cache.codec", "zstd",
    "Compression codec for df.cache()'s parquet-encoded batches "
    "(zstd / lz4 / snappy / gzip / none; ref "
    "ParquetCachedBatchSerializer's compressed columnar cache format).")


def encode_batches(batches, codec: str = "zstd") -> List[bytes]:
    import io

    import pyarrow.parquet as pq
    codec = (codec or "zstd").lower()
    if codec == "none":
        codec = "NONE"
    blobs = []
    for b in batches:
        buf = io.BytesIO()
        pq.write_table(b.to_arrow(), buf, compression=codec)
        blobs.append(buf.getvalue())
    return blobs


class CachedRelation:
    """Logical node over parquet-encoded cached batches. ``columns``
    (set by the pruning pass) narrows DECODE, not storage, so one cache
    serves any projection of the cached frame."""

    def __init__(self, blobs: List[bytes], schema: Schema,
                 columns: Optional[List[str]] = None):
        self.blobs = blobs
        self._schema = schema
        self.columns = columns
        self.children = []

    def schema(self) -> Schema:
        if self.columns is None:
            return self._schema
        return Schema([self._schema[c] for c in self.columns])

    def estimated_size_bytes(self) -> int:
        return sum(len(b) for b in self.blobs)

    def describe(self):
        total = sum(len(b) for b in self.blobs)
        cols = "" if self.columns is None else f", cols={self.columns}"
        return (f"InMemoryParquetCache[{len(self.blobs)} batches, "
                f"{total}B{cols}]")

    def tree_string(self, indent: int = 0) -> str:
        return "  " * indent + self.describe() + "\n"


class ParquetCachedScanExec(TpuExec):
    def __init__(self, blobs: List[bytes], schema: Schema,
                 columns: Optional[List[str]] = None, predicate=None):
        super().__init__([])
        self.blobs = blobs
        self._schema = (schema if columns is None
                        else Schema([schema[c] for c in columns]))
        self.columns = columns
        #: pushed-down predicate for batch skipping via the parquet
        #: row-group statistics inside each cached blob
        self.predicate = predicate

    def output_schema(self) -> Schema:
        return self._schema

    def set_predicate(self, pred) -> None:
        self.predicate = pred

    def _skip_blob(self, pf) -> bool:
        """True when the predicate provably excludes every row group of
        this cached batch (shares parquet's interval matcher)."""
        if self.predicate is None:
            return False
        from ..io.parquet import _maybe_matches
        try:
            for i in range(pf.metadata.num_row_groups):
                rg = pf.metadata.row_group(i)
                stats = {}
                for j in range(rg.num_columns):
                    c = rg.column(j)
                    if c.statistics is not None \
                            and c.statistics.has_min_max:
                        stats[c.path_in_schema] = (c.statistics.min,
                                                   c.statistics.max)
                if _maybe_matches(self.predicate, stats):
                    return False
            return True
        except Exception:
            return False

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        import pyarrow as pa
        import pyarrow.parquet as pq
        rows_m = ctx.metric(self._exec_id, "numOutputRows", ESSENTIAL)
        skipped_m = ctx.metric(self._exec_id, "cachedBatchesSkipped")
        emitted = False
        for blob in self.blobs:
            pf = pq.ParquetFile(pa.BufferReader(blob))
            if self._skip_blob(pf):
                skipped_m.add(1)
                continue
            t = pf.read(columns=self.columns)
            with ctx.semaphore.held():
                b = ColumnarBatch.from_arrow(t)
            rows_m.add(b.num_rows)
            emitted = True
            yield b
        if not emitted:
            from .joins import _empty_batch
            yield _empty_batch(self._schema)

    def describe(self):
        pd = (f", pushdown={self.predicate.name_hint}"
              if self.predicate is not None else "")
        return f"ParquetCachedScan[{len(self.blobs)} batches{pd}]"


@rule(CachedRelation)
class _CachedMeta(PlanMeta):
    def convert_to_tpu(self, children):
        return ParquetCachedScanExec(self.plan.blobs, self.plan._schema,
                                     self.plan.columns)

    convert_to_cpu = convert_to_tpu
