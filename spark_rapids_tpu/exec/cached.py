"""df.cache() storage (ref ParquetCachedBatchSerializer.scala, 1,407 LoC —
`spark.sql.cache` columnar serializer storing batches PARQUET-ENCODED in
memory: far smaller than raw buffers, decode on demand).

Same design here: caching a DataFrame materializes its batches once,
parquet-encodes each into an in-memory buffer (host RAM, compressed
encodings), and replaces the plan with a scan that decodes per batch."""
from __future__ import annotations

from typing import Iterator, List

from ..columnar import ColumnarBatch
from ..plan.meta import PlanMeta
from ..plan.overrides import rule
from ..types import Schema
from .base import ESSENTIAL, ExecContext, TpuExec

__all__ = ["CachedRelation", "ParquetCachedScanExec", "encode_batches"]


def encode_batches(batches) -> List[bytes]:
    import io

    import pyarrow.parquet as pq
    blobs = []
    for b in batches:
        buf = io.BytesIO()
        pq.write_table(b.to_arrow(), buf)
        blobs.append(buf.getvalue())
    return blobs


class CachedRelation:
    """Logical node over parquet-encoded cached batches."""

    def __init__(self, blobs: List[bytes], schema: Schema):
        self.blobs = blobs
        self._schema = schema
        self.children = []

    def schema(self) -> Schema:
        return self._schema

    def describe(self):
        total = sum(len(b) for b in self.blobs)
        return f"InMemoryParquetCache[{len(self.blobs)} batches, {total}B]"

    def tree_string(self, indent: int = 0) -> str:
        return "  " * indent + self.describe() + "\n"


class ParquetCachedScanExec(TpuExec):
    def __init__(self, blobs: List[bytes], schema: Schema):
        super().__init__([])
        self.blobs = blobs
        self._schema = schema

    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        import pyarrow as pa
        import pyarrow.parquet as pq
        rows_m = ctx.metric(self._exec_id, "numOutputRows", ESSENTIAL)
        if not self.blobs:
            from .joins import _empty_batch
            yield _empty_batch(self._schema)
            return
        for blob in self.blobs:
            t = pq.read_table(pa.BufferReader(blob))
            with ctx.semaphore.held():
                b = ColumnarBatch.from_arrow(t)
            rows_m.add(b.num_rows)
            yield b

    def describe(self):
        return f"ParquetCachedScan[{len(self.blobs)} batches]"


@rule(CachedRelation)
class _CachedMeta(PlanMeta):
    def convert_to_tpu(self, children):
        return ParquetCachedScanExec(self.plan.blobs, self.plan.schema())

    convert_to_cpu = convert_to_tpu
