"""Hash-based distinct flagging — the sort-free count-distinct path.

``count(DISTINCT e) GROUP BY g`` is rewritten (plan/rewrites.py
``_rewrite_distinct_hash``) to ``count(CASE WHEN __hd THEN e END)`` over
this operator, which appends a boolean column marking the stream-global
FIRST occurrence of each ``(g, e)`` pair. Reference analog: cudf's
hash-based distinct aggregation that spark-rapids lowers count-distinct
onto (aggregateFunctions count-distinct path; the sort-based two-level
expansion in GpuAggregateExec.scala:718 is what this replaces).

TPU-first design notes:
  * The flag is computed with a PERSISTENT device hash table (open
    addressing, a fresh hash salt per probe round) driven by scatter-min
    claims inside one ``lax.while_loop``. No ``lax.sort`` anywhere — a
    sort's compile time multiplies with everything else in its XLA
    module on this backend (docs/performance.md, r4), while this module
    is elementwise + scatter/gather and compiles in seconds.
  * Zero per-batch host syncs: the while_loop's condition runs on
    device, table growth is triggered by host-known row-count upper
    bounds, and the (practically impossible) probe-exhaustion leftover
    count rides the existing speculation-validation fetch at the sink
    (ExecContext.check_speculations), which re-runs the plan if it ever
    fires.
  * Exactness: full ``(group, value)`` keys are stored and compared —
    no reliance on hash uniqueness. NaNs are canonicalized to one bit
    pattern and ``-0.0`` to ``+0.0`` (SQL distinct semantics: NaN==NaN,
    0.0 == -0.0). NULL values produce no flag (count/sum/avg DISTINCT
    ignore NULLs); a NULL group is a real group, tracked via a stored
    null bit.
"""
from __future__ import annotations

import functools
from typing import Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import ColumnarBatch, DeviceColumn
from ..types import BOOL, Schema, StructField
from .base import ESSENTIAL, ExecContext, TpuExec

__all__ = ["HashDistinctFlagExec", "CpuDistinctFlagExec"]

_C1 = np.uint64(0xBF58476D1CE4E5B9)
_C2 = np.uint64(0x94D049BB133111EB)
_GOLD = np.uint64(0x9E3779B97F4A7C15)
_BIG = np.int32(2**31 - 1)
#: probe-round safety cap; with load kept <= 1/2 the expected round
#: count is O(log n) and the cap is unreachable, but an unbounded
#: while_loop must not exist in a production kernel
_MAX_ROUNDS = 4096


def _mix64(x, salt):
    """splitmix64 finalizer (public-domain constant set)."""
    x = x + salt
    x = x ^ (x >> np.uint64(30))
    x = x * _C1
    x = x ^ (x >> np.uint64(27))
    x = x * _C2
    x = x ^ (x >> np.uint64(31))
    return x


def _norm_bits(data, dtype):
    """Canonical int64 bit representation of a device-backed column:
    equal SQL values map to equal bits (NaN -> one pattern, -0.0 -> +0.0,
    narrow ints sign-extend)."""
    np_dt = dtype.np_dtype
    if np.issubdtype(np_dt, np.floating):
        data = jnp.where(data == 0, jnp.zeros((), data.dtype), data)
        width = jnp.int32 if np_dt == np.float32 else jnp.int64
        bits = jax.lax.bitcast_convert_type(data, width)
        canonical_nan = jax.lax.bitcast_convert_type(
            jnp.full((), np.nan, data.dtype), width)
        return jnp.where(jnp.isnan(data), canonical_nan,
                         bits).astype(jnp.int64)
    return data.astype(jnp.int64)


def _probe_insert(tables, grp, gnull, val, active, rowid, padded_len):
    """Shared while_loop core: insert/lookup ``(grp, gnull, val)`` keys
    for ``active`` rows into the open-addressed tables. Returns
    (first_flags, leftover_active_count, tables)."""
    Tv, Tg, Tgn, Tocc = tables
    M = Tv.shape[0]
    mask_m = np.uint64(M - 1)
    key_u = (val.astype(jnp.uint64)
             ^ (grp.astype(jnp.uint64) * _GOLD)
             ^ (gnull.astype(jnp.uint64) << np.uint64(1)))
    flags0 = jnp.zeros(padded_len, jnp.bool_)

    def cond(st):
        r, active, _, _, _, _, _ = st
        return jnp.logical_and(jnp.any(active), r < _MAX_ROUNDS)

    def body(st):
        r, active, flags, Tv, Tg, Tgn, Tocc = st
        salt = _GOLD * (r.astype(jnp.uint64) + np.uint64(1))
        h = (_mix64(key_u, salt) & mask_m).astype(jnp.int32)
        occ = Tocc[h]
        hit = (occ & (Tv[h] == val) & (Tg[h] == grp) & (Tgn[h] == gnull))
        cand = active & ~occ
        idx = jnp.where(cand, h, M)
        claim = jnp.full(M + 1, _BIG, jnp.int32).at[idx].min(rowid)
        claimed = claim[h]
        winner = cand & (claimed == rowid)
        wi = jnp.where(winner, h, M)
        Tv = Tv.at[wi].set(val, mode="drop")
        Tg = Tg.at[wi].set(grp, mode="drop")
        Tgn = Tgn.at[wi].set(gnull, mode="drop")
        Tocc = Tocc.at[wi].set(True, mode="drop")
        # rows whose slot was claimed by a SAME-key winner this round are
        # duplicates of a now-counted value; different-key losers retry
        # under the next round's salt
        wrow = jnp.clip(claimed, 0, padded_len - 1)
        wsame = (cand & (claimed < _BIG)
                 & (val[wrow] == val) & (grp[wrow] == grp)
                 & (gnull[wrow] == gnull))
        flags = flags | winner
        active = active & ~(hit | winner | wsame)
        return r + 1, active, flags, Tv, Tg, Tgn, Tocc

    r, active, flags, Tv, Tg, Tgn, Tocc = jax.lax.while_loop(
        cond, body, (jnp.int32(0), active, flags0, Tv, Tg, Tgn, Tocc))
    leftover = jnp.sum(active).astype(jnp.int32)
    return flags, leftover, (Tv, Tg, Tgn, Tocc)


@functools.lru_cache(maxsize=None)
def _flag_kernel(has_grp: bool):
    """Batch-kernel factory: normalize key columns, probe/insert, emit
    flags. The returned builder is cached per dtype pair; jit itself
    re-specializes per (M, padded_len). Tables are donated — they are
    this exec's private state, replaced every batch."""

    def build(gdtype, vdtype):
        @functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3),
                           static_argnums=(7,))
        def run(Tv, Tg, Tgn, Tocc, gpair, vpair, num_rows, padded_len):
            rowid = jnp.arange(padded_len, dtype=jnp.int32)
            real = rowid < num_rows
            vd, vv = vpair
            val = _norm_bits(vd, vdtype)
            if has_grp:
                gd, gv = gpair
                grp = jnp.where(gv, _norm_bits(gd, gdtype),
                                jnp.zeros((), jnp.int64))
                gnull = ~gv & real
            else:
                grp = jnp.zeros(padded_len, jnp.int64)
                gnull = jnp.zeros(padded_len, jnp.bool_)
            active = real & vv
            flags, leftover, tables = _probe_insert(
                (Tv, Tg, Tgn, Tocc), grp, gnull, val, active, rowid,
                padded_len)
            return flags, real, leftover, *tables
        return run

    return functools.lru_cache(maxsize=None)(build)


@functools.partial(jax.jit, static_argnums=(4,))
def _rebuild_kernel(Tv, Tg, Tgn, Tocc, new_m: int):
    """Grow the table: reinsert every occupied slot into fresh tables of
    ``new_m`` slots (the stored keys are all distinct, so this is pure
    re-placement)."""
    old_m = Tv.shape[0]
    nTv = jnp.zeros(new_m, jnp.int64)
    nTg = jnp.zeros(new_m, jnp.int64)
    nTgn = jnp.zeros(new_m, jnp.bool_)
    nTocc = jnp.zeros(new_m, jnp.bool_)
    rowid = jnp.arange(old_m, dtype=jnp.int32)
    _, leftover, tables = _probe_insert(
        (nTv, nTg, nTgn, nTocc), Tg, Tgn, Tv, Tocc, rowid, old_m)
    return leftover, *tables


def _next_pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 1).bit_length()


class HashDistinctFlagExec(TpuExec):
    """Appends ``flag_name``: True on the first stream occurrence of each
    (key_exprs, value_expr) combination, False elsewhere (incl. NULL
    values). See module docstring."""

    #: table load is kept at or below 1/2: rows_seen*2 <= M
    _LOAD_NUM, _LOAD_DEN = 2, 1
    _MIN_SLOTS = 1 << 16

    def __init__(self, key_exprs, value_expr, flag_name: str, child):
        super().__init__([child])
        self.key_exprs = list(key_exprs)
        # the table stores ONE group word; multi-key grouping would need
        # key packing the kernel doesn't do (the rewrite never emits it)
        assert len(self.key_exprs) <= 1, "at most one distinct group key"
        self.value_expr = value_expr
        self.flag_name = flag_name
        cs = child.output_schema()
        self._schema = Schema(list(cs.fields)
                              + [StructField(flag_name, BOOL, True)])
        self._key_dtypes = [e.data_type(cs) for e in self.key_exprs]
        self._val_dtype = value_expr.data_type(cs)

    def output_schema(self) -> Schema:
        return self._schema

    def describe(self):
        k = ", ".join(e.name_hint for e in self.key_exprs)
        return (f"HashDistinctFlag[keys=[{k}], "
                f"value={self.value_expr.name_hint}]")

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        from ..exprs.compiler import compile_projection
        cs = self.children[0].output_schema()
        proj = compile_projection(self.key_exprs + [self.value_expr], cs)
        has_grp = bool(self.key_exprs)
        kern_for = _flag_kernel(has_grp)
        tables = None
        m = 0
        seen_ub = 0          # host-known upper bound on rows inserted
        flags_m = ctx.metric(self._exec_id, "distinctFlagRows", ESSENTIAL)
        for batch in self.children[0].execute(ctx):
            batch = batch.ensure_device()
            rows_ub = batch.padded_len
            seen_ub += rows_ub
            need = _next_pow2(self._LOAD_NUM * seen_ub)
            with ctx.semaphore.held():
                if tables is None:
                    m = max(self._MIN_SLOTS, need)
                    tables = (jnp.zeros(m, jnp.int64),
                              jnp.zeros(m, jnp.int64),
                              jnp.zeros(m, jnp.bool_),
                              jnp.zeros(m, jnp.bool_))
                elif m < need:
                    # grow past the target so growth stays logarithmic
                    m = need * 2
                    leftover, *tables = _rebuild_kernel(*tables, m)
                    tables = tuple(tables)
                    if ctx.speculate:
                        ctx.speculations.append((leftover, 0, None,
                                                 None))
                    elif int(leftover):
                        # non-speculative path has no deferred check:
                        # validate the rebuild synchronously
                        raise RuntimeError(
                            "distinct-flag rebuild exhausted probes")
                cols = proj.run(batch)
                vcol = cols[-1]
                gpair = ((cols[0].data, cols[0].validity) if has_grp
                         else None)
                kern = kern_for(self._key_dtypes[0] if has_grp else None,
                                self._val_dtype)
                flags, valid, leftover, *tables = kern(
                    *tables, gpair, (vcol.data, vcol.validity),
                    jnp.int32(batch.num_rows_raw), batch.padded_len)
                tables = tuple(tables)
                if not ctx.speculate:
                    # exact-sizing re-run (or speculation disabled):
                    # check synchronously and SELF-HEAL — grow the table
                    # and replay this batch; already-inserted keys hit
                    # and cannot double-flag, so OR-ing the flags is
                    # exact. Loud failure if growth doesn't resolve it.
                    for _ in range(3):
                        if int(leftover) == 0:
                            break
                        m *= 4
                        lo, *tables = _rebuild_kernel(*tables, m)
                        if int(lo):
                            raise RuntimeError(
                                "distinct-flag rebuild exhausted probes")
                        f2, _, leftover, *tables = kern(
                            *tables, gpair, (vcol.data, vcol.validity),
                            jnp.int32(batch.num_rows_raw),
                            batch.padded_len)
                        tables = tuple(tables)
                        flags = flags | f2
                    if int(leftover) != 0:
                        raise RuntimeError(
                            "distinct-flag probe exhaustion persists "
                            "after 3 table growths")
            if ctx.speculate:
                # probe exhaustion is ~impossible (load <= 1/2); if it
                # ever fires, the sink's speculation check triggers the
                # plan re-run, which takes the synchronous self-healing
                # path above (ctx.speculate is False on the re-run)
                ctx.speculations.append((leftover, 0, None, None))
            flags_m.add(batch.padded_len)
            out_cols = list(batch.columns) + [
                DeviceColumn(flags, valid, BOOL)]
            yield ColumnarBatch(out_cols, batch.num_rows_raw,
                                self._schema, meta=batch.meta)
        tables = None     # release table HBM promptly at stream end


class CpuDistinctFlagExec(TpuExec):
    """Host twin (cost-reverted path): same flag semantics via a python
    set over normalized (keys, value) tuples."""

    is_tpu = False

    def __init__(self, key_exprs, value_expr, flag_name: str, child):
        super().__init__([child])
        self.key_exprs = list(key_exprs)
        self.value_expr = value_expr
        self.flag_name = flag_name
        cs = child.output_schema()
        self._schema = Schema(list(cs.fields)
                              + [StructField(flag_name, BOOL, True)])

    def output_schema(self) -> Schema:
        return self._schema

    def describe(self):
        k = ", ".join(e.name_hint for e in self.key_exprs)
        return (f"CpuDistinctFlag[keys=[{k}], "
                f"value={self.value_expr.name_hint}]")

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        """Vectorized: every column is normalized to an int64 lane
        (floats -> canonical bit patterns with -0.0 -> +0.0 and one NaN,
        matching SQL distinct; objects -> first-seen dictionary codes;
        null masks ride as extra lanes), in-batch first occurrences come
        from pandas duplicated() over the int64 frame, and the
        cross-batch seen set stores PACKED BYTES of each normalized row
        (ADVICE r5) — one compact ~8*lanes-byte key per distinct row
        instead of a per-row python tuple of boxed objects. The host
        twin must stay within pandas speed or the engine arbitration
        mis-prices the host route."""
        import pandas as pd
        import pyarrow as pa
        seen = set()
        #: object value -> stable int64 code, assigned at first sight;
        #: persists across batches so packed keys stay comparable
        obj_codes: dict = {}
        for batch in self.children[0].execute(ctx):
            t = batch.to_arrow()
            n = t.num_rows
            arrs = []
            for e in self.key_exprs + [self.value_expr]:
                a = e.eval_host(batch)
                if isinstance(a, pa.ChunkedArray):
                    a = a.combine_chunks()
                arrs.append(a)
            lanes = []
            for a in arrs:
                # EXACT normalized representation (to_pandas would turn
                # int64-with-nulls into lossy float64, and raw NaN keys
                # break cross-batch set membership — nan != nan)
                from ..exprs.arithmetic import arrow_to_masked_numpy
                try:
                    v, _ok = arrow_to_masked_numpy(a)
                    v = np.asarray(v)
                except Exception:
                    v = np.asarray(a.to_pylist(), dtype=object)
                if v.dtype.kind == "f":
                    f = v.astype(np.float64) + 0.0
                    f = np.where(np.isnan(f), np.nan, f)
                    lanes.append(f.view(np.int64))
                elif v.dtype.kind in "biu":
                    lanes.append(v.astype(np.int64))
                elif v.dtype.kind in "mM":
                    lanes.append(v.view(np.int64))
                else:
                    lanes.append(np.fromiter(
                        (obj_codes.setdefault(x, len(obj_codes))
                         for x in v),
                        dtype=np.int64, count=len(v)))
                # pandas conflates None/NaN for floats; SQL must not
                # (NULL ignored, NaN counts) — key the null mask in
                lanes.append(np.asarray(a.is_null()).astype(np.int64))
            # C-contiguous (n, lanes) matrix: row j's packed key is its
            # raw bytes — fixed width, hashable, no boxing
            M = (np.column_stack(lanes) if lanes
                 else np.zeros((n, 0), np.int64))
            valid = ~np.asarray(arrs[-1].is_null())
            flags = np.zeros(n, np.bool_)
            first = (~pd.DataFrame(M).duplicated()).to_numpy() & valid
            idx = np.nonzero(first)[0]
            if len(idx):
                fresh = []
                for j in idx:
                    key = M[j].tobytes()
                    if key not in seen:
                        seen.add(key)
                        fresh.append(j)
                flags[np.asarray(fresh, np.int64)] = True
            t = t.append_column(self.flag_name, pa.array(flags))
            out = ColumnarBatch.from_arrow_host(t)
            out.meta = batch.meta   # keep partition_id/input_file
            yield out
