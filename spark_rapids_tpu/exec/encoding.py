"""Order-preserving key encodings for device sort / groupby / range-partition.

TPU-native core trick: Spark orderings (asc/desc, nulls first/last, NaN
greatest, -0.0 == 0.0) are implemented by turning every key column into sort
operands whose XLA ordering equals the desired row order, then ONE
``lax.sort`` over (keys..., payload...) does the whole job. The reference
gets this from cudf's typed sort (GpuSortExec.scala); XLA has no typed
multi-column null-aware sort, so the encoding IS the design.

TPU constraint honoured here: no 64-bit bitcasts (XLA's x64-rewrite does not
implement them on TPU), so
  * integers sort as themselves; descending uses ``~x`` (= -x-1, an
    overflow-free order reversal for two's complement)
  * floats rely on XLA sort's total-order comparator, which places NaN above
    +inf — exactly Spark's float ordering; descending negates (so -NaN sinks
    to the front). -0.0 and NaN are canonicalized first so grouping treats
    them as single values (ref NormalizeFloatingNumbers).
Nulls travel as a leading uint8 rank operand per key.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..exprs.base import DVal

__all__ = ["order_key_operands", "grouping_operands", "operands_equal",
           "canonicalize_floats"]


def canonicalize_floats(d):
    """-0.0 -> 0.0, every NaN -> the canonical positive NaN."""
    d = jnp.where(d == 0.0, jnp.zeros_like(d), d)
    return jnp.where(jnp.isnan(d), jnp.full_like(d, jnp.nan), d)


def order_key_operands(v: DVal, ascending: bool, nulls_first: bool):
    """One SortOrder -> sort operands ([null_rank uint8, key] for scalar
    lanes; [null_rank, length, words...] for byte-rectangle strings —
    packed big-endian int64 words order like the bytes, and the length
    operand keeps strings with trailing NULs distinct)."""
    from ..exprs.base import StrVal
    if isinstance(v.data, StrVal):
        from ..columnar.strrect import pack_words
        sv: StrVal = v.data
        if nulls_first:
            null_rank = jnp.where(v.validity, jnp.uint8(1), jnp.uint8(0))
        else:
            null_rank = jnp.where(v.validity, jnp.uint8(0), jnp.uint8(1))
        ln = jnp.where(v.validity, sv.lengths, 0)
        words = pack_words(sv.bytes_, sv.lengths)
        if not ascending:
            ln = -ln
            words = [~w for w in words]
        # words FIRST (byte order decides), length only breaks the
        # prefix-tie ("a" vs "a\x00") — zero padding already sorts short
        # strings before their extensions
        return [null_rank] + words + [ln]
    d = v.data
    if jnp.issubdtype(d.dtype, jnp.floating):
        d = canonicalize_floats(d)
        key = d if ascending else -d
        key = jnp.where(v.validity, key, jnp.zeros_like(key))
    elif d.dtype == jnp.bool_:
        k = d.astype(jnp.int8)
        key = k if ascending else (1 - k)
        key = jnp.where(v.validity, key, jnp.zeros_like(key))
    else:
        key = d if ascending else ~d
        key = jnp.where(v.validity, key, jnp.zeros_like(key))
    if nulls_first:
        null_rank = jnp.where(v.validity, jnp.uint8(1), jnp.uint8(0))
    else:
        null_rank = jnp.where(v.validity, jnp.uint8(0), jnp.uint8(1))
    return [null_rank, key]


def grouping_operands(v: DVal):
    """Key operands for groupby (order irrelevant, equality must hold:
    null == null forms one group, NaN == NaN one group)."""
    return order_key_operands(v, ascending=True, nulls_first=False)


def operands_equal(a, b):
    """Row-wise equality for boundary detection over sorted key operands;
    canonicalized NaNs must compare equal."""
    eq = a == b
    if jnp.issubdtype(a.dtype, jnp.floating):
        eq = jnp.logical_or(eq, jnp.logical_and(jnp.isnan(a), jnp.isnan(b)))
    return eq
