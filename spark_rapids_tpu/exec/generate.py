"""Generate exec: explode / posexplode / stack row generation.

Reference analog: GpuGenerateExec.scala (984 LoC, explode/posexplode with
retry+split). TPU-first split of the work:

  * the generator itself (list flattening) touches host-resident nested
    payloads and runs on the host, producing per-row repeat counts and the
    flattened output arrays;
  * the *repetition of the pass-through columns* — the wide, expensive part —
    is a device gather driven by a repeat-index map (np.repeat of arange by
    counts), the same gather-map idiom as the join (JoinGatherer.scala);
  * output size can exceed the input batch arbitrarily (big lists), so each
    input batch is processed under the split-and-retry framework: on
    SplitAndRetryOOM the input batch halves and the pieces re-run, mirroring
    GpuGenerateExec's retry handling.
"""
from __future__ import annotations

from typing import Iterator, List

import numpy as np

from ..columnar import ColumnarBatch, DeviceColumn, HostColumn
from ..columnar.bucketing import bucket_for
from ..exprs.compiler import gather_batch_device
from ..exprs.generators import Generator
from ..mem import SpillableBatch, with_retry
from ..types import Schema, StructField
from .base import ESSENTIAL, ExecContext, TpuExec

__all__ = ["TpuGenerateExec"]


class TpuGenerateExec(TpuExec):
    def __init__(self, generator: Generator, required_cols: List[str],
                 child: TpuExec, output_names: List[str] = None):
        super().__init__([child])
        self.generator = generator
        self.required_cols = list(required_cols)
        child_schema = child.output_schema()
        gen_fields = generator.generator_output(child_schema)
        if output_names:
            assert len(output_names) == len(gen_fields)
            gen_fields = [StructField(n, f.dtype, f.nullable)
                          for n, f in zip(output_names, gen_fields)]
        self._gen_fields = gen_fields
        self._schema = Schema(
            [child_schema.fields[child_schema.index_of(c)]
             for c in self.required_cols] + gen_fields)

    def output_schema(self) -> Schema:
        return self._schema

    def _generate_one(self, ctx: ExecContext, sb: SpillableBatch):
        import pyarrow as pa
        batch = sb.get()
        counts, gen_arrays = self.generator.generate(batch)
        total = int(counts.sum())
        # repeat-index gather map: output row j comes from input row rep[j]
        rep = np.repeat(np.arange(len(counts), dtype=np.int32), counts)

        out_cols: List[object] = []
        if self.required_cols:
            idxs = [batch.schema.index_of(c) for c in self.required_cols]
            dev = [i for i in idxs
                   if isinstance(batch.columns[i], DeviceColumn)]
            if dev:
                p_out = bucket_for(total)
                sub_schema = Schema([batch.schema.fields[i] for i in dev])
                sub = ColumnarBatch([batch.columns[i] for i in dev],
                                    batch.num_rows, sub_schema)
                pad = np.full(p_out - total, -1, dtype=np.int32)
                with ctx.semaphore.held():
                    gathered = gather_batch_device(
                        sub, np.concatenate([rep, pad]).astype(np.int32),
                        total, p_out)
                dev_out = dict(zip(dev, gathered.columns))
            else:
                dev_out = {}
            for i in idxs:
                c = batch.columns[i]
                if i in dev_out:
                    out_cols.append(dev_out[i])
                else:
                    arr = c.to_arrow(batch.num_rows)
                    out_cols.append(HostColumn(
                        arr.take(pa.array(rep, type=pa.int32())), c.dtype))

        for arr, f in zip(gen_arrays, self._gen_fields):
            if f.dtype.device_backed:
                with ctx.semaphore.held():
                    hb = ColumnarBatch.from_arrow(pa.table({"c": arr}))
                out_cols.append(hb.columns[0])
            else:
                out_cols.append(HostColumn(arr, f.dtype))
        out = ColumnarBatch(out_cols, total, self._schema, meta=batch.meta)
        sb.close()
        return out

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        rows_m = ctx.metric(self._exec_id, "numOutputRows", ESSENTIAL)
        for batch in self.children[0].execute(ctx):
            sb = SpillableBatch(batch, ctx.memory)
            for out in with_retry([sb],
                                  lambda b: self._generate_one(ctx, b),
                                  mm=ctx.memory, ctx=ctx,
                                  op=self._exec_id):
                rows_m.add(out.num_rows)
                yield out

    def describe(self):
        return (f"Generate[{self.generator.key()}, "
                f"required={self.required_cols}]")
