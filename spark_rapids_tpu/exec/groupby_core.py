"""Core traced groupby: encode keys -> ONE variadic lax.sort (payloads ride
the sort network) -> segmented scans -> one compaction sort. Shared by the
single-device aggregate exec (exec/aggregate.py) and the multi-chip SPMD
path (parallel/collective.py), so local and distributed aggregation are the
same maths by construction (the reference gets this by reusing cudf groupby
in both its first-pass and merge pass, GpuAggregateExec.scala:718).

TPU note: this pipeline deliberately contains NO row-sized gathers or
scatters — both serialize on the scalar core (~15-45 ms per 1M rows
measured on v5e). Values are carried through the key sort as sort payloads,
per-segment aggregation is a Hillis-Steele segmented scan
(columnar/segmented.SortedSegments), and the per-group results — which land
at each segment's last row — are packed to the front by one more variadic
sort keyed on "segment id at end rows, +inf elsewhere".
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar.segmented import SortedSegments, prefix_sum
from ..exprs.base import DVal
from .encoding import grouping_operands, operands_equal

__all__ = ["segmented_groupby"]


def segmented_groupby(keys: List[DVal], vals: List[List[DVal]],
                      aggs: Sequence, mode: str, num_rows, padded_len: int,
                      row_mask=None):
    """Returns (key_outs [(data, validity)...], partial_outs, num_groups).

    mode='update' runs agg.update, mode='merge' runs agg.merge. All inputs
    are padded device values; rows >= num_rows are ignored. Output group
    arrays have length padded_len with groups packed at the front.
    ``row_mask`` (bool[P]) overrides the row-count mask so a fused
    pre-filter can drop rows without a separate compaction kernel."""
    if row_mask is None:
        row_mask = jnp.arange(padded_len, dtype=jnp.int32) < num_rows
    idx = jnp.arange(padded_len, dtype=jnp.int32)

    if not keys:
        # single group over the unsorted rows; the scans' inclusive total
        # lands at the last row (dead rows contribute the neutral)
        seg = SortedSegments(idx == 0, row_mask, orig_index=idx)
        num_groups = jnp.int32(1)
        partial_rows = _run_aggs(aggs, vals, seg, mode, row_mask)
        key_outs: List[Tuple] = []
        partial_outs = [(jnp.where(idx == 0, d[-1],
                                   jnp.zeros((), dtype=d.dtype)),
                         jnp.where(idx == 0, v[-1], False))
                        for d, v in partial_rows]
    else:
        pad_flag = jnp.where(row_mask, jnp.uint8(0), jnp.uint8(1))
        operands = [pad_flag]
        for k in keys:
            operands.extend(grouping_operands(k))
        n_key_ops = len(operands)
        # payloads (carried through the sort network — far cheaper than
        # row-sized gathers): original index, key columns, value columns
        payload: List = [idx]
        for k in keys:
            payload.extend((k.data, k.validity))
        for vs in vals:
            for v in vs:
                payload.extend((v.data, v.validity))
        sorted_all = jax.lax.sort(tuple(operands + payload),
                                  num_keys=n_key_ops, is_stable=True)
        s_ops = sorted_all[:n_key_ops]
        it = iter(sorted_all[n_key_ops:])
        perm = next(it)
        s_keys = [DVal(next(it), next(it), k.dtype) for k in keys]
        sorted_vals = [[DVal(next(it), next(it), v.dtype) for v in vs]
                       for vs in vals]

        differs = jnp.zeros(padded_len, dtype=jnp.bool_)
        for op in s_ops[1:]:
            prev = jnp.roll(op, 1)
            differs = jnp.logical_or(
                differs, jnp.logical_not(operands_equal(op, prev)))
        # live rows sort first (pad_flag), so the sorted-domain live mask
        # is a prefix of length sum(row_mask) — row_mask itself is in the
        # UNSORTED domain and may be arbitrary (fused pre-filter)
        s_live = idx < jnp.sum(row_mask)
        flags = jnp.logical_and(jnp.logical_or(idx == 0, differs), s_live)
        num_groups = jnp.sum(flags).astype(jnp.int32)
        # segment id without live-masking: the trailing dead region simply
        # extends the last segment (its scans see only neutrals there)
        gid_seg = prefix_sum(flags, jnp.int32) - 1

        seg = SortedSegments(flags, s_live, orig_index=perm)
        partial_rows = _run_aggs(aggs, sorted_vals, seg, mode, s_live)

        # extraction: each segment's total sits at its last LIVE row (the
        # scan there covers the whole segment; the raw key payload there is
        # a real row, unlike the trailing dead region); one stable sort
        # packs those rows — already in segment order — to the front
        one_true = jnp.ones((1,), dtype=jnp.bool_)
        nxt_flag = jnp.concatenate([flags[1:], one_true])
        nxt_dead = jnp.concatenate([jnp.logical_not(s_live[1:]), one_true])
        end_mask = jnp.logical_and(
            s_live, jnp.logical_or(nxt_flag, nxt_dead))
        ckey = jnp.where(end_mask, gid_seg, padded_len)
        carry: List = []
        for k in s_keys:
            carry.extend((k.data, k.validity))
        for d, v in partial_rows:
            carry.extend((d, v))
        packed = jax.lax.sort(tuple([ckey] + carry), num_keys=1,
                              is_stable=True)
        it = iter(packed[1:])
        key_outs = [(next(it), next(it)) for _ in keys]
        partial_outs = [(next(it), next(it)) for _ in partial_rows]

    group_live = idx < num_groups
    key_outs = [(d, jnp.logical_and(v, group_live)) for d, v in key_outs]
    partial_outs = [(d, jnp.logical_and(v, group_live))
                    for d, v in partial_outs]
    return key_outs, partial_outs, num_groups


def _run_aggs(aggs, vals, seg, mode, update_mask):
    outs = []
    for a, vs in zip(aggs, vals):
        if mode == "update":
            outs.extend(a.update(vs, seg, None, update_mask))
        else:
            outs.extend(a.merge(vs, seg, None))
    return outs
