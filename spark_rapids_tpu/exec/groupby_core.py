"""Core traced groupby: encode keys -> ONE variadic lax.sort (payloads ride
the sort network) -> segmented scans -> one compaction sort. Shared by the
single-chip aggregate exec and the SPMD fragment compiler.

Reference analog: cudf's hash groupby behind GpuHashAggregateExec
(GpuAggregateExec.scala). A hash table is the wrong shape for a TPU (random
scatter/gather serialize on the scalar core); sorting is native (variadic
bitonic sort on the VPU, 4-8 ms per 1M rows measured on v5e). Values are
carried through the key sort as sort payloads, aggregates become segmented
scans over the sorted domain, and results pack to the front with one more
sort keyed on "segment id at end rows, +inf elsewhere".

The pipeline is exposed BOTH as one traceable composition
(``segmented_groupby`` — required inside shard_map SPMD fragments and the
fused single-batch kernels) AND as three separately-traceable stages
(``stage_sort`` / ``stage_scan`` / ``stage_pack``). The split form exists
for COMPILE time: on the tunneled v5e backend, a lax.sort's compile cost
multiplies with the complexity of the surrounding module (a bare 7-operand
sort compiles in ~6 s, the same sort fed by two jnp.where's in ~22 s, and
the full fused two-key merge kernel never finished in >20 minutes), while
the three stages jitted separately compile in ~30-100 s total and add only
dispatch latency — the right trade everywhere except inside shard_map.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar.segmented import (GlobalSegments, SortedSegments,
                                  prefix_sum)
from ..exprs.base import DVal
from .encoding import grouping_operands, operands_equal

__all__ = ["segmented_groupby", "stage_sort", "stage_scan", "stage_pack",
           "global_groupby"]


def global_groupby(vals: List[List[DVal]], aggs: Sequence, mode: str,
                   num_rows, padded_len: int, row_mask=None):
    """Key-less (global) aggregation: ONE segment, evaluated as plain
    masked reductions (GlobalSegments) — every aggregate's update is a
    single vector pass instead of a log2(n) segmented scan, and ALL N
    aggregates trace into the one kernel: the q9 multi-aggregate shape
    costs one dispatch and ~N fused HBM sweeps per batch."""
    if row_mask is None:
        row_mask = jnp.arange(padded_len, dtype=jnp.int32) < num_rows
    idx = jnp.arange(padded_len, dtype=jnp.int32)
    seg = GlobalSegments(row_mask, orig_index=idx)
    num_groups = jnp.int32(1)
    partial_rows = _run_aggs(aggs, vals, seg, mode, row_mask)
    partial_outs = [(jnp.where(idx == 0, d[-1],
                               jnp.zeros((), dtype=d.dtype)),
                     jnp.where(idx == 0, v[-1], False))
                    for d, v in partial_rows]
    return [], partial_outs, num_groups


def _flatten_key(k: DVal):
    """Key payload lanes: (lanes, rebuild). Byte-rectangle strings ride
    as W/8 packed words + length (the same lanes their sort operands
    use); scalar keys as (data, validity)."""
    from ..exprs.base import StrVal
    if isinstance(k.data, StrVal):
        from ..columnar.strrect import pack_words, unpack_words
        sv: StrVal = k.data
        w = sv.bytes_.shape[1]
        lanes = list(pack_words(sv.bytes_, sv.lengths)) \
            + [sv.lengths, k.validity]

        def rebuild(ls, dtype=k.dtype, w=w):
            words, lengths, validity = ls[:-2], ls[-2], ls[-1]
            return DVal(StrVal(unpack_words(list(words), w),
                               lengths.astype(jnp.int32)),
                        validity, dtype)
        return lanes, rebuild
    lanes = [k.data, k.validity]

    def rebuild(ls, dtype=k.dtype):
        return DVal(ls[0], ls[1], dtype)
    return lanes, rebuild


def stage_sort(keys: List[DVal], vals: List[List[DVal]], num_rows,
               padded_len: int, row_mask=None):
    """Stage 1: encode key operands and run THE sort, values riding as
    payloads. Returns (s_ops, perm, s_keys, sorted_vals, live_count)."""
    if row_mask is None:
        row_mask = jnp.arange(padded_len, dtype=jnp.int32) < num_rows
    idx = jnp.arange(padded_len, dtype=jnp.int32)
    pad_flag = jnp.where(row_mask, jnp.uint8(0), jnp.uint8(1))
    operands = [pad_flag]
    for k in keys:
        operands.extend(grouping_operands(k))
    n_key_ops = len(operands)
    # payloads (carried through the sort network — far cheaper than
    # row-sized gathers): original index, key columns, value columns
    payload: List = [idx]
    rebuilds = []
    spans = []
    for k in keys:
        lanes, rebuild = _flatten_key(k)
        spans.append((len(payload), len(payload) + len(lanes)))
        payload.extend(lanes)
        rebuilds.append(rebuild)
    v_start = len(payload)
    for vs in vals:
        for v in vs:
            payload.extend((v.data, v.validity))
    sorted_all = jax.lax.sort(tuple(operands + payload),
                              num_keys=n_key_ops, is_stable=True)
    s_ops = sorted_all[:n_key_ops]
    rest = sorted_all[n_key_ops:]
    perm = rest[0]
    s_keys = [rb(rest[a:b]) for (a, b), rb in zip(spans, rebuilds)]
    it = iter(rest[v_start:])
    sorted_vals = [[DVal(next(it), next(it), v.dtype) for v in vs]
                   for vs in vals]
    live_count = jnp.sum(row_mask).astype(jnp.int32)
    return s_ops, perm, s_keys, sorted_vals, live_count


def stage_scan(aggs: Sequence, mode: str, s_ops, perm, s_keys,
               sorted_vals, live_count, padded_len: int):
    """Stage 2: segment boundaries from adjacent-key comparison, then the
    segmented scans. Returns (ckey, carry, num_groups) where ``carry`` is
    a NESTED (key_lane_groups, partial_pairs) structure the compaction
    sort moves — byte-rectangle string keys contribute a lane group of
    packed words + length + validity, scalar keys (data, validity)."""
    idx = jnp.arange(padded_len, dtype=jnp.int32)
    differs = jnp.zeros(padded_len, dtype=jnp.bool_)
    for op in s_ops[1:]:
        prev = jnp.roll(op, 1)
        differs = jnp.logical_or(
            differs, jnp.logical_not(operands_equal(op, prev)))
    # live rows sort first (pad_flag), so the sorted-domain live mask
    # is a prefix of length live_count — row_mask itself is in the
    # UNSORTED domain and may be arbitrary (fused pre-filter)
    s_live = idx < live_count
    flags = jnp.logical_and(jnp.logical_or(idx == 0, differs), s_live)
    num_groups = jnp.sum(flags).astype(jnp.int32)
    # segment id without live-masking: the trailing dead region simply
    # extends the last segment (its scans see only neutrals there)
    gid_seg = prefix_sum(flags, jnp.int32) - 1

    seg = SortedSegments(flags, s_live, orig_index=perm)
    partial_rows = _run_aggs(aggs, sorted_vals, seg, mode, s_live)

    # extraction: each segment's total sits at its last LIVE row (the
    # scan there covers the whole segment; the raw key payload there is
    # a real row, unlike the trailing dead region); one stable sort
    # packs those rows — already in segment order — to the front
    one_true = jnp.ones((1,), dtype=jnp.bool_)
    nxt_flag = jnp.concatenate([flags[1:], one_true])
    nxt_dead = jnp.concatenate([jnp.logical_not(s_live[1:]), one_true])
    end_mask = jnp.logical_and(
        s_live, jnp.logical_or(nxt_flag, nxt_dead))
    ckey = jnp.where(end_mask, gid_seg, padded_len)
    key_groups = []
    for k in s_keys:
        lanes, _rb = _flatten_key(k)
        key_groups.append(tuple(lanes))
    carry = (tuple(key_groups),
             tuple((d, v) for d, v in partial_rows))
    return ckey, carry, num_groups


def stage_pack(ckey, carry, num_groups, key_dtypes, padded_len: int):
    """Stage 3: the compaction sort over the nested carry. Returns
    (key_outs, partial_outs, num_groups) with group validities masked to
    the live prefix; a byte-rectangle key comes back as
    (StrVal, validity)."""
    from ..exprs.base import StrVal
    key_groups, partial_pairs = carry
    flat: List = []
    for g in key_groups:
        flat.extend(g)
    for d, v in partial_pairs:
        flat.extend((d, v))
    idx = jnp.arange(padded_len, dtype=jnp.int32)
    packed = jax.lax.sort(tuple([ckey] + flat), num_keys=1,
                          is_stable=True)
    it = iter(packed[1:])
    group_live = idx < num_groups
    key_outs = []
    for g, dt in zip(key_groups, key_dtypes):
        lanes = [next(it) for _ in g]
        if len(lanes) == 2:
            key_outs.append((lanes[0],
                             jnp.logical_and(lanes[1], group_live)))
        else:                      # rect string: words... + length + valid
            from ..columnar.strrect import unpack_words
            words, lengths, valid = lanes[:-2], lanes[-2], lanes[-1]
            w = 8 * len(words)
            key_outs.append((StrVal(unpack_words(list(words), w),
                                    lengths.astype(jnp.int32)),
                             jnp.logical_and(valid, group_live)))
    partial_outs = [(next(it), jnp.logical_and(next(it), group_live))
                    for _ in partial_pairs]
    return key_outs, partial_outs, num_groups


def segmented_groupby(keys: List[DVal], vals: List[List[DVal]],
                      aggs: Sequence, mode: str, num_rows, padded_len: int,
                      row_mask=None):
    """Returns (key_outs [(data, validity)...], partial_outs, num_groups).

    mode='update' runs agg.update, mode='merge' runs agg.merge. All inputs
    are padded device values; rows >= num_rows are ignored. Output group
    arrays have length padded_len with groups packed at the front.
    ``row_mask`` (bool[P]) overrides the row-count mask so a fused
    pre-filter can drop rows without a separate compaction kernel.

    One traceable composition of the three stages — required inside
    shard_map fragments and the fused single-batch kernels; the aggregate
    exec's classic path jits the stages separately instead (see module
    docstring for why)."""
    if not keys:
        return global_groupby(vals, aggs, mode, num_rows, padded_len,
                              row_mask)
    s_ops, perm, s_keys, sorted_vals, live_count = stage_sort(
        keys, vals, num_rows, padded_len, row_mask)
    ckey, carry, num_groups = stage_scan(
        aggs, mode, s_ops, perm, s_keys, sorted_vals, live_count,
        padded_len)
    return stage_pack(ckey, carry, num_groups,
                      [k.dtype for k in keys], padded_len)


def _run_aggs(aggs, vals, seg, mode, update_mask):
    outs = []
    for a, vs in zip(aggs, vals):
        if mode == "update":
            outs.extend(a.update(vs, seg, None, update_mask))
        else:
            outs.extend(a.merge(vs, seg, None))
    return outs
