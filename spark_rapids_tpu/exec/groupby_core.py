"""Core traced groupby: encode keys -> one lax.sort -> segment boundaries ->
per-aggregate segment reductions. Shared by the single-device aggregate exec
(exec/aggregate.py) and the multi-chip SPMD path (parallel/collective.py),
so local and distributed aggregation are the same maths by construction
(the reference gets this by reusing cudf groupby in both its first-pass and
merge pass, GpuAggregateExec.scala:718).
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..exprs.base import DVal
from .encoding import grouping_operands, operands_equal

__all__ = ["segmented_groupby"]


def segmented_groupby(keys: List[DVal], vals: List[List[DVal]],
                      aggs: Sequence, mode: str, num_rows, padded_len: int,
                      row_mask=None):
    """Returns (key_outs [(data, validity)...], partial_outs, num_groups).

    mode='update' runs agg.update, mode='merge' runs agg.merge. All inputs
    are padded device values; rows >= num_rows are ignored. Output group
    arrays have length padded_len with groups packed at the front.
    ``row_mask`` (bool[P]) overrides the row-count mask so a fused
    pre-filter can drop rows without a separate compaction kernel."""
    if row_mask is None:
        row_mask = jnp.arange(padded_len, dtype=jnp.int32) < num_rows
    if not keys:
        gid = jnp.where(row_mask, 0, padded_len).astype(jnp.int32)
        num_groups = jnp.int32(1)
        sorted_vals = vals
        key_outs: List[Tuple] = []
        update_mask = row_mask        # vals stay in the unsorted domain
    else:
        pad_flag = jnp.where(row_mask, jnp.uint8(0), jnp.uint8(1))
        operands = [pad_flag]
        for k in keys:
            operands.extend(grouping_operands(k))
        # sort ONLY (key operands, row index); payloads are gathered after —
        # far cheaper than carrying every column through the sort network
        perm0 = jnp.arange(padded_len, dtype=jnp.int32)
        n_key_ops = len(operands)
        sorted_all = jax.lax.sort(tuple(operands + [perm0]),
                                  num_keys=n_key_ops, is_stable=True)
        s_ops = sorted_all[:n_key_ops]
        perm = sorted_all[n_key_ops]
        idx = jnp.arange(padded_len)
        differs = jnp.zeros(padded_len, dtype=jnp.bool_)
        for op in s_ops[1:]:
            prev = jnp.roll(op, 1)
            differs = jnp.logical_or(
                differs, jnp.logical_not(operands_equal(op, prev)))
        flags = jnp.logical_or(idx == 0, differs)
        # live rows sort first (pad_flag), so the sorted-domain live mask
        # is a prefix of length sum(row_mask) — row_mask itself is in the
        # UNSORTED domain and may be arbitrary (fused pre-filter)
        s_live = idx < jnp.sum(row_mask)
        flags = jnp.logical_and(flags, s_live)
        num_groups = jnp.sum(flags).astype(jnp.int32)
        gid = jnp.where(s_live, (jnp.cumsum(flags) - 1).astype(jnp.int32),
                        padded_len)
        s_keys = [DVal(jnp.take(k.data, perm), jnp.take(k.validity, perm),
                       k.dtype) for k in keys]
        sorted_vals = [[DVal(jnp.take(v.data, perm),
                             jnp.take(v.validity, perm), v.dtype)
                        for v in vs] for vs in vals]
        key_outs = []
        safe_gid = jnp.where(flags, gid, padded_len)
        for k in s_keys:
            kd = jnp.zeros((padded_len,), dtype=k.data.dtype) \
                .at[safe_gid].set(k.data, mode="drop")
            kv = jnp.zeros((padded_len,), dtype=jnp.bool_) \
                .at[safe_gid].set(k.validity, mode="drop")
            key_outs.append((kd, kv))
        update_mask = s_live          # vals were permuted live-first

    partial_outs = []
    for a, vs in zip(aggs, sorted_vals):
        if mode == "update":
            outs = a.update(vs, gid, padded_len, update_mask)
        else:
            outs = a.merge(vs, gid, padded_len)
        partial_outs.extend(outs)

    group_live = jnp.arange(padded_len, dtype=jnp.int32) < num_groups
    key_outs = [(d, jnp.logical_and(v, group_live)) for d, v in key_outs]
    partial_outs = [(d, jnp.logical_and(v, group_live))
                    for d, v in partial_outs]
    return key_outs, partial_outs, num_groups
