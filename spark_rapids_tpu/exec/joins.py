"""Equi-join execs (ref GpuHashJoin.scala:1033, JoinGatherer.scala,
GpuShuffledHashJoinExec, GpuBroadcastNestedLoopJoinExecBase).

TPU-first design: cudf's hash join has no XLA analog, so the join is a
SORT-based group-match, all static shapes:

  phase A (count kernel): concatenate both sides' encoded keys, one
    lax.sort, segment boundaries -> per-group counts/starts for each side,
    per-group output pair counts, total output size.
  host sync: total -> output shape bucket (the reference similarly sizes
    gather maps before gathering).
  phase B (gather kernel, static output): for each output slot, locate its
    group via searchsorted over the pair-count prefix sums, derive
    (left_row, right_row) indices arithmetically, gather columns; -1 index
    = null-extended row (outer joins).

Join semantics: null keys never match (each null-key row forms a singleton
group); NaN keys match NaN (canonicalized — ref NormalizeFloatingNumbers);
left/right/full use countX' = max(countX, 1) so null-extension falls out of
the same index maths. Residual (non-equi) conditions are applied as a
post-filter for inner/cross and tagged fallback otherwise.
"""
from __future__ import annotations

import functools
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar.segmented import prefix_sum
import numpy as np

from ..columnar import ColumnarBatch, DeviceColumn, concat_batches
from ..columnar.bucketing import bucket_for
from ..exprs.base import DVal, EvalContext, Expression
from ..exprs.compiler import (_compact_kernel, eval_predicate_device,
                              filter_batch_device, gather_batch_device)
from ..mem import (SpillableBatch, with_retry_no_split,
                   wrap_spillable_sides)
from ..types import BOOL, Schema, StructField
from .base import ESSENTIAL, ExecContext, TpuExec
from .encoding import grouping_operands, operands_equal

__all__ = ["TpuHashJoinExec", "TpuNestedLoopJoinExec",
           "TpuBroadcastHashJoinExec", "CpuJoinExec"]

_COUNT_CACHE: Dict[Tuple, object] = {}
_FUSED_CACHE: Dict[Tuple, object] = {}
_GATHER_CACHE: Dict[Tuple, object] = {}
#: last observed output total per join shape (feeds speculative sizing)
_TOTAL_STATS: Dict[Tuple, int] = {}


def _build_count_kernel(lkey_exprs, rkey_exprs, lschema, rschema, join_type):
    ldtypes = [f.dtype for f in lschema.fields]
    rdtypes = [f.dtype for f in rschema.fields]

    @functools.partial(jax.jit, static_argnums=(4, 5))
    def kernel(lcols, rcols, n_l, n_r, p_l, p_r):
        ldv = [None if c is None else DVal(c[0], c[1], dt)
               for c, dt in zip(lcols, ldtypes)]
        rdv = [None if c is None else DVal(c[0], c[1], dt)
               for c, dt in zip(rcols, rdtypes)]
        lctx = EvalContext(lschema, ldv, n_l, p_l)
        rctx = EvalContext(rschema, rdv, n_r, p_r)
        lkeys = [e.eval_device(lctx) for e in lkey_exprs]
        rkeys = [e.eval_device(rctx) for e in rkey_exprs]
        P = p_l + p_r
        lmask = lctx.row_mask()
        rmask = rctx.row_mask()
        real = jnp.concatenate([lmask, rmask])
        pad = jnp.where(real, jnp.uint8(0), jnp.uint8(1))
        operands = [pad]
        null_key = jnp.zeros(P, dtype=jnp.bool_)
        for lk, rk in zip(lkeys, rkeys):
            # promote both sides to a common dtype before encoding
            wide = jnp.promote_types(lk.data.dtype, rk.data.dtype)
            both = DVal(jnp.concatenate([lk.data.astype(wide),
                                         rk.data.astype(wide)]),
                        jnp.concatenate([lk.validity, rk.validity]),
                        lk.dtype)
            operands.extend(grouping_operands(both))
            null_key = jnp.logical_or(null_key,
                                      jnp.logical_not(both.validity))
        null_key = jnp.logical_and(null_key, real)
        side = jnp.concatenate([jnp.zeros(p_l, jnp.uint8),
                                jnp.ones(p_r, jnp.uint8)])
        orig = jnp.concatenate([jnp.arange(p_l, dtype=jnp.int32),
                                jnp.arange(p_r, dtype=jnp.int32)])
        n_ops = len(operands) + 1  # + side (L rows first within a group)
        sorted_all = jax.lax.sort(
            tuple(operands + [side] + [orig, null_key.astype(jnp.uint8)]),
            num_keys=n_ops, is_stable=True)
        s_ops = sorted_all[:len(operands)]
        s_side = sorted_all[len(operands)]
        s_orig = sorted_all[n_ops]
        s_nullk = sorted_all[n_ops + 1].astype(jnp.bool_)
        idx = jnp.arange(P)
        n_total = n_l + n_r
        s_real = idx < n_total
        differs = jnp.zeros(P, dtype=jnp.bool_)
        for op in s_ops[1:]:
            prev = jnp.roll(op, 1)
            differs = jnp.logical_or(
                differs, jnp.logical_not(operands_equal(op, prev)))
        # null-key rows are singleton groups: boundary at them and after them
        flags = jnp.logical_or(idx == 0, differs)
        flags = jnp.logical_or(flags, s_nullk)
        flags = jnp.logical_or(flags, jnp.roll(s_nullk, 1) & (idx != 0))
        flags = jnp.logical_and(flags, s_real)
        gid = jnp.where(s_real, prefix_sum(flags, jnp.int32) - 1, P)
        num_groups = jnp.sum(flags).astype(jnp.int32)
        is_l = jnp.logical_and(s_side == 0, s_real)
        is_r = jnp.logical_and(s_side == 1, s_real)
        # i32 segment sums: emulated-i64 scatter combiners serialize ~4x
        # slower on the TPU scalar core (72 ms vs 18 ms per 1M rows)
        cnt_l = jax.ops.segment_sum(is_l.astype(jnp.int32), gid,
                                    num_segments=P).astype(jnp.int64)
        cnt_r = jax.ops.segment_sum(is_r.astype(jnp.int32), gid,
                                    num_segments=P).astype(jnp.int64)
        big = jnp.array(np.iinfo(np.int32).max, jnp.int32)
        start_l = jax.ops.segment_min(jnp.where(is_l, idx.astype(jnp.int32),
                                                big), gid, num_segments=P)
        start_r = jax.ops.segment_min(jnp.where(is_r, idx.astype(jnp.int32),
                                                big), gid, num_segments=P)
        # per-group output pair counts by join type
        cl1 = jnp.maximum(cnt_l, 1)
        cr1 = jnp.maximum(cnt_r, 1)
        if join_type == "inner":
            pairs = cnt_l * cnt_r
        elif join_type == "left":
            pairs = cnt_l * cr1
        elif join_type == "right":
            pairs = cl1 * cnt_r
        elif join_type == "full":
            pairs = cl1 * cr1
            # group with neither side is impossible
        elif join_type == "leftsemi":
            pairs = jnp.where(cnt_r > 0, cnt_l, 0)
        elif join_type == "leftanti":
            pairs = jnp.where(cnt_r == 0, cnt_l, 0)
        else:
            raise ValueError(join_type)
        glive = jnp.arange(P, dtype=jnp.int32) < num_groups
        pairs = jnp.where(glive, pairs, 0)
        offsets = prefix_sum(pairs)  # inclusive
        total = offsets[-1]
        return (s_orig, cnt_l, cnt_r, start_l, start_r, pairs, offsets,
                total, num_groups)

    return kernel


@functools.partial(jax.jit, static_argnums=(7,))
def _gather_index_kernel(s_orig, cnt_l, cnt_r, start_l, start_r, offsets,
                         join_cfg, out_p):
    """out slot k -> (left row index or -1, right row index or -1).
    join_cfg: (left_nullable, right_nullable, semi_like) as traced bools are
    static via closure — passed as int32 flags array instead."""
    left_nullable, right_nullable, semi_like = (join_cfg[0], join_cfg[1],
                                                join_cfg[2])
    P = offsets.shape[0]
    # group id per output slot WITHOUT searchsorted (a 1M-element binary
    # search costs ~20 serialized gather passes on TPU): scatter +1 at each
    # live group's output start position, then g = prefix_sum - 1. Empty
    # groups stack their +1 on the next start, which reproduces
    # searchsorted's "count of offsets <= k" exactly.
    pairs_g = jnp.diff(offsets, prepend=offsets[:1] * 0)
    excl = (offsets - pairs_g).astype(jnp.int32)
    # dead/empty groups scatter onto position `total`, polluting only the
    # dead output region beyond n_out (masked by the caller), exactly like
    # searchsorted's clipped result did
    starts = jnp.zeros(out_p, jnp.int32).at[excl].add(1, mode="drop")
    g = prefix_sum(starts) - 1
    gc = jnp.clip(g, 0, P - 1)
    # group-table lookups (i32 tables: 64-bit gathers pay double)
    base = jnp.take(excl, gc, mode="clip")
    k = jnp.arange(out_p, dtype=jnp.int32)
    r = k - base  # position within the group's pair block
    cl = jnp.take(cnt_l.astype(jnp.int32), gc, mode="clip")
    cr = jnp.take(cnt_r.astype(jnp.int32), gc, mode="clip")
    cr1 = jnp.maximum(cr, 1)
    # semi/anti emit each left row once regardless of right multiplicity
    cr1 = jnp.where(semi_like != 0, jnp.ones_like(cr1), cr1)
    li = r // cr1
    ri = r % cr1
    sl = jnp.take(start_l, gc, mode="clip")
    sr = jnp.take(start_r, gc, mode="clip")
    lpos = jnp.where(jnp.logical_and(left_nullable != 0, cl == 0),
                     -1, sl + li.astype(jnp.int32))
    rpos = jnp.where(jnp.logical_and(right_nullable != 0, cr == 0),
                     -1, sr + ri.astype(jnp.int32))
    l_row = jnp.where(lpos >= 0, jnp.take(s_orig, jnp.maximum(lpos, 0),
                                          mode="clip"), -1)
    r_row = jnp.where(rpos >= 0, jnp.take(s_orig, jnp.maximum(rpos, 0),
                                          mode="clip"), -1)
    return l_row.astype(jnp.int32), r_row.astype(jnp.int32)


def _packed_gather(cols, idx_rows, out_p):
    """Materialize columns by row index with ONE validity gather per 32
    columns: validities pack into int32 bit lanes before the take, so an
    n-column table pays n data gathers + ceil(n/32) validity gathers
    instead of 2n (gathers serialize per element on the TPU scalar core —
    docs/performance.md)."""
    idx = jnp.clip(idx_rows, 0, None)
    null_row = idx_rows < 0
    present = [(i, c) for i, c in enumerate(cols) if c is not None]
    outs = [None] * len(cols)
    for base in range(0, len(present), 32):
        chunk = present[base:base + 32]
        vmask = None
        for bit, (_, (d, v)) in enumerate(chunk):
            lane = v.astype(jnp.uint32) << bit
            vmask = lane if vmask is None else (vmask | lane)
        gmask = jnp.take(vmask, idx, mode="clip")
        for bit, (i, (d, v)) in enumerate(chunk):
            od = jnp.take(d, idx, mode="clip")
            ov = jnp.logical_and(((gmask >> bit) & 1).astype(jnp.bool_),
                                 jnp.logical_not(null_row))
            outs[i] = (od, ov)
    return outs


def _build_fused_join_kernel(count_kern, semi_like: bool):
    """count + gather-map + materialization in ONE dispatch (speculative
    sizing makes out_p static without reading the device total, so the
    whole join is a single kernel launch — three tunnel round trips
    become one)."""

    @functools.partial(jax.jit, static_argnums=(4, 5, 6))
    def fused(lcols, rcols, n_l, n_r, p_l, p_r, out_p, cfg):
        (s_orig, cnt_l, cnt_r, start_l, start_r, _pairs, offsets, total,
         _ng) = count_kern(lcols, rcols, n_l, n_r, p_l, p_r)
        l_row, r_row = _gather_index_kernel(
            s_orig, cnt_l, cnt_r, start_l, start_r, offsets, cfg, out_p)
        live = jnp.arange(out_p, dtype=jnp.int64) < total
        l_row = jnp.where(live, l_row, -1)
        r_row = jnp.where(live, r_row, -1)
        louts = _packed_gather(lcols, l_row, out_p)
        routs = ([] if semi_like
                 else _packed_gather(rcols, r_row, out_p))
        return total, louts, routs

    return fused


def _join_schema(ls: Schema, rs: Schema, join_type: str,
                 exists_name: str = "exists") -> Schema:
    if join_type in ("leftsemi", "leftanti"):
        return Schema(list(ls.fields))
    if join_type == "existence":
        return Schema(list(ls.fields) + [StructField(exists_name, BOOL,
                                                     nullable=False)])
    return Schema(list(ls.fields) + list(rs.fields))


@functools.partial(jax.jit, static_argnums=(3, 4))
def _matched_counts_kernel(l_row, r_row, match, p_l, p_r):
    """Per-source-row surviving-pair counts (segment sums over the pair set).
    Pairs with row index -1 (padding) fall into the overflow segment."""
    m = match.astype(jnp.int32)
    ml = jax.ops.segment_sum(m, jnp.where(l_row >= 0, l_row, p_l),
                             num_segments=p_l + 1)[:p_l]
    mr = jax.ops.segment_sum(m, jnp.where(r_row >= 0, r_row, p_r),
                             num_segments=p_r + 1)[:p_r]
    return ml, mr


@functools.partial(jax.jit, static_argnums=(5,))
def _assemble_index_kernel(l_row, r_row, match, ul, ur, out_p):
    """Build the combined output gather maps: surviving pairs first, then
    unmatched-left rows (null-extended right), then unmatched-right rows
    (null-extended left). -1 = null row."""
    buf_l = jnp.full(out_p, -1, jnp.int32)
    buf_r = jnp.full(out_p, -1, jnp.int32)
    mi = match.astype(jnp.int32)
    pos = jnp.where(match, prefix_sum(mi) - 1, out_p)
    buf_l = buf_l.at[pos].set(l_row, mode="drop")
    buf_r = buf_r.at[pos].set(r_row, mode="drop")
    nm = jnp.sum(mi)
    uli = ul.astype(jnp.int32)
    posl = jnp.where(ul, nm + prefix_sum(uli) - 1, out_p)
    buf_l = buf_l.at[posl].set(
        jnp.arange(ul.shape[0], dtype=jnp.int32), mode="drop")
    nu = nm + jnp.sum(uli)
    uri = ur.astype(jnp.int32)
    posr = jnp.where(ur, nu + prefix_sum(uri) - 1, out_p)
    buf_r = buf_r.at[posr].set(
        jnp.arange(ur.shape[0], dtype=jnp.int32), mode="drop")
    return buf_l, buf_r


def _finish_pair_join(join_type: str, lb: ColumnarBatch, rb: ColumnarBatch,
                      l_row, r_row, live, condition: Optional[Expression],
                      out_schema: Schema) -> ColumnarBatch:
    """Finish any join from a candidate pair set: evaluate the residual
    condition on the gathered pairs, then emit per join type (ref
    GpuBroadcastNestedLoopJoinExecBase / conditional JoinGatherer paths).

    ``l_row``/``r_row``: int32 candidate pair gather maps; ``live`` gates
    padding slots. Works for both key-derived candidates (conditional equi-
    joins) and the full cross product (nested loop)."""
    pair_schema = Schema(list(lb.schema.fields) + list(rb.schema.fields))
    if condition is not None:
        n_pairs = int(jnp.sum(live))
        lo = gather_batch_device(lb, l_row, n_pairs, int(l_row.shape[0]))
        ro = gather_batch_device(rb, r_row, n_pairs, int(r_row.shape[0]))
        pairs = ColumnarBatch(lo.columns + ro.columns, n_pairs, pair_schema)
        cond = eval_predicate_device(condition, pairs)
        match = jnp.logical_and(cond, live)
    else:
        match = live
    p_l, p_r = lb.padded_len, rb.padded_len
    ml, mr = _matched_counts_kernel(l_row, r_row, match, p_l, p_r)
    lmask = jnp.arange(p_l, dtype=jnp.int32) < lb.num_rows
    rmask = jnp.arange(p_r, dtype=jnp.int32) < rb.num_rows

    if join_type in ("leftsemi", "leftanti"):
        from ..exprs.compiler import filter_batch_by_mask
        keep = jnp.logical_and(ml > 0 if join_type == "leftsemi" else ml == 0,
                               lmask)
        return filter_batch_by_mask(lb, keep, schema=out_schema)
    if join_type == "existence":
        exists = DeviceColumn(ml > 0, lmask, BOOL)
        return ColumnarBatch(list(lb.columns) + [exists], lb.num_rows,
                             out_schema)

    zl = jnp.zeros_like(lmask)
    ul = jnp.logical_and(ml == 0, lmask) if join_type in ("left", "full") \
        else zl
    ur = jnp.logical_and(mr == 0, rmask) if join_type in ("right", "full") \
        else jnp.zeros_like(rmask)
    n_match = int(jnp.sum(match))
    n_ul = int(jnp.sum(ul))
    n_ur = int(jnp.sum(ur))
    if join_type == "inner":
        n_ul = n_ur = 0
        ul, ur = zl, jnp.zeros_like(rmask)
    n_out = n_match + n_ul + n_ur
    out_p = bucket_for(max(n_out, 1))
    gl, gr = _assemble_index_kernel(l_row, r_row, match, ul, ur, out_p)
    lo = gather_batch_device(lb, gl, n_out, out_p)
    ro = gather_batch_device(rb, gr, n_out, out_p)
    return ColumnarBatch(lo.columns + ro.columns, n_out, out_schema)


def _record_sides(sides) -> None:
    """Record each join side's LOGICAL size into the adaptive stats;
    ``sides`` = [(sig, spillables, schema)]. Logical bytes = the batch's
    ACTUAL device footprint scaled by its live-row fraction (the padded
    layout carries the true per-row width, including strings' code+dict
    representation); lazy device row counts from BOTH sides fetch in
    ONE packed transfer (only the big-sides shuffled join pays this
    round trip — the broadcast path's counts are already host ints)."""
    from ..columnar.packing import fetch_packed
    from ..plan.cost import record_runtime_size
    # SpillableBatch mirrors the lazy count — read it WITHOUT get(),
    # which would unspill whole batches just for a row count
    lazy = []
    for _sig, spillables, _schema in sides:
        for s in spillables:
            if not isinstance(s._num_rows, (int, np.integer)):
                lazy.append(s)
    if lazy:
        vals = fetch_packed([s._num_rows for s in lazy])
        for s, v in zip(lazy, vals):
            s._num_rows = int(v)
    for sig, spillables, schema in sides:
        total = 0.0
        for s in spillables:
            rows = int(s._num_rows)
            cap = s._cap or max(rows, 1)
            total += s.device_bytes() * (rows / max(cap, 1))
        record_runtime_size(sig, int(total))


class TpuHashJoinExec(TpuExec):
    def __init__(self, left: TpuExec, right: TpuExec, join_type: str,
                 left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression],
                 condition: Optional[Expression] = None):
        super().__init__([left, right])
        self.join_type = join_type
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.condition = condition
        ls, rs = left.output_schema(), right.output_schema()
        self._schema = _join_schema(ls, rs, join_type)

    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        rows_m = ctx.metric(self._exec_id, "numOutputRows", ESSENTIAL)
        # build side: coalesce right entirely; stream left batches
        # (ref GpuShuffledHashJoinExec build-side semantics)
        # list payloads materialize host-side: the join gather kernels move
        # 1D lanes only (columnar/nested.py with_lists_on_host)
        right_batches, left_batches = wrap_spillable_sides(
            ctx.memory,
            (b.ensure_device().with_lists_on_host()
             for b in self.children[1].execute(ctx)),
            (b.ensure_device().with_lists_on_host()
             for b in self.children[0].execute(ctx)))
        ls, rs = (self.children[0].output_schema(),
                  self.children[1].output_schema())
        total_bytes = sum(s.device_bytes() for s in right_batches +
                          left_batches)
        threshold = ctx.conf.join_subpartition_size_bytes
        if (threshold > 0 and total_bytes > threshold and self.left_keys
                and self.join_type != "cross" and self.condition is None
                and self._subpartitionable(ls, rs)):
            yield from self._subpartitioned(ctx, left_batches, right_batches,
                                            ls, rs, rows_m, total_bytes)
            return

        def run():
            with ctx.semaphore.held():
                lb = concat_batches([s.get() for s in left_batches]) \
                    if left_batches else _empty_batch(ls)
                rb = concat_batches([s.get() for s in right_batches]) \
                    if right_batches else _empty_batch(rs)
                lb = self._maybe_bloom_filter(ctx, lb, rb)
                return self._join(lb, rb, ctx)

        try:
            out = with_retry_no_split(run, ctx=ctx, op=self._exec_id)
            sigs = getattr(self, "side_sigs", None)
            if sigs is not None:
                # AQE stage stats (ref GpuCustomShuffleReaderExec): record
                # LOGICAL side sizes for the next planning of this shape
                _record_sides([(sigs[0], left_batches, ls),
                               (sigs[1], right_batches, rs)])
        finally:
            for s in right_batches + left_batches:
                s.close()
        rows_m.add(out.num_rows_raw)
        yield out

    # -- runtime bloom filter (ref InjectRuntimeFilter + jni BloomFilter):
    # inner/semi equi-joins may drop stream rows whose keys cannot be in
    # the build side before paying for the join kernel ------------------
    def _maybe_bloom_filter(self, ctx, lb: ColumnarBatch,
                            rb: ColumnarBatch) -> ColumnarBatch:
        bloom = self._build_bloom(ctx, lb.schema, rb)
        if bloom is None or lb.num_rows == 0:
            return lb
        return self._apply_bloom(ctx, bloom, lb)

    def _build_bloom(self, ctx, ls: Schema, rb: ColumnarBatch):
        """Build a bloom filter over the build side's keys, or None when
        the runtime filter does not apply (conf off, non-inner/semi join,
        join condition present, or non-device-hashable keys)."""
        from ..config import JOIN_BLOOM_FILTER
        if (not ctx.conf.get(JOIN_BLOOM_FILTER)
                or self.join_type not in ("inner", "leftsemi")
                or not self.left_keys or self.condition is not None
                or rb.num_rows == 0):
            return None
        from ..exprs.hash_fns import device_hashable
        from ..types import from_numpy_dtype
        rs = rb.schema
        self._bloom_key_dtypes = []
        for lk, rk in zip(self.left_keys, self.right_keys):
            ldt, rdt = lk.data_type(ls), rk.data_type(rs)
            if (device_hashable.reason_not_supported(ldt)
                    or device_hashable.reason_not_supported(rdt)):
                return None
            # mixed-width keys hash differently per width; promote both
            # sides to the common numpy dtype so probes match the build
            if ldt.np_dtype != rdt.np_dtype:
                try:
                    cdt = from_numpy_dtype(
                        np.promote_types(ldt.np_dtype, rdt.np_dtype))
                except Exception:
                    return None
                if device_hashable.reason_not_supported(cdt):
                    return None
                self._bloom_key_dtypes.append(cdt)
            else:
                self._bloom_key_dtypes.append(ldt)
        from ..exprs.bloom_filter import build_bloom
        from ..exprs.compiler import compile_projection
        rvals = [self._cast_key(DVal(c.data, c.validity, c.dtype), dt)
                 for c, dt in zip(compile_projection(
                     self.right_keys, rs).run(rb), self._bloom_key_dtypes)]
        return build_bloom(rvals, rb.num_rows)

    @staticmethod
    def _cast_key(v: DVal, dt) -> DVal:
        if v.dtype.np_dtype == dt.np_dtype:
            return v
        return DVal(v.data.astype(dt.np_dtype), v.validity, dt)

    def _apply_bloom(self, ctx, bloom, lb: ColumnarBatch) -> ColumnarBatch:
        from ..exprs.compiler import (compile_projection,
                                      filter_batch_by_mask)
        ls = lb.schema
        lvals = [self._cast_key(DVal(c.data, c.validity, c.dtype), dt)
                 for c, dt in zip(compile_projection(
                     self.left_keys, ls).run(lb), self._bloom_key_dtypes)]
        live = jnp.arange(lb.padded_len, dtype=jnp.int32) < lb.num_rows
        keep = jnp.logical_and(bloom.might_contain_mask(lvals), live)
        out = filter_batch_by_mask(lb, keep)
        ctx.metric(self._exec_id, "bloomFilterRowsFiltered").add(
            lb.num_rows - out.num_rows)
        return out

    # -- sub-partitioned big join (ref GpuSubPartitionHashJoin.scala,
    # JoinPartitioner at GpuShuffledSizedHashJoinExec.scala:1255-1340) ------
    def _subpartitionable(self, ls: Schema, rs: Schema) -> bool:
        from ..exprs.hash_fns import device_hashable
        for lk, rk in zip(self.left_keys, self.right_keys):
            ldt, rdt = lk.data_type(ls), rk.data_type(rs)
            if (device_hashable.reason_not_supported(ldt)
                    or device_hashable.reason_not_supported(rdt)):
                return False
            # both sides must hash identically: the join kernel promotes
            # mixed-width keys before matching, but the partitioner hashes
            # raw values — int32 5 and int64 5 hash to different words and
            # would land in different sub-partitions (silent row loss)
            if ldt.np_dtype != rdt.np_dtype:
                return False
        return True

    #: sub-partition hash seed — deliberately NOT the shuffle seed (42):
    #: after a repartition on the join keys every row of a task satisfies
    #: murmur3_42(key) % P == const, so re-hashing with the same seed would
    #: collapse all rows into one sub-partition (ref GpuSubPartitionHashJoin
    #: uses a distinct seed for the same reason)
    SUBPARTITION_SEED = 1610612741

    def _subpartitioned(self, ctx, left_batches, right_batches, ls, rs,
                        rows_m, total_bytes) -> Iterator[ColumnarBatch]:
        """Hash both sides into N sub-partitions on the same key hash and run
        N independent joins — matching keys (and null keys, which never match
        anyway) co-locate, so every equi-join type distributes over the
        partitioning. All device work (and the semaphore) is scoped inside
        the retry closure; outputs are parked spillable and yielded after
        the permit is released."""
        from ..shuffle.partitioning import partition_batch
        n_parts = 1 << max(1, (int(total_bytes) //
                                ctx.conf.join_subpartition_size_bytes
                                ).bit_length())
        n_parts = min(n_parts, 64)

        def run():
            outs = []
            try:
                with ctx.semaphore.held():
                    lb = concat_batches([s.get() for s in left_batches]) \
                        if left_batches else _empty_batch(ls)
                    rb = concat_batches([s.get() for s in right_batches]) \
                        if right_batches else _empty_batch(rs)
                    lp = partition_batch(lb, self.left_keys, n_parts,
                                         seed=self.SUBPARTITION_SEED)
                    rp = partition_batch(rb, self.right_keys, n_parts,
                                         seed=self.SUBPARTITION_SEED)
                    for p in range(n_parts):
                        lbp = lp.partition_device(p)
                        rbp = rp.partition_device(p)
                        if lbp.num_rows == 0 and rbp.num_rows == 0:
                            continue
                        out = self._join(lbp, rbp)
                        if out.num_rows:
                            outs.append(SpillableBatch(out, ctx.memory))
            except Exception:
                for s in outs:
                    s.close()
                raise
            return outs

        try:
            outs = with_retry_no_split(run, ctx=ctx, op=self._exec_id)
        finally:
            for s in left_batches + right_batches:
                s.close()
        try:
            for s in outs:
                b = s.get()
                s.close()
                rows_m.add(b.num_rows)
                yield b
        except BaseException:
            # a failed unspill or an abandoned consumer would leak the
            # partitions still parked (close() is idempotent)
            for s in outs:
                s.close()
            raise

    # ------------------------------------------------------------------
    def _join(self, lb: ColumnarBatch, rb: ColumnarBatch,
              ctx: Optional[ExecContext] = None) -> ColumnarBatch:
        if self.join_type == "cross" or not self.left_keys:
            return self._cross(lb, rb)
        if (self.condition is not None and
                self.join_type != "inner") or self.join_type == "existence":
            # conditional non-inner equi-join / existence: enumerate inner
            # candidate pairs on the keys, then finish through the shared
            # pair machinery (ref JoinGatherer conditional gathers)
            l_row, r_row, live = self._candidate_pairs(lb, rb)
            return _finish_pair_join(self.join_type, lb, rb, l_row, r_row,
                                     live, self.condition, self._schema)
        ls, rs = lb.schema, rb.schema
        ck = (tuple(e.key() for e in self.left_keys),
              tuple(e.key() for e in self.right_keys),
              tuple((f.name, f.dtype.name) for f in ls.fields),
              tuple((f.name, f.dtype.name) for f in rs.fields),
              self.join_type)
        kern = _COUNT_CACHE.get(ck)
        if kern is None:
            kern = _build_count_kernel(self.left_keys, self.right_keys,
                                       ls, rs, self.join_type)
            _COUNT_CACHE[ck] = kern
        lcols = [(c.data, c.validity) if isinstance(c, DeviceColumn)
                 else None for c in lb.columns]
        rcols = [(c.data, c.validity) if isinstance(c, DeviceColumn)
                 else None for c in rb.columns]
        semi_like = self.join_type in ("leftsemi", "leftanti")

        # ONE-dispatch fused path: with speculative sizing the output
        # bucket is known without reading the device total, so count +
        # gather maps + packed materialization run as a single kernel
        # (vs three launches, each a tunnel round trip)
        spec0 = (ctx is not None and ctx.speculate)
        stat0 = _TOTAL_STATS.get(ck)
        all_dev = lb.all_device and rb.all_device
        if all_dev and spec0 and self.condition is None \
                and (semi_like or stat0 is not None):
            return self._join_fused(ctx, lb, rb, lcols, rcols, ck,
                                    kern, semi_like, stat0)

        (s_orig, cnt_l, cnt_r, start_l, start_r, pairs, offsets, total,
         num_groups) = kern(lcols, rcols, jnp.int32(lb.num_rows_raw),
                            jnp.int32(rb.num_rows_raw), lb.padded_len,
                            rb.padded_len)
        # speculative output sizing: guessing the output bucket from the
        # input sizes skips the count->host->gather sync (a full tunnel
        # round trip, ~40-150 ms, PER JOIN). semi/anti have the hard bound
        # out <= n_l; inner/left/right/full register the device total with
        # the context, and the sink validates every registered total once
        # (one batched fetch) — on overflow the plan re-runs with exact
        # sizing (ColumnarBatch.num_rows also guards any other force site).
        spec = (ctx is not None and ctx.speculate)
        stat = _TOTAL_STATS.get(ck)
        if semi_like and spec:
            # hard bound: semi/anti emit at most the left input's rows, so
            # lazy sizing needs no validation at all
            n_out = total
            out_p = bucket_for(max(lb.padded_len, 1))
        elif spec and stat is not None:
            # adaptive guess from this join shape's last observed total
            # (x1.5 headroom); validated at the sink, exact re-run on
            # overflow — the AQE-statistics analog of sizing gather maps
            n_out = total
            out_p = bucket_for(max(int(stat * 1.5), 1))
            ctx.speculations.append((total, out_p, ck,
                                     getattr(self, 'plan_sig', None)))
        else:
            n_out = int(total)
            _TOTAL_STATS[ck] = n_out
            out_p = bucket_for(max(n_out, 1))
        left_nullable = 1 if self.join_type in ("right", "full") else 0
        right_nullable = 1 if self.join_type in ("left", "full") else 0
        cfg = jnp.array([left_nullable, right_nullable,
                         1 if semi_like else 0], dtype=jnp.int32)
        l_row, r_row = _gather_index_kernel(
            s_orig, cnt_l, cnt_r, start_l, start_r, offsets, cfg, out_p)
        live = jnp.arange(out_p, dtype=jnp.int64) < jnp.asarray(n_out)
        l_row = jnp.where(live, l_row, -1)
        r_row = jnp.where(live, r_row, -1)
        lo = gather_batch_device(lb, l_row, n_out, out_p)
        if semi_like:
            return ColumnarBatch(lo.columns, n_out, self._schema)
        ro = gather_batch_device(rb, r_row, n_out, out_p)
        out = ColumnarBatch(lo.columns + ro.columns, n_out, self._schema)
        if self.condition is not None:
            out = filter_batch_device(self.condition, out)
        return out

    def _join_fused(self, ctx, lb: ColumnarBatch, rb: ColumnarBatch,
                    lcols, rcols, ck, count_kern, semi_like: bool,
                    stat) -> ColumnarBatch:
        fk = _FUSED_CACHE.get(ck)
        if fk is None:
            fk = _build_fused_join_kernel(count_kern, semi_like)
            _FUSED_CACHE[ck] = fk
        if semi_like:
            out_p = bucket_for(max(lb.padded_len, 1))
        else:
            out_p = bucket_for(max(int(stat * 1.5), 1))
        left_nullable = 1 if self.join_type in ("right", "full") else 0
        right_nullable = 1 if self.join_type in ("left", "full") else 0
        cfg = jnp.array([left_nullable, right_nullable,
                         1 if semi_like else 0], dtype=jnp.int32)
        total, louts, routs = fk(lcols, rcols, jnp.int32(lb.num_rows_raw),
                                 jnp.int32(rb.num_rows_raw),
                                 lb.padded_len, rb.padded_len, out_p, cfg)
        if not semi_like:
            ctx.speculations.append((total, out_p, ck,
                                     getattr(self, 'plan_sig', None)))
        new_cols = [c.with_arrays(d, v)
                    for c, (d, v) in zip(lb.columns, louts)]
        if not semi_like:
            new_cols += [c.with_arrays(d, v)
                         for c, (d, v) in zip(rb.columns, routs)]
        return ColumnarBatch(new_cols, total, self._schema)

    def _cross(self, lb: ColumnarBatch, rb: ColumnarBatch) -> ColumnarBatch:
        n_out = lb.num_rows * rb.num_rows
        out_p = bucket_for(max(n_out, 1))
        k = jnp.arange(out_p, dtype=jnp.int64)
        li = (k // max(rb.num_rows, 1)).astype(jnp.int32)
        ri = (k % max(rb.num_rows, 1)).astype(jnp.int32)
        live = jnp.asarray(np.arange(out_p) < n_out)
        li = jnp.where(live, li, -1)
        ri = jnp.where(live, ri, -1)
        lo = gather_batch_device(lb, li, n_out, out_p)
        ro = gather_batch_device(rb, ri, n_out, out_p)
        out = ColumnarBatch(lo.columns + ro.columns, n_out, self._schema)
        if self.condition is not None:
            out = filter_batch_device(self.condition, out)
        return out

    def _candidate_pairs(self, lb: ColumnarBatch, rb: ColumnarBatch):
        """Inner-join candidate pair index arrays on the equi keys."""
        ls, rs = lb.schema, rb.schema
        ck = (tuple(e.key() for e in self.left_keys),
              tuple(e.key() for e in self.right_keys),
              tuple((f.name, f.dtype.name) for f in ls.fields),
              tuple((f.name, f.dtype.name) for f in rs.fields), "inner")
        kern = _COUNT_CACHE.get(ck)
        if kern is None:
            kern = _build_count_kernel(self.left_keys, self.right_keys,
                                       ls, rs, "inner")
            _COUNT_CACHE[ck] = kern
        lcols = [(c.data, c.validity) if isinstance(c, DeviceColumn)
                 else None for c in lb.columns]
        rcols = [(c.data, c.validity) if isinstance(c, DeviceColumn)
                 else None for c in rb.columns]
        (s_orig, cnt_l, cnt_r, start_l, start_r, _pairs, offsets, total,
         _ng) = kern(lcols, rcols, jnp.int32(lb.num_rows),
                     jnp.int32(rb.num_rows), lb.padded_len, rb.padded_len)
        n_out = int(total)
        out_p = bucket_for(max(n_out, 1))
        cfg = jnp.zeros(3, dtype=jnp.int32)
        l_row, r_row = _gather_index_kernel(
            s_orig, cnt_l, cnt_r, start_l, start_r, offsets, cfg, out_p)
        live = jnp.asarray(np.arange(out_p) < n_out)
        return (jnp.where(live, l_row, -1), jnp.where(live, r_row, -1),
                live)

    def describe(self):
        k = ", ".join(f"{a.name_hint}={b.name_hint}"
                      for a, b in zip(self.left_keys, self.right_keys))
        c = f", cond={self.condition.name_hint}" if self.condition else ""
        return f"HashJoin[{self.join_type}, keys=({k}){c}]"


def _common_arrow_type(a, b):
    """Numeric promotion for host join keys (the device kernel promotes via
    jnp.promote_types; arrow joins require identical key types). Returns
    None when no promotion exists — callers keep the original types and
    let arrow raise its type-mismatch error rather than silently casting
    one side."""
    if a.equals(b):
        return a
    import pyarrow as pa
    try:
        return pa.from_numpy_dtype(np.promote_types(a.to_pandas_dtype(),
                                                    b.to_pandas_dtype()))
    except Exception:
        return None


def _empty_batch(schema: Schema) -> ColumnarBatch:
    import pyarrow as pa
    from ..types import to_arrow
    t = pa.table({f.name: pa.array([], type=to_arrow(f.dtype))
                  for f in schema.fields})
    return ColumnarBatch.from_arrow(t)


class TpuNestedLoopJoinExec(TpuExec):
    """Nested-loop join: arbitrary (non-equi) condition, every join type
    (ref GpuBroadcastNestedLoopJoinExecBase, GpuCartesianProductExec).

    TPU-first design: the candidate pair set is the full cross product laid
    out as one static-shaped index range (li = k / n_r, ri = k % n_r); the
    condition is one fused XLA evaluation over the gathered pair batch and
    the per-type finishing (outer null-extension, semi/anti/existence) is
    the same segment-sum machinery as the conditional equi-join."""

    def __init__(self, left: TpuExec, right: TpuExec, join_type: str,
                 condition: Optional[Expression] = None):
        super().__init__([left, right])
        self.join_type = join_type
        self.condition = condition
        self._schema = _join_schema(left.output_schema(),
                                    right.output_schema(), join_type)

    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        rows_m = ctx.metric(self._exec_id, "numOutputRows", ESSENTIAL)
        ls, rs = (self.children[0].output_schema(),
                  self.children[1].output_schema())
        # list payloads materialize host-side: the join gather kernels move
        # 1D lanes only (columnar/nested.py with_lists_on_host)
        right_batches, left_batches = wrap_spillable_sides(
            ctx.memory,
            (b.ensure_device().with_lists_on_host()
             for b in self.children[1].execute(ctx)),
            (b.ensure_device().with_lists_on_host()
             for b in self.children[0].execute(ctx)))

        def run():
            with ctx.semaphore.held():
                lb = concat_batches([s.get() for s in left_batches]) \
                    if left_batches else _empty_batch(ls)
                rb = concat_batches([s.get() for s in right_batches]) \
                    if right_batches else _empty_batch(rs)
                n_pairs = lb.num_rows * rb.num_rows
                out_p = bucket_for(max(n_pairs, 1))
                k = jnp.arange(out_p, dtype=jnp.int64)
                nr = max(rb.num_rows, 1)
                li = (k // nr).astype(jnp.int32)
                ri = (k % nr).astype(jnp.int32)
                live = jnp.asarray(np.arange(out_p) < n_pairs)
                li = jnp.where(live, li, -1)
                ri = jnp.where(live, ri, -1)
                if self.join_type == "cross":
                    lo = gather_batch_device(lb, li, n_pairs, out_p)
                    ro = gather_batch_device(rb, ri, n_pairs, out_p)
                    out = ColumnarBatch(lo.columns + ro.columns, n_pairs,
                                        self._schema)
                    if self.condition is not None:
                        out = filter_batch_device(self.condition, out)
                    return out
                return _finish_pair_join(self.join_type, lb, rb, li, ri,
                                         live, self.condition, self._schema)

        try:
            out = with_retry_no_split(run, ctx=ctx, op=self._exec_id)
        finally:
            for s in right_batches + left_batches:
                s.close()
        rows_m.add(out.num_rows_raw)
        yield out

    def describe(self):
        c = f", cond={self.condition.name_hint}" if self.condition else ""
        return f"NestedLoopJoin[{self.join_type}{c}]"


class TpuBroadcastHashJoinExec(TpuHashJoinExec):
    """Equi-join against a broadcast build side (ref
    GpuBroadcastHashJoinExecBase): the build child is a
    BroadcastExchangeExec whose single cached batch is reused across every
    stream batch — the stream side is NOT coalesced, each incoming batch
    joins independently. Only join types needing no null-extension (or
    per-row marks) of the BUILD side across stream batches may stream; the
    rest take the coalesced whole-sides path."""

    #: join types streamable per build side
    STREAMABLE = {
        "right": ("inner", "left", "leftsemi", "leftanti", "existence",
                  "cross"),
        "left": ("inner", "right", "cross"),
    }

    def __init__(self, left, right, join_type, left_keys, right_keys,
                 condition=None, build_side: str = "right"):
        super().__init__(left, right, join_type, left_keys, right_keys,
                         condition)
        assert build_side in ("left", "right")
        self.build_side = build_side

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        from ..shuffle.broadcast import BroadcastExchangeExec
        bi = 1 if self.build_side == "right" else 0
        build = self.children[bi]
        if (self.join_type not in self.STREAMABLE[self.build_side]
                or not isinstance(build, BroadcastExchangeExec)):
            yield from super().do_execute(ctx)
            return
        rows_m = ctx.metric(self._exec_id, "numOutputRows", ESSENTIAL)
        bb = build.broadcast(ctx)
        if bb is not None:
            # list payloads demote like every other join intake: the
            # gather path moves 1D lanes only
            bb = bb.with_lists_on_host()
        sigs = getattr(self, "side_sigs", None)
        if sigs is not None and bb is not None:
            # record the build side's MEASURED logical bytes: an
            # over-eager broadcast flips back to shuffled next planning
            from ..plan.cost import record_runtime_size
            frac = bb.num_rows / max(bb.padded_len or bb.num_rows, 1)
            measured = int(bb.device_size_bytes() * frac)
            record_runtime_size(sigs[bi], measured)
            from .. import aqe as aqe_mod
            log = aqe_mod.LOG
            if log is not None:
                from ..aqe import AQE_BROADCAST_DEMOTE_ENABLED
                from ..config import AUTO_BROADCAST_THRESHOLD
                thr = int(ctx.conf.get(AUTO_BROADCAST_THRESHOLD))
                if (thr >= 0 and measured > thr
                        and ctx.conf.get(AQE_BROADCAST_DEMOTE_ENABLED)):
                    try:  # tpulint: never-raise
                        log.record(aqe_mod.make_decision(
                            aqe_mod.BROADCAST_DEMOTE,
                            detail=f"build side measured {measured}B > "
                                   f"threshold {thr}B; next planning "
                                   "uses shuffled join",
                            parts=1))
                    except Exception:
                        pass
        # runtime bloom filter: built ONCE from the broadcast build side,
        # applied to every stream batch (build side must be right — the
        # filter drops stream=left rows whose keys cannot match). Like
        # every device kernel here, build and probe run under the
        # semaphore with OOM retry.
        if bi == 1:
            def build_bloom_run():
                with ctx.semaphore.held():
                    return self._build_bloom(
                        ctx, self.children[0].output_schema(), bb)
            bloom = with_retry_no_split(build_bloom_run, ctx=ctx,
                                        op=self._exec_id)
        else:
            bloom = None
        produced = False
        for sb in self.children[1 - bi].execute(ctx):
            sb = sb.ensure_device().with_lists_on_host()
            def run(sb=sb):
                with ctx.semaphore.held():
                    if bloom is not None and sb.num_rows > 0:
                        sb2 = self._apply_bloom(ctx, bloom, sb)
                    else:
                        sb2 = sb
                    return (self._join(sb2, bb, ctx) if bi == 1
                            else self._join(bb, sb2, ctx))
            out = with_retry_no_split(run, ctx=ctx, op=self._exec_id)
            rows_m.add(out.num_rows_raw)
            produced = True
            yield out
        if not produced:
            empty = _empty_batch(self.children[1 - bi].output_schema())

            def run_empty():
                with ctx.semaphore.held():
                    return (self._join(empty, bb, ctx) if bi == 1
                            else self._join(bb, empty, ctx))
            yield with_retry_no_split(run_empty, ctx=ctx, op=self._exec_id)

    def describe(self):
        return "Broadcast" + super().describe()[:-1] + \
            f", build={self.build_side}]"


class CpuJoinExec(TpuExec):
    """Host fallback / oracle via Arrow's join (SQL null semantics match)."""
    is_tpu = False

    def __init__(self, left, right, join_type, left_keys, right_keys,
                 condition=None):
        super().__init__([left, right])
        self.join_type = join_type
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.condition = condition
        self._schema = _join_schema(left.output_schema(),
                                    right.output_schema(), join_type)

    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        import pyarrow as pa
        lt = self.children[0].collect(ctx, validate=False)
        rt = self.children[1].collect(ctx, validate=False)
        if (self.join_type == "existence"
                or (self.condition is not None
                    and self.join_type not in ("inner", "cross"))
                or (not self.left_keys
                    and self.join_type not in ("inner", "cross"))):
            # pair-set path: the condition (or its absence, for keyless
            # outer/semi/anti joins) decides matched-ness per row
            yield self._pairwise_host(lt, rt)
            return
        if self.join_type == "cross" or not self.left_keys:
            out = self._cross_host(lt, rt)
        else:
            lb = ColumnarBatch.from_arrow_host(lt)
            rb = ColumnarBatch.from_arrow_host(rt)
            lkn, rkn = [], []
            for i, (lk, rk) in enumerate(zip(self.left_keys,
                                             self.right_keys)):
                la = lk.eval_host(lb)
                ra = rk.eval_host(rb)
                ct = _common_arrow_type(la.type, ra.type)
                lt = lt.append_column(
                    f"__jk{i}", la.cast(ct) if ct is not None else la)
                rt = rt.append_column(
                    f"__jk{i}", ra.cast(ct) if ct is not None else ra)
                lkn.append(f"__jk{i}")
                rkn.append(f"__jk{i}")
            jt = {"inner": "inner", "left": "left outer",
                  "right": "right outer", "full": "full outer",
                  "leftsemi": "left semi", "leftanti": "left anti"}[
                      self.join_type]
            # suffix every right column to avoid collisions (restored after);
            # coalesce_keys=False keeps Spark semantics: unmatched side's
            # key columns stay null
            rt2 = rt.rename_columns([c + "\x00r" for c in rt.column_names])
            out = lt.join(rt2, keys=lkn,
                          right_keys=[c + "\x00r" for c in rkn],
                          join_type=jt, coalesce_keys=False)
            keep = [c for c in out.column_names
                    if not c.startswith("__jk")]
            out = out.select(keep)
            out = out.rename_columns([c[:-2] if c.endswith("\x00r") else c
                                      for c in out.column_names])
        if self.condition is not None:
            b = ColumnarBatch.from_arrow_host(out)
            import pyarrow.compute as pc
            mask = self.condition.eval_host(b)
            out = out.filter(pc.fill_null(mask, False))
        # host-only output (see CpuFilterExec): no device bounce on the
        # CPU-reverted path
        yield ColumnarBatch.from_arrow_host(out)

    def _cross_host(self, lt, rt):
        import pyarrow as pa
        import numpy as np
        n, m = lt.num_rows, rt.num_rows
        li = pa.array(np.repeat(np.arange(n), m))
        ri = pa.array(np.tile(np.arange(m), n))
        lo = lt.take(li)
        ro = rt.take(ri)
        arrays = list(lo.columns) + list(ro.columns)
        return pa.Table.from_arrays(arrays, names=self._schema.names())

    def _pairwise_host(self, lt, rt) -> ColumnarBatch:
        """Generic host join over an explicit candidate pair set — the only
        correct way to apply a residual condition to outer/semi/anti joins
        (the condition decides matched-ness, it does not post-filter)."""
        import pyarrow as pa
        import pyarrow.compute as pc
        n_l, n_r = lt.num_rows, rt.num_rows
        if self.left_keys:
            lb = ColumnarBatch.from_arrow_host(lt)
            rb = ColumnarBatch.from_arrow_host(rt)
            lks = [k.eval_host(lb) for k in self.left_keys]
            rks = [k.eval_host(rb) for k in self.right_keys]
            cts = [_common_arrow_type(a.type, b.type)
                   for a, b in zip(lks, rks)]
            kt_l = pa.table(
                {f"__jk{i}": a.cast(ct) if ct is not None else a
                 for i, (a, ct) in enumerate(zip(lks, cts))} |
                {"__l": pa.array(np.arange(n_l, dtype=np.int64))})
            kt_r = pa.table(
                {f"__jk{i}": a.cast(ct) if ct is not None else a
                 for i, (a, ct) in enumerate(zip(rks, cts))} |
                {"__r": pa.array(np.arange(n_r, dtype=np.int64))})
            keys = [f"__jk{i}" for i in range(len(self.left_keys))]
            pairs = kt_l.join(kt_r, keys=keys, right_keys=keys,
                              join_type="inner", coalesce_keys=True)
            li = pairs.column("__l").to_numpy()
            ri = pairs.column("__r").to_numpy()
        else:
            li = np.repeat(np.arange(n_l), n_r)
            ri = np.tile(np.arange(n_r), n_l)
        if self.condition is not None and len(li):
            pair_schema = Schema(list(self.children[0].output_schema().fields)
                                 + list(self.children[1].output_schema().fields))
            lo = lt.take(pa.array(li))
            ro = rt.take(pa.array(ri))
            pair_t = pa.Table.from_arrays(
                list(lo.columns) + list(ro.columns),
                names=[f.name for f in pair_schema.fields])
            pb = ColumnarBatch.from_arrow_host(pair_t)
            pb.schema = pair_schema
            mask = pc.fill_null(self.condition.eval_host(pb), False)
            m = mask.to_numpy(zero_copy_only=False)
            li, ri = li[m], ri[m]
        ml = np.bincount(li, minlength=n_l) if n_l else np.zeros(0, np.int64)
        names = self._schema.names()
        if self.join_type == "leftsemi":
            return ColumnarBatch.from_arrow(
                lt.take(pa.array(np.nonzero(ml > 0)[0])))
        if self.join_type == "leftanti":
            return ColumnarBatch.from_arrow(
                lt.take(pa.array(np.nonzero(ml == 0)[0])))
        if self.join_type == "existence":
            out = lt.append_column(names[-1], pa.array(ml > 0))
            return ColumnarBatch.from_arrow(out)
        mr = np.bincount(ri, minlength=n_r) if n_r else np.zeros(0, np.int64)
        gl, gr = [li], [ri]
        if self.join_type in ("left", "full"):
            u = np.nonzero(ml == 0)[0]
            gl.append(u)
            gr.append(np.full(len(u), -1, np.int64))
        if self.join_type in ("right", "full"):
            u = np.nonzero(mr == 0)[0]
            gl.append(np.full(len(u), -1, np.int64))
            gr.append(u)
        gl = np.concatenate(gl) if gl else np.zeros(0, np.int64)
        gr = np.concatenate(gr) if gr else np.zeros(0, np.int64)
        lo = lt.take(pa.array(gl, mask=gl < 0))
        ro = rt.take(pa.array(gr, mask=gr < 0))
        out = pa.Table.from_arrays(list(lo.columns) + list(ro.columns),
                                   names=names)
        return ColumnarBatch.from_arrow(out)

    def describe(self):
        return f"CpuJoin[{self.join_type}]"
