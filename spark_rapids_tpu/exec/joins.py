"""Equi-join execs (ref GpuHashJoin.scala:1033, JoinGatherer.scala,
GpuShuffledHashJoinExec, GpuBroadcastNestedLoopJoinExecBase).

TPU-first design: cudf's hash join has no XLA analog, so the join is a
SORT-based group-match, all static shapes:

  phase A (count kernel): concatenate both sides' encoded keys, one
    lax.sort, segment boundaries -> per-group counts/starts for each side,
    per-group output pair counts, total output size.
  host sync: total -> output shape bucket (the reference similarly sizes
    gather maps before gathering).
  phase B (gather kernel, static output): for each output slot, locate its
    group via searchsorted over the pair-count prefix sums, derive
    (left_row, right_row) indices arithmetically, gather columns; -1 index
    = null-extended row (outer joins).

Join semantics: null keys never match (each null-key row forms a singleton
group); NaN keys match NaN (canonicalized — ref NormalizeFloatingNumbers);
left/right/full use countX' = max(countX, 1) so null-extension falls out of
the same index maths. Residual (non-equi) conditions are applied as a
post-filter for inner/cross and tagged fallback otherwise.
"""
from __future__ import annotations

import functools
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import ColumnarBatch, DeviceColumn, concat_batches
from ..columnar.bucketing import bucket_for
from ..exprs.base import DVal, EvalContext, Expression
from ..exprs.compiler import filter_batch_device, gather_batch_device
from ..mem import SpillableBatch, with_retry_no_split
from ..types import Schema, StructField
from .base import ESSENTIAL, ExecContext, TpuExec
from .encoding import grouping_operands, operands_equal

__all__ = ["TpuHashJoinExec", "CpuJoinExec"]

_COUNT_CACHE: Dict[Tuple, object] = {}
_GATHER_CACHE: Dict[Tuple, object] = {}


def _build_count_kernel(lkey_exprs, rkey_exprs, lschema, rschema, join_type):
    ldtypes = [f.dtype for f in lschema.fields]
    rdtypes = [f.dtype for f in rschema.fields]

    @functools.partial(jax.jit, static_argnums=(4, 5))
    def kernel(lcols, rcols, n_l, n_r, p_l, p_r):
        ldv = [None if c is None else DVal(c[0], c[1], dt)
               for c, dt in zip(lcols, ldtypes)]
        rdv = [None if c is None else DVal(c[0], c[1], dt)
               for c, dt in zip(rcols, rdtypes)]
        lctx = EvalContext(lschema, ldv, n_l, p_l)
        rctx = EvalContext(rschema, rdv, n_r, p_r)
        lkeys = [e.eval_device(lctx) for e in lkey_exprs]
        rkeys = [e.eval_device(rctx) for e in rkey_exprs]
        P = p_l + p_r
        lmask = lctx.row_mask()
        rmask = rctx.row_mask()
        real = jnp.concatenate([lmask, rmask])
        pad = jnp.where(real, jnp.uint8(0), jnp.uint8(1))
        operands = [pad]
        null_key = jnp.zeros(P, dtype=jnp.bool_)
        for lk, rk in zip(lkeys, rkeys):
            # promote both sides to a common dtype before encoding
            wide = jnp.promote_types(lk.data.dtype, rk.data.dtype)
            both = DVal(jnp.concatenate([lk.data.astype(wide),
                                         rk.data.astype(wide)]),
                        jnp.concatenate([lk.validity, rk.validity]),
                        lk.dtype)
            operands.extend(grouping_operands(both))
            null_key = jnp.logical_or(null_key,
                                      jnp.logical_not(both.validity))
        null_key = jnp.logical_and(null_key, real)
        side = jnp.concatenate([jnp.zeros(p_l, jnp.uint8),
                                jnp.ones(p_r, jnp.uint8)])
        orig = jnp.concatenate([jnp.arange(p_l, dtype=jnp.int32),
                                jnp.arange(p_r, dtype=jnp.int32)])
        n_ops = len(operands) + 1  # + side (L rows first within a group)
        sorted_all = jax.lax.sort(
            tuple(operands + [side] + [orig, null_key.astype(jnp.uint8)]),
            num_keys=n_ops, is_stable=True)
        s_ops = sorted_all[:len(operands)]
        s_side = sorted_all[len(operands)]
        s_orig = sorted_all[n_ops]
        s_nullk = sorted_all[n_ops + 1].astype(jnp.bool_)
        idx = jnp.arange(P)
        n_total = n_l + n_r
        s_real = idx < n_total
        differs = jnp.zeros(P, dtype=jnp.bool_)
        for op in s_ops[1:]:
            prev = jnp.roll(op, 1)
            differs = jnp.logical_or(
                differs, jnp.logical_not(operands_equal(op, prev)))
        # null-key rows are singleton groups: boundary at them and after them
        flags = jnp.logical_or(idx == 0, differs)
        flags = jnp.logical_or(flags, s_nullk)
        flags = jnp.logical_or(flags, jnp.roll(s_nullk, 1) & (idx != 0))
        flags = jnp.logical_and(flags, s_real)
        gid = jnp.where(s_real, (jnp.cumsum(flags) - 1).astype(jnp.int32), P)
        num_groups = jnp.sum(flags).astype(jnp.int32)
        is_l = jnp.logical_and(s_side == 0, s_real)
        is_r = jnp.logical_and(s_side == 1, s_real)
        cnt_l = jax.ops.segment_sum(is_l.astype(jnp.int64), gid,
                                    num_segments=P)
        cnt_r = jax.ops.segment_sum(is_r.astype(jnp.int64), gid,
                                    num_segments=P)
        big = jnp.array(np.iinfo(np.int32).max, jnp.int32)
        start_l = jax.ops.segment_min(jnp.where(is_l, idx.astype(jnp.int32),
                                                big), gid, num_segments=P)
        start_r = jax.ops.segment_min(jnp.where(is_r, idx.astype(jnp.int32),
                                                big), gid, num_segments=P)
        # per-group output pair counts by join type
        cl1 = jnp.maximum(cnt_l, 1)
        cr1 = jnp.maximum(cnt_r, 1)
        if join_type == "inner":
            pairs = cnt_l * cnt_r
        elif join_type == "left":
            pairs = cnt_l * cr1
        elif join_type == "right":
            pairs = cl1 * cnt_r
        elif join_type == "full":
            pairs = cl1 * cr1
            # group with neither side is impossible
        elif join_type == "leftsemi":
            pairs = jnp.where(cnt_r > 0, cnt_l, 0)
        elif join_type == "leftanti":
            pairs = jnp.where(cnt_r == 0, cnt_l, 0)
        else:
            raise ValueError(join_type)
        glive = jnp.arange(P, dtype=jnp.int32) < num_groups
        pairs = jnp.where(glive, pairs, 0)
        offsets = jnp.cumsum(pairs)  # inclusive
        total = offsets[-1]
        return (s_orig, cnt_l, cnt_r, start_l, start_r, pairs, offsets,
                total, num_groups)

    return kernel


@functools.partial(jax.jit, static_argnums=(7,))
def _gather_index_kernel(s_orig, cnt_l, cnt_r, start_l, start_r, offsets,
                         join_cfg, out_p):
    """out slot k -> (left row index or -1, right row index or -1).
    join_cfg: (left_nullable, right_nullable, semi_like) as traced bools are
    static via closure — passed as int32 flags array instead."""
    left_nullable, right_nullable, semi_like = (join_cfg[0], join_cfg[1],
                                                join_cfg[2])
    k = jnp.arange(out_p, dtype=jnp.int64)
    g = jnp.searchsorted(offsets, k, side="right").astype(jnp.int32)
    gc = jnp.clip(g, 0, offsets.shape[0] - 1)
    base = jnp.where(gc > 0, jnp.take(offsets, jnp.maximum(gc - 1, 0),
                                      mode="clip"), 0)
    r = k - base  # position within the group's pair block
    cl = jnp.take(cnt_l, gc, mode="clip")
    cr = jnp.take(cnt_r, gc, mode="clip")
    cr1 = jnp.maximum(cr, 1)
    # semi/anti emit each left row once regardless of right multiplicity
    cr1 = jnp.where(semi_like != 0, jnp.ones_like(cr1), cr1)
    li = r // cr1
    ri = r % cr1
    sl = jnp.take(start_l, gc, mode="clip")
    sr = jnp.take(start_r, gc, mode="clip")
    lpos = jnp.where(jnp.logical_and(left_nullable != 0, cl == 0),
                     -1, sl + li.astype(jnp.int32))
    rpos = jnp.where(jnp.logical_and(right_nullable != 0, cr == 0),
                     -1, sr + ri.astype(jnp.int32))
    l_row = jnp.where(lpos >= 0, jnp.take(s_orig, jnp.maximum(lpos, 0),
                                          mode="clip"), -1)
    r_row = jnp.where(rpos >= 0, jnp.take(s_orig, jnp.maximum(rpos, 0),
                                          mode="clip"), -1)
    return l_row.astype(jnp.int32), r_row.astype(jnp.int32)


class TpuHashJoinExec(TpuExec):
    def __init__(self, left: TpuExec, right: TpuExec, join_type: str,
                 left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression],
                 condition: Optional[Expression] = None):
        super().__init__([left, right])
        self.join_type = join_type
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.condition = condition
        ls, rs = left.output_schema(), right.output_schema()
        if join_type in ("leftsemi", "leftanti"):
            self._schema = ls
        else:
            self._schema = Schema(list(ls.fields) + list(rs.fields))
        if condition is not None and join_type not in ("inner", "cross"):
            raise NotImplementedError(
                "residual conditions only on inner/cross joins for now")

    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        rows_m = ctx.metric(self._exec_id, "numOutputRows", ESSENTIAL)
        # build side: coalesce right entirely; stream left batches
        # (ref GpuShuffledHashJoinExec build-side semantics)
        right_batches = [SpillableBatch(b, ctx.memory)
                         for b in self.children[1].execute(ctx)]
        left_batches = [SpillableBatch(b, ctx.memory)
                        for b in self.children[0].execute(ctx)]

        def run():
            with ctx.semaphore.held():
                ls, rs = (self.children[0].output_schema(),
                          self.children[1].output_schema())
                lb = concat_batches([s.get() for s in left_batches]) \
                    if left_batches else _empty_batch(ls)
                rb = concat_batches([s.get() for s in right_batches]) \
                    if right_batches else _empty_batch(rs)
                return self._join(lb, rb)

        out = with_retry_no_split(run, ctx.memory)
        for s in right_batches + left_batches:
            s.close()
        rows_m.add(out.num_rows)
        yield out

    # ------------------------------------------------------------------
    def _join(self, lb: ColumnarBatch, rb: ColumnarBatch) -> ColumnarBatch:
        if self.join_type == "cross" or not self.left_keys:
            return self._cross(lb, rb)
        ls, rs = lb.schema, rb.schema
        ck = (tuple(e.key() for e in self.left_keys),
              tuple(e.key() for e in self.right_keys),
              tuple((f.name, f.dtype.name) for f in ls.fields),
              tuple((f.name, f.dtype.name) for f in rs.fields),
              self.join_type)
        kern = _COUNT_CACHE.get(ck)
        if kern is None:
            kern = _build_count_kernel(self.left_keys, self.right_keys,
                                       ls, rs, self.join_type)
            _COUNT_CACHE[ck] = kern
        lcols = [(c.data, c.validity) for c in lb.columns]
        rcols = [(c.data, c.validity) for c in rb.columns]
        (s_orig, cnt_l, cnt_r, start_l, start_r, pairs, offsets, total,
         num_groups) = kern(lcols, rcols, jnp.int32(lb.num_rows),
                            jnp.int32(rb.num_rows), lb.padded_len,
                            rb.padded_len)
        n_out = int(total)
        out_p = bucket_for(max(n_out, 1))
        semi_like = self.join_type in ("leftsemi", "leftanti")
        left_nullable = 1 if self.join_type in ("right", "full") else 0
        right_nullable = 1 if self.join_type in ("left", "full") else 0
        cfg = jnp.array([left_nullable, right_nullable,
                         1 if semi_like else 0], dtype=jnp.int32)
        l_row, r_row = _gather_index_kernel(
            s_orig, cnt_l, cnt_r, start_l, start_r, offsets, cfg, out_p)
        live = np.arange(out_p) < n_out
        l_row = jnp.where(jnp.asarray(live), l_row, -1)
        r_row = jnp.where(jnp.asarray(live), r_row, -1)
        lo = gather_batch_device(lb, l_row, n_out, out_p)
        if semi_like:
            return ColumnarBatch(lo.columns, n_out, self._schema)
        ro = gather_batch_device(rb, r_row, n_out, out_p)
        out = ColumnarBatch(lo.columns + ro.columns, n_out, self._schema)
        if self.condition is not None:
            out = filter_batch_device(self.condition, out)
        return out

    def _cross(self, lb: ColumnarBatch, rb: ColumnarBatch) -> ColumnarBatch:
        n_out = lb.num_rows * rb.num_rows
        out_p = bucket_for(max(n_out, 1))
        k = jnp.arange(out_p, dtype=jnp.int64)
        li = (k // max(rb.num_rows, 1)).astype(jnp.int32)
        ri = (k % max(rb.num_rows, 1)).astype(jnp.int32)
        live = jnp.asarray(np.arange(out_p) < n_out)
        li = jnp.where(live, li, -1)
        ri = jnp.where(live, ri, -1)
        lo = gather_batch_device(lb, li, n_out, out_p)
        ro = gather_batch_device(rb, ri, n_out, out_p)
        out = ColumnarBatch(lo.columns + ro.columns, n_out, self._schema)
        if self.condition is not None:
            out = filter_batch_device(self.condition, out)
        return out

    def describe(self):
        k = ", ".join(f"{a.name_hint}={b.name_hint}"
                      for a, b in zip(self.left_keys, self.right_keys))
        c = f", cond={self.condition.name_hint}" if self.condition else ""
        return f"HashJoin[{self.join_type}, keys=({k}){c}]"


def _empty_batch(schema: Schema) -> ColumnarBatch:
    import pyarrow as pa
    from ..types import to_arrow
    t = pa.table({f.name: pa.array([], type=to_arrow(f.dtype))
                  for f in schema.fields})
    return ColumnarBatch.from_arrow(t)


class CpuJoinExec(TpuExec):
    """Host fallback / oracle via Arrow's join (SQL null semantics match)."""
    is_tpu = False

    def __init__(self, left, right, join_type, left_keys, right_keys,
                 condition=None):
        super().__init__([left, right])
        self.join_type = join_type
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.condition = condition
        ls, rs = left.output_schema(), right.output_schema()
        if join_type in ("leftsemi", "leftanti"):
            self._schema = ls
        else:
            self._schema = Schema(list(ls.fields) + list(rs.fields))

    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        import pyarrow as pa
        lt = self.children[0].collect(ctx)
        rt = self.children[1].collect(ctx)
        if self.join_type == "cross" or not self.left_keys:
            out = self._cross_host(lt, rt)
        else:
            lb = ColumnarBatch.from_arrow(lt, pad=False)
            rb = ColumnarBatch.from_arrow(rt, pad=False)
            lkn, rkn = [], []
            for i, (lk, rk) in enumerate(zip(self.left_keys,
                                             self.right_keys)):
                lt = lt.append_column(f"__jk{i}", lk.eval_host(lb))
                rt = rt.append_column(f"__jk{i}", rk.eval_host(rb))
                lkn.append(f"__jk{i}")
                rkn.append(f"__jk{i}")
            jt = {"inner": "inner", "left": "left outer",
                  "right": "right outer", "full": "full outer",
                  "leftsemi": "left semi", "leftanti": "left anti"}[
                      self.join_type]
            # suffix every right column to avoid collisions (restored after);
            # coalesce_keys=False keeps Spark semantics: unmatched side's
            # key columns stay null
            rt2 = rt.rename_columns([c + "\x00r" for c in rt.column_names])
            out = lt.join(rt2, keys=lkn,
                          right_keys=[c + "\x00r" for c in rkn],
                          join_type=jt, coalesce_keys=False)
            keep = [c for c in out.column_names
                    if not c.startswith("__jk")]
            out = out.select(keep)
            out = out.rename_columns([c[:-2] if c.endswith("\x00r") else c
                                      for c in out.column_names])
        if self.condition is not None:
            b = ColumnarBatch.from_arrow(out, pad=False)
            import pyarrow.compute as pc
            mask = self.condition.eval_host(b)
            out = out.filter(pc.fill_null(mask, False))
        yield ColumnarBatch.from_arrow(out)

    def _cross_host(self, lt, rt):
        import pyarrow as pa
        import numpy as np
        n, m = lt.num_rows, rt.num_rows
        li = pa.array(np.repeat(np.arange(n), m))
        ri = pa.array(np.tile(np.arange(m), n))
        lo = lt.take(li)
        ro = rt.take(ri)
        arrays = list(lo.columns) + list(ro.columns)
        return pa.Table.from_arrays(arrays, names=self._schema.names())

    def describe(self):
        return f"CpuJoin[{self.join_type}]"
