"""Pandas-UDF execs (ref execution/python/: GpuArrowEvalPythonExec,
GpuMapInPandasExec, GpuFlatMapGroupsInPandasExec, GpuAggregateInPandasExec;
Arrow IPC bridge GpuArrowWriter.scala; PythonWorkerSemaphore.scala).

The reference ships device batches to separate Python worker processes over
Arrow IPC because its engine lives in the JVM. This engine is already
in-process Python, so the "worker" boundary collapses to a host call — the
Arrow hand-off (device batch -> Arrow -> pandas -> Arrow -> device) and the
worker-concurrency semaphore are kept, the socket is not.
"""
from __future__ import annotations

import threading
from typing import Callable, Iterator, List

from ..columnar import ColumnarBatch
from ..config import register
from ..types import Schema
from .base import ESSENTIAL, ExecContext, TpuExec

__all__ = ["MapInPandasExec", "FlatMapGroupsInPandasExec",
           "python_worker_semaphore"]

CONCURRENT_PYTHON_WORKERS = register(
    "spark.rapids.tpu.python.concurrentPythonWorkers", 0,
    "Max concurrent pandas-UDF evaluations; 0 = unlimited "
    "(ref python/PythonWorkerSemaphore.scala + PythonConfEntries).")

_SEM_LOCK = threading.Lock()
_SEMAPHORES = {}     # tpulint: guarded-by _SEM_LOCK


def python_worker_semaphore(n: int):
    """Process-wide gate keyed by permit count (the PythonWorkerSemaphore
    analog); returns None when unlimited."""
    if n <= 0:
        return None
    with _SEM_LOCK:
        if n not in _SEMAPHORES:
            _SEMAPHORES[n] = threading.Semaphore(n)
        return _SEMAPHORES[n]


class _PandasExecBase(TpuExec):
    is_tpu = True  # device batches in/out; the UDF body runs on host

    def __init__(self, child: TpuExec, fn: Callable, schema: Schema):
        super().__init__([child])
        self.fn = fn
        self._schema = schema

    def output_schema(self) -> Schema:
        return self._schema

    def _gate(self, ctx: ExecContext):
        return python_worker_semaphore(
            int(ctx.conf.get(CONCURRENT_PYTHON_WORKERS)))

    def _emit(self, pdf) -> ColumnarBatch:
        import pyarrow as pa

        from ..types import to_arrow
        fields = [(f.name, to_arrow(f.dtype)) for f in self._schema.fields]
        t = pa.Table.from_pandas(pdf, preserve_index=False)
        arrays = [t.column(n).cast(at) for n, at in fields]
        return ColumnarBatch.from_arrow(
            pa.Table.from_arrays(arrays, names=[n for n, _ in fields]))


class MapInPandasExec(_PandasExecBase):
    """df.map_in_pandas(fn): fn(pandas.DataFrame) -> pandas.DataFrame per
    batch (ref GpuMapInPandasExec)."""

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        rows_m = ctx.metric(self._exec_id, "numOutputRows", ESSENTIAL)
        gate = self._gate(ctx)
        for b in self.children[0].execute(ctx):
            pdf = b.to_arrow().to_pandas()
            if gate:
                with gate:
                    out = self.fn(pdf)
            else:
                out = self.fn(pdf)
            ob = self._emit(out)
            rows_m.add(ob.num_rows)
            yield ob

    def describe(self):
        return f"MapInPandas[{getattr(self.fn, '__name__', 'fn')}]"


class FlatMapGroupsInPandasExec(_PandasExecBase):
    """group_by(keys).apply_in_pandas(fn): fn(pandas.DataFrame per group)
    -> pandas.DataFrame (ref GpuFlatMapGroupsInPandasExec; grouping uses the
    same coalesced host grouping the CPU aggregate oracle uses)."""

    def __init__(self, child: TpuExec, keys: List[str], fn: Callable,
                 schema: Schema):
        super().__init__(child, fn, schema)
        self.keys = keys

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        import pandas as pd
        rows_m = ctx.metric(self._exec_id, "numOutputRows", ESSENTIAL)
        gate = self._gate(ctx)
        tables = [b.to_arrow() for b in self.children[0].execute(ctx)]
        if not tables:
            return
        import pyarrow as pa
        pdf = pa.concat_tables(tables).to_pandas()
        outs = []
        for _, g in pdf.groupby(self.keys, dropna=False, sort=False):
            if gate:
                with gate:
                    outs.append(self.fn(g))
            else:
                outs.append(self.fn(g))
        if not outs:
            return
        out = pd.concat(outs, ignore_index=True)
        ob = self._emit(out)
        rows_m.add(ob.num_rows)
        yield ob

    def describe(self):
        return (f"FlatMapGroupsInPandas[keys={self.keys}, "
                f"{getattr(self.fn, '__name__', 'fn')}]")
