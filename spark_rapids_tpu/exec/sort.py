"""Sort exec (ref GpuSortExec.scala:86; out-of-core iterator :281).

Device sort = encode each SortOrder into (null_rank u8, key u64) operands
(exec/encoding.py) and run ONE stable ``lax.sort`` carrying every output
column as payload.

Global sort has two regimes (selected by spark.rapids.tpu.sql.batchSizeBytes,
the reference's targetSizeBytes role):
  * small input — concatenate + one device sort (single-batch goal);
  * out-of-core — the reference's GpuOutOfCoreSortIterator re-designed
    TPU-first as a SAMPLE SORT: sort each input batch into a spillable run,
    sample each run's encoded sort keys to pick K-1 range splitters, bucket
    every run by splitter rank on device (one fused lexicographic-compare
    kernel + the contiguous-split sorter), then per bucket concat the slices
    from all runs and device-sort once more. Buckets are range-disjoint and
    emitted in order, so the stream of output batches is globally sorted
    while only ~|total|/K rows are ever resident. Sample sort replaces the
    reference's priority-queue merge because a K-way streaming merge is
    scalar-sequential (hostile to the MXU/vector units), while bucketing and
    re-sorting are single fused XLA ops over static shapes.
"""
from __future__ import annotations

import functools
from typing import Dict, Iterator, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import ColumnarBatch, DeviceColumn, concat_batches
from ..exprs.base import DVal, EvalContext
from ..mem import SpillableBatch, with_retry_no_split, wrap_spillables
from ..plan.logical import SortOrder
from ..types import Schema
from .base import ExecContext, TpuExec
from .encoding import order_key_operands

__all__ = ["TpuSortExec", "CpuSortExec", "sort_batch_device"]


def _np_total_order_key(v, valid=None):
    """uint64 whose unsigned order == Spark ascending order (host-side twin
    of exec/encoding.py; numpy has no 64-bit bitcast restriction). Strings
    and other non-numeric comparables are dense-ranked (UTF-8 byte order ==
    codepoint order, which np sorting follows); ``valid`` masks rows whose
    value may be None and must not poison the ranking."""
    import numpy as np
    v = np.asarray(v)
    if np.issubdtype(v.dtype, np.floating):
        d = v.astype(np.float64)
        d = np.where(d == 0.0, 0.0, d)
        d = np.where(np.isnan(d), np.nan, d)
        b = d.view(np.uint64)
        return np.where(b >> np.uint64(63) != 0, ~b,
                        b | np.uint64(1 << 63))
    if v.dtype == np.bool_:
        return v.astype(np.uint64)
    if v.dtype.kind in ("U", "S", "O"):
        vv = v
        if valid is not None and not valid.all():
            if not valid.any():
                return np.zeros(len(v), np.uint64)
            vv = v.copy()
            # placeholder comparable with the column's own values (could
            # be str OR Decimal); null rank decides actual order
            vv[~valid] = vv[valid][0]
        _, inv = np.unique(vv, return_inverse=True)
        return inv.astype(np.uint64)
    return v.astype(np.int64).view(np.uint64) ^ np.uint64(1 << 63)

_SORT_KERNEL_CACHE: Dict[Tuple, object] = {}


def _kernel_cache_key(orders: List[SortOrder], schema: Schema):
    return (tuple(f"{o.expr.key()}|{o.ascending}|{o.nulls_first}"
                  for o in orders),
            tuple((f.name, f.dtype.name) for f in schema.fields))


def _build_sort_kernel(orders: List[SortOrder], schema: Schema,
                       with_keys: bool = False):
    dtypes = [f.dtype for f in schema.fields]

    @functools.partial(jax.jit, static_argnums=(2,))
    def kernel(cols, num_rows, padded_len):
        dvals = [None if c is None else DVal(c[0], c[1], dt)
                 for c, dt in zip(cols, dtypes)]
        ctx = EvalContext(schema, dvals, num_rows, padded_len)
        row_mask = ctx.row_mask()
        pad_flag = jnp.where(row_mask, jnp.uint8(0), jnp.uint8(1))
        operands = [pad_flag]
        for o in orders:
            v = o.expr.eval_device(ctx)
            operands.extend(order_key_operands(v, o.ascending, o.nulls_first))
        # sort (keys, row-index) then gather columns — payload-free sort
        perm0 = jnp.arange(padded_len, dtype=jnp.int32)
        n_ops = len(operands)
        out = jax.lax.sort(tuple(operands + [perm0]), num_keys=n_ops,
                           is_stable=True)
        perm = out[n_ops]
        sorted_cols = [(jnp.take(dv.data, perm), jnp.take(dv.validity, perm))
                       for dv in dvals]
        if with_keys:
            # permuted encoded keys ride along so the out-of-core sampler
            # needn't re-evaluate the sort expressions over the run
            return sorted_cols, tuple(out[1:n_ops])
        return sorted_cols

    return kernel


def sort_batch_device(orders: List[SortOrder], batch: ColumnarBatch,
                      with_keys: bool = False):
    key = _kernel_cache_key(orders, batch.schema) + (with_keys,)
    kernel = _SORT_KERNEL_CACHE.get(key)
    if kernel is None:
        kernel = _build_sort_kernel(orders, batch.schema, with_keys)
        _SORT_KERNEL_CACHE[key] = kernel
    cols = [(c.data, c.validity) for c in batch.columns]
    outs = kernel(cols, jnp.int32(batch.num_rows), batch.padded_len)
    ops = None
    if with_keys:
        outs, ops = outs
    new_cols = [c.with_arrays(d, v)
                for (d, v), c in zip(outs, batch.columns)]
    out = ColumnarBatch(new_cols, batch.num_rows, batch.schema)
    return (out, ops) if with_keys else out


_KEYENC_CACHE: Dict[Tuple, object] = {}


def _build_keyenc_kernel(orders: List[SortOrder], schema: Schema):
    """Encoded sort-key operand arrays for a batch (same encoding the sort
    kernel orders by, so host-side splitter maths agrees with device order)."""
    dtypes = [f.dtype for f in schema.fields]

    @functools.partial(jax.jit, static_argnums=(2,))
    def kernel(cols, num_rows, padded_len):
        dvals = [None if c is None else DVal(c[0], c[1], dt)
                 for c, dt in zip(cols, dtypes)]
        ctx = EvalContext(schema, dvals, num_rows, padded_len)
        operands = []
        for o in orders:
            v = o.expr.eval_device(ctx)
            operands.extend(order_key_operands(v, o.ascending, o.nulls_first))
        return tuple(operands)

    return kernel


def _encode_keys(orders: List[SortOrder], batch: ColumnarBatch):
    key = _kernel_cache_key(orders, batch.schema)
    kern = _KEYENC_CACHE.get(key)
    if kern is None:
        kern = _build_keyenc_kernel(orders, batch.schema)
        _KEYENC_CACHE[key] = kern
    cols = [(c.data, c.validity) for c in batch.columns]
    return kern(cols, jnp.int32(batch.num_rows), batch.padded_len)


@functools.partial(jax.jit, static_argnums=(3,))
def _bucket_id_kernel(operands, splitters, num_rows, padded_len):
    """bucket(row) = #{splitters lexicographically <= row_key}; padding rows
    go to the virtual last bucket. Accumulates over splitters with a
    fori_loop so peak memory is O(P), not O(P x K) — this path runs exactly
    when HBM is tight."""
    P = padded_len
    S = splitters[0].shape[0]

    def body(i, bucket):
        gt = jnp.zeros(P, dtype=jnp.bool_)
        eq = jnp.ones(P, dtype=jnp.bool_)
        for op, sv in zip(operands, splitters):
            s = jax.lax.dynamic_index_in_dim(sv, i, keepdims=False)
            gt = jnp.logical_or(gt, jnp.logical_and(eq, op > s))
            eq = jnp.logical_and(eq, op == s)
        return bucket + jnp.logical_or(gt, eq).astype(jnp.int32)

    bucket = jax.lax.fori_loop(0, S, body, jnp.zeros(P, dtype=jnp.int32))
    live = jnp.arange(P, dtype=jnp.int32) < num_rows
    return jnp.where(live, bucket, jnp.int32(S + 1))


class TpuSortExec(TpuExec):
    #: splitter-sample rows taken per sorted run per target bucket
    OVERSAMPLE = 8

    def __init__(self, orders: List[SortOrder], child: TpuExec,
                 global_sort: bool = True):
        super().__init__([child])
        self.orders = orders
        self.global_sort = global_sort

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        if not self.global_sort:
            for batch in self.children[0].execute(ctx):
                with ctx.semaphore.held():
                    yield sort_batch_device(
                        self.orders,
                        batch.ensure_device().with_lists_on_host())
            return
        spillables = wrap_spillables(
            (b.ensure_device().with_lists_on_host()
             for b in self.children[0].execute(ctx)), ctx.memory)
        if not spillables:
            return
        total = sum(s.device_bytes() for s in spillables)
        target = ctx.conf.batch_size_bytes
        if total > target:
            yield from self._out_of_core(ctx, spillables, total, target)
            return

        def do_sort():
            with ctx.semaphore.held():
                big = concat_batches([sb.get() for sb in spillables])
                return sort_batch_device(self.orders, big)

        try:
            out = with_retry_no_split(do_sort, ctx=ctx, op=self._exec_id)
        finally:
            for sb in spillables:
                sb.close()
        yield out

    # ------------------------------------------------------------------
    def _out_of_core(self, ctx: ExecContext, spillables, total, target
                     ) -> Iterator[ColumnarBatch]:
        from ..shuffle.partitioning import (PartitionedBatches, _split_kernel,
                                            scatter_spillables)
        n_buckets = min(int(-(-total // max(target, 1))), 256)
        splits_m = ctx.metric(self._exec_id, "sortBuckets")
        splits_m.set(n_buckets)

        # pass 1: sort every batch into a run + sample its encoded keys;
        # sample counts are proportional to run size so a small run cannot
        # skew the pooled quantiles (and so bucket loads stay balanced)
        total_rows = max(sum(sb.num_rows for sb in spillables), 1)
        budget = n_buckets * self.OVERSAMPLE * len(spillables)
        runs = []
        samples = []
        try:
            for sb in spillables:
                def sort_one(sb=sb):
                    with ctx.semaphore.held():
                        run, ops = sort_batch_device(self.orders, sb.get(),
                                                     with_keys=True)
                        n = run.num_rows
                        if n == 0:
                            return SpillableBatch(run, ctx.memory), None
                        k = max(min(n, -(-budget * n // total_rows)), 1)
                        idx = jnp.asarray(
                            np.linspace(0, n - 1, num=k, dtype=np.int64))
                        samp = [np.asarray(jnp.take(op, idx)) for op in ops]
                        return SpillableBatch(run, ctx.memory), samp
                run_sb, samp = with_retry_no_split(sort_one, ctx=ctx,
                                                   op=self._exec_id)
                sb.close()
                runs.append(run_sb)
                if samp is not None:
                    samples.append(samp)
        except Exception:
            # close() is idempotent: already-consumed inputs are no-ops
            for x in runs + spillables:
                x.close()
            raise
        if not samples:
            for r in runs:
                r.close()
            return

        # pick K-1 splitters from the pooled samples (host; encoded keys
        # order identically to the device sort)
        pooled = [np.concatenate([s[j] for s in samples])
                  for j in range(len(samples[0]))]
        order = np.lexsort(tuple(reversed(pooled)))
        m = len(order)
        cut = [order[int(m * (b + 1) / n_buckets) - 1]
               for b in range(n_buckets - 1)]
        splitters = tuple(jnp.asarray(p[cut]) for p in pooled)

        # pass 2: bucket every run by splitter rank (device)
        def bucket_run(run: ColumnarBatch) -> PartitionedBatches:
            ops = _encode_keys(self.orders, run)
            pid = _bucket_id_kernel(ops, splitters, jnp.int32(run.num_rows),
                                    run.padded_len)
            arrays = [(c.data, c.validity) for c in run.columns]
            cols, counts = _split_kernel(arrays, pid, run.padded_len,
                                         n_buckets + 2)
            return PartitionedBatches(cols, np.asarray(counts)[:n_buckets],
                                      run.schema)

        bucket_slices = scatter_spillables(ctx, runs, bucket_run, n_buckets)

        # pass 3: per bucket, concat + device sort; buckets are range-
        # disjoint and ordered, so the output stream is globally sorted
        try:
            for b in range(n_buckets):
                parts = bucket_slices[b]
                if not parts:
                    continue

                def merge_bucket(parts=parts):
                    with ctx.semaphore.held():
                        big = concat_batches([p.get() for p in parts])
                        return sort_batch_device(self.orders, big)
                try:
                    out = with_retry_no_split(merge_bucket, ctx=ctx,
                                              op=self._exec_id)
                finally:
                    for p in parts:
                        p.close()
                yield out
        except BaseException:
            # fatal merge or abandoned consumer: LATER buckets' slices
            # still pin pool budget (close() is idempotent, so the
            # current bucket's already-closed parts are no-ops)
            for slot in bucket_slices:
                for p in slot:
                    p.close()
            raise

    def describe(self):
        return "Sort[" + ", ".join(map(repr, self.orders)) + "]"


class CpuSortExec(TpuExec):
    is_tpu = False

    def __init__(self, orders: List[SortOrder], child: TpuExec,
                 global_sort: bool = True):
        super().__init__([child])
        self.orders = orders
        self.global_sort = global_sort

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        import numpy as np
        import pyarrow as pa
        from ..exprs.arithmetic import arrow_to_masked_numpy
        tables = [b.to_arrow() for b in self.children[0].execute(ctx)]
        if not tables:
            return
        t = pa.concat_tables(tables)
        # host columns only: from_arrow would put columns back on device,
        # and each eval_host key fetch would then pay two tunnel syncs
        batch = ColumnarBatch.from_arrow_host(t)
        # stable lexsort with per-key order/null-placement (Spark semantics:
        # NaN greatest, -0.0 == 0.0, null rank independent per key)
        lex_keys = []
        for o in reversed(self.orders):  # np.lexsort: last key is primary
            v, ok = arrow_to_masked_numpy(o.expr.eval_host(batch))
            enc = _np_total_order_key(v, ok)
            if not o.ascending:
                enc = ~enc
            enc = np.where(ok, enc, np.uint64(0))
            rank = np.where(ok, 1, 0) if o.nulls_first else np.where(ok, 0, 1)
            lex_keys.extend([enc, rank.astype(np.uint8)])
        idx = np.lexsort(tuple(lex_keys))
        # host-only output: the sorted result is usually terminal (feeds
        # collect) — round-tripping it through HBM costs two tunnel syncs
        yield ColumnarBatch.from_arrow_host(t.take(pa.array(idx)))

    def describe(self):
        return "CpuSort[" + ", ".join(map(repr, self.orders)) + "]"
