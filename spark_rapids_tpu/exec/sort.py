"""Sort exec (ref GpuSortExec.scala:86; out-of-core iterator :281).

Device sort = encode each SortOrder into (null_rank u8, key u64) operands
(exec/encoding.py) and run ONE stable ``lax.sort`` carrying every output
column as payload. Global sort currently concatenates batches then sorts
(single-batch goal) under the retry framework; the reference's out-of-core
merge-sort with spillable pending queues is the planned widening.
"""
from __future__ import annotations

import functools
from typing import Dict, Iterator, List, Tuple

import jax
import jax.numpy as jnp

from ..columnar import ColumnarBatch, DeviceColumn, concat_batches
from ..exprs.base import DVal, EvalContext
from ..mem import SpillableBatch, with_retry_no_split
from ..plan.logical import SortOrder
from ..types import Schema
from .base import ExecContext, TpuExec
from .encoding import order_key_operands

__all__ = ["TpuSortExec", "CpuSortExec", "sort_batch_device"]


def _np_total_order_key(v):
    """uint64 whose unsigned order == Spark ascending order (host-side twin
    of exec/encoding.py; numpy has no 64-bit bitcast restriction)."""
    import numpy as np
    v = np.asarray(v)
    if np.issubdtype(v.dtype, np.floating):
        d = v.astype(np.float64)
        d = np.where(d == 0.0, 0.0, d)
        d = np.where(np.isnan(d), np.nan, d)
        b = d.view(np.uint64)
        return np.where(b >> np.uint64(63) != 0, ~b,
                        b | np.uint64(1 << 63))
    if v.dtype == np.bool_:
        return v.astype(np.uint64)
    return v.astype(np.int64).view(np.uint64) ^ np.uint64(1 << 63)

_SORT_KERNEL_CACHE: Dict[Tuple, object] = {}


def _build_sort_kernel(orders: List[SortOrder], schema: Schema):
    dtypes = [f.dtype for f in schema.fields]

    @functools.partial(jax.jit, static_argnums=(2,))
    def kernel(cols, num_rows, padded_len):
        dvals = [None if c is None else DVal(c[0], c[1], dt)
                 for c, dt in zip(cols, dtypes)]
        ctx = EvalContext(schema, dvals, num_rows, padded_len)
        row_mask = ctx.row_mask()
        pad_flag = jnp.where(row_mask, jnp.uint8(0), jnp.uint8(1))
        operands = [pad_flag]
        for o in orders:
            v = o.expr.eval_device(ctx)
            operands.extend(order_key_operands(v, o.ascending, o.nulls_first))
        # sort (keys, row-index) then gather columns — payload-free sort
        perm0 = jnp.arange(padded_len, dtype=jnp.int32)
        n_ops = len(operands)
        out = jax.lax.sort(tuple(operands + [perm0]), num_keys=n_ops,
                           is_stable=True)
        perm = out[n_ops]
        return [(jnp.take(dv.data, perm), jnp.take(dv.validity, perm))
                for dv in dvals]

    return kernel


def sort_batch_device(orders: List[SortOrder], batch: ColumnarBatch) -> ColumnarBatch:
    key = (tuple(f"{o.expr.key()}|{o.ascending}|{o.nulls_first}"
                 for o in orders),
           tuple((f.name, f.dtype.name) for f in batch.schema.fields))
    kernel = _SORT_KERNEL_CACHE.get(key)
    if kernel is None:
        kernel = _build_sort_kernel(orders, batch.schema)
        _SORT_KERNEL_CACHE[key] = kernel
    cols = [(c.data, c.validity) for c in batch.columns]
    outs = kernel(cols, jnp.int32(batch.num_rows), batch.padded_len)
    new_cols = [DeviceColumn(d, v, c.dtype)
                for (d, v), c in zip(outs, batch.columns)]
    return ColumnarBatch(new_cols, batch.num_rows, batch.schema)


class TpuSortExec(TpuExec):
    def __init__(self, orders: List[SortOrder], child: TpuExec,
                 global_sort: bool = True):
        super().__init__([child])
        self.orders = orders
        self.global_sort = global_sort

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        if not self.global_sort:
            for batch in self.children[0].execute(ctx):
                with ctx.semaphore.held():
                    yield sort_batch_device(self.orders, batch)
            return
        spillables = [SpillableBatch(b, ctx.memory)
                      for b in self.children[0].execute(ctx)]
        if not spillables:
            return

        def do_sort():
            with ctx.semaphore.held():
                big = concat_batches([sb.get() for sb in spillables])
                return sort_batch_device(self.orders, big)

        out = with_retry_no_split(do_sort, ctx.memory)
        for sb in spillables:
            sb.close()
        yield out

    def describe(self):
        return "Sort[" + ", ".join(map(repr, self.orders)) + "]"


class CpuSortExec(TpuExec):
    is_tpu = False

    def __init__(self, orders: List[SortOrder], child: TpuExec,
                 global_sort: bool = True):
        super().__init__([child])
        self.orders = orders
        self.global_sort = global_sort

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        import numpy as np
        import pyarrow as pa
        from ..exprs.arithmetic import arrow_to_masked_numpy
        tables = [b.to_arrow() for b in self.children[0].execute(ctx)]
        if not tables:
            return
        t = pa.concat_tables(tables)
        batch = ColumnarBatch.from_arrow(t, pad=False)
        # stable lexsort with per-key order/null-placement (Spark semantics:
        # NaN greatest, -0.0 == 0.0, null rank independent per key)
        lex_keys = []
        for o in reversed(self.orders):  # np.lexsort: last key is primary
            v, ok = arrow_to_masked_numpy(o.expr.eval_host(batch))
            enc = _np_total_order_key(v)
            if not o.ascending:
                enc = ~enc
            enc = np.where(ok, enc, np.uint64(0))
            rank = np.where(ok, 1, 0) if o.nulls_first else np.where(ok, 0, 1)
            lex_keys.extend([enc, rank.astype(np.uint8)])
        idx = np.lexsort(tuple(lex_keys))
        yield ColumnarBatch.from_arrow(t.take(pa.array(idx)))

    def describe(self):
        return "CpuSort[" + ", ".join(map(repr, self.orders)) + "]"
