"""Whole-stage fusion: one compiled XLA program per operator region.

Reference analog: Spark's whole-stage codegen collapsed onto the XLA
fusion model ("Operator Fusion in XLA", PAPERS.md): the physical plan is
walked for maximal chains of fusible device operators between pipeline
breakers (scan -> filter -> project -> ...; sorts, joins, aggregations,
exchanges and host-fallback execs break the stage), and each chain is
replaced by ONE ``WholeStageExec`` that dispatches a single jitted
kernel per batch (exprs/compiler.FusedStageKernel) instead of one
dispatch + one compaction per operator. On a latency-bound tunneled TPU
the dispatch count IS the cost model, so an N-operator region goes from
N round-trip-priced launches to one.

Aggregations already fuse their input chain into the update kernel
(plan/overrides.AggregateMeta._fold_stages); this pass covers every
region an aggregate does not swallow — join inputs, sort inputs,
filter/project pipelines feeding windows, limits or sinks.

Observability contract:
  * EXPLAIN shows the region as ``WholeStage[fused=[...]]``;
  * the PR-4 trace shows ONE span per batch with a ``fused=[...]`` arg;
  * EXPLAIN ANALYZE still reports per-operator rows and self time
    inside the region: the kernel returns one survivor count per fused
    stage (device scalars, forced only through the metrics view's
    packed fetch) and the fused dispatch wall is apportioned across the
    fused operators (metrics/analyze.py renders them indented under the
    WholeStage row).

Compiled programs resolve through the two-tier executable cache
(plan/exec_cache.py): warm repeats of a plan shape pay zero retrace in
process and zero XLA compile across processes.
"""
from __future__ import annotations

import time
from typing import Iterator, List, Optional, Tuple

from ..columnar import ColumnarBatch, DeviceColumn
from ..config import TpuConf, register
from ..types import Schema
from . import basic as B
from .base import ESSENTIAL, ExecContext, TpuExec

__all__ = ["WholeStageExec", "fuse_whole_stages", "FUSION_ENABLED",
           "AGG_FUSION_ENABLED"]

FUSION_ENABLED = register(
    "spark.rapids.tpu.fusion.enabled", True,
    "Fuse chains of device filter/project operators between pipeline "
    "breakers into one compiled XLA program per region (WholeStageExec):"
    " one kernel dispatch and ONE row compaction per batch instead of "
    "one per operator — the whole-stage-codegen analog on a backend "
    "where dispatch latency is the unit of cost. Fused regions show as "
    "WholeStage[fused=[...]] in EXPLAIN and as one span in the trace; "
    "EXPLAIN ANALYZE still reports per-operator rows/self time inside "
    "them. Executables resolve through the two-tier compile cache "
    "(spark.rapids.tpu.compile.cache.*).", commonly_used=True)

FUSION_MIN_OPS = register(
    "spark.rapids.tpu.fusion.minOperators", 2,
    "Minimum chain length worth fusing: a single operator already is "
    "one dispatch, so wrapping it only adds indirection.", internal=True)

AGG_FUSION_ENABLED = register(
    "spark.rapids.tpu.fusion.aggregate.enabled", True,
    "Fold the chain of device filter/project operators feeding an "
    "aggregation INTO its update kernel (plan/overrides.py "
    "_fold_stages): scan->filter->project->partial-agg runs as ONE "
    "compiled dispatch per batch — the whole-stage fusion extended "
    "through partial aggregation, the tpcds q9/q28 multi-aggregate "
    "shape. EXPLAIN shows the folded region as "
    "HashAggregate[...] fused=[...]; the exec's updateDispatches "
    "metric counts the actual kernel launches per batch. Off = the "
    "per-operator pipeline (byte-identical results, one dispatch and "
    "one compaction per stage).", commonly_used=True)


def _nondeterministic(exprs) -> bool:
    """Expressions carrying per-task state (rand, monotonically
    increasing id) observe row positions: evaluating them row-wise over
    the uncompacted bucket would disagree with the per-operator
    pipeline, so their chains never fuse."""
    stack = list(exprs)
    while stack:
        e = stack.pop()
        if e is None:
            continue
        if getattr(e, "reset_task_state", None) is not None:
            return True
        stack.extend(getattr(e, "children", ()))
    return False


def _fusible(op: TpuExec) -> bool:
    if type(op) is B.TpuFilterExec:
        schema = op.children[0].output_schema()
        return (op.condition.fully_device_supported(schema) is None
                and not _nondeterministic([op.condition]))
    if type(op) is B.TpuProjectExec:
        return (not op.host_idx and not op._list_refs
                and not _nondeterministic(op.exprs))
    return False


def fuse_whole_stages(node: TpuExec, conf: TpuConf) -> TpuExec:
    """Physical-plan pass replacing maximal fusible chains with
    WholeStageExec. The disabled path is one conf read — no tree walk,
    no cache traffic (the trace/metrics off-path contract)."""
    if not conf.get(FUSION_ENABLED):
        return node
    return _fuse(node, max(1, int(conf.get(FUSION_MIN_OPS))))


def _fuse(node: TpuExec, min_ops: int) -> TpuExec:
    chain: List[TpuExec] = []
    cur = node
    while _fusible(cur):
        chain.append(cur)
        cur = cur.children[0]
    if len(chain) >= min_ops:
        return WholeStageExec(list(reversed(chain)), _fuse(cur, min_ops))
    node.children = [_fuse(c, min_ops)
                     for c in getattr(node, "children", [])]
    return node


class WholeStageExec(TpuExec):
    """Executes a fused region of filter/project operators as one
    compiled program per batch (module doc)."""

    def __init__(self, fused_ops: List[TpuExec], child: TpuExec):
        super().__init__([child])
        self.fused_ops = list(fused_ops)          # bottom-up order
        self._schema = self.fused_ops[-1].output_schema()
        in_schema = child.output_schema()
        self.stages: List[Tuple] = []
        for op in self.fused_ops:
            if isinstance(op, B.TpuFilterExec):
                self.stages.append(("filter", op.condition))
            else:
                self.stages.append(("project", op.exprs,
                                    op.output_schema()))
        #: measured-rows feedback rides the TOP op's plan signature —
        #: the region's output rows are exactly that operator's
        self.plan_sig = getattr(self.fused_ops[-1], "plan_sig", None)
        self.trace_args = {
            "fused": [op.describe() for op in self.fused_ops]}
        self._origins = self._trace_origins(in_schema)
        self._kernel = None

    def __getstate__(self):
        # plans ship to shuffle workers by pickle; the compiled kernel
        # is process-local (the receiving process resolves its own from
        # the executable cache)
        state = dict(self.__dict__)
        state["_kernel"] = None
        return state

    def _trace_origins(self, in_schema: Schema) -> List[Optional[str]]:
        """Per output ordinal: the INPUT column name when the output is
        an identity chain from it (dictionary-coded strings must be
        rebuilt around their dictionary after compaction)."""
        from ..exprs.base import Alias, ColumnRef
        mapping = {n: n for n in in_schema.names()}
        for st in self.stages:
            if st[0] == "filter":
                continue
            new = {}
            for e in st[1]:
                inner = e.children[0] if isinstance(e, Alias) else e
                new[e.name_hint] = (mapping.get(inner.name)
                                    if isinstance(inner, ColumnRef)
                                    else None)
            mapping = new
        return [mapping.get(f.name) for f in self._schema.fields]

    def output_schema(self) -> Schema:
        return self._schema

    # ------------------------------------------------------------ execution
    def _fast_ok(self, batch: ColumnarBatch) -> bool:
        """The single-dispatch kernel moves columns as plain
        (data, validity) lanes: every input column must be a plain
        DeviceColumn or a DictColumn (codes are a plain lane; the
        dictionary is rebuilt from the passthrough origin), and every
        output must either be such a passthrough or a numeric-lane
        type. Byte-rectangle / list / host columns take the per-stage
        fallback path — same results, more dispatches."""
        from ..columnar.column import DictColumn
        for c in batch.columns:
            if type(c) is not DeviceColumn and type(c) is not DictColumn:
                return False
        for f, origin in zip(self._schema.fields, self._origins):
            if origin is None and getattr(f.dtype, "np_dtype",
                                          None) is None:
                return False
        return True

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        rows_m = ctx.metric(self._exec_id, "numOutputRows", ESSENTIAL)
        per_op = [(ctx.metric(op._exec_id, "opTime"),
                   ctx.metric(op._exec_id, "numOutputRows", ESSENTIAL),
                   ctx.metric(op._exec_id, "numOutputBatches"))
                  for op in self.fused_ops]
        from ..plan import exec_cache
        cache0 = exec_cache.stats()
        in_rows = 0
        stage_wall = 0.0
        for batch in self.children[0].execute(ctx):
            batch = batch.ensure_device()
            t0 = time.perf_counter()
            with ctx.semaphore.held():
                if self._fast_ok(batch):
                    out, counts = self._run_fused(batch)
                else:
                    out, counts = self._run_stages(batch)
            dt = time.perf_counter() - t0
            stage_wall += dt
            # fused-region attribution: the dispatch wall is one
            # indivisible launch — apportion it evenly so EXPLAIN
            # ANALYZE keeps a per-operator breakdown; rows are exact
            # (one survivor count per stage from the kernel)
            share = dt / len(self.fused_ops)
            for (m_t, m_r, m_b), c in zip(per_op, counts):
                m_t.add(share)
                m_b.add(1)
                if c is not None:
                    m_r.add(c)
            rows_m.add(out.num_rows_raw)
            if isinstance(batch.num_rows_raw, int):
                in_rows += batch.num_rows_raw
            yield out
        if in_rows and stage_wall > 0.0:
            # measured fused-stage device wall -> the cost model: the
            # optimizer learns that fused device regions are cheap
            # instead of pricing them from static per-row guesses.
            # Keyed on exec-cache hit status: a first run whose wall
            # includes jit trace / XLA compile measures the cold start,
            # not the region — only compile-free walls are learned
            # (that keying is what let trusted_engine_wall drop its
            # old >=2-observation workaround to >=1-with-cache-hit)
            compile_free = exec_cache.compile_free_since(cache0)
            from ..plan import cost as plan_cost
            plan_cost.record_op_wall(
                "WholeStageExec", "device", in_rows, stage_wall,
                compile_free=compile_free,
                # under-scale regions measure dispatch floor, not per-row
                # cost — the same sample gate as the analyze.py feed
                # (without it, warm small repeats would accumulate
                # dispatch-dominated quotients past _OP_COST_MIN_ROWS
                # and poison the trusted per-row price)
                min_rows=plan_cost._OP_COST_SAMPLE_MIN_ROWS)

    def _run_fused(self, batch: ColumnarBatch):
        from ..columnar.column import DictColumn
        from ..exprs.compiler import compile_fused_stages
        if self._kernel is None:
            self._kernel = compile_fused_stages(
                self.stages, self.children[0].output_schema())
        outs, count, counts = self._kernel.run(batch)
        cols = []
        for (d, v), f, origin in zip(outs, self._schema.fields,
                                     self._origins):
            src = (batch.column_by_name(origin)
                   if origin is not None else None)
            if isinstance(src, DictColumn):
                cols.append(DictColumn(d, v, f.dtype, src.dictionary))
            else:
                cols.append(DeviceColumn(d, v, f.dtype))
        out = ColumnarBatch(cols, count, self._schema, meta=batch.meta)
        return out, list(counts)

    def _run_stages(self, batch: ColumnarBatch):
        """Per-stage fallback for batches carrying columns the fused
        kernel's plain lanes cannot represent (byte rectangles, lists,
        host columns): the original operators' semantics, one dispatch
        per stage."""
        counts = []
        for st in self.stages:
            if st[0] == "filter":
                batch = self._apply_filter(batch, st[1])
            else:
                batch = self._apply_project(batch, st[1], st[2])
            counts.append(batch.num_rows_raw)
        return batch, counts

    @staticmethod
    def _apply_filter(batch: ColumnarBatch, cond) -> ColumnarBatch:
        from ..exprs.compiler import filter_mixed_batch
        return filter_mixed_batch(cond, batch)

    @staticmethod
    def _apply_project(batch: ColumnarBatch, exprs,
                       out_schema: Schema) -> ColumnarBatch:
        from ..exprs.base import Alias, ColumnRef
        from ..exprs.compiler import compile_projection
        out_cols: List[Optional[object]] = [None] * len(exprs)
        dev_idx = []
        for i, e in enumerate(exprs):
            inner = e.children[0] if isinstance(e, Alias) else e
            if isinstance(inner, ColumnRef):
                out_cols[i] = batch.column_by_name(inner.name)
            else:
                dev_idx.append(i)
        if dev_idx:
            proj = compile_projection([exprs[i] for i in dev_idx],
                                      batch.schema)
            for i, c in zip(dev_idx, proj.run(batch)):
                out_cols[i] = c
        return ColumnarBatch(out_cols, batch.num_rows_raw, out_schema,
                             meta=batch.meta)

    # -------------------------------------------------------------- explain
    def describe(self) -> str:
        return ("WholeStage[fused=["
                + ", ".join(op.describe() for op in self.fused_ops)
                + "]]")
