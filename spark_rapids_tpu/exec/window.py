"""Window exec (ref window/GpuWindowExec.scala:146 + specializations:
GpuRunningWindowExec scan-based running aggs, GpuBatchedBoundedWindowExec
bounded frames, BasicWindowCalc).

TPU-first, one fused kernel: ONE index-only lax.sort by (partition keys,
order keys), segment ids from boundaries, then every window column is
segment arithmetic on the VPU:
  row_number  = idx - partition_start + 1
  rank        = order-run start - partition_start + 1 (associative max scan)
  dense_rank  = per-partition cumsum of order-run starts
  lag/lead    = shifted gather with partition-boundary nulling
  unbounded aggregate frames = segment reduction broadcast via take(gid)
  running / bounded-rows sum,count,avg frames = partition-local prefix sums
    (prefix[i+hi] - prefix[i+lo-1])
Results scatter back to input row order through the inverse permutation, so
the exec preserves row order like the reference does.
"""
from __future__ import annotations

import functools
from typing import Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..columnar.segmented import (SortedSegments, last_valid_scan,
                                  prefix_sum, reverse_last_valid_scan,
                                  shift_static)
import numpy as np

from ..columnar import ColumnarBatch, DeviceColumn, concat_batches
from ..exprs.aggregates import AggregateExpression, Average, Count, CountStar, \
    Max, Min, Sum
from ..exprs.base import DVal, EvalContext
from ..exprs.window_fns import (DenseRank, Lag, Lead, NTile, Rank, RowNumber,
                                WindowFunction)
from ..mem import SpillableBatch, with_retry_no_split
from ..plan.logical import WindowSpec
from ..types import FLOAT64, INT32, INT64, Schema, StructField
from .base import ExecContext, TpuExec
from .encoding import grouping_operands, operands_equal, order_key_operands

__all__ = ["TpuWindowExec", "CpuWindowExec"]

_WIN_CACHE: Dict[Tuple, object] = {}


def _start_broadcast(values, pflags):
    """values at partition-start rows propagated forward to every row of
    the partition (scan, not a group-table gather)."""
    return last_valid_scan(values, pflags)[0]


def _end_broadcast(values, end_mask):
    """values at partition-end rows propagated backward."""
    return reverse_last_valid_scan(values, end_mask)[0]


def _build_window_kernel(window_exprs, schema: Schema, padded_len_key=None):
    dtypes = [f.dtype for f in schema.fields]

    @functools.partial(jax.jit, static_argnums=(2,))
    def kernel(cols, num_rows, padded_len):
        P = padded_len
        dvals = [None if c is None else DVal(c[0], c[1], dt)
                 for c, dt in zip(cols, dtypes)]
        ctx = EvalContext(schema, dvals, num_rows, P)
        row_mask = ctx.row_mask()
        outs = []
        for fn, spec, _name in window_exprs:
            # --- sort by (partition, order) --------------------------------
            pad_flag = jnp.where(row_mask, jnp.uint8(0), jnp.uint8(1))
            operands = [pad_flag]
            n_part_ops = 1
            for pk in spec.partition_by:
                operands.extend(grouping_operands(pk.eval_device(ctx)))
            n_part_ops = len(operands)
            for o in spec.order_by:
                operands.extend(order_key_operands(
                    o.expr.eval_device(ctx), o.ascending, o.nulls_first))
            perm0 = jnp.arange(P, dtype=jnp.int32)
            # carry the aggregated/lagged value through the sort network
            # instead of gathering it by perm afterwards (row gathers
            # serialize on the TPU scalar core)
            payload = [perm0]
            child = getattr(fn, "child", None)
            if child is not None:
                cv = child.eval_device(ctx)
                payload.extend((cv.data, cv.validity))
            srt = jax.lax.sort(tuple(operands + payload),
                               num_keys=len(operands), is_stable=True)
            perm = srt[len(operands)]
            sorted_child = (DVal(srt[len(operands) + 1],
                                 srt[len(operands) + 2], cv.dtype)
                            if child is not None else None)
            s_ops = srt[:len(operands)]
            idx = jnp.arange(P, dtype=jnp.int32)
            # partition boundaries
            pdiff = jnp.zeros(P, dtype=jnp.bool_)
            for op in s_ops[1:n_part_ops]:
                prev = jnp.roll(op, 1)
                pdiff = jnp.logical_or(
                    pdiff, jnp.logical_not(operands_equal(op, prev)))
            pflags = jnp.logical_and(jnp.logical_or(idx == 0, pdiff), row_mask)
            gid = jnp.where(row_mask,
                            prefix_sum(pflags, jnp.int32) - 1, P)
            part_start = _start_broadcast(idx, pflags)
            nlive = jnp.sum(row_mask.astype(jnp.int32))
            end_mask = jnp.logical_and(
                row_mask,
                jnp.logical_or(
                    jnp.concatenate([pflags[1:],
                                     jnp.ones((1,), jnp.bool_)]),
                    idx + 1 >= nlive))
            pend = _end_broadcast(idx, end_mask)
            # order-value run boundaries (for rank/dense_rank)
            odiff = pdiff
            for op in s_ops[n_part_ops:]:
                prev = jnp.roll(op, 1)
                odiff = jnp.logical_or(
                    odiff, jnp.logical_not(operands_equal(op, prev)))
            oflags = jnp.logical_and(jnp.logical_or(idx == 0, odiff), row_mask)

            val = self_validity = None
            if isinstance(fn, (RowNumber,)):
                out_sorted = (idx - part_start + 1).astype(jnp.int32)
                ov_sorted = row_mask
            elif isinstance(fn, Rank):
                run_start = _start_broadcast(idx, oflags)
                out_sorted = (run_start - part_start + 1).astype(jnp.int32)
                ov_sorted = row_mask
            elif isinstance(fn, DenseRank):
                c = prefix_sum(oflags, jnp.int32)
                c_at_pstart = _start_broadcast(c, pflags)
                out_sorted = (c - c_at_pstart + 1).astype(jnp.int32)
                ov_sorted = row_mask
            elif isinstance(fn, NTile):
                cnt = (pend - part_start + 1).astype(jnp.int32)
                rn = idx - part_start
                n = jnp.int32(fn.n)
                base = cnt // n
                rem = cnt % n
                # Spark NTile: first `rem` buckets get base+1 rows
                big_rows = rem * (base + 1)
                out_sorted = jnp.where(
                    rn < big_rows,
                    rn // jnp.maximum(base + 1, 1),
                    rem + (rn - big_rows) // jnp.maximum(base, 1)
                ).astype(jnp.int32) + 1
                ov_sorted = row_mask
            elif isinstance(fn, (Lag, Lead)):
                sd = sorted_child.data
                sv = sorted_child.validity
                off = fn.offset if isinstance(fn, Lag) else -fn.offset
                # STATIC shift (a concatenate), not a row gather
                ok = jnp.logical_and(idx - off >= 0, idx - off < P)
                out_sorted = shift_static(sd, off,
                                          jnp.zeros((), sd.dtype))
                ov_sorted = jnp.logical_and(
                    shift_static(sv, off, jnp.array(False)), ok)
                # must stay inside the partition
                same_part = shift_static(
                    gid, off, jnp.full((), P, gid.dtype)) == gid
                ov_sorted = jnp.logical_and(ov_sorted, same_part)
                if fn.default is not None:
                    dflt = jnp.asarray(fn.default, dtype=out_sorted.dtype)
                    fill = jnp.logical_and(jnp.logical_not(
                        jnp.logical_and(ok, same_part)), row_mask)
                    out_sorted = jnp.where(fill, dflt, out_sorted)
                    ov_sorted = jnp.logical_or(ov_sorted, fill)
            elif isinstance(fn, AggregateExpression):
                out_sorted, ov_sorted = _windowed_agg(
                    fn, spec, ctx, sorted_child, part_start, idx,
                    row_mask, P, pflags, end_mask, pend)
            else:
                raise NotImplementedError(type(fn).__name__)

            # restore original order: ONE variadic sort keyed on the
            # carried original index (scatter + inverse gathers serialize
            # on the scalar core)
            _, od, ov = jax.lax.sort((perm, out_sorted, ov_sorted),
                                     num_keys=1, is_stable=True)
            outs.append((od, jnp.logical_and(ov, row_mask)))
        return outs

    return kernel


def _windowed_agg(fn: AggregateExpression, spec: WindowSpec, ctx,
                  sorted_child, part_start, idx, row_mask, P,
                  pflags, end_mask, pend):
    """Aggregate over a window frame. Default frames follow Spark: with
    order_by -> running (unbounded preceding..current row); without ->
    whole partition. All segment maths are scans + STATIC shifts — no
    row-sized gather or scatter anywhere (TPU scalar-core serialization).
    """
    if isinstance(fn, CountStar):
        vd = jnp.ones(P, dtype=jnp.int64)
        vv = row_mask
    else:
        vd = sorted_child.data
        vv = sorted_child.validity
    vv = jnp.logical_and(vv, row_mask)
    seg = SortedSegments(pflags, row_mask)

    frame = spec.frame
    if frame is None:
        frame = ("rows", None, 0) if spec.order_by else ("rows", None, None)
    kind, lo, hi = frame

    whole = lo is None and hi is None
    if whole:
        if isinstance(fn, (Sum, Average, Count, CountStar)):
            acc = vd
            if isinstance(fn, (Count, CountStar)):
                acc = vv.astype(jnp.int64)
            acc = acc.astype(jnp.float64 if isinstance(fn, Average)
                             else acc.dtype)
            tot = _end_broadcast(seg.sum(acc, vv), end_mask)
            cnt = _end_broadcast(seg.count(vv), end_mask)
            if isinstance(fn, (Count, CountStar)):
                return tot, row_mask
            if isinstance(fn, Average):
                ok = cnt > 0
                return (tot / jnp.maximum(cnt, 1).astype(jnp.float64), ok)
            return tot, cnt > 0
        if isinstance(fn, (Min, Max)):
            if jnp.issubdtype(vd.dtype, jnp.floating):
                # Spark: NaN is greatest; all-NaN group -> NaN
                notnan = jnp.logical_and(vv, jnp.logical_not(jnp.isnan(vd)))
                has_nan = _end_broadcast(
                    seg.max(jnp.logical_and(vv, jnp.isnan(vd))
                            .astype(jnp.int32), vv), end_mask) > 0
                red = seg.min if isinstance(fn, Min) else seg.max
                m = _end_broadcast(red(vd, notnan), end_mask)
                n_notnan = _end_broadcast(seg.count(notnan), end_mask)
                nanv = jnp.array(jnp.nan, dtype=vd.dtype)
                if isinstance(fn, Max):
                    m = jnp.where(has_nan, nanv, m)
                else:
                    m = jnp.where(jnp.logical_and(n_notnan == 0, has_nan),
                                  nanv, m)
            else:
                red = seg.min if isinstance(fn, Min) else seg.max
                m = _end_broadcast(red(vd, vv), end_mask)
            cnt = _end_broadcast(seg.count(vv), end_mask)
            return m, cnt > 0
        raise NotImplementedError(type(fn).__name__)

    # prefix-sum frames (running / bounded rows) for sum/count/avg
    if not isinstance(fn, (Sum, Average, Count, CountStar)):
        raise NotImplementedError(
            f"bounded frame for {type(fn).__name__}")
    acc_dt = jnp.float64 if (isinstance(fn, Average)
                             or jnp.issubdtype(vd.dtype, jnp.floating)) \
        else jnp.int64
    is_f = jnp.issubdtype(vd.dtype, jnp.floating)
    # NaN must poison only frames CONTAINING it, not every later prefix:
    # sum finite values in the prefix and track NaN positions separately
    # (a frame whose NaN-count difference is >0 yields NaN)
    isnan = (jnp.logical_and(vv, jnp.isnan(vd)) if is_f
             else jnp.zeros(P, jnp.bool_))
    finite_ok = jnp.logical_and(vv, jnp.logical_not(isnan))
    acc = jnp.where(finite_ok, vd, jnp.zeros_like(vd)).astype(acc_dt)
    cntv = vv.astype(jnp.int64)
    ps = prefix_sum(acc)          # global prefix (inclusive)
    pc = prefix_sum(cntv)
    pn = prefix_sum(isnan.astype(jnp.int32))
    lo_i = part_start if lo is None else jnp.maximum(part_start, idx + lo)
    hi_i = pend if hi is None else jnp.minimum(pend, idx + hi)
    empty = hi_i < lo_i

    def window_sum(prefix):
        z = jnp.zeros((), prefix.dtype)
        # prefix value just BEFORE the partition (0 at the table start)
        before = _start_broadcast(shift_static(prefix, 1, z), pflags)
        at_end = _end_broadcast(prefix, end_mask)
        # upper = prefix[min(pend, idx+hi)] via a STATIC shift + clamp fix
        if hi is None:
            upper = at_end
        else:
            upper = jnp.where(idx + hi > pend, at_end,
                              shift_static(prefix, -hi, z))
        # lower = prefix[max(pstart, idx+lo) - 1]
        if lo is None:
            lower = before
        else:
            lower = jnp.where(idx + lo <= part_start, before,
                              shift_static(prefix, -(lo - 1), z))
        return jnp.where(empty, z, upper - lower)

    s = window_sum(ps)
    c = window_sum(pc)
    if isinstance(fn, (Count, CountStar)):
        return c, row_mask
    if is_f:
        frame_nan = window_sum(pn) > 0
        s = jnp.where(frame_nan, jnp.array(jnp.nan, s.dtype), s)
    if isinstance(fn, Average):
        ok = jnp.logical_and(c > 0, row_mask)
        return s.astype(jnp.float64) / jnp.maximum(c, 1).astype(jnp.float64), ok
    ok = jnp.logical_and(c > 0, row_mask)
    if jnp.issubdtype(vd.dtype, jnp.integer):
        s = s.astype(jnp.int64)
    return s, ok


class TpuWindowExec(TpuExec):
    def __init__(self, window_exprs, child: TpuExec):
        super().__init__([child])
        self.window_exprs = list(window_exprs)
        cs = child.output_schema()
        fields = list(cs.fields)
        for e, spec, name in self.window_exprs:
            fields.append(StructField(name, e.data_type(cs), True))
        self._schema = Schema(fields)

    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        cs = self.children[0].output_schema()
        key = (tuple(f"{type(e).__name__}|{n}" for e, s, n in
                     self.window_exprs),
               tuple((f.name, f.dtype.name) for f in cs.fields), id(self))
        kern = _WIN_CACHE.get(key)
        if kern is None:
            kern = _build_window_kernel(self.window_exprs, cs)
            _WIN_CACHE[key] = kern
        # window needs whole partitions: single-batch goal
        spill = [SpillableBatch(b.ensure_device(), ctx.memory)
                 for b in self.children[0].execute(ctx)]
        if not spill:
            return

        def run():
            with ctx.semaphore.held():
                batch = concat_batches([s.get() for s in spill])
                # host columns (e.g. high-cardinality strings) ride
                # through untouched; the kernel must not dereference them
                cols = [(c.data, c.validity)
                        if isinstance(c, DeviceColumn) else None
                        for c in batch.columns]
                outs = kern(cols, jnp.int32(batch.num_rows),
                            batch.padded_len)
                new_cols = list(batch.columns)
                for (d, v), (e, s, name) in zip(outs, self.window_exprs):
                    new_cols.append(DeviceColumn(d, v, e.data_type(cs)))
                return ColumnarBatch(new_cols, batch.num_rows, self._schema)

        out = with_retry_no_split(run, ctx.memory)
        for s in spill:
            s.close()
        yield out

    def describe(self):
        names = ", ".join(n for _, _, n in self.window_exprs)
        return f"Window[{names}]"


class CpuWindowExec(TpuExec):
    is_tpu = False

    def __init__(self, window_exprs, child: TpuExec):
        super().__init__([child])
        self.window_exprs = list(window_exprs)
        cs = child.output_schema()
        fields = list(cs.fields)
        for e, spec, name in self.window_exprs:
            fields.append(StructField(name, e.data_type(cs), True))
        self._schema = Schema(fields)

    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        import pandas as pd
        import pyarrow as pa
        tables = [b.to_arrow() for b in self.children[0].execute(ctx)]
        if not tables:
            return
        t = pa.concat_tables(tables)
        df = t.to_pandas()
        batch = ColumnarBatch.from_arrow(t, pad=False)
        for fn, spec, name in self.window_exprs:
            pcols = []
            for i, pk in enumerate(spec.partition_by):
                pc = f"__p{i}"
                df[pc] = pk.eval_host(batch).to_pandas()
                pcols.append(pc)
            ocols = []
            for i, o in enumerate(spec.order_by):
                oc = f"__o{i}"
                df[oc] = o.expr.eval_host(batch).to_pandas()
                ocols.append(oc)
            if pcols or ocols:
                # per-column direction AND null placement must match the
                # device kernel (order_key_operands); pandas sort_values
                # has one global na_position, so encode like CpuSortExec
                import numpy as np
                from ..exprs.arithmetic import arrow_to_masked_numpy
                from .sort import _np_total_order_key
                lex = []
                specs = [(o.expr, o.ascending, o.nulls_first)
                         for o in spec.order_by]
                specs = [(pk, True, True) for pk in spec.partition_by] + specs
                for e, asc_, nf in reversed(specs):
                    v, ok = arrow_to_masked_numpy(e.eval_host(batch))
                    enc = _np_total_order_key(v, ok)
                    if not asc_:
                        enc = ~enc
                    enc = np.where(ok, enc, np.uint64(0))
                    rank = np.where(ok, 1, 0) if nf else np.where(ok, 0, 1)
                    lex.extend([enc, rank.astype(np.uint8)])
                order = np.lexsort(tuple(lex))
                work = df.iloc[order]
            else:
                work = df
            g = work.groupby(pcols, dropna=False, sort=False) if pcols \
                else work.assign(__one=1).groupby("__one")
            if isinstance(fn, RowNumber):
                res = g.cumcount() + 1
            elif isinstance(fn, Rank):
                res = _sorted_rank(work, pcols, ocols, dense=False)
            elif isinstance(fn, DenseRank):
                res = _sorted_rank(work, pcols, ocols, dense=True)
            elif isinstance(fn, NTile):
                rn = g.cumcount()
                cnt = g[work.columns[0]].transform("size") \
                    if pcols else pd.Series(len(work), index=work.index)
                base, rem = cnt // fn.n, cnt % fn.n
                big = rem * (base + 1)
                res = (rn.where(rn < big, other=None).floordiv(base + 1)
                       .fillna(rem + (rn - big) // base.clip(lower=1))
                       .astype("int64") + 1)
            elif isinstance(fn, (Lag, Lead)):
                # validity-aware shift: out-of-partition slots are SQL
                # NULL (or the default), never NaN — pandas shift's NaN
                # fill is indistinguishable from a real NaN value
                res = _host_shift(fn, g, work, batch)
            elif isinstance(fn, AggregateExpression):
                res = self._host_agg(fn, spec, g, work, batch)
            else:
                raise NotImplementedError(type(fn).__name__)
            df[name] = res.reindex(df.index) if hasattr(res, "reindex") \
                else res
            # drop only the temporaries THIS loop created — input columns
            # may legitimately start with "__" (e.g. SQL-hoisted windows)
            temps = set(pcols + ocols) | {"__v", "__a", "__one"}
            df = df.drop(columns=[c for c in df.columns if c in temps])
        from ..types import to_arrow
        arrays = []
        for f in self._schema.fields:
            isf = f.dtype.name in ("float", "double")
            vals = [x if (isf and isinstance(x, float) and np.isnan(x))
                    else (None if pd.isna(x) else x)
                    for x in df[f.name].tolist()]
            arrays.append(pa.array(vals, type=to_arrow(f.dtype)))
        yield ColumnarBatch.from_arrow(
            pa.Table.from_arrays(arrays, names=self._schema.names()))

    def _host_agg(self, fn, spec, g, work, batch):
        """Frame aggregation on the host oracle with Spark semantics:
        SQL NULL (arrow validity) is skipped, NaN is a VALUE that poisons
        any frame containing it; FOLLOWING bounds are honored (pandas
        rolling is trailing-only and skips NaN, so frames are computed
        from per-partition prefix arrays instead)."""
        import numpy as np
        import pandas as pd
        n = len(work)
        if isinstance(fn, CountStar):
            vals = np.ones(n)
            ok = np.ones(n, dtype=bool)
        else:
            import pyarrow as pa
            arr = fn.child.eval_host(batch)
            if isinstance(arr, pa.ChunkedArray):
                arr = arr.combine_chunks()
            ok_full = ~np.asarray(arr.is_null())
            v_full = np.asarray(arr.to_pandas().to_numpy(), dtype=object)
            pos = work.index.to_numpy()
            vals = v_full[pos]
            ok = ok_full[pos]
        import pyarrow as pa
        if isinstance(fn, (Count, CountStar)):
            is_f, is_num, is_dec = False, True, False
        else:
            # decimal SUM/AVG take the float64 path (approximate — exact
            # decimal accumulation is future work); decimal MIN/MAX stay
            # exact via the object path below; int64 stays exact
            is_dec = pa.types.is_decimal(arr.type)
            is_f = pa.types.is_floating(arr.type) or is_dec
            is_num = is_f or pa.types.is_integer(arr.type)
        if is_f:
            fvals = np.asarray([np.nan if x is None else float(x)
                                for x in vals], dtype=np.float64)
        elif is_num:
            # int64 prefix sums stay EXACT (float64 would lose precision
            # past 2^53 and mangle decimals)
            fvals = np.asarray([0 if x is None else int(x)
                                for x in vals], dtype=np.int64)
        else:
            fvals = vals            # strings/dates: min/max only

        frame = spec.frame
        if frame is None:
            frame = ("rows", None, 0) if spec.order_by \
                else ("rows", None, None)
        kind, lo, hi = frame

        out = np.empty(n, dtype=object)
        start = 0
        sizes = (g.size().to_numpy() if hasattr(g, "size") else [n])
        for sz in sizes:
            sl = slice(start, start + int(sz))
            v = fvals[sl]
            k = ok[sl]
            m = int(sz)
            if is_num:
                isn = np.where(k, np.isnan(v), False) if is_f \
                    else np.zeros(m, dtype=bool)
                fin = k & ~isn
                acc = np.where(fin, v, 0).cumsum()
            else:
                isn = fin = np.zeros(m, dtype=bool)
                acc = np.zeros(m)
            nc = isn.astype(np.int64).cumsum()
            cnt = k.astype(np.int64).cumsum()
            i = np.arange(m)
            lo_i = np.zeros(m, np.int64) if lo is None \
                else np.clip(i + lo, 0, m)
            hi_i = np.full(m, m - 1) if hi is None \
                else np.minimum(i + hi, m - 1)
            empty = hi_i < lo_i
            hs = np.clip(hi_i, 0, m - 1)

            def dif(p):
                upper = p[hs]
                lower = np.where(lo_i > 0, p[np.maximum(lo_i - 1, 0)], 0)
                return np.where(empty, 0, upper - lower)

            if isinstance(fn, (Min, Max)):
                # whole-partition only (bounded min/max unsupported on
                # both engines); Spark: NaN is greatest, all-NaN -> NaN
                if lo is not None or hi is not None:
                    raise NotImplementedError(
                        f"bounded frame for {type(fn).__name__}")
                if not k.any():
                    val = None
                elif not is_num or is_dec:  # strings/dates/decimals: exact
                    src = vals[sl] if is_dec else v
                    vv = [x for x, kk in zip(src, k) if kk]
                    val = min(vv) if isinstance(fn, Min) else max(vv)
                elif isinstance(fn, Max):
                    val = np.nan if (is_f and isn.any()) else v[fin].max()
                elif len(v[fin]):
                    val = v[fin].min()
                else:
                    val = np.nan
                out[sl] = np.full(m, val, dtype=object)
                start += int(sz)
                continue
            s_ = dif(acc)
            c_ = dif(cnt)
            has_nan = dif(nc) > 0
            if isinstance(fn, (Count, CountStar)):
                res = c_.astype(object)
            elif isinstance(fn, Average):
                res = np.where(has_nan, np.nan,
                               s_ / np.maximum(c_, 1))
                res = np.asarray(res, dtype=object)
                res[c_ == 0] = None
            else:  # Sum
                if is_f:
                    res = np.where(has_nan, np.nan, s_)
                else:
                    res = s_        # int64: exact, no NaN possible
                res = np.asarray(res, dtype=object)
                res[c_ == 0] = None
                if not is_f:
                    res = np.asarray(
                        [None if x is None else int(x) for x in res],
                        dtype=object)
            out[sl] = res
            start += int(sz)
        return pd.Series(out, index=work.index)

    def describe(self):
        return "CpuWindow[" + ", ".join(n for _, _, n in
                                        self.window_exprs) + "]"


def _host_shift(fn, g, work, batch):
    import numpy as np
    import pandas as pd
    import pyarrow as pa
    arr = fn.child.eval_host(batch)
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    ok_full = ~np.asarray(arr.is_null())
    v_full = np.asarray(arr.to_pandas().to_numpy(), dtype=object)
    pos = work.index.to_numpy()
    vals, ok = v_full[pos], ok_full[pos]
    off = fn.offset if isinstance(fn, Lag) else -fn.offset
    out = np.empty(len(work), dtype=object)
    start = 0
    for sz in g.size().to_numpy():
        m = int(sz)
        sl_v, sl_k = vals[start:start + m], ok[start:start + m]
        res = np.full(m, fn.default, dtype=object)   # outside partition
        if off >= 0:                                  # lag: shift right
            d = min(off, m)
            src_v, src_k = sl_v[:m - d], sl_k[:m - d]
            res[d:] = np.where(src_k, src_v, None)
        else:                                         # lead: shift left
            d = min(-off, m)
            src_v, src_k = sl_v[d:], sl_k[d:]
            res[:m - d] = np.where(src_k, src_v, None)
        out[start:start + m] = res
        start += m
    return pd.Series(out, index=work.index)


def _sorted_rank(work, pcols, ocols, dense: bool):
    """rank/dense_rank computed POSITIONALLY over the pre-sorted frame:
    the sort already applied each order column's ASC/DESC and null
    placement, so equal-key runs are contiguous and direction never needs
    re-deriving (pandas' value rank() is ascending-only and was wrong for
    DESC orders). Nulls compare EQUAL for ranking (Spark semantics), so
    run detection uses null-safe per-column equality, never tuple !=."""
    import pandas as pd
    grp = [work[c] for c in pcols] if pcols else \
        [pd.Series(0, index=work.index)]
    anchor = work[ocols[0]] if ocols else pd.Series(0, index=work.index)
    rn = anchor.groupby(grp, dropna=False, sort=False).cumcount() + 1
    same = pd.Series(True, index=work.index)
    for c in ocols:
        col, prev = work[c], work[c].shift(1)
        same &= (col == prev) | (col.isna() & prev.isna())
    newrun = (rn == 1) | ~same
    if dense:
        return newrun.groupby(grp, dropna=False, sort=False) \
            .cumsum().astype("int64")
    r = rn.where(newrun)
    return r.groupby(grp, dropna=False, sort=False).ffill().astype("int64")