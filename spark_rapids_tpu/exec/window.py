"""Window exec (ref window/GpuWindowExec.scala:146 + specializations:
GpuRunningWindowExec scan-based running aggs, GpuBatchedBoundedWindowExec
bounded frames, BasicWindowCalc).

TPU-first, one fused kernel: ONE index-only lax.sort by (partition keys,
order keys), segment ids from boundaries, then every window column is
segment arithmetic on the VPU:
  row_number  = idx - partition_start + 1
  rank        = order-run start - partition_start + 1 (associative max scan)
  dense_rank  = per-partition cumsum of order-run starts
  lag/lead    = shifted gather with partition-boundary nulling
  unbounded aggregate frames = segment reduction broadcast via take(gid)
  running / bounded-rows sum,count,avg frames = partition-local prefix sums
    (prefix[i+hi] - prefix[i+lo-1])
Results scatter back to input row order through the inverse permutation, so
the exec preserves row order like the reference does.
"""
from __future__ import annotations

import functools
from typing import Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..columnar.segmented import (SortedSegments, last_valid_scan,
                                  prefix_sum, reverse_last_valid_scan,
                                  shift_static)
import numpy as np

from ..columnar import (ColumnarBatch, DeviceColumn, DictColumn,
                        concat_batches)
from ..exprs.aggregates import AggregateExpression, Average, Count, CountStar, \
    Max, Min, Sum
from ..exprs.base import DVal, EvalContext
from ..exprs.window_fns import (DenseRank, Lag, Lead, NthValue, NTile,
                                PercentRank, Rank, RowNumber,
                                WindowFunction)
from ..mem import SpillableBatch, with_retry_no_split, wrap_spillables
from ..plan.logical import WindowSpec
from ..types import FLOAT64, INT32, INT64, Schema, StructField
from .base import ExecContext, TpuExec
from .encoding import grouping_operands, operands_equal, order_key_operands

__all__ = ["TpuWindowExec", "CpuWindowExec"]

_WIN_CACHE: Dict[Tuple, object] = {}


def _start_broadcast(values, pflags):
    """values at partition-start rows propagated forward to every row of
    the partition (scan, not a group-table gather)."""
    return last_valid_scan(values, pflags)[0]


def _end_broadcast(values, end_mask):
    """values at partition-end rows propagated backward."""
    return reverse_last_valid_scan(values, end_mask)[0]


def _build_window_kernel(window_exprs, schema: Schema, padded_len_key=None):
    dtypes = [f.dtype for f in schema.fields]

    @functools.partial(jax.jit, static_argnums=(2,))
    def kernel(cols, num_rows, padded_len):
        P = padded_len
        dvals = [None if c is None else DVal(c[0], c[1], dt)
                 for c, dt in zip(cols, dtypes)]
        ctx = EvalContext(schema, dvals, num_rows, P)
        row_mask = ctx.row_mask()
        outs = []
        for fn, spec, _name in window_exprs:
            # --- sort by (partition, order) --------------------------------
            pad_flag = jnp.where(row_mask, jnp.uint8(0), jnp.uint8(1))
            operands = [pad_flag]
            n_part_ops = 1
            for pk in spec.partition_by:
                operands.extend(grouping_operands(pk.eval_device(ctx)))
            n_part_ops = len(operands)
            for o in spec.order_by:
                operands.extend(order_key_operands(
                    o.expr.eval_device(ctx), o.ascending, o.nulls_first))
            perm0 = jnp.arange(P, dtype=jnp.int32)
            # carry the aggregated/lagged value through the sort network
            # instead of gathering it by perm afterwards (row gathers
            # serialize on the TPU scalar core)
            payload = [perm0]
            child = getattr(fn, "child", None)
            if child is not None:
                cv = child.eval_device(ctx)
                payload.extend((cv.data, cv.validity))
            srt = jax.lax.sort(tuple(operands + payload),
                               num_keys=len(operands), is_stable=True)
            perm = srt[len(operands)]
            sorted_child = (DVal(srt[len(operands) + 1],
                                 srt[len(operands) + 2], cv.dtype)
                            if child is not None else None)
            s_ops = srt[:len(operands)]
            idx = jnp.arange(P, dtype=jnp.int32)
            # partition boundaries
            pdiff = jnp.zeros(P, dtype=jnp.bool_)
            for op in s_ops[1:n_part_ops]:
                prev = jnp.roll(op, 1)
                pdiff = jnp.logical_or(
                    pdiff, jnp.logical_not(operands_equal(op, prev)))
            pflags = jnp.logical_and(jnp.logical_or(idx == 0, pdiff), row_mask)
            gid = jnp.where(row_mask,
                            prefix_sum(pflags, jnp.int32) - 1, P)
            part_start = _start_broadcast(idx, pflags)
            nlive = jnp.sum(row_mask.astype(jnp.int32))
            end_mask = jnp.logical_and(
                row_mask,
                jnp.logical_or(
                    jnp.concatenate([pflags[1:],
                                     jnp.ones((1,), jnp.bool_)]),
                    idx + 1 >= nlive))
            pend = _end_broadcast(idx, end_mask)
            # order-value run boundaries (for rank/dense_rank)
            odiff = pdiff
            for op in s_ops[n_part_ops:]:
                prev = jnp.roll(op, 1)
                odiff = jnp.logical_or(
                    odiff, jnp.logical_not(operands_equal(op, prev)))
            oflags = jnp.logical_and(jnp.logical_or(idx == 0, odiff), row_mask)

            val = self_validity = None
            if isinstance(fn, (RowNumber,)):
                out_sorted = (idx - part_start + 1).astype(jnp.int32)
                ov_sorted = row_mask
            elif isinstance(fn, Rank):
                run_start = _start_broadcast(idx, oflags)
                out_sorted = (run_start - part_start + 1).astype(jnp.int32)
                ov_sorted = row_mask
            elif isinstance(fn, PercentRank):
                run_start = _start_broadcast(idx, oflags)
                rank = (run_start - part_start + 1).astype(jnp.float64)
                cnt = (pend - part_start + 1).astype(jnp.float64)
                out_sorted = jnp.where(cnt > 1, (rank - 1.0)
                                       / jnp.maximum(cnt - 1.0, 1.0),
                                       0.0)
                ov_sorted = row_mask
            elif isinstance(fn, DenseRank):
                c = prefix_sum(oflags, jnp.int32)
                c_at_pstart = _start_broadcast(c, pflags)
                out_sorted = (c - c_at_pstart + 1).astype(jnp.int32)
                ov_sorted = row_mask
            elif isinstance(fn, NTile):
                cnt = (pend - part_start + 1).astype(jnp.int32)
                rn = idx - part_start
                n = jnp.int32(fn.n)
                base = cnt // n
                rem = cnt % n
                # Spark NTile: first `rem` buckets get base+1 rows
                big_rows = rem * (base + 1)
                out_sorted = jnp.where(
                    rn < big_rows,
                    rn // jnp.maximum(base + 1, 1),
                    rem + (rn - big_rows) // jnp.maximum(base, 1)
                ).astype(jnp.int32) + 1
                ov_sorted = row_mask
            elif isinstance(fn, (Lag, Lead)):
                sd = sorted_child.data
                sv = sorted_child.validity
                off = fn.signed_offset
                # STATIC shift (a concatenate), not a row gather
                ok = jnp.logical_and(idx - off >= 0, idx - off < P)
                out_sorted = shift_static(sd, off,
                                          jnp.zeros((), sd.dtype))
                ov_sorted = jnp.logical_and(
                    shift_static(sv, off, jnp.array(False)), ok)
                # must stay inside the partition
                same_part = shift_static(
                    gid, off, jnp.full((), P, gid.dtype)) == gid
                ov_sorted = jnp.logical_and(ov_sorted, same_part)
                if fn.default is not None:
                    dflt = jnp.asarray(fn.default, dtype=out_sorted.dtype)
                    fill = jnp.logical_and(jnp.logical_not(
                        jnp.logical_and(ok, same_part)), row_mask)
                    out_sorted = jnp.where(fill, dflt, out_sorted)
                    ov_sorted = jnp.logical_or(ov_sorted, fill)
            elif isinstance(fn, NthValue):
                sd = sorted_child.data
                sv = sorted_child.validity
                rel = idx - part_start
                src_flags = jnp.logical_and(rel == fn.n - 1, row_mask)
                out_sorted = last_valid_scan(sd, src_flags)[0]
                nth_valid = last_valid_scan(sv, src_flags)[0]
                ov_sorted = jnp.logical_and(
                    jnp.logical_and(rel >= fn.n - 1, nth_valid),
                    row_mask)
            elif isinstance(fn, AggregateExpression):
                out_sorted, ov_sorted = _windowed_agg(
                    fn, spec, ctx, sorted_child, part_start, idx,
                    row_mask, P, pflags, end_mask, pend)
            else:
                raise NotImplementedError(type(fn).__name__)

            # restore original order: ONE variadic sort keyed on the
            # carried original index (scatter + inverse gathers serialize
            # on the scalar core)
            _, od, ov = jax.lax.sort((perm, out_sorted, ov_sorted),
                                     num_keys=1, is_stable=True)
            outs.append((od, jnp.logical_and(ov, row_mask)))
        return outs

    return kernel


def _windowed_agg(fn: AggregateExpression, spec: WindowSpec, ctx,
                  sorted_child, part_start, idx, row_mask, P,
                  pflags, end_mask, pend):
    """Aggregate over a window frame. Default frames follow Spark: with
    order_by -> running (unbounded preceding..current row); without ->
    whole partition. All segment maths are scans + STATIC shifts — no
    row-sized gather or scatter anywhere (TPU scalar-core serialization).
    """
    if isinstance(fn, CountStar):
        vd = jnp.ones(P, dtype=jnp.int64)
        vv = row_mask
    else:
        vd = sorted_child.data
        vv = sorted_child.validity
    vv = jnp.logical_and(vv, row_mask)
    seg = SortedSegments(pflags, row_mask)

    frame = spec.frame
    if frame is None:
        frame = ("rows", None, 0) if spec.order_by else ("rows", None, None)
    kind, lo, hi = frame

    whole = lo is None and hi is None
    if whole:
        if isinstance(fn, (Sum, Average, Count, CountStar)):
            acc = vd
            if isinstance(fn, (Count, CountStar)):
                acc = vv.astype(jnp.int64)
            acc = acc.astype(jnp.float64 if isinstance(fn, Average)
                             else acc.dtype)
            tot = _end_broadcast(seg.sum(acc, vv), end_mask)
            cnt = _end_broadcast(seg.count(vv), end_mask)
            if isinstance(fn, (Count, CountStar)):
                return tot, row_mask
            if isinstance(fn, Average):
                ok = cnt > 0
                return (tot / jnp.maximum(cnt, 1).astype(jnp.float64), ok)
            return tot, cnt > 0
        if isinstance(fn, (Min, Max)):
            if jnp.issubdtype(vd.dtype, jnp.floating):
                # Spark: NaN is greatest; all-NaN group -> NaN
                notnan = jnp.logical_and(vv, jnp.logical_not(jnp.isnan(vd)))
                has_nan = _end_broadcast(
                    seg.max(jnp.logical_and(vv, jnp.isnan(vd))
                            .astype(jnp.int32), vv), end_mask) > 0
                red = seg.min if isinstance(fn, Min) else seg.max
                m = _end_broadcast(red(vd, notnan), end_mask)
                n_notnan = _end_broadcast(seg.count(notnan), end_mask)
                nanv = jnp.array(jnp.nan, dtype=vd.dtype)
                if isinstance(fn, Max):
                    m = jnp.where(has_nan, nanv, m)
                else:
                    m = jnp.where(jnp.logical_and(n_notnan == 0, has_nan),
                                  nanv, m)
            else:
                red = seg.min if isinstance(fn, Min) else seg.max
                m = _end_broadcast(red(vd, vv), end_mask)
            cnt = _end_broadcast(seg.count(vv), end_mask)
            return m, cnt > 0
        raise NotImplementedError(type(fn).__name__)

    # frame geometry shared by every bounded/running aggregate
    is_f = jnp.issubdtype(vd.dtype, jnp.floating)
    isnan = (jnp.logical_and(vv, jnp.isnan(vd)) if is_f
             else jnp.zeros(P, jnp.bool_))
    lo_i = part_start if lo is None else jnp.maximum(part_start, idx + lo)
    hi_i = pend if hi is None else jnp.minimum(pend, idx + hi)
    empty = hi_i < lo_i

    def window_sum(prefix):
        z = jnp.zeros((), prefix.dtype)
        # prefix value just BEFORE the partition (0 at the table start)
        before = _start_broadcast(shift_static(prefix, 1, z), pflags)
        at_end = _end_broadcast(prefix, end_mask)
        # upper = prefix[min(pend, idx+hi)] via a STATIC shift + clamp fix
        if hi is None:
            upper = at_end
        else:
            upper = jnp.where(idx + hi > pend, at_end,
                              shift_static(prefix, -hi, z))
        # lower = prefix[max(pstart, idx+lo) - 1]
        if lo is None:
            lower = before
        else:
            lower = jnp.where(idx + lo <= part_start, before,
                              shift_static(prefix, -(lo - 1), z))
        return jnp.where(empty, z, upper - lower)

    if isinstance(fn, (Min, Max)):
        return _bounded_minmax(fn, vd, vv, isnan, lo, hi, part_start,
                               pend, idx, row_mask, P, pflags, end_mask,
                               window_sum, empty)

    if not isinstance(fn, (Sum, Average, Count, CountStar)):
        raise NotImplementedError(
            f"bounded frame for {type(fn).__name__}")
    acc_dt = jnp.float64 if (isinstance(fn, Average)
                             or jnp.issubdtype(vd.dtype, jnp.floating)) \
        else jnp.int64
    # NaN must poison only frames CONTAINING it, not every later prefix:
    # sum finite values in the prefix and track NaN positions separately
    # (a frame whose NaN-count difference is >0 yields NaN)
    finite_ok = jnp.logical_and(vv, jnp.logical_not(isnan))
    acc = jnp.where(finite_ok, vd, jnp.zeros_like(vd)).astype(acc_dt)
    cntv = vv.astype(jnp.int64)
    ps = prefix_sum(acc)          # global prefix (inclusive)
    pc = prefix_sum(cntv)
    pn = prefix_sum(isnan.astype(jnp.int32))

    s = window_sum(ps)
    c = window_sum(pc)
    if isinstance(fn, (Count, CountStar)):
        return c, row_mask
    if is_f:
        frame_nan = window_sum(pn) > 0
        s = jnp.where(frame_nan, jnp.array(jnp.nan, s.dtype), s)
    if isinstance(fn, Average):
        ok = jnp.logical_and(c > 0, row_mask)
        return s.astype(jnp.float64) / jnp.maximum(c, 1).astype(jnp.float64), ok
    ok = jnp.logical_and(c > 0, row_mask)
    if jnp.issubdtype(vd.dtype, jnp.integer):
        s = s.astype(jnp.int64)
    return s, ok


def _numpy_window_one(fn, spec, col_np, n: int):
    """One window expression over host arrays; returns (data, validity)
    in ORIGINAL row order, or None if unsupported. Mirrors the device
    kernel's frame semantics (incl. Spark NaN/NULL rules)."""
    from .sort import _np_total_order_key
    keys = []
    for pk in spec.partition_by:
        got = col_np(pk)
        if got is None:
            return None
        keys.append((got, True, True))
    for o in spec.order_by:
        got = col_np(o.expr)
        if got is None:
            return None
        keys.append((got, o.ascending, o.nulls_first))
    child_pair = None
    child = getattr(fn, "child", None)
    if child is not None:
        child_pair = col_np(child)
        if child_pair is None:
            return None

    # one total-order encoding per key, shared by the sort AND boundary
    # detection (raw-value comparison would merge NULLs with the fill
    # value and split equal NaNs — the device kernel compares encoded
    # operands, so must we)
    encs = []
    for (v, ok), asc, nf in keys:
        enc = _np_total_order_key(np.asarray(v), np.asarray(ok))
        if not asc:
            enc = ~enc
        enc = np.where(ok, enc, np.uint64(0))
        rank = (np.where(ok, 1, 0) if nf else np.where(ok, 0, 1)) \
            .astype(np.uint8)
        encs.append((enc, rank))
    lex = []
    for enc, rank in reversed(encs):
        lex.extend([enc, rank])
    order = (np.lexsort(tuple(lex)) if lex
             else np.arange(n, dtype=np.int64))
    idx = np.arange(n, dtype=np.int64)

    def run_flags(pairs):
        flags = np.zeros(n, dtype=bool)
        if n:
            flags[0] = True
        for enc, rank in pairs:
            se, sr = enc[order], rank[order]
            diff = np.zeros(n, dtype=bool)
            diff[1:] = (se[1:] != se[:-1]) | (sr[1:] != sr[:-1])
            flags |= diff
        return flags

    npart = len(spec.partition_by)
    pflags = run_flags(encs[:npart])
    part_start = np.maximum.accumulate(np.where(pflags, idx, 0))
    # partition end: reverse accumulate of end flags
    endf = np.zeros(n, dtype=bool)
    if n:
        endf[-1] = True
        endf[:-1] = pflags[1:]
    pend = np.minimum.accumulate(np.where(endf, idx, n - 1)[::-1])[::-1]

    oflags = pflags | run_flags(encs[npart:])

    if isinstance(fn, RowNumber):
        out, ov = (idx - part_start + 1).astype(np.int64), \
            np.ones(n, bool)
    elif isinstance(fn, Rank):
        run_start = np.maximum.accumulate(np.where(oflags, idx, 0))
        out = (run_start - part_start + 1).astype(np.int64)
        ov = np.ones(n, bool)
    elif isinstance(fn, PercentRank):
        run_start = np.maximum.accumulate(np.where(oflags, idx, 0))
        rank = (run_start - part_start + 1).astype(np.float64)
        cnt = (pend - part_start + 1).astype(np.float64)
        out = np.where(cnt > 1, (rank - 1.0) / np.maximum(cnt - 1.0, 1.0),
                       0.0)
        ov = np.ones(n, bool)
    elif isinstance(fn, DenseRank):
        c = np.cumsum(oflags)
        c_at = np.maximum.accumulate(np.where(pflags, c, 0))
        out = (c - c_at + 1).astype(np.int64)
        ov = np.ones(n, bool)
    elif isinstance(fn, NthValue):
        vd = np.asarray(child_pair[0])[order]
        vv = np.asarray(child_pair[1])[order]
        rel = idx - part_start
        src = np.clip(part_start + fn.n - 1, 0, n - 1)
        ok = rel >= fn.n - 1
        out = np.where(ok, vd[src], np.zeros((), vd.dtype))
        ov = ok & vv[src]
    elif isinstance(fn, (Lag, Lead)):
        vd = np.asarray(child_pair[0])[order]
        vv = np.asarray(child_pair[1])[order]
        off = fn.signed_offset
        src = idx - off
        inside = (src >= part_start) & (src <= pend)
        srcc = np.clip(src, 0, n - 1)
        out = np.where(inside, vd[srcc], np.zeros((), vd.dtype))
        ov = np.where(inside, vv[srcc], False)
        if getattr(fn, "default", None) is not None:
            fill = ~inside
            out = np.where(fill, np.asarray(fn.default, vd.dtype), out)
            ov = ov | fill
    elif isinstance(fn, AggregateExpression) and isinstance(
            fn, (Sum, Average, Count, CountStar, Min, Max)):
        got = _numpy_frame_agg(fn, spec, child_pair, order, idx,
                               part_start, pend, n)
        if got is None:
            return None
        out, ov = got
    else:
        return None

    inv = np.empty(n, dtype=np.int64)
    inv[order] = idx
    return out[inv], ov[inv]


def _numpy_frame_agg(fn, spec, child_pair, order, idx, part_start, pend,
                     n: int):
    frame = spec.frame
    if frame is None:
        frame = ("rows", None, 0) if spec.order_by else \
            ("rows", None, None)
    kind, lo, hi = frame
    if kind != "rows":
        return None
    if isinstance(fn, CountStar):
        vd = np.ones(n, dtype=np.int64)
        vv = np.ones(n, dtype=bool)
    else:
        vd = np.asarray(child_pair[0])[order]
        vv = np.asarray(child_pair[1])[order]
    is_f = np.issubdtype(vd.dtype, np.floating)
    isnan = (vv & np.isnan(vd)) if is_f else np.zeros(n, bool)
    ok = vv & ~isnan
    lo_i = part_start if lo is None else np.maximum(part_start, idx + lo)
    hi_i = pend if hi is None else np.minimum(pend, idx + hi)
    empty = hi_i < lo_i
    hs = np.clip(hi_i, 0, max(n - 1, 0))
    ls = np.clip(lo_i, 0, max(n - 1, 0))

    def wsum(prefix):
        upper = prefix[hs]
        lower = np.where(ls > 0, prefix[np.maximum(ls - 1, 0)], 0)
        return np.where(empty, 0, upper - lower)

    c_valid = wsum(np.cumsum(vv.astype(np.int64)))
    c_nan = wsum(np.cumsum(isnan.astype(np.int64)))
    if isinstance(fn, (Min, Max)):
        is_min = isinstance(fn, Min)
        from ..columnar.segmented import _neutral_max, _neutral_min
        neutral = np.asarray(_neutral_max(vd.dtype) if is_min
                             else _neutral_min(vd.dtype), vd.dtype)
        masked = np.where(ok, vd, neutral)
        combine = np.minimum if is_min else np.maximum
        # sparse table over clamped per-row spans (log2 passes)
        span = (hs - ls + 1).astype(np.int64)
        span = np.where(empty, 1, span)
        K = int(max(span.max(), 1)).bit_length() - 1 if n else 0
        tables = [masked]
        for k in range(K):
            t = tables[-1]
            shifted = np.concatenate(
                [t[1 << k:], np.full(min(1 << k, n), neutral, vd.dtype)])
            tables.append(combine(t, shifted))
        k_i = np.maximum(
            np.int64(np.log2(np.maximum(span, 1))), 0).astype(np.int64) \
            if n else np.zeros(0, np.int64)
        # per-row table pick via np.select over log-many tables
        out = np.full(n, neutral, vd.dtype)
        for k in range(K + 1):
            sel = k_i == k
            if not sel.any():
                continue
            t = tables[k]
            a = ls[sel]
            b = hs[sel] - (1 << k) + 1
            out[sel] = combine(t[a], t[np.maximum(b, 0)])
        if is_f:
            n_ok = c_valid - c_nan
            if is_min:
                out = np.where((n_ok == 0) & (c_nan > 0), np.nan, out)
            else:
                out = np.where(c_nan > 0, np.nan, out)
        return out, (~empty) & (c_valid > 0)
    # sum / avg / count
    acc_dt = np.float64 if (isinstance(fn, Average) or is_f) else np.int64
    acc = np.where(ok, vd, 0).astype(acc_dt)
    s = wsum(np.cumsum(acc))
    if isinstance(fn, (Count, CountStar)):
        return wsum(np.cumsum(vv.astype(np.int64))), np.ones(n, bool)
    if is_f:
        s = np.where(c_nan > 0, np.nan, s)
    c_ok = wsum(np.cumsum(vv.astype(np.int64)))
    if isinstance(fn, Average):
        out = s.astype(np.float64) / np.maximum(c_ok, 1)
        return out, (c_ok > 0)
    if np.issubdtype(vd.dtype, np.integer):
        s = s.astype(np.int64)
    return s, (c_ok > 0)


def _seg_combine_scan(vals, flags, combine, neutral):
    """Segmented inclusive forward scan (Hillis-Steele: log2(P) STATIC
    shift+combine passes, unrolled — the rolled traced-shift form
    composes pathologically with surrounding sorts at compile time; see
    columnar/segmented.py)."""
    from ..columnar.segmented import shift_static
    v, f = vals, flags
    n = v.shape[0]
    neutral = jnp.asarray(neutral, dtype=v.dtype)
    d = 1
    while d < n:
        pv = shift_static(v, d, neutral)
        pf = shift_static(f, d, True)
        v = jnp.where(f, v, combine(pv, v))
        f = jnp.logical_or(f, pf)
        d <<= 1
    return v


def _bounded_minmax(fn, vd, vv, isnan, lo, hi, part_start, pend, idx,
                    row_mask, P, pflags, end_mask, window_sum, empty):
    """Bounded-frame MIN/MAX (removes the r1 limitation; ref
    GpuBatchedBoundedWindowExec). Sliding extrema without gathers:

      * interior rows (frame fully inside the partition) query a sparse
        table: T_k[i] = extremum over [i, i+2^k); the frame [a, a+W-1] is
        combine(T_K[a], T_K[a+W-2^K]) with K = floor(log2(W)) — both
        reads are STATIC shifts because a = i+lo;
      * start-clamped rows read the partition-running scan at i+hi;
      * end-clamped rows read the reverse (suffix) scan at i+lo;
      * doubly-clamped rows take the whole-partition extremum.

    All four candidates are elementwise selects over scans and static
    shifts — the same no-gather discipline as the rest of the kernel.
    Spark NaN semantics: max -> NaN if the frame contains any NaN; min ->
    NaN only when the frame has NaNs and no other valid values."""
    from ..columnar.segmented import _neutral_max, _neutral_min
    is_min = isinstance(fn, Min)
    combine = jnp.minimum if is_min else jnp.maximum
    neutral = _neutral_max(vd.dtype) if is_min else _neutral_min(vd.dtype)
    ok = jnp.logical_and(vv, jnp.logical_not(isnan))
    masked = jnp.where(ok, vd, jnp.asarray(neutral, vd.dtype))

    z = jnp.asarray(neutral, vd.dtype)
    run_fwd = _seg_combine_scan(masked, pflags, combine, neutral)
    # suffix scan = forward scan of the flipped array with flipped
    # segment-start flags (= end flags)
    run_rev = jnp.flip(_seg_combine_scan(
        jnp.flip(masked), jnp.flip(end_mask), combine, neutral))
    whole_part = _end_broadcast(run_fwd, end_mask)

    cands = []
    if lo is not None and hi is not None and hi >= lo:
        W = hi - lo + 1
        K = max(W.bit_length() - 1, 0)      # floor(log2(W))
        T = masked
        for k in range(K):
            T = combine(T, shift_static(T, -(1 << k), z))
        interior_val = combine(shift_static(T, -lo, z),
                               shift_static(T, -(hi - (1 << K) + 1), z))
        interior = jnp.logical_and(idx + lo >= part_start,
                                   idx + hi <= pend)
        cands.append((interior, interior_val))
    if hi is not None:
        start_clamped = shift_static(run_fwd, -hi, z)
        cands.append((jnp.logical_and(
            (idx + lo < part_start) if lo is not None
            else jnp.ones(P, jnp.bool_),
            idx + hi <= pend), start_clamped))
    if lo is not None:
        end_clamped = shift_static(run_rev, -lo, z)
        cands.append((jnp.logical_and(
            idx + lo >= part_start,
            (idx + hi > pend) if hi is not None
            else jnp.ones(P, jnp.bool_)), end_clamped))
    out = whole_part
    for mask, val in cands:
        out = jnp.where(mask, val, out)

    # null / NaN semantics from frame counts (prefix-sum machinery)
    c_valid = window_sum(prefix_sum(vv.astype(jnp.int64)))
    c_nan = window_sum(prefix_sum(isnan.astype(jnp.int32)))
    c_ok = window_sum(prefix_sum(ok.astype(jnp.int64)))
    has_val = jnp.logical_and(jnp.logical_not(empty), c_valid > 0)
    if jnp.issubdtype(vd.dtype, jnp.floating):
        nanv = jnp.array(jnp.nan, dtype=vd.dtype)
        if is_min:
            out = jnp.where(jnp.logical_and(c_ok == 0, c_nan > 0),
                            nanv, out)
        else:
            out = jnp.where(c_nan > 0, nanv, out)
    return out, jnp.logical_and(has_val, row_mask)


class TpuWindowExec(TpuExec):
    def __init__(self, window_exprs, child: TpuExec,
                 host_sink: bool = False):
        super().__init__([child])
        self.window_exprs = list(window_exprs)
        #: True when this window is the query's terminal stage: its
        #: row-sized result goes straight to a host collect, so the D2H
        #: fetch (not the compute) is the dominant cost on a tunneled
        #: backend — the cost model may run the SAME kernel on host XLA
        #: (ref CostBasedOptimizer's transition-cost reverts,
        #: RapidsConf.scala:2126)
        self.host_sink = host_sink
        cs = child.output_schema()
        fields = list(cs.fields)
        for e, spec, name in self.window_exprs:
            fields.append(StructField(name, e.data_type(cs), True))
        self._schema = Schema(fields)

    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        cs = self.children[0].output_schema()
        key = (tuple(f"{type(e).__name__}|{n}" for e, s, n in
                     self.window_exprs),
               tuple((f.name, f.dtype.name) for f in cs.fields), id(self))
        kern = _WIN_CACHE.get(key)
        if kern is None:
            kern = _build_window_kernel(self.window_exprs, cs)
            _WIN_CACHE[key] = kern
        # window needs whole partitions: single-batch goal
        spill = wrap_spillables(
            (b.ensure_device().with_lists_on_host()
             for b in self.children[0].execute(ctx)), ctx.memory)
        if not spill:
            return

        from ..config import WINDOW_HOST_SINK_ROWS
        thr = int(ctx.conf.get(WINDOW_HOST_SINK_ROWS))

        def run():
            with ctx.semaphore.held():
                batch = concat_batches([s.get() for s in spill])
                np_cols = (self._host_inputs(batch)
                           if self.host_sink and thr
                           and batch.num_rows >= thr else None)
                if np_cols is not None:
                    out = self._run_host_numpy(batch, cs, np_cols)
                    if out is not None:
                        return out
                    return self._run_host_xla(kern, batch, cs, np_cols)
                # host columns (e.g. high-cardinality strings) ride
                # through untouched; the kernel must not dereference them
                cols = [(c.data, c.validity)
                        if isinstance(c, DeviceColumn) else None
                        for c in batch.columns]
                outs = kern(cols, jnp.int32(batch.num_rows),
                            batch.padded_len)
                new_cols = list(batch.columns)
                for (d, v), (e, s, name) in zip(outs, self.window_exprs):
                    new_cols.append(DeviceColumn(d, v, e.data_type(cs)))
                return ColumnarBatch(new_cols, batch.num_rows, self._schema)

        try:
            out = with_retry_no_split(run, ctx=ctx, op=self._exec_id)
        finally:
            for s in spill:
                s.close()
        yield out

    # -- host numpy execution (terminal, fetch-bound windows) --------------
    def _run_host_numpy(self, batch, cs, np_cols):
        """Vectorized numpy evaluation of the window — the same
        prefix-sum / segment-broadcast formulas as the device kernel, on
        host-sorted arrays (np.lexsort ~3x faster than XLA-CPU's
        lax.sort). Returns None when an expression falls outside the
        supported set (caller then uses the host-XLA kernel, then the
        device). Differentially tested against BOTH other engines."""
        from ..columnar.column import HostColumn
        from ..exprs.arithmetic import masked_numpy_to_arrow
        n = batch.num_rows
        name_to = {f.name: i for i, f in enumerate(cs.fields)}

        def col_np(e):
            from ..exprs.base import Alias, ColumnRef
            inner = e.children[0] if isinstance(e, Alias) else e
            if not isinstance(inner, ColumnRef) \
                    or inner.name not in name_to:
                return None
            pair = np_cols[name_to[inner.name]]
            if pair is None:
                return None
            return pair[0][:n], pair[1][:n]

        new_cols = list(batch.columns)
        for fn, spec, name in self.window_exprs:
            res = _numpy_window_one(fn, spec, col_np, n)
            if res is None:
                return None
            d, v = res
            dt = fn.data_type(cs)
            new_cols.append(HostColumn(masked_numpy_to_arrow(d, v, dt),
                                       dt))
        return ColumnarBatch(new_cols, n, self._schema)

    # -- host-XLA execution (terminal, fetch-bound windows) ----------------
    def _host_inputs(self, batch):
        """Padded numpy (data, validity) pairs for every device column,
        WITHOUT a device fetch (host mirrors only); None when any needed
        column lacks a mirror (then the device path runs)."""
        from ..columnar.column import HostColumn
        from ..exprs.arithmetic import arrow_to_masked_numpy
        cols = []
        for c in batch.columns:
            if isinstance(c, DictColumn):
                return None          # codes live on device only
            if isinstance(c, DeviceColumn):
                mirror = c.host_mirror
                if mirror is None:
                    return None
                v, ok = arrow_to_masked_numpy(
                    mirror.combine_chunks() if hasattr(mirror,
                                                       "combine_chunks")
                    else mirror)
                d, val = DeviceColumn.host_prepare(
                    v, c.dtype, mask=ok, padded_len=batch.padded_len)
                cols.append((d, val))
            elif isinstance(c, HostColumn):
                cols.append(None)
            else:
                return None
        return cols

    def _run_host_xla(self, kern, batch, cs, np_cols):
        """Run the SAME window kernel compiled for the host XLA backend:
        identical semantics by construction, zero tunnel round trips.
        Output columns are HostColumns — the terminal collect reads them
        without any D2H."""
        import jax
        from ..columnar.column import HostColumn
        from ..exprs.arithmetic import masked_numpy_to_arrow
        cpu = jax.devices("cpu")[0]
        dev_cols = [None if c is None else
                    (jax.device_put(c[0], cpu), jax.device_put(c[1], cpu))
                    for c in np_cols]
        n = jax.device_put(jnp.int32(batch.num_rows), cpu)
        outs = kern(dev_cols, n, batch.padded_len)
        new_cols = list(batch.columns)
        for (d, v), (e, s, name) in zip(outs, self.window_exprs):
            dt = e.data_type(cs)
            dn = np.asarray(d)[:batch.num_rows]
            vn = np.asarray(v)[:batch.num_rows]
            new_cols.append(HostColumn(masked_numpy_to_arrow(dn, vn, dt),
                                       dt))
        return ColumnarBatch(new_cols, batch.num_rows, self._schema)

    def describe(self):
        names = ", ".join(n for _, _, n in self.window_exprs)
        return f"Window[{names}]"


class CpuWindowExec(TpuExec):
    is_tpu = False

    def __init__(self, window_exprs, child: TpuExec):
        super().__init__([child])
        self.window_exprs = list(window_exprs)
        cs = child.output_schema()
        fields = list(cs.fields)
        for e, spec, name in self.window_exprs:
            fields.append(StructField(name, e.data_type(cs), True))
        self._schema = Schema(fields)

    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        import pandas as pd
        import pyarrow as pa
        tables = [b.to_arrow() for b in self.children[0].execute(ctx)]
        if not tables:
            return
        t = pa.concat_tables(tables)
        df = t.to_pandas()
        batch = ColumnarBatch.from_arrow_host(t)
        for fn, spec, name in self.window_exprs:
            pcols = []
            for i, pk in enumerate(spec.partition_by):
                pc = f"__p{i}"
                df[pc] = pk.eval_host(batch).to_pandas()
                pcols.append(pc)
            ocols = []
            for i, o in enumerate(spec.order_by):
                oc = f"__o{i}"
                df[oc] = o.expr.eval_host(batch).to_pandas()
                ocols.append(oc)
            if pcols or ocols:
                # per-column direction AND null placement must match the
                # device kernel (order_key_operands); pandas sort_values
                # has one global na_position, so encode like CpuSortExec
                import numpy as np
                from ..exprs.arithmetic import arrow_to_masked_numpy
                from .sort import _np_total_order_key
                lex = []
                specs = [(o.expr, o.ascending, o.nulls_first)
                         for o in spec.order_by]
                specs = [(pk, True, True) for pk in spec.partition_by] + specs
                for e, asc_, nf in reversed(specs):
                    v, ok = arrow_to_masked_numpy(e.eval_host(batch))
                    enc = _np_total_order_key(v, ok)
                    if not asc_:
                        enc = ~enc
                    enc = np.where(ok, enc, np.uint64(0))
                    rank = np.where(ok, 1, 0) if nf else np.where(ok, 0, 1)
                    lex.extend([enc, rank.astype(np.uint8)])
                order = np.lexsort(tuple(lex))
                work = df.iloc[order]
            else:
                work = df
            g = work.groupby(pcols, dropna=False, sort=False) if pcols \
                else work.assign(__one=1).groupby("__one")
            if isinstance(fn, RowNumber):
                res = g.cumcount() + 1
            elif isinstance(fn, Rank):
                res = _sorted_rank(work, pcols, ocols, dense=False)
            elif isinstance(fn, DenseRank):
                res = _sorted_rank(work, pcols, ocols, dense=True)
            elif isinstance(fn, PercentRank):
                rk = _sorted_rank(work, pcols, ocols, dense=False)
                cnt = (g[work.columns[0]].transform("size") if pcols
                       else pd.Series(len(work), index=work.index))
                res = ((rk - 1) / (cnt - 1).clip(lower=1)) \
                    .where(cnt > 1, other=0.0)
            elif isinstance(fn, NTile):
                rn = g.cumcount()
                cnt = g[work.columns[0]].transform("size") \
                    if pcols else pd.Series(len(work), index=work.index)
                base, rem = cnt // fn.n, cnt % fn.n
                big = rem * (base + 1)
                res = (rn.where(rn < big, other=None).floordiv(base + 1)
                       .fillna(rem + (rn - big) // base.clip(lower=1))
                       .astype("int64") + 1)
            elif isinstance(fn, NthValue):
                res = _host_nth_value(fn, g, work, batch)
            elif isinstance(fn, (Lag, Lead)):
                # validity-aware shift: out-of-partition slots are SQL
                # NULL (or the default), never NaN — pandas shift's NaN
                # fill is indistinguishable from a real NaN value
                res = _host_shift(fn, g, work, batch)
            elif isinstance(fn, AggregateExpression):
                res = self._host_agg(fn, spec, g, work, batch)
            else:
                raise NotImplementedError(type(fn).__name__)
            df[name] = res.reindex(df.index) if hasattr(res, "reindex") \
                else res
            # drop only the temporaries THIS loop created — input columns
            # may legitimately start with "__" (e.g. SQL-hoisted windows)
            temps = set(pcols + ocols) | {"__v", "__a", "__one"}
            df = df.drop(columns=[c for c in df.columns if c in temps])
        from ..types import to_arrow
        arrays = []
        n_in = len(t.column_names)
        for fi, f in enumerate(self._schema.fields):
            if fi < n_in:
                # passthrough columns come straight from the input table:
                # the pandas round trip turns SQL NULL into NaN and could
                # not restore it (NaN-vs-NULL parity)
                col = t.column(fi).combine_chunks()
                if col.type != to_arrow(f.dtype):
                    col = col.cast(to_arrow(f.dtype))
                arrays.append(col)
                continue
            isf = f.dtype.name in ("float", "double")
            vals = [x if (isf and isinstance(x, float) and np.isnan(x))
                    else (None if pd.isna(x) else x)
                    for x in df[f.name].tolist()]
            arrays.append(pa.array(vals, type=to_arrow(f.dtype)))
        yield ColumnarBatch.from_arrow(
            pa.Table.from_arrays(arrays, names=self._schema.names()))

    def _host_agg(self, fn, spec, g, work, batch):
        """Frame aggregation on the host oracle with Spark semantics:
        SQL NULL (arrow validity) is skipped, NaN is a VALUE that poisons
        any frame containing it; FOLLOWING bounds are honored (pandas
        rolling is trailing-only and skips NaN, so frames are computed
        from per-partition prefix arrays instead)."""
        import numpy as np
        import pandas as pd
        n = len(work)
        if isinstance(fn, CountStar):
            vals = np.ones(n)
            ok = np.ones(n, dtype=bool)
        else:
            import pyarrow as pa
            arr = fn.child.eval_host(batch)
            if isinstance(arr, pa.ChunkedArray):
                arr = arr.combine_chunks()
            ok_full = ~np.asarray(arr.is_null())
            v_full = np.asarray(arr.to_pandas().to_numpy(), dtype=object)
            pos = work.index.to_numpy()
            vals = v_full[pos]
            ok = ok_full[pos]
        import pyarrow as pa
        if isinstance(fn, (Count, CountStar)):
            is_f, is_num, is_dec = False, True, False
        else:
            # decimal SUM/AVG take the float64 path (approximate — exact
            # decimal accumulation is future work); decimal MIN/MAX stay
            # exact via the object path below; int64 stays exact
            is_dec = pa.types.is_decimal(arr.type)
            is_f = pa.types.is_floating(arr.type) or is_dec
            is_num = is_f or pa.types.is_integer(arr.type)
        if is_f:
            fvals = np.asarray([np.nan if x is None else float(x)
                                for x in vals], dtype=np.float64)
        elif is_num:
            # int64 prefix sums stay EXACT (float64 would lose precision
            # past 2^53 and mangle decimals)
            fvals = np.asarray([0 if x is None else int(x)
                                for x in vals], dtype=np.int64)
        else:
            fvals = vals            # strings/dates: min/max only

        frame = spec.frame
        if frame is None:
            frame = ("rows", None, 0) if spec.order_by \
                else ("rows", None, None)
        kind, lo, hi = frame

        out = np.empty(n, dtype=object)
        start = 0
        sizes = (g.size().to_numpy() if hasattr(g, "size") else [n])
        for sz in sizes:
            sl = slice(start, start + int(sz))
            v = fvals[sl]
            k = ok[sl]
            m = int(sz)
            if is_num:
                isn = np.where(k, np.isnan(v), False) if is_f \
                    else np.zeros(m, dtype=bool)
                fin = k & ~isn
                acc = np.where(fin, v, 0).cumsum()
            else:
                isn = fin = np.zeros(m, dtype=bool)
                acc = np.zeros(m)
            nc = isn.astype(np.int64).cumsum()
            cnt = k.astype(np.int64).cumsum()
            i = np.arange(m)
            lo_i = np.zeros(m, np.int64) if lo is None \
                else np.clip(i + lo, 0, m)
            hi_i = np.full(m, m - 1) if hi is None \
                else np.minimum(i + hi, m - 1)
            empty = hi_i < lo_i
            hs = np.clip(hi_i, 0, m - 1)

            def dif(p):
                upper = p[hs]
                lower = np.where(lo_i > 0, p[np.maximum(lo_i - 1, 0)], 0)
                return np.where(empty, 0, upper - lower)

            if isinstance(fn, (Min, Max)) and (lo is not None
                                               or hi is not None):
                # bounded frames: direct per-row slice evaluation — the
                # oracle optimizes for obviousness, not speed
                res = np.empty(m, dtype=object)
                src = vals[sl]
                for j in range(m):
                    a = 0 if lo is None else max(j + lo, 0)
                    b_ = m - 1 if hi is None else min(j + hi, m - 1)
                    if b_ < a:
                        res[j] = None
                        continue
                    win_v = src[a:b_ + 1]
                    win_k = k[a:b_ + 1]
                    sel = [x for x, kk2 in zip(win_v, win_k) if kk2]
                    if not sel:
                        res[j] = None
                        continue
                    if is_f:
                        fs = [float(x) for x in sel]
                        nn = [x for x in fs if not np.isnan(x)]
                        if isinstance(fn, Max):
                            res[j] = np.nan if len(nn) < len(fs) \
                                else max(nn)
                        else:
                            res[j] = min(nn) if nn else np.nan
                    else:
                        res[j] = (min(sel) if isinstance(fn, Min)
                                  else max(sel))
                out[sl] = res
                start += int(sz)
                continue
            if isinstance(fn, (Min, Max)):
                # whole partition; Spark: NaN is greatest, all-NaN -> NaN
                if not k.any():
                    val = None
                elif not is_num or is_dec:  # strings/dates/decimals: exact
                    src = vals[sl] if is_dec else v
                    vv = [x for x, kk in zip(src, k) if kk]
                    val = min(vv) if isinstance(fn, Min) else max(vv)
                elif isinstance(fn, Max):
                    val = np.nan if (is_f and isn.any()) else v[fin].max()
                elif len(v[fin]):
                    val = v[fin].min()
                else:
                    val = np.nan
                out[sl] = np.full(m, val, dtype=object)
                start += int(sz)
                continue
            s_ = dif(acc)
            c_ = dif(cnt)
            has_nan = dif(nc) > 0
            if isinstance(fn, (Count, CountStar)):
                res = c_.astype(object)
            elif isinstance(fn, Average):
                res = np.where(has_nan, np.nan,
                               s_ / np.maximum(c_, 1))
                res = np.asarray(res, dtype=object)
                res[c_ == 0] = None
            else:  # Sum
                if is_f:
                    res = np.where(has_nan, np.nan, s_)
                else:
                    res = s_        # int64: exact, no NaN possible
                res = np.asarray(res, dtype=object)
                res[c_ == 0] = None
                if not is_f:
                    res = np.asarray(
                        [None if x is None else int(x) for x in res],
                        dtype=object)
            out[sl] = res
            start += int(sz)
        return pd.Series(out, index=work.index)

    def describe(self):
        return "CpuWindow[" + ", ".join(n for _, _, n in
                                        self.window_exprs) + "]"


def _host_nth_value(fn, g, work, batch):
    """Running-frame nth value: the partition's n-th row's value for
    rows at position >= n-1, else NULL."""
    import numpy as np
    import pyarrow as pa
    arr = fn.child.eval_host(batch)
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    ok_full = ~np.asarray(arr.is_null())
    v_full = np.asarray(arr.to_pandas().to_numpy(), dtype=object)
    pos = work.index.to_numpy()
    vals, ok = v_full[pos], ok_full[pos]
    out = np.empty(len(work), dtype=object)
    start = 0
    for sz in g.size().to_numpy():
        m = int(sz)
        res = np.full(m, None, dtype=object)
        if m >= fn.n:
            v = vals[start + fn.n - 1] if ok[start + fn.n - 1] else None
            res[fn.n - 1:] = v
        out[start:start + m] = res
        start += m
    import pandas as pd
    return pd.Series(out, index=work.index)


def _host_shift(fn, g, work, batch):
    import numpy as np
    import pandas as pd
    import pyarrow as pa
    arr = fn.child.eval_host(batch)
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    ok_full = ~np.asarray(arr.is_null())
    v_full = np.asarray(arr.to_pandas().to_numpy(), dtype=object)
    pos = work.index.to_numpy()
    vals, ok = v_full[pos], ok_full[pos]
    off = fn.signed_offset
    out = np.empty(len(work), dtype=object)
    start = 0
    for sz in g.size().to_numpy():
        m = int(sz)
        sl_v, sl_k = vals[start:start + m], ok[start:start + m]
        res = np.full(m, fn.default, dtype=object)   # outside partition
        if off >= 0:                                  # lag: shift right
            d = min(off, m)
            src_v, src_k = sl_v[:m - d], sl_k[:m - d]
            res[d:] = np.where(src_k, src_v, None)
        else:                                         # lead: shift left
            d = min(-off, m)
            src_v, src_k = sl_v[d:], sl_k[d:]
            res[:m - d] = np.where(src_k, src_v, None)
        out[start:start + m] = res
        start += m
    return pd.Series(out, index=work.index)


def _sorted_rank(work, pcols, ocols, dense: bool):
    """rank/dense_rank computed POSITIONALLY over the pre-sorted frame:
    the sort already applied each order column's ASC/DESC and null
    placement, so equal-key runs are contiguous and direction never needs
    re-deriving (pandas' value rank() is ascending-only and was wrong for
    DESC orders). Nulls compare EQUAL for ranking (Spark semantics), so
    run detection uses null-safe per-column equality, never tuple !=."""
    import pandas as pd
    grp = [work[c] for c in pcols] if pcols else \
        [pd.Series(0, index=work.index)]
    anchor = work[ocols[0]] if ocols else pd.Series(0, index=work.index)
    rn = anchor.groupby(grp, dropna=False, sort=False).cumcount() + 1
    same = pd.Series(True, index=work.index)
    for c in ocols:
        col, prev = work[c], work[c].shift(1)
        same &= (col == prev) | (col.isna() & prev.isna())
    newrun = (rn == 1) | ~same
    if dense:
        return newrun.groupby(grp, dropna=False, sort=False) \
            .cumsum().astype("int64")
    r = rn.where(newrun)
    return r.groupby(grp, dropna=False, sort=False).ffill().astype("int64")