"""Window exec (ref window/GpuWindowExec.scala:146 + specializations:
GpuRunningWindowExec scan-based running aggs, GpuBatchedBoundedWindowExec
bounded frames, BasicWindowCalc).

TPU-first, one fused kernel: ONE index-only lax.sort by (partition keys,
order keys), segment ids from boundaries, then every window column is
segment arithmetic on the VPU:
  row_number  = idx - partition_start + 1
  rank        = order-run start - partition_start + 1 (associative max scan)
  dense_rank  = per-partition cumsum of order-run starts
  lag/lead    = shifted gather with partition-boundary nulling
  unbounded aggregate frames = segment reduction broadcast via take(gid)
  running / bounded-rows sum,count,avg frames = partition-local prefix sums
    (prefix[i+hi] - prefix[i+lo-1])
Results scatter back to input row order through the inverse permutation, so
the exec preserves row order like the reference does.
"""
from __future__ import annotations

import functools
from typing import Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..columnar.segmented import prefix_sum
import numpy as np

from ..columnar import ColumnarBatch, DeviceColumn, concat_batches
from ..exprs.aggregates import AggregateExpression, Average, Count, CountStar, \
    Max, Min, Sum
from ..exprs.base import DVal, EvalContext
from ..exprs.window_fns import (DenseRank, Lag, Lead, NTile, Rank, RowNumber,
                                WindowFunction)
from ..mem import SpillableBatch, with_retry_no_split
from ..plan.logical import WindowSpec
from ..types import FLOAT64, INT32, INT64, Schema, StructField
from .base import ExecContext, TpuExec
from .encoding import grouping_operands, operands_equal, order_key_operands

__all__ = ["TpuWindowExec", "CpuWindowExec"]

_WIN_CACHE: Dict[Tuple, object] = {}


def _seg_broadcast(per_group, gid):
    return jnp.take(per_group, jnp.clip(gid, 0, per_group.shape[0] - 1))


def _build_window_kernel(window_exprs, schema: Schema, padded_len_key=None):
    dtypes = [f.dtype for f in schema.fields]

    @functools.partial(jax.jit, static_argnums=(2,))
    def kernel(cols, num_rows, padded_len):
        P = padded_len
        dvals = [None if c is None else DVal(c[0], c[1], dt)
                 for c, dt in zip(cols, dtypes)]
        ctx = EvalContext(schema, dvals, num_rows, P)
        row_mask = ctx.row_mask()
        outs = []
        for fn, spec, _name in window_exprs:
            # --- sort by (partition, order) --------------------------------
            pad_flag = jnp.where(row_mask, jnp.uint8(0), jnp.uint8(1))
            operands = [pad_flag]
            n_part_ops = 1
            for pk in spec.partition_by:
                operands.extend(grouping_operands(pk.eval_device(ctx)))
            n_part_ops = len(operands)
            for o in spec.order_by:
                operands.extend(order_key_operands(
                    o.expr.eval_device(ctx), o.ascending, o.nulls_first))
            perm0 = jnp.arange(P, dtype=jnp.int32)
            srt = jax.lax.sort(tuple(operands + [perm0]),
                               num_keys=len(operands), is_stable=True)
            perm = srt[len(operands)]
            s_ops = srt[:len(operands)]
            idx = jnp.arange(P, dtype=jnp.int32)
            # partition boundaries
            pdiff = jnp.zeros(P, dtype=jnp.bool_)
            for op in s_ops[1:n_part_ops]:
                prev = jnp.roll(op, 1)
                pdiff = jnp.logical_or(
                    pdiff, jnp.logical_not(operands_equal(op, prev)))
            pflags = jnp.logical_and(jnp.logical_or(idx == 0, pdiff), row_mask)
            gid = jnp.where(row_mask,
                            prefix_sum(pflags, jnp.int32) - 1, P)
            part_start = jax.lax.associative_scan(
                jnp.maximum, jnp.where(pflags, idx, 0))
            # order-value run boundaries (for rank/dense_rank)
            odiff = pdiff
            for op in s_ops[n_part_ops:]:
                prev = jnp.roll(op, 1)
                odiff = jnp.logical_or(
                    odiff, jnp.logical_not(operands_equal(op, prev)))
            oflags = jnp.logical_and(jnp.logical_or(idx == 0, odiff), row_mask)

            val = self_validity = None
            if isinstance(fn, (RowNumber,)):
                out_sorted = (idx - part_start + 1).astype(jnp.int32)
                ov_sorted = row_mask
            elif isinstance(fn, Rank):
                run_start = jax.lax.associative_scan(
                    jnp.maximum, jnp.where(oflags, idx, 0))
                out_sorted = (run_start - part_start + 1).astype(jnp.int32)
                ov_sorted = row_mask
            elif isinstance(fn, DenseRank):
                c = prefix_sum(oflags, jnp.int32)
                c_at_pstart = _seg_broadcast(
                    jnp.zeros(P, jnp.int32).at[
                        jnp.where(pflags, gid, P)].set(c, mode="drop"), gid)
                out_sorted = (c - c_at_pstart + 1).astype(jnp.int32)
                ov_sorted = row_mask
            elif isinstance(fn, NTile):
                pcount = jax.ops.segment_sum(
                    row_mask.astype(jnp.int32), gid, num_segments=P)
                cnt = _seg_broadcast(pcount, gid)
                rn = idx - part_start
                n = jnp.int32(fn.n)
                base = cnt // n
                rem = cnt % n
                # Spark NTile: first `rem` buckets get base+1 rows
                big_rows = rem * (base + 1)
                out_sorted = jnp.where(
                    rn < big_rows,
                    rn // jnp.maximum(base + 1, 1),
                    rem + (rn - big_rows) // jnp.maximum(base, 1)
                ).astype(jnp.int32) + 1
                ov_sorted = row_mask
            elif isinstance(fn, (Lag, Lead)):
                v = fn.child.eval_device(ctx)
                sd = jnp.take(v.data, perm)
                sv = jnp.take(v.validity, perm)
                off = fn.offset if isinstance(fn, Lag) else -fn.offset
                shifted_idx = idx - off
                ok = jnp.logical_and(shifted_idx >= 0, shifted_idx < P)
                src = jnp.clip(shifted_idx, 0, P - 1)
                out_sorted = jnp.take(sd, src)
                ov_sorted = jnp.logical_and(jnp.take(sv, src), ok)
                # must stay inside the partition
                same_part = jnp.take(gid, src) == gid
                ov_sorted = jnp.logical_and(ov_sorted, same_part)
                if fn.default is not None:
                    dflt = jnp.asarray(fn.default, dtype=out_sorted.dtype)
                    fill = jnp.logical_and(jnp.logical_not(
                        jnp.logical_and(ok, same_part)), row_mask)
                    out_sorted = jnp.where(fill, dflt, out_sorted)
                    ov_sorted = jnp.logical_or(ov_sorted, fill)
            elif isinstance(fn, AggregateExpression):
                out_sorted, ov_sorted = _windowed_agg(
                    fn, spec, ctx, perm, gid, part_start, idx, row_mask, P)
            else:
                raise NotImplementedError(type(fn).__name__)

            # scatter back to original order via inverse permutation
            inv = jnp.zeros(P, dtype=jnp.int32).at[perm].set(
                idx, mode="drop")
            outs.append((jnp.take(out_sorted, inv),
                         jnp.logical_and(jnp.take(ov_sorted, inv),
                                         row_mask)))
        return outs

    return kernel


def _windowed_agg(fn: AggregateExpression, spec: WindowSpec, ctx, perm, gid,
                  part_start, idx, row_mask, P):
    """Aggregate over a window frame. Default frames follow Spark: with
    order_by -> running (unbounded preceding..current row); without ->
    whole partition. Explicit ('rows', lo, hi) uses prefix sums."""
    if isinstance(fn, CountStar):
        vd = jnp.ones(P, dtype=jnp.int64)
        vv = row_mask
        dt = INT64
    else:
        v = fn.child.eval_device(ctx)
        vd = jnp.take(v.data, perm)
        vv = jnp.take(v.validity, perm)
        dt = v.dtype
    vv = jnp.logical_and(vv, row_mask)

    frame = spec.frame
    if frame is None:
        frame = ("rows", None, 0) if spec.order_by else ("rows", None, None)
    kind, lo, hi = frame

    whole = lo is None and hi is None
    if whole:
        if isinstance(fn, (Sum, Average, Count, CountStar)):
            acc = jnp.where(vv, vd, jnp.zeros_like(vd))
            if isinstance(fn, (Count, CountStar)):
                acc = vv.astype(jnp.int64)
            tot = jax.ops.segment_sum(acc.astype(
                jnp.float64 if isinstance(fn, Average) else acc.dtype),
                gid, num_segments=P)
            cnt = jax.ops.segment_sum(vv.astype(jnp.int64), gid,
                                      num_segments=P)
            if isinstance(fn, (Count, CountStar)):
                return _seg_broadcast(tot, gid), row_mask
            if isinstance(fn, Average):
                c = _seg_broadcast(cnt, gid)
                s = _seg_broadcast(tot, gid)
                ok = c > 0
                return s / jnp.maximum(c, 1).astype(jnp.float64), ok
            s = _seg_broadcast(tot, gid)
            ok = _seg_broadcast(cnt, gid) > 0
            return s, ok
        if isinstance(fn, (Min, Max)):
            from ..exprs.aggregates import _seg_max, _seg_min
            red = _seg_min if isinstance(fn, Min) else _seg_max
            m, cnt = red(vd, vv, gid, P)
            return _seg_broadcast(m, gid), _seg_broadcast(cnt, gid) > 0
        raise NotImplementedError(type(fn).__name__)

    # prefix-sum frames (running / bounded rows) for sum/count/avg
    if not isinstance(fn, (Sum, Average, Count, CountStar)):
        raise NotImplementedError(
            f"bounded frame for {type(fn).__name__}")
    acc_dt = jnp.float64 if (isinstance(fn, Average)
                             or jnp.issubdtype(vd.dtype, jnp.floating)) \
        else jnp.int64
    acc = jnp.where(vv, vd, jnp.zeros_like(vd)).astype(acc_dt)
    cntv = vv.astype(jnp.int64)
    ps = prefix_sum(acc)          # global prefix (inclusive)
    pc = prefix_sum(cntv)

    def window_sum(prefix):
        # sum over [max(pstart, i+lo), min(pend, i+hi)] in sorted space
        lo_i = part_start if lo is None else jnp.maximum(part_start, idx + lo)
        pcount = jax.ops.segment_sum(row_mask.astype(jnp.int32), gid,
                                     num_segments=P)
        pend = part_start + _seg_broadcast(pcount, gid) - 1
        hi_i = pend if hi is None else jnp.minimum(pend, idx + hi)
        hi_i = jnp.clip(hi_i, 0, P - 1)
        lo_i = jnp.clip(lo_i, 0, P)
        upper = jnp.take(prefix, hi_i)
        lower = jnp.where(lo_i > 0,
                          jnp.take(prefix, jnp.maximum(lo_i - 1, 0)),
                          jnp.zeros_like(upper))
        empty = hi_i < lo_i
        return jnp.where(empty, jnp.zeros_like(upper), upper - lower), empty

    s, empty = window_sum(ps)
    c, _ = window_sum(pc)
    if isinstance(fn, (Count, CountStar)):
        return c, row_mask
    if isinstance(fn, Average):
        ok = jnp.logical_and(c > 0, row_mask)
        return s.astype(jnp.float64) / jnp.maximum(c, 1).astype(jnp.float64), ok
    ok = jnp.logical_and(c > 0, row_mask)
    if jnp.issubdtype(vd.dtype, jnp.integer):
        s = s.astype(jnp.int64)
    return s, ok


class TpuWindowExec(TpuExec):
    def __init__(self, window_exprs, child: TpuExec):
        super().__init__([child])
        self.window_exprs = list(window_exprs)
        cs = child.output_schema()
        fields = list(cs.fields)
        for e, spec, name in self.window_exprs:
            fields.append(StructField(name, e.data_type(cs), True))
        self._schema = Schema(fields)

    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        cs = self.children[0].output_schema()
        key = (tuple(f"{type(e).__name__}|{n}" for e, s, n in
                     self.window_exprs),
               tuple((f.name, f.dtype.name) for f in cs.fields), id(self))
        kern = _WIN_CACHE.get(key)
        if kern is None:
            kern = _build_window_kernel(self.window_exprs, cs)
            _WIN_CACHE[key] = kern
        # window needs whole partitions: single-batch goal
        spill = [SpillableBatch(b.ensure_device(), ctx.memory)
                 for b in self.children[0].execute(ctx)]
        if not spill:
            return

        def run():
            with ctx.semaphore.held():
                batch = concat_batches([s.get() for s in spill])
                # host columns (e.g. high-cardinality strings) ride
                # through untouched; the kernel must not dereference them
                cols = [(c.data, c.validity)
                        if isinstance(c, DeviceColumn) else None
                        for c in batch.columns]
                outs = kern(cols, jnp.int32(batch.num_rows),
                            batch.padded_len)
                new_cols = list(batch.columns)
                for (d, v), (e, s, name) in zip(outs, self.window_exprs):
                    new_cols.append(DeviceColumn(d, v, e.data_type(cs)))
                return ColumnarBatch(new_cols, batch.num_rows, self._schema)

        out = with_retry_no_split(run, ctx.memory)
        for s in spill:
            s.close()
        yield out

    def describe(self):
        names = ", ".join(n for _, _, n in self.window_exprs)
        return f"Window[{names}]"


class CpuWindowExec(TpuExec):
    is_tpu = False

    def __init__(self, window_exprs, child: TpuExec):
        super().__init__([child])
        self.window_exprs = list(window_exprs)
        cs = child.output_schema()
        fields = list(cs.fields)
        for e, spec, name in self.window_exprs:
            fields.append(StructField(name, e.data_type(cs), True))
        self._schema = Schema(fields)

    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        import pandas as pd
        import pyarrow as pa
        tables = [b.to_arrow() for b in self.children[0].execute(ctx)]
        if not tables:
            return
        t = pa.concat_tables(tables)
        df = t.to_pandas()
        batch = ColumnarBatch.from_arrow(t, pad=False)
        for fn, spec, name in self.window_exprs:
            pcols = []
            for i, pk in enumerate(spec.partition_by):
                pc = f"__p{i}"
                df[pc] = pk.eval_host(batch).to_pandas()
                pcols.append(pc)
            ocols = []
            for i, o in enumerate(spec.order_by):
                oc = f"__o{i}"
                df[oc] = o.expr.eval_host(batch).to_pandas()
                ocols.append(oc)
            if pcols or ocols:
                # per-column direction AND null placement must match the
                # device kernel (order_key_operands); pandas sort_values
                # has one global na_position, so encode like CpuSortExec
                import numpy as np
                from ..exprs.arithmetic import arrow_to_masked_numpy
                from .sort import _np_total_order_key
                lex = []
                specs = [(o.expr, o.ascending, o.nulls_first)
                         for o in spec.order_by]
                specs = [(pk, True, True) for pk in spec.partition_by] + specs
                for e, asc_, nf in reversed(specs):
                    v, ok = arrow_to_masked_numpy(e.eval_host(batch))
                    enc = _np_total_order_key(v, ok)
                    if not asc_:
                        enc = ~enc
                    enc = np.where(ok, enc, np.uint64(0))
                    rank = np.where(ok, 1, 0) if nf else np.where(ok, 0, 1)
                    lex.extend([enc, rank.astype(np.uint8)])
                order = np.lexsort(tuple(lex))
                work = df.iloc[order]
            else:
                work = df
            g = work.groupby(pcols, dropna=False, sort=False) if pcols \
                else work.assign(__one=1).groupby("__one")
            if isinstance(fn, RowNumber):
                res = g.cumcount() + 1
            elif isinstance(fn, Rank):
                res = _sorted_rank(work, pcols, ocols, dense=False)
            elif isinstance(fn, DenseRank):
                res = _sorted_rank(work, pcols, ocols, dense=True)
            elif isinstance(fn, NTile):
                rn = g.cumcount()
                cnt = g[work.columns[0]].transform("size") \
                    if pcols else pd.Series(len(work), index=work.index)
                base, rem = cnt // fn.n, cnt % fn.n
                big = rem * (base + 1)
                res = (rn.where(rn < big, other=None).floordiv(base + 1)
                       .fillna(rem + (rn - big) // base.clip(lower=1))
                       .astype("int64") + 1)
            elif isinstance(fn, Lag):
                src = fn.child.eval_host(batch).to_pandas()
                work["__v"] = src.reindex(work.index)
                res = g["__v"].shift(fn.offset, fill_value=fn.default)
            elif isinstance(fn, Lead):
                src = fn.child.eval_host(batch).to_pandas()
                work["__v"] = src.reindex(work.index)
                res = g["__v"].shift(-fn.offset, fill_value=fn.default)
            elif isinstance(fn, AggregateExpression):
                res = self._host_agg(fn, spec, g, work, batch)
            else:
                raise NotImplementedError(type(fn).__name__)
            df[name] = res.reindex(df.index) if hasattr(res, "reindex") \
                else res
            # drop only the temporaries THIS loop created — input columns
            # may legitimately start with "__" (e.g. SQL-hoisted windows)
            temps = set(pcols + ocols) | {"__v", "__a", "__one"}
            df = df.drop(columns=[c for c in df.columns if c in temps])
        from ..types import to_arrow
        arrays = []
        for f in self._schema.fields:
            vals = [None if pd.isna(x) else x for x in df[f.name].tolist()]
            arrays.append(pa.array(vals, type=to_arrow(f.dtype)))
        yield ColumnarBatch.from_arrow(
            pa.Table.from_arrays(arrays, names=self._schema.names()))

    def _host_agg(self, fn, spec, g, work, batch):
        if isinstance(fn, CountStar):
            col = None
        else:
            work["__a"] = fn.child.eval_host(batch).to_pandas() \
                .reindex(work.index)
            col = "__a"
        frame = spec.frame
        if frame is None:
            frame = ("rows", None, 0) if spec.order_by \
                else ("rows", None, None)
        kind, lo, hi = frame
        if lo is None and hi is None:
            if isinstance(fn, CountStar):
                return g["__one" if "__one" in work.columns else
                         work.columns[0]].transform("size")
            m = {Sum: "sum", Min: "min", Max: "max", Average: "mean",
                 Count: "count"}[type(fn)]
            return g[col].transform(m)
        # running / bounded rows
        if isinstance(fn, CountStar):
            work["__a"] = 1
            col = "__a"
        window = (hi or 0) - (lo if lo is not None else -(10**9)) + 1
        minp = 1
        roll = g[col].rolling(window=window if lo is not None else 10**9,
                              min_periods=minp)
        m = {Sum: "sum", Count: "count", Average: "mean",
             CountStar: "count"}[type(fn)]
        res = getattr(roll, m)()
        if hi:
            res = g[col].rolling(window=window, min_periods=minp).agg(m)
        res.index = res.index.droplevel(list(range(res.index.nlevels - 1)))
        return res

    def describe(self):
        return "CpuWindow[" + ", ".join(n for _, _, n in
                                        self.window_exprs) + "]"


def _sorted_rank(work, pcols, ocols, dense: bool):
    """rank/dense_rank computed POSITIONALLY over the pre-sorted frame:
    the sort already applied each order column's ASC/DESC and null
    placement, so equal-key runs are contiguous and direction never needs
    re-deriving (pandas' value rank() is ascending-only and was wrong for
    DESC orders). Nulls compare EQUAL for ranking (Spark semantics), so
    run detection uses null-safe per-column equality, never tuple !=."""
    import pandas as pd
    grp = [work[c] for c in pcols] if pcols else \
        [pd.Series(0, index=work.index)]
    anchor = work[ocols[0]] if ocols else pd.Series(0, index=work.index)
    rn = anchor.groupby(grp, dropna=False, sort=False).cumcount() + 1
    same = pd.Series(True, index=work.index)
    for c in ocols:
        col, prev = work[c], work[c].shift(1)
        same &= (col == prev) | (col.isna() & prev.isna())
    newrun = (rn == 1) | ~same
    if dense:
        return newrun.groupby(grp, dropna=False, sort=False) \
            .cumsum().astype("int64")
    r = rn.where(newrun)
    return r.groupby(grp, dropna=False, sort=False).ffill().astype("int64")