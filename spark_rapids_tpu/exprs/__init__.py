from .base import (Alias, BoundReference, ColumnRef, DVal, EvalContext,
                   Expression, Literal, Unsupported, promote_types)
from .arithmetic import (Abs, Add, Divide, IntegralDivide, Multiply, Pmod,
                         Remainder, Subtract, UnaryMinus)
from .comparison import (EqualNullSafe, EqualTo, GreaterThan,
                         GreaterThanOrEqual, In, IsNaN, IsNotNull, IsNull,
                         LessThan, LessThanOrEqual, NotEqual)
from .logical import And, Not, Or
from .math_fns import (Acos, Asin, Atan, Atan2, Cbrt, Ceil, Cos, Cosh, Exp,
                       Expm1, Floor, Log, Log1p, Log2, Log10, Pow, Rint,
                       Round, Signum, Sin, Sinh, Sqrt, Tan, Tanh, ToDegrees,
                       ToRadians)
from .conditional import CaseWhen, Coalesce, If, NaNvl
from .cast import Cast
from .compiler import (DeviceProjector, compile_projection,
                       eval_predicate_device, filter_batch_device,
                       gather_batch_device)
