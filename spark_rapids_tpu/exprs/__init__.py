from .base import (Alias, BoundReference, ColumnRef, DVal, EvalContext,
                   Expression, Literal, Unsupported, promote_types)
from .arithmetic import (Abs, Add, Divide, IntegralDivide, Multiply, Pmod,
                         Remainder, Subtract, UnaryMinus)
from .comparison import (EqualNullSafe, EqualTo, GreaterThan,
                         GreaterThanOrEqual, In, IsNaN, IsNotNull, IsNull,
                         LessThan, LessThanOrEqual, NotEqual)
from .logical import And, Not, Or
from .math_fns import (Acos, Asin, Atan, Atan2, Cbrt, Ceil, Cos, Cosh, Exp,
                       Expm1, Floor, Log, Log1p, Log2, Log10, Pow, Rint,
                       Round, Signum, Sin, Sinh, Sqrt, Tan, Tanh, ToDegrees,
                       ToRadians)
from .conditional import CaseWhen, Coalesce, If, NaNvl
from .cast import Cast
from .datetime_fns import (DateAdd, DateDiff, DateSub, DayOfMonth, DayOfWeek,
                           DayOfYear, FromUtcTimestamp, Hour, Minute, Month,
                           Quarter, Second, ToUtcTimestamp, UnixDate,
                           WeekDay, Year)
from .string_fns import (ConcatStrings, Contains, EndsWith, InitCap, Length,
                         Like, Lower, Lpad, ParseUrl, RLike, RegExpExtract,
                         RegExpReplace, Reverse, Rpad, StartsWith,
                         StringLocate, StringRepeat, StringReplace,
                         StringSplit, StringTrim, StringTrimLeft,
                         StringTrimRight, Substring, SubstringIndex, Upper)
from .regex_transpiler import (RegexUnsupported, sql_like_to_regex,
                               transpile_java_regex)
from .window_fns import DenseRank, Lag, Lead, NTile, Rank, RowNumber
from .collection_fns import (ArrayContains, ArrayDistinct, ArrayExcept,
                             ArrayIntersect, ArrayJoin, ArrayMax, ArrayMin,
                             ArrayPosition, ArrayRemove, ArrayRepeat,
                             ArrayReverse, ArraysOverlap, ArraysZip,
                             ArrayUnion, Concat, CreateArray, CreateMap,
                             CreateNamedStruct, ElementAt, Flatten,
                             GetArrayItem, GetMapValue, GetStructField,
                             MapConcat, MapEntries, MapFromArrays, MapKeys,
                             MapValues, Sequence, Size, Slice, SortArray,
                             StringToMap)
from .higher_order import (ArrayAggregate, ArrayExists, ArrayFilter,
                           ArrayForAll, ArrayTransform, MapFilter,
                           NamedLambdaVariable, TransformKeys,
                           TransformValues, ZipWith)
from .hash_fns import (Crc32, HiveHash, Md5, Murmur3Hash, Sha1, Sha2,
                       XxHash64)
from .json_fns import (GetJsonObject, JsonToStructs, JsonTuple,
                       StructsToJson)
from .generators import Explode, Generator, PosExplode, Stack
from .nondeterministic import (InputFileName, MonotonicallyIncreasingID,
                               Rand, SparkPartitionID)
from .compiler import (DeviceProjector, compile_projection,
                       eval_predicate_device, filter_batch_device,
                       gather_batch_device)
