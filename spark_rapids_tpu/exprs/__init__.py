from .base import (Alias, BoundReference, ColumnRef, DVal, EvalContext,
                   Expression, Literal, Unsupported, promote_types)
from .arithmetic import (Abs, Add, BitwiseAnd, BitwiseNot, BitwiseOr,
                         BitwiseXor, Divide, IntegralDivide, Multiply, Pmod,
                         Remainder, ShiftLeft, ShiftRight,
                         ShiftRightUnsigned, Subtract, UnaryMinus,
                         UnaryPositive)
from .comparison import (EqualNullSafe, EqualTo, GreaterThan,
                         GreaterThanOrEqual, In, IsNaN, IsNotNull, IsNull,
                         LessThan, LessThanOrEqual, NotEqual)
from .logical import And, Not, Or
from .math_fns import (Acos, Acosh, Asin, Asinh, Atan, Atan2, Atanh,
                       BRound, Cbrt, Ceil, Cos, Cosh, Cot, Exp, Expm1,
                       Floor, Hypot, Log, Log1p, Log2, Log10, Logarithm,
                       Pow, Rint, Round, Signum, Sin, Sinh, Sqrt, Tan,
                       Tanh, ToDegrees, ToRadians)
from .conditional import (AtLeastNNonNulls, CaseWhen, Coalesce, Greatest,
                          NullIf,
                          If, KnownFloatingPointNormalized, KnownNotNull,
                          Least, NaNvl, NormalizeNaNAndZero)
from .cast import Cast
from .datetime_fns import (AddMonths, DateAdd, DateDiff, DateFormatClass,
                           DateSub, DayOfMonth, DayOfWeek, DayOfYear,
                           FromUnixTime, FromUtcTimestamp, Hour, LastDay,
                           MicrosToTimestamp, MillisToTimestamp, Minute,
                           Month, MonthsBetween, Quarter, Second,
                           SecondsToTimestamp, TimeAdd, ToUnixTimestamp,
                           ToUtcTimestamp, TruncDate, UnixDate,
                           UnixTimestamp, WeekDay, Year)
from .string_fns import (Ascii, BitLength, Chr, ConcatStrings, ConcatWs,
                         Contains, EndsWith, FormatNumber, InitCap, Length,
                         Like, Lower, Lpad, OctetLength, ParseUrl, RLike,
                         RegExpExtract, RegExpReplace, Reverse, Rpad,
                         StartsWith, StringInstr, StringLocate,
                         StringRepeat, StringReplace, StringSplit,
                         StringTranslate, StringTrim, StringTrimLeft,
                         StringTrimRight, Substring, SubstringIndex, Upper)
from .regex_transpiler import (RegexUnsupported, sql_like_to_regex,
                               transpile_java_regex)
from .window_fns import (DenseRank, Lag, Lead, NthValue, NTile,
                         PercentRank, Rank,
                         RowNumber)
from .collection_fns import (ArrayContains, ArrayDistinct, ArrayExcept,
                             ArrayIntersect, ArrayJoin, ArrayMax, ArrayMin,
                             ArrayPosition, ArrayRemove, ArrayRepeat,
                             ArrayReverse, ArraysOverlap, ArraysZip,
                             ArrayUnion, Concat, CreateArray, CreateMap,
                             CreateNamedStruct, ElementAt, Flatten,
                             GetArrayItem, GetMapValue, GetStructField,
                             MapConcat, MapEntries, MapFromArrays, MapKeys,
                             MapValues, Sequence, Size, Slice, SortArray,
                             StringToMap)
from .higher_order import (ArrayAggregate, ArrayExists, ArrayFilter,
                           ArrayForAll, ArrayTransform, MapFilter,
                           NamedLambdaVariable, TransformKeys,
                           TransformValues, ZipWith)
from .hash_fns import (Crc32, HiveHash, Md5, Murmur3Hash, Sha1, Sha2,
                       XxHash64)
from .json_fns import (GetJsonObject, JsonToStructs, JsonTuple,
                       StructsToJson)
from .generators import Explode, Generator, PosExplode, Stack
from .nondeterministic import (InputFileName, MonotonicallyIncreasingID,
                               Rand, SparkPartitionID)
from .compiler import (DeviceProjector, compile_projection,
                       eval_predicate_device, filter_batch_device,
                       gather_batch_device)
