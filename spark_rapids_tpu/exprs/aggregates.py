"""Aggregate functions (ref aggregate/aggregateFunctions.scala, 2,158 LoC;
GpuAggregateFunction trait aggregateBase.scala:79).

TPU-first design: groupby is segmented reduction, not hash tables (cudf's
hash groupby relies on device atomics, which have no XLA analog). Two
regimes, both scatter-free (columnar/segmented.py): dense one-hot
broadcast+reduce when the group-id space is small (dictionary-coded keys),
and sort + Hillis-Steele segmented scans for the general case — every
aggregate's seg_* call dispatches on the context it is handed. Each
aggregate declares:
  update   : per-row values  -> per-group partials      (first pass, per batch)
  merge    : per-group partials -> per-group partials   (combining batches or
             shuffle partitions — identical maths to the reference's
             GpuMergeAggregateIterator pass, GpuAggregateExec.scala:718)
  finalize : partials -> result column
Spark null semantics: sum/min/max/avg ignore nulls, empty group -> null;
count is never null. Float NaN: NaN is greatest for min/max.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..types import (BOOL, DataType, DecimalType, FLOAT64, INT64, Schema,
                     TypeEnum, numeric, tpuNative)
from .base import DVal, Expression, Literal
from ..columnar.segmented import SortedSegments, seg_max, seg_min, seg_sum

__all__ = ["AggregateExpression", "Sum", "Count", "CountStar", "Min", "Max",
           "Average", "First", "Last", "StddevSamp", "StddevPop",
           "VarianceSamp", "VariancePop", "CollectList", "CollectSet",
           "MinBy", "MaxBy", "Percentile", "ApproximatePercentile"]


def _seg_sum(data, valid, gid, num_segments):
    masked = jnp.where(valid, data, jnp.zeros_like(data))
    s = seg_sum(masked, gid, num_segments=num_segments)
    cnt = seg_sum(valid.astype(jnp.int64), gid,
                              num_segments=num_segments)
    return s, cnt


def _seg_min(data, valid, gid, num_segments):
    if jnp.issubdtype(data.dtype, jnp.floating):
        big = jnp.array(jnp.inf, dtype=data.dtype)
        masked = jnp.where(valid & ~jnp.isnan(data), data, big)
        has_nan = seg_max(
            (valid & jnp.isnan(data)).astype(jnp.int32), gid,
            num_segments=num_segments) > 0
        non_nan_cnt = seg_sum(
            (valid & ~jnp.isnan(data)).astype(jnp.int64), gid,
            num_segments=num_segments)
        m = seg_min(masked, gid, num_segments=num_segments)
        # all-NaN group: min is NaN (NaN is greatest but it's all there is)
        m = jnp.where((non_nan_cnt == 0) & has_nan,
                      jnp.array(jnp.nan, dtype=data.dtype), m)
        cnt = seg_sum(valid.astype(jnp.int64), gid,
                                  num_segments=num_segments)
        return m, cnt
    info = jnp.iinfo(data.dtype) if jnp.issubdtype(data.dtype, jnp.integer) \
        else None
    big = jnp.array(info.max, dtype=data.dtype) if info is not None else True
    masked = jnp.where(valid, data, big)
    m = seg_min(masked, gid, num_segments=num_segments)
    cnt = seg_sum(valid.astype(jnp.int64), gid,
                              num_segments=num_segments)
    return m, cnt


def _seg_max(data, valid, gid, num_segments):
    if jnp.issubdtype(data.dtype, jnp.floating):
        small = jnp.array(-jnp.inf, dtype=data.dtype)
        masked = jnp.where(valid & ~jnp.isnan(data), data, small)
        has_nan = seg_max(
            (valid & jnp.isnan(data)).astype(jnp.int32), gid,
            num_segments=num_segments) > 0
        m = seg_max(masked, gid, num_segments=num_segments)
        # Spark: NaN is greatest, so any NaN -> max is NaN
        m = jnp.where(has_nan, jnp.array(jnp.nan, dtype=data.dtype), m)
        cnt = seg_sum(valid.astype(jnp.int64), gid,
                                  num_segments=num_segments)
        return m, cnt
    info = jnp.iinfo(data.dtype) if jnp.issubdtype(data.dtype, jnp.integer) \
        else None
    small = jnp.array(info.min, dtype=data.dtype) if info is not None else False
    masked = jnp.where(valid, data, small)
    m = seg_max(masked, gid, num_segments=num_segments)
    cnt = seg_sum(valid.astype(jnp.int64), gid,
                              num_segments=num_segments)
    return m, cnt


class AggregateExpression:
    """Base: not an Expression (cannot appear mid-row-expression); planner
    handles it in Aggregate nodes only (ref GpuAggregateExpression:219)."""

    #: DISTINCT modifier (agg(DISTINCT e)); the TPU path rewrites the plan
    #: into a two-level aggregation (plan/rewrites.py), the host aggregate
    #: dedups natively
    distinct: bool = False

    def __init__(self, child: Optional[Expression], name: Optional[str] = None):
        self.child = child
        self._name = name

    def as_distinct(self) -> "AggregateExpression":
        self.distinct = True
        return self

    # ---- naming / typing -------------------------------------------------
    @property
    def name_hint(self) -> str:
        if self._name:
            return self._name
        cn = self.child.name_hint if self.child is not None else "*"
        return f"{type(self).__name__.lower()}({cn})"

    def with_name(self, name: str) -> "AggregateExpression":
        self._name = name
        return self

    def data_type(self, schema: Schema) -> DataType:
        raise NotImplementedError

    def device_unsupported_reason(self, schema: Schema) -> Optional[str]:
        from .base import expression_disabled_reason
        r = expression_disabled_reason(type(self))
        if r:
            return r
        if self.child is None:
            return None
        r = self.child.fully_device_supported(schema)
        if r:
            return r
        dt = self.child.data_type(schema)
        if not dt.device_backed:
            return f"{self.name_hint}: input type {dt.name} is host-only"
        return None

    # ---- device pipeline -------------------------------------------------
    def input_exprs(self) -> List[Expression]:
        return [self.child] if self.child is not None else []

    def partial_types(self, schema: Schema) -> List[DataType]:
        raise NotImplementedError

    def update(self, vals: List[DVal], gid, num_segments, row_mask):
        """per-row DVals -> list of per-group (data, validity) partials."""
        raise NotImplementedError

    def merge(self, partials: List[DVal], gid, num_segments):
        raise NotImplementedError

    def finalize(self, partials: List[DVal]) -> DVal:
        raise NotImplementedError

    # ---- host (CPU fallback + oracle) -----------------------------------
    #: pandas groupby aggregation name used by the host aggregate exec
    pandas_agg: str = "?"

    def key(self) -> str:
        c = self.child.key() if self.child is not None else "*"
        d = "DISTINCT " if self.distinct else ""
        return f"{type(self).__name__}({d}{c})"


#: decimal SUM limb base: 3 limbs of 10^12 cover 36+ digits of running
#: total, and a <=2^20-row segment of limb values stays inside int64
_DEC_LIMB = 10 ** 12
_DEC_LIMB2 = _DEC_LIMB * _DEC_LIMB


def _dec_normalize(l0, l1, l2):
    """Carry-propagate limb sums back into canonical form
    (l0, l1 in [0, base); sign carried by l2)."""
    l1 = l1 + l0 // _DEC_LIMB
    l0 = l0 % _DEC_LIMB
    l2 = l2 + l1 // _DEC_LIMB
    l1 = l1 % _DEC_LIMB
    return l0, l1, l2


class Sum(AggregateExpression):
    pandas_agg = "sum"
    device_type_sig = tpuNative.with_psnote(
        TypeEnum.DECIMAL,
        "totals whose |unscaled value| >= 2^63 finalize as NULL (device "
        "decimals are int64-scaled; Spark non-ANSI would return up to "
        "38 digits)")

    def data_type(self, schema):
        dt = self.child.data_type(schema)
        if dt.name in ("tinyint", "smallint", "int", "bigint"):
            return INT64
        if isinstance(dt, DecimalType):
            # Spark: sum(decimal(p,s)) -> decimal(min(p+10, 38), s).
            # ENGINE LIMITATION (documented in docs/performance.md and
            # supported_ops): device decimals are int64-scaled, so a
            # finalized total whose |unscaled value| >= 2^63 returns
            # NULL even when the declared result precision could hold it
            # (Spark non-ANSI would return the value up to min(p+10,38)
            # digits). The limb accumulation itself is exact; only the
            # final materialization is capped. Same cap as ingest
            # (types.py/_decimal-to-int64).
            return DecimalType(min(dt.precision + 10, 38), dt.scale)
        return FLOAT64 if dt.name in ("float", "double") else dt

    def _is_decimal(self, schema) -> bool:
        return isinstance(self.child.data_type(schema), DecimalType)

    def partial_types(self, schema):
        if self._is_decimal(schema):
            return [INT64, INT64, INT64]
        return [self.data_type(schema)]

    def update(self, vals, gid, num_segments, row_mask):
        v = vals[0]
        if isinstance(v.dtype, DecimalType):
            # exact 128-bit-wide accumulation in 10^12-base limbs: every
            # per-segment limb sum fits int64 (ref DecimalUtils JNI
            # 128-bit sums; TPU has no int128, limbs are the XLA shape)
            x = v.data.astype(jnp.int64)
            xd = x // _DEC_LIMB
            l0, c = _seg_sum(x % _DEC_LIMB, v.validity, gid, num_segments)
            l1, _ = _seg_sum(xd % _DEC_LIMB, v.validity,
                             gid, num_segments)
            l2, _ = _seg_sum(xd // _DEC_LIMB, v.validity, gid,
                             num_segments)
            l0, l1, l2 = _dec_normalize(l0, l1, l2)
            ok = c > 0
            return [(l0, ok), (l1, ok), (l2, ok)]
        # promote to the accumulator type before summing
        acc_dt = jnp.int64 if jnp.issubdtype(v.data.dtype, jnp.integer) \
            else jnp.float64
        s, cnt = _seg_sum(v.data.astype(acc_dt), v.validity, gid, num_segments)
        return [(s, cnt > 0)]

    def merge(self, partials, gid, num_segments):
        if len(partials) == 3:         # decimal limbs
            sums = []
            ok = None
            for p in partials:
                s, cnt = _seg_sum(p.data, p.validity, gid, num_segments)
                sums.append(s)
                ok = cnt > 0 if ok is None else ok
            l0, l1, l2 = _dec_normalize(*sums)
            return [(l0, ok), (l1, ok), (l2, ok)]
        p = partials[0]
        s, cnt = _seg_sum(p.data, p.validity, gid, num_segments)
        return [(s, cnt > 0)]

    def finalize(self, partials):
        if len(partials) == 3:
            l0, l1, l2 = (p.data for p in partials)
            ok = partials[0].validity
            # representable on device iff the exact total fits int64;
            # beyond that Spark's (non-ANSI) overflow answer is NULL —
            # the f64 magnitude test is exact to ~1e3 at the boundary,
            # erring to NULL inside the last few thousand ulps
            est = (l2.astype(jnp.float64) * float(_DEC_LIMB2)
                   + l1.astype(jnp.float64) * float(_DEC_LIMB)
                   + l0.astype(jnp.float64))
            fits = jnp.abs(est) < 9.223372e18
            # nested form keeps every constant and (when fits) every
            # intermediate inside int64: value = (l2*M + l1)*M + l0;
            # non-fitting lanes wrap silently and are masked NULL
            total = (l2 * _DEC_LIMB + l1) * _DEC_LIMB + l0
            return DVal(jnp.where(fits, total, 0),
                        jnp.logical_and(ok, fits), INT64)
        return partials[0]


class Count(AggregateExpression):
    pandas_agg = "count"

    def data_type(self, schema):
        return INT64

    def partial_types(self, schema):
        return [INT64]

    def update(self, vals, gid, num_segments, row_mask):
        v = vals[0]
        cnt = seg_sum(v.validity.astype(jnp.int64), gid,
                                  num_segments=num_segments)
        return [(cnt, jnp.ones_like(cnt, dtype=jnp.bool_))]

    def merge(self, partials, gid, num_segments):
        p = partials[0]
        s, _ = _seg_sum(p.data, p.validity, gid, num_segments)
        return [(s, jnp.ones_like(s, dtype=jnp.bool_))]

    def finalize(self, partials):
        p = partials[0]
        # count is never null: empty merge slots become 0
        return DVal(jnp.where(p.validity, p.data, jnp.zeros_like(p.data)),
                    jnp.ones_like(p.validity), INT64)


class CountStar(Count):
    def __init__(self, name: Optional[str] = None):
        super().__init__(None, name)

    @property
    def name_hint(self):
        return self._name or "count(1)"

    def input_exprs(self):
        return [Literal(1)]

    def update(self, vals, gid, num_segments, row_mask):
        ones = row_mask.astype(jnp.int64)
        cnt = seg_sum(ones, gid, num_segments=num_segments)
        return [(cnt, jnp.ones_like(cnt, dtype=jnp.bool_))]


class Min(AggregateExpression):
    pandas_agg = "min"

    def data_type(self, schema):
        return self.child.data_type(schema)

    def partial_types(self, schema):
        return [self.data_type(schema)]

    def update(self, vals, gid, num_segments, row_mask):
        v = vals[0]
        m, cnt = _seg_min(v.data, v.validity, gid, num_segments)
        return [(m, cnt > 0)]

    def merge(self, partials, gid, num_segments):
        p = partials[0]
        m, cnt = _seg_min(p.data, p.validity, gid, num_segments)
        return [(m, cnt > 0)]

    def finalize(self, partials):
        return partials[0]


class Max(AggregateExpression):
    pandas_agg = "max"

    def data_type(self, schema):
        return self.child.data_type(schema)

    def partial_types(self, schema):
        return [self.data_type(schema)]

    def update(self, vals, gid, num_segments, row_mask):
        v = vals[0]
        m, cnt = _seg_max(v.data, v.validity, gid, num_segments)
        return [(m, cnt > 0)]

    def merge(self, partials, gid, num_segments):
        p = partials[0]
        m, cnt = _seg_max(p.data, p.validity, gid, num_segments)
        return [(m, cnt > 0)]

    def finalize(self, partials):
        return partials[0]


class Average(AggregateExpression):
    pandas_agg = "mean"

    def data_type(self, schema):
        return FLOAT64

    def partial_types(self, schema):
        return [FLOAT64, INT64]  # sum, count

    def update(self, vals, gid, num_segments, row_mask):
        v = vals[0]
        s, cnt = _seg_sum(v.data.astype(jnp.float64), v.validity, gid,
                          num_segments)
        ok = cnt > 0
        return [(s, ok), (cnt, jnp.ones_like(ok))]

    def merge(self, partials, gid, num_segments):
        s, _ = _seg_sum(partials[0].data, partials[0].validity, gid,
                        num_segments)
        c, _ = _seg_sum(partials[1].data, partials[1].validity, gid,
                        num_segments)
        return [(s, c > 0), (c, jnp.ones_like(c, dtype=jnp.bool_))]

    def finalize(self, partials):
        s, c = partials[0], partials[1]
        ok = jnp.logical_and(s.validity, c.data > 0)
        denom = jnp.where(c.data > 0, c.data, jnp.ones_like(c.data))
        return DVal(s.data / denom.astype(jnp.float64), ok, FLOAT64)


class First(AggregateExpression):
    """first(x, ignoreNulls=True) — within-batch order; cross-batch order
    follows batch arrival like the reference's first agg."""
    pandas_agg = "first"

    def data_type(self, schema):
        return self.child.data_type(schema)

    def partial_types(self, schema):
        return [self.data_type(schema), INT64]  # value, first-row-index

    def update(self, vals, gid, num_segments, row_mask):
        v = vals[0]
        n = v.data.shape[0]
        big = jnp.array(np.iinfo(np.int64).max, dtype=jnp.int64)
        if isinstance(gid, SortedSegments):
            idx = gid.orig_index.astype(jnp.int64)
            (val,), fi, ok = gid.select_by_rank([v.data], idx, v.validity,
                                                "min")
            return [(val, ok), (jnp.where(ok, fi, big), jnp.ones_like(ok))]
        idx = jnp.arange(n, dtype=jnp.int64)
        first_idx = seg_min(jnp.where(v.validity, idx, big), gid,
                                        num_segments=num_segments)
        ok = first_idx < big
        safe = jnp.where(ok, first_idx, 0)
        val = jnp.take(v.data, safe, mode="clip")
        return [(val, ok), (jnp.where(ok, first_idx, big), jnp.ones_like(ok))]

    def merge(self, partials, gid, num_segments):
        val, pos = partials[0], partials[1]
        big = jnp.array(np.iinfo(np.int64).max, dtype=jnp.int64)
        eff = jnp.where(val.validity, pos.data, big)
        if isinstance(gid, SortedSegments):
            (out,), fp, ok = gid.select_by_rank([val.data], eff,
                                                val.validity, "min")
            return [(out, ok), (jnp.where(ok, fp, big), jnp.ones_like(ok))]
        first_pos = seg_min(eff, gid, num_segments=num_segments)
        ok = first_pos < big
        # gather the value whose pos equals first_pos within the segment
        is_first = jnp.logical_and(eff == jnp.take(first_pos, gid, mode="clip"),
                                   val.validity)
        out = jnp.zeros((num_segments,), dtype=val.data.dtype) \
            .at[jnp.where(is_first, gid, num_segments)] \
            .set(val.data, mode="drop")
        return [(out, ok), (jnp.where(ok, first_pos, big), jnp.ones_like(ok))]

    def finalize(self, partials):
        return partials[0]


class Last(AggregateExpression):
    pandas_agg = "last"

    def data_type(self, schema):
        return self.child.data_type(schema)

    def partial_types(self, schema):
        return [self.data_type(schema), INT64]

    def update(self, vals, gid, num_segments, row_mask):
        v = vals[0]
        n = v.data.shape[0]
        small = jnp.array(-1, dtype=jnp.int64)
        if isinstance(gid, SortedSegments):
            idx = gid.orig_index.astype(jnp.int64)
            (val,), li, ok = gid.select_by_rank([v.data], idx, v.validity,
                                                "max")
            return [(val, ok), (jnp.where(ok, li, small),
                                jnp.ones_like(ok))]
        idx = jnp.arange(n, dtype=jnp.int64)
        last_idx = seg_max(jnp.where(v.validity, idx, small), gid,
                                       num_segments=num_segments)
        ok = last_idx >= 0
        safe = jnp.where(ok, last_idx, 0)
        val = jnp.take(v.data, safe, mode="clip")
        return [(val, ok), (jnp.where(ok, last_idx, small), jnp.ones_like(ok))]

    def merge(self, partials, gid, num_segments):
        val, pos = partials[0], partials[1]
        small = jnp.array(-1, dtype=jnp.int64)
        eff = jnp.where(val.validity, pos.data, small)
        if isinstance(gid, SortedSegments):
            (out,), lp, ok = gid.select_by_rank([val.data], eff,
                                                val.validity, "max")
            return [(out, ok), (jnp.where(ok, lp, small),
                                jnp.ones_like(ok))]
        last_pos = seg_max(eff, gid, num_segments=num_segments)
        ok = last_pos >= 0
        is_last = jnp.logical_and(eff == jnp.take(last_pos, gid, mode="clip"),
                                  val.validity)
        out = jnp.zeros((num_segments,), dtype=val.data.dtype) \
            .at[jnp.where(is_last, gid, num_segments)] \
            .set(val.data, mode="drop")
        return [(out, ok), (jnp.where(ok, last_pos, small), jnp.ones_like(ok))]

    def finalize(self, partials):
        return partials[0]


class _MomentAgg(AggregateExpression):
    """Shared machinery for variance/stddev: partials (count, sum, sum_sq)."""
    ddof = 1

    def data_type(self, schema):
        return FLOAT64

    def partial_types(self, schema):
        return [INT64, FLOAT64, FLOAT64]

    def update(self, vals, gid, num_segments, row_mask):
        v = vals[0]
        d = v.data.astype(jnp.float64)
        s, cnt = _seg_sum(d, v.validity, gid, num_segments)
        s2, _ = _seg_sum(d * d, v.validity, gid, num_segments)
        ones = jnp.ones_like(cnt, dtype=jnp.bool_)
        return [(cnt, ones), (s, ones), (s2, ones)]

    def merge(self, partials, gid, num_segments):
        outs = []
        for p in partials:
            s, _ = _seg_sum(p.data, p.validity, gid, num_segments)
            outs.append((s, jnp.ones_like(s, dtype=jnp.bool_)))
        return outs

    def _moments(self, partials):
        n = partials[0].data.astype(jnp.float64)
        s = partials[1].data
        s2 = partials[2].data
        denom = jnp.where(n > 0, n, 1.0)
        mean = s / denom
        m2 = s2 - n * mean * mean
        return n, m2


class VariancePop(_MomentAgg):
    pandas_agg = "var_pop"
    ddof = 0

    def finalize(self, partials):
        n, m2 = self._moments(partials)
        ok = n > 0
        out = m2 / jnp.where(ok, n, 1.0)
        return DVal(jnp.maximum(out, 0.0), ok, FLOAT64)


class VarianceSamp(_MomentAgg):
    pandas_agg = "var"

    def finalize(self, partials):
        n, m2 = self._moments(partials)
        ok = n > 1
        out = m2 / jnp.where(ok, n - 1.0, 1.0)
        # n <= 1 -> NULL (Spark 3.1+ divide-by-zero semantics,
        # SPARK-33726; the legacy NaN behavior is gone)
        return DVal(jnp.maximum(out, 0.0), ok, FLOAT64)


class StddevPop(VariancePop):
    pandas_agg = "std_pop"

    def finalize(self, partials):
        v = super().finalize(partials)
        return DVal(jnp.sqrt(v.data), v.validity, FLOAT64)


class StddevSamp(VarianceSamp):
    pandas_agg = "std"

    def finalize(self, partials):
        # reuse the sample-variance finalize (incl. its FP-cancellation
        # clamp to >= 0 — sqrt of a tiny negative m2 would be NaN)
        v = VarianceSamp.finalize(self, partials)
        return DVal(jnp.sqrt(v.data), v.validity, FLOAT64)


class _HostOnlyAgg(AggregateExpression):
    """Aggregates without a device update/merge pipeline: the planner
    reverts the whole aggregation to the CPU twin, whose per-group
    evaluation lives in exec/aggregate.CpuAggregateExec (honest whole-exec
    fallback, ref the reference's TypeSig rejections)."""

    def device_unsupported_reason(self, schema: Schema) -> Optional[str]:
        from .base import expression_disabled_reason
        return (expression_disabled_reason(type(self))
                or f"{type(self).__name__} evaluates on host")


class CollectList(_HostOnlyAgg):
    """collect_list(e): non-null values per group in arrival order
    (ref GpuCollectList in aggregateFunctions.scala)."""

    def data_type(self, schema: Schema):
        from ..types import ArrayType
        return ArrayType(self.child.data_type(schema))

    def nullable(self, schema):
        return False


class CollectSet(CollectList):
    """collect_set(e): distinct non-null values (ref GpuCollectSet)."""


class MinBy(_HostOnlyAgg):
    """min_by(value, ordering) (ref GpuMinBy)."""

    _pick_min = True

    def __init__(self, child, ordering, name=None):
        super().__init__(child, name)
        self.ordering = ordering

    def data_type(self, schema: Schema):
        return self.child.data_type(schema)

    def input_exprs(self):
        return [self.child, self.ordering]

    def key(self):
        return (f"{type(self).__name__}({self.child.key()},"
                f"{self.ordering.key()})")


class MaxBy(MinBy):
    _pick_min = False


class Percentile(_HostOnlyAgg):
    """percentile(e, p): exact percentile with linear interpolation
    between closest ranks (Spark's Percentile; ref GpuPercentileDefault)."""

    def __init__(self, child, percentage: float, name=None):
        super().__init__(child, name)
        self.percentage = float(percentage)

    def data_type(self, schema: Schema):
        from ..types import FLOAT64
        return FLOAT64

    def key(self):
        return f"percentile({self.child.key()},{self.percentage})"

class ApproximatePercentile(Percentile):
    """approx_percentile(e, p[, accuracy]): Spark's t-digest sketch is
    an ACCURACY/memory trade; this engine computes the EXACT percentile
    instead (a strictly tighter answer — the accuracy argument is
    accepted and ignored). Ref GpuApproximatePercentile /
    ApproxPercentileFromTDigestExpr."""

    def __init__(self, child, percentage: float, accuracy: int = 10000,
                 name=None):
        super().__init__(child, percentage, name)
        self.accuracy = int(accuracy)

