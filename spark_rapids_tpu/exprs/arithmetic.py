"""Arithmetic expressions with Spark semantics.

Reference analog: sql-plugin arithmetic.scala (GpuAdd, GpuSubtract, ...,
1,282 LoC). Spark (non-ANSI) semantics implemented:
  * division / modulo by zero -> NULL (not inf/exception)
  * `/` always produces double for integral inputs; `div` is integral division
  * `%` takes the sign of the dividend (Java remainder)
Device path is traced jax.numpy (fused by XLA); host path is masked numpy.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..types import (BOOL, DataType, FLOAT32, FLOAT64, INT64, Schema,
                     integral, numeric, TypeSig)
from .base import DVal, EvalContext, Expression, null_and, promote_types

__all__ = ["Add", "Subtract", "Multiply", "Divide", "IntegralDivide",
           "Remainder", "Pmod", "UnaryMinus", "UnaryPositive", "Abs",
           "BitwiseAnd", "BitwiseOr", "BitwiseXor", "BitwiseNot",
           "ShiftLeft", "ShiftRight", "ShiftRightUnsigned",
           "host_binary_numpy", "arrow_to_masked_numpy",
           "masked_numpy_to_arrow"]


def arrow_to_masked_numpy(arr):
    """pyarrow.Array -> (values ndarray, valid bool ndarray)."""
    import pyarrow as pa
    valid = ~np.asarray(arr.is_null())
    if arr.null_count:
        if pa.types.is_boolean(arr.type):
            fill = False
        elif pa.types.is_string(arr.type) or pa.types.is_large_string(
                arr.type):
            fill = ""
        elif pa.types.is_binary(arr.type):
            fill = b""
        else:
            fill = 0
        vals = arr.fill_null(fill).to_numpy(zero_copy_only=False)
    else:
        vals = arr.to_numpy(zero_copy_only=False)
    return vals, valid


def masked_numpy_to_arrow(vals, valid, dtype: DataType):
    import pyarrow as pa
    from ..types import to_arrow
    vals = np.asarray(vals)
    if dtype.np_dtype is not None and vals.dtype != dtype.np_dtype:
        vals = vals.astype(dtype.np_dtype)
    return pa.Array.from_pandas(vals, mask=~np.asarray(valid), type=to_arrow(dtype))


def host_binary_numpy(expr, batch, fn, out_dtype: DataType,
                      cast_to=None, null_on_zero_rhs=False):
    l, lv = arrow_to_masked_numpy(expr.children[0].eval_host(batch))
    r, rv = arrow_to_masked_numpy(expr.children[1].eval_host(batch))
    if cast_to is not None:
        l = l.astype(cast_to)
        r = r.astype(cast_to)
    valid = lv & rv
    if null_on_zero_rhs:
        valid = valid & (r != 0)
        r = np.where(r == 0, np.ones_like(r), r)
    with np.errstate(all="ignore"):
        vals = fn(l, r)
    return masked_numpy_to_arrow(vals, valid, out_dtype)


class BinaryArithmetic(Expression):
    # decimal ARITHMETIC stays capped at precision 18 on device: the
    # int64 lanes would silently wrap beyond that (only SUM has
    # limb-exact wide accumulation, exprs/aggregates.py). Storage /
    # grouping / min-max of wider decimals remain device-backed.
    device_type_sig: TypeSig = TypeSig(numeric.types,
                                       max_decimal_precision=18)
    symbol = "?"

    def __init__(self, left: Expression, right: Expression):
        self.children = [left, right]

    def data_type(self, schema: Schema) -> DataType:
        return promote_types(self.children[0].data_type(schema),
                             self.children[1].data_type(schema))

    def _promoted_device_operands(self, ctx: EvalContext):
        dt = self.data_type(ctx.schema)
        l = self.children[0].eval_device(ctx)
        r = self.children[1].eval_device(ctx)
        np_dt = dt.np_dtype
        ld = l.data.astype(np_dt) if l.data.dtype != np_dt else l.data
        rd = r.data.astype(np_dt) if r.data.dtype != np_dt else r.data
        return ld, rd, null_and(l.validity, r.validity), dt

    def key(self):
        return f"{type(self).__name__}({self.children[0].key()},{self.children[1].key()})"

    @property
    def name_hint(self):
        return (f"({self.children[0].name_hint} {self.symbol} "
                f"{self.children[1].name_hint})")


class Add(BinaryArithmetic):
    symbol = "+"

    def eval_device(self, ctx):
        ld, rd, v, dt = self._promoted_device_operands(ctx)
        return DVal(ld + rd, v, dt)

    def eval_host(self, batch):
        return host_binary_numpy(self, batch, np.add,
                                 self.data_type(batch.schema))


class Subtract(BinaryArithmetic):
    symbol = "-"

    def eval_device(self, ctx):
        ld, rd, v, dt = self._promoted_device_operands(ctx)
        return DVal(ld - rd, v, dt)

    def eval_host(self, batch):
        return host_binary_numpy(self, batch, np.subtract,
                                 self.data_type(batch.schema))


class Multiply(BinaryArithmetic):
    symbol = "*"

    def eval_device(self, ctx):
        ld, rd, v, dt = self._promoted_device_operands(ctx)
        return DVal(ld * rd, v, dt)

    def eval_host(self, batch):
        return host_binary_numpy(self, batch, np.multiply,
                                 self.data_type(batch.schema))


class Divide(BinaryArithmetic):
    """Spark `/`: result is double for non-decimal inputs; 0 divisor -> NULL
    (ref arithmetic.scala GpuDivide)."""
    symbol = "/"

    def data_type(self, schema: Schema) -> DataType:
        base = super().data_type(schema)
        return FLOAT32 if base == FLOAT32 else FLOAT64

    def eval_device(self, ctx):
        dt = self.data_type(ctx.schema)
        l = self.children[0].eval_device(ctx)
        r = self.children[1].eval_device(ctx)
        ld = l.data.astype(dt.np_dtype)
        rd = r.data.astype(dt.np_dtype)
        zero = rd == 0
        v = null_and(l.validity, r.validity, jnp.logical_not(zero))
        safe = jnp.where(zero, jnp.ones_like(rd), rd)
        return DVal(ld / safe, v, dt)

    def eval_host(self, batch):
        dt = self.data_type(batch.schema)
        return host_binary_numpy(self, batch, np.divide, dt,
                                 cast_to=dt.np_dtype, null_on_zero_rhs=True)


class IntegralDivide(BinaryArithmetic):
    """Spark `div`: integral division -> long; 0 divisor -> NULL."""
    symbol = "div"

    def data_type(self, schema: Schema) -> DataType:
        return INT64

    def eval_device(self, ctx):
        l = self.children[0].eval_device(ctx)
        r = self.children[1].eval_device(ctx)
        ld = l.data.astype(jnp.int64)
        rd = r.data.astype(jnp.int64)
        zero = rd == 0
        v = null_and(l.validity, r.validity, jnp.logical_not(zero))
        safe = jnp.where(zero, jnp.ones_like(rd), rd)
        # C-style truncation toward zero (Spark/Java), not Python floor
        q = (jnp.abs(ld) // jnp.abs(safe)) * jnp.sign(ld) * jnp.sign(safe)
        return DVal(q.astype(jnp.int64), v, INT64)

    def eval_host(self, batch):
        def f(l, r):
            return (np.abs(l) // np.abs(r)) * np.sign(l) * np.sign(r)
        return host_binary_numpy(self, batch, f, INT64, cast_to=np.int64,
                                 null_on_zero_rhs=True)


class Remainder(BinaryArithmetic):
    """Spark `%`: sign of the dividend (Java); 0 divisor -> NULL."""
    symbol = "%"

    def eval_device(self, ctx):
        ld, rd, v, dt = self._promoted_device_operands(ctx)
        zero = rd == 0
        v = null_and(v, jnp.logical_not(zero))
        safe = jnp.where(zero, jnp.ones_like(rd), rd)
        return DVal(jnp.fmod(ld, safe), v, dt)

    def eval_host(self, batch):
        return host_binary_numpy(self, batch, np.fmod,
                                 self.data_type(batch.schema),
                                 null_on_zero_rhs=True)


class Pmod(BinaryArithmetic):
    """Positive modulo (ref GpuPmod)."""
    symbol = "pmod"

    def eval_device(self, ctx):
        ld, rd, v, dt = self._promoted_device_operands(ctx)
        zero = rd == 0
        v = null_and(v, jnp.logical_not(zero))
        safe = jnp.where(zero, jnp.ones_like(rd), rd)
        m = jnp.fmod(ld, safe)
        m = jnp.where(m < 0, jnp.fmod(m + safe, safe), m)
        return DVal(m, v, dt)

    def eval_host(self, batch):
        def f(l, r):
            m = np.fmod(l, r)
            return np.where(m < 0, np.fmod(m + r, r), m)
        return host_binary_numpy(self, batch, f, self.data_type(batch.schema),
                                 null_on_zero_rhs=True)


class UnaryMinus(Expression):
    device_type_sig = TypeSig(numeric.types, max_decimal_precision=18)

    def __init__(self, child: Expression):
        self.children = [child]

    def data_type(self, schema):
        return self.children[0].data_type(schema)

    def eval_device(self, ctx):
        c = self.children[0].eval_device(ctx)
        return DVal(-c.data, c.validity, c.dtype)

    def eval_host(self, batch):
        v, ok = arrow_to_masked_numpy(self.children[0].eval_host(batch))
        return masked_numpy_to_arrow(-v, ok, self.data_type(batch.schema))

    def key(self):
        return f"neg({self.children[0].key()})"


class Abs(Expression):
    device_type_sig = TypeSig(numeric.types, max_decimal_precision=18)

    def __init__(self, child: Expression):
        self.children = [child]

    def data_type(self, schema):
        return self.children[0].data_type(schema)

    def eval_device(self, ctx):
        c = self.children[0].eval_device(ctx)
        return DVal(jnp.abs(c.data), c.validity, c.dtype)

    def eval_host(self, batch):
        v, ok = arrow_to_masked_numpy(self.children[0].eval_host(batch))
        return masked_numpy_to_arrow(np.abs(v), ok, self.data_type(batch.schema))

    def key(self):
        return f"abs({self.children[0].key()})"


class UnaryPositive(Expression):
    """`+x`: identity on numerics (ref GpuOverrides UnaryPositive rule)."""

    device_type_sig = TypeSig(numeric.types)

    def __init__(self, child: Expression):
        self.children = [child]

    def data_type(self, schema):
        return self.children[0].data_type(schema)

    def eval_device(self, ctx):
        return self.children[0].eval_device(ctx)

    def eval_host(self, batch):
        return self.children[0].eval_host(batch)

    def key(self):
        return f"pos({self.children[0].key()})"


# ---------------------------------------------------------------------------
# bitwise (ref bitwise.scala — cudf bitwise kernels; here plain VPU int ops)
# ---------------------------------------------------------------------------

class _BitwiseBinary(BinaryArithmetic):
    device_type_sig = integral
    jnp_fn = None
    np_fn = None

    def eval_device(self, ctx):
        ld, rd, v, dt = self._promoted_device_operands(ctx)
        return DVal(type(self).jnp_fn(ld, rd), v, dt)

    def eval_host(self, batch):
        return host_binary_numpy(self, batch, type(self).np_fn,
                                 self.data_type(batch.schema))


class BitwiseAnd(_BitwiseBinary):
    symbol = "&"
    jnp_fn = staticmethod(jnp.bitwise_and)
    np_fn = staticmethod(np.bitwise_and)


class BitwiseOr(_BitwiseBinary):
    symbol = "|"
    jnp_fn = staticmethod(jnp.bitwise_or)
    np_fn = staticmethod(np.bitwise_or)


class BitwiseXor(_BitwiseBinary):
    symbol = "^"
    jnp_fn = staticmethod(jnp.bitwise_xor)
    np_fn = staticmethod(np.bitwise_xor)


class BitwiseNot(Expression):
    device_type_sig = integral

    def __init__(self, child: Expression):
        self.children = [child]

    def data_type(self, schema):
        return self.children[0].data_type(schema)

    def eval_device(self, ctx):
        c = self.children[0].eval_device(ctx)
        return DVal(jnp.bitwise_not(c.data), c.validity, c.dtype)

    def eval_host(self, batch):
        v, ok = arrow_to_masked_numpy(self.children[0].eval_host(batch))
        return masked_numpy_to_arrow(np.bitwise_not(v), ok,
                                     self.data_type(batch.schema))

    def key(self):
        return f"~({self.children[0].key()})"


class _Shift(Expression):
    """shiftleft/shiftright/shiftrightunsigned(x, n): Java semantics —
    byte/short values promote to INT (like Java's << on sub-int types)
    and the shift amount uses only the low 5 (int) or 6 (long) bits
    (ref GpuShiftLeft/Right in arithmetic.scala)."""

    device_type_sig = integral

    def __init__(self, value: Expression, amount: Expression):
        self.children = [value, amount]

    def data_type(self, schema):
        from ..types import INT32
        dt = self.children[0].data_type(schema)
        return dt if dt.np_dtype.itemsize >= 4 else INT32

    def _mask(self, dt) -> int:
        return 63 if dt.np_dtype.itemsize == 8 else 31

    def _shift_np(self, v, n, dt):
        raise NotImplementedError

    def eval_device(self, ctx):
        import jax.numpy as jnp
        c = self.children[0].eval_device(ctx)
        a = self.children[1].eval_device(ctx)
        dt = self.data_type(ctx.schema)
        n = a.data.astype(jnp.int32) & self._mask(dt)
        out = self._shift_jnp(c.data.astype(dt.np_dtype), n, dt)
        from .base import null_and
        return DVal(out, null_and(c.validity, a.validity), dt)

    def eval_host(self, batch):
        v, vok = arrow_to_masked_numpy(self.children[0].eval_host(batch))
        n, nok = arrow_to_masked_numpy(self.children[1].eval_host(batch))
        dt = self.data_type(batch.schema)
        v = v.astype(dt.np_dtype, copy=False)
        n = n.astype(np.int64) & self._mask(dt)
        out = self._shift_np(v, n, dt)
        return masked_numpy_to_arrow(out, vok & nok, dt)

    def key(self):
        return (f"{type(self).__name__}({self.children[0].key()},"
                f"{self.children[1].key()})")


class ShiftLeft(_Shift):
    def _shift_jnp(self, v, n, dt):
        return jnp.left_shift(v, n.astype(v.dtype))

    def _shift_np(self, v, n, dt):
        return np.left_shift(v, n.astype(v.dtype))


class ShiftRight(_Shift):
    """Arithmetic (sign-propagating) right shift, Java >>."""

    def _shift_jnp(self, v, n, dt):
        return jnp.right_shift(v, n.astype(v.dtype))

    def _shift_np(self, v, n, dt):
        return np.right_shift(v, n.astype(v.dtype))


class ShiftRightUnsigned(_Shift):
    """Logical right shift, Java >>>: shift the UNSIGNED bit pattern."""

    def _shift_jnp(self, v, n, dt):
        u = jnp.asarray(v).view(
            jnp.uint64 if dt.np_dtype.itemsize == 8 else jnp.uint32)
        return jnp.right_shift(u, n.astype(u.dtype)).view(v.dtype)

    def _shift_np(self, v, n, dt):
        udt = np.uint64 if dt.np_dtype.itemsize == 8 else np.uint32
        u = v.astype(dt.np_dtype, copy=False).view(udt)
        return np.right_shift(u, n.astype(udt)).view(dt.np_dtype)
