"""Expression core: trees that compile into fused XLA kernels.

Reference analog: GpuExpression (GpuExpressions.scala) + the ~224 expression
rules in GpuOverrides.scala:3935. Key TPU-first divergence: the reference
interprets expression trees node-by-node, each node a cudf JNI kernel launch;
here an operator's whole expression list is traced into ONE jitted XLA
computation per shape bucket, so XLA fuses the elementwise work (HBM-bandwidth
friendly) and there is exactly one dispatch per batch.

Null semantics follow Spark: values travel as (data, validity) pairs; most
expressions are null-propagating (validity = AND of child validities);
AND/OR use Kleene logic (see logical.py).
"""
from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..types import (BOOL, DATE, DataType, DecimalType, FLOAT32, FLOAT64,
                     INT8, INT16, INT32, INT64, NULLTYPE, STRING, Schema,
                     TIMESTAMP, TypeSig, tpuNative, from_numpy_dtype)

__all__ = ["DVal", "EvalContext", "Expression", "ColumnRef", "BoundReference",
           "Literal", "Unsupported", "promote_types", "Alias"]


class Unsupported(Exception):
    """Raised when an expression cannot run on the device; the tagging pass
    converts this into a fallback reason (ref RapidsMeta willNotWorkOnGpu)."""


#: expression class names disabled by `spark.rapids.tpu.sql.expression.<Name>`
#: confs (ref GpuOverrides.scala:3935 — every ExprRule gets an enable conf;
#: disabling it forces the expression off the accelerator). Thread-local:
#: plan/op_confs.install_from_conf installs the set from the query's conf at
#: BOTH plan time (tagging) and execution time (the dataframe sink
#: re-installs before running), so interleaved sessions on other threads
#: cannot contaminate this query's fallback decisions. Consulted by the SAME
#: fully_device_supported checks the execs use at run time, so a disabled
#: expression falls back to host evaluation end to end.
import threading as _thr

_DISABLED = _thr.local()


def set_disabled_expressions(names) -> None:
    _DISABLED.sets = frozenset(names)


def expression_disabled_reason(cls) -> Optional[str]:
    name = cls.__name__
    if name in getattr(_DISABLED, "sets", ()):
        return (f"{name} disabled by "
                f"spark.rapids.tpu.sql.expression.{name}=false")
    return None


class ListVal(NamedTuple):
    """Traced device LIST value in the rectangular layout
    (columnar/nested.py): rides in DVal.data for ArrayType-typed values.
    values[P, W] element data, elem_valid[P, W], lengths[P]."""
    values: jnp.ndarray
    elem_valid: jnp.ndarray
    lengths: jnp.ndarray


class StrVal(NamedTuple):
    """Traced device STRING value as a dense byte rectangle
    (columnar/strrect.py): rides in DVal.data for STRING-typed values
    when the column is rectangle-backed (high cardinality — dictionary
    codes stay the low-cardinality representation).
    bytes_[P, W] uint8 (zero-padded past each row's length),
    lengths[P] int32 (byte == char: the device path is ASCII-gated)."""
    bytes_: jnp.ndarray
    lengths: jnp.ndarray


class DVal(NamedTuple):
    """A traced device value: padded data + validity mask (+static dtype).
    For ArrayType values, ``data`` is a ListVal rectangle and ``validity``
    remains the per-row mask."""
    data: jnp.ndarray
    validity: jnp.ndarray
    dtype: DataType


class EvalContext:
    """Trace-time context handed to Expression.eval_device.

    columns: per-input-ordinal DVal (traced jnp arrays)
    num_rows: traced int32 scalar — the true (unpadded) row count
    padded_len: static int — the shape bucket
    scalars/literal_slots: traced literal values (see parameterized_keys) —
    numeric literals ride into the kernel as scalar operands so queries
    differing only in constants share ONE compiled executable
    """

    def __init__(self, schema: Schema, columns: Sequence[DVal], num_rows,
                 padded_len: int, scalars=None, literal_slots=None):
        self.schema = schema
        self.columns = list(columns)
        self.num_rows = num_rows
        self.padded_len = padded_len
        self.scalars = scalars
        self.literal_slots = literal_slots

    def row_mask(self):
        """bool[P]: True for real rows, False for padding."""
        return jnp.arange(self.padded_len, dtype=jnp.int32) < self.num_rows


import contextlib as _contextlib
import threading as _threading

_PARAM_KEYS = _threading.local()


def _param_keys_on() -> bool:
    return getattr(_PARAM_KEYS, "on", False)


@_contextlib.contextmanager
def parameterized_keys():
    """Within this context, Literal.key() renders parameterizable values
    as a type-only placeholder. Kernel caches compute their keys under it,
    so queries that differ only in numeric constants (TPC parameter
    sweeps) resolve to the SAME compiled kernel; the actual values ride in
    as traced scalar operands collected by collect_param_literals."""
    prev = getattr(_PARAM_KEYS, "on", False)
    prev_map = getattr(_PARAM_KEYS, "slots", None)
    _PARAM_KEYS.on = True
    _PARAM_KEYS.slots = {}
    try:
        yield
    finally:
        _PARAM_KEYS.on = prev
        _PARAM_KEYS.slots = prev_map


def collect_param_literals(exprs) -> list:
    """Deterministic DFS over expression trees -> parameterizable Literal
    nodes (deduped by identity), the slot order shared by kernel build
    and call sites."""
    out, seen = [], set()

    def walk(e):
        if e is None:
            return
        if isinstance(e, Literal):
            if e.parameterizable() and id(e) not in seen:
                seen.add(id(e))
                out.append(e)
            return
        for c in getattr(e, "children", []):
            walk(c)

    for e in exprs:
        walk(e)
    return out


def literal_slot_map(exprs) -> dict:
    """id(Literal) -> slot index in the shared DFS order; kernel builders
    derive slots and call sites derive values from the SAME traversal."""
    return {id(l): i for i, l in enumerate(collect_param_literals(exprs))}


def literal_scalars(lits) -> tuple:
    """Call-time traced operand tuple for the collected literals."""
    return tuple(jnp.asarray(np.asarray(l.value, dtype=l.dtype.np_dtype))
                 for l in lits)


class Expression:
    children: List["Expression"] = []

    # --- analysis ---------------------------------------------------------
    def data_type(self, schema: Schema) -> DataType:
        raise NotImplementedError

    def nullable(self, schema: Schema) -> bool:
        return True

    @property
    def name_hint(self) -> str:
        return str(self)

    def references(self) -> List[str]:
        out: List[str] = []
        for c in self.children:
            out.extend(c.references())
        return out

    # --- planner tagging (ref BaseExprMeta.tagExprForGpu) ----------------
    #: types this expression supports on device; planner checks child+output
    device_type_sig: TypeSig = tpuNative

    def device_unsupported_reason(self, schema: Schema) -> Optional[str]:
        """None if the expression (this node only) can run on device."""
        dt = self.data_type(schema)
        r = self.device_type_sig.reason_not_supported(dt)
        if r is not None:
            return f"{type(self).__name__}: output {r}"
        for c in self.children:
            cdt = c.data_type(schema)
            if cdt == NULLTYPE:
                # an untyped NULL literal adapts to the consumer's output
                # type (all-invalid lanes) — e.g. CASE WHEN ... ELSE NULL
                continue
            cr = self.device_type_sig.reason_not_supported(cdt)
            if cr is not None:
                return f"{type(self).__name__}: input {cr}"
        return None

    def fully_device_supported(self, schema: Schema) -> Optional[str]:
        r = expression_disabled_reason(type(self))
        if r:
            return r
        r = self.device_unsupported_reason(schema)
        if r:
            return r
        for c in self.children:
            r = c.fully_device_supported(schema)
            if r:
                return r
        return None

    # --- evaluation -------------------------------------------------------
    def eval_device(self, ctx: EvalContext) -> DVal:
        raise Unsupported(f"{type(self).__name__} has no device implementation")

    def eval_host(self, batch) -> "object":
        """Vectorized host (Arrow) evaluation — the CPU-fallback interpreter.
        Returns a pyarrow.Array of length batch.num_rows."""
        raise Unsupported(f"{type(self).__name__} has no host implementation")

    # --- identity (kernel-cache key) -------------------------------------
    def key(self) -> str:
        kids = ",".join(c.key() for c in self.children)
        return f"{type(self).__name__}({kids})"

    def __repr__(self):
        return self.key()


class ColumnRef(Expression):
    """Named attribute reference; resolved to an ordinal at bind time."""

    def __init__(self, name: str):
        self.name = name
        self.children = []

    def data_type(self, schema: Schema) -> DataType:
        return schema[self.name].dtype

    def references(self):
        return [self.name]

    def device_unsupported_reason(self, schema: Schema) -> Optional[str]:
        dt = schema[self.name].dtype
        if dt.device_backed:
            return None
        from ..columnar.nested import device_list_ok
        if device_list_ok(dt):
            # list-of-primitive rides the dense rectangle (nested.py);
            # width-capped batches demote to host per batch at run time
            return None
        return f"column {self.name}: {dt.name} is host-only"

    def eval_device(self, ctx: EvalContext) -> DVal:
        return ctx.columns[ctx.schema.index_of(self.name)]

    def eval_host(self, batch):
        return batch.column_by_name(self.name).to_arrow(batch.num_rows)

    def key(self):
        return f"col({self.name})"

    @property
    def name_hint(self):
        return self.name


class BoundReference(Expression):
    """Ordinal reference (post-binding), ref BoundReference in Catalyst."""

    def __init__(self, ordinal: int, dtype: DataType):
        self.ordinal = ordinal
        self._dtype = dtype
        self.children = []

    def data_type(self, schema: Schema) -> DataType:
        return self._dtype

    def eval_device(self, ctx: EvalContext) -> DVal:
        return ctx.columns[self.ordinal]

    def eval_host(self, batch):
        return batch.column(self.ordinal).to_arrow(batch.num_rows)

    def key(self):
        return f"bound({self.ordinal}:{self._dtype.name})"


def _literal_type(value) -> DataType:
    import datetime
    if value is None:
        return NULLTYPE
    if isinstance(value, bool):
        return BOOL
    if isinstance(value, int):
        return INT32 if -(2**31) <= value < 2**31 else INT64
    if isinstance(value, float):
        return FLOAT64
    if isinstance(value, str):
        return STRING
    if isinstance(value, np.datetime64):
        unit = np.datetime_data(value.dtype)[0]
        return DATE if unit in ("D", "W", "M", "Y") else TIMESTAMP
    if isinstance(value, datetime.datetime):
        return TIMESTAMP
    if isinstance(value, datetime.date):
        return DATE
    if isinstance(value, np.generic):
        return from_numpy_dtype(value.dtype)
    raise TypeError(f"cannot infer literal type for {value!r}")


def _canonical_literal(value, dtype: DataType):
    """Store date/timestamp literals as their device representation
    (DATE: int32 days since epoch, TIMESTAMP: int64 microseconds) so both
    the device kernel (jnp.full) and the host path (pa.array with the
    arrow logical type) consume the same value."""
    if value is None:
        return None
    if dtype == DATE and not isinstance(value, (int, np.integer)):
        return int(np.datetime64(value, "D").astype(np.int64))
    if dtype == TIMESTAMP and not isinstance(value, (int, np.integer)):
        return int(np.datetime64(value, "us").astype(np.int64))
    return value


class Literal(Expression):
    def __init__(self, value, dtype: Optional[DataType] = None):
        self.dtype = dtype if dtype is not None else _literal_type(value)
        self.value = _canonical_literal(value, self.dtype)
        self.children = []

    def data_type(self, schema: Schema) -> DataType:
        return self.dtype

    def nullable(self, schema: Schema) -> bool:
        return self.value is None

    def device_unsupported_reason(self, schema: Schema) -> Optional[str]:
        if self.value is None:
            return None  # typed null literal is fine on device
        if not self.dtype.device_backed:
            return f"literal of host-only type {self.dtype.name}"
        return None

    def eval_device(self, ctx: EvalContext) -> DVal:
        p = ctx.padded_len
        if self.value is None:
            np_dt = self.dtype.np_dtype or np.dtype(np.int32)
            return DVal(jnp.zeros(p, dtype=np_dt),
                        jnp.zeros(p, dtype=jnp.bool_), self.dtype)
        slots = ctx.literal_slots
        if slots is not None and id(self) in slots \
                and ctx.scalars is not None:
            v = ctx.scalars[slots[id(self)]]
            return DVal(jnp.broadcast_to(v, (p,)),
                        jnp.ones(p, dtype=jnp.bool_), self.dtype)
        data = jnp.full((p,), self.value, dtype=self.dtype.np_dtype)
        return DVal(data, jnp.ones(p, dtype=jnp.bool_), self.dtype)

    def eval_host(self, batch):
        import pyarrow as pa
        from ..types import to_arrow
        at = to_arrow(self.dtype) if self.dtype != NULLTYPE else pa.null()
        if self.value is None:
            return pa.nulls(batch.num_rows, type=at)
        # C-level broadcast: a python-list literal column costs ~30 ms per
        # 1M rows and was the host engine's single biggest line
        return pa.repeat(pa.scalar(self.value, type=at), batch.num_rows)

    def key(self):
        if _param_keys_on() and self.parameterizable():
            # slot index in the key: two queries whose literal-object
            # SHARING differs must not collide on one compiled kernel
            slots = _PARAM_KEYS.slots
            slot = slots.setdefault(id(self), len(slots))
            return f"lit(?{slot}:{self.dtype.name})"
        return f"lit({self.value!r}:{self.dtype.name})"

    def parameterizable(self) -> bool:
        """True when the value can ride into a kernel as a traced scalar
        operand (numeric/bool/date/timestamp; not strings/decimals/NULL)."""
        from ..types import DecimalType, STRING
        return (self.value is not None
                and self.dtype.np_dtype is not None
                and self.dtype != STRING
                and not isinstance(self.dtype, DecimalType))

    @property
    def name_hint(self):
        return repr(self.value)


class Alias(Expression):
    def __init__(self, child: Expression, name: str):
        self.children = [child]
        self.name = name

    def data_type(self, schema: Schema) -> DataType:
        return self.children[0].data_type(schema)

    def device_unsupported_reason(self, schema):
        return None

    def eval_device(self, ctx: EvalContext) -> DVal:
        return self.children[0].eval_device(ctx)

    def eval_host(self, batch):
        return self.children[0].eval_host(batch)

    def key(self):
        return self.children[0].key()

    @property
    def name_hint(self):
        return self.name


# ---------------------------------------------------------------------------
# numeric type promotion (simplified Catalyst TypeCoercion)
# ---------------------------------------------------------------------------

_NUMERIC_ORDER = [INT8, INT16, INT32, INT64, FLOAT32, FLOAT64]


def promote_types(l: DataType, r: DataType) -> DataType:
    if l == r:
        return l
    if isinstance(l, DecimalType) or isinstance(r, DecimalType):
        # simplified: decimal op decimal -> wider; decimal op int -> decimal
        if isinstance(l, DecimalType) and isinstance(r, DecimalType):
            return DecimalType(max(l.precision, r.precision), max(l.scale, r.scale))
        return l if isinstance(l, DecimalType) else r
    try:
        li, ri = _NUMERIC_ORDER.index(l), _NUMERIC_ORDER.index(r)
    except ValueError:
        raise TypeError(f"cannot promote {l} and {r}")
    return _NUMERIC_ORDER[max(li, ri)]


def null_and(*validities):
    out = validities[0]
    for v in validities[1:]:
        out = jnp.logical_and(out, v)
    return out
