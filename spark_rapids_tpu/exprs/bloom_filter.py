"""Bloom-filter runtime join filters (ref `BloomFilter` JNI in
spark-rapids-jni — used by Spark's InjectRuntimeFilter rewrite:
BloomFilterAggregate builds a filter from the build side's join keys and
BloomFilterMightContain pre-filters the stream side before the join).

TPU-native design: the filter is an UNPACKED uint8 bit array in HBM (one
lane per bit — scatter/gather friendly; at the default 3% FPP that is
~7.3 bits/key, i.e. ~7 MB per million build keys, negligible next to the
build table). Build = k murmur3 probes per key (independent seeds, same
FPP maths as Spark's two-hash derivation) scattered with ``.at[].set(1)``
— idempotent, so duplicate positions are a correct OR. Probe = k gathers +
AND. One fused XLA op each way, no host round trip."""
from __future__ import annotations

import functools
import math
from typing import List

import jax
import jax.numpy as jnp

from .base import DVal
from .hash_fns import murmur3_fold_device

__all__ = ["BloomFilter", "build_bloom", "optimal_bits", "optimal_hashes"]


def optimal_bits(n_items: int, fpp: float = 0.03) -> int:
    """m = -n ln(p) / (ln 2)^2 (standard bloom sizing)."""
    n_items = max(n_items, 1)
    m = int(-n_items * math.log(fpp) / (math.log(2) ** 2))
    return max(m, 64)


def optimal_hashes(n_items: int, m_bits: int) -> int:
    k = int(round(m_bits / max(n_items, 1) * math.log(2)))
    return min(max(k, 1), 8)


_KERNEL_CACHE = {}


def _get_kernels(dtypes):
    """(build, probe) kernels for a key-dtype tuple; DVals are rebuilt
    inside the trace (DVal itself is not a pytree)."""
    key = tuple(dt.name for dt in dtypes)
    got = _KERNEL_CACHE.get(key)
    if got is not None:
        return got

    def mk_vals(arrays):
        return [DVal(d, v, dt) for (d, v), dt in zip(arrays, dtypes)]

    @functools.partial(jax.jit, static_argnums=(2, 3))
    def build(arrays, valid, m_bits, k):
        vals = mk_vals(arrays)
        bits = jnp.zeros(m_bits, dtype=jnp.uint8)
        for seed in range(k):
            h = murmur3_fold_device(vals, seed).astype(jnp.uint32)
            pos = (h % jnp.uint32(m_bits)).astype(jnp.int32)
            pos = jnp.where(valid, pos, m_bits)   # invalid rows drop out
            bits = bits.at[pos].set(1, mode="drop")
        return bits

    @functools.partial(jax.jit, static_argnums=(3, 4))
    def probe(arrays, valid, bits, m_bits, k):
        vals = mk_vals(arrays)
        hit = valid
        for seed in range(k):
            h = murmur3_fold_device(vals, seed).astype(jnp.uint32)
            pos = (h % jnp.uint32(m_bits)).astype(jnp.int32)
            hit = jnp.logical_and(hit,
                                  jnp.take(bits, pos, mode="clip") == 1)
        return hit

    _KERNEL_CACHE[key] = (build, probe)
    return build, probe


def _and_validity(vals: List[DVal]):
    valid = vals[0].validity
    for v in vals[1:]:
        valid = jnp.logical_and(valid, v.validity)
    return valid


class BloomFilter:
    """Device-resident filter state (bit array + parameters)."""

    def __init__(self, bits, m_bits: int, k: int, dtypes):
        self.bits = bits
        self.m_bits = m_bits
        self.k = k
        self.dtypes = tuple(dtypes)

    def might_contain_mask(self, vals: List[DVal]):
        """bool mask over (possibly padded) rows; null keys -> False (null
        never matches an equi-join key, so filtering them early is safe for
        the inner/semi paths that use runtime filters)."""
        _, probe = _get_kernels(self.dtypes)
        arrays = [(v.data, v.validity) for v in vals]
        return probe(arrays, _and_validity(vals), self.bits, self.m_bits,
                     self.k)


def build_bloom(vals: List[DVal], n_items: int,
                fpp: float = 0.03) -> BloomFilter:
    m_bits = optimal_bits(n_items, fpp)
    k = optimal_hashes(n_items, m_bits)
    dtypes = [v.dtype for v in vals]
    build, _ = _get_kernels(dtypes)
    arrays = [(v.data, v.validity) for v in vals]
    bits = build(arrays, _and_validity(vals), m_bits, k)
    return BloomFilter(bits, m_bits, k, dtypes)


