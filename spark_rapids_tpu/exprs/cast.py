"""Cast expression (ref GpuCast.scala, 1,795 LoC of compat-matrix dispatch).

Implemented semantics (non-ANSI Spark):
  * numeric -> numeric: Java narrowing; float->int truncates toward zero,
    NaN -> 0, out-of-range clamps to the target min/max (Java (int)/(long)).
  * numeric <-> boolean: 0=false else true; bool -> 0/1.
  * date -> timestamp (midnight UTC) and timestamp -> date (floor).
  * string casts run on the host path (Arrow), tagged host-only.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..types import (BOOL, DATE, DataType, DecimalType, Schema, STRING,
                     TIMESTAMP, all_types)
from .base import DVal, Expression
from .arithmetic import arrow_to_masked_numpy, masked_numpy_to_arrow

__all__ = ["Cast"]

_MICROS_PER_DAY = 86_400_000_000


def _int_bounds(np_dt):
    info = np.iinfo(np_dt)
    return info.min, info.max


def _float_to_int_java(d, np_dt, xp):
    """Java (int)/(long) cast semantics: NaN -> 0, truncate toward zero,
    out-of-range saturates to min/max (ref GpuCast float->int handling).
    `xp` is numpy or jax.numpy so device and host paths share one definition."""
    lo, hi = _int_bounds(np_dt)
    bits = np.iinfo(np_dt).bits
    t_hi = 2.0 ** (bits - 1)               # first value that overflows
    max_safe = np.nextafter(t_hi, 0.0)     # largest representable below 2^(b-1)
    clean = xp.where(xp.isnan(d), xp.zeros_like(d), d)
    safe = xp.clip(clean, float(lo), max_safe)
    out = xp.trunc(safe).astype(np_dt)
    out = xp.where(clean >= t_hi, xp.asarray(hi, dtype=np_dt), out)
    return out.astype(np_dt)


class Cast(Expression):
    device_type_sig = all_types  # per-pair support decided in reason check

    def __init__(self, child: Expression, dtype: DataType):
        self.children = [child]
        self.dtype = dtype

    def data_type(self, schema: Schema) -> DataType:
        return self.dtype

    def device_unsupported_reason(self, schema):
        src = self.children[0].data_type(schema)
        if not src.device_backed or not self.dtype.device_backed:
            return (f"cast {src.name} -> {self.dtype.name} runs on host "
                    f"(string/nested path)")
        if isinstance(src, DecimalType) or isinstance(self.dtype, DecimalType):
            return "decimal cast not yet on device"
        return None

    def eval_device(self, ctx):
        src = self.children[0].data_type(ctx.schema)
        c = self.children[0].eval_device(ctx)
        dst = self.dtype
        d = c.data
        if src == dst:
            return c
        if dst == BOOL:
            out = d != 0
        elif src == BOOL:
            out = d.astype(dst.np_dtype)
        elif src == DATE and dst == TIMESTAMP:
            out = d.astype(jnp.int64) * _MICROS_PER_DAY
        elif src == TIMESTAMP and dst == DATE:
            out = jnp.floor_divide(d, _MICROS_PER_DAY).astype(jnp.int32)
        elif (jnp.issubdtype(d.dtype, jnp.floating)
              and np.issubdtype(dst.np_dtype, np.integer)):
            out = _float_to_int_java(d, dst.np_dtype, jnp)
        else:
            out = d.astype(dst.np_dtype)
        return DVal(out, c.validity, dst)

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        from ..types import to_arrow
        src = self.children[0].data_type(batch.schema)
        arr = self.children[0].eval_host(batch)
        dst = self.dtype
        if src == dst:
            return arr
        if src.device_backed and dst.device_backed:
            # mirror the device semantics exactly with numpy
            v, ok = arrow_to_masked_numpy(arr)
            if dst == BOOL:
                out = v != 0
            elif src == BOOL:
                out = v.astype(dst.np_dtype)
            elif src == DATE and dst == TIMESTAMP:
                out = v.astype("datetime64[D]").astype("datetime64[us]") \
                    if v.dtype.kind == "M" else v.astype(np.int64) * _MICROS_PER_DAY
            elif src == TIMESTAMP and dst == DATE:
                iv = v.astype(np.int64) if v.dtype.kind != "M" else \
                    v.astype("datetime64[us]").astype(np.int64)
                out = np.floor_divide(iv, _MICROS_PER_DAY).astype(np.int32)
            elif (np.issubdtype(v.dtype, np.floating)
                  and np.issubdtype(dst.np_dtype, np.integer)):
                out = _float_to_int_java(v, dst.np_dtype, np)
            else:
                out = v.astype(dst.np_dtype)
            return masked_numpy_to_arrow(out, ok, dst)
        # string/nested paths via Arrow cast (best-effort Spark compat)
        if dst == STRING:
            if pa.types.is_floating(arr.type):
                # Spark formats doubles with trailing .0; arrow matches closely
                return pc.cast(arr, pa.string())
            return pc.cast(arr, pa.string())
        try:
            return pc.cast(arr, to_arrow(dst), safe=False)
        except pa.ArrowInvalid:
            # Spark non-ANSI: unparseable -> null
            py = arr.to_pylist()
            out = []
            for x in py:
                try:
                    out.append(None if x is None else
                               _py_cast(x, dst))
                except (ValueError, TypeError):
                    out.append(None)
            return pa.array(out, type=to_arrow(dst))

    def key(self):
        return f"cast({self.children[0].key()} as {self.dtype.name})"

    @property
    def name_hint(self):
        return f"CAST({self.children[0].name_hint} AS {self.dtype.name})"


def _py_cast(x, dst: DataType):
    if dst.np_dtype is not None and np.issubdtype(dst.np_dtype, np.integer):
        return int(float(x))
    if dst.np_dtype is not None and np.issubdtype(dst.np_dtype, np.floating):
        return float(x)
    if dst == BOOL:
        s = str(x).strip().lower()
        if s in ("t", "true", "y", "yes", "1"):
            return True
        if s in ("f", "false", "n", "no", "0"):
            return False
        raise ValueError(s)
    return str(x)
