"""Collection (array/map) and complex-type expressions.

Reference analog: collectionOperations.scala (1,802 LoC), complexTypeCreator /
complexTypeExtractors, registered in GpuOverrides.scala:3935. There these are
cudf list/struct kernels; nested types on TPU have no dense HBM layout in
round 1, so every expression here is a vectorized host (Arrow) kernel,
honestly tagged host-only so the planner records the fallback exactly like the
reference's TypeSig machinery records per-type NOT_ON_GPU reasons.

Null semantics follow Spark 3.4 non-ANSI behavior:
  * ``size``           legacy mode (default): size(NULL) = -1
  * ``array_contains`` three-valued (null element => NULL when not found)
  * ``element_at``     1-based, negative from end, out-of-bounds => NULL
  * ``sort_array``     nulls first ascending, nulls last descending
  * set ops            null-safe equality (NULL == NULL within the set)
"""
from __future__ import annotations

from typing import List, Optional, Sequence

from ..types import (ArrayType, BOOL, DataType, INT32, INT64, MapType,
                     NULLTYPE, STRING, Schema, StructField, StructType,
                     to_arrow)
from .base import Expression, Literal, Unsupported, promote_types

__all__ = [
    "Size", "ArrayContains", "ArrayPosition", "ElementAt", "GetArrayItem",
    "GetMapValue", "GetStructField", "SortArray", "ArrayMin", "ArrayMax",
    "ArrayJoin", "Slice", "ArrayRepeat", "ArraysZip", "Concat", "Flatten",
    "Sequence", "ArrayDistinct", "ArrayUnion", "ArrayIntersect",
    "ArrayExcept", "ArrayRemove", "ArraysOverlap", "ArrayReverse",
    "MapKeys", "MapValues", "MapEntries", "MapConcat", "MapFromArrays",
    "StringToMap", "CreateArray", "CreateMap", "CreateNamedStruct",
]


class _HostCollectionExpr(Expression):
    """Base for nested-type expressions. Host (Arrow) evaluation is the
    floor; subclasses with a dense rectangular device path (lists of
    primitives in the [P, W] layout, columnar/nested.py) override
    ``_device_list_reason`` to return None and implement ``eval_device``
    over ListVal rectangles — the TPU-first replacement for cudf's list
    kernels (ref collectionOperations.scala)."""

    def _device_list_reason(self, schema: Schema) -> Optional[str]:
        return "nested-type expression runs on host"

    def device_unsupported_reason(self, schema: Schema) -> Optional[str]:
        from .base import expression_disabled_reason
        r = expression_disabled_reason(type(self))
        if r is not None:
            return r
        r = self._device_list_reason(schema)
        return None if r is None else f"{type(self).__name__}: {r}"


def _dense_list_reason(e, schema) -> Optional[str]:
    """Shared precondition for the device path: first child is a list of
    device-backed primitives (the rectangle layout)."""
    from ..columnar.nested import device_list_ok
    dt = e.children[0].data_type(schema)
    if not device_list_ok(dt):
        return f"{dt} has no dense device layout"
    return None


def _in_list_mask(lv):
    """bool[P, W]: positions before each row's length."""
    import jax.numpy as jnp
    w = lv.values.shape[1]
    return jnp.arange(w, dtype=jnp.int32)[None, :] < lv.lengths[:, None]


def _list_arg(ctx, e):
    from .base import ListVal
    v = e.eval_device(ctx)
    assert isinstance(v.data, ListVal), "planner must route host lists away"
    return v


def _needle_reason(e, schema) -> Optional[str]:
    """Shared by ArrayContains/ArrayPosition: dense list + primitive needle."""
    r = _dense_list_reason(e, schema)
    if r is not None:
        return r
    vdt = e.children[1].data_type(schema)
    if vdt.np_dtype is None:
        return f"needle type {vdt} is host-only"
    return None


def _needle_eq(ctx, array_expr, value_expr):
    """(array DVal, value DVal, eq[P, W]): element equality restricted to
    valid in-list positions — the shared core of contains/position."""
    import jax.numpy as jnp
    arr = _list_arg(ctx, array_expr)
    val = value_expr.eval_device(ctx)
    lv = arr.data
    in_list = _in_list_mask(lv)
    needle = jnp.broadcast_to(jnp.asarray(val.data),
                              (lv.values.shape[0],))
    eq = jnp.logical_and(lv.values == needle[:, None],
                         jnp.logical_and(lv.elem_valid, in_list))
    return arr, val, eq


def _gather_element(lv, idx):
    """Gather element at 0-based ``idx`` (int32[P]) per row from a ListVal:
    (data[P], elem_valid_at[P], in_bounds[P]) — the shared core of
    element_at/get."""
    import jax.numpy as jnp
    w = lv.values.shape[1]
    ok = jnp.logical_and(idx >= 0, idx < lv.lengths)
    j = jnp.clip(idx, 0, w - 1)[:, None]
    data = jnp.take_along_axis(lv.values, j, axis=1)[:, 0]
    ev = jnp.take_along_axis(lv.elem_valid, j, axis=1)[:, 0]
    return data, ev, ok


def _elem_type(dt: DataType) -> DataType:
    if isinstance(dt, ArrayType):
        return dt.element
    raise Unsupported(f"expected array type, got {dt}")


def _pa(values, dtype: DataType):
    import pyarrow as pa
    return pa.array(values, type=to_arrow(dtype))


def _null_safe_eq(a, b) -> bool:
    """Set-op equality: NULL equals NULL (ref cudf NaN/null-equal set ops)."""
    return a == b or (a is None and b is None)


class Size(_HostCollectionExpr):
    """size(array|map). legacy_size_of_null (Spark default with ANSI off):
    size(NULL) = -1; ref GpuSize collectionOperations.scala."""

    def __init__(self, child, legacy_size_of_null: bool = True):
        self.children = [child]
        self.legacy = legacy_size_of_null

    def data_type(self, schema):
        return INT32

    def eval_host(self, batch):
        rows = self.children[0].eval_host(batch).to_pylist()
        out = []
        for v in rows:
            if v is None:
                out.append(-1 if self.legacy else None)
            else:
                out.append(len(v))
        return _pa(out, INT32)

    def key(self):
        return f"Size({self.children[0].key()},legacy={self.legacy})"

    def _device_list_reason(self, schema):
        return _dense_list_reason(self, schema)

    def eval_device(self, ctx):
        import jax.numpy as jnp
        from .base import DVal
        v = _list_arg(ctx, self.children[0])
        lens = v.data.lengths.astype(jnp.int32)
        if self.legacy:
            return DVal(jnp.where(v.validity, lens, jnp.int32(-1)),
                        jnp.ones_like(v.validity), INT32)
        return DVal(lens, v.validity, INT32)


class ArrayContains(_HostCollectionExpr):
    def __init__(self, array, value):
        self.children = [array, value]

    def data_type(self, schema):
        return BOOL

    def eval_host(self, batch):
        arrs = self.children[0].eval_host(batch).to_pylist()
        vals = self.children[1].eval_host(batch).to_pylist()
        out = []
        for a, v in zip(arrs, vals):
            if a is None or v is None:
                out.append(None)
            elif v in a:
                out.append(True)
            elif None in a:
                out.append(None)
            else:
                out.append(False)
        return _pa(out, BOOL)

    def _device_list_reason(self, schema):
        return _needle_reason(self, schema)

    def eval_device(self, ctx):
        import jax.numpy as jnp
        from .base import DVal
        arr, val, eq = _needle_eq(ctx, self.children[0], self.children[1])
        lv = arr.data
        found = jnp.any(eq, axis=1)
        has_null = jnp.any(jnp.logical_and(_in_list_mask(lv),
                                           ~lv.elem_valid), axis=1)
        valid = jnp.logical_and(
            jnp.logical_and(arr.validity, val.validity),
            jnp.logical_or(found, ~has_null))
        return DVal(found, valid, BOOL)


class ArrayPosition(_HostCollectionExpr):
    """1-based position of first match, 0 if absent, NULL on null inputs."""

    def __init__(self, array, value):
        self.children = [array, value]

    def data_type(self, schema):
        return INT64

    def eval_host(self, batch):
        arrs = self.children[0].eval_host(batch).to_pylist()
        vals = self.children[1].eval_host(batch).to_pylist()
        out = []
        for a, v in zip(arrs, vals):
            if a is None or v is None:
                out.append(None)
            else:
                out.append(a.index(v) + 1 if v in a else 0)
        return _pa(out, INT64)

    def _device_list_reason(self, schema):
        return _needle_reason(self, schema)

    def eval_device(self, ctx):
        import jax.numpy as jnp
        from .base import DVal
        arr, val, eq = _needle_eq(ctx, self.children[0], self.children[1])
        found = jnp.any(eq, axis=1)
        first = jnp.argmax(eq, axis=1).astype(jnp.int64)
        data = jnp.where(found, first + 1, jnp.int64(0))
        return DVal(data, jnp.logical_and(arr.validity, val.validity),
                    INT64)


class ElementAt(_HostCollectionExpr):
    """element_at(array, 1-based-index) / element_at(map, key).
    Out-of-bounds / missing key => NULL (non-ANSI); index 0 is an error."""

    def __init__(self, child, key):
        self.children = [child, key]

    def data_type(self, schema):
        dt = self.children[0].data_type(schema)
        if isinstance(dt, ArrayType):
            return dt.element
        if isinstance(dt, MapType):
            return dt.value
        raise Unsupported(f"element_at on {dt}")

    def eval_host(self, batch):
        coll = self.children[0].eval_host(batch)
        keys = self.children[1].eval_host(batch).to_pylist()
        dt = self.data_type(batch.schema)
        rows = coll.to_pylist()
        out = []
        is_map = isinstance(self.children[0].data_type(batch.schema), MapType)
        for c, k in zip(rows, keys):
            if c is None or k is None:
                out.append(None)
            elif is_map:
                out.append(dict(c).get(k))
            else:
                if k == 0:
                    raise ValueError("SQL array indices start at 1")
                i = k - 1 if k > 0 else len(c) + k
                out.append(c[i] if 0 <= i < len(c) else None)
        return _pa(out, dt)

    def _device_list_reason(self, schema):
        r = _dense_list_reason(self, schema)
        if r is not None:
            return r
        # index 0 raises in Spark; a kernel cannot raise mid-trace, so
        # only statically-nonzero literal indices take the device path
        k = self.children[1]
        if not (isinstance(k, Literal) and isinstance(k.value, int)
                and k.value != 0):
            return "index must be a nonzero integer literal"
        return None

    def eval_device(self, ctx):
        import jax.numpy as jnp
        from .base import DVal
        kval = self.children[1].eval_device(ctx)
        arr = _list_arg(ctx, self.children[0])
        k = jnp.broadcast_to(jnp.asarray(kval.data).astype(jnp.int32),
                             (ctx.padded_len,))
        idx = jnp.where(k > 0, k - 1, arr.data.lengths + k)
        data, ev, ok = _gather_element(arr.data, idx)
        valid = jnp.logical_and(
            jnp.logical_and(arr.validity, kval.validity),
            jnp.logical_and(ok, ev))
        return DVal(data, valid, self.data_type(ctx.schema))


class GetArrayItem(_HostCollectionExpr):
    """arr[i]: 0-based ordinal extraction, OOB/negative => NULL."""

    def __init__(self, array, ordinal):
        self.children = [array, ordinal]

    def data_type(self, schema):
        return _elem_type(self.children[0].data_type(schema))

    def eval_host(self, batch):
        arrs = self.children[0].eval_host(batch).to_pylist()
        idxs = self.children[1].eval_host(batch).to_pylist()
        dt = self.data_type(batch.schema)
        out = []
        for a, i in zip(arrs, idxs):
            if a is None or i is None or not (0 <= i < len(a)):
                out.append(None)
            else:
                out.append(a[i])
        return _pa(out, dt)

    def _device_list_reason(self, schema):
        r = _dense_list_reason(self, schema)
        if r is not None:
            return r
        idt = self.children[1].data_type(schema)
        if idt.np_dtype is None or idt.np_dtype.kind not in "iu":
            return "ordinal must be integral"
        return None

    def eval_device(self, ctx):
        import jax.numpy as jnp
        from .base import DVal
        ordv = self.children[1].eval_device(ctx)
        arr = _list_arg(ctx, self.children[0])
        idx = jnp.broadcast_to(
            jnp.asarray(ordv.data).astype(jnp.int32), (ctx.padded_len,))
        data, ev, ok = _gather_element(arr.data, idx)
        valid = jnp.logical_and(
            jnp.logical_and(arr.validity, ordv.validity),
            jnp.logical_and(ok, ev))
        return DVal(data, valid, self.data_type(ctx.schema))


class GetMapValue(_HostCollectionExpr):
    def __init__(self, child, key):
        self.children = [child, key]

    def data_type(self, schema):
        dt = self.children[0].data_type(schema)
        assert isinstance(dt, MapType)
        return dt.value

    def eval_host(self, batch):
        maps = self.children[0].eval_host(batch).to_pylist()
        keys = self.children[1].eval_host(batch).to_pylist()
        dt = self.data_type(batch.schema)
        out = [None if m is None or k is None else dict(m).get(k)
               for m, k in zip(maps, keys)]
        return _pa(out, dt)


class GetStructField(_HostCollectionExpr):
    def __init__(self, child, field_name: str):
        self.children = [child]
        self.field = field_name

    def data_type(self, schema):
        dt = self.children[0].data_type(schema)
        assert isinstance(dt, StructType), dt
        return dt.fields[dt.index_of(self.field)].dtype

    def eval_host(self, batch):
        import pyarrow.compute as pc
        arr = self.children[0].eval_host(batch)
        # struct_field propagates parent nulls into the child
        return pc.struct_field(arr, self.field)

    def key(self):
        return f"GetStructField({self.children[0].key()},{self.field})"


class SortArray(_HostCollectionExpr):
    """sort_array: asc puts NULLs first, desc puts NULLs last (Spark)."""

    def __init__(self, array, ascending=None):
        asc = ascending if ascending is not None else Literal(True)
        self.children = [array, asc]

    def data_type(self, schema):
        return self.children[0].data_type(schema)

    def eval_host(self, batch):
        arrs = self.children[0].eval_host(batch).to_pylist()
        ascs = self.children[1].eval_host(batch).to_pylist()
        dt = self.data_type(batch.schema)
        out = []
        for a, asc in zip(arrs, ascs):
            if a is None:
                out.append(None)
                continue
            nn = sorted(v for v in a if v is not None)
            nulls = [None] * (len(a) - len(nn))
            out.append(nulls + nn if asc else list(reversed(nn)) + nulls)
        return _pa(out, dt)

    def _device_list_reason(self, schema):
        r = _dense_list_reason(self, schema)
        if r is not None:
            return r
        if not isinstance(self.children[1], Literal):
            return "ascending flag must be a literal"
        return None

    def key(self):
        # the ascending flag is baked into the kernel STRUCTURE (not a
        # traced operand), so it must stay in the cache key even under
        # literal parameterization
        asc = (self.children[1].value
               if isinstance(self.children[1], Literal) else "?")
        return f"SortArray({self.children[0].key()},asc={asc})"

    def eval_device(self, ctx):
        import jax
        import jax.numpy as jnp
        from .base import DVal, ListVal
        arr = _list_arg(ctx, self.children[0])
        asc = bool(self.children[1].value)
        lv = arr.data
        in_list = _in_list_mask(lv)
        live = jnp.logical_and(lv.elem_valid, in_list)
        # rank: nulls 0, valid 1, padding 2 -> ascending variadic sort
        # along axis 1 gives [nulls][values asc][padding] per row
        rank = jnp.where(in_list,
                         jnp.where(lv.elem_valid, jnp.int32(1),
                                   jnp.int32(0)),
                         jnp.int32(2))
        srank, svals = jax.lax.sort((rank, lv.values), dimension=1,
                                    num_keys=2)
        w = lv.values.shape[1]
        nullcnt = jnp.sum(jnp.logical_and(in_list, ~lv.elem_valid),
                          axis=1, dtype=jnp.int32)
        validcnt = jnp.sum(live, axis=1, dtype=jnp.int32)
        if asc:
            out_vals, out_rank = svals, srank
        else:
            # desc = reversed valid run first, then the null slots
            j = jnp.arange(w, dtype=jnp.int32)[None, :]
            idx = jnp.where(j < validcnt[:, None],
                            lv.lengths[:, None] - 1 - j,
                            j - validcnt[:, None])
            idx = jnp.clip(idx, 0, w - 1)
            out_vals = jnp.take_along_axis(svals, idx, axis=1)
            out_rank = jnp.take_along_axis(srank, idx, axis=1)
        out_ev = jnp.logical_and(out_rank == jnp.int32(1), in_list)
        return DVal(ListVal(out_vals, out_ev, lv.lengths), arr.validity,
                    self.data_type(ctx.schema))


class _ArrayReduce(_HostCollectionExpr):
    """min/max over elements ignoring nulls; empty/all-null => NULL."""

    _pick = None  # min or max

    def __init__(self, array):
        self.children = [array]

    def data_type(self, schema):
        return _elem_type(self.children[0].data_type(schema))

    def eval_host(self, batch):
        arrs = self.children[0].eval_host(batch).to_pylist()
        dt = self.data_type(batch.schema)
        out = []
        for a in arrs:
            vs = [v for v in (a or []) if v is not None]
            out.append(type(self)._pick(vs) if vs else None)
        return _pa(out, dt)

    def _device_list_reason(self, schema):
        return _dense_list_reason(self, schema)

    def eval_device(self, ctx):
        import jax.numpy as jnp
        from .base import DVal
        arr = _list_arg(ctx, self.children[0])
        lv = arr.data
        live = jnp.logical_and(lv.elem_valid, _in_list_mask(lv))
        vdt = lv.values.dtype
        is_min = type(self)._pick is min
        if jnp.issubdtype(vdt, jnp.floating):
            hi, lo = jnp.asarray(jnp.inf, vdt), jnp.asarray(-jnp.inf, vdt)
            nanv = jnp.isnan(lv.values)
            # Spark orders NaN greatest: min skips NaN unless all-NaN,
            # max returns NaN when any NaN present
            if is_min:
                base = jnp.where(jnp.logical_and(live, ~nanv),
                                 lv.values, hi)
                red = jnp.min(base, axis=1)
                all_nan = ~jnp.any(jnp.logical_and(live, ~nanv), axis=1)
                red = jnp.where(all_nan, jnp.asarray(jnp.nan, vdt), red)
            else:
                base = jnp.where(jnp.logical_and(live, ~nanv),
                                 lv.values, lo)
                red = jnp.max(base, axis=1)
                any_nan = jnp.any(jnp.logical_and(live, nanv), axis=1)
                red = jnp.where(any_nan, jnp.asarray(jnp.nan, vdt), red)
        elif vdt == jnp.bool_:
            sentinel = is_min               # True floors min, False maxes
            base = jnp.where(live, lv.values, sentinel)
            red = (jnp.min if is_min else jnp.max)(base, axis=1)
        else:
            info = jnp.iinfo(vdt)
            sentinel = info.max if is_min else info.min
            base = jnp.where(live, lv.values,
                             jnp.asarray(sentinel, vdt))
            red = (jnp.min if is_min else jnp.max)(base, axis=1)
        has = jnp.any(live, axis=1)
        return DVal(red, jnp.logical_and(arr.validity, has),
                    self.data_type(ctx.schema))


class ArrayMin(_ArrayReduce):
    _pick = min


class ArrayMax(_ArrayReduce):
    _pick = max


class ArrayJoin(_HostCollectionExpr):
    """array_join(arr, delim[, null_replacement]); nulls skipped unless a
    replacement is given."""

    def __init__(self, array, delimiter, null_replacement=None):
        self.children = ([array, delimiter] +
                         ([null_replacement] if null_replacement else []))

    def data_type(self, schema):
        return STRING

    def eval_host(self, batch):
        arrs = self.children[0].eval_host(batch).to_pylist()
        delims = self.children[1].eval_host(batch).to_pylist()
        reps = (self.children[2].eval_host(batch).to_pylist()
                if len(self.children) > 2 else [None] * len(arrs))
        out = []
        for a, d, r in zip(arrs, delims, reps):
            if a is None or d is None:
                out.append(None)
                continue
            parts = [r if v is None else str(v) for v in a]
            out.append(d.join(p for p in parts if p is not None))
        return _pa(out, STRING)


class Slice(_HostCollectionExpr):
    """slice(arr, start, length): 1-based, negative start counts from end;
    start=0 or length<0 is an error (Spark)."""

    def __init__(self, array, start, length):
        self.children = [array, start, length]

    def data_type(self, schema):
        return self.children[0].data_type(schema)

    def eval_host(self, batch):
        arrs = self.children[0].eval_host(batch).to_pylist()
        starts = self.children[1].eval_host(batch).to_pylist()
        lens = self.children[2].eval_host(batch).to_pylist()
        dt = self.data_type(batch.schema)
        out = []
        for a, s, ln in zip(arrs, starts, lens):
            if a is None or s is None or ln is None:
                out.append(None)
                continue
            if s == 0:
                raise ValueError("Unexpected value for start in function slice: SQL array indices start at 1")
            if ln < 0:
                raise ValueError("Unexpected value for length in function slice: length must be greater than or equal to 0")
            i = s - 1 if s > 0 else len(a) + s
            out.append([] if i < 0 else a[i:i + ln])
        return _pa(out, dt)

    def _device_list_reason(self, schema):
        r = _dense_list_reason(self, schema)
        if r is not None:
            return r
        # start=0 / length<0 raise in Spark; kernels cannot raise, so the
        # device path requires statically-checked literals
        s, ln = self.children[1], self.children[2]
        if not (isinstance(s, Literal) and isinstance(s.value, int)
                and s.value != 0):
            return "start must be a nonzero integer literal"
        if not (isinstance(ln, Literal) and isinstance(ln.value, int)
                and ln.value >= 0):
            return "length must be a non-negative integer literal"
        return None

    def key(self):
        # start/length shape the kernel statically — keep them in the
        # cache key even under literal parameterization
        s = (self.children[1].value
             if isinstance(self.children[1], Literal) else "?")
        ln = (self.children[2].value
              if isinstance(self.children[2], Literal) else "?")
        return f"Slice({self.children[0].key()},{s},{ln})"

    def eval_device(self, ctx):
        import jax.numpy as jnp
        from .base import DVal, ListVal
        arr = _list_arg(ctx, self.children[0])
        s = int(self.children[1].value)
        ln = int(self.children[2].value)
        lv = arr.data
        w = lv.values.shape[1]
        start = (jnp.full_like(lv.lengths, s - 1) if s > 0
                 else lv.lengths + jnp.int32(s))
        neg = start < 0                      # before the array: []
        j = jnp.arange(w, dtype=jnp.int32)[None, :]
        src = start[:, None] + j
        take = jnp.logical_and(
            jnp.logical_and(j < ln, src < lv.lengths[:, None]),
            jnp.logical_and(src >= 0, ~neg[:, None]))
        srcc = jnp.clip(src, 0, w - 1)
        vals = jnp.where(take,
                         jnp.take_along_axis(lv.values, srcc, axis=1),
                         jnp.zeros((), lv.values.dtype))
        ev = jnp.logical_and(
            jnp.take_along_axis(lv.elem_valid, srcc, axis=1), take)
        out_len = jnp.where(
            neg, jnp.int32(0),
            jnp.clip(lv.lengths - start, 0, jnp.int32(ln)))
        return DVal(ListVal(vals, ev, out_len), arr.validity,
                    self.data_type(ctx.schema))


class ArrayRepeat(_HostCollectionExpr):
    def __init__(self, element, count):
        self.children = [element, count]

    def data_type(self, schema):
        return ArrayType(self.children[0].data_type(schema))

    def eval_host(self, batch):
        elems = self.children[0].eval_host(batch).to_pylist()
        counts = self.children[1].eval_host(batch).to_pylist()
        dt = self.data_type(batch.schema)
        out = [None if c is None else [e] * max(c, 0)
               for e, c in zip(elems, counts)]
        return _pa(out, dt)


class ArraysZip(_HostCollectionExpr):
    """arrays_zip: array of structs, padded to the longest input with NULLs."""

    def __init__(self, *arrays, names: Optional[Sequence[str]] = None):
        self.children = list(arrays)
        self.names = list(names) if names else [str(i) for i in range(len(arrays))]

    def data_type(self, schema):
        fields = [StructField(n, _elem_type(c.data_type(schema)))
                  for n, c in zip(self.names, self.children)]
        return ArrayType(StructType(fields))

    def eval_host(self, batch):
        cols = [c.eval_host(batch).to_pylist() for c in self.children]
        dt = self.data_type(batch.schema)
        out = []
        for row in zip(*cols):
            if any(a is None for a in row):
                out.append(None)
                continue
            n = max((len(a) for a in row), default=0)
            out.append([{nm: (a[i] if i < len(a) else None)
                         for nm, a in zip(self.names, row)} for i in range(n)])
        return _pa(out, dt)


class Concat(_HostCollectionExpr):
    """Array concat (Spark's Concat over ArrayType inputs; the STRING case
    is ConcatStrings in string_fns.py — ref GpuConcat handles both by cudf
    kernel choice, here they are separate hosts kernels).
    NULL input => NULL result."""

    def __init__(self, *children):
        self.children = list(children)

    def data_type(self, schema):
        dt = self.children[0].data_type(schema)
        if not isinstance(dt, ArrayType):
            raise Unsupported("Concat handles arrays; use ConcatStrings for strings")
        return dt

    def eval_host(self, batch):
        cols = [c.eval_host(batch).to_pylist() for c in self.children]
        dt = self.data_type(batch.schema)
        out = []
        for row in zip(*cols):
            if any(v is None for v in row):
                out.append(None)
            else:
                out.append([v for part in row for v in part])
        return _pa(out, dt)


class Flatten(_HostCollectionExpr):
    """flatten(array<array<T>>): NULL if outer or any inner array is NULL."""

    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        return _elem_type(self.children[0].data_type(schema))

    def eval_host(self, batch):
        rows = self.children[0].eval_host(batch).to_pylist()
        dt = self.data_type(batch.schema)
        out = []
        for r in rows:
            if r is None or any(inner is None for inner in r):
                out.append(None)
            else:
                out.append([v for inner in r for v in inner])
        return _pa(out, dt)


class Sequence(_HostCollectionExpr):
    """sequence(start, stop[, step]) over integrals; default step +-1."""

    def __init__(self, start, stop, step=None):
        self.children = [start, stop] + ([step] if step is not None else [])

    def data_type(self, schema):
        return ArrayType(promote_types(self.children[0].data_type(schema),
                                       self.children[1].data_type(schema)))

    def eval_host(self, batch):
        starts = self.children[0].eval_host(batch).to_pylist()
        stops = self.children[1].eval_host(batch).to_pylist()
        steps = (self.children[2].eval_host(batch).to_pylist()
                 if len(self.children) > 2 else [None] * len(starts))
        dt = self.data_type(batch.schema)
        out = []
        for a, b, s in zip(starts, stops, steps):
            if a is None or b is None:
                out.append(None)
                continue
            if s is None:
                s = 1 if b >= a else -1
            if (s == 0 and a != b) or (s > 0 and b < a) or (s < 0 and b > a):
                raise ValueError(
                    f"Illegal sequence boundaries: {a} to {b} by {s}")
            seq = []
            v = a
            if s > 0:
                while v <= b:
                    seq.append(v)
                    v += s
            else:
                while v >= b:
                    seq.append(v)
                    v += s
            out.append(seq)
        return _pa(out, dt)


class ArrayDistinct(_HostCollectionExpr):
    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        return self.children[0].data_type(schema)

    def eval_host(self, batch):
        rows = self.children[0].eval_host(batch).to_pylist()
        dt = self.data_type(batch.schema)
        out = []
        for a in rows:
            if a is None:
                out.append(None)
                continue
            seen, res = [], []
            for v in a:
                if not any(_null_safe_eq(v, s) for s in seen):
                    seen.append(v)
                    res.append(v)
            out.append(res)
        return _pa(out, dt)


class _ArraySetOp(_HostCollectionExpr):
    def __init__(self, left, right):
        self.children = [left, right]

    def data_type(self, schema):
        return self.children[0].data_type(schema)

    def _combine(self, a: list, b: list) -> list:
        raise NotImplementedError

    def eval_host(self, batch):
        ls = self.children[0].eval_host(batch).to_pylist()
        rs = self.children[1].eval_host(batch).to_pylist()
        dt = self.data_type(batch.schema)
        out = [None if a is None or b is None else self._combine(a, b)
               for a, b in zip(ls, rs)]
        return _pa(out, dt)


def _distinct(vals):
    seen = []
    for v in vals:
        if not any(_null_safe_eq(v, s) for s in seen):
            seen.append(v)
    return seen


class ArrayUnion(_ArraySetOp):
    def _combine(self, a, b):
        return _distinct(list(a) + list(b))


class ArrayIntersect(_ArraySetOp):
    def _combine(self, a, b):
        return [v for v in _distinct(a)
                if any(_null_safe_eq(v, w) for w in b)]


class ArrayExcept(_ArraySetOp):
    def _combine(self, a, b):
        return [v for v in _distinct(a)
                if not any(_null_safe_eq(v, w) for w in b)]


class ArrayRemove(_HostCollectionExpr):
    """array_remove(arr, elem): removes all == elem; NULL elem => NULL."""

    def __init__(self, array, element):
        self.children = [array, element]

    def data_type(self, schema):
        return self.children[0].data_type(schema)

    def eval_host(self, batch):
        arrs = self.children[0].eval_host(batch).to_pylist()
        elems = self.children[1].eval_host(batch).to_pylist()
        dt = self.data_type(batch.schema)
        out = [None if a is None or e is None else [v for v in a if v != e]
               for a, e in zip(arrs, elems)]
        return _pa(out, dt)


class ArraysOverlap(_HostCollectionExpr):
    """Three-valued overlap: TRUE on a common non-null element; NULL if no
    match but either side holds a NULL (and both non-empty); else FALSE."""

    def __init__(self, left, right):
        self.children = [left, right]

    def data_type(self, schema):
        return BOOL

    def eval_host(self, batch):
        ls = self.children[0].eval_host(batch).to_pylist()
        rs = self.children[1].eval_host(batch).to_pylist()
        out = []
        for a, b in zip(ls, rs):
            if a is None or b is None:
                out.append(None)
                continue
            nn = set(v for v in a if v is not None)
            if any(v in nn for v in b if v is not None):
                out.append(True)
            elif a and b and (None in a or None in b):
                out.append(None)
            else:
                out.append(False)
        return _pa(out, BOOL)


class ArrayReverse(_HostCollectionExpr):
    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        return self.children[0].data_type(schema)

    def eval_host(self, batch):
        rows = self.children[0].eval_host(batch).to_pylist()
        dt = self.data_type(batch.schema)
        return _pa([None if a is None else list(reversed(a)) for a in rows], dt)

    def _device_list_reason(self, schema):
        return _dense_list_reason(self, schema)

    def eval_device(self, ctx):
        import jax.numpy as jnp
        from .base import DVal, ListVal
        arr = _list_arg(ctx, self.children[0])
        lv = arr.data
        w = lv.values.shape[1]
        j = jnp.arange(w, dtype=jnp.int32)[None, :]
        src = lv.lengths[:, None] - 1 - j
        ok = j < lv.lengths[:, None]
        srcc = jnp.clip(src, 0, w - 1)
        vals = jnp.where(ok,
                         jnp.take_along_axis(lv.values, srcc, axis=1),
                         jnp.zeros((), lv.values.dtype))
        ev = jnp.logical_and(
            jnp.take_along_axis(lv.elem_valid, srcc, axis=1), ok)
        return DVal(ListVal(vals, ev, lv.lengths), arr.validity,
                    self.data_type(ctx.schema))


# ---------------------------------------------------------------------------
# Map expressions
# ---------------------------------------------------------------------------

def _map_items(m):
    """pyarrow renders map values as list-of-(key, value) tuples."""
    return list(m) if m is not None else None


class MapKeys(_HostCollectionExpr):
    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        dt = self.children[0].data_type(schema)
        assert isinstance(dt, MapType)
        return ArrayType(dt.key, contains_null=False)

    def eval_host(self, batch):
        rows = self.children[0].eval_host(batch).to_pylist()
        dt = self.data_type(batch.schema)
        return _pa([None if m is None else [k for k, _ in m] for m in rows], dt)


class MapValues(_HostCollectionExpr):
    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        dt = self.children[0].data_type(schema)
        assert isinstance(dt, MapType)
        return ArrayType(dt.value)

    def eval_host(self, batch):
        rows = self.children[0].eval_host(batch).to_pylist()
        dt = self.data_type(batch.schema)
        return _pa([None if m is None else [v for _, v in m] for m in rows], dt)


class MapEntries(_HostCollectionExpr):
    def __init__(self, child):
        self.children = [child]

    def data_type(self, schema):
        dt = self.children[0].data_type(schema)
        assert isinstance(dt, MapType)
        return ArrayType(StructType([StructField("key", dt.key, False),
                                     StructField("value", dt.value)]))

    def eval_host(self, batch):
        rows = self.children[0].eval_host(batch).to_pylist()
        dt = self.data_type(batch.schema)
        return _pa([None if m is None else
                    [{"key": k, "value": v} for k, v in m] for m in rows], dt)


class MapConcat(_HostCollectionExpr):
    """map_concat with LAST_WIN dedup (ref GpuMapConcat follows
    spark.sql.mapKeyDedupPolicy=LAST_WIN)."""

    def __init__(self, *children):
        self.children = list(children)

    def data_type(self, schema):
        return self.children[0].data_type(schema)

    def eval_host(self, batch):
        cols = [c.eval_host(batch).to_pylist() for c in self.children]
        dt = self.data_type(batch.schema)
        out = []
        for row in zip(*cols):
            if any(m is None for m in row):
                out.append(None)
                continue
            merged = {}
            for m in row:
                for k, v in m:
                    merged[k] = v
            out.append(list(merged.items()))
        return _pa(out, dt)


class MapFromArrays(_HostCollectionExpr):
    def __init__(self, keys, values):
        self.children = [keys, values]

    def data_type(self, schema):
        return MapType(_elem_type(self.children[0].data_type(schema)),
                       _elem_type(self.children[1].data_type(schema)))

    def eval_host(self, batch):
        ks = self.children[0].eval_host(batch).to_pylist()
        vs = self.children[1].eval_host(batch).to_pylist()
        dt = self.data_type(batch.schema)
        out = []
        for k, v in zip(ks, vs):
            if k is None or v is None:
                out.append(None)
                continue
            if len(k) != len(v):
                raise ValueError("map_from_arrays: key/value length mismatch")
            if any(x is None for x in k):
                raise ValueError("Cannot use null as map key")
            out.append(list(zip(k, v)))
        return _pa(out, dt)


class StringToMap(_HostCollectionExpr):
    """str_to_map(text, pairDelim=',', keyValueDelim=':')."""

    def __init__(self, text, pair_delim=None, kv_delim=None):
        self.children = [text, pair_delim or Literal(","),
                         kv_delim or Literal(":")]

    def data_type(self, schema):
        return MapType(STRING, STRING)

    def eval_host(self, batch):
        ts = self.children[0].eval_host(batch).to_pylist()
        pds = self.children[1].eval_host(batch).to_pylist()
        kds = self.children[2].eval_host(batch).to_pylist()
        dt = self.data_type(batch.schema)
        out = []
        for t, pd_, kd in zip(ts, pds, kds):
            if t is None or pd_ is None or kd is None:
                out.append(None)
                continue
            m = {}
            for pair in t.split(pd_):
                k, sep, v = pair.partition(kd)
                m[k] = v if sep else None
            out.append(list(m.items()))
        return _pa(out, dt)


# ---------------------------------------------------------------------------
# Complex-type creators (ref complexTypeCreator: GpuCreateArray,
# GpuCreateMap, GpuCreateNamedStruct)
# ---------------------------------------------------------------------------

class CreateArray(_HostCollectionExpr):
    def __init__(self, *children):
        self.children = list(children)

    def data_type(self, schema):
        if not self.children:
            return ArrayType(NULLTYPE)
        dts = [c.data_type(schema) for c in self.children]
        et = dts[0]
        for d in dts[1:]:
            if d != et and d != NULLTYPE:
                et = promote_types(et, d) if et != NULLTYPE else d
        return ArrayType(et)

    def nullable(self, schema):
        return False

    def eval_host(self, batch):
        cols = [c.eval_host(batch).to_pylist() for c in self.children]
        dt = self.data_type(batch.schema)
        if not cols:
            return _pa([[]] * batch.num_rows, dt)
        return _pa([list(row) for row in zip(*cols)], dt)

    def _device_list_reason(self, schema):
        from ..columnar.nested import device_list_ok, width_bucket
        if not self.children:
            return "empty array literal is host-built"
        if width_bucket(len(self.children)) is None:
            return (f"{len(self.children)} elements exceeds the device "
                    "width cap")
        if not device_list_ok(self.data_type(schema)):
            return f"{self.data_type(schema)} has no dense device layout"
        for c in self.children:
            if c.data_type(schema).np_dtype is None \
                    and c.data_type(schema) != NULLTYPE:
                return "element type is host-only"
        return None

    def eval_device(self, ctx):
        import jax.numpy as jnp
        from ..columnar.nested import width_bucket
        from .base import DVal, ListVal
        dt = self.data_type(ctx.schema)
        np_dt = dt.element.np_dtype
        k = len(self.children)
        w = width_bucket(k)
        vals, evs = [], []
        for c in self.children:
            v = c.eval_device(ctx)
            data = jnp.broadcast_to(jnp.asarray(v.data),
                                    (ctx.padded_len,)).astype(np_dt)
            vv = jnp.broadcast_to(jnp.asarray(v.validity),
                                  (ctx.padded_len,))
            vals.append(data)
            evs.append(vv)
        pad = w - k
        values = jnp.stack(vals + [jnp.zeros(ctx.padded_len, np_dt)] * pad,
                           axis=1)
        ev = jnp.stack(evs + [jnp.zeros(ctx.padded_len, jnp.bool_)] * pad,
                       axis=1)
        lengths = jnp.full(ctx.padded_len, jnp.int32(k))
        return DVal(ListVal(values, ev, lengths),
                    jnp.ones(ctx.padded_len, jnp.bool_), dt)


class CreateMap(_HostCollectionExpr):
    def __init__(self, *children):
        assert len(children) % 2 == 0, "CreateMap needs key/value pairs"
        self.children = list(children)

    def data_type(self, schema):
        return MapType(self.children[0].data_type(schema),
                       self.children[1].data_type(schema))

    def eval_host(self, batch):
        cols = [c.eval_host(batch).to_pylist() for c in self.children]
        dt = self.data_type(batch.schema)
        out = []
        for row in zip(*cols):
            m = {}
            for i in range(0, len(row), 2):
                if row[i] is None:
                    raise ValueError("Cannot use null as map key")
                m[row[i]] = row[i + 1]
            out.append(list(m.items()))
        return _pa(out, dt)


class CreateNamedStruct(_HostCollectionExpr):
    """named_struct(name1, val1, ...); names must be foldable strings."""

    def __init__(self, *name_value_pairs):
        assert len(name_value_pairs) % 2 == 0
        self.names: List[str] = []
        self.children = []
        for i in range(0, len(name_value_pairs), 2):
            n = name_value_pairs[i]
            self.names.append(n.value if isinstance(n, Literal) else str(n))
            self.children.append(name_value_pairs[i + 1])

    def data_type(self, schema):
        return StructType([StructField(n, c.data_type(schema))
                           for n, c in zip(self.names, self.children)])

    def nullable(self, schema):
        return False

    def eval_host(self, batch):
        cols = [c.eval_host(batch).to_pylist() for c in self.children]
        dt = self.data_type(batch.schema)
        out = [dict(zip(self.names, row)) for row in zip(*cols)]
        return _pa(out, dt)

    def key(self):
        kids = ",".join(f"{n}={c.key()}" for n, c in zip(self.names, self.children))
        return f"CreateNamedStruct({kids})"
