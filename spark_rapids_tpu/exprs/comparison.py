"""Comparison predicates (ref sql-plugin predicates.scala GpuEqualTo etc.).

Numeric comparisons promote operands; NaN handling follows Spark: NaN == NaN
is true and NaN is largest for ordering (ref GpuGreaterThan docs / cudf NaN
config spark.rapids.sql.hasNans).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..types import BOOL, DataType, Schema, comparable, STRING
from .base import DVal, EvalContext, Expression, null_and, promote_types
from .arithmetic import arrow_to_masked_numpy, masked_numpy_to_arrow

__all__ = ["EqualTo", "EqualNullSafe", "NotEqual", "LessThan",
           "LessThanOrEqual", "GreaterThan", "GreaterThanOrEqual",
           "IsNull", "IsNotNull", "IsNaN", "In", "InSet"]


def _nan_eq(l, r):
    base = l == r
    if jnp.issubdtype(l.dtype, jnp.floating):
        both_nan = jnp.logical_and(jnp.isnan(l), jnp.isnan(r))
        return jnp.logical_or(base, both_nan)
    return base


def _nan_lt(l, r):
    # Spark ordering: NaN is greater than everything
    if jnp.issubdtype(l.dtype, jnp.floating):
        ln, rn = jnp.isnan(l), jnp.isnan(r)
        return jnp.where(rn, jnp.logical_not(ln), jnp.logical_and(
            jnp.logical_not(ln), l < r))
    return l < r


class BinaryComparison(Expression):
    device_type_sig = comparable
    symbol = "?"

    def __init__(self, left: Expression, right: Expression):
        self.children = [left, right]

    def data_type(self, schema: Schema) -> DataType:
        return BOOL

    def _operands(self, ctx: EvalContext):
        l = self.children[0].eval_device(ctx)
        r = self.children[1].eval_device(ctx)
        ldt = self.children[0].data_type(ctx.schema)
        rdt = self.children[1].data_type(ctx.schema)
        if ldt != rdt:
            wide = promote_types(ldt, rdt)
            return (l.data.astype(wide.np_dtype), r.data.astype(wide.np_dtype),
                    null_and(l.validity, r.validity))
        return l.data, r.data, null_and(l.validity, r.validity)

    def _host_operands(self, batch):
        from .base import Literal

        from ..types import DATE, TIMESTAMP, DecimalType

        def is_lit(e):
            if not (isinstance(e, Literal) and e.value is not None
                    and e.dtype.np_dtype is not None):
                return False
            # DATE/TIMESTAMP/decimal columns materialize as datetime64 /
            # object arrays on host — their literals must keep the arrow
            # path so dtypes line up
            other = self.children[1] if e is self.children[0] \
                else self.children[0]
            odt = other.data_type(batch.schema)
            if odt in (DATE, TIMESTAMP) or isinstance(odt, DecimalType) \
                    or e.dtype in (DATE, TIMESTAMP) \
                    or isinstance(e.dtype, DecimalType):
                return False
            return True

        def side(e, as_scalar):
            # literal operands ride as numpy scalars (broadcast is free;
            # materializing a constant column costs ~30 ms per 1M rows)
            if as_scalar:
                import numpy as _np
                return (_np.asarray(e.value, dtype=e.dtype.np_dtype),
                        True)
            return arrow_to_masked_numpy(e.eval_host(batch))

        lit0, lit1 = is_lit(self.children[0]), is_lit(self.children[1])
        # at most one side stays scalar so the result keeps batch length
        l, lv = side(self.children[0], lit0 and not lit1)
        r, rv = side(self.children[1], lit1)
        ldt = self.children[0].data_type(batch.schema)
        rdt = self.children[1].data_type(batch.schema)
        if ldt != rdt and ldt.device_backed and rdt.device_backed:
            wide = promote_types(ldt, rdt).np_dtype
            l, r = l.astype(wide), r.astype(wide)
        return l, r, lv & rv

    def key(self):
        return f"{type(self).__name__}({self.children[0].key()},{self.children[1].key()})"

    @property
    def name_hint(self):
        return (f"({self.children[0].name_hint} {self.symbol} "
                f"{self.children[1].name_hint})")


class EqualTo(BinaryComparison):
    symbol = "="

    def eval_device(self, ctx):
        l, r, v = self._operands(ctx)
        return DVal(_nan_eq(l, r), v, BOOL)

    def eval_host(self, batch):
        l, r, v = self._host_operands(batch)
        with np.errstate(all="ignore"):
            eq = l == r
            if np.issubdtype(np.asarray(l).dtype, np.floating):
                eq = eq | (np.isnan(l) & np.isnan(r))
        return masked_numpy_to_arrow(eq, v, BOOL)


class EqualNullSafe(BinaryComparison):
    """<=> : never null; null <=> null is true."""
    symbol = "<=>"

    def eval_device(self, ctx):
        l = self.children[0].eval_device(ctx)
        r = self.children[1].eval_device(ctx)
        eq = _nan_eq(l.data, r.data)
        both_null = jnp.logical_and(~l.validity, ~r.validity)
        both_valid = jnp.logical_and(l.validity, r.validity)
        out = jnp.logical_or(both_null, jnp.logical_and(both_valid, eq))
        return DVal(out, jnp.ones_like(out, dtype=jnp.bool_), BOOL)

    def eval_host(self, batch):
        l, lv = arrow_to_masked_numpy(self.children[0].eval_host(batch))
        r, rv = arrow_to_masked_numpy(self.children[1].eval_host(batch))
        with np.errstate(all="ignore"):
            eq = l == r
        out = (~lv & ~rv) | (lv & rv & eq)
        return masked_numpy_to_arrow(out, np.ones_like(out, dtype=bool), BOOL)


class NotEqual(BinaryComparison):
    symbol = "!="

    def eval_device(self, ctx):
        l, r, v = self._operands(ctx)
        return DVal(jnp.logical_not(_nan_eq(l, r)), v, BOOL)

    def eval_host(self, batch):
        l, r, v = self._host_operands(batch)
        with np.errstate(all="ignore"):
            eq = l == r
            if np.issubdtype(np.asarray(l).dtype, np.floating):
                eq = eq | (np.isnan(l) & np.isnan(r))
        return masked_numpy_to_arrow(~eq, v, BOOL)


def _host_cmp(op):
    def f(self, batch):
        l, r, v = self._host_operands(batch)
        fl = np.issubdtype(np.asarray(l).dtype, np.floating)
        with np.errstate(all="ignore"):
            if fl:
                # Spark float ordering: NaN compares greater than everything
                ln, rn = np.isnan(l), np.isnan(r)
                l2 = np.where(ln, 0, l)
                r2 = np.where(rn, 0, r)
                lt = np.where(rn, ~ln, ~ln & (l2 < r2))
                eq = np.where(ln & rn, True, (~ln & ~rn) & (l2 == r2))
                out = {"lt": lt, "le": lt | eq, "gt": ~(lt | eq), "ge": ~lt}[op]
            else:
                out = {"lt": l < r, "le": l <= r, "gt": l > r, "ge": l >= r}[op]
        return masked_numpy_to_arrow(out, v, BOOL)
    return f


class LessThan(BinaryComparison):
    symbol = "<"

    def eval_device(self, ctx):
        l, r, v = self._operands(ctx)
        return DVal(_nan_lt(l, r), v, BOOL)

    eval_host = _host_cmp("lt")


class LessThanOrEqual(BinaryComparison):
    symbol = "<="

    def eval_device(self, ctx):
        l, r, v = self._operands(ctx)
        return DVal(jnp.logical_or(_nan_lt(l, r), _nan_eq(l, r)), v, BOOL)

    eval_host = _host_cmp("le")


class GreaterThan(BinaryComparison):
    symbol = ">"

    def eval_device(self, ctx):
        l, r, v = self._operands(ctx)
        return DVal(jnp.logical_not(
            jnp.logical_or(_nan_lt(l, r), _nan_eq(l, r))), v, BOOL)

    eval_host = _host_cmp("gt")


class GreaterThanOrEqual(BinaryComparison):
    symbol = ">="

    def eval_device(self, ctx):
        l, r, v = self._operands(ctx)
        return DVal(jnp.logical_not(_nan_lt(l, r)), v, BOOL)

    eval_host = _host_cmp("ge")


class IsNull(Expression):
    def __init__(self, child: Expression):
        self.children = [child]

    def data_type(self, schema):
        return BOOL

    def nullable(self, schema):
        return False

    def device_unsupported_reason(self, schema):
        return None  # works for any child whose column is device-backed

    def eval_device(self, ctx):
        c = self.children[0].eval_device(ctx)
        out = jnp.logical_not(c.validity)
        # padding rows must not count as "null rows"
        out = jnp.logical_and(out, ctx.row_mask())
        return DVal(out, jnp.ones_like(out), BOOL)

    def eval_host(self, batch):
        import pyarrow.compute as pc
        return pc.is_null(self.children[0].eval_host(batch))

    def key(self):
        return f"isnull({self.children[0].key()})"


class IsNotNull(Expression):
    def __init__(self, child: Expression):
        self.children = [child]

    def data_type(self, schema):
        return BOOL

    def nullable(self, schema):
        return False

    def device_unsupported_reason(self, schema):
        return None

    def eval_device(self, ctx):
        c = self.children[0].eval_device(ctx)
        return DVal(c.validity, jnp.ones_like(c.validity), BOOL)

    def eval_host(self, batch):
        import pyarrow.compute as pc
        return pc.is_valid(self.children[0].eval_host(batch))

    def key(self):
        return f"isnotnull({self.children[0].key()})"


class IsNaN(Expression):
    def __init__(self, child: Expression):
        self.children = [child]

    def data_type(self, schema):
        return BOOL

    def eval_device(self, ctx):
        c = self.children[0].eval_device(ctx)
        if jnp.issubdtype(c.data.dtype, jnp.floating):
            out = jnp.isnan(c.data)
        else:
            out = jnp.zeros_like(c.validity)
        return DVal(out, c.validity, BOOL)

    def eval_host(self, batch):
        v, ok = arrow_to_masked_numpy(self.children[0].eval_host(batch))
        out = np.isnan(v) if np.issubdtype(v.dtype, np.floating) \
            else np.zeros(len(v), dtype=bool)
        return masked_numpy_to_arrow(out, ok, BOOL)

    def key(self):
        return f"isnan({self.children[0].key()})"


class In(Expression):
    """value IN (literals...) (ref GpuInSet)."""

    def __init__(self, child: Expression, values):
        self.children = [child]
        self.values = tuple(values)

    def data_type(self, schema):
        return BOOL

    def eval_device(self, ctx):
        c = self.children[0].eval_device(ctx)
        out = jnp.zeros(ctx.padded_len, dtype=jnp.bool_)
        fl = jnp.issubdtype(c.data.dtype, jnp.floating)
        for v in self.values:
            if v is None:
                continue
            if fl and isinstance(v, (int, float, np.floating, np.integer)):
                # same NaN-eq semantics as EqualTo (ADVICE r5): Spark's
                # double('NaN') IN (NaN) is true — a bare == would miss it
                out = jnp.logical_or(
                    out, _nan_eq(c.data, jnp.asarray(v, c.data.dtype)))
            else:
                out = jnp.logical_or(out, c.data == v)
        valid = c.validity
        if any(v is None for v in self.values):
            # SQL three-valued IN: x IN (..., NULL) is NULL unless a
            # listed value matches (x = NULL is unknown, not false)
            valid = jnp.logical_and(valid, out)
        return DVal(out, valid, BOOL)

    def eval_host(self, batch):
        import pyarrow as pa
        import pyarrow.compute as pc
        arr = self.children[0].eval_host(batch)
        nan_listed = any(isinstance(v, float) and np.isnan(v)
                        for v in self.values)
        vals = pa.array([v for v in self.values if v is not None
                         and not (isinstance(v, float) and np.isnan(v))],
                        type=arr.type)
        res = pc.is_in(arr, value_set=vals)
        if nan_listed and pa.types.is_floating(arr.type):
            # Spark NaN semantics (as EqualTo/_nan_eq): NaN IN (NaN) is
            # true; arrow's is_in must not decide NaN membership
            res = pc.or_(res, pc.is_nan(arr))
        # Spark: null IN (...) -> NULL (pc.is_in yields false for nulls)
        out = pc.if_else(pc.is_valid(arr), res,
                         pa.nulls(len(arr), pa.bool_()))
        if any(v is None for v in self.values):
            # non-match against a list containing NULL is NULL too
            out = pc.if_else(pc.fill_null(out, False), out,
                             pa.nulls(len(arr), pa.bool_()))
        return out

    def key(self):
        return f"in({self.children[0].key()},{self.values!r})"


class InSet(In):
    """Optimizer-produced literal-set IN (ref GpuInSet): identical
    evaluation to In — Spark splits them only because InSet carries a
    pre-built set; here the literal tuple already is one."""
